"""PR 5 pod-level stream placement tests: ChipPool routing EC streams
to single chips instead of column-slicing every stream across the mesh
(ec/chip_pool.py), the rows x bytes admission cost model, and the
per-Store scheduler scope.

Load-bearing properties:

- bit-identity: a stream placed on one chip produces byte-for-byte the
  mesh-sliced and CPU outputs (the placement decision is scheduling
  only);
- routing: deterministic least-loaded placement under skewed stream
  costs; a lone wide stream keeps the mesh in "auto", competing
  streams get chips; "mesh"/"chip" pin the policy;
- fault isolation: one chip dying replays only ITS streams' batches on
  CPU — sibling streams keep their chips and their own breakers;
- cost model: a 1-row reconstruction stream is admitted ~m x more often
  per unit of banked share credit than a parity-encode stream of equal
  width (heterogeneous-batch fairness);
- per-Store scopes: two Stores' scheduler configs no longer clobber
  each other (configure() stops being process-wide last-caller-wins).

The conftest forces an 8-device virtual CPU platform, so the mesh
backend (and therefore the pool) is real in every run.
"""

import os
import threading

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import (
    ChipPool,
    CpuBackend,
    ECContext,
    FallbackBackend,
    JaxBackend,
    QueueScope,
    ec_encode_volume,
    place_stream,
    pool_for,
)
from seaweedfs_tpu.ec.backend import _decode_coeffs
from seaweedfs_tpu.ec.bitrot import BitrotProtection
from seaweedfs_tpu.ec.device_queue import DeviceQueue, batch_cost
from seaweedfs_tpu.ec.pipeline import run_staged_apply
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils.retry import CircuitBreaker

CTX = ECContext(10, 4)
K = CTX.data_shards
M = CTX.parity_shards


def decode_coeffs(targets, src):
    rs = gf256.ReedSolomon(K, CTX.parity_shards)
    return _decode_coeffs(rs.matrix, K, tuple(targets), tuple(src))


def run_stream(backend, queue, coeffs, data, priority="foreground", batch=4096):
    """One staged stream through an explicit (backend, queue) pair."""
    out = np.zeros((coeffs.shape[0], data.shape[1]), dtype=np.uint8)

    def produce():
        for off in range(0, data.shape[1], batch):
            yield off, data[:, off : off + batch]

    def consume(off, rec):
        out[:, off : off + rec.shape[1]] = rec

    run_staged_apply(
        backend, coeffs, produce, consume,
        priority=priority, device_queue=queue, describe="placement test",
    )
    return out


# ------------------------------------------------------------------- pool


def test_pool_exists_only_for_mesh_backends():
    mesh_be = JaxBackend(CTX)  # 8 virtual devices -> column mesh
    pool = pool_for(mesh_be)
    assert pool is not None and pool.n_chips == 8
    assert pool_for(mesh_be) is pool  # one pool per backend instance
    assert pool_for(CpuBackend(CTX)) is None
    assert pool_for(JaxBackend(CTX, impl="xla", n_devices=1)) is None
    assert pool_for(None) is None
    # chip labels are device ids — these key the queue stats/metrics
    assert pool.labels[0].startswith("cpu:")
    assert len(set(pool.labels)) == 8
    # two backends over the SAME physical chips (another shard ratio)
    # get their own pool (ctx-specific chip backends) but share the
    # LOAD ledger: a stream placed by one is visible to the other
    be2 = JaxBackend(ECContext(5, 2))
    pool2 = pool_for(be2)
    assert pool2 is not pool
    i, _, release = pool.acquire(77)
    try:
        assert not pool2.idle()
        assert pool2.loads()[i] == 77
    finally:
        release()
    assert pool2.idle() and pool.idle()


def test_least_loaded_routing_under_skewed_costs():
    """Deterministic routing core (no jax): streams with skewed cost
    hints spread by least outstanding cost, ties to the lowest index;
    releases drain the load so the pool returns to idle."""
    made = []
    pool = ChipPool(
        devices=list(range(4)),
        make_chip=lambda d: made.append(d) or f"chip{d}",
        labels=[f"c{d}" for d in range(4)],
    )
    assert pool.idle()
    i1, be1, rel1 = pool.acquire(100)  # heavy stream -> chip 0
    assert (i1, be1) == (0, "chip0")
    picks = [pool.acquire(1) for _ in range(3)]  # light -> 1, 2, 3
    assert [p[0] for p in picks] == [1, 2, 3]
    # next light stream lands on the least-loaded (chip 1, load 1) —
    # NOT round-robin back to the heavy chip 0 (load 100)
    i5, _, rel5 = pool.acquire(1)
    assert i5 == 1
    assert pool.loads() == [100, 2, 1, 1]
    assert not pool.idle()
    rel1()
    rel1()  # idempotent
    for _, _, rel in picks:
        rel()
    rel5()
    assert pool.loads() == [0, 0, 0, 0]
    assert pool.idle()
    # chips were constructed lazily, once each, only for used indices
    assert made == [0, 1, 2, 3]


def test_wide_lone_stream_keeps_mesh_competing_streams_get_chips():
    be = JaxBackend(CTX)
    pool = pool_for(be)
    scope = QueueScope(placement="auto")
    # lone wide stream on an idle pod: mesh slicing wins — and it
    # charges EVERY chip, so the pod reads busy while it runs
    p_wide = place_stream(be, "foreground", scope=scope, wide=True,
                          cost_hint=1000)
    assert p_wide.chip is None and p_wide.backend is be
    assert not pool.idle() and all(l > 0 for l in pool.loads())
    # a second wide stream mid-encode must NOT stack onto the mesh
    # queue behind the first — the pod is busy, it gets a chip
    p_wide2 = place_stream(be, "foreground", scope=scope, wide=True)
    assert p_wide2.chip is not None
    p_wide2.close()
    p_wide.close()
    assert pool.idle()
    # a competing stream exists: the wide stream gets a chip too
    p1 = place_stream(be, "foreground", scope=scope, cost_hint=10)
    assert p1.chip is not None
    p2 = place_stream(be, "foreground", scope=scope, cost_hint=10, wide=True)
    assert p2.chip is not None and p2.chip != p1.chip
    p1.close()
    p2.close()
    # pinned modes — a pinned-mesh stream keeps the mesh but still
    # charges the pod (another scope's wide-auto arrival must not see
    # an idle pod and stack a second column-sliced stream)
    p_mesh = place_stream(be, "foreground",
                          scope=QueueScope(placement="mesh"))
    assert p_mesh.chip is None and p_mesh.backend is be
    assert not pool.idle()
    p_auto_wide = place_stream(be, "foreground", scope=scope, wide=True)
    assert p_auto_wide.chip is not None
    p_auto_wide.close()
    p_mesh.close()
    p = place_stream(be, "foreground",
                     scope=QueueScope(placement="chip"), wide=True)
    assert p.chip is not None
    p.close()
    assert pool_for(be).idle()
    # non-wide small stream in auto mode routes to a chip
    p = place_stream(be, "recovery", scope=scope)
    assert p.chip is not None
    p.close()


def test_scheduler_disabled_disables_placement():
    be = JaxBackend(CTX)
    scope = QueueScope(enabled=False)
    p = place_stream(be, "foreground", scope=scope)
    assert p.queue is None and p.chip is None and p.backend is be
    p.close()


# ------------------------------------------------------- bit-identity


def test_pod_sharded_pjit_encode_bit_identical(monkeypatch):
    """ISSUE 15: the wide/mesh path's explicit NamedSharding/pjit
    encode (stripe-axis-constrained, full device mesh) is bit-identical
    to the shard_map lowering, the single-device chip path, and the
    CPU truth — ragged tail included — and the knob really selects the
    lowering."""
    from seaweedfs_tpu.ops.rs_jax import RSJax
    from seaweedfs_tpu.parallel import MeshRS, make_mesh, pad_cols

    rng = np.random.default_rng(0xB0D)
    data = rng.integers(0, 256, (K, 3 * 4096 + 131), dtype=np.uint8)
    want = CpuBackend(CTX).encode(data)

    rs = RSJax(K, M, impl="xla")
    mesh = make_mesh(8)

    def mesh_encode(m):
        padded, n = pad_cols(data, m.n_devices)
        return np.asarray(m.encode(m.put(padded)), dtype=np.uint8)[:, :n]

    monkeypatch.delenv("SEAWEED_EC_POD_PJIT", raising=False)
    pod = MeshRS(rs, mesh)
    assert pod.pod_sharded, "xla impl must take the pjit pod lowering"
    got_pjit = mesh_encode(pod)

    monkeypatch.setenv("SEAWEED_EC_POD_PJIT", "0")
    legacy = MeshRS(rs, mesh)
    assert not legacy.pod_sharded
    got_shard_map = mesh_encode(legacy)

    single = JaxBackend(CTX, impl="xla", n_devices=1)
    got_single = np.asarray(
        single.to_host(single.encode_staged(single.to_device(data))),
        dtype=np.uint8,
    )
    assert np.array_equal(got_pjit, want)
    assert np.array_equal(got_shard_map, want)
    assert np.array_equal(got_single, want)


def test_chip_vs_mesh_vs_single_bit_identical():
    """The same stream through a placed chip, the column mesh, and a
    single-device backend yields byte-identical output (ragged tail
    included) — the acceptance bit-identity criterion."""
    mesh_be = JaxBackend(CTX)
    single_be = JaxBackend(CTX, impl="xla", n_devices=1)
    cpu = CpuBackend(CTX)
    coeffs = decode_coeffs((0, 13), tuple(range(1, 11)))
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (K, 5 * 4096 + 997), dtype=np.uint8)
    want = cpu.apply(coeffs, data)

    chip_scope = QueueScope(placement="chip")
    placement = place_stream(mesh_be, "foreground", scope=chip_scope,
                             cost_hint=2 * data.shape[1])
    assert placement.chip is not None
    try:
        got_chip = run_stream(placement.backend, placement.queue, coeffs, data)
    finally:
        placement.close()
    got_mesh = run_stream(mesh_be, DeviceQueue(), coeffs, data)
    got_single = run_stream(single_be, DeviceQueue(), coeffs, data)
    assert np.array_equal(got_chip, want)
    assert np.array_equal(got_mesh, want)
    assert np.array_equal(got_single, want)


def test_encode_volume_placed_bit_identical_to_cpu(tmp_path):
    """Full ec_encode_volume through the mesh backend under chip
    placement: shard bytes and .ecsum CRCs equal the CPU encode —
    the encoder's placement integration is output-invisible."""
    rng = np.random.default_rng(6)
    vol = Volume(str(tmp_path), 1, needle_map_kind="memory")
    for nid in range(1, 6):
        vol.write_needle(Needle(
            cookie=9, needle_id=nid,
            data=rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes(),
        ))
    vol.flush()
    base = vol.base_file_name(str(tmp_path), "", 1)
    vol.close()

    mesh_be = JaxBackend(CTX)
    pool = pool_for(mesh_be)
    ec_encode_volume(
        base, CTX, backend=mesh_be, batch_size=32 * 1024 + 7,
        scheduler=QueueScope(placement="chip"),
    )
    assert pool.idle()  # encode stream released its chip
    placed_prot = BitrotProtection.load(base + ".ecsum")
    shard_bytes = {}
    for i in range(CTX.total):
        with open(base + CTX.to_ext(i), "rb") as f:
            shard_bytes[i] = f.read()
        os.unlink(base + CTX.to_ext(i))
    os.unlink(base + ".ecsum")

    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    cpu_prot = BitrotProtection.load(base + ".ecsum")
    assert placed_prot.shard_crcs == cpu_prot.shard_crcs
    assert placed_prot.shard_sizes == cpu_prot.shard_sizes
    for i in range(CTX.total):
        with open(base + CTX.to_ext(i), "rb") as f:
            assert f.read() == shard_bytes[i], f"shard {i} differs"


# --------------------------------------------------------- cost model


def test_cost_model_one_row_reconstruction_not_starved():
    """window=1, recovery share 0.2: against a saturating foreground
    ENCODE-cost stream (m=4 rows/batch), a recovery stream of 1-row
    reconstruction batches is admitted ~m x more often than the old
    byte-denominated accounting allowed — its batches cost 1/m as much,
    so the same banked credit covers m x more of them."""
    W = 10_000
    q = DeviceQueue(window=1, shares={"recovery": 0.2})
    order: list = []
    stop = threading.Event()

    def recovery_one_row():
        s = q.stream("recovery")
        try:
            while not stop.is_set():
                t, _ = s.dispatch(lambda: None, batch_cost(1, W))
                order.append("recovery")
                stop.wait(0.001)
                s.release(t)
        finally:
            s.close()

    rt = threading.Thread(target=recovery_one_row)
    rt.start()
    try:
        while len(order) < 5:
            stop.wait(0.001)
        s = q.stream("foreground")
        try:
            for _ in range(40):
                t, _ = s.dispatch(lambda: None, batch_cost(4, W))
                order.append("foreground")
                stop.wait(0.001)
                s.release(t)
        finally:
            s.close()
    finally:
        stop.set()
        rt.join(timeout=30)
    span = [i for i, c in enumerate(order) if c == "foreground"]
    window = order[span[0] : span[-1] + 1]
    fg = sum(1 for c in window if c == "foreground")
    rec = sum(1 for c in window if c == "recovery")
    # credit per fg batch = 4W * 0.2/0.8 = W = one whole 1-row batch:
    # expect ~1 recovery admission per foreground admission. The old
    # byte accounting (every batch = k*W bytes) would yield ~0.25.
    assert rec >= fg * 0.5, (fg, rec)
    assert rec <= fg * 2.0, (fg, rec)
    assert q.inflight == 0


def test_queue_cost_accounting_sums_to_dispatched_work():
    q = DeviceQueue(window=2)
    s = q.stream("foreground")
    costs = [batch_cost(4, w) for w in (100, 7, 4096, 1)]
    try:
        for c in costs:
            t, _ = s.dispatch(lambda: None, c)
            s.release(t)
    finally:
        s.close()
    st = q.stats()["foreground"]
    assert st["admitted_cost"] == st["drained_cost"] == sum(costs)
    assert st["admitted"] == st["drained"] == len(costs)
    assert q.load() == 0 and q.inflight == 0


# --------------------------------------------------- chaos: chip death


@pytest.mark.chaos
def test_chip_death_isolates_its_streams():
    """Two streams placed on two chips of one pool; one chip's to_host
    dies repeatedly. Only the victim chip's batches replay on CPU
    (bit-identical), the sibling chip's stream never falls back, and
    each chip's OWN breaker sees the failures."""
    fb = FallbackBackend(
        JaxBackend(CTX, impl="xla", n_devices=8),
        CpuBackend(CTX),
        breaker=CircuitBreaker(failure_threshold=50, reset_timeout=9999.0),
    )
    assert fb.primary._mesh_rs is not None  # 8-dev mesh engaged
    scope = QueueScope(placement="chip")
    p0 = place_stream(fb, "foreground", scope=scope, cost_hint=100)
    p1 = place_stream(fb, "recovery", scope=scope, cost_hint=100)
    assert p0.chip != p1.chip
    victim_be, sibling_be = p0.backend, p1.backend
    assert victim_be.chip_label != sibling_be.chip_label
    # per-chip FallbackBackends with per-chip breakers
    assert victim_be is not fb and sibling_be is not fb
    assert victim_be.breaker is not sibling_be.breaker

    cpu = CpuBackend(CTX)
    c_fg = decode_coeffs((0,), tuple(range(1, 11)))
    c_rec = decode_coeffs((13,), tuple(range(10)))
    rng = np.random.default_rng(21)
    d_fg = rng.integers(0, 256, (K, 12 * 4096), dtype=np.uint8)
    d_rec = rng.integers(0, 256, (K, 12 * 4096), dtype=np.uint8)

    victim_label = victim_be.chip_label
    state = {"fired": 0}

    def kill_victim_chip(ctx):
        if ctx.get("chip") == victim_label and state["fired"] < 2:
            state["fired"] += 1
            raise faults.InjectedIOError(f"chip {victim_label} died")

    results: dict = {}
    errors: list = []

    def run(name, placement, coeffs, data, priority):
        try:
            results[name] = run_stream(
                placement.backend, placement.queue, coeffs, data, priority
            )
        except BaseException as e:  # pragma: no cover
            errors.append((name, e))
        finally:
            placement.close()

    with faults.injected(
        "ec.backend.device.to_host", kill_victim_chip, when=faults.always()
    ):
        ts = [
            threading.Thread(
                target=run, args=("victim", p0, c_fg, d_fg, "foreground")
            ),
            threading.Thread(
                target=run, args=("sibling", p1, c_rec, d_rec, "recovery")
            ),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    assert not errors, errors
    assert state["fired"] == 2
    assert np.array_equal(results["victim"], cpu.apply(c_fg, d_fg))
    assert np.array_equal(results["sibling"], cpu.apply(c_rec, d_rec))
    # isolation: only the victim chip fell back; its sibling kept its
    # chip and a clean breaker
    assert victim_be.fallback_batches == 2
    assert sibling_be.fallback_batches == 0
    assert sibling_be.breaker.state == "closed"
    assert victim_be.breaker.state == "closed"  # below threshold
    assert fb.fallback_batches == 0  # the pooled wrapper never dispatched
    assert pool_for(fb).idle()


# ------------------------------------------------- per-Store scopes


def make_degraded_ec_volume(tmp_path, vid, seed=0):
    rng = np.random.default_rng(seed)
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, 9):
        data = rng.integers(0, 256, int(rng.integers(1, 30_000)),
                            dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x1000 + i, needle_id=i, data=data))
        payloads[i] = data
    v.close()
    base = Volume.base_file_name(str(tmp_path), "", vid)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    os.unlink(base + CTX.to_ext(0))  # degrade: reads reconstruct
    os.unlink(base + ".dat")
    os.unlink(base + ".idx")
    return payloads


def test_per_store_scheduler_scope(tmp_path):
    """A Store with scheduler knobs gets its OWN QueueScope (threaded
    to its EcVolumes like the interval cache); a bare Store rides the
    process-wide default; two configured Stores never clobber each
    other's config."""
    from seaweedfs_tpu.ec.device_queue import default_scope

    d1 = tmp_path / "s1"
    d2 = tmp_path / "s2"
    d1.mkdir()
    d2.mkdir()
    payloads = make_degraded_ec_volume(d1, 1, seed=7)
    make_degraded_ec_volume(d2, 1, seed=8)

    s1 = Store([str(d1)], ec_backend="cpu", ec_queue_window=2,
               ec_placement="mesh")
    s2 = Store([str(d2)], ec_backend="cpu",
               ec_queue_shares={"recovery": 0.5})
    s3 = Store([str(tmp_path)], ec_backend="cpu")
    try:
        assert s1.ec_scheduler is not s2.ec_scheduler
        assert s3.ec_scheduler is default_scope()
        cfg1 = s1.ec_scheduler.configure()
        cfg2 = s2.ec_scheduler.configure()
        assert cfg1["window"] == 2 and cfg1["placement"] == "mesh"
        assert cfg2["window"] != 2 and cfg2["shares"]["recovery"] == 0.5
        assert cfg2["placement"] == "auto"
        # one tenant reconfiguring stays inside its scope
        s1.ec_scheduler.configure(shares={"scrub": 0.3})
        assert s2.ec_scheduler.configure()["shares"]["scrub"] != 0.3
        # the scope reaches the mounted EC volumes (degraded-read path)
        ev = s1.find_ec_volume(1)
        assert ev is not None and ev.scheduler is s1.ec_scheduler
        nid = next(iter(payloads))
        assert ev.read_needle(nid, cookie=0x1000 + nid).data == payloads[nid]
        # per-scope stats snapshots are disjoint
        assert isinstance(s1.ec_scheduler.stats_snapshot(), list)
    finally:
        s1.close()
        s2.close()
        s3.close()


# ----------------------------------------------- live load-feedback routing


def test_live_queue_load_steers_routing_and_is_recorded():
    """PR 14 acceptance: routing reads LIVE DeviceQueue.load(), not just
    the static placed-cost ledger — skew one chip's queue and watch the
    next stream land elsewhere, with the decision's signal source and
    live loads recorded as a span event."""
    from seaweedfs_tpu.utils import trace

    be = JaxBackend(CTX)
    pool = pool_for(be)
    scope = QueueScope(placement="chip")
    # ledger idle: with no live signal the deterministic pick is chip 0
    p0 = place_stream(be, "foreground", scope=scope, cost_hint=1)
    assert p0.chip == 0
    p0.close()
    # skew chip 0's LIVE queue load (an admission the ledger never saw:
    # the one-shot gateway-read shape) and the next stream must follow
    # the live signal to chip 1 even though the ledger reads all-zero
    q0 = scope.for_backend(pool.chip_backend(0))
    trace.configure(enabled=True, ring_size=64, slow_op_s=0.0)
    try:
        with q0.admission("foreground", 50_000):
            assert q0.load() == 50_000
            sp = trace.start("ec.encode", name="live-routing-test")
            p1 = place_stream(be, "foreground", scope=scope,
                              cost_hint=1, span=sp)
            trace.finish(sp)
            assert p1.chip is not None and p1.chip != 0
            p1.close()
            ev = [e for e in sp.to_dict()["events"]
                  if e["name"] == "placement"]
            assert ev, "placement decision must be recorded"
            attrs = ev[-1]["attrs"]
            assert attrs["signal"] == "live"
            assert attrs["live_loads"][0] == 50_000
            assert attrs["chip"] == pool.labels[p1.chip]
        # queue drained: the live signal is gone, chip 0 wins again
        p2 = place_stream(be, "foreground", scope=scope, cost_hint=1)
        assert p2.chip == 0
        p2.close()
    finally:
        trace.configure(enabled=False, slow_op_s=0.0)
        trace.reset()


def test_open_breaker_repels_placement():
    """A chip whose fallback breaker is OPEN (its streams are running
    on CPU) loses routing to any healthy sibling, however the ledger
    and queue loads compare."""
    base = FallbackBackend(JaxBackend(CTX), CpuBackend(CTX))
    pool = pool_for(base)
    scope = QueueScope(placement="chip")
    chip0 = pool.chip_backend(0)
    assert isinstance(chip0, FallbackBackend)
    scope.for_backend(chip0)  # materialize the queue (its label carries
    # the breaker state into queue_loads)
    for _ in range(chip0.breaker.failure_threshold):
        chip0.breaker.record_failure()
    assert chip0.breaker.state == "open"
    try:
        p = place_stream(base, "foreground", scope=scope, cost_hint=1)
        assert p.chip is not None and p.chip != 0
        p.close()
    finally:
        chip0.breaker.record_success()


def test_placement_decision_counter_by_signal():
    from seaweedfs_tpu.ec.chip_pool import _placement_decisions

    be = JaxBackend(CTX)
    pool = pool_for(be)
    scope = QueueScope(placement="chip")
    before = dict(_placement_decisions.snapshot())
    p = place_stream(be, "foreground", scope=scope, cost_hint=1)
    p.close()
    after = _placement_decisions.snapshot()
    assert after.get(("ledger",), 0) == before.get(("ledger",), 0) + 1
    q0 = scope.for_backend(pool.chip_backend(0))
    with q0.admission("foreground", 999):
        p = place_stream(be, "foreground", scope=scope, cost_hint=1)
        p.close()
    assert _placement_decisions.snapshot().get(("live",), 0) == (
        before.get(("live",), 0) + 1
    )
