"""FUSE mount tests (reference test/fuse_integration): real kernel mount
of the filer namespace, exercised with plain os/file calls. Skipped
where /dev/fuse or fusermount is unavailable."""

import os
import shutil
import subprocess
import sys
import time

import pytest
import requests

from conftest import allocate_port as free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not os.path.exists("/dev/fuse") or shutil.which("fusermount") is None,
    reason="FUSE unavailable",
)


@pytest.fixture
def mounted(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    mport, fport = free_port(), free_port()
    mnt = str(tmp_path / "mnt")
    os.makedirs(mnt)
    srv = mp = None
    try:
        srv = subprocess.Popen(
            [
                sys.executable, "-m", "seaweedfs_tpu.server", "server",
                "-masterPort", str(mport), "-port", str(free_port()),
                "-filerPort", str(fport), "-filer",
                "-dir", str(tmp_path / "data"), "-ec.backend", "cpu",
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        deadline = time.time() + 40
        while True:
            try:
                requests.get(f"http://localhost:{fport}/", timeout=1)
                break
            except requests.RequestException:
                assert time.time() < deadline and srv.poll() is None
                time.sleep(0.2)
        mp = subprocess.Popen(
            [
                sys.executable, "-m", "seaweedfs_tpu.mount",
                "-filer", f"localhost:{fport}", "-dir", mnt,
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        deadline = time.time() + 20
        while not os.path.ismount(mnt):
            if mp.poll() is not None:
                pytest.skip(
                    "mount failed (container restriction): "
                    + mp.stdout.read().decode()[:300]
                )
            assert time.time() < deadline
            time.sleep(0.2)
        yield mnt, fport
    finally:
        # teardown must run even when setup skips/fails: a leaked server
        # would hold its ports for the rest of the pytest run
        if os.path.ismount(mnt):
            subprocess.run(["fusermount", "-u", mnt], timeout=10)
        if mp is not None:
            try:
                mp.wait(timeout=10)
            except subprocess.TimeoutExpired:
                mp.kill()
        if srv is not None:
            srv.terminate()
            try:
                srv.wait(timeout=10)
            except subprocess.TimeoutExpired:
                srv.kill()


def test_mount_posix_ops(mounted):
    mnt, fport = mounted
    base = f"http://localhost:{fport}"
    requests.post(f"{base}/seed/hello.txt", data=b"from http")

    assert "seed" in os.listdir(mnt)
    assert open(f"{mnt}/seed/hello.txt").read() == "from http"

    os.makedirs(f"{mnt}/work/sub")
    with open(f"{mnt}/work/sub/data.bin", "wb") as f:
        f.write(b"B" * 70_000)
    assert os.stat(f"{mnt}/work/sub/data.bin").st_size == 70_000
    # visible via HTTP (write-through on close)
    assert requests.get(f"{base}/work/sub/data.bin").content == b"B" * 70_000

    os.rename(f"{mnt}/work/sub/data.bin", f"{mnt}/work/moved.bin")
    assert requests.get(f"{base}/work/moved.bin").status_code == 200
    with open(f"{mnt}/work/moved.bin", "r+b") as f:
        f.seek(0, 2)
        f.write(b"tail")
    assert requests.get(f"{base}/work/moved.bin").content == b"B" * 70_000 + b"tail"

    os.remove(f"{mnt}/work/moved.bin")
    os.rmdir(f"{mnt}/work/sub")
    assert requests.get(f"{base}/work/moved.bin").status_code == 404
    # cp through the mount
    subprocess.run(
        ["cp", f"{mnt}/seed/hello.txt", f"{mnt}/seed/copy.txt"], check=True
    )
    assert requests.get(f"{base}/seed/copy.txt").content == b"from http"


def test_mount_large_write_chunked(mounted):
    """dd a file bigger than the page writer's flush bound through the
    kernel mount: spilled chunks + commit must be byte-exact, and the
    committed entry must actually be chunked (not inline)."""
    import hashlib

    mnt, fport = mounted
    base = f"http://localhost:{fport}"
    total = 24 * 1024 * 1024  # > 2x FLUSH_BYTES
    h = hashlib.sha256()
    os.makedirs(f"{mnt}/big", exist_ok=True)
    with open(f"{mnt}/big/stream.bin", "wb") as f:
        for i in range(total // (1024 * 1024)):
            block = bytes([i % 251]) * (1024 * 1024)
            f.write(block)
            h.update(block)
    assert os.stat(f"{mnt}/big/stream.bin").st_size == total
    r = requests.get(f"{base}/big/stream.bin")
    assert r.status_code == 200
    assert hashlib.sha256(r.content).hexdigest() == h.hexdigest()
    # stored as chunks, not one buffered blob
    meta = requests.get(f"{base}/big/stream.bin?chunks=true").json()
    assert len(meta["chunks"]) >= total // (8 * 1024 * 1024)
    # random access back through the mount
    with open(f"{mnt}/big/stream.bin", "rb") as f:
        f.seek(5 * 1024 * 1024 + 123)
        assert f.read(4) == bytes([5 % 251]) * 4


def test_mount_posix_metadata(mounted):
    """pjdfstest-subset: chmod/chown/utimens persist (no more silent
    no-ops), xattrs round-trip, symlink/readlink, hardlink."""
    mnt, fport = mounted
    os.makedirs(f"{mnt}/meta")
    p = f"{mnt}/meta/f.txt"
    with open(p, "w") as f:
        f.write("hello meta")

    # chmod persists and survives the attr-cache TTL
    os.chmod(p, 0o640)
    time.sleep(1.1)  # ATTR_TTL
    assert (os.stat(p).st_mode & 0o7777) == 0o640

    # utimens persists
    os.utime(p, (1_600_000_000, 1_600_000_000))
    time.sleep(1.1)
    assert os.stat(p).st_mtime == 1_600_000_000

    # chown persists in the entry metadata (we run unprivileged, so
    # only verify via the filer metadata, chown to self never fails)
    os.chown(p, os.getuid(), os.getgid())
    meta = requests.get(
        f"http://localhost:{fport}/meta/f.txt?chunks=true"
    ).json()
    assert meta.get("uid", os.getuid()) == os.getuid()

    # xattr round trip incl. binary values and flags. Some sandboxed
    # kernels refuse FUSE xattr wholesale (EOPNOTSUPP before our
    # callbacks ever run) — skip the block there, keep the rest of the
    # POSIX surface asserted.
    import errno as _errno

    try:
        os.setxattr(p, "user.color", b"blu\x00e")
        xattr_supported = True
    except OSError as e:
        if e.errno != _errno.ENOTSUP:
            raise
        xattr_supported = False
    if xattr_supported:
        os.setxattr(p, "user.shape", b"round")
        assert os.getxattr(p, "user.color") == b"blu\x00e"
        assert sorted(os.listxattr(p)) == ["user.color", "user.shape"]
        with pytest.raises(OSError):  # XATTR_CREATE on existing
            os.setxattr(p, "user.color", b"x", os.XATTR_CREATE)
        with pytest.raises(OSError):  # XATTR_REPLACE on missing
            os.setxattr(p, "user.nope", b"x", os.XATTR_REPLACE)
        os.removexattr(p, "user.shape")
        assert os.listxattr(p) == ["user.color"]
        with pytest.raises(OSError):
            os.getxattr(p, "user.shape")

    # symlink / readlink
    os.symlink("f.txt", f"{mnt}/meta/ln")
    assert os.readlink(f"{mnt}/meta/ln") == "f.txt"
    assert os.path.islink(f"{mnt}/meta/ln")
    assert open(f"{mnt}/meta/ln").read() == "hello meta"

    # hardlink: same content, nlink visible
    os.link(p, f"{mnt}/meta/hard.txt")
    assert open(f"{mnt}/meta/hard.txt").read() == "hello meta"
    time.sleep(1.1)
    assert os.stat(p).st_nlink >= 2

    # create() mode honored
    fd = os.open(f"{mnt}/meta/modefile", os.O_CREAT | os.O_WRONLY, 0o600)
    os.write(fd, b"x")
    os.close(fd)
    time.sleep(1.1)
    assert (os.stat(f"{mnt}/meta/modefile").st_mode & 0o7777) == 0o600


def test_mount_posix_locks(mounted):
    """fcntl byte-range locks ride the filer lock service: two
    processes (this one and a subprocess) must conflict."""
    import fcntl
    import textwrap

    mnt, _ = mounted
    p = f"{mnt}/lockfile"
    with open(p, "w") as f:
        f.write("0123456789")

    f1 = open(p, "r+b")
    fcntl.lockf(f1, fcntl.LOCK_EX | fcntl.LOCK_NB, 4, 0)  # lock [0,4)

    # another PROCESS must see the conflict (locks coordinate through
    # the filer, not the local kernel)
    probe = textwrap.dedent(f"""
        import fcntl, sys
        f = open({p!r}, "r+b")
        try:
            fcntl.lockf(f, fcntl.LOCK_EX | fcntl.LOCK_NB, 4, 0)
            print("GRANTED")
        except OSError:
            print("BLOCKED")
    """)
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True,
        timeout=30,
    )
    assert "BLOCKED" in out.stdout, out.stdout + out.stderr

    # a non-overlapping range is fine from the other process
    probe2 = probe.replace("LOCK_NB, 4, 0", "LOCK_NB, 2, 6")
    out = subprocess.run(
        [sys.executable, "-c", probe2], capture_output=True, text=True,
        timeout=30,
    )
    assert "GRANTED" in out.stdout, out.stdout + out.stderr

    # unlock releases for other processes
    fcntl.lockf(f1, fcntl.LOCK_UN, 4, 0)
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True,
        timeout=30,
    )
    assert "GRANTED" in out.stdout, out.stdout + out.stderr
    f1.close()
