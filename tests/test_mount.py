"""FUSE mount tests (reference test/fuse_integration): real kernel mount
of the filer namespace, exercised with plain os/file calls. Skipped
where /dev/fuse or fusermount is unavailable."""

import os
import shutil
import subprocess
import sys
import time

import pytest
import requests

from conftest import allocate_port as free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not os.path.exists("/dev/fuse") or shutil.which("fusermount") is None,
    reason="FUSE unavailable",
)


@pytest.fixture
def mounted(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    mport, fport = free_port(), free_port()
    mnt = str(tmp_path / "mnt")
    os.makedirs(mnt)
    srv = mp = None
    try:
        srv = subprocess.Popen(
            [
                sys.executable, "-m", "seaweedfs_tpu.server", "server",
                "-masterPort", str(mport), "-port", str(free_port()),
                "-filerPort", str(fport), "-filer",
                "-dir", str(tmp_path / "data"), "-ec.backend", "cpu",
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        deadline = time.time() + 40
        while True:
            try:
                requests.get(f"http://localhost:{fport}/", timeout=1)
                break
            except requests.RequestException:
                assert time.time() < deadline and srv.poll() is None
                time.sleep(0.2)
        mp = subprocess.Popen(
            [
                sys.executable, "-m", "seaweedfs_tpu.mount",
                "-filer", f"localhost:{fport}", "-dir", mnt,
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        deadline = time.time() + 20
        while not os.path.ismount(mnt):
            if mp.poll() is not None:
                pytest.skip(
                    "mount failed (container restriction): "
                    + mp.stdout.read().decode()[:300]
                )
            assert time.time() < deadline
            time.sleep(0.2)
        yield mnt, fport
    finally:
        # teardown must run even when setup skips/fails: a leaked server
        # would hold its ports for the rest of the pytest run
        if os.path.ismount(mnt):
            subprocess.run(["fusermount", "-u", mnt], timeout=10)
        if mp is not None:
            try:
                mp.wait(timeout=10)
            except subprocess.TimeoutExpired:
                mp.kill()
        if srv is not None:
            srv.terminate()
            try:
                srv.wait(timeout=10)
            except subprocess.TimeoutExpired:
                srv.kill()


def test_mount_posix_ops(mounted):
    mnt, fport = mounted
    base = f"http://localhost:{fport}"
    requests.post(f"{base}/seed/hello.txt", data=b"from http")

    assert "seed" in os.listdir(mnt)
    assert open(f"{mnt}/seed/hello.txt").read() == "from http"

    os.makedirs(f"{mnt}/work/sub")
    with open(f"{mnt}/work/sub/data.bin", "wb") as f:
        f.write(b"B" * 70_000)
    assert os.stat(f"{mnt}/work/sub/data.bin").st_size == 70_000
    # visible via HTTP (write-through on close)
    assert requests.get(f"{base}/work/sub/data.bin").content == b"B" * 70_000

    os.rename(f"{mnt}/work/sub/data.bin", f"{mnt}/work/moved.bin")
    assert requests.get(f"{base}/work/moved.bin").status_code == 200
    with open(f"{mnt}/work/moved.bin", "r+b") as f:
        f.seek(0, 2)
        f.write(b"tail")
    assert requests.get(f"{base}/work/moved.bin").content == b"B" * 70_000 + b"tail"

    os.remove(f"{mnt}/work/moved.bin")
    os.rmdir(f"{mnt}/work/sub")
    assert requests.get(f"{base}/work/moved.bin").status_code == 404
    # cp through the mount
    subprocess.run(
        ["cp", f"{mnt}/seed/hello.txt", f"{mnt}/seed/copy.txt"], check=True
    )
    assert requests.get(f"{base}/seed/copy.txt").content == b"from http"


def test_mount_large_write_chunked(mounted):
    """dd a file bigger than the page writer's flush bound through the
    kernel mount: spilled chunks + commit must be byte-exact, and the
    committed entry must actually be chunked (not inline)."""
    import hashlib

    mnt, fport = mounted
    base = f"http://localhost:{fport}"
    total = 24 * 1024 * 1024  # > 2x FLUSH_BYTES
    h = hashlib.sha256()
    os.makedirs(f"{mnt}/big", exist_ok=True)
    with open(f"{mnt}/big/stream.bin", "wb") as f:
        for i in range(total // (1024 * 1024)):
            block = bytes([i % 251]) * (1024 * 1024)
            f.write(block)
            h.update(block)
    assert os.stat(f"{mnt}/big/stream.bin").st_size == total
    r = requests.get(f"{base}/big/stream.bin")
    assert r.status_code == 200
    assert hashlib.sha256(r.content).hexdigest() == h.hexdigest()
    # stored as chunks, not one buffered blob
    meta = requests.get(f"{base}/big/stream.bin?chunks=true").json()
    assert len(meta["chunks"]) >= total // (8 * 1024 * 1024)
    # random access back through the mount
    with open(f"{mnt}/big/stream.bin", "rb") as f:
        f.seek(5 * 1024 * 1024 + 123)
        assert f.read(4) == bytes([5 % 251]) * 4
