"""Iceberg REST catalog + AWS S3Tables API (reference weed/s3api/iceberg
and s3api_tables.go), driven over real HTTP against a live gateway."""

from __future__ import annotations

import json
import time

import pytest
import requests

from conftest import allocate_port as free_port
from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.s3 import S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tbl")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


@pytest.fixture
def s3(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    srv = S3Server(filer, ip="localhost", port=free_port())
    srv.start()
    yield f"http://localhost:{srv.port}", srv
    srv.stop()
    filer.close()


SCHEMA = {
    "type": "struct",
    "schema-id": 0,
    "fields": [
        {"id": 1, "name": "id", "required": True, "type": "long"},
        {"id": 2, "name": "data", "required": False, "type": "string"},
    ],
}


def test_iceberg_catalog_lifecycle(s3):
    url, _srv = s3
    ib = f"{url}/iceberg/v1"

    r = requests.get(f"{ib}/config", timeout=10)
    assert r.status_code == 200 and "defaults" in r.json()

    # namespace CRUD
    r = requests.post(
        f"{ib}/namespaces",
        json={"namespace": ["analytics"], "properties": {"owner": "t"}},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert requests.get(f"{ib}/namespaces", timeout=10).json()[
        "namespaces"
    ] == [["analytics"]]
    r = requests.get(f"{ib}/namespaces/analytics", timeout=10)
    assert r.json()["properties"] == {"owner": "t"}
    assert (
        requests.head(f"{ib}/namespaces/analytics", timeout=10).status_code
        == 204
    )
    r = requests.post(
        f"{ib}/namespaces/analytics/properties",
        json={"removals": ["owner"], "updates": {"team": "core"}},
        timeout=10,
    )
    assert r.json()["updated"] == ["team"]

    # table create -> load -> metadata file readable over plain S3
    r = requests.post(
        f"{ib}/namespaces/analytics/tables",
        json={"name": "events", "schema": SCHEMA, "properties": {"p": "1"}},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    created = r.json()
    md = created["metadata"]
    assert md["format-version"] == 2
    assert md["schemas"][0]["fields"][0]["name"] == "id"
    assert md["last-column-id"] == 2
    loc = created["metadata-location"]
    assert loc.startswith("s3://default/analytics/events/metadata/")

    r = requests.get(f"{ib}/namespaces/analytics/tables/events", timeout=10)
    assert r.status_code == 200
    assert r.json()["metadata"]["table-uuid"] == md["table-uuid"]
    # the metadata file is an ordinary S3 object
    key = loc[len("s3://default/") :]
    r = requests.get(f"{url}/default/{key}", timeout=10)
    assert r.status_code == 200
    assert json.loads(r.content)["table-uuid"] == md["table-uuid"]

    # commit: set-properties writes a NEW metadata file + logs the old
    r = requests.post(
        f"{ib}/namespaces/analytics/tables/events",
        json={"updates": [{"action": "set-properties", "updates": {"x": "y"}}]},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    out = r.json()
    assert out["metadata"]["properties"]["x"] == "y"
    assert out["metadata-location"] != loc
    assert out["metadata"]["metadata-log"][-1]["metadata-file"] == loc
    # unsupported update kinds fail loudly
    r = requests.post(
        f"{ib}/namespaces/analytics/tables/events",
        json={"updates": [{"action": "add-snapshot", "snapshot": {}}]},
        timeout=10,
    )
    assert r.status_code == 400

    # rename + list + drop
    requests.post(
        f"{ib}/namespaces",
        json={"namespace": ["archive"]},
        timeout=10,
    )
    r = requests.post(
        f"{ib}/tables/rename",
        json={
            "source": {"namespace": ["analytics"], "name": "events"},
            "destination": {"namespace": ["archive"], "name": "events_v2"},
        },
        timeout=10,
    )
    assert r.status_code == 204, r.text
    ids = requests.get(
        f"{ib}/namespaces/archive/tables", timeout=10
    ).json()["identifiers"]
    assert ids == [{"namespace": ["archive"], "name": "events_v2"}]
    assert (
        requests.get(
            f"{ib}/namespaces/analytics/tables/events", timeout=10
        ).status_code
        == 404
    )
    # nonempty namespace refuses to drop; empty one drops
    assert (
        requests.delete(f"{ib}/namespaces/archive", timeout=10).status_code
        == 409
    )
    assert (
        requests.delete(
            f"{ib}/namespaces/archive/tables/events_v2", timeout=10
        ).status_code
        == 204
    )
    assert (
        requests.delete(f"{ib}/namespaces/archive", timeout=10).status_code
        == 204
    )


def test_iceberg_prefixed_catalog_uses_table_bucket(s3):
    url, _srv = s3
    # create a table bucket via S3Tables, then address it as the
    # Iceberg {prefix}
    r = requests.post(
        f"{url}/",
        json={"name": "warehouse1"},
        headers={"X-Amz-Target": "S3Tables.CreateTableBucket"},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    ib = f"{url}/iceberg/v1/warehouse1"
    r = requests.post(
        f"{ib}/namespaces", json={"namespace": ["raw"]}, timeout=10
    )
    assert r.status_code == 200, r.text
    r = requests.post(
        f"{ib}/namespaces/raw/tables",
        json={"name": "t1", "schema": SCHEMA},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert r.json()["metadata-location"].startswith(
        "s3://warehouse1/raw/t1/metadata/"
    )


def test_s3tables_target_and_rest_ops(s3):
    url, _srv = s3
    tgt = lambda op: {"X-Amz-Target": f"S3Tables.{op}"}  # noqa: E731

    r = requests.post(
        f"{url}/", json={"name": "tb1"}, headers=tgt("CreateTableBucket"),
        timeout=10,
    )
    assert r.status_code == 200
    arn = r.json()["arn"]
    # duplicate -> 409
    assert (
        requests.post(
            f"{url}/", json={"name": "tb1"},
            headers=tgt("CreateTableBucket"), timeout=10,
        ).status_code
        == 409
    )
    names = [
        b["name"]
        for b in requests.post(
            f"{url}/", json={}, headers=tgt("ListTableBuckets"), timeout=10
        ).json()["tableBuckets"]
    ]
    assert "tb1" in names

    # namespace + table through the target protocol
    r = requests.post(
        f"{url}/",
        json={"tableBucketARN": arn, "namespace": ["ns1"]},
        headers=tgt("CreateNamespace"),
        timeout=10,
    )
    assert r.status_code == 200, r.text
    r = requests.post(
        f"{url}/",
        json={"tableBucketARN": arn, "namespace": "ns1", "name": "t"},
        headers=tgt("CreateTable"),
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert r.json()["metadataLocation"].startswith("s3://tb1/ns1/t/")

    r = requests.post(
        f"{url}/",
        json={"tableBucketARN": arn, "namespace": "ns1", "name": "t"},
        headers=tgt("GetTable"),
        timeout=10,
    )
    assert r.json()["format"] == "ICEBERG"

    # REST-style aliases (AWS CLI shapes)
    r = requests.get(f"{url}/buckets/{arn}", timeout=10)
    assert r.status_code == 200 and r.json()["name"] == "tb1"
    r = requests.get(f"{url}/namespaces/{arn}", timeout=10)
    assert r.json()["namespaces"] == [{"namespace": ["ns1"]}]
    r = requests.get(f"{url}/tables/{arn}", timeout=10)
    assert r.json()["tables"] == [{"namespace": ["ns1"], "name": "t"}]
    assert (
        requests.delete(
            f"{url}/tables/{arn}/ns1/t", timeout=10
        ).status_code
        == 204
    )
    assert (
        requests.delete(f"{url}/namespaces/{arn}/ns1", timeout=10).status_code
        == 204
    )
    assert requests.delete(f"{url}/buckets/{arn}", timeout=10).status_code == 204


def test_catalog_requires_admin_action(cluster):
    """A policy-limited identity must NOT get catalog admin (review
    r5): the tables surface bypasses _authorize, so it enforces the
    Admin action itself."""
    from seaweedfs_tpu.s3.auth import Identity, IdentityStore

    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    idents = IdentityStore()
    idents.add(Identity("admin", "AKADM", "adminsecret"))  # full access
    idents.add(
        Identity("ro", "AKRO", "rosecret", actions=("Read", "List"))
    )
    srv = S3Server(filer, ip="localhost", port=free_port(), identities=idents)
    srv.start()
    url = f"http://localhost:{srv.port}"
    try:
        from test_s3 import sign_request

        def call(ak, sk, body=b'{"name":"gated"}'):
            h = sign_request("POST", f"{url}/", ak, sk, body=body)
            h["X-Amz-Target"] = "S3Tables.CreateTableBucket"
            return requests.post(f"{url}/", data=body, headers=h, timeout=10)

        assert call("AKRO", "rosecret").status_code == 403
        assert call("AKADM", "adminsecret").status_code == 200
        # anonymous refused outright
        r = requests.post(
            f"{url}/",
            data=b"{}",
            headers={"X-Amz-Target": "S3Tables.ListTableBuckets"},
            timeout=10,
        )
        assert r.status_code == 403
    finally:
        srv.stop()
        filer.close()
