"""Iceberg REST catalog + AWS S3Tables API (reference weed/s3api/iceberg
and s3api_tables.go), driven over real HTTP against a live gateway."""

from __future__ import annotations

import json
import time

import pytest
import requests

from conftest import allocate_port as free_port
from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.s3 import S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tbl")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


@pytest.fixture
def s3(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    srv = S3Server(filer, ip="localhost", port=free_port())
    srv.start()
    yield f"http://localhost:{srv.port}", srv
    srv.stop()
    filer.close()


SCHEMA = {
    "type": "struct",
    "schema-id": 0,
    "fields": [
        {"id": 1, "name": "id", "required": True, "type": "long"},
        {"id": 2, "name": "data", "required": False, "type": "string"},
    ],
}


def test_iceberg_catalog_lifecycle(s3):
    url, _srv = s3
    ib = f"{url}/iceberg/v1"

    r = requests.get(f"{ib}/config", timeout=10)
    assert r.status_code == 200 and "defaults" in r.json()

    # namespace CRUD
    r = requests.post(
        f"{ib}/namespaces",
        json={"namespace": ["analytics"], "properties": {"owner": "t"}},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert requests.get(f"{ib}/namespaces", timeout=10).json()[
        "namespaces"
    ] == [["analytics"]]
    r = requests.get(f"{ib}/namespaces/analytics", timeout=10)
    assert r.json()["properties"] == {"owner": "t"}
    assert (
        requests.head(f"{ib}/namespaces/analytics", timeout=10).status_code
        == 204
    )
    r = requests.post(
        f"{ib}/namespaces/analytics/properties",
        json={"removals": ["owner"], "updates": {"team": "core"}},
        timeout=10,
    )
    assert r.json()["updated"] == ["team"]

    # table create -> load -> metadata file readable over plain S3
    r = requests.post(
        f"{ib}/namespaces/analytics/tables",
        json={"name": "events", "schema": SCHEMA, "properties": {"p": "1"}},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    created = r.json()
    md = created["metadata"]
    assert md["format-version"] == 2
    assert md["schemas"][0]["fields"][0]["name"] == "id"
    assert md["last-column-id"] == 2
    loc = created["metadata-location"]
    assert loc.startswith("s3://default/analytics/events/metadata/")

    r = requests.get(f"{ib}/namespaces/analytics/tables/events", timeout=10)
    assert r.status_code == 200
    assert r.json()["metadata"]["table-uuid"] == md["table-uuid"]
    # the metadata file is an ordinary S3 object
    key = loc[len("s3://default/") :]
    r = requests.get(f"{url}/default/{key}", timeout=10)
    assert r.status_code == 200
    assert json.loads(r.content)["table-uuid"] == md["table-uuid"]

    # commit: set-properties writes a NEW metadata file + logs the old
    r = requests.post(
        f"{ib}/namespaces/analytics/tables/events",
        json={"updates": [{"action": "set-properties", "updates": {"x": "y"}}]},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    out = r.json()
    assert out["metadata"]["properties"]["x"] == "y"
    assert out["metadata-location"] != loc
    assert out["metadata"]["metadata-log"][-1]["metadata-file"] == loc
    # unsupported update kinds fail loudly
    r = requests.post(
        f"{ib}/namespaces/analytics/tables/events",
        json={"updates": [{"action": "add-snapshot", "snapshot": {}}]},
        timeout=10,
    )
    assert r.status_code == 400

    # rename + list + drop
    requests.post(
        f"{ib}/namespaces",
        json={"namespace": ["archive"]},
        timeout=10,
    )
    r = requests.post(
        f"{ib}/tables/rename",
        json={
            "source": {"namespace": ["analytics"], "name": "events"},
            "destination": {"namespace": ["archive"], "name": "events_v2"},
        },
        timeout=10,
    )
    assert r.status_code == 204, r.text
    ids = requests.get(
        f"{ib}/namespaces/archive/tables", timeout=10
    ).json()["identifiers"]
    assert ids == [{"namespace": ["archive"], "name": "events_v2"}]
    assert (
        requests.get(
            f"{ib}/namespaces/analytics/tables/events", timeout=10
        ).status_code
        == 404
    )
    # nonempty namespace refuses to drop; empty one drops
    assert (
        requests.delete(f"{ib}/namespaces/archive", timeout=10).status_code
        == 409
    )
    assert (
        requests.delete(
            f"{ib}/namespaces/archive/tables/events_v2", timeout=10
        ).status_code
        == 204
    )
    assert (
        requests.delete(f"{ib}/namespaces/archive", timeout=10).status_code
        == 204
    )


def test_iceberg_prefixed_catalog_uses_table_bucket(s3):
    url, _srv = s3
    # create a table bucket via S3Tables, then address it as the
    # Iceberg {prefix}
    r = requests.post(
        f"{url}/",
        json={"name": "warehouse1"},
        headers={"X-Amz-Target": "S3Tables.CreateTableBucket"},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    ib = f"{url}/iceberg/v1/warehouse1"
    r = requests.post(
        f"{ib}/namespaces", json={"namespace": ["raw"]}, timeout=10
    )
    assert r.status_code == 200, r.text
    r = requests.post(
        f"{ib}/namespaces/raw/tables",
        json={"name": "t1", "schema": SCHEMA},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert r.json()["metadata-location"].startswith(
        "s3://warehouse1/raw/t1/metadata/"
    )


def test_s3tables_target_and_rest_ops(s3):
    url, _srv = s3
    tgt = lambda op: {"X-Amz-Target": f"S3Tables.{op}"}  # noqa: E731

    r = requests.post(
        f"{url}/", json={"name": "tb1"}, headers=tgt("CreateTableBucket"),
        timeout=10,
    )
    assert r.status_code == 200
    arn = r.json()["arn"]
    # duplicate -> 409
    assert (
        requests.post(
            f"{url}/", json={"name": "tb1"},
            headers=tgt("CreateTableBucket"), timeout=10,
        ).status_code
        == 409
    )
    names = [
        b["name"]
        for b in requests.post(
            f"{url}/", json={}, headers=tgt("ListTableBuckets"), timeout=10
        ).json()["tableBuckets"]
    ]
    assert "tb1" in names

    # namespace + table through the target protocol
    r = requests.post(
        f"{url}/",
        json={"tableBucketARN": arn, "namespace": ["ns1"]},
        headers=tgt("CreateNamespace"),
        timeout=10,
    )
    assert r.status_code == 200, r.text
    r = requests.post(
        f"{url}/",
        json={"tableBucketARN": arn, "namespace": "ns1", "name": "t"},
        headers=tgt("CreateTable"),
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert r.json()["metadataLocation"].startswith("s3://tb1/ns1/t/")

    r = requests.post(
        f"{url}/",
        json={"tableBucketARN": arn, "namespace": "ns1", "name": "t"},
        headers=tgt("GetTable"),
        timeout=10,
    )
    assert r.json()["format"] == "ICEBERG"

    # REST-style aliases (AWS CLI shapes)
    r = requests.get(f"{url}/buckets/{arn}", timeout=10)
    assert r.status_code == 200 and r.json()["name"] == "tb1"
    r = requests.get(f"{url}/namespaces/{arn}", timeout=10)
    assert r.json()["namespaces"] == [{"namespace": ["ns1"]}]
    r = requests.get(f"{url}/tables/{arn}", timeout=10)
    assert r.json()["tables"] == [{"namespace": ["ns1"], "name": "t"}]
    assert (
        requests.delete(
            f"{url}/tables/{arn}/ns1/t", timeout=10
        ).status_code
        == 204
    )
    assert (
        requests.delete(f"{url}/namespaces/{arn}/ns1", timeout=10).status_code
        == 204
    )
    assert requests.delete(f"{url}/buckets/{arn}", timeout=10).status_code == 204


def test_catalog_requires_admin_action(cluster):
    """A policy-limited identity must NOT get catalog admin (review
    r5): the tables surface bypasses _authorize, so it enforces the
    Admin action itself."""
    from seaweedfs_tpu.s3.auth import Identity, IdentityStore

    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    idents = IdentityStore()
    idents.add(Identity("admin", "AKADM", "adminsecret"))  # full access
    idents.add(
        Identity("ro", "AKRO", "rosecret", actions=("Read", "List"))
    )
    srv = S3Server(filer, ip="localhost", port=free_port(), identities=idents)
    srv.start()
    url = f"http://localhost:{srv.port}"
    try:
        from test_s3 import sign_request

        def call(ak, sk, body=b'{"name":"gated"}'):
            h = sign_request("POST", f"{url}/", ak, sk, body=body)
            h["X-Amz-Target"] = "S3Tables.CreateTableBucket"
            return requests.post(f"{url}/", data=body, headers=h, timeout=10)

        assert call("AKRO", "rosecret").status_code == 403
        assert call("AKADM", "adminsecret").status_code == 200
        # anonymous refused outright
        r = requests.post(
            f"{url}/",
            data=b"{}",
            headers={"X-Amz-Target": "S3Tables.ListTableBuckets"},
            timeout=10,
        )
        assert r.status_code == 403
    finally:
        srv.stop()
        filer.close()


def test_iceberg_snapshot_commit_lifecycle(s3):
    """The commit kinds real Iceberg writers emit: add-snapshot +
    set-snapshot-ref advance current-snapshot-id and the snapshot log;
    schema evolution via add-schema/set-current-schema; refs; snapshot
    expiry via remove-snapshots."""
    url, _srv = s3
    ib = f"{url}/iceberg/v1"
    requests.post(f"{ib}/namespaces", json={"namespace": ["snapns"]}, timeout=10)
    r = requests.post(
        f"{ib}/namespaces/snapns/tables",
        json={"name": "t", "schema": SCHEMA},
        timeout=10,
    )
    assert r.status_code == 200, r.text

    def commit(updates, expect=200, requirements=None):
        r = requests.post(
            f"{ib}/namespaces/snapns/tables/t",
            json={"updates": updates, "requirements": requirements or []},
            timeout=10,
        )
        assert r.status_code == expect, r.text
        return r.json() if expect == 200 else r

    snap = {
        "snapshot-id": 4242,
        "sequence-number": 1,
        "timestamp-ms": 1700000000000,
        "manifest-list": "s3://default/snapns/t/metadata/snap-4242.avro",
        "summary": {"operation": "append"},
    }
    out = commit([
        {"action": "add-snapshot", "snapshot": snap},
        {"action": "set-snapshot-ref", "ref-name": "main",
         "snapshot-id": 4242, "type": "branch"},
    ])
    md = out["metadata"]
    assert md["current-snapshot-id"] == 4242
    assert md["snapshots"][0]["snapshot-id"] == 4242
    assert md["last-sequence-number"] == 1
    assert md["snapshot-log"][-1]["snapshot-id"] == 4242
    assert md["refs"]["main"]["snapshot-id"] == 4242

    # schema evolution
    new_schema = {
        "type": "struct", "schema-id": 1,
        "fields": SCHEMA["fields"] + [
            {"id": 3, "name": "extra", "required": False, "type": "string"}
        ],
    }
    out = commit([
        {"action": "add-schema", "schema": new_schema},
        {"action": "set-current-schema", "schema-id": -1},
    ])
    md = out["metadata"]
    assert md["current-schema-id"] == 1
    assert md["last-column-id"] == 3
    assert len(md["schemas"]) == 2

    # add-schema WITHOUT last-column-id, highest id nested in a struct:
    # the fallback must recurse (top-level-only would persist 4)
    nested = {
        "type": "struct", "schema-id": 2,
        "fields": new_schema["fields"] + [
            {"id": 4, "name": "s", "required": False,
             "type": {"type": "struct", "fields": [
                 {"id": 5, "name": "inner", "required": False,
                  "type": "string"}]}},
        ],
    }
    out = commit([{"action": "add-schema", "schema": nested}])
    assert out["metadata"]["last-column-id"] == 5

    # TableRequirements: the optimistic-concurrency preconditions.
    # A stale writer (expects main at the pre-commit snapshot) gets 409
    # CommitFailedException and must NOT clobber the committed state.
    snap2 = dict(snap, **{"snapshot-id": 4343, "sequence-number": 2})
    r = commit(
        [{"action": "add-snapshot", "snapshot": snap2},
         {"action": "set-snapshot-ref", "ref-name": "main",
          "snapshot-id": 4343, "type": "branch"}],
        expect=409,
        requirements=[{"type": "assert-ref-snapshot-id", "ref": "main",
                       "snapshot-id": 777}],
    )
    assert "CommitFailedException" in r.text
    md = requests.get(
        f"{ib}/namespaces/snapns/tables/t", timeout=10
    ).json()["metadata"]
    assert md["current-snapshot-id"] == 4242  # rejected commit not applied
    # the CORRECT precondition passes and advances main
    out = commit(
        [{"action": "add-snapshot", "snapshot": snap2},
         {"action": "set-snapshot-ref", "ref-name": "main",
          "snapshot-id": 4343, "type": "branch"}],
        requirements=[
            {"type": "assert-ref-snapshot-id", "ref": "main",
             "snapshot-id": 4242},
            {"type": "assert-table-uuid", "uuid": md["table-uuid"]},
        ],
    )
    assert out["metadata"]["refs"]["main"]["snapshot-id"] == 4343
    # wrong uuid and unknown requirement kinds fail loudly
    r = commit([], expect=409,
               requirements=[{"type": "assert-table-uuid", "uuid": "nope"}])
    assert "CommitFailedException" in r.text
    commit([], expect=400, requirements=[{"type": "assert-bogus"}])
    # roll main back so the expiry checks below see the original state
    commit([
        {"action": "set-snapshot-ref", "ref-name": "main",
         "snapshot-id": 4242, "type": "branch"},
        {"action": "remove-snapshots", "snapshot-ids": [4343]},
    ])

    # ref to an unknown snapshot fails loudly
    commit(
        [{"action": "set-snapshot-ref", "ref-name": "main",
          "snapshot-id": 999}],
        expect=400,
    )
    # snapshot expiry also drops every pointer at the gone snapshot
    out = commit([{"action": "remove-snapshots", "snapshot-ids": [4242]}])
    md = out["metadata"]
    assert md["snapshots"] == []
    assert md["current-snapshot-id"] == -1
    assert md["refs"] == {}
    assert all(e["snapshot-id"] != 4242 for e in md["snapshot-log"])
    # the reloaded table reflects every commit (metadata persisted)
    r = requests.get(f"{ib}/namespaces/snapns/tables/t", timeout=10)
    assert r.json()["metadata"]["current-schema-id"] == 1


def test_iceberg_snapshot_expiry_task(cluster, s3):
    """The `iceberg` maintenance kind end to end: a worker posts the
    gateway's /iceberg/v1/maintenance route and old unreferenced
    snapshots are expired while refs and current stay (reference
    worker tasks: iceberg)."""
    import threading

    from seaweedfs_tpu.server.master import MasterServer  # noqa: F401
    from seaweedfs_tpu.worker import Worker

    url, srv = s3
    ib = f"{url}/iceberg/v1"
    requests.post(f"{ib}/namespaces", json={"namespace": ["expns"]}, timeout=10)
    r = requests.post(
        f"{ib}/namespaces/expns/tables",
        json={"name": "t", "schema": SCHEMA},
        timeout=10,
    )
    assert r.status_code == 200, r.text

    def snap(sid, ts):
        return {
            "snapshot-id": sid, "sequence-number": sid,
            "timestamp-ms": ts, "manifest-list": f"s3://x/{sid}",
            "summary": {"operation": "append"},
        }

    old_ms = int(time.time() * 1000) - 90 * 86400_000
    now_ms = int(time.time() * 1000)
    r = requests.post(
        f"{ib}/namespaces/expns/tables/t",
        json={"updates": [
            {"action": "add-snapshot", "snapshot": snap(1, old_ms)},
            {"action": "add-snapshot", "snapshot": snap(2, now_ms)},
            {"action": "set-snapshot-ref", "ref-name": "main",
             "snapshot-id": 2, "type": "branch"},
        ]},
        timeout=10,
    )
    assert r.status_code == 200, r.text

    master_addr = f"localhost:{cluster}"
    w = Worker(master=master_addr, backend="cpu")
    threading.Thread(target=w.run, daemon=True).start()
    try:
        import grpc as _grpc

        from seaweedfs_tpu.pb import rpc as _rpc
        from seaweedfs_tpu.pb import worker_pb2 as wk

        mhost, mport = master_addr.split(":")
        gaddr = f"{mhost}:{int(mport) + 10000}"
        with _grpc.insecure_channel(gaddr) as ch:
            stub = _rpc.Stub(ch, _rpc.WORKER_SERVICE)
            deadline = time.time() + 15
            while time.time() < deadline:
                if any(
                    "iceberg" in wi.capabilities
                    for wi in stub.ListWorkers(
                        wk.ListWorkersRequest(), timeout=10
                    ).workers
                ):
                    break
                time.sleep(0.2)
            r = stub.SubmitTask(
                wk.SubmitTaskRequest(
                    kind="iceberg",
                    params={
                        "s3": f"localhost:{srv.port}",
                        "older_than_days": "30",
                    },
                ),
                timeout=10,
            )
            assert not r.error, r.error
            tid = r.task_id
            deadline = time.time() + 60
            state, err = "", "timed out waiting for terminal state"
            while time.time() < deadline:
                tasks = {
                    t.task_id: t
                    for t in stub.ListTasks(
                        wk.ListTasksRequest(), timeout=10
                    ).tasks
                }
                state = tasks[tid].state
                if state in ("done", "failed"):
                    err = tasks[tid].error
                    break
                time.sleep(0.3)
            assert state == "done", err
    finally:
        w.stop()

    md = requests.get(
        f"{ib}/namespaces/expns/tables/t", timeout=10
    ).json()["metadata"]
    sids = [s["snapshot-id"] for s in md["snapshots"]]
    assert sids == [2], sids  # old unreferenced snapshot expired
    assert md["refs"]["main"]["snapshot-id"] == 2
    # dry-run via the route directly reports zero further work
    r = requests.post(
        f"{ib}/maintenance",
        json={"older-than-days": 30, "all-buckets": True, "dry-run": True},
        timeout=10,
    )
    assert r.status_code == 200, r.text
    assert r.json()["snapshots_expired"] == 0


def test_iceberg_multi_table_transaction(s3):
    """POST /v1/transactions/commit applies changes to several tables
    atomically: a failed requirement on ANY table leaves every table
    untouched (Iceberg REST spec commitTransaction)."""
    url, _srv = s3
    ib = f"{url}/iceberg/v1"
    requests.post(f"{ib}/namespaces", json={"namespace": ["txn"]}, timeout=10)
    for name in ("a", "b"):
        r = requests.post(
            f"{ib}/namespaces/txn/tables",
            json={"name": name, "schema": SCHEMA},
            timeout=10,
        )
        assert r.status_code == 200, r.text

    def change(name, props, reqs=None):
        return {
            "identifier": {"namespace": ["txn"], "name": name},
            "updates": [{"action": "set-properties", "updates": props}],
            "requirements": reqs or [],
        }

    # both tables commit in one transaction
    r = requests.post(
        f"{ib}/transactions/commit",
        json={"table-changes": [change("a", {"k": "1"}),
                                change("b", {"k": "2"})]},
        timeout=10,
    )
    assert r.status_code == 204, r.text
    for name, want in (("a", "1"), ("b", "2")):
        md = requests.get(
            f"{ib}/namespaces/txn/tables/{name}", timeout=10
        ).json()["metadata"]
        assert md["properties"]["k"] == want

    # failed requirement on b -> NOTHING persists (a keeps k=1)
    r = requests.post(
        f"{ib}/transactions/commit",
        json={"table-changes": [
            change("a", {"k": "9"}),
            change("b", {"k": "9"},
                   reqs=[{"type": "assert-table-uuid", "uuid": "wrong"}]),
        ]},
        timeout=10,
    )
    assert r.status_code == 409, r.text
    md = requests.get(
        f"{ib}/namespaces/txn/tables/a", timeout=10
    ).json()["metadata"]
    assert md["properties"]["k"] == "1"

    # duplicate table in one transaction is rejected
    r = requests.post(
        f"{ib}/transactions/commit",
        json={"table-changes": [change("a", {"x": "1"}),
                                change("a", {"y": "2"})]},
        timeout=10,
    )
    assert r.status_code == 400, r.text
    # unknown table 404s and persists nothing
    r = requests.post(
        f"{ib}/transactions/commit",
        json={"table-changes": [change("a", {"k": "3"}),
                                change("ghost", {"k": "3"})]},
        timeout=10,
    )
    assert r.status_code == 404, r.text
    md = requests.get(
        f"{ib}/namespaces/txn/tables/a", timeout=10
    ).json()["metadata"]
    assert md["properties"]["k"] == "1"


def test_iceberg_view_lifecycle(s3):
    """Iceberg REST views: create (version w/ SQL representation), load,
    list, replace-commit, rename, name-collision with tables, drop."""
    url, _srv = s3
    ib = f"{url}/iceberg/v1"
    requests.post(f"{ib}/namespaces", json={"namespace": ["vws"]}, timeout=10)
    rep = {"type": "sql", "sql": "SELECT id FROM t", "dialect": "spark"}
    r = requests.post(
        f"{ib}/namespaces/vws/views",
        json={
            "name": "v1",
            "schema": SCHEMA,
            "view-version": {
                "version-id": 1,
                "representations": [rep],
                "summary": {"engine-name": "spark"},
            },
        },
        timeout=10,
    )
    assert r.status_code == 200, r.text
    md = r.json()["metadata"]
    assert md["format-version"] == 1
    assert md["current-version-id"] == 1
    assert md["versions"][0]["representations"] == [rep]

    # load + exists + list
    r = requests.get(f"{ib}/namespaces/vws/views/v1", timeout=10)
    assert r.status_code == 200
    assert r.json()["metadata"]["view-uuid"] == md["view-uuid"]
    assert requests.head(
        f"{ib}/namespaces/vws/views/v1", timeout=10
    ).status_code == 204
    ids = requests.get(f"{ib}/namespaces/vws/views", timeout=10).json()
    assert ids["identifiers"] == [{"namespace": ["vws"], "name": "v1"}]

    # replace: add-view-version + set-current (with the uuid guard)
    rep2 = {"type": "sql", "sql": "SELECT id, data FROM t",
            "dialect": "spark"}
    r = requests.post(
        f"{ib}/namespaces/vws/views/v1",
        json={
            "updates": [
                {"action": "add-view-version",
                 "view-version": {"version-id": 2,
                                  "schema-id": 0,
                                  "representations": [rep2]}},
                {"action": "set-current-view-version",
                 "view-version-id": -1},
            ],
            "requirements": [
                {"type": "assert-view-uuid", "uuid": md["view-uuid"]}
            ],
        },
        timeout=10,
    )
    assert r.status_code == 200, r.text
    out = r.json()["metadata"]
    assert out["current-version-id"] == 2
    assert out["version-log"][-1]["version-id"] == 2
    # stale uuid 409s
    r = requests.post(
        f"{ib}/namespaces/vws/views/v1",
        json={"updates": [],
              "requirements": [{"type": "assert-view-uuid", "uuid": "x"}]},
        timeout=10,
    )
    assert r.status_code == 409

    # a table cannot shadow the view name (and vice versa)
    r = requests.post(
        f"{ib}/namespaces/vws/tables",
        json={"name": "v1", "schema": SCHEMA},
        timeout=10,
    )
    assert r.status_code == 409, r.text
    requests.post(f"{ib}/namespaces/vws/tables",
                  json={"name": "t1", "schema": SCHEMA}, timeout=10)
    r = requests.post(
        f"{ib}/namespaces/vws/views",
        json={"name": "t1", "schema": SCHEMA, "view-version": {}},
        timeout=10,
    )
    assert r.status_code == 409, r.text

    # renames cannot cross the table/view identifier invariant either
    r = requests.post(
        f"{ib}/views/rename",
        json={"source": {"namespace": ["vws"], "name": "v1"},
              "destination": {"namespace": ["vws"], "name": "t1"}},
        timeout=10,
    )
    assert r.status_code == 409, r.text
    r = requests.post(
        f"{ib}/tables/rename",
        json={"source": {"namespace": ["vws"], "name": "t1"},
              "destination": {"namespace": ["vws"], "name": "v1"}},
        timeout=10,
    )
    assert r.status_code == 409, r.text

    # rename + nonempty-namespace guard + drop
    r = requests.post(
        f"{ib}/views/rename",
        json={"source": {"namespace": ["vws"], "name": "v1"},
              "destination": {"namespace": ["vws"], "name": "v2"}},
        timeout=10,
    )
    assert r.status_code == 204, r.text
    assert requests.get(
        f"{ib}/namespaces/vws/views/v1", timeout=10
    ).status_code == 404
    requests.delete(f"{ib}/namespaces/vws/tables/t1", timeout=10)
    assert requests.delete(
        f"{ib}/namespaces/vws", timeout=10
    ).status_code == 409  # view still inside
    assert requests.delete(
        f"{ib}/namespaces/vws/views/v2", timeout=10
    ).status_code == 204
    assert requests.delete(
        f"{ib}/namespaces/vws", timeout=10
    ).status_code == 204
