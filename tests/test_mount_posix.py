"""pjdfstest-style POSIX compliance sweep over a REAL kernel mount.

Reference: test/pjdfstest (the reference runs the upstream suite over
`weed mount`). This port covers the categories that apply to a
single-user root test environment: open (O_EXCL/O_TRUNC/O_APPEND/
O_DIRECTORY), unlink-while-open, rename (over open files, dirs,
error cases), mkdir/rmdir, link/nlink, symlink/readlink, chmod/chown
persistence, utimens, truncate/holes, and errno fidelity (EEXIST,
ENOENT, ENOTDIR, EISDIR, ENOTEMPTY, ENAMETOOLONG).

Documented waivers (not bugs; environmental):
- sticky-bit deletion restrictions and EACCES permission denials are
  unobservable when the suite runs as root (the kernel bypasses
  permission checks for uid 0); pjdfstest's multi-user cases need the
  unprivileged-user harness the reference CI provides.
- atime semantics are not asserted (mount may be relatime/noatime).
- cross-name cache coherence (hardlinks) is close-to-open with a
  bounded attribute-cache window (~2s: mount ATTR_TTL + kernel attr
  timeout) — the NFS contract; the link case outwaits it explicitly.

The first run of this sweep found and fixed four real gaps: no
NAME_MAX enforcement (ENAMETOOLONG), hardlinked names reporting
distinct st_ino (now -o use_ino + link-id-derived inodes), rename onto
an existing directory answering EIO instead of POSIX semantics
(replace-if-empty / ENOTEMPTY / EISDIR / ENOTDIR), and hardlink
write-through (a write via one name was invisible via the others until
the filer grew a shared inode record keyed by the link id).
"""

from __future__ import annotations

import errno
import os
import shutil
import stat
import time

import pytest

from test_mount import mounted  # noqa: F401 — real-kernel mount fixture

pytestmark = pytest.mark.skipif(
    not os.path.exists("/dev/fuse") or shutil.which("fusermount") is None,
    reason="FUSE unavailable",
)


def _errno_of(fn) -> int:
    try:
        fn()
    except OSError as e:
        return e.errno
    return 0


# ------------------------------------------------------------- open(2)


def case_open_excl_eexist(root):
    p = f"{root}/excl"
    fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    os.close(fd)
    assert _errno_of(
        lambda: os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    ) == errno.EEXIST


def case_open_excl_dangling_symlink(root):
    os.symlink(f"{root}/nowhere", f"{root}/dangle")
    # POSIX: O_CREAT|O_EXCL fails if the NAME exists, symlink included
    assert _errno_of(
        lambda: os.open(
            f"{root}/dangle", os.O_CREAT | os.O_EXCL | os.O_WRONLY
        )
    ) == errno.EEXIST


def case_open_trunc(root):
    p = f"{root}/trunc"
    with open(p, "wb") as f:
        f.write(b"0123456789")
    fd = os.open(p, os.O_WRONLY | os.O_TRUNC)
    os.close(fd)
    assert os.path.getsize(p) == 0


def case_open_append(root):
    p = f"{root}/app"
    with open(p, "wb") as f:
        f.write(b"AAAA")
    fd = os.open(p, os.O_WRONLY | os.O_APPEND)
    os.write(fd, b"BB")
    os.close(fd)
    assert open(p, "rb").read() == b"AAAABB"


def case_open_dir_wronly_eisdir(root):
    os.mkdir(f"{root}/odir")
    assert _errno_of(
        lambda: os.open(f"{root}/odir", os.O_WRONLY)
    ) == errno.EISDIR


def case_open_o_directory_on_file(root):
    p = f"{root}/plain"
    open(p, "wb").write(b"x")
    assert _errno_of(
        lambda: os.open(p, os.O_RDONLY | os.O_DIRECTORY)
    ) == errno.ENOTDIR


def case_open_enoent(root):
    assert _errno_of(
        lambda: os.open(f"{root}/missing", os.O_RDONLY)
    ) == errno.ENOENT


def case_enotdir_component(root):
    p = f"{root}/notdir"
    open(p, "wb").write(b"x")
    assert _errno_of(
        lambda: os.open(f"{p}/below", os.O_RDONLY)
    ) == errno.ENOTDIR


def case_enametoolong(root):
    assert _errno_of(
        lambda: os.open(f"{root}/{'n' * 256}", os.O_CREAT | os.O_WRONLY)
    ) == errno.ENAMETOOLONG


# ---------------------------------------------------------- unlink(2)


def case_unlink_while_open(root):
    p = f"{root}/uwo"
    fd = os.open(p, os.O_CREAT | os.O_RDWR, 0o644)
    os.write(fd, b"still-here")
    os.unlink(p)
    assert not os.path.exists(p)
    # the open fd keeps working after the name is gone
    os.lseek(fd, 0, os.SEEK_SET)
    assert os.read(fd, 32) == b"still-here"
    os.write(fd, b"!")
    assert os.fstat(fd).st_nlink == 0
    os.close(fd)


def case_unlink_dir_eisdir(root):
    os.mkdir(f"{root}/udir")
    assert _errno_of(lambda: os.unlink(f"{root}/udir")) in (
        errno.EISDIR,
        errno.EPERM,  # POSIX allows either for unlink(dir)
    )


def case_unlink_symlink_keeps_target(root):
    t = f"{root}/starget"
    open(t, "wb").write(b"keep")
    os.symlink(t, f"{root}/slink")
    os.unlink(f"{root}/slink")
    assert open(t, "rb").read() == b"keep"


# ---------------------------------------------------------- rename(2)


def case_rename_basic_and_self(root):
    p = f"{root}/r1"
    open(p, "wb").write(b"v")
    os.rename(p, p)  # rename onto itself: success, no-op
    assert open(p, "rb").read() == b"v"
    os.rename(p, f"{root}/r2")
    assert not os.path.exists(p)
    assert open(f"{root}/r2", "rb").read() == b"v"


def case_rename_over_open_file(root):
    old, new = f"{root}/ro_old", f"{root}/ro_new"
    open(old, "wb").write(b"NEW")
    open(new, "wb").write(b"OLD")
    fd = os.open(new, os.O_RDONLY)  # hold the victim open
    os.rename(old, new)
    assert open(new, "rb").read() == b"NEW"
    # the held fd still reads the PRE-rename content
    assert os.read(fd, 16) == b"OLD"
    os.close(fd)


def case_rename_file_onto_dir_eisdir(root):
    open(f"{root}/rf", "wb").write(b"x")
    os.mkdir(f"{root}/rd")
    assert _errno_of(
        lambda: os.rename(f"{root}/rf", f"{root}/rd")
    ) == errno.EISDIR


def case_rename_dir_onto_file_enotdir(root):
    os.mkdir(f"{root}/rdd")
    open(f"{root}/rff", "wb").write(b"x")
    assert _errno_of(
        lambda: os.rename(f"{root}/rdd", f"{root}/rff")
    ) == errno.ENOTDIR


def case_rename_dir_onto_nonempty_dir(root):
    os.mkdir(f"{root}/rsrc")
    os.mkdir(f"{root}/rdst")
    open(f"{root}/rdst/kid", "wb").write(b"x")
    assert _errno_of(
        lambda: os.rename(f"{root}/rsrc", f"{root}/rdst")
    ) in (errno.ENOTEMPTY, errno.EEXIST)


def case_rename_dir_onto_empty_dir(root):
    os.mkdir(f"{root}/resrc")
    open(f"{root}/resrc/kid", "wb").write(b"k")
    os.mkdir(f"{root}/redst")
    os.rename(f"{root}/resrc", f"{root}/redst")
    assert open(f"{root}/redst/kid", "rb").read() == b"k"
    assert not os.path.exists(f"{root}/resrc")


# ------------------------------------------------------ mkdir/rmdir(2)


def case_mkdir_eexist(root):
    os.mkdir(f"{root}/md")
    assert _errno_of(lambda: os.mkdir(f"{root}/md")) == errno.EEXIST


def case_rmdir_nonempty_enotempty(root):
    os.mkdir(f"{root}/rne")
    open(f"{root}/rne/kid", "wb").write(b"x")
    assert _errno_of(lambda: os.rmdir(f"{root}/rne")) in (
        errno.ENOTEMPTY,
        errno.EEXIST,
    )


def case_rmdir_file_enotdir(root):
    open(f"{root}/rmf", "wb").write(b"x")
    assert _errno_of(lambda: os.rmdir(f"{root}/rmf")) == errno.ENOTDIR


def case_rmdir_then_recreate(root):
    os.mkdir(f"{root}/cycle")
    os.rmdir(f"{root}/cycle")
    os.mkdir(f"{root}/cycle")
    assert os.path.isdir(f"{root}/cycle")


# ------------------------------------------------------------- link(2)


def case_link_nlink_and_content(root):
    a, b = f"{root}/la", f"{root}/lb"
    open(a, "wb").write(b"shared")
    os.link(a, b)
    # nlink rides the same bounded attribute-cache window as content
    # (see below): the kernel may serve a pre-link getattr for up to
    # ~1s — outwait it so the assertion tests the semantics
    time.sleep(1.2)
    assert os.stat(a).st_nlink == 2
    # shared-inode identity: our getattr supplies hard_link_id-derived
    # hash inos (-o use_ino; < 2^32 with probability ~2^-31), but a
    # kernel that minted its own small node id for a name seen BEFORE
    # the link may keep serving it (sandboxed FUSE does); only assert
    # identity when both inos are demonstrably ours. nlink + write
    # coherence are the portable contract.
    ia, ib = os.stat(a).st_ino, os.stat(b).st_ino
    if ia >= (1 << 32) and ib >= (1 << 32):
        assert ia == ib
    # write through one name, read through the other. Coherence model
    # is close-to-open with a bounded attribute-cache window (mount
    # ATTR_TTL + kernel attr timeout, ~1s each) — the same contract
    # NFS gives; outwait it so the assertion tests the SEMANTICS, not
    # the cache.
    with open(b, "ab") as f:
        f.write(b"+more")
    time.sleep(2.2)
    assert open(a, "rb").read() == b"shared+more"
    os.unlink(a)
    time.sleep(1.2)  # attr-cache window again (nlink of the survivor)
    assert os.stat(b).st_nlink == 1
    assert open(b, "rb").read() == b"shared+more"


def case_link_eexist(root):
    open(f"{root}/lsrc", "wb").write(b"x")
    open(f"{root}/ldst", "wb").write(b"y")
    assert _errno_of(
        lambda: os.link(f"{root}/lsrc", f"{root}/ldst")
    ) == errno.EEXIST


def case_link_dir_eperm(root):
    os.mkdir(f"{root}/ldir")
    assert _errno_of(
        lambda: os.link(f"{root}/ldir", f"{root}/ldir2")
    ) == errno.EPERM


# ---------------------------------------------------------- symlink(2)


def case_symlink_roundtrip(root):
    os.symlink("relative/target path", f"{root}/sl")
    assert os.readlink(f"{root}/sl") == "relative/target path"
    st = os.lstat(f"{root}/sl")
    assert stat.S_ISLNK(st.st_mode)


def case_symlink_follow(root):
    open(f"{root}/sreal", "wb").write(b"through")
    os.symlink(f"{root}/sreal", f"{root}/svia")
    assert open(f"{root}/svia", "rb").read() == b"through"
    # stat follows, lstat does not
    assert os.stat(f"{root}/svia").st_size == 7
    assert os.lstat(f"{root}/svia").st_size != 7 or stat.S_ISLNK(
        os.lstat(f"{root}/svia").st_mode
    )


def case_symlink_dangling_enoent(root):
    os.symlink(f"{root}/gone", f"{root}/sdang")
    assert _errno_of(lambda: os.stat(f"{root}/sdang")) == errno.ENOENT
    assert stat.S_ISLNK(os.lstat(f"{root}/sdang").st_mode)


def case_symlink_eexist(root):
    open(f"{root}/se", "wb").write(b"x")
    assert _errno_of(
        lambda: os.symlink("t", f"{root}/se")
    ) == errno.EEXIST


# --------------------------------------------- chmod/chown/utimens(2)


def case_chmod_persists(root):
    p = f"{root}/cm"
    open(p, "wb").write(b"x")
    for mode in (0o755, 0o600, 0o444, 0o000):
        os.chmod(p, mode)
        assert stat.S_IMODE(os.stat(p).st_mode) == mode
    os.chmod(p, 0o644)


def case_chmod_setuid_setgid(root):
    p = f"{root}/suid"
    open(p, "wb").write(b"x")
    os.chmod(p, 0o4755)
    assert stat.S_IMODE(os.stat(p).st_mode) == 0o4755
    os.chmod(p, 0o2755)
    assert stat.S_IMODE(os.stat(p).st_mode) == 0o2755


def case_chown_persists(root):
    p = f"{root}/co"
    open(p, "wb").write(b"x")
    os.chown(p, 12345, 54321)  # root may chown arbitrarily
    st = os.stat(p)
    assert (st.st_uid, st.st_gid) == (12345, 54321)


def case_utimens_explicit(root):
    p = f"{root}/ut"
    open(p, "wb").write(b"x")
    os.utime(p, (1_600_000_000, 1_500_000_000))
    st = os.stat(p)
    assert int(st.st_mtime) == 1_500_000_000


def case_mtime_advances_on_write(root):
    p = f"{root}/mt"
    open(p, "wb").write(b"x")
    os.utime(p, (1_000_000_000, 1_000_000_000))
    before = os.stat(p).st_mtime
    time.sleep(0.05)
    with open(p, "ab") as f:
        f.write(b"y")
    assert os.stat(p).st_mtime > before


# ---------------------------------------------------- truncate/holes


def case_truncate_shrink_grow(root):
    p = f"{root}/tr"
    open(p, "wb").write(b"0123456789")
    os.truncate(p, 4)
    assert open(p, "rb").read() == b"0123"
    os.truncate(p, 8)  # grow: zero-filled
    assert open(p, "rb").read() == b"0123\x00\x00\x00\x00"


def case_seek_hole_write(root):
    p = f"{root}/hole"
    fd = os.open(p, os.O_CREAT | os.O_WRONLY, 0o644)
    os.lseek(fd, 1 << 16, os.SEEK_SET)
    os.write(fd, b"END")
    os.close(fd)
    data = open(p, "rb").read()
    assert len(data) == (1 << 16) + 3
    assert data[: 1 << 16] == b"\x00" * (1 << 16)
    assert data[-3:] == b"END"


def case_ftruncate_open_fd(root):
    p = f"{root}/ftr"
    fd = os.open(p, os.O_CREAT | os.O_RDWR, 0o644)
    os.write(fd, b"abcdefgh")
    os.ftruncate(fd, 3)
    os.lseek(fd, 0, os.SEEK_SET)
    assert os.read(fd, 16) == b"abc"
    os.close(fd)


CASES = [
    v for k, v in sorted(globals().items()) if k.startswith("case_")
]


def test_posix_sweep(mounted):  # noqa: F811 — fixture import
    """Run every case against one real mount; report ALL failures with
    their case names (a pjdfstest-style tally, not first-failure)."""
    mnt, _fport = mounted
    failures = []
    for fn in CASES:
        workdir = os.path.join(mnt, fn.__name__)
        os.makedirs(workdir, exist_ok=True)
        try:
            fn(workdir)
        except AssertionError as e:
            failures.append(f"{fn.__name__}: {e}")
        except OSError as e:
            failures.append(f"{fn.__name__}: unexpected {e!r}")
    assert not failures, (
        f"{len(failures)}/{len(CASES)} POSIX cases failed:\n"
        + "\n".join(failures)
    )
