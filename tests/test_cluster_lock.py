"""Cluster lock manager + shell/worker lock discipline.

Reference: weed/cluster/lock_manager/lock_manager.go and the shell's
confirmIsLocked gate — mutating commands and worker tasks must not
race each other on a volume.
"""

import time

import pytest

from seaweedfs_tpu.client.master_client import LockHeldError, MasterClient
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.cluster_lock import LockManager
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, cluster_guard, run_command
from seaweedfs_tpu.storage.file_id import FileId

from conftest import allocate_port as free_port


class TestLockManager:
    def test_acquire_release(self):
        lm = LockManager()
        ok, tok, holder, _ = lm.acquire("admin", "alice", 10.0)
        assert ok and tok and holder == "alice"
        ok2, _, holder2, _ = lm.acquire("admin", "bob", 10.0)
        assert not ok2 and holder2 == "alice"
        assert lm.release("admin", tok)
        ok3, _, _, _ = lm.acquire("admin", "bob", 10.0)
        assert ok3

    def test_renewal_and_wrong_token(self):
        lm = LockManager()
        _, tok, _, _ = lm.acquire("x", "a", 5.0)
        ok, tok2, _, _ = lm.acquire("x", "a", 5.0, token=tok)
        assert ok and tok2 == tok  # renewal keeps the token
        assert not lm.release("x", "bogus")
        assert lm.release("x", tok)

    def test_expiry(self, monkeypatch):
        lm = LockManager()
        _, tok, _, _ = lm.acquire("x", "a", 1.0)
        real = time.monotonic
        monkeypatch.setattr(time, "monotonic", lambda: real() + 2.0)
        ok, _, holder, _ = lm.acquire("x", "b", 5.0)
        assert ok and holder == "b"  # expired lease fell to the new owner

    def test_independent_names(self):
        lm = LockManager()
        assert lm.acquire("volume/1", "a", 5.0)[0]
        assert lm.acquire("volume/2", "b", 5.0)[0]
        assert len(lm.status()) == 2


@pytest.fixture
def cluster(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    while len(master.topo.nodes) < 2:
        time.sleep(0.05)
    yield master, vols
    for vs in vols:
        vs.stop()
    master.stop()


def test_two_shells_serialize(cluster):
    master, _ = cluster
    addr = f"localhost:{master.port}"
    env1, env2 = ShellEnv(addr), ShellEnv(addr)
    env2.lock_wait = 0.5
    try:
        assert "locked" in run_command(env1, "lock")
        # session 2's mutating command is refused while session 1 holds
        out = run_command(env2, "volume.delete -volumeId 999")
        assert "held by" in out and env1.owner in out
        # lock.status shows the lease
        assert env1.owner in run_command(env2, "lock.status")
        assert "unlocked" in run_command(env1, "unlock")
        # now session 2's command proceeds past the lock (fails on the
        # nonexistent volume instead)
        out = run_command(env2, "volume.delete -volumeId 999")
        assert "held by" not in out
    finally:
        env1.close()
        env2.close()


def test_shell_ec_encode_blocked_by_volume_lease(cluster):
    """The exact VERDICT race: a worker-held volume lease keeps shell
    ec.encode off the volume until released."""
    master, _ = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    fid = ops.upload(b"lockme" * 2000)
    vid = FileId.parse(fid).volume_id

    worker_mc = MasterClient(addr, keepconnected=False)
    env = ShellEnv(addr)
    env.lock_wait = 0.5
    try:
        token = worker_mc.lock(f"volume/{vid}", "fake-worker", ttl=30.0)
        out = run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        assert "held by fake-worker" in out
        # the volume was NOT touched (no EC artifacts, still writable)
        assert not master.topo.lookup_ec(vid)
        worker_mc.unlock(f"volume/{vid}", token)
        out = run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        assert "generation" in out
    finally:
        env.close()
        worker_mc.close()


def test_worker_task_blocked_by_shell_lease(cluster, tmp_path):
    """And the mirror image: a shell-held volume lease fails the worker
    task instead of letting it interleave."""
    from seaweedfs_tpu.worker.worker import Worker

    master, _ = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    fid = ops.upload(b"workerlock" * 1000)
    vid = FileId.parse(fid).volume_id

    import threading

    env = ShellEnv(addr)
    w = Worker(master=addr, backend="cpu", worker_id="w1")
    threading.Thread(target=w.run, daemon=True).start()
    try:
        with cluster_guard(env, vids=[vid], wait=1.0):
            tid = master.worker_control.submit("ec_encode", vid)
            # the task bounces off the shell's volume lease (requeued
            # with the contention recorded) instead of interleaving
            deadline = time.time() + 30
            while time.time() < deadline:
                t = master.worker_control._tasks.get(tid)
                if t and t.attempts >= 1:
                    break
                time.sleep(0.2)
            assert t is not None and t.attempts >= 1, t.state
            assert "held by" in t.error
            assert not master.topo.lookup_ec(vid)  # nothing destructive ran
        # lease released: the SAME task completes on a later attempt
        deadline = time.time() + 60
        while time.time() < deadline:
            t = master.worker_control._tasks.get(tid)
            if t and t.state in ("done", "failed"):
                break
            time.sleep(0.2)
        assert t is not None and t.state == "done", (t.state, t.error)
    finally:
        w.stop()
        env.close()


def test_guard_reentrant(cluster):
    master, _ = cluster
    env = ShellEnv(f"localhost:{master.port}")
    try:
        with cluster_guard(env, wait=1.0):
            with cluster_guard(env, vids=[7], wait=1.0):
                names = [n for n, _, _ in env.master.lock_status()]
                assert "admin" in names and "volume/7" in names
        assert env.master.lock_status() == []
    finally:
        env.close()
