"""Crash-consistent leaf repair (ec/repair_journal.py + scrub/peer
integration): the journal window matrix under hard process death, the
in-place scrub repair path, ranged peer fetch request shapes, journal
sweep/aging satellites, and capacity-aware placement.

The crash matrix is the ISSUE-8 acceptance gate: for EVERY enumerated
journal window, a fault-injected kill followed by mount-time recovery
must leave the shard either fully-old-verified or fully-new-verified
against its sidecar — never an unverifiable mix — and degraded reads
over the real byte path must stay bit-exact.
"""

from __future__ import annotations

import multiprocessing
import os

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import (
    CpuBackend,
    ECContext,
    ECError,
    EcVolume,
    rebuild_from_peers,
)
from seaweedfs_tpu.ec.bitrot import BitrotProtection, ShardChecksumBuilder
from seaweedfs_tpu.ec.context import QUARANTINE_SUFFIX
from seaweedfs_tpu.ec.peer_rebuild import staging_dir
from seaweedfs_tpu.ec.repair_journal import (
    JournalError,
    LeafPatch,
    RepairJournal,
    apply_leaf_repair,
    journal_path,
    leaf_ranges,
    leaf_verdict,
    reconstruct_leaves,
    recover_volume_journals,
    sweep_stale_journals,
)
from seaweedfs_tpu.ec.scrub import scrub_ec_volume
from seaweedfs_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.chaos

CTX = ECContext(4, 2)
BLOCK = 4096
LEAF = 1024
SHARD_SIZE = 3 * BLOCK + 57  # partial final leaf on purpose
FAST = RetryPolicy(max_attempts=3, base_delay=0.0, max_delay=0.0, jitter=0.0)


def synth(tmp_path, local=None, seed=0, name="1"):
    """RS-consistent shard set + v2 (leaf-CRC) sidecar. `local` limits
    which shard files exist on disk (None = all). Returns (base,
    blobs: sid -> bytes)."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (CTX.data_shards, SHARD_SIZE), dtype=np.uint8)
    parity = CpuBackend(CTX).encode(data)
    shards = np.concatenate([data, parity], axis=0)
    blobs = {i: shards[i].tobytes() for i in range(CTX.total)}
    builders = [
        ShardChecksumBuilder(BLOCK, leaf_size=LEAF) for _ in range(CTX.total)
    ]
    for i in range(CTX.total):
        builders[i].write(blobs[i])
    base = str(tmp_path / name)
    BitrotProtection.from_builders(CTX, builders, generation=7).save(
        base + ".ecsum"
    )
    for i in range(CTX.total) if local is None else local:
        with open(base + CTX.to_ext(i), "wb") as f:
            f.write(blobs[i])
    return base, blobs


def rot_leaf(base, sid, leaf, at=11):
    with open(base + CTX.to_ext(sid), "r+b") as f:
        f.seek(leaf * LEAF + at)
        b = f.read(1)
        f.seek(leaf * LEAF + at)
        f.write(bytes([b[0] ^ 0x42]))


def local_reader(base):
    def read_range(sid, lo, size):
        try:
            with open(base + CTX.to_ext(sid), "rb") as f:
                f.seek(lo)
                return f.read(size)
        except OSError:
            return None

    return read_range


def shard_fully_verifies(base, sid, prot=None) -> bool:
    if prot is None:
        prot = BitrotProtection.load(base + ".ecsum")
    return leaf_verdict(base + CTX.to_ext(sid), sid, prot) == []


# ------------------------------------------------------- journal format


def test_journal_roundtrip_and_torn_detection():
    p = [LeafPatch(3, 3 * LEAF, b"\x01" * LEAF, 123), LeafPatch(7, 7 * LEAF, b"z" * 57, 9)]
    j = RepairJournal(2, 7, b"u" * 16, LEAF, SHARD_SIZE, p)
    raw = j.to_bytes()
    j2 = RepairJournal.from_bytes(raw)
    assert j2.shard_id == 2 and j2.generation == 7 and j2.uuid == b"u" * 16
    assert j2.patches == p and j2.shard_size == SHARD_SIZE
    # every torn prefix fails its own checksum — never parses as intent
    for cut in (1, len(raw) // 2, len(raw) - 1):
        with pytest.raises(JournalError):
            RepairJournal.from_bytes(raw[:cut])
    # a flipped byte inside the payload fails too
    bad = bytearray(raw)
    bad[len(raw) // 2] ^= 0x10
    with pytest.raises(JournalError):
        RepairJournal.from_bytes(bytes(bad))


def test_leaf_ranges_grouping_and_tail_clamp():
    assert leaf_ranges([0, 1, 2], LEAF, SHARD_SIZE) == [(0, 3 * LEAF, [0, 1, 2])]
    assert leaf_ranges([1, 3], LEAF, SHARD_SIZE) == [
        (LEAF, 2 * LEAF, [1]),
        (3 * LEAF, 4 * LEAF, [3]),
    ]
    last = SHARD_SIZE // LEAF  # the 57-byte tail leaf
    assert leaf_ranges([last], LEAF, SHARD_SIZE) == [
        (last * LEAF, SHARD_SIZE, [last])
    ]


def test_leaf_verdict_pins_rot_and_rejects_resize(tmp_path):
    base, blobs = synth(tmp_path)
    prot = BitrotProtection.load(base + ".ecsum")
    assert leaf_verdict(base + CTX.to_ext(0), 0, prot) == []
    rot_leaf(base, 0, 2)
    rot_leaf(base, 0, 12)  # tail leaf
    assert leaf_verdict(base + CTX.to_ext(0), 0, prot) == [2, 12]
    # truncation is NOT leaf-repairable (offsets suspect)
    os.truncate(base + CTX.to_ext(1), SHARD_SIZE - 10)
    assert leaf_verdict(base + CTX.to_ext(1), 1, prot) is None


# ------------------------------------------- crash-window matrix (tentpole)

# Every enumerated window of the journal protocol, each killed with
# os._exit (no cleanup handlers — the power-loss model) in a forked
# child, optionally with a torn-write mutate at the same seam.
WINDOWS = [
    # (fire point to hard-exit at, mutate point to tear, expect_new)
    ("ec.repair.journal_write", "ec.repair.journal_bytes", False),
    ("ec.repair.journal_write", None, False),  # journal not yet fsynced*
    ("ec.repair.after_journal", None, True),
    ("ec.repair.patch_write", "ec.repair.patch_bytes", True),
    ("ec.repair.patch_write", None, True),
    ("ec.repair.after_patch", None, True),
    ("ec.repair.after_sidecar", None, True),
]
# *the bytes usually survive a process kill (they're in the page cache),
#  so recovery may also land fully-new — the assert below accepts either
#  terminal state but never a mix.


def _crashing_repair_child(base, sid, point, mutate_point):
    faults.inject(point, faults.hard_exit(137))
    if mutate_point:
        faults.inject(mutate_point, faults.truncate(0.5))
    prot = BitrotProtection.load(base + ".ecsum")
    bad = leaf_verdict(base + CTX.to_ext(sid), sid, prot)
    patches = reconstruct_leaves(
        prot, CTX, sid, bad, local_reader(base),
        [i for i in range(CTX.total) if i != sid], backend=CpuBackend(CTX),
    )
    apply_leaf_repair(base + CTX.to_ext(sid), sid, prot, patches)


@pytest.mark.parametrize("point,mutate_point,expect_new", WINDOWS)
def test_crash_window_matrix_recovers_verified(
    tmp_path, point, mutate_point, expect_new
):
    """Kill the repair at every journal window: after recovery the shard
    must FULLY verify against the sidecar (fully-new) or be exactly the
    pre-repair bytes (fully-old, journal rolled back) — never a mix —
    and a disarmed scrub then heals it bit-exact either way."""
    base, blobs = synth(tmp_path, seed=3)
    sid, leaf = 2, 1
    rot_leaf(base, sid, leaf)
    with open(base + CTX.to_ext(sid), "rb") as f:
        pre_repair = f.read()

    mp = multiprocessing.get_context("fork")
    p = mp.Process(
        target=_crashing_repair_child, args=(base, sid, point, mutate_point)
    )
    p.start()
    p.join(timeout=120)
    assert p.exitcode == 137, f"expected hard crash, got {p.exitcode}"

    # ---- recovery (the mount/scrub hook) ----
    prot = BitrotProtection.load(base + ".ecsum")
    rec = recover_volume_journals(base, CTX, prot)
    assert not os.path.exists(journal_path(base + CTX.to_ext(sid))), (
        "journal must be retired (replay) or rolled back after recovery"
    )
    with open(base + CTX.to_ext(sid), "rb") as f:
        after = f.read()
    fully_new = after == blobs[sid]
    fully_old = after == pre_repair
    assert fully_new or fully_old, (
        "shard is neither fully-old nor fully-new after recovery"
    )
    if fully_new:
        assert shard_fully_verifies(base, sid, prot)
        assert rec["replayed"].get(sid) == [leaf] or not rec["replayed"]
    if expect_new:
        assert fully_new, f"window {point} must roll FORWARD"

    # either way a disarmed scrub converges to bit-exact
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert not r.refused
    with open(base + CTX.to_ext(sid), "rb") as f:
        assert f.read() == blobs[sid]
    assert shard_fully_verifies(base, sid)


def _crashing_recovery_child(base):
    faults.inject("ec.repair.patch_write", faults.hard_exit(137))
    recover_volume_journals(base, CTX)


def test_crash_during_recovery_replay_is_reenterable(tmp_path):
    """Recovery itself dying mid-replay (power loss during the repair
    of a crash...) must leave the journal pending so the NEXT recovery
    converges — the protocol is re-enterable at every depth."""
    from seaweedfs_tpu.ec.repair_journal import _write_journal
    from seaweedfs_tpu.utils.crc import crc32c

    base, blobs = synth(tmp_path, seed=4)
    rot_leaf(base, 0, 1)
    prot = BitrotProtection.load(base + ".ecsum")
    good = blobs[0][LEAF : 2 * LEAF]
    _write_journal(
        journal_path(base + CTX.to_ext(0)),
        RepairJournal(
            0, prot.generation, prot.uuid, LEAF, SHARD_SIZE,
            [LeafPatch(1, LEAF, good, crc32c(good))],
        ),
    )
    mp = multiprocessing.get_context("fork")
    p = mp.Process(target=_crashing_recovery_child, args=(base,))
    p.start()
    p.join(timeout=60)
    assert p.exitcode == 137
    assert os.path.exists(journal_path(base + CTX.to_ext(0))), (
        "journal must survive a crashed replay"
    )
    rec = recover_volume_journals(base, CTX)
    assert rec["replayed"] == {0: [1]}
    assert open(base + CTX.to_ext(0), "rb").read() == blobs[0]
    assert shard_fully_verifies(base, 0)


def test_crash_window_then_mount_recovers_and_reads_bit_exact(tmp_path):
    """EcVolume mount runs journal recovery BEFORE opening shard fds:
    a crash between journal and patch heals transparently at mount and
    the (real byte path) reads come back bit-exact."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.ec import ec_encode_volume

    ctx = ECContext(10, 4)
    rng = np.random.default_rng(17)
    v = Volume(str(tmp_path), 1)
    payloads = {}
    for i in range(1, 16):
        data = rng.integers(0, 256, 30_000, dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x1000 + i, needle_id=i, data=data))
        payloads[i] = data
    v.close()
    base = Volume.base_file_name(str(tmp_path), "", 1)
    ec_encode_volume(base, ctx)
    prot = BitrotProtection.load(base + ".ecsum")
    original = open(base + ctx.to_ext(0), "rb").read()

    # simulate a crash AFTER intent, BEFORE patch: rot a leaf, write the
    # journal carrying the correct bytes, and "die"
    lsize = prot.leaf_size
    with open(base + ctx.to_ext(0), "r+b") as f:
        f.seek(5)
        f.write(b"\xff\xee\xdd")
    good = original[:lsize]
    from seaweedfs_tpu.ec.repair_journal import RepairJournal, _write_journal

    _write_journal(
        journal_path(base + ctx.to_ext(0)),
        RepairJournal(
            0, prot.generation, prot.uuid, lsize, prot.shard_sizes[0],
            [LeafPatch(0, 0, good, __import__(
                "seaweedfs_tpu.utils.crc", fromlist=["crc32c"]
            ).crc32c(good))],
        ),
    )

    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        assert not os.path.exists(journal_path(base + ctx.to_ext(0)))
        assert open(base + ctx.to_ext(0), "rb").read() == original
        for i, want in payloads.items():
            assert ev.read_needle(i, cookie=0x1000 + i).data == want
    finally:
        ev.close()


def test_content_changing_patch_flips_sidecar(tmp_path):
    """The general protocol: a patch whose CRCs DIFFER from the sidecar
    publishes the flipped sidecar (leaf row + re-folded block row), and
    a crash between patch and flip still converges on recovery."""
    from seaweedfs_tpu.utils.crc import crc32c

    base, blobs = synth(tmp_path, seed=5)
    sid = 0
    new_leaf = bytes(255 - b for b in blobs[sid][LEAF : 2 * LEAF])
    patch = LeafPatch(1, LEAF, new_leaf, crc32c(new_leaf))
    prot = BitrotProtection.load(base + ".ecsum")

    with faults.injected("ec.repair.after_patch", faults.crash()):
        with pytest.raises(faults.InjectedCrash):
            apply_leaf_repair(base + CTX.to_ext(sid), sid, prot, [patch])
    # crash window: shard patched, sidecar flip pending on disk
    disk_prot = BitrotProtection.load(base + ".ecsum")
    assert disk_prot.shard_leaf_crcs[sid][1] != patch.crc
    rec = recover_volume_journals(base, CTX, disk_prot)
    assert rec["replayed"] == {sid: [1]}
    disk_prot = BitrotProtection.load(base + ".ecsum")
    assert disk_prot.shard_leaf_crcs[sid][1] == patch.crc
    # block CRCs were re-folded: the whole shard verifies clean
    assert shard_fully_verifies(base, sid, disk_prot)
    got = open(base + CTX.to_ext(sid), "rb").read()
    assert got[LEAF : 2 * LEAF] == new_leaf


def test_stale_journal_kept_then_ttl_swept(tmp_path):
    """A journal whose generation fence mismatches the mounted sidecar
    (volume re-encoded since) is NEVER replayed — kept for forensics,
    then retired by scrub's TTL sweep and counted in the report."""
    base, blobs = synth(tmp_path, seed=6)
    jp = journal_path(base + CTX.to_ext(3))
    from seaweedfs_tpu.ec.repair_journal import _write_journal
    from seaweedfs_tpu.utils.crc import crc32c

    stale_data = b"\x00" * LEAF
    _write_journal(
        jp,
        RepairJournal(
            3, 999999, b"x" * 16, LEAF, SHARD_SIZE,
            [LeafPatch(0, 0, stale_data, crc32c(stale_data))],
        ),
    )
    original = open(base + CTX.to_ext(3), "rb").read()
    rec = recover_volume_journals(base, CTX)
    assert rec["kept"] == [jp] and not rec["replayed"]
    assert os.path.exists(jp)
    assert open(base + CTX.to_ext(3), "rb").read() == original, (
        "a stale journal must never patch the shard"
    )
    # young journal survives the sweep; an expired one is retired
    assert sweep_stale_journals(base, CTX, ttl_s=3600.0) == []
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), journal_ttl_s=0.0
    )
    assert r.swept_journals == [jp]
    assert not os.path.exists(jp)
    # a VALID journal is never swept, whatever its age
    prot = BitrotProtection.load(base + ".ecsum")
    good = original[:LEAF]
    _write_journal(
        jp,
        RepairJournal(
            3, prot.generation, prot.uuid, LEAF, prot.shard_sizes[3],
            [LeafPatch(0, 0, good, crc32c(good))],
        ),
    )
    assert sweep_stale_journals(base, CTX, ttl_s=0.0) == []
    assert os.path.exists(jp)


# --------------------------------------------------- scrub integration


def test_scrub_leaf_repairs_in_place_no_quarantine(tmp_path):
    base, blobs = synth(tmp_path, seed=8)
    rot_leaf(base, 2, 1)
    rot_leaf(base, 2, 12)  # tail leaf too
    events = []
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=True,
        on_leaf_patched=lambda sid, rg: events.append((sid, rg)),
    )
    assert r.leaf_repaired == {2: [1, 12]}, r
    assert not r.corrupt_shards and not r.quarantined and not r.rebuilt
    assert not os.path.exists(base + CTX.to_ext(2) + QUARANTINE_SUFFIX)
    assert open(base + CTX.to_ext(2), "rb").read() == blobs[2]
    assert events == [(2, [(LEAF, 2 * LEAF), (12 * LEAF, SHARD_SIZE)])]
    assert scrub_ec_volume(base, CTX, backend=CpuBackend(CTX)).healthy


def test_scrub_leaf_repair_below_floor_leaves_file_for_peers(tmp_path):
    """A subset holder below k verified-good local shards cannot leaf-
    repair locally: scrub refuses (existing floor rule) and the rotten
    file stays IN PLACE — exactly what the peer-fetch ranged repair
    needs (a quarantine would delete the canonical offsets)."""
    base, blobs = synth(tmp_path, local=(0, 1, 2), seed=9)
    rot_leaf(base, 2, 0)
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=True,
        expected_shards=[0, 1, 2],
    )
    assert r.refused and "refusing to quarantine" in r.refused
    assert not r.leaf_repaired
    assert os.path.exists(base + CTX.to_ext(2))
    assert not os.path.exists(base + CTX.to_ext(2) + QUARANTINE_SUFFIX)


def test_scrub_leaf_repair_corrupt_sibling_excluded(tmp_path):
    """Two shards rot at once: each repair must exclude the OTHER
    corrupt shard from its source set (verify-and-exclude) and both
    heal from the clean remainder."""
    base, blobs = synth(tmp_path, seed=10)
    rot_leaf(base, 0, 1)
    rot_leaf(base, 5, 1)  # same leaf index in a parity shard
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert sorted(r.leaf_repaired) == [0, 5], r
    assert open(base + CTX.to_ext(0), "rb").read() == blobs[0]
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_bad_leaves_aging_parity_after_leaf_repair(tmp_path):
    """Satellite: a stale .bad + .bad.leaves pair from an earlier
    whole-shard pass ages out once the shard is leaf-repaired (a
    verified replacement), and an ORPHANED .bad.leaves (its .bad
    already gone) ages out too."""
    import json as _json

    base, blobs = synth(tmp_path, seed=11)
    # stale quarantine artifacts for shard 1 (earlier pass), orphaned
    # leaf marker for shard 4
    bad1 = base + CTX.to_ext(1) + QUARANTINE_SUFFIX
    with open(bad1, "wb") as f:
        f.write(b"old forensic copy")
    with open(bad1 + ".leaves", "w") as f:
        _json.dump({"leaf_size": LEAF, "leaves": [3]}, f)
    orphan = base + CTX.to_ext(4) + QUARANTINE_SUFFIX + ".leaves"
    with open(orphan, "w") as f:
        _json.dump({"leaf_size": LEAF, "leaves": [0]}, f)

    rot_leaf(base, 1, 3)
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=True, bad_retention_s=0.0
    )
    assert r.leaf_repaired == {1: [3]}
    assert bad1 in r.aged_out and not os.path.exists(bad1)
    assert not os.path.exists(bad1 + ".leaves"), (
        ".bad.leaves must retire with its .bad"
    )
    assert orphan in r.aged_out and not os.path.exists(orphan)


def test_scrub_journal_recovery_reported(tmp_path):
    """A pending valid journal is replayed AT PASS START and the pass
    then verifies clean — the walk judges fully-new bytes."""
    from seaweedfs_tpu.ec.repair_journal import _write_journal
    from seaweedfs_tpu.utils.crc import crc32c

    base, blobs = synth(tmp_path, seed=12)
    rot_leaf(base, 3, 2)
    prot = BitrotProtection.load(base + ".ecsum")
    good = blobs[3][2 * LEAF : 3 * LEAF]
    _write_journal(
        journal_path(base + CTX.to_ext(3)),
        RepairJournal(
            3, prot.generation, prot.uuid, LEAF, prot.shard_sizes[3],
            [LeafPatch(2, 2 * LEAF, good, crc32c(good))],
        ),
    )
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX))
    assert r.journal_replayed == {3: [2]} and r.healthy, r
    assert open(base + CTX.to_ext(3), "rb").read() == blobs[3]


# ------------------------------------------------- ranged peer fetch


def test_ranged_fetch_request_shape_regression(tmp_path):
    """ISSUE-8 acceptance: a single-leaf repair moves <= 2·k·leaf bytes
    over the wire, and every request is exactly the rotten leaf's
    byte range — never a whole shard."""
    base, blobs = synth(tmp_path, local=(0, 1, 2), seed=13)
    rot_leaf(base, 2, 2)
    calls = []

    def fetch(peer, sid, off, size):
        calls.append((sid, off, size))
        return blobs[sid][off : off + size]

    rep = rebuild_from_peers(
        base, {s: ["peerB"] for s in range(CTX.total)}, fetch,
        targets=[], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.leaf_repaired == {2: [2]}
    assert open(base + CTX.to_ext(2), "rb").read() == blobs[2]
    assert rep.rebuilt == [] and not os.path.exists(staging_dir(base))
    # request shape: leaf-aligned ranges only
    assert calls and all(
        off == 2 * LEAF and size == LEAF for _, off, size in calls
    ), calls
    # wire budget: k sources, 2 good local => k-2 fetched leaves;
    # hard acceptance bound is 2·k·leaf
    assert rep.repair_wire_bytes == (CTX.data_shards - 2) * LEAF
    assert rep.repair_wire_bytes <= 2 * CTX.data_shards * LEAF


def test_ranged_fetch_corrupt_peer_excluded_and_replanned(tmp_path):
    """A peer serving persistent rot for a range is excluded after one
    granule re-read and the repair re-routes to a clean holder."""
    base, blobs = synth(tmp_path, local=(0, 1, 2), seed=14)
    rot_leaf(base, 0, 1)

    def fetch(peer, sid, off, size):
        chunk = blobs[sid][off : off + size]
        if peer == "rotten":
            return bytes([chunk[0] ^ 0xFF]) + chunk[1:]
        return chunk

    holders = {s: ["rotten", "clean"] for s in range(CTX.total)}
    rep = rebuild_from_peers(
        base, holders, fetch,
        targets=[], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.leaf_repaired == {0: [1]}
    assert rep.excluded_peers == ["rotten"]
    assert open(base + CTX.to_ext(0), "rb").read() == blobs[0]


def test_ranged_fetch_below_k_falls_back_to_whole_shard(tmp_path):
    """Rot that is NOT leaf-localized (truncation) keeps the existing
    whole-shard replacement path — and the two compose in one run."""
    base, blobs = synth(tmp_path, local=(0, 1, 2, 3), seed=15)
    rot_leaf(base, 2, 1)  # leaf-repairable
    path3 = base + CTX.to_ext(3)
    os.truncate(path3, SHARD_SIZE - 100)  # NOT leaf-repairable

    rep = rebuild_from_peers(
        base, {s: ["peerB"] for s in range(CTX.total)},
        lambda peer, sid, off, size: blobs[sid][off : off + size],
        targets=[], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.leaf_repaired == {2: [1]}
    assert 3 in rep.rebuilt  # whole-shard replaced
    assert open(base + CTX.to_ext(2), "rb").read() == blobs[2]
    assert open(path3, "rb").read() == blobs[3]


def test_ranged_fetch_local_read_error_falls_through_to_peers(tmp_path):
    """A transient local I/O error on a verified-good source must NOT
    forfeit the ranged repair: the same shard's range is fetched from
    a peer holder instead (the cheap path survives one flaky pread)."""
    base, blobs = synth(tmp_path, local=(0, 1, 2), seed=17)
    rot_leaf(base, 2, 1)
    fetched = []

    def fetch(peer, sid, off, size):
        fetched.append(sid)
        return blobs[sid][off : off + size]

    # every LOCAL source read errors once; peers cover the gap
    with faults.injected(
        "ec.repair.source_read", faults.io_error("flaky local disk")
    ):
        rep = rebuild_from_peers(
            base, {s: ["peerB"] for s in range(CTX.total)}, fetch,
            targets=[], backend=CpuBackend(CTX), policy=FAST,
        )
    assert rep.leaf_repaired == {2: [1]}
    assert open(base + CTX.to_ext(2), "rb").read() == blobs[2]
    # the good-local shards' ranges came over the wire instead
    assert set(fetched) >= {0, 1}, fetched


def test_ranged_fetch_unreachable_peers_fall_back(tmp_path):
    """Every peer dead: ranged repair refuses, the shard falls through
    to the whole-shard path, which ALSO refuses below k — the canonical
    file stays untouched (fail-closed end to end)."""
    base, blobs = synth(tmp_path, local=(0, 1, 2), seed=16)
    rot_leaf(base, 2, 0)
    pre = open(base + CTX.to_ext(2), "rb").read()

    def dead(peer, sid, off, size):
        raise IOError("peer down")

    with pytest.raises(ECError, match="refusing"):
        rebuild_from_peers(
            base, {s: ["peerB"] for s in range(CTX.total)}, dead,
            targets=[], backend=CpuBackend(CTX), policy=FAST,
        )
    assert open(base + CTX.to_ext(2), "rb").read() == pre
    assert not os.path.exists(journal_path(base + CTX.to_ext(2)))


# --------------------------------------------- capacity-aware placement


def test_placement_capacity_gating_and_headroom_tiebreak():
    from seaweedfs_tpu.ec.placement import NodeView, plan_shard_placement

    full = NodeView(id="full", free_slots=10, free_bytes=100)
    roomy = NodeView(id="roomy", free_slots=10, free_bytes=10_000)
    unknown = NodeView(id="unknown", free_slots=10)  # free_bytes=-1
    # byte gate: a shard that does not fit never lands on `full`
    plan = plan_shard_placement([full, roomy], 1, [0, 1], shard_bytes=500)
    assert plan == {0: "roomy", 1: "roomy"}
    # headroom tiebreak (equal shards/slots): roomy beats full
    plan = plan_shard_placement(
        [NodeView(id="a", free_slots=5, free_bytes=100),
         NodeView(id="b", free_slots=5, free_bytes=900)],
        1, [0], shard_bytes=50,
    )
    assert plan == {0: "b"}
    # unknown headroom keeps slot-only planning (no byte gate)
    plan = plan_shard_placement([unknown], 1, [0], shard_bytes=10**12)
    assert plan == {0: "unknown"}
    # planner decrements headroom as it assigns: 2 shards of 600 can't
    # both land on a 1000-byte node
    a = NodeView(id="a", free_slots=10, free_bytes=1000)
    b = NodeView(id="b", free_slots=10, free_bytes=1000)
    plan = plan_shard_placement([a, b], 1, [0, 1, 2], shard_bytes=600)
    assert sorted(plan.values()) == ["a", "b"] and len(plan) == 2


def test_node_view_for_headroom():
    from seaweedfs_tpu.ec.placement import node_view_for

    class E:
        def __init__(s, id, bits):
            s.id, s.shard_bits, s.collection = id, bits, ""

    v = node_view_for(
        "n1", "r", "dc", 8, 2, [E(1, 0b111)],
        used_bytes=300, capacity_bytes=1000,
    )
    assert v.free_bytes == 700
    v2 = node_view_for("n1", "r", "dc", 8, 2, [E(1, 0b111)])
    assert v2.free_bytes == -1  # unknown stays unknown


# ----------------------------------------------------- cache precision


def test_interval_cache_invalidated_per_patched_range(tmp_path):
    """invalidate_shard_ranges drops ONLY cached extents overlapping
    the patched bytes; the shard's other cached reconstructions stay."""
    from seaweedfs_tpu.utils.chunk_cache import ChunkCache

    cache = ChunkCache(1 << 20)
    base, blobs = synth(tmp_path, seed=18)
    # minimal EcVolume stand-in state: use the real method via an
    # instance (needs .ecx; fabricate through the public ctor is heavy
    # here, so drive drop_matching directly the way EcVolume keys it)
    ns = "1:"
    cache.put(f"{ns}2:0:0:1024", b"a" * 10)
    cache.put(f"{ns}2:0:2048:4096", b"b" * 10)
    cache.put(f"{ns}3:0:0:1024", b"c" * 10)
    prefix = f"{ns}2:0:"

    def overlaps(key):
        lo, hi = map(int, key[len(prefix):].split(":"))
        return lo < 4096 and 2048 < hi

    cache.drop_matching(prefix, overlaps)
    assert cache.get(f"{ns}2:0:0:1024") is not None
    assert cache.get(f"{ns}2:0:2048:4096") is None
    assert cache.get(f"{ns}3:0:0:1024") is not None


# ------------------------------------------------------------- metrics


def test_leaf_repair_metrics_registered_and_incremented(tmp_path):
    from seaweedfs_tpu.utils import metrics as M

    base, blobs = synth(tmp_path, seed=19)
    rot_leaf(base, 0, 0)
    scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    text = M.REGISTRY.render().decode()
    assert 'sw_ec_leaf_repairs_total{outcome="repaired"}' in text
    assert "sw_ec_repair_journal_total" in text
