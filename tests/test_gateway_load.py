"""ISSUE 11: gateway read path at production concurrency.

The serving-side contract under load: (1) the bounded worker-pool HTTP
front end degrades gracefully (keep-alive reuse, park/resume, explicit
503 + Retry-After with a parseable body at saturation — never unbounded
thread spawn or silent collapse); (2) the hot-chunk cache collapses
concurrent misses to ONE degraded reconstruction (singleflight) and
never serves a stale generation after remount/rebuild invalidation;
(3) with the fault registry ARMED (one shard dead + injected latency)
and >=32 concurrent clients, every response is byte-correct or a clean
503 — no hangs, no corrupt bodies — while gateway reads run in the
scheduler's FOREGROUND class (visible via span stage attribution).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import xml.etree.ElementTree as ET

import numpy as np
import pytest
import requests

from conftest import allocate_port as free_port

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import CpuBackend, ECContext, EcVolume, ec_encode_volume
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils import trace
from seaweedfs_tpu.utils.http_pool import PooledHTTPServer

CTX = ECContext(10, 4)


def _wait(cond, timeout=20.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        assert time.time() < deadline, f"timed out: {msg}"
        time.sleep(0.05)


# =====================================================================
# Pooled HTTP front end (utils/http_pool.py)
# =====================================================================


def _make_echo_handler():
    from http.server import BaseHTTPRequestHandler

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"echo:" + self.path.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return H


def test_pooled_server_keepalive_park_resume():
    """A keep-alive connection survives idle parking: requests flow,
    the connection parks (no worker pinned), and a later request on the
    SAME connection is served."""
    srv = PooledHTTPServer(
        ("127.0.0.1", 0), _make_echo_handler(), workers=2, accept_queue=4,
        server_kind="test",
    )
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        sess = requests.Session()
        assert sess.get(f"http://127.0.0.1:{port}/a").content == b"echo:/a"
        time.sleep(1.0)  # parked well past any dispatch loop
        assert sess.get(f"http://127.0.0.1:{port}/b").content == b"echo:/b"
        st = srv.pool_status()
        assert st["requests_served"] >= 2
        assert st["open_connections"] >= 1  # the parked keep-alive conn
    finally:
        srv.shutdown()
        srv.server_close()


def test_pooled_server_bounded_and_503_shape():
    """Past workers + accept_queue live connections, a new connection
    is answered 503 + Retry-After with the configured body — explicit
    backpressure, not an unbounded thread or a hung accept."""
    srv = PooledHTTPServer(
        ("127.0.0.1", 0), _make_echo_handler(), workers=1, accept_queue=1,
        server_kind="test",
        reject_body=lambda: ("application/json", b'{"error": "full"}'),
    )
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    helds = []
    try:
        # hold max_connections=2 idle keep-alive connections
        for _ in range(2):
            c = socket.create_connection(("127.0.0.1", port))
            helds.append(c)
        time.sleep(0.3)  # let the acceptor admit both
        r = requests.get(f"http://127.0.0.1:{port}/x", timeout=5)
        assert r.status_code == 503
        assert r.headers.get("Retry-After")
        assert r.headers.get("Content-Type") == "application/json"
        assert json.loads(r.content)["error"] == "full"
        assert srv.pool_status()["rejected_total"] >= 1
        # draining a held connection frees budget for new clients
        helds.pop().close()
        _wait(
            lambda: requests.get(
                f"http://127.0.0.1:{port}/y", timeout=5
            ).status_code == 200,
            msg="admission after a connection freed",
        )
    finally:
        for c in helds:
            c.close()
        srv.shutdown()
        srv.server_close()


def test_pooled_server_concurrent_correctness():
    """More concurrent clients than workers: every response still maps
    to ITS request (no cross-connection body mixups under dispatch)."""
    srv = PooledHTTPServer(
        ("127.0.0.1", 0), _make_echo_handler(), workers=4, accept_queue=64,
        server_kind="test",
    )
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    errors = []

    def client(i):
        try:
            sess = requests.Session()
            for j in range(5):
                r = sess.get(f"http://127.0.0.1:{port}/c{i}-{j}", timeout=15)
                assert r.status_code == 200
                assert r.content == b"echo:/c%d-%d" % (i, j)
        except Exception as e:
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(24)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    alive = [t for t in threads if t.is_alive()]
    try:
        assert not alive, f"{len(alive)} clients hung"
        assert not errors, errors[:5]
        assert time.time() - t0 < 60
    finally:
        srv.shutdown()
        srv.server_close()


def test_s3_saturation_returns_wellformed_error_document():
    """The S3 plane's 503 body parses as an S3 error document
    (Code=SlowDown) so SDK clients back off instead of choking."""
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.s3 import S3Server

    filer = Filer(MemoryStore(), master="localhost:1")
    srv = S3Server(
        filer, ip="127.0.0.1", port=free_port(),
        lifecycle_interval=0, http_workers=1, http_queue=0,
    )
    srv.start()
    helds = []
    try:
        helds.append(socket.create_connection(("127.0.0.1", srv.port)))
        time.sleep(0.3)
        r = requests.get(f"http://127.0.0.1:{srv.port}/", timeout=5)
        assert r.status_code == 503
        assert r.headers.get("Retry-After")
        doc = ET.fromstring(r.content)
        assert doc.tag == "Error"
        assert doc.findtext("Code") == "SlowDown"
        assert doc.findtext("Message")
    finally:
        for c in helds:
            c.close()
        srv.stop()
        filer.close()


# =====================================================================
# Hot-chunk cache semantics on the EC degraded-read path
# =====================================================================


def _make_degraded_volume(tmp_path, vid=1, needles=24, seed=3):
    rng = np.random.default_rng(seed)
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, needles + 1):
        size = int(rng.integers(2_000, 30_000))
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x2000 + i, needle_id=i, data=data))
        payloads[i] = data
    v.close()
    base = Volume.base_file_name(str(tmp_path), "", vid)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    vol = EcVolume(str(tmp_path), vid, backend_name="cpu")
    vol.unmount_shards([0])  # degrade: stripe-0 reads must reconstruct
    return vol, payloads


def _needle_on_shard0(vol, needles=24) -> int:
    """A needle whose whole record lives on shard 0 (single-interval
    reconstruction — deterministic singleflight key)."""
    from seaweedfs_tpu.ec.locate import locate_data
    from seaweedfs_tpu.storage.types import actual_offset
    from seaweedfs_tpu.ec.decoder import record_actual_size

    for nid in range(1, needles + 1):
        nv = vol._ecx.get(nid)
        ivs = list(
            locate_data(
                actual_offset(nv.offset),
                record_actual_size(nv.size, vol.version),
                vol._locate_shard_size,
                CTX.data_shards,
            )
        )
        if len(ivs) == 1:
            sid, _ = ivs[0].to_shard_and_offset(CTX.data_shards)
            if sid == 0:
                return nid
    pytest.skip("no single-interval needle landed on shard 0")


def test_concurrent_degraded_reads_collapse_to_one_reconstruction(tmp_path):
    """THE tentpole assert: K concurrent misses on one degraded chunk
    -> exactly ONE reconstruction, all K responses byte-identical."""
    vol, payloads = _make_degraded_volume(tmp_path)
    nid = _needle_on_shard0(vol)

    recon_calls = []
    orig = vol.backend.reconstruct
    gate = threading.Event()

    def counting_reconstruct(sources, want):
        recon_calls.append(want)
        gate.wait(5)  # hold the leader so every reader joins the flight
        return orig(sources, want=want)

    vol.backend.reconstruct = counting_reconstruct
    results, errors = [], []
    lock = threading.Lock()

    def reader():
        try:
            n = vol.read_needle(nid)
            with lock:
                results.append(n.data)
        except Exception as e:
            with lock:
                errors.append(repr(e))

    threads = [threading.Thread(target=reader) for _ in range(12)]
    for t in threads:
        t.start()
    _wait(lambda: len(recon_calls) >= 1, msg="leader reconstruction")
    time.sleep(0.2)  # let every follower pile onto the flight
    gate.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors[:3]
    assert len(results) == 12
    assert all(r == payloads[nid] for r in results), "byte-identity"
    assert len(recon_calls) == 1, (
        f"{len(recon_calls)} reconstructions for 12 concurrent reads "
        "(singleflight must collapse them to 1)"
    )
    assert vol.interval_cache.singleflight_waits >= 11
    # the flight's verified output is now cached: a fresh read is free
    vol.read_needle(nid)
    assert len(recon_calls) == 1
    vol.close()


def test_invalidation_never_serves_stale_generation(tmp_path):
    """Remount/rebuild bumps the shard generation: cached extents (and
    any in-flight load parked under the old key) become invisible — the
    next read reconstructs fresh bytes."""
    vol, payloads = _make_degraded_volume(tmp_path, seed=5)
    nid = _needle_on_shard0(vol)

    recon_calls = []
    orig = vol.backend.reconstruct

    def counting_reconstruct(sources, want):
        recon_calls.append(want)
        return orig(sources, want=want)

    vol.backend.reconstruct = counting_reconstruct
    assert vol.read_needle(nid).data == payloads[nid]
    assert len(recon_calls) == 1
    assert vol.read_needle(nid).data == payloads[nid]
    assert len(recon_calls) == 1, "second read must be a cache hit"
    # invalidate shard 0's cached extents (what rebuild/remount do)
    vol.reopen_shards([0])
    vol.unmount_shards([0])  # re-degrade (reopen remounted the shard)
    assert vol.read_needle(nid).data == payloads[nid]
    assert len(recon_calls) == 2, (
        "a generation bump must force a fresh reconstruction — the old "
        "cached extent may be stale"
    )
    vol.close()


# =====================================================================
# Chaos under gateway load (the carried PR 1 variant)
# =====================================================================


@pytest.fixture(scope="module")
def gateway_cluster(tmp_path_factory):
    """Real in-process cluster (master + pooled volume server + pooled
    S3 gateway) over ONE object on a DEGRADED EC volume."""
    import grpc

    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.pb import cluster_pb2 as cpb
    from seaweedfs_tpu.pb import rpc as _rpc
    from seaweedfs_tpu.s3 import S3Server
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.shell.commands import ShellEnv, run_command
    from seaweedfs_tpu.storage.file_id import FileId

    tmp = tmp_path_factory.mktemp("gwload")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    _wait(lambda: master.topo.nodes, msg="volume registration")
    filer = Filer(
        MemoryStore(), master=f"localhost:{mport}", chunk_size=32 * 1024
    )
    s3 = S3Server(filer, ip="localhost", port=free_port())
    s3.start()
    base = f"http://localhost:{s3.port}"
    rng = np.random.default_rng(0xC0FFEE)
    data = rng.integers(0, 256, 128 << 10, dtype=np.uint8).tobytes()
    assert requests.put(f"{base}/load").status_code == 200
    assert requests.put(f"{base}/load/obj", data=data).status_code == 200
    entry = filer.find_entry("/buckets/load/obj")
    vid = FileId.parse(entry.chunks[0].fid).volume_id
    env = ShellEnv(f"localhost:{mport}")
    try:
        out = run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        assert "generation" in out, out
    finally:
        env.close()
    _wait(
        lambda: any(vid in n.ec_shards for n in master.topo.nodes.values()),
        msg="ec shards via heartbeat",
    )
    with grpc.insecure_channel(f"localhost:{vs.grpc_port}") as ch:
        _rpc.volume_stub(ch).VolumeEcShardsUnmount(
            cpb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[0])
        )
    yield {
        "master": master,
        "vs": vs,
        "filer": filer,
        "s3": s3,
        "base": base,
        "data": data,
        "vid": vid,
    }
    s3.stop()
    filer.close()
    vs.stop()
    master.stop()


def _drop_gateway_caches(gw):
    gw["filer"].chunk_cache.clear()
    cache = gw["vs"].store.ec_interval_cache
    if cache is not None:
        cache.clear()


def test_chaos_under_gateway_load(gateway_cluster):
    """Fault registry ARMED (one data shard dead + latency spikes on
    mounted shard reads) while 32 concurrent clients hammer GETs:
    every response must be byte-correct or a clean 503 — no hangs, no
    corrupt bodies. Caches dropped per burst so the data plane (and its
    fault points) stays exercised."""
    gw = gateway_cluster
    handle = faults.inject(
        "ec.volume.shard_read",
        faults.latency(0.02),
        when=faults.every(7),
    )
    counts = {"ok": 0, "unavailable": 0, "bad": 0}
    lock = threading.Lock()

    def client(i: int):
        sess = requests.Session()
        for j in range(3):
            if j == 0 and i % 8 == 0:
                _drop_gateway_caches(gw)  # keep misses flowing
            try:
                r = sess.get(f"{gw['base']}/load/obj", timeout=60)
            except Exception:
                with lock:
                    counts["bad"] += 1
                continue
            with lock:
                if r.status_code == 200 and r.content == gw["data"]:
                    counts["ok"] += 1
                elif r.status_code == 503:
                    counts["unavailable"] += 1  # clean backpressure
                else:
                    counts["bad"] += 1

    try:
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(32)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        alive = [t for t in threads if t.is_alive()]
        assert not alive, f"{len(alive)} clients hung under chaos"
    finally:
        faults.REGISTRY.remove(handle)
    assert counts["bad"] == 0, counts
    assert counts["ok"] > 0, counts
    assert counts["ok"] + counts["unavailable"] == 32 * 3
    # serving traffic ran in the scheduler's FOREGROUND class
    snaps = gw["vs"].store.ec_scheduler.stats_snapshot()
    fg_admitted = sum(
        s["classes"]["foreground"]["admitted"] for s in snaps
    )
    assert fg_admitted > 0, snaps


def test_degraded_get_trace_shows_foreground_admission(gateway_cluster):
    """Span stage attribution proves the scheduler integration: a
    degraded GET's trace carries an ec.degraded_read span with an
    admission_wait stage (the foreground ticket's wait)."""
    gw = gateway_cluster
    trace.configure(
        enabled=True, ring_size=512,
        ring_spans=trace.DEFAULT_RING_SPANS, slow_op_s=0.0,
    )
    trace.reset()
    try:
        _drop_gateway_caches(gw)
        r = requests.get(f"{gw['base']}/load/obj", timeout=60)
        assert r.status_code == 200 and r.content == gw["data"]
        tid = r.headers.get(trace.TRACE_ID_HEADER)
        assert tid

        def walk(node):
            yield node
            for ch in node.get("children", ()):
                yield from walk(ch)

        stages = set()
        found_degraded = False
        for doc in trace.traces(tid):
            for node in walk(doc):
                if node["op"] == "ec.degraded_read":
                    found_degraded = True
                    stages.update(node["stages"])
        assert found_degraded, "degraded read must be in the GET's trace"
        assert "admission_wait" in stages, (
            f"foreground admission must be attributed in stages: {stages}"
        )
    finally:
        trace.configure(enabled=False)
        trace.reset()


def test_hot_cache_kills_miss_path_and_debug_gateway_surface(
    gateway_cluster,
):
    """With caches warm, repeated GETs stay off the reconstruction
    path (hot-cache hits climb, reconstructions don't), and the
    /debug/gateway surface exposes the counters + front-end state."""
    gw = gateway_cluster
    _drop_gateway_caches(gw)
    assert (
        requests.get(f"{gw['base']}/load/obj", timeout=60).content
        == gw["data"]
    )
    hits_before = gw["filer"].chunk_cache.hits
    loads_before = gw["filer"].chunk_cache.loads
    for _ in range(5):
        r = requests.get(f"{gw['base']}/load/obj", timeout=60)
        assert r.status_code == 200 and r.content == gw["data"]
    assert gw["filer"].chunk_cache.hits > hits_before
    assert gw["filer"].chunk_cache.loads == loads_before, (
        "warm GETs must not touch the chunk-fetch path"
    )
    # the SLO-adjacent surface on the volume server's status plane
    vs = gw["vs"]
    doc = requests.get(
        f"http://localhost:{vs.port}/debug/gateway", timeout=10
    ).json()
    assert doc["front_end"]["kind"] == "pooled"
    assert doc["front_end"]["workers"] > 0
    assert "filer_chunk" in doc["hot_cache"]
    assert doc["hot_cache"]["filer_chunk"]["hits"] > 0
    assert "ec_interval" in doc["hot_cache"]
    assert "inflight" in doc and "rejected" in doc
