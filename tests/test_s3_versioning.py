"""S3 depth tests: object versioning, object lock/retention, lifecycle,
streaming-chunked SigV4.

Reference models: test/s3/versioning, test/s3/retention, test/s3
lifecycle suites and weed/s3api/chunked_reader_v4.go.
"""

import hashlib
import hmac
import time
import urllib.parse
import xml.etree.ElementTree as ET
from datetime import datetime, timedelta, timezone

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.s3 import Identity, IdentityStore, S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from conftest import allocate_port as free_port

REGION = "us-east-1"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3vvol")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


@pytest.fixture
def s3srv(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    srv = S3Server(filer, ip="localhost", port=free_port(), lifecycle_interval=0)
    srv.start()
    yield srv
    srv.stop()
    filer.close()


@pytest.fixture
def s3(s3srv):
    return f"http://localhost:{s3srv.port}"


def _xml_all(text, tag):
    root = ET.fromstring(text)
    ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
    return [e.text or "" for e in root.iter(f"{ns}{tag}")]


def _enable_versioning(s3, bucket):
    assert requests.put(f"{s3}/{bucket}").status_code in (200, 409)
    r = requests.put(
        f"{s3}/{bucket}?versioning",
        data="<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>",
    )
    assert r.status_code == 200


# --------------------------------------------------------------- versioning


def test_versioned_put_get_list(s3):
    _enable_versioning(s3, "vb")
    r1 = requests.put(f"{s3}/vb/doc", data=b"one")
    v1 = r1.headers["x-amz-version-id"]
    r2 = requests.put(f"{s3}/vb/doc", data=b"two")
    v2 = r2.headers["x-amz-version-id"]
    assert v1 != v2
    # latest wins on plain GET
    g = requests.get(f"{s3}/vb/doc")
    assert g.content == b"two"
    assert g.headers["x-amz-version-id"] == v2
    # versionId reads hit specific versions
    assert requests.get(f"{s3}/vb/doc?versionId={v1}").content == b"one"
    assert requests.get(f"{s3}/vb/doc?versionId={v2}").content == b"two"
    assert (
        requests.get(f"{s3}/vb/doc?versionId=deadbeef").status_code == 404
    )
    # ListObjectVersions: both versions, newest marked latest
    r = requests.get(f"{s3}/vb?versions")
    assert r.status_code == 200
    vids = _xml_all(r.text, "VersionId")
    assert v1 in vids and v2 in vids
    latest = dict(zip(vids, _xml_all(r.text, "IsLatest")))
    assert latest[v2] == "true" and latest[v1] == "false"
    # normal listing shows the key exactly once
    r = requests.get(f"{s3}/vb?list-type=2")
    assert _xml_all(r.text, "Key").count("doc") == 1


def test_delete_marker_and_restore(s3):
    _enable_versioning(s3, "vbm")
    v1 = requests.put(f"{s3}/vbm/k", data=b"data").headers["x-amz-version-id"]
    d = requests.delete(f"{s3}/vbm/k")
    assert d.status_code == 204
    assert d.headers.get("x-amz-delete-marker") == "true"
    marker_vid = d.headers["x-amz-version-id"]
    # plain GET now 404s but flags the marker
    g = requests.get(f"{s3}/vbm/k")
    assert g.status_code == 404
    assert g.headers.get("x-amz-delete-marker") == "true"
    # old version still readable by id
    assert requests.get(f"{s3}/vbm/k?versionId={v1}").content == b"data"
    # marker shows in versions listing
    r = requests.get(f"{s3}/vbm?versions")
    assert "DeleteMarker" in r.text
    # ...but not in the normal listing
    r = requests.get(f"{s3}/vbm?list-type=2")
    assert "k" not in _xml_all(r.text, "Key")
    # deleting the marker version restores the object
    assert (
        requests.delete(f"{s3}/vbm/k?versionId={marker_vid}").status_code
        == 204
    )
    assert requests.get(f"{s3}/vbm/k").content == b"data"


def test_delete_specific_version_promotes(s3):
    _enable_versioning(s3, "vbp")
    v1 = requests.put(f"{s3}/vbp/k", data=b"one").headers["x-amz-version-id"]
    v2 = requests.put(f"{s3}/vbp/k", data=b"two").headers["x-amz-version-id"]
    # delete the CURRENT version -> previous version becomes latest
    assert requests.delete(f"{s3}/vbp/k?versionId={v2}").status_code == 204
    g = requests.get(f"{s3}/vbp/k")
    assert g.content == b"one"
    assert g.headers["x-amz-version-id"] == v1
    # delete the last one -> object gone entirely
    assert requests.delete(f"{s3}/vbp/k?versionId={v1}").status_code == 204
    assert requests.get(f"{s3}/vbp/k").status_code == 404


def test_suspended_versioning_null_version(s3):
    _enable_versioning(s3, "vbs")
    v1 = requests.put(f"{s3}/vbs/k", data=b"one").headers["x-amz-version-id"]
    requests.put(
        f"{s3}/vbs?versioning",
        data="<VersioningConfiguration><Status>Suspended</Status></VersioningConfiguration>",
    )
    r = requests.put(f"{s3}/vbs/k", data=b"null-a")
    assert r.headers["x-amz-version-id"] == "null"
    # overwriting replaces the null version, keeps v1
    requests.put(f"{s3}/vbs/k", data=b"null-b")
    assert requests.get(f"{s3}/vbs/k").content == b"null-b"
    assert requests.get(f"{s3}/vbs/k?versionId={v1}").content == b"one"
    vids = _xml_all(requests.get(f"{s3}/vbs?versions").text, "VersionId")
    assert vids.count("null") == 1 and v1 in vids


def test_versioned_copy_and_multipart(s3):
    _enable_versioning(s3, "vbc")
    v1 = requests.put(f"{s3}/vbc/src", data=b"orig").headers["x-amz-version-id"]
    requests.put(f"{s3}/vbc/src", data=b"newer")
    # copy a specific source version
    r = requests.put(
        f"{s3}/vbc/dst",
        headers={"x-amz-copy-source": f"/vbc/src?versionId={v1}"},
    )
    assert r.status_code == 200
    assert "x-amz-version-id" in r.headers
    assert requests.get(f"{s3}/vbc/dst").content == b"orig"
    # multipart completion produces a version too
    up = requests.post(f"{s3}/vbc/mp?uploads")
    upload_id = _xml_all(up.text, "UploadId")[0]
    p1 = b"a" * 70_000
    requests.put(f"{s3}/vbc/mp?partNumber=1&uploadId={upload_id}", data=p1)
    done = requests.post(f"{s3}/vbc/mp?uploadId={upload_id}", data="")
    assert done.status_code == 200
    assert "x-amz-version-id" in done.headers
    assert requests.get(f"{s3}/vbc/mp").content == p1


def test_batch_delete_versioned_creates_markers(s3):
    _enable_versioning(s3, "vbb")
    requests.put(f"{s3}/vbb/a", data=b"1")
    requests.put(f"{s3}/vbb/b", data=b"2")
    body = (
        "<Delete><Object><Key>a</Key></Object>"
        "<Object><Key>b</Key></Object></Delete>"
    )
    r = requests.post(f"{s3}/vbb?delete", data=body)
    assert r.status_code == 200
    assert r.text.count("<DeleteMarkerVersionId>") == 2
    assert requests.get(f"{s3}/vbb/a").status_code == 404
    # data is retained as noncurrent versions
    vers = requests.get(f"{s3}/vbb?versions").text
    assert vers.count("<Version>") == 2 and vers.count("<DeleteMarker>") == 2


# --------------------------------------------------------------- object lock


def test_object_lock_retention_blocks_delete(s3):
    requests.put(
        f"{s3}/lockb", headers={"x-amz-bucket-object-lock-enabled": "true"}
    )
    # bucket came up with lock + versioning enabled
    assert "Enabled" in requests.get(f"{s3}/lockb?versioning").text
    assert (
        requests.get(f"{s3}/lockb?object-lock").status_code == 200
    )
    until = (datetime.now(timezone.utc) + timedelta(days=1)).isoformat()
    v = requests.put(
        f"{s3}/lockb/doc",
        data=b"held",
        headers={
            "x-amz-object-lock-mode": "COMPLIANCE",
            "x-amz-object-lock-retain-until-date": until,
        },
    ).headers["x-amz-version-id"]
    # GET surfaces the lock
    g = requests.get(f"{s3}/lockb/doc")
    assert g.headers["x-amz-object-lock-mode"] == "COMPLIANCE"
    # version deletion denied, even with governance bypass
    r = requests.delete(f"{s3}/lockb/doc?versionId={v}")
    assert r.status_code == 403
    r = requests.delete(
        f"{s3}/lockb/doc?versionId={v}",
        headers={"x-amz-bypass-governance-retention": "true"},
    )
    assert r.status_code == 403
    # simple DELETE (marker) is always allowed
    assert requests.delete(f"{s3}/lockb/doc").status_code == 204
    # the version itself survives
    assert requests.get(f"{s3}/lockb/doc?versionId={v}").content == b"held"


def test_governance_retention_bypass(s3):
    requests.put(
        f"{s3}/lockg", headers={"x-amz-bucket-object-lock-enabled": "true"}
    )
    v = requests.put(f"{s3}/lockg/doc", data=b"gov").headers["x-amz-version-id"]
    until = (datetime.now(timezone.utc) + timedelta(days=1)).isoformat()
    r = requests.put(
        f"{s3}/lockg/doc?retention",
        data=f"<Retention><Mode>GOVERNANCE</Mode><RetainUntilDate>{until}</RetainUntilDate></Retention>",
    )
    assert r.status_code == 200
    # readable retention
    r = requests.get(f"{s3}/lockg/doc?retention")
    assert "GOVERNANCE" in r.text
    # denied without bypass, allowed with it
    assert requests.delete(f"{s3}/lockg/doc?versionId={v}").status_code == 403
    r = requests.delete(
        f"{s3}/lockg/doc?versionId={v}",
        headers={"x-amz-bypass-governance-retention": "true"},
    )
    assert r.status_code == 204
    assert requests.get(f"{s3}/lockg/doc").status_code == 404


def test_legal_hold(s3):
    requests.put(
        f"{s3}/lockh", headers={"x-amz-bucket-object-lock-enabled": "true"}
    )
    v = requests.put(f"{s3}/lockh/doc", data=b"hh").headers["x-amz-version-id"]
    r = requests.put(
        f"{s3}/lockh/doc?legal-hold",
        data="<LegalHold><Status>ON</Status></LegalHold>",
    )
    assert r.status_code == 200
    assert "ON" in requests.get(f"{s3}/lockh/doc?legal-hold").text
    assert requests.delete(f"{s3}/lockh/doc?versionId={v}").status_code == 403
    requests.put(
        f"{s3}/lockh/doc?legal-hold",
        data="<LegalHold><Status>OFF</Status></LegalHold>",
    )
    assert requests.delete(f"{s3}/lockh/doc?versionId={v}").status_code == 204


def test_object_lock_bucket_cannot_suspend_versioning(s3):
    requests.put(
        f"{s3}/locks", headers={"x-amz-bucket-object-lock-enabled": "true"}
    )
    r = requests.put(
        f"{s3}/locks?versioning",
        data="<VersioningConfiguration><Status>Suspended</Status></VersioningConfiguration>",
    )
    assert r.status_code == 409


# ---------------------------------------------------------------- lifecycle


def test_lifecycle_config_roundtrip(s3):
    requests.put(f"{s3}/lcb")
    assert requests.get(f"{s3}/lcb?lifecycle").status_code == 404
    conf = (
        "<LifecycleConfiguration><Rule><ID>exp</ID><Status>Enabled</Status>"
        "<Filter><Prefix>logs/</Prefix></Filter>"
        "<Expiration><Days>7</Days></Expiration>"
        "</Rule></LifecycleConfiguration>"
    )
    assert requests.put(f"{s3}/lcb?lifecycle", data=conf).status_code == 200
    r = requests.get(f"{s3}/lcb?lifecycle")
    assert r.status_code == 200 and "<ID>exp</ID>" in r.text
    assert requests.delete(f"{s3}/lcb?lifecycle").status_code == 204
    assert requests.get(f"{s3}/lcb?lifecycle").status_code == 404
    # a rule with no action is malformed
    bad = (
        "<LifecycleConfiguration><Rule><ID>x</ID><Status>Enabled</Status>"
        "</Rule></LifecycleConfiguration>"
    )
    assert requests.put(f"{s3}/lcb?lifecycle", data=bad).status_code == 400


def test_lifecycle_expiration_scan(s3, s3srv):
    requests.put(f"{s3}/lce")
    requests.put(f"{s3}/lce/logs/old", data=b"old")
    requests.put(f"{s3}/lce/keep", data=b"keep")
    conf = (
        "<LifecycleConfiguration><Rule><Status>Enabled</Status>"
        "<Filter><Prefix>logs/</Prefix></Filter>"
        "<Expiration><Days>7</Days></Expiration>"
        "</Rule></LifecycleConfiguration>"
    )
    requests.put(f"{s3}/lce?lifecycle", data=conf)
    # nothing is old enough yet
    stats = s3srv.lifecycle.run_once()
    assert stats["expired"] == 0
    # jump the clock 8 days
    stats = s3srv.lifecycle.run_once(now=time.time() + 8 * 86400)
    assert stats["expired"] == 1
    assert requests.get(f"{s3}/lce/logs/old").status_code == 404
    assert requests.get(f"{s3}/lce/keep").content == b"keep"


def test_lifecycle_versioned_expiry_and_noncurrent(s3, s3srv):
    _enable_versioning(s3, "lcv")
    requests.put(f"{s3}/lcv/doc", data=b"v1")
    requests.put(f"{s3}/lcv/doc", data=b"v2")
    conf = (
        "<LifecycleConfiguration><Rule><Status>Enabled</Status>"
        "<Expiration><Days>7</Days></Expiration>"
        "<NoncurrentVersionExpiration><NoncurrentDays>30</NoncurrentDays>"
        "</NoncurrentVersionExpiration>"
        "</Rule></LifecycleConfiguration>"
    )
    requests.put(f"{s3}/lcv?lifecycle", data=conf)
    stats = s3srv.lifecycle.run_once(now=time.time() + 8 * 86400)
    # current expired to a delete marker; both versions retained
    assert stats["expired"] == 1
    assert requests.get(f"{s3}/lcv/doc").status_code == 404
    vers = requests.get(f"{s3}/lcv?versions").text
    assert vers.count("<Version>") == 2
    # noncurrent expiry reaps the archived versions
    stats = s3srv.lifecycle.run_once(now=time.time() + 40 * 86400)
    assert stats["noncurrent_expired"] >= 2
    vers = requests.get(f"{s3}/lcv?versions").text
    assert vers.count("<Version>") == 0


def test_lifecycle_abort_multipart(s3, s3srv):
    requests.put(f"{s3}/lcm")
    up = requests.post(f"{s3}/lcm/big?uploads")
    upload_id = _xml_all(up.text, "UploadId")[0]
    requests.put(
        f"{s3}/lcm/big?partNumber=1&uploadId={upload_id}", data=b"x" * 70_000
    )
    conf = (
        "<LifecycleConfiguration><Rule><Status>Enabled</Status>"
        "<AbortIncompleteMultipartUpload><DaysAfterInitiation>3"
        "</DaysAfterInitiation></AbortIncompleteMultipartUpload>"
        "</Rule></LifecycleConfiguration>"
    )
    requests.put(f"{s3}/lcm?lifecycle", data=conf)
    stats = s3srv.lifecycle.run_once(now=time.time() + 4 * 86400)
    assert stats["aborted_uploads"] == 1
    r = requests.get(f"{s3}/lcm/big?uploadId={upload_id}")
    assert r.status_code == 404


# ------------------------------------------------- streaming-chunked SigV4


ACCESS, SECRET = "AKIASTREAM", "streamsecret"


@pytest.fixture
def s3_signed(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    ids = IdentityStore()
    ids.add(Identity("streamer", ACCESS, SECRET, actions=("Admin",)))
    srv = S3Server(
        filer, ip="localhost", port=free_port(), identities=ids,
        lifecycle_interval=0,
    )
    srv.start()
    yield f"http://localhost:{srv.port}"
    srv.stop()
    filer.close()


def _hmac(key, msg):
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _skey(date):
    k = _hmac(("AWS4" + SECRET).encode(), date)
    k = _hmac(k, REGION)
    k = _hmac(k, "s3")
    return _hmac(k, "aws4_request")


def _streaming_put(url, path, payload, chunk_size=65536, corrupt=None):
    """Client-side implementation of the AWS streaming SigV4 protocol
    (independent of the server code under test)."""
    host = urllib.parse.urlparse(url).netloc
    now = datetime.now(timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    scope = f"{date}/{REGION}/s3/aws4_request"
    chunks = [
        payload[i : i + chunk_size] for i in range(0, len(payload), chunk_size)
    ] + [b""]
    framed_len = sum(
        len(f"{len(c):x};chunk-signature=" + "0" * 64 + "\r\n") + len(c) + 2
        for c in chunks
    )
    headers = {
        "Host": host,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
        "x-amz-decoded-content-length": str(len(payload)),
        "Content-Encoding": "aws-chunked",
        "Content-Length": str(framed_len),
    }
    signed = sorted(h.lower() for h in headers if h != "Content-Length")
    canon_headers = "".join(f"{h}:{headers[_hdr(h, headers)]}\n" for h in signed)
    creq = "\n".join(
        [
            "PUT",
            path,
            "",
            canon_headers,
            ";".join(signed),
            "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
        ]
    )
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(creq.encode()).hexdigest(),
        ]
    )
    skey = _skey(date)
    seed = hmac.new(skey, sts.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={ACCESS}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={seed}"
    )
    # frame chunks with the signature chain
    body = bytearray()
    prev = seed
    for i, c in enumerate(chunks):
        csts = "\n".join(
            [
                "AWS4-HMAC-SHA256-PAYLOAD",
                amz_date,
                scope,
                prev,
                hashlib.sha256(b"").hexdigest(),
                hashlib.sha256(c).hexdigest(),
            ]
        )
        sig = hmac.new(skey, csts.encode(), hashlib.sha256).hexdigest()
        data = c
        if corrupt is not None and i == corrupt and c:
            data = bytes([c[0] ^ 0xFF]) + c[1:]
        body += f"{len(c):x};chunk-signature={sig}\r\n".encode()
        body += data + b"\r\n"
        prev = sig
    return requests.put(url + path, data=bytes(body), headers=headers)


def _hdr(lower, headers):
    for k in headers:
        if k.lower() == lower:
            return k
    raise KeyError(lower)


def test_streaming_sigv4_roundtrip(s3_signed):
    payload = bytes(range(256)) * 1024  # 256 KiB, multiple chunks
    # create the bucket with a signed plain request via streaming helper
    r = _streaming_put(s3_signed, "/chunked", b"")
    assert r.status_code in (200, 409)
    r = _streaming_put(s3_signed, "/chunked/obj", payload, chunk_size=65536)
    assert r.status_code == 200, r.text
    # read back via presign-free path is denied; use another streaming GET?
    # the store is authoritative: fetch with a signed zero-byte helper's
    # sibling — instead verify via a fresh streaming PUT + size check on
    # a signed HEAD is overkill; simplest: anonymous read is rejected
    assert requests.get(f"{s3_signed}/chunked/obj").status_code == 403


def test_streaming_sigv4_tampered_chunk_rejected(s3_signed):
    _streaming_put(s3_signed, "/chunked2", b"")
    payload = b"z" * 100_000
    r = _streaming_put(
        s3_signed, "/chunked2/obj", payload, chunk_size=65536, corrupt=1
    )
    assert r.status_code == 403
    assert "SignatureDoesNotMatch" in r.text


def test_streaming_sigv4_roundtrip_content(cluster):
    """Open-mode server: streaming body stored equals the decoded payload."""
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    srv = S3Server(filer, ip="localhost", port=free_port(), lifecycle_interval=0)
    srv.start()
    url = f"http://localhost:{srv.port}"
    try:
        requests.put(f"{url}/cb")
        payload = b"q" * 150_000
        # unsigned streaming (STREAMING-UNSIGNED-PAYLOAD-TRAILER)
        chunks = [payload[:65536], payload[65536:131072], payload[131072:], b""]
        body = b"".join(
            f"{len(c):x}\r\n".encode() + c + b"\r\n" for c in chunks
        )
        r = requests.put(
            f"{url}/cb/obj",
            data=body,
            headers={
                "x-amz-content-sha256": "STREAMING-UNSIGNED-PAYLOAD-TRAILER",
                "Content-Encoding": "aws-chunked",
                "x-amz-decoded-content-length": str(len(payload)),
            },
        )
        assert r.status_code == 200
        assert requests.get(f"{url}/cb/obj").content == payload
        # open mode: a signed-streaming header with no auth context must
        # still decode (framing stripped, chain unverifiable)
        body2 = b"".join(
            f"{len(c):x};chunk-signature={'0' * 64}\r\n".encode() + c + b"\r\n"
            for c in [payload[:65536], payload[65536:], b""]
        )
        r = requests.put(
            f"{url}/cb/obj2",
            data=body2,
            headers={
                "x-amz-content-sha256": "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
                "Content-Encoding": "aws-chunked",
                "x-amz-decoded-content-length": str(len(payload)),
            },
        )
        assert r.status_code == 200
        assert requests.get(f"{url}/cb/obj2").content == payload
    finally:
        srv.stop()
        filer.close()
