"""Cross-cutting subsystems: TOML config + scaffold, telemetry,
image resizing, request-id tracing, pprof endpoints.

References: weed/util/config.go (viper search path), weed/command/
scaffold/, weed/telemetry/collector.go, weed/images/resizing.go,
weed/util/request_id, weed/util/grace/pprof.go.
"""

import io
import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from conftest import allocate_port

# --------------------------------------------------------------- config


def test_config_search_order_and_dotted_get(tmp_path):
    from seaweedfs_tpu.utils.config import load_config

    d1, d2 = tmp_path / "a", tmp_path / "b"
    d1.mkdir(), d2.mkdir()
    (d2 / "security.toml").write_text('[jwt.signing]\nkey = "from-b"\n')
    cfg = load_config("security", dirs=(str(d1), str(d2)))
    assert cfg.get_str("jwt.signing.key") == "from-b"
    assert cfg.get("jwt.signing.expires_after_seconds", 10) == 10
    # first hit wins
    (d1 / "security.toml").write_text('[jwt.signing]\nkey = "from-a"\n')
    assert (
        load_config("security", dirs=(str(d1), str(d2))).get_str(
            "jwt.signing.key"
        )
        == "from-a"
    )
    # malformed file -> empty config, not a crash
    (d1 / "security.toml").write_text("[[[ not toml")
    assert not load_config("security", dirs=(str(d1),))


def test_scaffold_emits_parseable_toml(tmp_path):
    # toml_loads is tomllib on >=3.11 and the gated fallback parser on
    # 3.10 containers (where a bare `import tomllib` used to crash
    # every spawned server at import time)
    from seaweedfs_tpu.server.__main__ import main
    from seaweedfs_tpu.utils.config import toml_load, toml_loads
    from seaweedfs_tpu.utils.scaffold import TEMPLATES, scaffold

    for name in TEMPLATES:
        toml_loads(scaffold(name))  # every template must parse
    rc = main(["scaffold", "-config", "security", "-output", str(tmp_path)])
    assert rc == 0
    data = toml_load(open(tmp_path / "security.toml", "rb"))
    assert "jwt" in data
    with pytest.raises(KeyError):
        scaffold("nonsense")


# ------------------------------------------------------------ telemetry


def test_telemetry_posts_only_from_leader():
    from seaweedfs_tpu.utils.telemetry import TelemetryCollector

    got = []

    class Sink(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            got.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    port = allocate_port()
    httpd = HTTPServer(("127.0.0.1", port), Sink)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{port}/collect"
        leader = [False]
        tc = TelemetryCollector(
            url,
            lambda: {"volume_count": 3},
            is_leader_fn=lambda: leader[0],
        )
        assert not tc.send_once()  # follower stays silent
        assert got == []
        leader[0] = True
        assert tc.send_once()
        assert got[0]["volume_count"] == 3
        assert got[0]["cluster_id"] == tc.cluster_id
        assert "/" in got[0]["os"]
    finally:
        httpd.shutdown()
        httpd.server_close()


# --------------------------------------------------------------- images


def _png(w: int, h: int) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", (w, h), (200, 10, 10)).save(buf, "PNG")
    return buf.getvalue()


def test_image_resize_modes():
    from PIL import Image

    from seaweedfs_tpu.utils.images import detect_format, resized

    src = _png(100, 50)
    assert detect_format(src) == "PNG"
    out, w, h = resized(src, 50, 50)
    assert (w, h) == (50, 25)  # aspect preserved
    assert Image.open(io.BytesIO(out)).size == (50, 25)
    out, w, h = resized(src, 40, 40, mode="fill")
    assert Image.open(io.BytesIO(out)).size == (40, 40)  # exact crop
    # default mode never upscales; fit does
    out, w, h = resized(src, 400, 400)
    assert Image.open(io.BytesIO(out)).size == (100, 50)
    out, w, h = resized(src, 400, 400, mode="fit")
    assert Image.open(io.BytesIO(out)).size == (400, 200)
    # non-image bytes pass through untouched
    blob = b"definitely not an image"
    assert resized(blob, 10, 10)[0] == blob


def test_volume_server_serves_thumbnails(spawned_cluster=None):
    import requests

    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from PIL import Image
    import tempfile

    mport, vport = allocate_port(), allocate_port()
    with tempfile.TemporaryDirectory() as td:
        ms = MasterServer(ip="127.0.0.1", port=mport)
        ms.start()
        vs = VolumeServer(
            directories=[td], master=f"127.0.0.1:{mport}",
            ip="127.0.0.1", port=vport,
        )
        vs.start()
        try:
            ops = Operations(master=f"127.0.0.1:{mport}")
            fid = ops.upload(_png(80, 40), name="pic.png")
            url = ops.master.lookup(int(fid.split(",")[0]))[0].url
            r = requests.get(f"http://{url}/{fid}?width=20", timeout=10)
            assert r.status_code == 200
            assert Image.open(io.BytesIO(r.content)).size == (20, 10)
        finally:
            vs.stop()
            ms.stop()


# ------------------------------------------------- request-id + pprof


def test_request_id_and_pprof_on_master():
    from seaweedfs_tpu.server.master import MasterServer

    port = allocate_port()
    ms = MasterServer(ip="127.0.0.1", port=port)
    ms.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/dir/status",
            headers={"X-Request-ID": "trace-me-123"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers.get("X-Request-ID") == "trace-me-123"
        # absent: server mints one
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/dir/status", timeout=10
        ) as r:
            assert len(r.headers.get("X-Request-ID", "")) >= 8
        # pprof: thread dump names this very request-handler thread
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/goroutine", timeout=10
        ) as r:
            dump = r.read().decode()
        assert "thread" in dump and "do_GET" in dump
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/pprof/profile?seconds=0.3",
            timeout=10,
        ) as r:
            prof = r.read().decode()
        assert prof == "" or " " in prof.splitlines()[0]
    finally:
        ms.stop()


def test_request_id_propagates_client_to_volume(tmp_path):
    """One id across client → volume upload hop."""
    import requests

    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.utils import request_id

    mport, vport = allocate_port(), allocate_port()
    ms = MasterServer(ip="127.0.0.1", port=mport)
    ms.start()
    vs = VolumeServer(
        directories=[str(tmp_path)], master=f"127.0.0.1:{mport}",
        ip="127.0.0.1", port=vport,
    )
    vs.start()
    try:
        rid = request_id.ensure("e2e-0123456789ab")
        ops = Operations(master=f"127.0.0.1:{mport}")
        fid = ops.upload(b"traced payload", name="t.txt")
        url = ops.master.lookup(int(fid.split(",")[0]))[0].url
        r = requests.get(
            f"http://{url}/{fid}", headers={"X-Request-ID": rid}, timeout=10
        )
        assert r.headers.get("X-Request-ID") == rid
        assert r.content == b"traced payload"
    finally:
        request_id.clear()
        vs.stop()
        ms.stop()


def test_telemetry_server_roundtrip(tmp_path):
    """Collector client -> collector server: ingestion, summary,
    Prometheus gauges, JSONL persistence across restart."""
    import json

    import requests

    from seaweedfs_tpu.utils.telemetry import TelemetryCollector
    from seaweedfs_tpu.utils.telemetry_server import TelemetryServer

    persist = str(tmp_path / "telemetry.jsonl")
    srv = TelemetryServer(ip="localhost", port=0, persist_path=persist)
    srv.start()
    try:
        url = f"http://localhost:{srv.port}/api/collect"
        col = TelemetryCollector(
            url,
            stats_fn=lambda: {"volume_count": 7, "server_count": 2},
        )
        assert col.send_once()
        col2 = TelemetryCollector(
            url, stats_fn=lambda: {"volume_count": 3, "server_count": 1}
        )
        assert col2.send_once()
        # re-report from the same cluster replaces, not duplicates
        assert col.send_once()

        stats = requests.get(
            f"http://localhost:{srv.port}/api/stats"
        ).json()
        assert stats["clusters"] == 2
        assert stats["total_volume_count"] == 10
        assert stats["total_server_count"] == 3

        metrics = requests.get(f"http://localhost:{srv.port}/metrics").text
        assert "seaweed_telemetry_clusters 2" in metrics
        assert "seaweed_telemetry_total_volume_count 10" in metrics
        assert f'cluster="{col.cluster_id}"' in metrics

        # malformed report -> 400, not a dropped connection
        r = requests.post(url, data=b"[1,2,3]")
        assert r.status_code == 400
    finally:
        srv.stop()

    # restart from the JSONL: state survives
    srv2 = TelemetryServer(ip="localhost", port=0, persist_path=persist)
    srv2.start()
    try:
        stats = requests.get(
            f"http://localhost:{srv2.port}/api/stats"
        ).json()
        assert stats["clusters"] == 2
        assert stats["total_volume_count"] == 10
    finally:
        srv2.stop()
