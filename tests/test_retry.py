"""Unified retry policy + circuit breaker (utils/retry.py): the one
backoff implementation every hand-rolled loop migrated onto."""

from __future__ import annotations

import random

import grpc
import pytest

from seaweedfs_tpu.utils.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryError,
    RetryPolicy,
    retry_call,
)


class Flaky:
    def __init__(self, fail_times: int, exc: Exception | None = None):
        self.calls = 0
        self.fail_times = fail_times
        self.exc = exc or ValueError("boom")

    def __call__(self):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise self.exc
        return "ok"


def test_policy_rejects_zero_attempts():
    with pytest.raises(ValueError, match="max_attempts"):
        RetryPolicy(max_attempts=0)


def test_policy_delay_schedule_no_jitter():
    p = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.5, jitter=0.0)
    assert [p.delay(a) for a in (1, 2, 3, 4, 5)] == [0.1, 0.2, 0.4, 0.5, 0.5]


def test_policy_jitter_bounded_and_seeded():
    p = RetryPolicy(base_delay=1.0, multiplier=1.0, jitter=0.25)
    rng = random.Random(42)
    ds = [p.delay(1, rng) for _ in range(100)]
    assert all(0.75 <= d <= 1.25 for d in ds)
    assert [p.delay(1, random.Random(7)) for _ in range(5)] == [
        p.delay(1, random.Random(7)) for _ in range(5)
    ]


def test_retry_succeeds_after_transient_failures():
    sleeps: list[float] = []
    fn = Flaky(2)
    out = retry_call(
        fn, RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.0),
        sleep=sleeps.append,
    )
    assert out == "ok" and fn.calls == 3
    assert sleeps == [0.1, 0.2]


def test_retry_exhaustion_wraps_cause():
    fn = Flaky(10)
    with pytest.raises(RetryError) as ei:
        retry_call(
            fn, RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0),
            sleep=lambda d: None,
        )
    assert ei.value.attempts == 3 and fn.calls == 3
    assert isinstance(ei.value.__cause__, ValueError)


def test_non_retryable_propagates_immediately():
    fn = Flaky(5, exc=KeyError("nope"))
    with pytest.raises(KeyError):
        retry_call(
            fn, RetryPolicy(max_attempts=5, retry_on=(ValueError,)),
            sleep=lambda d: None,
        )
    assert fn.calls == 1


def test_on_retry_hook_runs_between_attempts():
    seen: list[tuple[str, int]] = []
    fn = Flaky(2)
    retry_call(
        fn, RetryPolicy(max_attempts=4, base_delay=0.0, jitter=0.0),
        on_retry=lambda e, a: seen.append((type(e).__name__, a)),
        sleep=lambda d: None,
    )
    assert seen == [("ValueError", 1), ("ValueError", 2)]


def test_deadline_cuts_retries_short():
    t = [0.0]

    def clock():
        return t[0]

    def sleep(d):
        t[0] += d

    fn = Flaky(100)
    with pytest.raises(RetryError) as ei:
        retry_call(
            fn,
            RetryPolicy(
                max_attempts=100, base_delay=1.0, multiplier=1.0, jitter=0.0,
                deadline=2.5,
            ),
            sleep=sleep, clock=clock,
        )
    # attempts at t=0,1,2, then the backoff is CLAMPED to land a final
    # attempt exactly at the 2.5s deadline — the full budget is used
    assert fn.calls == 4
    assert ei.value.elapsed == pytest.approx(2.5)


def test_deadline_final_attempt_can_win():
    """A resource freed just before the deadline is still acquired."""
    t = [0.0]

    def clock():
        return t[0]

    def sleep(d):
        t[0] += d

    def fn():
        if t[0] < 1.9:
            raise ValueError("held")
        return "acquired"

    out = retry_call(
        fn,
        RetryPolicy(max_attempts=50, base_delay=1.0, multiplier=1.0,
                    jitter=0.0, deadline=2.0),
        sleep=sleep, clock=clock,
    )
    assert out == "acquired" and t[0] == pytest.approx(2.0)


def test_breaker_opens_after_threshold_and_half_open_probe():
    t = [0.0]
    b = CircuitBreaker(failure_threshold=3, reset_timeout=10.0, clock=lambda: t[0])
    assert b.state == "closed" and b.allows()
    for _ in range(3):
        b.record_failure()
    assert b.state == "open" and not b.allows()
    t[0] += 10.0
    assert b.state == "half-open"
    assert b.allows()  # the single probe
    assert not b.allows()  # second caller rejected during the probe
    b.record_success()
    assert b.state == "closed" and b.allows()


def test_breaker_probe_failure_reopens():
    t = [0.0]
    b = CircuitBreaker(failure_threshold=1, reset_timeout=5.0, clock=lambda: t[0])
    b.record_failure()
    assert b.state == "open"
    t[0] += 5.0
    assert b.allows()
    b.record_failure()  # probe failed
    assert b.state == "open" and not b.allows()
    t[0] += 4.9
    assert not b.allows()


def test_breaker_abandoned_probe_does_not_wedge():
    """A caller that took the half-open probe slot and died (never
    recorded an outcome) must not lock the breaker half-open forever."""
    t = [0.0]
    b = CircuitBreaker(failure_threshold=1, reset_timeout=5.0, clock=lambda: t[0])
    b.record_failure()
    t[0] += 5.0
    assert b.allows()  # probe taken...
    # ...and abandoned: no record_success/record_failure ever runs
    assert not b.allows()
    t[0] += 5.0  # a further reset window reopens the probe slot
    assert b.allows()
    b.record_success()
    assert b.state == "closed"


def test_breaker_call_wrapper():
    b = CircuitBreaker(failure_threshold=1, reset_timeout=999.0)
    with pytest.raises(ValueError):
        b.call(Flaky(5))
    with pytest.raises(CircuitOpenError):
        b.call(lambda: "never runs")


def test_master_client_with_leader_rides_unified_policy(monkeypatch):
    """_with_leader migrated onto retry_call: NotLeaderError triggers the
    hint-following recovery, transport errors re-resolve, and the caller
    still sees the underlying error class on exhaustion."""
    from seaweedfs_tpu.client.master_client import MasterClient, NotLeaderError

    mc = MasterClient("localhost:1", keepconnected=False)
    monkeypatch.setattr(
        "seaweedfs_tpu.utils.retry.time.sleep", lambda d: None
    )
    hints: list[str] = []
    monkeypatch.setattr(mc, "_note_leader_hint", lambda e: hints.append(e))
    monkeypatch.setattr(mc, "_resolve_leader", lambda skip=None: "localhost:1")
    monkeypatch.setattr(mc, "_leader_stub", lambda: object())

    calls = [0]

    def flaky(stub):
        calls[0] += 1
        if calls[0] < 3:
            raise NotLeaderError("not leader; leader=localhost:2")
        return "answer"

    assert mc._with_leader(flaky) == "answer"
    assert calls[0] == 3 and len(hints) == 2

    def always_not_leader(stub):
        raise NotLeaderError("not leader")

    with pytest.raises(NotLeaderError):  # not RetryError: class preserved
        mc._with_leader(always_not_leader)
    mc.close()


def test_master_client_lock_wait_deadline(monkeypatch):
    """lock(wait=...) polls a held lock under the policy and raises
    LockHeldError (not RetryError) at the deadline."""
    from seaweedfs_tpu.client.master_client import LockHeldError, MasterClient

    mc = MasterClient("localhost:1", keepconnected=False)

    class Resp:
        ok = False
        holder = "someone"
        error = ""
        token = ""

    monkeypatch.setattr(mc, "_with_leader", lambda call: Resp())
    monkeypatch.setattr(
        "seaweedfs_tpu.utils.retry.time.sleep", lambda d: None
    )
    with pytest.raises(LockHeldError):
        mc.lock("job", owner="me", wait=0.3)
    with pytest.raises(LockHeldError):
        mc.lock("job", owner="me", wait=0.0)  # immediate, no polling
    mc.close()
