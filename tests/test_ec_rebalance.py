"""Data-gravity tests (ISSUE 15): hot-volume rebalance planning,
stale-telemetry aging, gravity-vs-spread invariants, and whole-shard-set
migration over REAL gRPC — including the crash-rerun windows (kill
between copy/mount/unmount -> re-run converges to exactly one mounted
holder, bit-identical bytes).
"""

from __future__ import annotations

import os
import random
import time

import grpc
import numpy as np
import pytest
import requests

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec.placement import (
    NodeView,
    node_view_for,
    plan_ec_balance,
    plan_shard_placement,
)
from seaweedfs_tpu.ec.rebalance import (
    drive_migration,
    plan_hot_migrations,
    volume_heat,
)
from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from conftest import allocate_port as free_port
from conftest import wait_for

TOTAL = 14
KEEP_LOCAL = [0, 1, 2, 3]
MOVED = list(range(4, TOTAL))


def _tele(chips=0, load=0.0, breakers=0, vols=None, ts=None):
    blob = {
        "chips": {
            f"tpu:{i}": {"load": load / max(chips, 1), "breaker": "closed"}
            for i in range(chips)
        },
        "breakers_open": breakers,
        "ts": ts if ts is not None else time.time(),
        "received_at": ts if ts is not None else time.time(),
    }
    if vols:
        blob["ec_volumes"] = {
            str(v): {"read_bytes": rb, "reconstructed_bytes": xb}
            for v, (rb, xb) in vols.items()
        }
    return blob


# ------------------------------------------------------------- planner


def test_plan_hot_migrations_targets_chip_rich_node():
    views = [
        NodeView(id="poor", rack="r1", free_slots=50, ec_chips=0,
                 shards={7: set(range(5))}),
        NodeView(id="rich", rack="r1", free_slots=50, ec_chips=8,
                 ec_load=0.0),
    ]
    heat = {"poor": {7: 50 << 20}}
    plans = plan_hot_migrations(views, heat, min_heat=1 << 20)
    assert len(plans) == 1
    m = plans[0]
    assert (m.vid, m.src, m.dst) == (7, "poor", "rich")
    assert m.shard_ids == (0, 1, 2, 3, 4)
    assert m.dst_gravity > m.src_gravity


def test_plan_hot_migrations_deterministic_under_seeded_skew():
    """Same skewed snapshot in -> byte-identical plan out, every time."""
    rng = random.Random(0x5EED)
    def build():
        views, heat = [], {}
        for i in range(8):
            nid = f"n{i}"
            views.append(
                NodeView(
                    id=nid, rack=f"r{i % 3}", free_slots=40,
                    ec_chips=rng.choice([0, 0, 2, 4, 8]),
                    ec_load=rng.random() * 1e8,
                    shards={
                        v: set(range(rng.randint(1, 4)))
                        for v in rng.sample(range(20), 3)
                    },
                )
            )
            heat[nid] = {
                v: rng.randint(0, 200) << 20 for v in range(20)
            }
        return views, heat

    rng = random.Random(0x5EED)
    v1, h1 = build()
    rng = random.Random(0x5EED)
    v2, h2 = build()
    p1 = plan_hot_migrations(v1, h1, min_heat=1 << 20, max_migrations=4)
    p2 = plan_hot_migrations(v2, h2, min_heat=1 << 20, max_migrations=4)
    assert p1 == p2
    assert p1, "seeded skew must produce at least one migration"
    for m in p1:
        src = next(v for v in v1 if v.id == m.src)
        dst = next(v for v in v1 if v.id == m.dst)
        assert dst.gravity_score() > src.gravity_score()
        assert not dst.shards.get(m.vid), "dest already held the volume"


def test_plan_hot_migrations_respects_capacity_and_rack_ceiling():
    # dest rack already at the ceiling for vid 3: 2 racks, 4 shards ->
    # ceil(4/2)=2 per rack; moving 2 more into r2 would breach it
    views = [
        NodeView(id="src", rack="r1", free_slots=50, ec_chips=0,
                 shards={3: {0, 1}}),
        NodeView(id="richfull", rack="r2", free_slots=50, ec_chips=8,
                 shards={}),
        NodeView(id="r2holder", rack="r2", free_slots=50,
                 shards={3: {2, 3}}),
    ]
    heat = {"src": {3: 100 << 20}}
    plans = plan_hot_migrations(views, heat, min_heat=1)
    assert plans == [], "rack ceiling must veto the only candidate"
    # byte headroom gate: known-too-small destination is never chosen
    views = [
        NodeView(id="src", rack="r1", free_slots=50, ec_chips=0,
                 shards={3: {0, 1}}),
        NodeView(id="tiny", rack="r1", free_slots=50, ec_chips=8,
                 free_bytes=10),
    ]
    plans = plan_hot_migrations(
        views, {"src": {3: 100 << 20}}, shard_bytes={3: 1 << 20},
        min_heat=1,
    )
    assert plans == []


def test_gravity_balance_never_breaks_spread_or_capacity():
    """Property, seeded: plan_ec_balance(data_gravity=True) may add
    gravity moves, but the post-state never violates the across-rack
    ceiling, per-node free slots, or worsen the per-volume per-node
    maximum."""
    rng = random.Random(0xDA7A)
    for trial in range(20):
        views = []
        for i in range(6):
            views.append(
                NodeView(
                    id=f"n{i}", rack=f"r{i % 3}",
                    free_slots=rng.randint(0, 30),
                    ec_chips=rng.choice([0, 0, 4, 8]),
                    ec_load=rng.random() * 1e8,
                    shards={
                        v: set(rng.sample(range(14), rng.randint(1, 6)))
                        for v in rng.sample(range(8), rng.randint(1, 3))
                    },
                )
            )
        drops, moves = plan_ec_balance(views, data_gravity=True)
        # capacity: no node overdrawn
        for n in views:
            assert n.free_slots >= 0, f"trial {trial}: {n.id} overdrawn"
        # across-rack ceiling per volume
        racks = {}
        for n in views:
            racks.setdefault(n.rack_key(), []).append(n)
        vids = {v for n in views for v in n.shards}
        for vid in vids:
            total = sum(len(n.shards.get(vid, ())) for n in views)
            if total == 0 or len(racks) < 2:
                continue
            ceiling = -(-total // len(racks))
            for rk, members in racks.items():
                got = sum(len(n.shards.get(vid, ())) for n in members)
                assert got <= ceiling, (
                    f"trial {trial}: vid {vid} rack {rk} {got} > "
                    f"{ceiling} after gravity balance"
                )
        # gravity moves flow toward strictly better gravity
        for m in moves:
            if m.reason != "gravity":
                continue
            src = next(v for v in views if v.id == m.src)
            dst = next(v for v in views if v.id == m.dst)
            from seaweedfs_tpu.ec.placement import gravity_key

            assert gravity_key(dst) < gravity_key(src)


# --------------------------------------------------- telemetry aging


def test_stale_telemetry_stops_steering_but_keeps_age():
    fresh = _tele(chips=8, load=5.0, ts=time.time())
    stale = _tele(chips=8, load=5.0, ts=time.time() - 3600)
    v_fresh = node_view_for("a", "r", "dc", 8, 0, [], ec_telemetry=fresh)
    v_stale = node_view_for("b", "r", "dc", 8, 0, [], ec_telemetry=stale)
    assert v_fresh.ec_chips == 8 and v_fresh.ec_load > 0
    assert v_stale.ec_chips == 0 and v_stale.ec_load == -1.0
    assert v_stale.telemetry_age_s > 3000
    assert v_stale.gravity_score() == 0.0
    # a dead node's idle chips must not attract placement: both nodes
    # static-tie, so the STALE one no longer wins on its ghost chips
    plan = plan_shard_placement([v_stale, v_fresh], 5, [0])
    assert plan == {0: "b"} or plan == {0: "a"}
    # explicit knob: widen the window and the same blob steers again
    v_ok = node_view_for(
        "c", "r", "dc", 8, 0, [], ec_telemetry=stale, stale_after=7200.0
    )
    assert v_ok.ec_chips == 8


def test_volume_heat_parses_and_weighs_reconstruction():
    t = _tele(vols={7: (100, 50), 9: (10, 0)})
    heat = volume_heat(t)
    assert heat == {7: 200, 9: 10}  # read + 2x reconstructed
    assert volume_heat(None) == {}
    assert volume_heat({"ec_volumes": "garbage"}) == {}


# ------------------------------------------------ scanner (unit level)


def test_scan_for_ec_rebalance_dispatches_on_heat_delta():
    from seaweedfs_tpu.server.topology import DataNode, Topology
    from seaweedfs_tpu.worker.control import WorkerControl, _Worker
    from seaweedfs_tpu.worker.worker import Worker

    topo = Topology()
    wc = WorkerControl(topo=topo)
    try:
        # a connected worker declaring the ec_migrate descriptor (param
        # validation needs it)
        w = _Worker(
            worker_id="w1",
            capabilities={"ec_migrate"},
            max_concurrent=1,
            backend="cpu",
            descriptors={
                d.kind: d
                for d in Worker().descriptors
                if d.kind == "ec_migrate"
            },
        )
        with wc._lock:
            wc._workers["w1"] = w

        def node(nid, port, chips, vols):
            n = DataNode(
                node_id=nid, ip="h", port=port, public_url=nid,
                grpc_port=port + 10000, rack="r1",
            )
            n.ec_shards = {
                vid: pb.EcShardInfoMsg(
                    id=vid, shard_bits=bits, shard_size=1 << 20,
                    data_shards=10, parity_shards=4,
                )
                for vid, bits in vols.items()
            }
            n.ec_telemetry = _tele(
                chips=chips,
                vols={vid: (0, 0) for vid in vols},
            )
            return n

        a = node("h:1", 1, 0, {7: 0b11111})  # chip-poor holder of vid 7
        b = node("h:2", 2, 8, {})            # chip-rich idle
        topo.nodes = {a.node_id: a, b.node_id: b}
        # sweep 1: first sighting -> baseline only, nothing dispatched
        assert wc.scan_for_ec_rebalance(topo, min_heat=1 << 20) == []
        # heat arrives: 64 MiB of reads on vid 7 at the poor holder
        a.ec_telemetry = _tele(chips=0, vols={7: (64 << 20, 0)})
        tids = wc.scan_for_ec_rebalance(topo, min_heat=1 << 20)
        assert len(tids) == 1
        _, tasks = wc.snapshot()
        t = next(t for t in tasks if t["task_id"] == tids[0])
        assert t["kind"] == "ec_migrate" and t["volume_id"] == 7
        with wc._lock:
            params = wc._tasks[tids[0]].params
        assert params["source"] == "h:10001"
        assert params["target"] == "h:10002"
        assert params["shards"] == "0,1,2,3,4"
        assert wc.last_migrations[0]["volume_id"] == 7
        # same counters again -> zero delta -> nothing new
        assert wc.scan_for_ec_rebalance(topo, min_heat=1 << 20) == []
    finally:
        wc.stop()


# ------------------------------------------- migration over real gRPC


class Cluster:
    def __init__(self, tmp_path, n=2):
        self.mport = free_port()
        self.master = MasterServer(ip="localhost", port=self.mport)
        self.master.start()
        self.vols = [
            VolumeServer(
                directories=[str(tmp_path / f"v{i}")],
                master=f"localhost:{self.mport}",
                ip="localhost",
                port=free_port(),
                ec_backend="cpu",
            )
            for i in range(n)
        ]
        for vs in self.vols:
            vs.start()
        wait_for(
            lambda: len(self.master.topo.nodes) >= n,
            msg="volume servers did not register",
        )
        self._channels = []

    def stub_addr(self, addr):
        ch = grpc.insecure_channel(addr)
        self._channels.append(ch)
        return rpc.volume_stub(ch)

    def stub(self, vs):
        return self.stub_addr(f"localhost:{vs.grpc_port}")

    def locs(self, vid):
        return {
            sid: [l.url for l in locs]
            for sid, locs in self.master.topo.lookup_ec(vid).items()
        }

    def grpc_locs(self, vid):
        return {
            sid: [
                f"{l.url.split(':')[0]}:{l.grpc_port}" for l in locs
            ]
            for sid, locs in self.master.topo.lookup_ec(vid).items()
        }

    def stop(self):
        for ch in self._channels:
            ch.close()
        for vs in self.vols:
            vs.stop()
        self.master.stop()


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(tmp_path)
    yield c
    c.stop()


def split_ec_volume(c: Cluster):
    a = requests.get(f"http://localhost:{c.mport}/dir/assign").json()
    fid = a["fid"]
    vid = int(fid.split(",")[0])
    payload = np.random.default_rng(0x9A7E).integers(
        0, 256, 100_000, dtype=np.uint8
    ).tobytes()
    r = requests.post(
        f"http://{a['url']}/{fid}", files={"file": ("x.bin", payload)}
    )
    assert r.status_code == 201, r.text
    holder = next(v for v in c.vols if a["url"] == f"localhost:{v.port}")
    other = next(v for v in c.vols if v is not holder)
    st_h, st_o = c.stub(holder), c.stub(other)
    st_h.VolumeEcShardsGenerate(
        pb.EcShardsGenerateRequest(volume_id=vid, backend="cpu"), timeout=120
    )
    st_h.VolumeEcShardsMount(
        pb.EcShardsMountRequest(volume_id=vid), timeout=30
    )
    st_h.VolumeDelete(pb.VolumeCommandRequest(volume_id=vid), timeout=30)
    base = holder.service._ec_base(vid, "")
    ground = {
        i: open(base + f".ec{i:02d}", "rb").read() for i in range(TOTAL)
    }
    st_o.VolumeEcShardsCopy(
        pb.EcShardsCopyRequest(
            volume_id=vid,
            shard_ids=MOVED,
            source_url=f"localhost:{holder.grpc_port}",
            copy_ecx=True, copy_ecj=True, copy_vif=True, copy_ecsum=True,
        ),
        timeout=120,
    )
    st_o.VolumeEcShardsMount(
        pb.EcShardsMountRequest(volume_id=vid), timeout=30
    )
    st_h.VolumeEcShardsUnmount(
        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=MOVED), timeout=30
    )
    st_h.VolumeEcShardsDelete(
        pb.EcShardsDeleteRequest(volume_id=vid, shard_ids=MOVED), timeout=30
    )
    wait_for(
        lambda: len(c.locs(vid)) == TOTAL
        and all(len(v) == 1 for v in c.locs(vid).values()),
        msg="shard split did not reach the master",
    )
    return vid, fid, payload, holder, other, ground


def _migrate(c, vid, src_vs, dst_vs, sids):
    src_addr = f"localhost:{src_vs.grpc_port}"
    dst_addr = f"localhost:{dst_vs.grpc_port}"
    return drive_migration(
        vid, "", src_addr, dst_addr, sids,
        stub_for=c.stub_addr,
        lookup_ec=lambda: c.grpc_locs(vid),
    )


def _one_mounted_holder(c, vid, sids, dst_vs):
    """Every sid in `sids` is advertised by exactly the destination."""
    locs = c.locs(vid)
    want = [f"localhost:{dst_vs.port}"]
    return all(locs.get(s) == want for s in sids)


def _mount_counts(c, vid, sids):
    """GROUND-TRUTH mounts per sid, read from the stores themselves
    (the master map lags mounts/unmounts by a heartbeat)."""
    counts = {s: 0 for s in sids}
    for vs in c.vols:
        ev = vs.store.find_ec_volume(vid)
        if ev is None:
            continue
        for s in sids:
            if s in ev.shard_fds:
                counts[s] += 1
    return counts


def test_migration_moves_shard_set_bit_identical(cluster):
    from seaweedfs_tpu.ec import native_io
    from seaweedfs_tpu.utils import metrics as M

    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    hbase = holder.service._ec_base(vid, "")
    rec0 = M.net_bytes_received_total.snapshot()
    out = _migrate(cluster, vid, holder, other, KEEP_LOCAL)
    assert out["migrated"] == KEEP_LOCAL
    wait_for(
        lambda: _one_mounted_holder(cluster, vid, KEEP_LOCAL, other),
        msg="migration did not converge to the destination",
    )
    obase = other.service._ec_base(vid, "")
    for s in KEEP_LOCAL:
        assert open(obase + f".ec{s:02d}", "rb").read() == ground[s]
    for s in KEEP_LOCAL:
        assert not os.path.exists(hbase + f".ec{s:02d}"), "source kept files"
    # the object still reads back (now served by `other` alone)
    got = requests.get(f"http://localhost:{other.port}/{fid}").content
    assert got == payload
    if native_io.enabled():
        rec1 = M.net_bytes_received_total.snapshot()
        moved = sum(len(ground[s]) for s in KEEP_LOCAL)
        native_delta = rec1.get(("native", "read"), 0) - rec0.get(
            ("native", "read"), 0
        )
        assert native_delta >= moved, (
            "migration bytes did not ride the native plane"
        )


@pytest.mark.parametrize(
    "window",
    ["ec.migrate.after_copy", "ec.migrate.after_unmount",
     "ec.migrate.after_mount"],
)
def test_migration_crash_rerun_exactly_one_holder(cluster, window):
    """Kill the driver in each crash window; re-run converges to
    EXACTLY ONE mounted holder with bit-identical bytes, and at no
    point were two holders mounted for a migrated shard."""
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    hbase = holder.service._ec_base(vid, "")
    with faults.injected(window, faults.crash(), when=faults.nth_call(1)) as h:
        with pytest.raises(faults.InjectedCrash):
            _migrate(cluster, vid, holder, other, KEEP_LOCAL)
    assert h.fired == 1
    # never two mounted holders, even inside the crash window —
    # GROUND TRUTH from the stores (the master map lags by a heartbeat)
    for s, n in _mount_counts(cluster, vid, KEEP_LOCAL).items():
        assert n <= 1, f"shard {s} mounted on {n} holders in {window}"
    # re-run: idempotent convergence
    out = _migrate(cluster, vid, holder, other, KEEP_LOCAL)
    assert out["migrated"] == KEEP_LOCAL
    for s, n in _mount_counts(cluster, vid, KEEP_LOCAL).items():
        assert n == 1, f"shard {s} mounted on {n} holders after re-run"
    wait_for(
        lambda: _one_mounted_holder(cluster, vid, KEEP_LOCAL, other),
        msg=f"re-run after {window} did not converge",
    )
    obase = other.service._ec_base(vid, "")
    for s in KEEP_LOCAL:
        assert open(obase + f".ec{s:02d}", "rb").read() == ground[s]
    for s in KEEP_LOCAL:
        assert not os.path.exists(hbase + f".ec{s:02d}")
    got = requests.get(f"http://localhost:{other.port}/{fid}").content
    assert got == payload


def test_copy_refuses_corrupt_source_shard(cluster):
    """The migration copy path verifies landed shards against the
    .ecsum sidecar: a rotten source byte -> DATA_LOSS, nothing kept."""
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    hbase = holder.service._ec_base(vid, "")
    with open(hbase + ".ec01", "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(grpc.RpcError) as ei:
        cluster.stub(other).VolumeEcShardsCopy(
            pb.EcShardsCopyRequest(
                volume_id=vid,
                shard_ids=[1],
                source_url=f"localhost:{holder.grpc_port}",
            ),
            timeout=120,
        )
    assert ei.value.code() == grpc.StatusCode.DATA_LOSS
    obase = other.service._ec_base(vid, "")
    assert not os.path.exists(obase + ".ec01"), "rotten copy kept on disk"
