"""Volume tail / incremental replica catch-up.

Reference: weed/server/volume_grpc_tail.go (VolumeTailSender/Receiver),
weed/storage/volume_backup.go (BinarySearchByAppendAtNs,
VolumeIncrementalCopy). The headline test is the verdict-directed one:
a diverged replica resyncs needle-granularly and ends BIT-IDENTICAL to
the source volume's .dat.
"""

from __future__ import annotations

import time

import grpc
import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.client.volume_sync import (
    incremental_copy,
    sync_replica,
    tail_volume,
)
from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


# ------------------------------------------------------ unit primitives


def test_offset_after_ns_and_scan(tmp_path):
    v = Volume(str(tmp_path), 1)
    ts = []
    for i in range(1, 51):
        v.write_needle(Needle(cookie=7, needle_id=i, data=b"x" * (i % 13 + 1)))
        ts.append(v.last_append_at_ns())
    assert ts == sorted(ts)

    # since=0: everything follows
    ids = [n.needle_id for n, _, _ in v.scan_raw_since(0)]
    assert ids == list(range(1, 51))

    # middle boundary is exclusive
    mid = ts[24]
    ids = [n.needle_id for n, _, _ in v.scan_raw_since(mid)]
    assert ids == list(range(26, 51))

    # since=last: nothing; byte resume point == append end
    assert list(v.scan_raw_since(ts[-1])) == []
    assert v.offset_after_ns(ts[-1]) == v._append_end()
    assert v.offset_after_ns(0) == 8  # SUPER_BLOCK_SIZE
    v.close()


def test_delete_only_tail_propagates(tmp_path):
    """A tombstone NOT followed by any newer put must still stream
    (review r5: the reference's first-put-after-since search silently
    loses trailing deletes; ours pins the last put <= since and walks
    forward)."""
    v = Volume(str(tmp_path), 4)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=1, needle_id=i, data=b"d"))
    synced = v.last_append_at_ns()
    v.delete_needle(2)  # nothing appended after this tombstone
    recs = list(v.scan_raw_since(synced))
    assert [(n.needle_id, n.data) for n, _, _ in recs] == [(2, b"")]
    # the follower's own resume point includes the tombstone's ts
    assert v.last_append_at_ns() > synced
    # byte-level resume also lands exactly at the tombstone record
    off = v.offset_after_ns(synced)
    assert off < v._append_end()
    v.close()


def test_scan_raw_since_propagates_tombstones(tmp_path):
    v = Volume(str(tmp_path), 2)
    for i in range(1, 11):
        v.write_needle(Needle(cookie=1, needle_id=i, data=b"d"))
    mid = v.last_append_at_ns()
    v.write_needle(Needle(cookie=1, needle_id=11, data=b"d"))
    v.delete_needle(3)
    recs = list(v.scan_raw_since(mid))
    ids = [(n.needle_id, n.data) for n, _, _ in recs]
    assert ids == [(11, b"d"), (3, b"")]
    v.close()


def test_last_append_at_ns_includes_trailing_tombstone(tmp_path):
    """The resume point covers tombstones: a replica whose newest
    record is its own applied delete must not re-span it."""
    v = Volume(str(tmp_path), 3)
    v.write_needle(Needle(cookie=1, needle_id=1, data=b"a"))
    put_ts = v.last_append_at_ns()
    v.delete_needle(1)
    assert v.last_append_at_ns() > put_ts
    assert list(v.scan_raw_since(v.last_append_at_ns())) == []
    v.close()


# --------------------------------------------------- spawned-server sync


@pytest.fixture
def pair(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    deadline = time.time() + 10
    while len(master.topo.nodes) < 2:
        if time.time() > deadline:
            raise TimeoutError("volume servers did not register")
        time.sleep(0.05)
    yield master, vols
    for vs in vols:
        vs.stop()
    master.stop()


def _stub(vs):
    ch = grpc.insecure_channel(f"localhost:{vs.grpc_port}")
    return ch, rpc.volume_stub(ch)


def _write(stub, vid, nid, data, cookie=0x1234):
    r = stub.WriteNeedle(
        pb.WriteNeedleRequest(
            volume_id=vid,
            needle_id=nid,
            cookie=cookie,
            data=data,
            is_replicate=True,
        ),
        timeout=10,
    )
    assert not r.error, r.error


def _dat_bytes(vs, vid):
    v = vs.store.find_volume(vid)
    v.flush()
    with open(v.dat_path, "rb") as f:
        return f.read()


def test_replica_catchup_bit_identical(pair):
    """Verdict-directed: kill a replica (simulated as one replica not
    receiving the writes), write 1k needles, resync via
    VolumeTailReceiver, verify bit-identical .dat."""
    _, (a, b) = pair
    ca, sa = _stub(a)
    cb, sb = _stub(b)
    try:
        for s in (sa, sb):
            s.AllocateVolume(
                pb.AllocateVolumeRequest(volume_id=7, replication="000"),
                timeout=10,
            )
        # both replicas see the first 10 writes
        for i in range(1, 11):
            blob = f"seed-{i}".encode() * 3
            _write(sa, 7, i, blob)
        n = sync_replica(
            f"localhost:{b.grpc_port}", f"localhost:{a.grpc_port}", 7,
            idle_timeout_s=1,
        )
        assert n == 10
        assert _dat_bytes(a, 7) == _dat_bytes(b, 7)

        # replica b "down": a takes 1000 more writes, 5 deletes, 3
        # overwrites
        for i in range(11, 1011):
            _write(sa, 7, i, f"payload-{i}".encode() * (i % 7 + 1))
        for i in (2, 4, 500, 900, 1000):
            sa.DeleteNeedle(
                pb.DeleteNeedleRequest(
                    volume_id=7, needle_id=i, is_replicate=True
                ),
                timeout=10,
            )
        for i in (1, 3, 7):
            _write(sa, 7, i, f"rewrite-{i}".encode())

        n = sync_replica(
            f"localhost:{b.grpc_port}", f"localhost:{a.grpc_port}", 7,
            idle_timeout_s=1,
        )
        assert n == 1008, n
        assert _dat_bytes(a, 7) == _dat_bytes(b, 7)

        # the replica serves the synced content (including deletes)
        vb = b.store.find_volume(7)
        assert vb.read_needle(500 + 1).data.startswith(b"payload-501")
        assert vb.read_needle(1).data == b"rewrite-1"
        from seaweedfs_tpu.storage.volume import NotFoundError

        for i in (2, 4, 500):
            with pytest.raises(NotFoundError):
                vb.read_needle(i)

        # delete-only divergence: no put follows the tombstone
        sa.DeleteNeedle(
            pb.DeleteNeedleRequest(
                volume_id=7, needle_id=42, is_replicate=True
            ),
            timeout=10,
        )
        n = sync_replica(
            f"localhost:{b.grpc_port}", f"localhost:{a.grpc_port}", 7,
            idle_timeout_s=1,
        )
        assert n == 1, n
        assert _dat_bytes(a, 7) == _dat_bytes(b, 7)
        with pytest.raises(NotFoundError):
            vb.read_needle(42)
    finally:
        ca.close()
        cb.close()


def test_tail_volume_client_streams_live_appends(pair):
    _, (a, _b) = pair
    ca, sa = _stub(a)
    try:
        sa.AllocateVolume(
            pb.AllocateVolumeRequest(volume_id=9, replication="000"),
            timeout=10,
        )
        _write(sa, 9, 1, b"first")
        got = []

        import threading

        def consume():
            for n in tail_volume(
                f"localhost:{a.grpc_port}", 9, 0, idle_timeout_s=2
            ):
                got.append(n.needle_id)

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.6)  # tail is now past the first scan, following
        _write(sa, 9, 2, b"live-append" * 100_000)  # multi-chunk body
        t.join(timeout=30)
        assert not t.is_alive()
        assert got == [1, 2]
    finally:
        ca.close()


def test_incremental_copy_prefix_guard(pair):
    _, (a, _b) = pair
    ca, sa = _stub(a)
    try:
        sa.AllocateVolume(
            pb.AllocateVolumeRequest(volume_id=11, replication="000"),
            timeout=10,
        )
        for i in range(1, 6):
            _write(sa, 11, i, f"n{i}".encode())
        va = a.store.find_volume(11)
        mid_ns = va.last_append_at_ns()
        mid_size = len(_dat_bytes(a, 11))
        for i in range(6, 11):
            _write(sa, 11, i, f"n{i}".encode())

        start, chunks = incremental_copy(
            f"localhost:{a.grpc_port}", 11, mid_ns
        )
        tail = b"".join(chunks)
        assert start == mid_size
        assert _dat_bytes(a, 11)[start:] == tail

        # nothing newer: start == current size, empty stream
        start2, chunks2 = incremental_copy(
            f"localhost:{a.grpc_port}", 11, va.last_append_at_ns()
        )
        assert start2 == len(_dat_bytes(a, 11))
        assert b"".join(chunks2) == b""
    finally:
        ca.close()


def test_read_volume_file_status(pair):
    _, (a, _b) = pair
    ca, sa = _stub(a)
    try:
        sa.AllocateVolume(
            pb.AllocateVolumeRequest(volume_id=13, replication="000"),
            timeout=10,
        )
        _write(sa, 13, 1, b"hello")
        st = sa.ReadVolumeFileStatus(
            pb.VolumeFileStatusRequest(volume_id=13), timeout=10
        )
        assert not st.error
        v = a.store.find_volume(13)
        assert st.dat_size == len(_dat_bytes(a, 13))
        assert st.last_append_at_ns == v.last_append_at_ns()
        assert st.version == v.version
        missing = sa.ReadVolumeFileStatus(
            pb.VolumeFileStatusRequest(volume_id=99), timeout=10
        )
        assert missing.error
    finally:
        ca.close()


def test_shell_volume_sync_command(pair):
    from seaweedfs_tpu.shell.commands import ShellEnv, run_command

    master, (a, b) = pair
    ca, sa = _stub(a)
    cb, sb = _stub(b)
    env = ShellEnv(f"localhost:{master.port}")
    try:
        for s in (sa, sb):
            s.AllocateVolume(
                pb.AllocateVolumeRequest(volume_id=21, replication="000"),
                timeout=10,
            )
        for i in range(1, 31):
            _write(sa, 21, i, f"rec-{i}".encode())
        # master must know the volume exists for lookup
        deadline = time.time() + 10
        while not env.master.lookup(21, refresh=True):
            if time.time() > deadline:
                raise TimeoutError("master never learned volume 21")
            time.sleep(0.1)
        out = run_command(
            env,
            f"volume.sync -volumeId 21 -target localhost:{b.grpc_port} "
            f"-source localhost:{a.grpc_port} -idleTimeout 1",
        )
        assert "30 records applied" in out, out
        assert _dat_bytes(a, 21) == _dat_bytes(b, 21)
        # second run is a no-op (already converged)
        out = run_command(
            env,
            f"volume.sync -volumeId 21 -target localhost:{b.grpc_port} "
            f"-source localhost:{a.grpc_port} -idleTimeout 1",
        )
        assert "0 records applied" in out, out
    finally:
        env.close()
        ca.close()
        cb.close()
