"""Remote storage: SigV4 S3 client, lazy remote mounts, cloud sink.

References: weed/remote_storage/s3, weed/filer/read_remote.go,
weed/replication/sink/s3sink. The "cloud" in these tests is the
framework's own S3 gateway — the client must interop with it through
real SigV4-authenticated HTTP.
"""

import time

import pytest
import requests

from conftest import allocate_port
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filer_store import MemoryStore, NotFound
from seaweedfs_tpu.remote import RemoteS3Client, RemoteStorageError
from seaweedfs_tpu.remote import mount as rm
from seaweedfs_tpu.s3 import Identity, IdentityStore, S3Server
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

AK, SK = "remoteAK", "remoteSKsecret"


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """master + volume + a 'cloud' (our own S3 gateway on its own
    filer) + a local filer that will mount it."""
    tmp = tmp_path_factory.mktemp("remote")
    mport = allocate_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=allocate_port(),
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    cloud_filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    idents = IdentityStore()
    idents.add(Identity("remote-user", AK, SK))
    s3 = S3Server(
        cloud_filer,
        ip="localhost",
        port=allocate_port(),
        identities=idents,
        lifecycle_interval=0,
    )
    s3.start()
    local_filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    yield {
        "mport": mport,
        "s3": s3,
        "cloud_filer": cloud_filer,
        "filer": local_filer,
    }
    s3.stop()
    local_filer.close()
    cloud_filer.close()
    vs.stop()
    master.stop()


@pytest.fixture
def client(stack):
    return RemoteS3Client(
        endpoint=f"http://localhost:{stack['s3'].port}",
        access_key=AK,
        secret_key=SK,
    )


def test_s3_client_sigv4_round_trip(stack, client):
    client.ensure_bucket("cloud-data")
    client.put_object("cloud-data", "a/b/hello.txt", b"hello remote")
    assert client.get_object("cloud-data", "a/b/hello.txt") == b"hello remote"
    # ranged read
    assert client.get_object("cloud-data", "a/b/hello.txt", 6, 6) == b"remote"
    objs = client.list_objects("cloud-data", prefix="a/")
    assert [o.key for o in objs] == ["a/b/hello.txt"]
    assert objs[0].size == 12
    head = client.head_object("cloud-data", "a/b/hello.txt")
    assert head.size == 12
    assert client.head_object("cloud-data", "missing") is None
    client.delete_object("cloud-data", "a/b/hello.txt")
    assert client.list_objects("cloud-data", prefix="a/") == []
    # a wrong secret is rejected by the gateway
    bad = RemoteS3Client(
        endpoint=f"http://localhost:{stack['s3'].port}",
        access_key=AK,
        secret_key="wrong",
    )
    with pytest.raises(RemoteStorageError):
        bad.put_object("cloud-data", "x", b"y")


def test_remote_mount_read_through_cache(stack, client):
    filer = stack["filer"]
    client.ensure_bucket("datasets")
    blob = bytes(range(256)) * 64  # 16 KiB
    client.put_object("datasets", "v1/model.bin", blob)
    client.put_object("datasets", "v1/labels.txt", b"cat\ndog\n")
    rm.configure(
        filer,
        "cloud",
        {
            "endpoint": f"http://localhost:{stack['s3'].port}",
            "access_key": AK,
            "secret_key": SK,
        },
    )
    n = rm.mount(filer, "/mnt/data", "cloud", "datasets", prefix="v1")
    assert n == 2
    # metadata materialized, no data copied
    e = filer.find_entry("/mnt/data/model.bin")
    assert e.file_size == len(blob) and not e.chunks and not e.content
    # read-through
    assert filer.read_entry(e) == blob
    assert filer.read_entry(e, offset=256, size=16) == blob[256:272]
    # cache pins bytes locally; reads stop hitting the remote
    rm.cache(filer, "/mnt/data/model.bin")
    cached = filer.find_entry("/mnt/data/model.bin")
    assert cached.chunks or cached.content
    stack["s3"].stop()  # cloud goes dark
    try:
        assert filer.read_entry(cached) == blob
        # uncached file now fails (proves reads really were remote)
        lab = filer.find_entry("/mnt/data/labels.txt")
        with pytest.raises(Exception):
            filer.read_entry(lab)
    finally:
        stack["s3"]._http.server_activate  # noqa: B018 — keep ref
        # restart the gateway for later tests
        from seaweedfs_tpu.s3 import S3Server as _S3

        new = _S3(
            stack["cloud_filer"],
            ip="localhost",
            port=allocate_port(),
            identities=stack["s3"].identities,
            lifecycle_interval=0,
        )
        new.start()
        stack["s3"] = new
        client.endpoint = f"http://localhost:{new.port}"
        rm.configure(
            filer,
            "cloud",
            {
                "endpoint": client.endpoint,
                "access_key": AK,
                "secret_key": SK,
            },
        )
    # uncache drops local chunks; read-through works again
    rm.uncache(filer, "/mnt/data/model.bin")
    e = filer.find_entry("/mnt/data/model.bin")
    assert not e.chunks and not e.content
    assert e.file_size == len(blob)
    assert filer.read_entry(e) == blob
    # unmount removes the view, remote keeps the data
    rm.unmount(filer, "/mnt/data")
    with pytest.raises(NotFound):
        filer.find_entry("/mnt/data/model.bin")
    assert client.head_object("datasets", "v1/model.bin").size == len(blob)


def test_remote_ops_via_http_and_shell(stack):
    filer = stack["filer"]
    client = RemoteS3Client(
        endpoint=f"http://localhost:{stack['s3'].port}",
        access_key=AK,
        secret_key=SK,
    )
    client.ensure_bucket("shellbucket")
    client.put_object("shellbucket", "f.txt", b"from the cloud")
    srv = FilerServer(filer, ip="localhost", port=allocate_port())
    srv.start()
    try:
        base = f"http://localhost:{srv.port}"
        r = requests.post(
            base + "/~remote/configure",
            json={
                "name": "c2",
                "endpoint": f"http://localhost:{stack['s3'].port}",
                "access_key": AK,
                "secret_key": SK,
            },
            timeout=10,
        )
        assert r.status_code == 200
        r = requests.post(
            base + "/~remote/mount",
            json={"dir": "/cloud2", "remote": "c2", "bucket": "shellbucket"},
            timeout=30,
        )
        assert r.json()["mounted"] == 1
        # file readable through the filer HTTP API (read-through)
        assert (
            requests.get(base + "/cloud2/f.txt", timeout=10).content
            == b"from the cloud"
        )
        r = requests.post(
            base + "/~remote/cache", json={"path": "/cloud2/f.txt"}, timeout=30
        )
        assert r.status_code == 200
        r = requests.post(
            base + "/~remote/unmount", json={"dir": "/cloud2"}, timeout=30
        )
        assert r.status_code == 200
        # shell surface smoke: remote.* registered
        from seaweedfs_tpu.shell.commands import COMMANDS

        for name in (
            "remote.configure",
            "remote.mount",
            "remote.cache",
            "remote.uncache",
            "remote.unmount",
        ):
            assert name in COMMANDS
    finally:
        srv.stop()


def test_s3_sink_mirrors_filer_subtree(stack, tmp_path):
    from seaweedfs_tpu.filer.meta_log import MetaLog
    from seaweedfs_tpu.replication.s3_sink import S3Sink

    filer = stack["filer"]
    client = RemoteS3Client(
        endpoint=f"http://localhost:{stack['s3'].port}",
        access_key=AK,
        secret_key=SK,
    )
    srv = FilerServer(
        filer,
        ip="localhost",
        port=allocate_port(),
        meta_log=MetaLog(str(tmp_path / "metalog")),
    )
    srv.start()
    try:
        filer.write_file("/backup/a.txt", b"alpha" * 500)
        filer.write_file("/backup/sub/b.txt", b"beta")
        filer.write_file("/other/c.txt", b"out of scope")
        sink = S3Sink(
            f"localhost:{srv.port}",
            client,
            "mirror",
            path_prefix="/backup",
        )
        copied = sink.full_sync()
        assert copied == 2
        keys = {o.key for o in client.list_objects("mirror")}
        assert keys == {"a.txt", "sub/b.txt"}
        assert client.get_object("mirror", "a.txt") == b"alpha" * 500
        # live tail: new write + delete propagate
        sink.watermark = sink._source_now_ns()
        filer.write_file("/backup/new.txt", b"fresh")
        filer.delete_entry("/backup/a.txt")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sink.tail_once(wait_seconds=1)
            keys = {o.key for o in client.list_objects("mirror")}
            if "new.txt" in keys and "a.txt" not in keys:
                break
        assert "new.txt" in keys and "a.txt" not in keys
    finally:
        srv.stop()


def test_remote_provider_registry():
    """SPI: s3 + gcs-s3 resolve to the native client; cloud-SDK
    providers fail loudly; custom providers register."""
    import pytest as _pytest

    from seaweedfs_tpu.remote.providers import make_remote_client, register
    from seaweedfs_tpu.remote.s3_client import RemoteS3Client

    c = make_remote_client(
        "s3", endpoint="http://localhost:1", access_key="a", secret_key="b"
    )
    assert isinstance(c, RemoteS3Client)
    g = make_remote_client("gcs-s3", access_key="a", secret_key="b")
    assert isinstance(g, RemoteS3Client)
    assert "storage.googleapis.com" in g.endpoint

    with _pytest.raises((RuntimeError, NotImplementedError)):
        make_remote_client("gcs")
    with _pytest.raises((RuntimeError, NotImplementedError)):
        make_remote_client("azure")
    with _pytest.raises(ValueError):
        make_remote_client("dropbox")

    class Fake:
        def __init__(self, **kw):
            self.kw = kw

    register("fake", Fake)
    f = make_remote_client("fake", endpoint="x", access_key="k", secret_key="s")
    assert isinstance(f, Fake) and f.kw["endpoint"] == "x"


def test_remote_meta_sync(stack):
    """remote.meta.sync: new cloud objects appear, changed ones update,
    deleted ones drop their local entries (through the HTTP op the
    shell command rides)."""
    filer = stack["filer"]
    client = RemoteS3Client(
        endpoint=f"http://localhost:{stack['s3'].port}",
        access_key=AK,
        secret_key=SK,
    )
    client.ensure_bucket("syncb")
    client.put_object("syncb", "keep.txt", b"v1")
    client.put_object("syncb", "gone.txt", b"bye")
    srv = FilerServer(filer, ip="localhost", port=allocate_port())
    srv.start()
    try:
        base = f"http://localhost:{srv.port}"
        requests.post(
            base + "/~remote/configure",
            json={
                "name": "c3",
                "endpoint": f"http://localhost:{stack['s3'].port}",
                "access_key": AK,
                "secret_key": SK,
            },
            timeout=10,
        )
        r = requests.post(
            base + "/~remote/mount",
            json={"dir": "/sync3", "remote": "c3", "bucket": "syncb"},
            timeout=30,
        )
        assert r.json()["mounted"] == 2
        # cloud mutates behind the mount
        client.put_object("syncb", "keep.txt", b"v2-new-content")
        client.put_object("syncb", "new.txt", b"fresh")
        client.delete_object("syncb", "gone.txt")
        r = requests.post(
            base + "/~remote/meta.sync", json={"dir": "/sync3"}, timeout=30
        )
        doc = r.json()
        assert (doc["added"], doc["updated"], doc["removed"]) == (1, 1, 1), doc
        assert filer.find_entry("/sync3/new.txt").attr.file_size == 5
        assert filer.find_entry("/sync3/keep.txt").attr.file_size == len(
            b"v2-new-content"
        )
        import pytest as _pytest

        from seaweedfs_tpu.filer.filer_store import NotFound as _NF

        with _pytest.raises(_NF):
            filer.find_entry("/sync3/gone.txt")
        # and the refreshed content reads through
        assert (
            requests.get(base + "/sync3/keep.txt", timeout=10).content
            == b"v2-new-content"
        )
    finally:
        srv.stop()


def test_remote_mount_buckets(stack):
    """remote.mount.buckets: every (prefix-matched) cloud bucket lands
    under dir/<bucket>, already-mounted ones are skipped."""
    filer = stack["filer"]
    client = RemoteS3Client(
        endpoint=f"http://localhost:{stack['s3'].port}",
        access_key=AK,
        secret_key=SK,
    )
    for b, key in (("mb-one", "a.txt"), ("mb-two", "b.txt"), ("zz-skip", "c.txt")):
        client.ensure_bucket(b)
        client.put_object(b, key, b"data-" + b.encode())
    assert set(client.list_buckets()) >= {"mb-one", "mb-two", "zz-skip"}
    srv = FilerServer(filer, ip="localhost", port=allocate_port())
    srv.start()
    try:
        base = f"http://localhost:{srv.port}"
        requests.post(
            base + "/~remote/configure",
            json={
                "name": "cmb",
                "endpoint": f"http://localhost:{stack['s3'].port}",
                "access_key": AK,
                "secret_key": SK,
            },
            timeout=10,
        )
        r = requests.post(
            base + "/~remote/mount.buckets",
            json={"dir": "/clouds", "remote": "cmb", "prefix": "mb-"},
            timeout=30,
        )
        doc = r.json()
        assert doc["buckets"] == 2, doc
        assert (
            requests.get(base + "/clouds/mb-one/a.txt", timeout=10).content
            == b"data-mb-one"
        )
        # idempotent: a second call mounts nothing new
        r = requests.post(
            base + "/~remote/mount.buckets",
            json={"dir": "/clouds", "remote": "cmb", "prefix": "mb-"},
            timeout=30,
        )
        assert r.json()["buckets"] == 0
        from seaweedfs_tpu.shell.commands import COMMANDS

        assert "remote.mount.buckets" in COMMANDS
    finally:
        srv.stop()
