"""Raft under deterministic injected faults (drops, dups, delays,
partitions, crashes, torn journal tails).

Replaces sleep-and-hope timing tests: the SimNet transport
(tests/raft_sim.py) is seeded, every schedule is replayable, and the
assertions are the Raft paper's invariants — election safety, log
matching, applied-prefix consistency, state convergence — checked
structurally. Reference analog: test/multi_master/failover_test.go
(which drives real processes; this goes further with fault injection
no real network can do deterministically).
"""

from __future__ import annotations

import json
import os
import random
import time

import pytest

from raft_sim import Cluster
from seaweedfs_tpu.server import raft as R


def _propose_retry(c: Cluster, value: int, deadline_s: float = 20.0) -> None:
    """Client model: retry until SOME leader acks. A timed-out commit
    may still have landed, so the op may apply more than once — the
    invariants below must hold regardless (at-least-once client)."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            leader = c.wait_leader(timeout=deadline - time.monotonic())
            leader.propose("op", value=value, timeout=2.0)
            return
        except (R.NotLeader, TimeoutError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def _wait_quiescent(c: Cluster, timeout: float = 15.0) -> None:
    """Wait until every live node has applied the leader's commit."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leader = None
        try:
            leader = c.wait_leader(timeout=2.0)
        except TimeoutError:
            continue
        target = leader.commit_index
        if all(n.last_applied >= target for n in c.nodes.values()):
            return
        time.sleep(0.02)
    raise TimeoutError("cluster never quiesced")


def _check_all(c: Cluster) -> None:
    c.check_election_safety()
    c.check_log_matching()
    c.check_applied_prefix()


def test_fault_free_baseline(tmp_path):
    c = Cluster(3, str(tmp_path), seed=1)
    try:
        for i in range(50):
            _propose_retry(c, i)
        _wait_quiescent(c)
        _check_all(c)
        states = {json.dumps(c.state[n], sort_keys=True) for n in c.nodes}
        assert len(states) == 1
    finally:
        c.stop()


def test_loss_dup_delay_convergence(tmp_path):
    """20% loss each direction + 10% duplicate delivery + up to 5 ms
    delay: progress continues and no replica diverges."""
    c = Cluster(3, str(tmp_path), seed=2)
    try:
        c.net.set_faults(drop=0.2, dup=0.1, delay=(0.0, 0.005))
        for i in range(60):
            _propose_retry(c, i)
            if i % 20 == 19:
                _check_all(c)
        c.net.set_faults(drop=0.0, dup=0.0, delay=(0.0, 0.0))
        _wait_quiescent(c)
        _check_all(c)
        # every op committed at least once, order-consistent
        longest = max(
            ([v for k, v in c.applied[n] if k == "op"] for n in c.nodes),
            key=len,
        )
        assert set(longest) == set(range(60))
    finally:
        c.stop()


def test_minority_partition_cannot_commit(tmp_path):
    c = Cluster(3, str(tmp_path), seed=3)
    try:
        for i in range(5):
            _propose_retry(c, i)
        _wait_quiescent(c)
        old = c.wait_leader()
        minority = [old.node_id]
        majority = [n for n in c.ids if n != old.node_id]
        c.net.partition(minority, majority)
        # the stranded leader must not commit anything new
        with pytest.raises(TimeoutError):
            old.propose("op", value=999, timeout=1.0)
        # the majority elects and commits
        deadline = time.monotonic() + 10
        new = None
        while time.monotonic() < deadline:
            cand = [
                c.nodes[n] for n in majority
                if c.nodes[n].role == R.LEADER
            ]
            if cand:
                new = cand[0]
                break
            time.sleep(0.02)
        assert new is not None, "majority never elected"
        new.propose("op", value=100, timeout=5.0)
        assert 999 not in {v for _k, v in c.applied[new.node_id]}
        c.net.heal()
        _wait_quiescent(c)
        _check_all(c)
        # the uncommitted minority entry is gone everywhere
        for n in c.nodes:
            assert 999 not in {v for _k, v in c.applied[n]}
            assert 100 in {v for _k, v in c.applied[n]}
    finally:
        c.stop()


def test_torn_journal_tail_recovery(tmp_path):
    """SIGKILL mid-journal-write: the node restarts off the intact
    prefix and reconverges with the cluster."""
    c = Cluster(3, str(tmp_path), seed=4)
    try:
        for i in range(20):
            _propose_retry(c, i)
        _wait_quiescent(c)
        victim = next(
            n for n in c.ids if c.nodes[n].role != R.LEADER
        )
        c.crash(victim)
        path = os.path.join(
            str(tmp_path), victim.replace(":", "_"), "raft.jsonl"
        )
        with open(path, "r+b") as f:
            f.truncate(max(os.path.getsize(path) - 7, 0))  # torn record
        for i in range(20, 30):
            _propose_retry(c, i)
        c.restart(victim)
        _wait_quiescent(c)
        _check_all(c)
        assert {v for k, v in c.applied[victim] if k == "op"} >= set(
            range(20, 30)
        )
    finally:
        c.stop()


def test_randomized_fault_schedule(tmp_path):
    """Seeded random schedule of proposals, partitions, crashes,
    restarts, and loss bursts; invariants checked after every fault
    event and at quiescence. RAFT_SIM_STEPS scales it up for soak
    runs (default keeps CI fast; 500 is the validated soak scale —
    beyond that, wall time grows superlinearly because every proposal
    attempted during a no-quorum window burns its full client
    deadline)."""
    steps = int(os.environ.get("RAFT_SIM_STEPS", "120"))
    rng = random.Random(0xC0FFEE)
    c = Cluster(3, str(tmp_path), seed=5)
    down: list[str] = []
    val = 0
    acked: set[int] = set()
    try:
        for step in range(steps):
            roll = rng.random()
            if roll < 0.70:
                # at-least-once client: raft promises SAFETY under any
                # fault mix; liveness only under eventually-calm nets —
                # so a timed-out proposal is recorded as un-acked, not
                # treated as a harness failure
                try:
                    _propose_retry(c, val, deadline_s=6.0)
                    acked.add(val)
                except (TimeoutError, R.NotLeader):
                    pass
                val += 1
            elif roll < 0.78 and not down:
                groups = list(c.ids)
                rng.shuffle(groups)
                c.net.partition([groups[0]], groups[1:])
            elif roll < 0.84:
                c.net.heal()
            elif roll < 0.90 and len(c.nodes) == 3:
                victim = rng.choice(list(c.nodes))
                c.net.heal()  # crash+partition together can lose quorum
                c.crash(victim)
                down.append(victim)
            elif roll < 0.96 and down:
                c.restart(down.pop())
            else:
                burst = rng.choice([0.0, 0.1, 0.25])
                c.net.set_faults(drop=burst, dup=burst / 2)
            if step % 10 == 9:
                c.check_election_safety()
                c.check_log_matching()
        # settle: heal everything, bring every node back
        c.net.set_faults(drop=0.0, dup=0.0, delay=(0.0, 0.0))
        c.net.heal()
        while down:
            c.restart(down.pop())
        _propose_retry(c, val)
        acked.add(val)
        _wait_quiescent(c, timeout=30.0)
        c.check_election_safety()
        c.check_log_matching()
        # all live nodes reached identical state machines
        states = {
            json.dumps(c.state[n], sort_keys=True) for n in c.nodes
        }
        assert len(states) == 1, "replicas diverged"
        # every ACKED proposal survives (at-least-once). Read the op
        # set from the replicated STATE: a leader that restarted after
        # a snapshot never re-applies snapshot-covered entries, so the
        # volatile applied trace under-counts (found by the 2000-step
        # soak; the state-machine observable is restart-proof).
        leader = c.wait_leader()
        ops = set(c.state[leader.node_id].get("ops") or [])
        missing = acked - ops
        assert not missing, f"acked ops lost: {sorted(missing)[:10]}"
        assert len(acked) >= steps * 0.3, "schedule barely made progress"
    finally:
        c.stop()
