"""Cluster-level EC self-healing over REAL gRPC: an in-process master +
two volume servers on loopback (the reference's in-process harness
technique, same protocols as production), with the fault registry armed
across the actual RPC boundary — mid-stream peer death, torn/corrupt
shard-read responses, latency spikes, and crash-during-distribute.

Every scenario must end bit-exact or refuse cleanly; wedging, partial
publishes, and duplicate shard copies are failures. The fixed-seed
subset runs in tier-1 (`chaos` marker); the randomized multi-fault soak
is `slow`.
"""

from __future__ import annotations

import json
import os
import threading
import time

import grpc
import numpy as np
import pytest
import requests

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec.context import ECError  # noqa: F401 (doc anchor)
from seaweedfs_tpu.ec.peer_rebuild import staging_dir
from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from conftest import allocate_port as free_port
from conftest import wait_for

pytestmark = pytest.mark.chaos

TOTAL = 14  # default 10+4 ratio
KEEP_LOCAL = [0, 1, 2, 3]  # subset holder keeps 4 < k=10 shards
MOVED = list(range(4, TOTAL))


class Cluster:
    def __init__(self, tmp_path, n=2):
        self.mport = free_port()
        self.master = MasterServer(ip="localhost", port=self.mport)
        self.master.start()
        self.vols = [
            VolumeServer(
                directories=[str(tmp_path / f"v{i}")],
                master=f"localhost:{self.mport}",
                ip="localhost",
                port=free_port(),
                ec_backend="cpu",
            )
            for i in range(n)
        ]
        for vs in self.vols:
            vs.start()
        wait_for(
            lambda: len(self.master.topo.nodes) >= n,
            msg="volume servers did not register",
        )
        self._channels = []

    def stub(self, vs):
        ch = grpc.insecure_channel(f"localhost:{vs.grpc_port}")
        self._channels.append(ch)
        return rpc.volume_stub(ch)

    def locs(self, vid):
        return {
            sid: [l.url for l in locs]
            for sid, locs in self.master.topo.lookup_ec(vid).items()
        }

    def stop(self):
        for ch in self._channels:
            ch.close()
        for vs in self.vols:
            vs.stop()
        self.master.stop()


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(tmp_path)
    yield c
    c.stop()


@pytest.fixture
def cluster3(tmp_path):
    c = Cluster(tmp_path, n=3)
    yield c
    c.stop()


def split_ec_volume(c: Cluster):
    """Upload + EC-encode one volume, then split the shard set so the
    uploading server becomes a SUBSET holder (4 of 14 shards — below
    k=10, the configuration local rebuild refuses on). Returns
    (vid, fid, payload, holder, other, ground: sid -> bytes)."""
    a = requests.get(f"http://localhost:{c.mport}/dir/assign").json()
    fid = a["fid"]
    vid = int(fid.split(",")[0])
    payload = np.random.default_rng(0xC10D).integers(
        0, 256, 100_000, dtype=np.uint8
    ).tobytes()
    r = requests.post(
        f"http://{a['url']}/{fid}", files={"file": ("x.bin", payload)}
    )
    assert r.status_code == 201, r.text
    holder = next(v for v in c.vols if a["url"] == f"localhost:{v.port}")
    other = next(v for v in c.vols if v is not holder)
    st_h, st_o = c.stub(holder), c.stub(other)
    st_h.VolumeEcShardsGenerate(
        pb.EcShardsGenerateRequest(volume_id=vid, backend="cpu"), timeout=120
    )
    st_h.VolumeEcShardsMount(
        pb.EcShardsMountRequest(volume_id=vid), timeout=30
    )
    st_h.VolumeDelete(pb.VolumeCommandRequest(volume_id=vid), timeout=30)
    base = holder.service._ec_base(vid, "")
    ground = {
        i: open(base + f".ec{i:02d}", "rb").read() for i in range(TOTAL)
    }
    st_o.VolumeEcShardsCopy(
        pb.EcShardsCopyRequest(
            volume_id=vid,
            shard_ids=MOVED,
            source_url=f"localhost:{holder.grpc_port}",
            copy_ecx=True, copy_ecj=True, copy_vif=True, copy_ecsum=True,
        ),
        timeout=120,
    )
    st_o.VolumeEcShardsMount(
        pb.EcShardsMountRequest(volume_id=vid), timeout=30
    )
    st_h.VolumeEcShardsUnmount(
        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=MOVED), timeout=30
    )
    st_h.VolumeEcShardsDelete(
        pb.EcShardsDeleteRequest(volume_id=vid, shard_ids=MOVED), timeout=30
    )
    wait_for(
        lambda: len(c.locs(vid)) == TOTAL
        and all(len(v) == 1 for v in c.locs(vid).values()),
        msg="shard split did not reach the master",
    )
    return vid, fid, payload, holder, other, ground


def quarantine(holder, vid, base, sid):
    """Scrub-style quarantine: rename the shard to .bad and unmount it."""
    os.replace(base + f".ec{sid:02d}", base + f".ec{sid:02d}.bad")
    holder.store.unmount_ec_shards(vid, [sid])


def rebuild_from_peers_rpc(c, holder, vid, timeout=120):
    st = c.stub(holder)
    return st.VolumeEcShardsRebuild(
        pb.EcShardsRebuildRequest(volume_id=vid, from_peers=True),
        timeout=timeout,
    )


# --------------------------------------------------- happy path (tier-1)


def test_peer_fetch_restores_subset_holder_bit_identical(cluster):
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    quarantine(holder, vid, base, 0)
    wait_for(
        lambda: not cluster.locs(vid).get(0),
        msg="quarantine did not reach the master",
    )
    # the per-server rebuild refuses: 3 local shards < k
    with pytest.raises(grpc.RpcError) as ei:
        cluster.stub(holder).VolumeEcShardsRebuild(
            pb.EcShardsRebuildRequest(volume_id=vid), timeout=60
        )
    assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION

    r = rebuild_from_peers_rpc(cluster, holder, vid)
    assert list(r.rebuilt_shard_ids) == [0]
    assert len(r.fetched_shard_ids) == 7  # k(10) - 3 good local
    assert open(base + ".ec00", "rb").read() == ground[0]
    ev = holder.store.find_ec_volume(vid)
    assert 0 in ev.shard_fds, "regenerated shard not remounted"
    wait_for(
        lambda: cluster.locs(vid).get(0) == [f"localhost:{holder.port}"],
        msg="restored shard not re-advertised",
    )
    # the payload still reads back through the EC read path
    got = requests.get(f"http://localhost:{holder.port}/{fid}").content
    assert got == payload


def test_flight_recorder_one_trace_spans_cluster_heal(cluster):
    """ISSUE-7 acceptance: with the flight recorder armed, one
    `ec.rebuild -fromPeers` run over REAL gRPC yields a single trace id
    spanning the rebuilding holder's RPC root and every peer shard-read
    stream, with the X-Request-ID continuous across servers; the holder
    dumps it from /debug/traces as valid Chrome trace_event JSON, and
    the per-stage histograms + overlap gauge populate for encode,
    rebuild, and degraded read."""
    from seaweedfs_tpu.utils import trace
    from seaweedfs_tpu.utils.metrics import REGISTRY

    trace.configure(enabled=True, ring_size=512)
    try:
        trace.reset()
        vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
        quarantine(holder, vid, holder.service._ec_base(vid, ""), 0)
        wait_for(
            lambda: not cluster.locs(vid).get(0),
            msg="quarantine did not reach the master",
        )
        # shard 0 now has NO holder anywhere: reading the blob forces
        # sidecar-verified reconstruction from remote siblings — the
        # degraded-read op class populates its stage histograms
        got = requests.get(f"http://localhost:{holder.port}/{fid}").content
        assert got == payload

        trace.reset()  # isolate the heal: it must mint ONE fresh trace
        r = rebuild_from_peers_rpc(cluster, holder, vid)
        assert list(r.rebuilt_shard_ids) == [0]

        docs = trace.traces()
        rebuild_roots = [
            d for d in docs if d["op"] == "rpc.ec_shards_rebuild"
        ]
        assert len(rebuild_roots) == 1
        root = rebuild_roots[0]
        tid = root["trace_id"]
        assert root["server"] == f"localhost:{holder.port}"
        assert root["attrs"]["from_peers"] is True
        # the whole heal hangs off the RPC root on the holder side
        child_ops = {ch["op"] for ch in root["children"]}
        assert "ec.peer_rebuild" in child_ops

        # every peer shard-read stream adopted the SAME trace id and
        # landed on the OTHER server — k(10) - 3 good local = 7 fetches
        reads = [
            d for d in docs
            if d["op"] == "rpc.ec_shard_read" and d["trace_id"] == tid
        ]
        assert len(reads) >= 7
        assert {d["server"] for d in reads} == {
            f"localhost:{other.port}"
        }
        assert all("stream" in d["stages"] for d in reads)
        # parent linkage points back into the holder's span tree
        holder_span_ids = set()
        def _collect(d):
            holder_span_ids.add(d["span_id"])
            for ch in d["children"]:
                _collect(ch)
        _collect(root)
        assert all(d["parent_span_id"] in holder_span_ids for d in reads)
        # request id minted once, continuous across both servers
        rids = {root["request_id"]} | {d["request_id"] for d in reads}
        assert len(rids) == 1 and "" not in rids

        # /debug/traces: valid Chrome trace_event JSON with both
        # servers as process rows for this one trace id
        resp = requests.get(
            f"http://localhost:{holder.port}/debug/traces",
            params={"trace_id": tid},
        )
        assert resp.status_code == 200
        evs = resp.json()["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        assert any(e["cat"] == "rpc.ec_shards_rebuild" for e in xs)
        assert any(e["cat"] == "rpc.ec_shard_read" for e in xs)
        assert len({e["pid"] for e in xs}) >= 2  # holder + peer rows
        for e in xs:
            assert e["dur"] > 0 and e["args"]["trace_id"] == tid
        spans = requests.get(
            f"http://localhost:{holder.port}/debug/traces",
            params={"trace_id": tid, "format": "spans"},
        ).json()
        assert {d["op"] for d in spans} >= {
            "rpc.ec_shards_rebuild", "rpc.ec_shard_read",
        }

        # per-stage histograms + overlap gauge for the three op classes
        text = REGISTRY.render().decode()
        for op in ("ec.encode", "ec.rebuild", "ec.degraded_read"):
            assert f'op="{op}"' in text, op
        assert 'sw_ec_overlap_efficiency{op="ec.encode"}' in text
        assert 'sw_ec_overlap_efficiency{op="ec.rebuild"}' in text
    finally:
        trace.configure(enabled=False)
        trace.reset()


# ------------------------------------------- armed RPC faults (tier-1)


def test_peer_death_mid_stream_retries_and_converges(cluster):
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    quarantine(holder, vid, base, 1)
    wait_for(lambda: not cluster.locs(vid).get(1), msg="hb")
    with faults.injected(
        "server.ec_shard_read",
        faults.io_error("peer died mid-stream"),
        when=faults.every(3),
    ) as h:
        r = rebuild_from_peers_rpc(cluster, holder, vid)
    assert h.fired >= 1, "the peer-death fault never fired"
    assert list(r.rebuilt_shard_ids) == [1]
    assert open(base + ".ec01", "rb").read() == ground[1]


def test_latency_spike_on_peer_reads_converges(cluster):
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    quarantine(holder, vid, base, 2)
    wait_for(lambda: not cluster.locs(vid).get(2), msg="hb")
    with faults.injected(
        "server.ec_shard_read", faults.latency(0.05), when=faults.every(2)
    ) as h:
        r = rebuild_from_peers_rpc(cluster, holder, vid)
    assert h.fired >= 1
    assert list(r.rebuilt_shard_ids) == [2]
    assert open(base + ".ec02", "rb").read() == ground[2]


def test_corrupt_peer_stream_refuses_clean_then_heals(cluster):
    """The only sibling holder persistently serves corrupt bytes: the
    client's sidecar verification excludes it, exclusion leaves < k
    reachable sources, and the rebuild refuses CLEANLY over the RPC —
    no partial publish, no staging litter, no wedge. Disarming the
    fault and re-running converges bit-exact."""
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    quarantine(holder, vid, base, 3)
    wait_for(lambda: not cluster.locs(vid).get(3), msg="hb")
    with faults.injected(
        "server.ec_shard_read", faults.bit_flip(seed=0xBAD, flips=4)
    ):
        with pytest.raises(grpc.RpcError) as ei:
            rebuild_from_peers_rpc(cluster, holder, vid)
    assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    assert "refusing" in ei.value.details()
    assert not os.path.exists(base + ".ec03"), "partial publish!"
    assert not os.path.exists(staging_dir(base)), "staging litter"
    # registry disarmed (context manager): the same call now converges
    r = rebuild_from_peers_rpc(cluster, holder, vid)
    assert list(r.rebuilt_shard_ids) == [3]
    assert open(base + ".ec03", "rb").read() == ground[3]
    got = requests.get(f"http://localhost:{holder.port}/{fid}").content
    assert got == payload


def test_crash_during_distribute_rerun_no_duplicates(cluster):
    """A cluster-lost shard is rebuilt on the BIG holder (so the
    placement planner routes the regenerated shard to the smaller
    peer), and the rebuilder CRASHES after the destination mounted the
    copy but before the local handoff file was cleaned. The re-run must
    converge to EXACTLY ONE holder — finishing the handoff by deleting
    the local duplicate, never copying to a second destination."""
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    # lose shard 13 cluster-wide (it lived on `other`, the big holder)
    st_o = cluster.stub(other)
    st_o.VolumeEcShardsUnmount(
        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    st_o.VolumeEcShardsDelete(
        pb.EcShardsDeleteRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    wait_for(lambda: not cluster.locs(vid).get(13), msg="shard13 not lost")

    with faults.injected(
        "ec.peer_rebuild.after_distribute", faults.crash(),
        when=faults.nth_call(1),
    ) as h:
        # in-process call so the InjectedCrash (a BaseException) models
        # the process dying inside the distribute window; the big
        # holder (9 local shards) rebuilds, the planner picks the
        # 4-shard subset holder as the destination
        with pytest.raises(faults.InjectedCrash):
            other.peer_fetch_rebuild(vid)
    assert h.fired == 1, "crash window never reached (no distribution?)"
    # crash state: destination mounted the shard, rebuilder still has
    # the unmounted handoff file on disk
    obase = other.service._ec_base(vid, "")
    assert os.path.exists(obase + ".ec13"), "handoff file missing"
    wait_for(
        lambda: len(cluster.locs(vid).get(13, [])) >= 1,
        msg="no holder advertises shard 13 after crash window",
    )
    # re-run: idempotent convergence, no second copy
    out = other.peer_fetch_rebuild(vid)
    assert 13 not in out["rebuilt"], "re-run must not regenerate again"
    wait_for(
        lambda: len(cluster.locs(vid).get(13, [])) == 1,
        msg="shard 13 not at exactly one holder",
    )
    copies = 0
    for vs in cluster.vols:
        b = vs.service._ec_base(vid, "")
        if b and os.path.exists(b + ".ec13"):
            assert open(b + ".ec13", "rb").read() == ground[13]
            copies += 1
    assert copies == 1, f"{copies} on-disk copies of shard 13 (want 1)"


# ------------------------------------- fleet scrub control loop (tier-1)


def test_fleet_scrub_dispatches_peer_fetch_and_heals(cluster):
    """The whole loop: fleet scrub task -> per-holder scrub over gRPC ->
    unrebuildable holder detected (quarantined shard, < k good local) ->
    master dispatches ec_rebuild -fromPeers -> worker drives the RPC ->
    shard healed bit-exact; aggregation lands in /cluster/status and the
    fleet gauges."""
    from seaweedfs_tpu.worker.worker import Worker

    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    w = Worker(master=f"localhost:{cluster.mport}", backend="cpu")
    wt = threading.Thread(target=w.run, daemon=True)
    wt.start()
    try:
        wait_for(
            lambda: cluster.master.worker_control._workers,
            msg="worker did not register",
        )
        quarantine(holder, vid, base, 0)
        wait_for(lambda: not cluster.locs(vid).get(0), msg="hb")
        tids = cluster.master.worker_control.scan_for_ec_scrub(
            cluster.master.topo, 0.001
        )
        assert tids, "fleet scanner submitted nothing"
        # second sweep within the period: volume not due again
        assert not cluster.master.worker_control.scan_for_ec_scrub(
            cluster.master.topo, 3600.0
        )
        wait_for(
            lambda: cluster.master.worker_control.scrub_reports.get(vid),
            timeout=60,
            msg="scrub report never aggregated",
        )
        summary = cluster.master.worker_control.scrub_summary()
        assert vid in summary["unrebuildable_volumes"], summary
        hrep = summary["reports"][vid]["holders"][
            f"localhost:{holder.port}"
        ]
        assert hrep["quarantined"] == [0] and hrep["unrebuildable"]
        wait_for(
            lambda: 0 in (holder.store.find_ec_volume(vid).shard_fds),
            timeout=60,
            msg="dispatched peer-fetch rebuild never healed the shard",
        )
        assert open(base + ".ec00", "rb").read() == ground[0]
        cs = requests.get(
            f"http://localhost:{cluster.mport}/cluster/status"
        ).json()
        assert cs["EcFleetScrub"]["volumes"] >= 1
        assert vid in {
            int(k) for k in cs["EcFleetScrub"]["reports"]
        }
        _, tasks = cluster.master.worker_control.snapshot()
        kinds = {t["kind"]: t["state"] for t in tasks}
        assert kinds.get("ec_scrub") == "done"
        wait_for(
            lambda: any(
                t["kind"] == "ec_rebuild" and t["state"] == "done"
                for t in cluster.master.worker_control.snapshot()[1]
            ),
            timeout=30,
            msg="ec_rebuild task did not finish",
        )
        # next scrub period: the holder is healed — the forensic .bad
        # file still on disk must NOT mark it quarantined/unrebuildable
        # again, or the fleet loop would dispatch a no-op rebuild at it
        # every period forever
        assert os.path.exists(base + ".ec00.bad"), "forensic copy gone"
        before = sum(
            1
            for t in cluster.master.worker_control.snapshot()[1]
            if t["kind"] == "ec_rebuild"
        )
        ts0 = cluster.master.worker_control.scrub_reports[vid]["ts"]
        assert cluster.master.worker_control.scan_for_ec_scrub(
            cluster.master.topo, 0.001
        ), "second-period scan submitted nothing"
        wait_for(
            lambda: cluster.master.worker_control.scrub_reports[vid]["ts"]
            > ts0,
            timeout=60,
            msg="second scrub report never aggregated",
        )
        hrep2 = cluster.master.worker_control.scrub_reports[vid][
            "holders"
        ][f"localhost:{holder.port}"]
        assert hrep2["quarantined"] == [], hrep2
        assert not hrep2["unrebuildable"], hrep2
        after = sum(
            1
            for t in cluster.master.worker_control.snapshot()[1]
            if t["kind"] == "ec_rebuild"
        )
        assert after == before, "healed holder was dispatched at again"
    finally:
        w.stop()


def test_failed_distribute_leftover_not_mounted_by_task_driver(cluster):
    """When distributing a regenerated cluster-lost shard fails (dest
    unreachable), the handoff copy stays on the rebuilder's disk but
    must remain UNMOUNTED and unadvertised — the worker task driver
    must not blanket-mount it (that would advertise a holder whose copy
    the next run's dedupe pass then unlinks). A re-run with the dest
    healthy finishes the handoff to exactly one holder."""
    from seaweedfs_tpu.worker.worker import Worker

    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    # lose shard 13 cluster-wide; `other` (the big holder) rebuilds it,
    # so the planner routes the regenerated copy at the subset holder
    st_o = cluster.stub(other)
    st_o.VolumeEcShardsUnmount(
        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    st_o.VolumeEcShardsDelete(
        pb.EcShardsDeleteRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    wait_for(lambda: not cluster.locs(vid).get(13), msg="shard13 not lost")

    class _CopyDown(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return "injected: destination down"

    real_stub = other._peer_stub

    class _Proxy:
        def __init__(self, stub):
            self._stub = stub

        def __getattr__(self, name):
            if name == "VolumeEcShardsCopy":
                def _boom(*a, **k):
                    raise _CopyDown()
                return _boom
            return getattr(self._stub, name)

    w = Worker(master=f"localhost:{cluster.mport}", backend="cpu")
    wt = threading.Thread(target=w.run, daemon=True)
    wt.start()
    try:
        wait_for(
            lambda: cluster.master.worker_control._workers,
            msg="worker did not register",
        )
        other._peer_stub = lambda dest: _Proxy(real_stub(dest))
        try:
            cluster.master.worker_control.submit(
                "ec_rebuild",
                vid,
                "",
                params={
                    "fromPeers": "true",
                    "holder": f"localhost:{other.grpc_port}",
                },
            )
            wait_for(
                lambda: any(
                    t["kind"] == "ec_rebuild" and t["state"] == "done"
                    for t in cluster.master.worker_control.snapshot()[1]
                ),
                timeout=60,
                msg="ec_rebuild task did not finish",
            )
        finally:
            other._peer_stub = real_stub
        obase = other.service._ec_base(vid, "")
        assert os.path.exists(obase + ".ec13"), "handoff copy not kept"
        ev_o = other.store.find_ec_volume(vid)
        assert 13 not in ev_o.shard_fds, (
            "task driver mounted the failed-handoff copy"
        )
        time.sleep(1.5)  # a heartbeat round: it must NOT advertise 13
        assert not cluster.locs(vid).get(13), (
            "failed-handoff copy was advertised to the master"
        )
        # dest healthy again: re-run finishes the handoff, one holder
        cluster.master.worker_control.submit(
            "ec_rebuild",
            vid,
            "",
            params={
                "fromPeers": "true",
                "holder": f"localhost:{other.grpc_port}",
            },
        )
        wait_for(
            lambda: len(cluster.locs(vid).get(13, [])) == 1,
            timeout=60,
            msg="handoff never completed to exactly one holder",
        )
        wait_for(
            lambda: not os.path.exists(obase + ".ec13"),
            msg="local handoff copy not cleaned after successful handoff",
        )
        hbase = holder.service._ec_base(vid, "")
        assert open(hbase + ".ec13", "rb").read() == ground[13]
    finally:
        other._peer_stub = real_stub
        w.stop()


def test_distribute_replans_to_surviving_holder_in_pass(cluster3):
    """ISSUE-8 satellite: the FIRST planned destination dies mid-copy
    and the distribute step re-plans IN THE SAME RUN — the regenerated
    cluster-lost shard lands on exactly one SURVIVING alternate holder,
    no deferred handoff, and a re-run is an idempotent no-op."""
    c = cluster3
    vid, fid, payload, holder, other, ground = split_ec_volume(c)
    third = next(v for v in c.vols if v is not holder and v is not other)
    # lose shard 13 cluster-wide (it lived on `other`, the big holder)
    st_o = c.stub(other)
    st_o.VolumeEcShardsUnmount(
        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    st_o.VolumeEcShardsDelete(
        pb.EcShardsDeleteRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    wait_for(lambda: not cluster3.locs(vid).get(13), msg="shard13 not lost")

    # `third` holds ZERO shards of this volume, so the planner picks it
    # first; every copy to it fails as if it died mid-distribute
    third_grpc = f"localhost:{third.grpc_port}"
    failed = {"n": 0}

    class _CopyDown(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return "injected: destination died mid-copy"

    real_stub = other._peer_stub

    def picky_stub(dest):
        stub = real_stub(dest)
        if dest != third_grpc:
            return stub

        class _Proxy:
            def __getattr__(self, name):
                if name == "VolumeEcShardsCopy":
                    def _boom(*a, **k):
                        failed["n"] += 1
                        raise _CopyDown()
                    return _boom
                return getattr(stub, name)

        return _Proxy()

    other._peer_stub = picky_stub
    try:
        out = other.peer_fetch_rebuild(vid)
    finally:
        other._peer_stub = real_stub
    assert failed["n"] == 1, "first destination never tried"
    # the SAME run re-planned and finished the handoff elsewhere
    assert out["distributed"] == [13], out
    wait_for(
        lambda: len(c.locs(vid).get(13, [])) == 1,
        msg="shard 13 not at exactly one holder after in-pass re-plan",
    )
    assert c.locs(vid)[13] == [f"localhost:{holder.port}"], (
        "re-plan must land on the surviving subset holder"
    )
    copies = 0
    for vs in c.vols:
        b = vs.service._ec_base(vid, "")
        if b and os.path.exists(b + ".ec13"):
            assert open(b + ".ec13", "rb").read() == ground[13]
            copies += 1
    assert copies == 1, f"{copies} on-disk copies of shard 13 (want 1)"
    # idempotent re-run: nothing left to regenerate or distribute
    out2 = other.peer_fetch_rebuild(vid)
    assert 13 not in out2["rebuilt"] and not out2["distributed"]


def test_distribute_mount_failure_cleans_dest_copy(cluster3):
    """Copy SUCCEEDS but the mount fails: the re-plan must not leave a
    latent duplicate on the failed destination — the distribute step
    issues a best-effort delete before excluding it, so the shard ends
    at exactly one holder with exactly one on-disk copy cluster-wide."""
    c = cluster3
    vid, fid, payload, holder, other, ground = split_ec_volume(c)
    third = next(v for v in c.vols if v is not holder and v is not other)
    st_o = c.stub(other)
    st_o.VolumeEcShardsUnmount(
        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    st_o.VolumeEcShardsDelete(
        pb.EcShardsDeleteRequest(volume_id=vid, shard_ids=[13]), timeout=30
    )
    wait_for(lambda: not cluster3.locs(vid).get(13), msg="shard13 not lost")

    third_grpc = f"localhost:{third.grpc_port}"

    class _MountDown(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.DEADLINE_EXCEEDED

        def details(self):
            return "injected: mount timed out"

    real_stub = other._peer_stub

    def picky_stub(dest):
        stub = real_stub(dest)
        if dest != third_grpc:
            return stub

        class _Proxy:
            def __getattr__(self, name):
                if name == "VolumeEcShardsMount":
                    def _boom(*a, **k):
                        raise _MountDown()
                    return _boom
                return getattr(stub, name)

        return _Proxy()

    other._peer_stub = picky_stub
    try:
        out = other.peer_fetch_rebuild(vid)
    finally:
        other._peer_stub = real_stub
    assert out["distributed"] == [13], out
    wait_for(
        lambda: len(c.locs(vid).get(13, [])) == 1,
        msg="shard 13 not at exactly one holder",
    )
    # the failed destination's copied files were cleaned: exactly one
    # on-disk copy anywhere (a later mount on `third` can no longer
    # resurrect a duplicate holder)
    tbase = third.service._ec_base(vid, "")
    assert tbase is None or not os.path.exists(tbase + ".ec13"), (
        "copy left on the mount-failed destination"
    )
    copies = 0
    for vs in c.vols:
        b = vs.service._ec_base(vid, "")
        if b and os.path.exists(b + ".ec13"):
            assert open(b + ".ec13", "rb").read() == ground[13]
            copies += 1
    assert copies == 1, f"{copies} on-disk copies of shard 13 (want 1)"


def test_concurrent_peer_rebuild_refuses_cleanly(cluster):
    """Only one peer-fetch rebuild per volume runs on a server at a
    time: a second concurrent call (shell racing the fleet dispatcher)
    would wipe the first call's staging mid-flight, so it refuses with
    a clean ECError instead."""
    import threading as _threading

    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    busy = holder._peer_rebuild_busy.setdefault(vid, _threading.Lock())
    busy.acquire()
    try:
        with pytest.raises(ECError, match="already"):
            holder.peer_fetch_rebuild(vid)
    finally:
        busy.release()
    # released: the same call now proceeds (nothing to rebuild is fine)
    out = holder.peer_fetch_rebuild(vid)
    assert out["rebuilt"] == []


def test_total_loss_holder_flagged_unrebuildable_and_healed(cluster):
    """A holder whose EVERY shard file is gone (sidecar survives, fds
    still advertised) checks zero shards — the fleet scrub must report
    it all-missing/unrebuildable, not healthy, and the dispatched
    peer-fetch rebuild restores all of its shards bit-exact."""
    from seaweedfs_tpu.worker.worker import Worker

    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    for sid in KEEP_LOCAL:
        os.remove(base + f".ec{sid:02d}")
    w = Worker(master=f"localhost:{cluster.mport}", backend="cpu")
    wt = threading.Thread(target=w.run, daemon=True)
    wt.start()
    try:
        wait_for(
            lambda: cluster.master.worker_control._workers,
            msg="worker did not register",
        )
        tids = cluster.master.worker_control.scan_for_ec_scrub(
            cluster.master.topo, 0.001
        )
        assert tids, "fleet scanner submitted nothing"
        wait_for(
            lambda: cluster.master.worker_control.scrub_reports.get(vid),
            timeout=60,
            msg="scrub report never aggregated",
        )
        hrep = cluster.master.worker_control.scrub_reports[vid]["holders"][
            f"localhost:{holder.port}"
        ]
        assert hrep["missing"] == KEEP_LOCAL, hrep
        assert hrep["unrebuildable"], (
            "total-loss holder reported as rebuildable/healthy"
        )
        wait_for(
            lambda: all(
                os.path.exists(base + f".ec{sid:02d}")
                for sid in KEEP_LOCAL
            ),
            timeout=60,
            msg="dispatched peer-fetch rebuild never restored the shards",
        )
        for sid in KEEP_LOCAL:
            assert open(base + f".ec{sid:02d}", "rb").read() == ground[sid]
        got = requests.get(f"http://localhost:{holder.port}/{fid}").content
        assert got == payload
    finally:
        w.stop()


def test_rotten_handoff_leftover_regenerated_but_never_mounted(cluster):
    """A leftover handoff copy that ROTTED on disk (canonical filename,
    unmounted, outside this server's legitimate set) is replaced by the
    rebuild's verify-and-exclude pass, but must never be mounted or
    advertised here — the dedupe pass hands it back to the holder that
    already serves it. Mounting it would advertise a second holder whose
    file the same call then unlinks."""
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    # quarantine shard 0 so the rebuild has legitimate work
    quarantine(holder, vid, base, 0)
    wait_for(lambda: not cluster.locs(vid).get(0), msg="hb")
    # plant a rotten leftover of shard 13 (still served by `other`)
    rot = bytearray(ground[13])
    rot[7] ^= 0xFF
    with open(base + ".ec13", "wb") as f:
        f.write(rot)
    out = holder.peer_fetch_rebuild(vid)
    assert 0 in out["rebuilt"], out
    ev = holder.store.find_ec_volume(vid)
    assert 0 in ev.shard_fds, "quarantined shard not remounted"
    assert 13 not in ev.shard_fds, (
        "non-legitimate regenerated shard was mounted"
    )
    assert not os.path.exists(base + ".ec13"), (
        "dedupe pass did not clean the leftover"
    )
    assert cluster.locs(vid).get(13) == [f"localhost:{other.port}"]
    assert open(base + ".ec00", "rb").read() == ground[0]


# ------------------------------------------------ randomized soak (slow)


@pytest.mark.slow
def test_randomized_multi_fault_soak(cluster):
    """Random fault cocktails over the peer-rebuild RPC path: every
    round must converge bit-exact or refuse cleanly — wrong bytes on
    disk after a claimed success is a silent-corruption bug."""
    vid, fid, payload, holder, other, ground = split_ec_volume(cluster)
    base = holder.service._ec_base(vid, "")
    rng = np.random.default_rng(0x50AC)
    for round_i in range(5):
        sid = int(rng.integers(0, 4))
        path = base + f".ec{sid:02d}"
        if os.path.exists(path):
            quarantine(holder, vid, base, sid)
            wait_for(lambda: not cluster.locs(vid).get(sid), msg="hb")
        handles = []
        for point in ("server.ec_shard_read", "ec.peer_fetch.read"):
            roll = rng.random()
            if roll < 0.35:
                handles.append(
                    faults.inject(
                        point,
                        faults.io_error("soak"),
                        when=faults.probability(
                            0.3, seed=int(rng.integers(1 << 30))
                        ),
                    )
                )
            elif roll < 0.6:
                handles.append(
                    faults.inject(
                        point,
                        faults.bit_flip(
                            seed=int(rng.integers(1 << 30)), flips=2
                        ),
                        when=faults.probability(
                            0.3, seed=int(rng.integers(1 << 30))
                        ),
                    )
                )
        refused = False
        try:
            rebuild_from_peers_rpc(cluster, holder, vid, timeout=180)
        except grpc.RpcError as e:
            refused = True
            assert e.code() == grpc.StatusCode.FAILED_PRECONDITION, e
        finally:
            for h in handles:
                h.remove()
        if os.path.exists(path):
            assert open(path, "rb").read() == ground[sid], (
                f"round {round_i}: SILENT CORRUPTION on shard {sid} "
                f"(refused={refused})"
            )
        else:
            assert refused, "no publish without refusal"
            # disarmed retry must converge before the next round
            rebuild_from_peers_rpc(cluster, holder, vid, timeout=180)
            assert open(path, "rb").read() == ground[sid]
        assert not os.path.exists(staging_dir(base)), "staging litter"
    # final state: everything mounted and the payload reads back
    got = requests.get(f"http://localhost:{holder.port}/{fid}").content
    assert got == payload
