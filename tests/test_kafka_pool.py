"""Kafka data-plane fleet machinery (ISSUE 20): the bounded worker-pool
frame server, saturation backpressure, broker group commit over
durable parity, the zero-copy fetch spool, gravity-aware partition
assignment, and SQL scans racing live Kafka produce.

The pool tests drive the gateway over real sockets — well-formedness
of saturation responses is asserted byte-by-byte with a raw framing
helper, because the whole point is that a stock client parser must
never choke on a reject."""

import json
import multiprocessing
import os
import socket
import struct
import threading
import time
import urllib.request

import pytest

from conftest import allocate_port
from seaweedfs_tpu.faults import registry as faults
from seaweedfs_tpu.mq.broker import MqBroker, MqBrokerServer, MqService
from seaweedfs_tpu.mq.kafka import protocol as kp
from seaweedfs_tpu.mq.kafka.client import KafkaClient, KafkaError
from seaweedfs_tpu.mq.kafka.frame_pool import _native_mod
from seaweedfs_tpu.mq.kafka.gateway import KafkaGateway
from seaweedfs_tpu.mq.kafka.protocol import Reader, Writer
from seaweedfs_tpu.mq.kafka.records import Record, encode_batch

# ------------------------------------------------------------- helpers


def _raw_call(port: int, api_key: int, version: int, body: bytes):
    """One request frame on a fresh connection; returns (Reader past
    the correlation id, sock) — the caller closes the sock."""
    s = socket.create_connection(("localhost", port), timeout=10)
    frame = (
        Writer()
        .i16(api_key)
        .i16(version)
        .i32(7)
        .nullable_string("raw")
        .done()
        + body
    )
    s.sendall(struct.pack(">i", len(frame)) + frame)
    head = _recv_exact(s, 4)
    (size,) = struct.unpack(">i", head)
    r = Reader(_recv_exact(s, size))
    assert r.i32() == 7  # correlation id
    return r, s


def _recv_exact(s: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        assert chunk, f"peer closed mid-read ({len(buf)}/{n})"
        buf += chunk
    return buf


def _produce_v3_body(topic: str, part: int, blob: bytes) -> bytes:
    return (
        Writer()
        .nullable_string(None)  # transactional_id
        .i16(-1)  # acks
        .i32(10_000)  # timeout_ms
        .array(
            [topic],
            lambda w, t: w.string(t).array(
                [part], lambda w2, p: w2.i32(p).bytes_(blob)
            ),
        )
        .done()
    )


def _fetch_v4_body(topic: str, part: int, offset: int) -> bytes:
    return (
        Writer()
        .i32(-1)  # replica_id
        .i32(0)  # max_wait_ms
        .i32(1)  # min_bytes
        .i32(1 << 20)  # max_bytes
        .i8(0)  # isolation_level
        .array(
            [topic],
            lambda w, t: w.string(t).array(
                [part],
                lambda w2, p: w2.i32(p).i64(offset).i32(1 << 20),
            ),
        )
        .done()
    )


@pytest.fixture
def kafka_broker():
    srv = MqBrokerServer(
        ip="localhost", grpc_port=allocate_port(), kafka_port=0
    )
    srv.start()
    yield srv
    srv.stop()


# ------------------------------------------------- connection hygiene


def test_oversized_length_prefix_closes_before_allocation(kafka_broker):
    """An adversarial 1 GiB frame prefix must cost the server 4 bytes
    of reading — the connection closes without the payload ever being
    allocated, and the pool keeps serving others."""
    port = kafka_broker.kafka.port
    for prefix in (1 << 30, -5, 0):
        s = socket.create_connection(("localhost", port), timeout=5)
        s.sendall(struct.pack(">i", prefix))
        s.settimeout(5)
        assert s.recv(1) == b"", f"prefix {prefix} not rejected"
        s.close()
    # the server survived all three
    c = KafkaClient("localhost", port)
    assert kp.PRODUCE in c.api_versions
    c.close()


def test_mid_frame_death_is_bounded(kafka_broker):
    """A peer dying mid-frame (prefix promised more than it sent) must
    cost one read timeout on one worker, not a wedged thread."""
    port = kafka_broker.kafka.port
    s = socket.create_connection(("localhost", port), timeout=5)
    s.sendall(struct.pack(">i", 100) + b"short")
    s.close()  # die mid-frame
    # pool still serves a full round trip afterwards
    c = KafkaClient("localhost", port)
    c.create_topic("hygiene", partitions=1)
    base = c.produce("hygiene", 0, [Record(key=b"k", value=b"v")])
    assert base == 0
    _hw, recs = c.fetch("hygiene", 0, 0)
    assert [r.value for r in recs] == [b"v"]
    c.close()


# --------------------------------------------------------- saturation


def test_saturation_rejects_are_well_formed(monkeypatch):
    """Past the admission budget, produce and fetch get their NORMAL
    response shape carrying a retriable REQUEST_TIMED_OUT plus a
    non-zero throttle — then the connection closes. No partial frames,
    no silent thread growth, and the broker state is untouched."""
    monkeypatch.setenv("SEAWEED_MQ_KAFKA_QUEUE", "0")
    broker = MqBroker()
    broker.configure_topic("kafka", "sat", 1)
    gw = KafkaGateway(broker, port=0, workers=1)  # budget: 1 connection
    gw.start()
    holder = None
    try:
        holder = KafkaClient("localhost", gw.port)  # occupies the slot
        deadline = time.monotonic() + 5
        while gw.pool_status()["open_connections"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)

        blob = encode_batch([Record(key=b"k", value=b"v")], base_offset=0)
        r, s = _raw_call(
            gw.port, kp.PRODUCE, 3, _produce_v3_body("sat", 0, blob)
        )
        assert r.i32() == 1  # one topic
        assert r.string() == "sat"
        assert r.i32() == 1  # one partition
        assert r.i32() == 0  # partition index
        assert r.i16() == kp.REQUEST_TIMED_OUT
        assert r.i64() == -1  # no base offset assigned
        r.i64()  # log_append_time (v2+)
        assert r.i32() == 1000  # throttle_time_ms: explicit backpressure
        assert r.remaining() == 0
        assert s.recv(1) == b"", "reject connection must close"
        s.close()
        # nothing was appended
        assert broker.topic("kafka", "sat").logs[0].next_offset == 0

        r, s = _raw_call(
            gw.port, kp.FETCH, 4, _fetch_v4_body("sat", 0, 0)
        )
        assert r.i32() == 1000  # throttle
        assert r.i32() == 1  # one topic
        assert r.string() == "sat"
        assert r.i32() == 1  # one partition
        assert r.i32() == 0  # index
        assert r.i16() == kp.REQUEST_TIMED_OUT
        r.i64()  # high watermark
        r.i64()  # last stable
        assert r.i32() == 0  # aborted_transactions
        assert r.i32() == -1  # null records
        assert r.remaining() == 0
        assert s.recv(1) == b""
        s.close()

        st = gw.pool_status()
        assert st["rejected_total"] >= 2
        assert st["max_connections"] == 1
        # the admitted client still works end to end
        assert holder.produce("sat", 0, [Record(key=b"a", value=b"b")]) == 0
    finally:
        if holder is not None:
            holder.close()
        gw.stop()
        broker.close()


def test_32_clients_cross_connection_correctness(kafka_broker):
    """32 concurrent clients over a 16-worker pool: every client's
    records land on its own partition, dense and byte-exact — parking/
    dispatch never bleeds one connection's state into another's."""
    port = kafka_broker.kafka.port
    nclients, per = 32, 20
    setup = KafkaClient("localhost", port)
    setup.create_topic("fleet", partitions=nclients)
    setup.close()
    errors: list[BaseException] = []

    def run(idx: int) -> None:
        try:
            c = KafkaClient("localhost", port, client_id=f"c{idx}")
            for i in range(per):
                base = c.produce(
                    "fleet",
                    idx,
                    [Record(key=b"k%d" % i, value=b"c%d-%d" % (idx, i))],
                )
                assert base == i, (idx, i, base)
            _hw, recs = c.fetch("fleet", idx, 0, max_bytes=1 << 22)
            assert [r.value for r in recs] == [
                b"c%d-%d" % (idx, i) for i in range(per)
            ]
            c.close()
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(nclients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:3]
    st = kafka_broker.kafka.pool_status()
    assert st["frames_served"] >= nclients * (per + 1)


# ------------------------------------------------------- group commit


def _msg(i: int) -> tuple[bytes, bytes]:
    return b"key-%06d" % i, (b"val-%06d-" % i) * 8


def _gc_crash_child(pdir: str, port_file: str, acked_file: str,
                    grpc_port: int, kill_window: int) -> None:
    os.environ["SEAWEED_MQ_GROUP_COMMIT_MS"] = "10"
    faults.inject(
        "mq.produce.before_flush",
        faults.hard_exit(137),
        when=faults.nth_call(kill_window),
    )
    srv = MqBrokerServer(
        ip="localhost", grpc_port=grpc_port, kafka_port=0, parity_dir=pdir
    )
    srv.start()
    with open(port_file, "w") as f:
        f.write(str(srv.kafka.port))
    c = KafkaClient("localhost", srv.kafka.port)
    c.create_topic("gc", partitions=1)
    acked = open(acked_file, "w")
    for i in range(500):
        k, v = _msg(i)
        c.produce("gc", 0, [Record(key=k, value=v)], acks=-1)
        # the ack CERTIFIED durability — record it crash-consistently
        acked.write(f"{i}\n")
        acked.flush()
        os.fsync(acked.fileno())
    os._exit(0)  # not reached: the armed window kills us first


@pytest.mark.chaos
@pytest.mark.parametrize("kill_window", [1, 4])
def test_group_commit_acked_replayable_unacked_clean(tmp_path, kill_window):
    """Hard-kill the broker inside a group-commit window: every
    produce acked before the crash replays byte-exactly after restart
    (acked ⇒ durable), and whatever else survives is a dense prefix —
    unacked records never leave a torn or reordered tail."""
    pdir = str(tmp_path / "parity")
    port_file = str(tmp_path / "port")
    acked_file = str(tmp_path / "acked")
    mp = multiprocessing.get_context("fork")
    p = mp.Process(
        target=_gc_crash_child,
        args=(pdir, port_file, acked_file, allocate_port(), kill_window),
    )
    p.start()
    p.join(timeout=120)
    assert p.exitcode == 137, f"expected hard crash, got {p.exitcode}"
    acked = -1
    if os.path.exists(acked_file):
        lines = open(acked_file).read().split()
        if lines:
            acked = int(lines[-1])
    br = MqBroker(parity_dir=pdir)
    try:
        recs = br.topic("kafka", "gc").logs[0].read_from(
            0, max_records=10_000
        )
        # dense prefix from 0, byte-exact (the gateway stores keys and
        # values with its nullability marker — unwrap before comparing)
        from seaweedfs_tpu.mq.kafka.gateway import _unpack_null

        for n, (off, _ts, k, v) in enumerate(recs):
            assert off == n, f"replay not dense: offset {off} at {n}"
            assert (_unpack_null(k), _unpack_null(v)) == _msg(n), (
                f"record {n} corrupted"
            )
        # acked => replayable (the crash window certified nothing past
        # `acked`, and everything up to it)
        assert len(recs) >= acked + 1, (
            f"acked {acked + 1} records but only {len(recs)} replayed"
        )
    finally:
        br.close()


def test_group_commit_failed_window_fails_cohort(tmp_path, monkeypatch):
    """An I/O error inside the commit window must fail EVERY producer
    whose ack rode on that window (KAFKA_STORAGE_ERROR, retriable) —
    and the next window heals."""
    monkeypatch.setenv("SEAWEED_MQ_GROUP_COMMIT_MS", "20")
    srv = MqBrokerServer(
        ip="localhost",
        grpc_port=allocate_port(),
        kafka_port=0,
        parity_dir=str(tmp_path / "parity"),
    )
    srv.start()
    try:
        c = KafkaClient("localhost", srv.kafka.port)
        c.create_topic("cohort", partitions=1)
        c.produce("cohort", 0, [Record(key=b"warm", value=b"up")])
        with faults.injected(
            "mq.produce.before_flush", faults.io_error(), count=1
        ):
            with pytest.raises(KafkaError) as ei:
                c.produce("cohort", 0, [Record(key=b"k", value=b"v")])
            assert ei.value.code == kp.KAFKA_STORAGE_ERROR
        # the window after the failed one commits cleanly, offsets dense
        base = c.produce("cohort", 0, [Record(key=b"k2", value=b"v2")])
        _hw, recs = c.fetch("cohort", 0, 0)
        assert recs[-1].offset == base
        c.close()
    finally:
        srv.stop()


# --------------------------------------------------- zero-copy fetch


def _metric_value(name: str, **labels) -> float:
    from seaweedfs_tpu.utils.metrics import REGISTRY

    want = name
    if labels:
        inner = ",".join(
            f'{k}="{v}"' for k, v in sorted(labels.items())
        )
        want = f"{name}{{{inner}}}"
    for line in REGISTRY.render().decode().splitlines():
        if line.startswith(want + " "):
            return float(line.split()[-1])
    return 0.0


def test_fetch_spool_bit_identical_across_planes(monkeypatch):
    """Sealed segments egress through the fetch spool — via
    sn_send_file on the native plane, plain writes on the fallback —
    and the records a client decodes are IDENTICAL either way."""
    srv = MqBrokerServer(
        ip="localhost",
        grpc_port=allocate_port(),
        kafka_port=0,
        segment_records=64,
    )
    srv.start()
    try:
        c = KafkaClient("localhost", srv.kafka.port)
        c.create_topic("sealed", partitions=1)
        # memory-only brokers never seal; give the partition log a
        # spill store so segments rotate out of the tail like a
        # filer-backed deployment (dict-backed: content-identical)
        plog = srv.broker.topic("kafka", "sealed").logs[0]
        segs: dict[int, bytes] = {}
        plog._spill = segs.__setitem__
        plog._load = segs.get
        payload = bytes(range(256))
        for i in range(200):  # 3 sealed segments + live tail
            c.produce(
                "sealed", 0, [Record(key=b"k%03d" % i, value=payload)]
            )
        assert plog._tail_base >= 192 and segs

        def drain(client):
            out, off = [], 0
            while True:
                hw, recs = client.fetch(
                    "sealed", 0, off, max_wait_ms=0, max_bytes=1 << 22
                )
                if not recs:
                    break
                out.extend(recs)
                off = recs[-1].offset + 1
                if off >= 200:
                    break
            return [(r.offset, r.key, r.value) for r in out]

        monkeypatch.setenv("SEAWEED_EC_NATIVE", "0")
        py_recs = drain(c)
        monkeypatch.delenv("SEAWEED_EC_NATIVE")
        native_before = _metric_value(
            "sw_mq_fetch_bytes_total", plane="native"
        )
        c2 = KafkaClient("localhost", srv.kafka.port)
        nat_recs = drain(c2)
        c2.close()
        c.close()
        assert len(py_recs) == 200
        assert py_recs == nat_recs  # bit-identical across planes
        spool = srv.kafka.pool_status()["fetch_spool"]
        assert spool["builds"] >= 3  # the sealed segments went via spool
        if _native_mod() is not None:
            assert (
                _metric_value("sw_mq_fetch_bytes_total", plane="native")
                > native_before
            ), "native plane available but no native fetch bytes"
    finally:
        srv.stop()


# ------------------------------------------------------------ gravity


def test_gravity_assignment_swaps_only_past_hysteresis(monkeypatch):
    from seaweedfs_tpu.mq import balancer as bal

    b = bal.BrokerBalancer("a:1", ["a:1", "b:2"])
    try:
        lead, fol = b.assignment("ns", "t", 0)  # pure HRW, no telemetry
        # hotter leader within the margin: HRW ranking stands
        b._loads = {lead: 1.0, fol: 0.2}
        assert b.assignment("ns", "t", 0) == (lead, fol)
        # past the margin: the cooler broker takes the partition, the
        # HRW winner keeps the replica
        b._loads = {lead: 2.0, fol: 0.2}
        assert b.assignment("ns", "t", 0) == (fol, lead)
        # the margin is a live knob
        monkeypatch.setenv("SEAWEED_MQ_GRAVITY_HYSTERESIS", "5.0")
        assert b.assignment("ns", "t", 0) == (lead, fol)
        # missing telemetry on either side: never swap on a guess
        b._loads = {lead: 99.0}
        monkeypatch.delenv("SEAWEED_MQ_GRAVITY_HYSTERESIS")
        assert b.assignment("ns", "t", 0) == (lead, fol)
    finally:
        b.stop()


def test_broker_status_carries_load_score():
    broker = MqBroker()
    try:
        from seaweedfs_tpu.mq import balancer as bal

        b = bal.BrokerBalancer("a:1", ["a:1"])
        svc = MqService(broker, balancer=b, load_fn=lambda: 3.25)
        resp = svc.BrokerStatus(None, None)
        assert resp.load_score == 3.25
        # a broken load_fn degrades to 0, never fails the ping
        svc.load_fn = lambda: 1 / 0
        assert svc.BrokerStatus(None, None).load_score == 0.0
        b.stop()
    finally:
        broker.close()


# ------------------------------------------------------- status plane


def test_status_http_plane(kafka_broker):
    srv = MqBrokerServer(
        ip="localhost",
        grpc_port=allocate_port(),
        kafka_port=0,
        status_port=0,
    )
    srv.start()
    try:
        c = KafkaClient("localhost", srv.kafka.port)
        c.create_topic("obs", partitions=2)
        c.produce("obs", 0, [Record(key=b"k", value=b"v")])
        c.close()
        url = f"http://localhost:{srv.status_port}"
        st = json.load(urllib.request.urlopen(url + "/status"))
        assert st["kafka_pool"]["kind"] == "pooled"
        assert st["kafka_pool"]["workers"] >= 1
        assert {"namespace": "kafka", "name": "obs", "partitions": 2} in (
            st["topics"]
        )
        assert "load_score" in st and "broker_loads" in st
        body = urllib.request.urlopen(url + "/metrics").read().decode()
        assert "sw_mq_produce_bytes_total" in body
        assert "sw_mq_fetch_bytes_total" in body
        assert "sw_mq_group_commit_windows_total" in body
    finally:
        srv.stop()


# ------------------------------------- SQL scans vs. live Kafka produce


def test_sql_scan_under_concurrent_produce(kafka_broker):
    """A SQL consumer over a topic being produced to at full tilt:
    every scan sees a consistent count (monotone, never torn rows),
    and the final scan sees everything."""
    from seaweedfs_tpu.query.engine import QueryEngine

    port = kafka_broker.kafka.port
    c = KafkaClient("localhost", port)
    c.create_topic("events", partitions=2)
    engine = QueryEngine(kafka_broker.broker)
    total = 300
    done = threading.Event()

    def produce():
        try:
            for i in range(total):
                row = json.dumps({"seq": i, "by": "writer"}).encode()
                c.produce("events", i % 2, [Record(key=b"k", value=row)])
        finally:
            done.set()

    t = threading.Thread(target=produce)
    t.start()
    last = 0
    while not done.is_set():
        res = engine.execute("SELECT COUNT(*) FROM events")
        n = res.rows[0][0]
        assert n >= last, f"count went backwards: {last} -> {n}"
        last = n
    t.join(timeout=30)
    res = engine.execute("SELECT COUNT(*), MAX(seq) FROM events")
    assert res.rows[0][0] == total
    assert res.rows[0][1] == total - 1
    c.close()
