"""JWT write-authorization tests (reference weed/security/jwt.go +
volume_server_handlers_write.go maybeCheckJwtAuthorization)."""

import time

import pytest
import requests

from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.security import JwtError, sign_jwt, verify_jwt


from conftest import allocate_port as free_port


def test_jwt_roundtrip():
    tok = sign_jwt("k1", "3,1a2b3c4d")
    verify_jwt("k1", tok, "3,1a2b3c4d")
    # volume-scoped token covers any fid in the volume
    vol_tok = sign_jwt("k1", "3")
    verify_jwt("k1", vol_tok, "3,1a2b3c4d")
    with pytest.raises(JwtError):
        verify_jwt("k2", tok, "3,1a2b3c4d")  # wrong key
    with pytest.raises(JwtError):
        verify_jwt("k1", tok, "4,ffff0000")  # wrong fid
    with pytest.raises(JwtError):
        verify_jwt("k1", "garbage", "3,1a2b3c4d")
    expired = sign_jwt("k1", "3,1a2b3c4d", ttl_seconds=-5)
    with pytest.raises(JwtError):
        verify_jwt("k1", expired, "3,1a2b3c4d")


def test_jwt_enforced_cluster(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport, jwt_key="sekrit")
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
        jwt_key="sekrit",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    ops = Operations(f"localhost:{mport}", jwt_key="sekrit")
    try:
        # assign hands out a token; client upload uses it transparently
        fid = ops.upload(b"guarded payload")
        assert ops.read(fid) == b"guarded payload"
        # raw write without a token is rejected
        a = ops.master.assign()
        r = requests.post(
            f"http://{a.url}/{a.fid}", files={"file": ("x", b"nope")}
        )
        assert r.status_code == 401
        # with a forged token too
        bad = sign_jwt("wrongkey", a.fid)
        r = requests.post(
            f"http://{a.url}/{a.fid}",
            files={"file": ("x", b"nope")},
            headers={"Authorization": f"Bearer {bad}"},
        )
        assert r.status_code == 401
        # with the assign-issued token it succeeds
        r = requests.post(
            f"http://{a.url}/{a.fid}",
            files={"file": ("x", b"yes")},
            headers={"Authorization": f"Bearer {a.jwt}"},
        )
        assert r.status_code == 201
        # unauthenticated delete rejected; key-holding client succeeds
        r = requests.delete(f"http://{a.url}/{a.fid}")
        assert r.status_code == 401
        ops.delete(a.fid)
        assert requests.get(f"http://{a.url}/{a.fid}").status_code == 404
        # reads stay open (reference default: jwt guards writes)
        assert ops.read(fid) == b"guarded payload"
        # the gRPC port must not be a bypass: unauthenticated WriteNeedle
        # and DeleteNeedle are rejected; a key-holder's metadata passes
        import grpc

        from seaweedfs_tpu.pb import cluster_pb2 as pb
        from seaweedfs_tpu.pb import rpc as rpcmod
        from seaweedfs_tpu.storage.file_id import FileId

        f = FileId.parse(fid)
        with grpc.insecure_channel(f"localhost:{vs.grpc_port}") as ch:
            stub = rpcmod.volume_stub(ch)
            r = stub.WriteNeedle(
                pb.WriteNeedleRequest(
                    volume_id=f.volume_id, needle_id=999, cookie=1, data=b"x",
                    is_replicate=True,
                ),
                timeout=10,
            )
            assert r.error == "unauthorized"
            r = stub.DeleteNeedle(
                pb.DeleteNeedleRequest(
                    volume_id=f.volume_id, needle_id=f.needle_id, is_replicate=True
                ),
                timeout=10,
            )
            assert r.error == "unauthorized"
            md = (("authorization", f"Bearer {sign_jwt('sekrit', str(f.volume_id))}"),)
            r = stub.WriteNeedle(
                pb.WriteNeedleRequest(
                    volume_id=f.volume_id, needle_id=999, cookie=1, data=b"x",
                    is_replicate=True,
                ),
                timeout=10,
                metadata=md,
            )
            assert r.error == ""
        # a keyless client's delete raises instead of silently failing
        naive = Operations(f"localhost:{mport}")
        with pytest.raises(RuntimeError, match="401"):
            naive.delete(fid)
        naive.close()
        assert ops.read(fid) == b"guarded payload"
    finally:
        ops.close()
        vs.stop()
        master.stop()
