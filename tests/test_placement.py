"""Replica placement tests: XYZ honoring rack/DC labels (reference
volume_growth findEmptySlotsForOneVolume)."""

from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.server.topology import DataNode, Topology, _replica_copies


def node(nid, rack="r1", dc="dc1", slots=8):
    return DataNode(
        node_id=nid,
        ip="h" + nid,
        port=1,
        public_url=nid,
        grpc_port=2,
        rack=rack,
        data_center=dc,
        max_volume_count=slots,
    )


def build(topo, nodes):
    for n in nodes:
        topo.nodes[n.node_id] = n
        topo._tree_add_locked(n)  # plan_growth consults the DC/rack tree


def test_replica_copies():
    assert _replica_copies("") == 1
    assert _replica_copies("000") == 1
    assert _replica_copies("001") == 2
    assert _replica_copies("010") == 2
    assert _replica_copies("110") == 3


def test_same_rack_placement():
    topo = Topology()
    build(topo, [node("a"), node("b"), node("c", rack="r2")])
    got = topo.plan_growth("001")  # 1 extra copy same rack
    assert len(got) == 2
    assert got[0].rack == got[1].rack


def test_cross_rack_placement():
    topo = Topology()
    build(topo, [node("a"), node("b", rack="r2"), node("c", rack="r2")])
    got = topo.plan_growth("010")  # 1 copy on another rack
    assert len(got) == 2
    assert got[0].rack != got[1].rack
    assert got[0].data_center == got[1].data_center


def test_cross_dc_placement():
    topo = Topology()
    build(
        topo,
        [node("a"), node("b", dc="dc2", rack="r9"), node("c")],
    )
    got = topo.plan_growth("100")
    assert len(got) == 2
    assert got[0].data_center != got[1].data_center


def test_combined_placement():
    topo = Topology()
    build(
        topo,
        [
            node("a", rack="r1", dc="dc1"),
            node("b", rack="r1", dc="dc1"),
            node("c", rack="r2", dc="dc1"),
            node("d", rack="r3", dc="dc2"),
        ],
    )
    got = topo.plan_growth("111")  # 1 other-DC, 1 other-rack, 1 same-rack
    assert len(got) == 4
    primary = got[0]
    racks = [(n.data_center, n.rack) for n in got]
    assert sum(1 for dcr in racks if dcr == (primary.data_center, primary.rack)) == 2
    assert sum(1 for n in got if n.data_center != primary.data_center) == 1
    assert sum(
        1
        for n in got
        if n.data_center == primary.data_center and n.rack != primary.rack
    ) == 1


def test_unsatisfiable_placement():
    topo = Topology()
    build(topo, [node("a"), node("b")])  # one rack, one dc
    assert topo.plan_growth("010") == []  # needs another rack
    assert topo.plan_growth("100") == []  # needs another dc
    assert len(topo.plan_growth("001")) == 2


def test_full_nodes_excluded():
    topo = Topology()
    a, b = node("a"), node("b", slots=0)
    build(topo, [a, b])
    b.volumes[1] = pb.VolumeInfoMsg(id=1)
    assert topo.plan_growth("001") == []
    assert topo.plan_growth("") == [a]


def test_multi_dc_copies_land_on_distinct_dcs():
    """X>=2 requires each diff-DC copy on a DIFFERENT data center."""
    topo = Topology()
    build(
        topo,
        [
            node("a", dc="dc1"),
            node("b", dc="dc2"),
            node("c", dc="dc2"),
            node("d", dc="dc3"),
        ],
    )
    got = topo.plan_growth("200")
    assert len(got) == 3
    assert len({n.data_center for n in got}) == 3
    # only two DCs available for X=2 extras when dc3 is removed
    topo2 = Topology()
    build(topo2, [node("a", dc="dc1"), node("b", dc="dc2"), node("c", dc="dc2")])
    assert topo2.plan_growth("200") == []
