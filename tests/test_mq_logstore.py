"""MQ parquet archival + schema registry + SQL scan cap lift.

Reference: weed/mq/logstore (parquet archival of sealed segments),
weed/mq/schema (per-topic schema registry), and the query engine's
full-scan behavior (the pre-r4 1M-row cap silently truncated).
"""

import json
import time

import grpc
import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.mq.broker import MqBroker, MqBrokerServer
from seaweedfs_tpu.mq.logstore import (
    SegmentArchiver,
    parquet_stats,
    parquet_to_segment,
    segment_to_parquet,
)
from seaweedfs_tpu.mq.log_buffer import decode_records, encode_record
from seaweedfs_tpu.pb import mq_pb2 as mqpb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.query.engine import QueryEngine
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


def test_parquet_roundtrip_bit_exact():
    raw = b"".join(
        encode_record(i, 1_000_000 + i, f"k{i}".encode(), b"v" * (i % 7))
        for i in range(500)
    )
    pq = segment_to_parquet(raw)
    assert parquet_to_segment(pq) == raw
    st = parquet_stats(pq)
    assert st["rows"] == 500
    assert st["offset_min"] == 0 and st["offset_max"] == 499


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mqlog")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    fport = free_port()
    fsrv = FilerServer(filer, ip="localhost", port=fport)
    fsrv.start()
    yield fport
    fsrv.stop()
    filer.close()
    vs.stop()
    master.stop()


def test_archival_keeps_consumers_working(stack):
    """Sealed segments become parquet; offsets/records stay readable
    through the normal consume path AND survive broker recovery."""
    broker = MqBroker(filer=f"localhost:{stack}", segment_records=50)
    broker.configure_topic("default", "arch", 1)
    plog = broker.topic("default", "arch").logs[0]
    for i in range(175):  # 3 sealed segments + live tail
        plog.append(i + 1, b"", json.dumps({"i": i}).encode())

    arch = SegmentArchiver(broker, min_age_segments=1)
    n = arch.run_once()
    assert n >= 2  # oldest sealed segments archived

    # every record, including archived ones, reads back in order
    recs = []
    off = plog.earliest_offset
    while True:
        batch = plog.read_from(off, max_records=64)
        if not batch:
            break
        recs.extend(batch)
        off = batch[-1][0] + 1
    assert [r[0] for r in recs] == list(range(175))
    assert json.loads(recs[10][3]) == {"i": 10}

    # idempotent
    assert arch.run_once() == 0

    # recovery over archived segments preserves offsets (flush spills
    # the live tail; the archived prefix stays parquet-only)
    broker.flush()
    broker2 = MqBroker(filer=f"localhost:{stack}", segment_records=50)
    plog2 = broker2.topic("default", "arch").logs[0]
    assert plog2.next_offset == 175
    assert plog2.earliest_offset == 0
    first = plog2.read_from(0, max_records=4)
    assert [r[0] for r in first] == [0, 1, 2, 3]


def test_sql_scans_archived_data_past_old_cap(stack):
    """The SQL engine must see EVERY row of an archived topic — more
    rows than a tiny configured cap would have allowed, and the default
    engine has no cap at all."""
    broker = MqBroker(filer=f"localhost:{stack}", segment_records=100)
    broker.configure_topic("default", "big", 1)
    plog = broker.topic("default", "big").logs[0]
    total = 2500
    for i in range(total):
        plog.append(i + 1, b"", json.dumps({"n": i}).encode())
    SegmentArchiver(broker, min_age_segments=0).run_once()

    eng = QueryEngine(broker)  # default: unlimited
    r = eng.execute("SELECT COUNT(*) AS c FROM big")
    assert r.rows[0][0] == total
    r = eng.execute("SELECT MAX(n) AS m FROM big")
    assert r.rows[0][0] == total - 1
    # a positive cap is still honored as a guardrail
    capped = QueryEngine(broker, scan_limit=100)
    r = capped.execute("SELECT COUNT(*) AS c FROM big")
    assert r.rows[0][0] == 100


def test_schema_registry_and_enforcement(stack):
    srv = MqBrokerServer(
        ip="localhost",
        grpc_port=free_port(),
        filer=f"localhost:{stack}",
        archive_interval=0,
    )
    srv.start()
    try:
        ch = grpc.insecure_channel(f"localhost:{srv.grpc_port}")
        stub = rpc.Stub(ch, rpc.MQ_SERVICE)
        stub.ConfigureTopic(
            mqpb.ConfigureTopicRequest(
                topic=mqpb.Topic(namespace="default", name="typed"),
                partition_count=1,
            ),
            timeout=10,
        )
        schema = json.dumps(
            {
                "enforce": True,
                "fields": [
                    {"name": "id", "type": "int", "required": True},
                    {"name": "note", "type": "string"},
                ],
            }
        )
        r = stub.RegisterSchema(
            mqpb.RegisterSchemaRequest(
                topic=mqpb.Topic(namespace="default", name="typed"),
                schema_json=schema,
            ),
            timeout=10,
        )
        assert not r.error
        got = stub.GetSchema(
            mqpb.GetSchemaRequest(
                topic=mqpb.Topic(namespace="default", name="typed")
            ),
            timeout=10,
        )
        assert json.loads(got.schema_json)["enforce"] is True

        def publish(value: bytes):
            return stub.Publish(
                mqpb.PublishRequest(
                    topic=mqpb.Topic(namespace="default", name="typed"),
                    message=mqpb.DataMessage(key=b"", value=value),
                ),
                timeout=10,
            )

        ok = publish(json.dumps({"id": 1, "note": "fine"}).encode())
        assert not ok.error
        bad = publish(json.dumps({"note": "missing id"}).encode())
        assert "schema violation" in bad.error
        bad2 = publish(json.dumps({"id": "not-an-int"}).encode())
        assert "schema violation" in bad2.error
        bad3 = publish(b"\x00\x01 not json")
        assert "schema violation" in bad3.error

        # DESCRIBE uses the registered schema
        eng = QueryEngine(srv.broker)
        r = eng.execute("DESCRIBE typed")
        cols = dict(r.rows)
        assert cols.get("id") == "bigint" and cols.get("note") == "text"

        # schema survives a broker restart via the filer
        assert srv.broker.get_schema("default", "typed")
        broker2 = MqBroker(filer=f"localhost:{stack}")
        assert json.loads(broker2.get_schema("default", "typed"))["fields"]
        ch.close()
    finally:
        srv.stop()
