"""Filer extras: hardlinks, POSIX locks, per-entry TTL, TUS uploads.

References: weed/filer/filer_hardlink.go,
filer_grpc_server_posix_lock.go, filer TTL expiry,
weed/server/filer_server_tus_*.go.
"""

import time

import pytest
import requests

from conftest import allocate_port
from seaweedfs_tpu.filer.filer import Filer, FilerError
from seaweedfs_tpu.filer.filer_store import MemoryStore, NotFound
from seaweedfs_tpu.filer.locks import PosixLockManager
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fx")
    mport = allocate_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=allocate_port(),
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


@pytest.fixture
def filer(cluster):
    f = Filer(MemoryStore(), master=f"localhost:{cluster}")
    yield f
    f.close()


# ------------------------------------------------------------ hardlinks


def test_hardlink_shares_content_until_last_name(filer):
    data = b"H" * 10_000  # chunked, not inlined
    filer.write_file("/a.bin", data)
    filer.hard_link("/a.bin", "/b.bin")
    a = filer.find_entry("/a.bin")
    b = filer.find_entry("/b.bin")
    assert a.hard_link_id and a.hard_link_id == b.hard_link_id
    assert [c.fid for c in a.chunks] == [c.fid for c in b.chunks]
    assert filer.read_entry(b) == data
    # deleting one name keeps the content alive for the other
    filer.delete_entry("/a.bin")
    filer.flush_gc()
    assert filer.read_entry(filer.find_entry("/b.bin")) == data
    # deleting the last name reclaims the chunks
    fid = b.chunks[0].fid
    filer.delete_entry("/b.bin")
    filer.flush_gc()
    with pytest.raises(Exception):
        filer.ops.read(fid)


def test_hardlink_errors(filer):
    filer.write_file("/src.txt", b"x" * 1000)
    with pytest.raises(NotFound):
        filer.hard_link("/nodir", "/dst")  # missing source
    filer.hard_link("/src.txt", "/dst.txt")
    with pytest.raises(FilerError):
        filer.hard_link("/src.txt", "/dst.txt")  # destination exists
    from seaweedfs_tpu.filer.entry import new_entry

    filer.create_entry(new_entry("/adir", is_directory=True, mode=0o755))
    with pytest.raises(FilerError):
        filer.hard_link("/adir", "/dirlink")  # directory


def test_hardlink_survives_rename(filer):
    filer.write_file("/r1.bin", b"R" * 5000)
    filer.hard_link("/r1.bin", "/r2.bin")
    filer.rename("/r1.bin", "/moved.bin")
    moved = filer.find_entry("/moved.bin")
    assert moved.hard_link_id
    filer.delete_entry("/moved.bin")
    filer.flush_gc()
    assert filer.read_entry(filer.find_entry("/r2.bin")) == b"R" * 5000


# ---------------------------------------------------------- posix locks


def test_posix_lock_semantics():
    lm = PosixLockManager(default_lease=30)
    ok, _ = lm.lock("/f", "alice", 0, 100, exclusive=True)
    assert ok
    # overlapping exclusive from another owner: denied
    ok, who = lm.lock("/f", "bob", 50, 150, exclusive=True)
    assert not ok and who == "alice"
    # non-overlapping: granted
    ok, _ = lm.lock("/f", "bob", 100, 200, exclusive=True)
    assert ok
    # shared locks coexist...
    ok, _ = lm.lock("/g", "a", 0, 10, exclusive=False)
    ok2, _ = lm.lock("/g", "b", 0, 10, exclusive=False)
    assert ok and ok2
    # ...but block an exclusive
    ok, who = lm.lock("/g", "c", 0, 10, exclusive=True)
    assert not ok and who in ("a", "b")
    # same-owner relock replaces (upgrade in place)
    ok, _ = lm.lock("/f", "alice", 0, 100, exclusive=False)
    assert ok
    ok, _ = lm.lock("/f", "carol", 0, 50, exclusive=False)
    assert ok  # alice's range is now shared
    # unlock releases
    assert lm.unlock("/f", "alice", 0, 100) == 1
    assert lm.test("/f", 0, 50, exclusive=False) == ""


def test_posix_lock_lease_expiry():
    lm = PosixLockManager(default_lease=0.15)
    lm.lock("/lease", "gone-client", 0, 0, exclusive=True)
    assert lm.test("/lease") == "gone-client"
    time.sleep(0.2)
    assert lm.test("/lease") == ""  # dead client cannot wedge the file
    # renewal extends
    lm.lock("/lease2", "alive", 0, 0, exclusive=True, lease=0.2)
    time.sleep(0.12)
    assert lm.renew("/lease2", "alive", lease=0.5) == 1
    time.sleep(0.15)
    assert lm.test("/lease2") == "alive"


def test_lock_rpc_over_filer_grpc(filer):
    import grpc

    from seaweedfs_tpu.pb import filer_pb2 as fpb
    from seaweedfs_tpu.pb import rpc

    srv = FilerServer(filer, ip="localhost", port=allocate_port())
    srv.start()
    try:
        chan = grpc.insecure_channel(f"localhost:{srv.grpc_port}")
        stub = rpc.filer_stub(chan)
        r = stub.LockRange(
            fpb.LockRangeRequest(
                path="/x", owner="m1", exclusive=True, op=0
            )
        )
        assert r.granted
        r = stub.LockRange(
            fpb.LockRangeRequest(
                path="/x", owner="m2", exclusive=True, op=0
            )
        )
        assert not r.granted and r.conflict_owner == "m1"
        r = stub.LockRange(
            fpb.LockRangeRequest(path="/x", owner="m1", op=1)
        )
        assert r.granted and r.count == 1
        r = stub.LockRange(
            fpb.LockRangeRequest(
                path="/x", owner="m2", exclusive=True, op=0
            )
        )
        assert r.granted
        chan.close()
    finally:
        srv.stop()


# ----------------------------------------------------------- entry TTL


def test_entry_ttl_expires_on_read(filer):
    filer.write_file("/fleeting.txt", b"x" * 2000, ttl_sec=1)
    assert filer.find_entry("/fleeting.txt").attr.ttl_sec == 1
    # backdate creation instead of sleeping
    def age(e):
        e.attr.crtime -= 10

    filer.mutate_entry("/fleeting.txt", age)
    with pytest.raises(NotFound):
        filer.find_entry("/fleeting.txt")
    # the listing hides it too (and it is actually gone)
    assert "fleeting.txt" not in [
        e.name for e in filer.list_entries("/")
    ]


def test_entry_ttl_via_http(cluster, filer):
    srv = FilerServer(filer, ip="localhost", port=allocate_port())
    srv.start()
    try:
        base = f"http://localhost:{srv.port}"
        r = requests.post(base + "/ttl.txt?ttl=1h", data=b"keeps", timeout=10)
        assert r.status_code == 201
        assert filer.find_entry("/ttl.txt").attr.ttl_sec == 3600
        r = requests.post(base + "/ttl2.txt?ttl=oops", data=b"x", timeout=10)
        assert r.status_code == 400
    finally:
        srv.stop()


# ----------------------------------------------------------------- TUS


def test_tus_resumable_upload(cluster, filer):
    srv = FilerServer(filer, ip="localhost", port=allocate_port())
    srv.start()
    base = f"http://localhost:{srv.port}"
    tus = {"Tus-Resumable": "1.0.0"}
    try:
        r = requests.options(base + "/", timeout=10)
        assert r.headers["Tus-Version"] == "1.0.0"
        assert "creation" in r.headers["Tus-Extension"]

        payload = bytes(range(256)) * 300  # 76,800 bytes
        r = requests.post(
            base + "/uploads/final.bin",
            headers={**tus, "Upload-Length": str(len(payload))},
            timeout=10,
        )
        assert r.status_code == 201
        loc = r.headers["Location"]
        # patch in three chunks, with an offset probe between
        third = len(payload) // 3
        for i in range(3):
            chunk = payload[i * third :] if i == 2 else payload[
                i * third : (i + 1) * third
            ]
            head = requests.head(base + loc, headers=tus, timeout=10)
            assert int(head.headers["Upload-Offset"]) == i * third
            r = requests.patch(
                base + loc,
                headers={
                    **tus,
                    "Upload-Offset": str(i * third),
                    "Content-Type": "application/offset+octet-stream",
                },
                data=chunk,
                timeout=10,
            )
            assert r.status_code == 204, r.status_code
        # completed: target exists, session gone
        entry = filer.find_entry("/uploads/final.bin")
        assert filer.read_entry(entry) == payload
        r = requests.head(base + loc, headers=tus, timeout=10)
        assert r.status_code == 404
        # wrong offset is rejected with 409
        r = requests.post(
            base + "/uploads/x.bin",
            headers={**tus, "Upload-Length": "10"},
            timeout=10,
        )
        loc2 = r.headers["Location"]
        r = requests.patch(
            base + loc2,
            headers={**tus, "Upload-Offset": "5"},
            data=b"zzzzz",
            timeout=10,
        )
        assert r.status_code == 409
        # terminate aborts
        r = requests.delete(base + loc2, headers=tus, timeout=10)
        assert r.status_code == 204
        r = requests.head(base + loc2, headers=tus, timeout=10)
        assert r.status_code == 404
    finally:
        srv.stop()


def test_kv_put_if_absent_atomic(tmp_path):
    """First-boot keyring creation relies on create-if-absent: the
    first writer wins and every caller adopts the stored value
    (advisor r4 low: SSE master-key divergence)."""
    from seaweedfs_tpu.filer.filer_store import MemoryStore, SqliteStore

    for store in (MemoryStore(), SqliteStore(str(tmp_path / "kv.db"))):
        won = store.kv_put_if_absent(b"k", b"first")
        assert won == b"first"
        assert store.kv_put_if_absent(b"k", b"second") == b"first"
        assert store.kv_get(b"k") == b"first"
        store.close()


def test_sse_keyring_uses_put_if_absent(tmp_path):
    """Two gateways racing first boot converge on ONE master key."""
    from seaweedfs_tpu.filer.filer_store import MemoryStore
    from seaweedfs_tpu.s3 import sse

    store = MemoryStore()
    k1 = sse.load_or_create_keyring(
        store.kv_get, store.kv_put, store.kv_put_if_absent
    )
    k2 = sse.load_or_create_keyring(
        store.kv_get, store.kv_put, store.kv_put_if_absent
    )
    _, dk, wrapped = k1.generate_data_key()
    assert k2.decrypt_data_key("local-0", wrapped) == dk
