"""Admin server tests: dashboard endpoints, config persistence + live
apply, task submission through the HTTP API, and the full auto-EC flow
scanner -> queue -> worker -> done observed through the admin plane
(reference weed/admin maintenance system)."""

import json
import threading
import time
import urllib.request

import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.admin import AdminServer
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.worker import Worker


def wait_for(cond, timeout=20.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


def get(port, path):
    with urllib.request.urlopen(
        f"http://localhost:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read())


def post(port, path, body):
    req = urllib.request.Request(
        f"http://localhost:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def stack(tmp_path):
    mport = free_port()
    master = MasterServer(
        ip="localhost", port=mport, vacuum_interval=0.2, ec_quiet_seconds=0.0
    )
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    wait_for(lambda: master.topo.nodes, msg="volume server registers")
    aport = free_port()
    admin = AdminServer(
        master=f"localhost:{mport}",
        port=aport,
        config_path=str(tmp_path / "maintenance.json"),
    )
    admin.start()
    yield master, vs, admin, aport
    admin.stop()
    vs.stop()
    master.stop()


def test_dashboard_and_cluster_api(stack):
    master, vs, admin, aport = stack
    # the dashboard page itself
    with urllib.request.urlopen(
        f"http://localhost:{aport}/", timeout=10
    ) as r:
        page = r.read().decode()
    assert "seaweed-tpu admin" in page and "/api/maintenance" in page
    c = get(aport, "/api/cluster")
    assert c["node_count"] == 1
    t = get(aport, "/api/topology")
    assert len(t["nodes"]) == 1
    assert t["nodes"][0]["id"]


def test_config_roundtrip_persists_and_applies(stack, tmp_path):
    master, vs, admin, aport = stack
    cfg = {
        "ec_auto_fullness": 0.77,
        "ec_quiet_seconds": 1.5,
        "garbage_threshold": 0.4,
        "vacuum_interval_seconds": 9.0,
    }
    code, out = post(aport, "/api/config", cfg)
    assert code == 200, out
    # live-applied on the master
    assert master.ec_auto_fullness == pytest.approx(0.77)
    assert master.garbage_threshold == pytest.approx(0.4)
    assert master.vacuum_interval == pytest.approx(9.0)
    # persisted to disk
    persisted = json.loads((tmp_path / "maintenance.json").read_text())
    assert persisted["ec_auto_fullness"] == pytest.approx(0.77)
    # visible through GET
    assert get(aport, "/api/config")["ec_quiet_seconds"] == pytest.approx(1.5)

    # partial update of the round-5 knobs, incl. the string field;
    # untouched knobs keep their values (per-field merge)
    code, out = post(aport, "/api/config", {
        "ec_balance_interval_seconds": 120,
        "lifecycle_interval_seconds": 300,
        "lifecycle_filer": "filer:18888",
    })
    assert code == 200, out
    assert master.ec_balance_interval == pytest.approx(120.0)
    assert master.lifecycle_filer == "filer:18888"
    assert master.ec_auto_fullness == pytest.approx(0.77)  # kept
    got = get(aport, "/api/config")
    assert got["ec_balance_interval_seconds"] == pytest.approx(120.0)
    assert got["lifecycle_filer"] == "filer:18888"

    # invalid config is rejected wholesale and not persisted
    bad = dict(cfg, garbage_threshold=7.0)
    code, out = post(aport, "/api/config", bad)
    assert code == 400 and "garbage_threshold" in out["error"]
    assert master.garbage_threshold == pytest.approx(0.4)

    # NaN bypasses comparison-based range checks and would turn the
    # vacuum loop into a busy-spin: must be rejected wholesale
    code, out = post(
        aport, "/api/config", dict(cfg, vacuum_interval_seconds=float("nan"))
    )
    assert code == 400 and "finite" in out["error"]
    assert master.vacuum_interval == pytest.approx(9.0)

    # partial gRPC update (absent fields) keeps current values instead
    # of zeroing them (proto3 optional presence merge)
    import grpc as _grpc

    from seaweedfs_tpu.pb import rpc as _rpc
    from seaweedfs_tpu.pb import worker_pb2 as wk

    with _grpc.insecure_channel(f"localhost:{master.grpc_port}") as ch:
        resp = _rpc.worker_stub(ch).SetMaintenanceConfig(
            wk.MaintenanceConfig(garbage_threshold=0.5), timeout=5
        )
    assert not resp.error
    assert master.garbage_threshold == pytest.approx(0.5)
    assert master.ec_auto_fullness == pytest.approx(0.77)  # untouched

    # a NEW admin re-applies the persisted policy to a reconfigured master
    master.ec_auto_fullness = 0.0
    admin2 = AdminServer(
        master=f"localhost:{master.port}",
        port=free_port(),
        config_path=str(tmp_path / "maintenance.json"),
    )
    admin2.apply_persisted_config()
    assert master.ec_auto_fullness == pytest.approx(0.77)


def test_submit_task_via_admin_http(stack):
    master, vs, admin, aport = stack
    code, out = post(
        aport, "/api/maintenance/submit", {"kind": "bogus", "volume_id": 1}
    )
    assert code == 400 and "unknown task kind" in out["error"]

    ops = Operations(f"localhost:{master.port}")
    w = Worker(master=f"localhost:{master.port}", backend="cpu")
    threading.Thread(target=w.run, daemon=True).start()
    try:
        data = b"admin submits ec" * 2000
        fid = ops.upload(data)
        vid = FileId.parse(fid).volume_id
        wait_for(
            lambda: get(aport, "/api/maintenance")["workers"],
            msg="worker visible through admin",
        )
        code, out = post(
            aport,
            "/api/maintenance/submit",
            {"kind": "ec_encode", "volume_id": vid},
        )
        assert code == 200 and out["task_id"]

        def task_state():
            tasks = get(aport, "/api/maintenance")["tasks"]
            return {t["task_id"]: t["state"] for t in tasks}.get(
                out["task_id"]
            )

        wait_for(lambda: task_state() == "done", msg="task reaches done")
        assert ops.read(fid) == data
        # the EC volume now shows in the admin topology browser
        topo = get(aport, "/api/topology")
        assert any(
            e["id"] == vid for n in topo["nodes"] for e in n["ec_shards"]
        )
    finally:
        w.stop()
        ops.close()


def test_auto_ec_scanner_flow_through_admin(stack):
    """The VERDICT 'done' criterion: watch an auto-EC task flow
    scanner -> queue -> worker -> done through the admin API."""
    master, vs, admin, aport = stack
    ops = Operations(f"localhost:{master.port}")
    w = Worker(master=f"localhost:{master.port}", backend="cpu")
    threading.Thread(target=w.run, daemon=True).start()
    try:
        data = b"scanner finds me" * 4000
        fid = ops.upload(data)
        vid = FileId.parse(fid).volume_id
        size = master.topo.statistics().used_size
        # tune policy THROUGH the admin so the scanner (vacuum loop,
        # 0.2s interval) will pick the volume up: fullness threshold
        # just below the volume's current fill fraction
        frac = max(size / master.topo.volume_size_limit / 2, 1e-9)
        code, out = post(
            aport,
            "/api/config",
            {
                "ec_auto_fullness": frac,
                "ec_quiet_seconds": 0.0,
                "garbage_threshold": 0.3,
                "vacuum_interval_seconds": 0.2,
            },
        )
        assert code == 200, out

        def ec_task():
            for t in get(aport, "/api/maintenance")["tasks"]:
                if t["kind"] == "ec_encode" and t["volume_id"] == vid:
                    return t
            return None

        wait_for(lambda: ec_task() is not None, msg="scanner queues the task")
        wait_for(lambda: ec_task()["state"] == "done", msg="worker finishes")
        assert ops.read(fid) == data
    finally:
        w.stop()
        ops.close()


def test_malformed_submit_returns_json_400(stack):
    """ADVICE r3: volume_id:null (dashboard empty field) must produce a
    JSON 400, not a dropped connection."""
    master, vs, admin, aport = stack
    code, out = post(
        aport, "/api/maintenance/submit", {"kind": "ec_encode", "volume_id": None}
    )
    assert code == 400 and "error" in out
    code, out = post(
        aport, "/api/maintenance/submit", {"kind": "ec_encode", "volume_id": "xyz"}
    )
    assert code == 400 and "volume_id" in out["error"]
    # cluster-wide kinds need no volume: null volume_id submits fine
    code, out = post(
        aport, "/api/maintenance/submit",
        {"kind": "ec_balance", "volume_id": None},
    )
    assert code == 200 and out.get("task_id"), out


def test_admin_auth_token(stack, tmp_path):
    """POSTs require X-Admin-Token when configured; GETs stay open."""
    import urllib.request

    master, vs, admin, aport = stack
    port = free_port()
    locked = AdminServer(
        master=f"localhost:{master.port}",
        port=port,
        config_path=str(tmp_path / "m2.json"),
        auth_token="s3cret",
    )
    locked.start()
    try:
        assert get(port, "/healthz")["ok"]  # GET open
        code, out = post(port, "/api/maintenance/submit", {"kind": "x"})
        assert code == 401
        req = urllib.request.Request(
            f"http://localhost:{port}/api/maintenance/submit",
            data=json.dumps({"kind": "bogus", "volume_id": 1}).encode(),
            headers={"X-Admin-Token": "s3cret"},
            method="POST",
        )
        try:
            resp = urllib.request.urlopen(req)
            code = resp.status
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 400  # authenticated, rejected for unknown kind
    finally:
        locked.stop()


def test_plugin_task_descriptors(stack):
    """Declarative per-job config (reference weed/admin/plugin DESIGN):
    workers register descriptors, the admin API exposes them, submitted
    params are validated against them and reach the worker."""
    master, vs, admin, aport = stack
    w = Worker(master=f"localhost:{master.port}", backend="cpu")
    threading.Thread(target=w.run, daemon=True).start()
    try:
        def worker_rows():
            return get(aport, "/api/maintenance")["workers"]

        wait_for(lambda: worker_rows(), msg="worker registers")
        row = worker_rows()[0]
        kinds = {d["kind"]: d for d in row["descriptors"]}
        assert "vacuum" in kinds and "ec_encode" in kinds
        vac = kinds["vacuum"]["fields"][0]
        assert vac["name"] == "garbage_threshold"
        assert vac["type"] == "float" and vac["max"] == 1.0

        # invalid param values are rejected with the declared bounds
        code, out = post(
            aport,
            "/api/maintenance/submit",
            {
                "kind": "vacuum",
                "volume_id": 1,
                "params": {"garbage_threshold": "2.5"},
            },
        )
        assert code == 400 and "outside" in out["error"], out
        code, out = post(
            aport,
            "/api/maintenance/submit",
            {
                "kind": "vacuum",
                "volume_id": 1,
                "params": {"nope": "1"},
            },
        )
        assert code == 400 and "unknown param" in out["error"], out

        # a valid param flows through to execution
        ops = Operations(f"localhost:{master.port}")
        try:
            fid = ops.upload(b"descriptor config" * 500)
            vid = FileId.parse(fid).volume_id
            code, out = post(
                aport,
                "/api/maintenance/submit",
                {
                    "kind": "vacuum",
                    "volume_id": vid,
                    "params": {"garbage_threshold": "0.0"},
                },
            )
            assert code == 200, out

            def task_state():
                tasks = get(aport, "/api/maintenance")["tasks"]
                return {t["task_id"]: t["state"] for t in tasks}.get(
                    out["task_id"]
                )

            wait_for(lambda: task_state() == "done", msg="vacuum w/ params done")
        finally:
            ops.close()
    finally:
        w.stop()
