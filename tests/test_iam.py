"""IAM policy engine + STS tests.

Reference models: weed/iam/policy/policy_engine_test.go (wildcards,
deny-wins, conditions) and weed/iam/sts tests; gateway-level
enforcement mirrors test/s3/iam.
"""

import datetime
import hashlib
import hmac
import time
import urllib.parse

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.iam.policy import (
    PolicyEngine,
    evaluate_policies,
    s3_action_and_resource,
)
from seaweedfs_tpu.iam.sts import Role, StsService
from seaweedfs_tpu.s3 import Identity, IdentityStore, S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from conftest import allocate_port as free_port

REGION = "us-east-1"


# --------------------------------------------------------- policy engine


def _doc(*statements):
    return {"Version": "2012-10-17", "Statement": list(statements)}


def test_allow_with_wildcards():
    doc = _doc(
        {
            "Effect": "Allow",
            "Action": "s3:Get*",
            "Resource": "arn:aws:s3:::logs/*",
        }
    )
    assert evaluate_policies([doc], "s3:GetObject", "arn:aws:s3:::logs/a/b")
    assert not evaluate_policies([doc], "s3:PutObject", "arn:aws:s3:::logs/a")
    assert not evaluate_policies([doc], "s3:GetObject", "arn:aws:s3:::other/a")


def test_explicit_deny_wins():
    doc = _doc(
        {"Effect": "Allow", "Action": "s3:*", "Resource": "*"},
        {
            "Effect": "Deny",
            "Action": "s3:DeleteObject",
            "Resource": "arn:aws:s3:::prod/*",
        },
    )
    assert evaluate_policies([doc], "s3:DeleteObject", "arn:aws:s3:::dev/x")
    assert not evaluate_policies([doc], "s3:DeleteObject", "arn:aws:s3:::prod/x")
    # deny in ONE doc beats allow in another
    allow_all = _doc({"Effect": "Allow", "Action": "*", "Resource": "*"})
    deny = _doc({"Effect": "Deny", "Action": "s3:PutObject", "Resource": "*"})
    assert not evaluate_policies([allow_all, deny], "s3:PutObject", "x")


def test_implicit_deny():
    assert not evaluate_policies([], "s3:GetObject", "arn:aws:s3:::b/k")
    doc = _doc({"Effect": "Allow", "Action": "s3:GetObject", "Resource": "*"})
    assert not evaluate_policies([doc], "s3:ListBucket", "arn:aws:s3:::b")


def test_conditions():
    doc = _doc(
        {
            "Effect": "Allow",
            "Action": "s3:GetObject",
            "Resource": "*",
            "Condition": {"IpAddress": {"aws:SourceIp": "10.0.0.0/8"}},
        }
    )
    assert evaluate_policies(
        [doc], "s3:GetObject", "x", {"aws:SourceIp": "10.1.2.3"}
    )
    assert not evaluate_policies(
        [doc], "s3:GetObject", "x", {"aws:SourceIp": "192.168.1.1"}
    )
    assert not evaluate_policies([doc], "s3:GetObject", "x", {})  # no context
    like = _doc(
        {
            "Effect": "Allow",
            "Action": "s3:ListBucket",
            "Resource": "*",
            "Condition": {"StringLike": {"s3:prefix": ["reports/*", ""]}},
        }
    )
    assert evaluate_policies(
        [like], "s3:ListBucket", "x", {"s3:prefix": "reports/2026"}
    )
    assert not evaluate_policies(
        [like], "s3:ListBucket", "x", {"s3:prefix": "secrets/"}
    )
    # unknown condition operator fails closed
    weird = _doc(
        {
            "Effect": "Allow",
            "Action": "*",
            "Resource": "*",
            "Condition": {"QuantumEquals": {"x": "y"}},
        }
    )
    assert not evaluate_policies([weird], "s3:GetObject", "x", {"x": "y"})


def test_not_action_and_not_resource():
    """The AWS read-only pattern: Deny everything that is NOT a read."""
    doc = _doc(
        {"Effect": "Allow", "Action": "s3:*", "Resource": "*"},
        {"Effect": "Deny", "NotAction": ["s3:Get*", "s3:List*"], "Resource": "*"},
    )
    assert evaluate_policies([doc], "s3:GetObject", "arn:aws:s3:::b/k")
    assert not evaluate_policies([doc], "s3:PutObject", "arn:aws:s3:::b/k")
    assert not evaluate_policies([doc], "s3:DeleteObject", "arn:aws:s3:::b/k")
    nr = _doc(
        {
            "Effect": "Allow",
            "Action": "s3:GetObject",
            "NotResource": "arn:aws:s3:::secret/*",
        }
    )
    assert evaluate_policies([nr], "s3:GetObject", "arn:aws:s3:::open/x")
    assert not evaluate_policies([nr], "s3:GetObject", "arn:aws:s3:::secret/x")


def test_roles_only_config_rejected(tmp_path):
    import json as _json

    from seaweedfs_tpu.s3.config import load_s3_config

    p = tmp_path / "conf.json"
    p.write_text(_json.dumps({"roles": [{"name": "r", "policies": []}]}))
    with pytest.raises(ValueError):
        load_s3_config(str(p))


def test_across_racks_falls_back_when_best_rack_full():
    from seaweedfs_tpu.ec.placement import NodeView, plan_ec_balance

    nodes = [
        NodeView("a", rack="r1", shards={1: set(range(14))}),
        NodeView("b", rack="r2", free_slots=0),  # favorite but full
        NodeView("c", rack="r3", shards={1: set()}, free_slots=50),
    ]
    _, moves = plan_ec_balance(nodes)
    assert any(m.dst == "c" for m in moves)
    assert all(m.dst != "b" for m in moves)


def test_policy_engine_registry():
    eng = PolicyEngine()
    eng.put_policy(
        "ro", _doc({"Effect": "Allow", "Action": "s3:Get*", "Resource": "*"})
    )
    assert eng.is_allowed(["ro"], "s3:GetObject", "arn:aws:s3:::b/k")
    assert not eng.is_allowed(["ro"], "s3:PutObject", "arn:aws:s3:::b/k")
    assert not eng.is_allowed(["missing"], "s3:GetObject", "x")
    assert eng.names() == ["ro"]


def test_s3_action_mapping():
    assert s3_action_and_resource("GET", "b", "k", {}) == (
        "s3:GetObject",
        "arn:aws:s3:::b/k",
    )
    assert s3_action_and_resource("PUT", "b", "", {}) == (
        "s3:CreateBucket",
        "arn:aws:s3:::b",
    )
    assert s3_action_and_resource("GET", "b", "", {"versions": ""})[0] == (
        "s3:ListBucketVersions"
    )
    assert s3_action_and_resource("PUT", "b", "k", {"retention": ""})[0] == (
        "s3:PutObjectRetention"
    )
    assert s3_action_and_resource("DELETE", "b", "k", {"versionId": "v"})[0] == (
        "s3:DeleteObjectVersion"
    )
    assert s3_action_and_resource("GET", "", "", {})[0] == "s3:ListAllMyBuckets"


# ------------------------------------------------------------------ STS


def test_sts_assume_role_and_expiry():
    sts = StsService()
    sts.put_role(Role(name="uploader", policies=[_doc(
        {"Effect": "Allow", "Action": "s3:PutObject", "Resource": "*"}
    )]))
    caller_pol = [_doc({"Effect": "Allow", "Action": "sts:AssumeRole", "Resource": "*"})]
    cred = sts.assume_role("AKCALLER", caller_pol, "uploader", duration=900)
    assert cred.access_key.startswith("ASIA")
    assert sts.lookup(cred.access_key) is cred
    # unknown role / denied caller
    with pytest.raises(PermissionError):
        sts.assume_role("AKCALLER", caller_pol, "nope")
    with pytest.raises(PermissionError):
        sts.assume_role("AKCALLER", [], "uploader")
    # trusted principal gate
    sts.put_role(Role(name="locked", trusted=["AKOTHER"]))
    with pytest.raises(PermissionError):
        sts.assume_role("AKCALLER", None, "locked")
    # expiry reaps
    cred.expires_at = time.time() - 1
    assert sts.lookup(cred.access_key) is None


# --------------------------------------------------------- gateway level


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("iamvol")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


def _sign(method, url, access_key, secret, body=b"", token=""):
    u = urllib.parse.urlparse(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {
        "Host": u.netloc,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
    }
    if token:
        headers["x-amz-security-token"] = token
    signed = sorted(h.lower() for h in headers)
    canon_headers = "".join(
        f"{h}:{[v for k, v in headers.items() if k.lower() == h][0]}\n"
        for h in signed
    )
    creq = "\n".join(
        [
            method,
            u.path or "/",
            "&".join(
                f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
                for k, v in sorted(
                    urllib.parse.parse_qsl(u.query, keep_blank_values=True)
                )
            ),
            canon_headers,
            ";".join(signed),
            payload_hash,
        ]
    )
    scope = f"{date}/{REGION}/s3/aws4_request"
    sts_str = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(creq.encode()).hexdigest(),
        ]
    )

    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = h(("AWS4" + secret).encode(), date)
    k = h(k, REGION)
    k = h(k, "s3")
    k = h(k, "aws4_request")
    sig = hmac.new(k, sts_str.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}"
    )
    return headers


@pytest.fixture
def iam_s3(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    ids = IdentityStore()
    ids.add(Identity("boss", "AKBOSS", "bosssecret", actions=("Admin",)))
    ids.add(
        Identity(
            "readonly",
            "AKRO",
            "rosecret",
            actions=(),
            policies=(
                {
                    "Version": "2012-10-17",
                    "Statement": [
                        {
                            "Effect": "Allow",
                            "Action": ["s3:GetObject", "s3:ListBucket"],
                            "Resource": "arn:aws:s3:::pub*",
                        }
                    ],
                },
            ),
        )
    )
    sts = StsService()
    sts.put_role(
        Role(
            name="writer",
            policies=[
                {
                    "Statement": [
                        {
                            "Effect": "Allow",
                            "Action": ["s3:PutObject", "s3:GetObject",
                                       "s3:CreateBucket"],
                            "Resource": "*",
                        }
                    ]
                }
            ],
        )
    )
    srv = S3Server(
        filer, ip="localhost", port=free_port(), identities=ids,
        lifecycle_interval=0, sts=sts,
    )
    srv.start()
    yield f"http://localhost:{srv.port}"
    srv.stop()
    filer.close()


def test_policy_enforcement_at_gateway(iam_s3):
    url = iam_s3
    # admin seeds a bucket + object
    hh = _sign("PUT", f"{url}/pub", "AKBOSS", "bosssecret")
    assert requests.put(f"{url}/pub", headers=hh).status_code == 200
    hh = _sign("PUT", f"{url}/pub/doc", "AKBOSS", "bosssecret", body=b"data")
    assert (
        requests.put(f"{url}/pub/doc", headers=hh, data=b"data").status_code
        == 200
    )
    # readonly identity can GET...
    hh = _sign("GET", f"{url}/pub/doc", "AKRO", "rosecret")
    assert requests.get(f"{url}/pub/doc", headers=hh).content == b"data"
    # ...but not PUT (policy has no s3:PutObject)
    hh = _sign("PUT", f"{url}/pub/new", "AKRO", "rosecret", body=b"x")
    r = requests.put(f"{url}/pub/new", headers=hh, data=b"x")
    assert r.status_code == 403 and "denied by policy" in r.text
    # ...and not outside the pub* resource scope
    hh = _sign("GET", f"{url}/private/doc", "AKRO", "rosecret")
    assert requests.get(f"{url}/private/doc", headers=hh).status_code == 403


def test_sts_flow_at_gateway(iam_s3):
    url = iam_s3
    # assume the writer role as the admin
    body = urllib.parse.urlencode(
        {
            "Action": "AssumeRole",
            "RoleArn": "arn:aws:iam:::role/writer",
            "DurationSeconds": "900",
        }
    ).encode()
    hh = _sign("POST", f"{url}/", "AKBOSS", "bosssecret", body=body)
    r = requests.post(f"{url}/", headers=hh, data=body)
    assert r.status_code == 200, r.text
    import xml.etree.ElementTree as ET

    doc = ET.fromstring(r.text)
    ns = doc.tag[: doc.tag.index("}") + 1]
    ak = doc.findtext(f".//{ns}AccessKeyId")
    sk = doc.findtext(f".//{ns}SecretAccessKey")
    token = doc.findtext(f".//{ns}SessionToken")
    assert ak.startswith("ASIA")
    # temp creds + session token can write
    hh = _sign("PUT", f"{url}/stsbkt", ak, sk, token=token)
    assert requests.put(f"{url}/stsbkt", headers=hh).status_code == 200
    hh = _sign("PUT", f"{url}/stsbkt/obj", ak, sk, body=b"tmp", token=token)
    assert (
        requests.put(f"{url}/stsbkt/obj", headers=hh, data=b"tmp").status_code
        == 200
    )
    # missing session token -> rejected even with the right signature
    hh = _sign("PUT", f"{url}/stsbkt/obj2", ak, sk, body=b"x")
    assert (
        requests.put(f"{url}/stsbkt/obj2", headers=hh, data=b"x").status_code
        == 403
    )
    # the role policy has no DeleteObject -> denied
    hh = _sign("DELETE", f"{url}/stsbkt/obj", ak, sk, token=token)
    assert requests.delete(f"{url}/stsbkt/obj", headers=hh).status_code == 403


def test_oidc_bearer_auth(tmp_path):
    """OIDC bearer tokens (reference weed/iam OIDC provider): verified
    claims map to role-scoped identities; bad tokens are rejected, not
    anonymized."""
    import base64
    import hashlib
    import hmac
    import json
    import time as _time

    import requests

    from conftest import allocate_port as free_port
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.iam.oidc import OidcProvider
    from seaweedfs_tpu.s3 import Identity, IdentityStore, S3Server
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        _time.sleep(0.05)

    secret = "oidc-shared-secret"
    oidc = OidcProvider(
        issuer="https://idp.test",
        audience="seaweed",
        hs256_secret=secret,
        roles={
            "writer": {"actions": ["Admin"]},
            "reader": {"actions": ["Read", "List"]},
        },
    )
    idents = IdentityStore()
    idents.add(Identity("sig", "AKSIG", "sigsecret"))
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    srv = S3Server(
        filer, ip="localhost", port=free_port(), identities=idents, oidc=oidc
    )
    srv.start()
    url = f"http://localhost:{srv.port}"

    def b64(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    def token(claims):
        h = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        p = b64(json.dumps(claims).encode())
        sig = hmac.new(
            secret.encode(), f"{h}.{p}".encode(), hashlib.sha256
        ).digest()
        return f"{h}.{p}.{b64(sig)}"

    def bearer(tok):
        return {"Authorization": f"Bearer {tok}"}

    try:
        base_claims = {
            "iss": "https://idp.test", "aud": "seaweed",
            "sub": "alice", "exp": _time.time() + 300,
        }
        # writer role: full access
        t = token({**base_claims, "roles": ["writer"]})
        assert requests.put(f"{url}/oidcb", headers=bearer(t)).status_code == 200
        assert (
            requests.put(
                f"{url}/oidcb/k", data=b"v", headers=bearer(t)
            ).status_code
            == 200
        )
        # reader role: read passes, write denied
        r = token({**base_claims, "sub": "bob", "roles": ["reader"]})
        assert (
            requests.get(f"{url}/oidcb/k", headers=bearer(r)).content == b"v"
        )
        assert (
            requests.put(
                f"{url}/oidcb/x", data=b"w", headers=bearer(r)
            ).status_code
            == 403
        )
        # unmapped role: no permissions at all
        n = token({**base_claims, "sub": "eve", "roles": ["nobody"]})
        assert (
            requests.get(f"{url}/oidcb/k", headers=bearer(n)).status_code
            == 403
        )
        # tampered signature -> 403 InvalidToken (never anonymous)
        bad = t[:-4] + "AAAA"
        resp = requests.get(f"{url}/oidcb/k", headers=bearer(bad))
        assert resp.status_code == 403 and "InvalidToken" in resp.text
        # expired
        e = token({**base_claims, "exp": _time.time() - 600, "roles": ["writer"]})
        assert (
            requests.get(f"{url}/oidcb/k", headers=bearer(e)).status_code
            == 403
        )
        # wrong issuer
        w = token({**base_claims, "iss": "https://evil", "roles": ["writer"]})
        assert (
            requests.get(f"{url}/oidcb/k", headers=bearer(w)).status_code
            == 403
        )
        # SigV4 still works beside OIDC
        from test_s3 import sign_request

        h = sign_request("GET", f"{url}/oidcb/k", "AKSIG", "sigsecret")
        assert requests.get(f"{url}/oidcb/k", headers=h).content == b"v"
    finally:
        srv.stop()
        filer.close()
        vs.stop()
        master.stop()


def test_oidc_rs256_verify():
    import base64
    import json
    import time as _time

    import pytest

    pytest.importorskip(
        "cryptography", reason="RS256 verify needs 'cryptography'"
    )
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    from cryptography.hazmat.primitives.hashes import SHA256

    import pytest as _pytest

    from seaweedfs_tpu.iam.oidc import OidcError, OidcProvider

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    ).decode()
    prov = OidcProvider(issuer="iss", rs256_public_key_pem=pem)

    def b64(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    h = b64(json.dumps({"alg": "RS256"}).encode())
    p = b64(json.dumps({"iss": "iss", "exp": _time.time() + 60, "sub": "x"}).encode())
    sig = key.sign(f"{h}.{p}".encode(), padding.PKCS1v15(), SHA256())
    claims = prov.verify(f"{h}.{p}.{b64(sig)}")
    assert claims["sub"] == "x"
    with _pytest.raises(OidcError):
        prov.verify(f"{h}.{p}.{b64(sig[:-2] + b'xx')}")
    # alg confusion: an HS256 token must not pass an RS256-only provider
    import hashlib
    import hmac as _hmac

    h2 = b64(json.dumps({"alg": "HS256"}).encode())
    forged = _hmac.new(pem.encode(), f"{h2}.{p}".encode(), hashlib.sha256).digest()
    with _pytest.raises(OidcError):
        prov.verify(f"{h2}.{p}.{b64(forged)}")


def test_oidc_only_gateway_is_not_open_mode(tmp_path):
    """An OIDC-configured gateway with an empty SigV4 store must treat
    tokenless requests as ANONYMOUS (denied), never open mode."""
    import time as _time

    import requests

    from conftest import allocate_port as free_port
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.iam.oidc import OidcProvider
    from seaweedfs_tpu.s3 import S3Server
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        _time.sleep(0.05)
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    srv = S3Server(
        filer, ip="localhost", port=free_port(),
        oidc=OidcProvider(issuer="i", hs256_secret="s"),
    )
    srv.start()
    try:
        url = f"http://localhost:{srv.port}"
        assert requests.put(f"{url}/nope", timeout=5).status_code == 403
        assert requests.get(f"{url}/", timeout=5).status_code == 403
        # POST-policy uploads must also be anonymous (not open mode) on
        # an OIDC-only gateway: an unsigned multipart form may not
        # write without a bucket-policy/ACL grant (advisor r4 high).
        from seaweedfs_tpu.filer.entry import new_entry

        filer.create_entry(new_entry("/buckets/pb", is_directory=True))
        body = (
            b"--BB\r\n"
            b'Content-Disposition: form-data; name="key"\r\n\r\n'
            b"x.txt\r\n"
            b"--BB\r\n"
            b'Content-Disposition: form-data; name="file"; filename="x"\r\n'
            b"Content-Type: text/plain\r\n\r\n"
            b"owned\r\n"
            b"--BB--\r\n"
        )
        r = requests.post(
            f"{url}/pb",
            data=body,
            headers={"Content-Type": "multipart/form-data; boundary=BB"},
            timeout=5,
        )
        assert r.status_code == 403, r.text
    finally:
        srv.stop()
        filer.close()
        vs.stop()
        master.stop()


def test_load_s3_config_with_oidc(tmp_path):
    import json as _json

    from seaweedfs_tpu.iam.oidc import OidcProvider
    from seaweedfs_tpu.s3.config import load_s3_config

    p = tmp_path / "s3.json"
    p.write_text(
        _json.dumps(
            {
                "identities": [
                    {"name": "a", "accessKey": "AK", "secretKey": "SK"}
                ],
                "oidc": {
                    "issuer": "https://idp",
                    "hs256_secret": "x",
                    "roles": {"admin": {"actions": ["Admin"]}},
                },
            }
        )
    )
    store, sts, oidc, _ldap = load_s3_config(str(p))
    assert isinstance(oidc, OidcProvider) and oidc.issuer == "https://idp"
    assert store.lookup("AK") is not None


# ---------------------------------------------------------------- LDAP


def test_ldap_provider_and_mini_server():
    from seaweedfs_tpu.iam.ldap import LdapError, LdapProvider, MiniLdapServer

    srv = MiniLdapServer(
        {"uid=alice,ou=users,dc=test": "alicepw"}
    )
    try:
        p = LdapProvider(
            f"ldap://127.0.0.1:{srv.port}",
            "uid={username},ou=users,dc=test",
        )
        assert p.authenticate("alice", "alicepw") == (
            "uid=alice,ou=users,dc=test"
        )
        with pytest.raises(LdapError):
            p.authenticate("alice", "wrong")
        with pytest.raises(LdapError):
            p.authenticate("nobody", "x")
        # RFC 4513: empty password must never authenticate (anonymous
        # bind) — refused client-side AND by the server (code 53)
        with pytest.raises(LdapError):
            p.authenticate("alice", "")
        # DN injection via username is refused before any bind
        with pytest.raises(LdapError):
            p.authenticate("alice,ou=admins", "x")
    finally:
        srv.close()


def test_sts_assume_role_with_ldap_identity(tmp_path):
    """Full path: LDAP bind -> temp credentials -> SigV4 signed S3
    request with the minted credentials."""
    import requests

    from conftest import allocate_port as free_port
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.iam.ldap import LdapProvider, MiniLdapServer
    from seaweedfs_tpu.iam.sts import Role, StsService
    from seaweedfs_tpu.s3 import S3Server
    from seaweedfs_tpu.s3.auth import Identity, IdentityStore
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    ldap_srv = MiniLdapServer({
        "uid=bob,ou=users,dc=test": "bobpw",
        "uid=eve,ou=users,dc=test": "evepw",  # valid LDAP, NOT trusted
    })
    sts = StsService()
    sts.put_role(
        Role(
            name="ldap-writer",
            policies=[{
                "Version": "2012-10-17",
                "Statement": [{
                    "Effect": "Allow",
                    "Action": "s3:*",
                    "Resource": "*",
                }],
            }],
            trusted=["ldap:bob"],
        )
    )
    idents = IdentityStore()
    idents.add(Identity("admin", "AKADM", "adminsecret"))
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    srv = S3Server(
        filer, ip="localhost", port=free_port(), identities=idents,
        sts=sts,
        ldap=LdapProvider(
            f"ldap://127.0.0.1:{ldap_srv.port}",
            "uid={username},ou=users,dc=test",
        ),
    )
    srv.start()
    url = f"http://localhost:{srv.port}"
    try:
        # wrong password -> 403
        r = requests.post(url, data={
            "Action": "AssumeRoleWithLdapIdentity",
            "LdapUsername": "bob", "LdapPassword": "nope",
            "RoleName": "ldap-writer",
        }, timeout=10)
        assert r.status_code == 403
        # valid LDAP credentials but NOT in the role's trusted list
        r = requests.post(url, data={
            "Action": "AssumeRoleWithLdapIdentity",
            "LdapUsername": "eve", "LdapPassword": "evepw",
            "RoleName": "ldap-writer",
        }, timeout=10)
        assert r.status_code == 403, r.text
        # trusted user with the right password -> credentials minted
        r = requests.post(url, data={
            "Action": "AssumeRoleWithLdapIdentity",
            "LdapUsername": "bob", "LdapPassword": "bobpw",
            "RoleName": "ldap-writer",
        }, timeout=10)
        assert r.status_code == 200, r.text
        import re as _re

        ak = _re.search(r"<AccessKeyId>([^<]+)", r.text).group(1)
        sk = _re.search(r"<SecretAccessKey>([^<]+)", r.text).group(1)
        tok = _re.search(r"<SessionToken>([^<]+)", r.text).group(1)
        # the minted credentials sign real S3 requests
        from test_s3 import sign_request

        requests.put(f"{url}/ldapbkt", headers=sign_request(
            "PUT", f"{url}/ldapbkt", "AKADM", "adminsecret"))
        h = sign_request("PUT", f"{url}/ldapbkt/f.txt", ak, sk, body=b"via-ldap")
        h["x-amz-security-token"] = tok
        r = requests.put(f"{url}/ldapbkt/f.txt", data=b"via-ldap", headers=h, timeout=10)
        assert r.status_code == 200, r.text
    finally:
        srv.stop()
        filer.close()
        ldap_srv.close()
        vs.stop()
        master.stop()


# ----------------------------------------------------- embedded IAM API


def test_embedded_iam_api(tmp_path):
    """weed/iamapi analog: user + access-key + policy lifecycle over
    the AWS 2010-05-08 query protocol, with minted keys authenticating
    real S3 requests within the identity store's reload window."""
    import json
    import re as _re

    from conftest import allocate_port as free_port
    from seaweedfs_tpu.filer import Filer, MemoryStore

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    idents = IdentityStore()
    idents.add(Identity("root", "AKROOT", "rootsecret"))  # admin
    idents.add(Identity("ro", "AKRO2", "rosecret2", actions=("Read",)))
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    srv = S3Server(filer, ip="localhost", port=free_port(), identities=idents)
    # fast identity reload so minted keys work inside the test
    srv.identities._ttl = 0.1
    srv.start()
    url = f"http://localhost:{srv.port}"
    from test_s3 import sign_request

    def iam(form, ak="AKROOT", sk="rootsecret"):
        import urllib.parse as _up

        body = _up.urlencode(form).encode()
        h = sign_request("POST", f"{url}/", ak, sk, body=body)
        h["Content-Type"] = "application/x-www-form-urlencoded"
        return requests.post(url, data=body, headers=h, timeout=10)

    try:
        # non-admin refused
        assert iam(
            {"Action": "CreateUser", "UserName": "x"}, "AKRO2", "rosecret2"
        ).status_code == 403
        # create user -> key -> authenticate with it
        r = iam({"Action": "CreateUser", "UserName": "svc"})
        assert r.status_code == 200 and "<UserName>svc<" in r.text
        assert iam({"Action": "CreateUser", "UserName": "svc"}).status_code == 409
        r = iam({"Action": "CreateAccessKey", "UserName": "svc"})
        assert r.status_code == 200, r.text
        ak = _re.search(r"<AccessKeyId>([^<]+)", r.text).group(1)
        sk = _re.search(r"<SecretAccessKey>([^<]+)", r.text).group(1)
        r = iam({"Action": "ListUsers"})
        assert "<UserName>svc<" in r.text
        r = iam({"Action": "ListAccessKeys", "UserName": "svc"})
        assert ak in r.text
        # the minted key signs a real S3 request (admin by default)
        time.sleep(0.3)  # identity reload TTL
        h = sign_request("PUT", f"{url}/iambkt", ak, sk)
        assert requests.put(f"{url}/iambkt", headers=h, timeout=10).status_code == 200
        # attach a read-only policy: writes now refused for that key
        pol = {
            "Version": "2012-10-17",
            "Statement": [{
                "Effect": "Allow",
                "Action": ["s3:GetObject", "s3:ListBucket"],
                "Resource": "*",
            }],
        }
        r = iam({
            "Action": "PutUserPolicy", "UserName": "svc",
            "PolicyName": "ro", "PolicyDocument": json.dumps(pol),
        })
        assert r.status_code == 200, r.text
        r = iam({"Action": "GetUserPolicy", "UserName": "svc"})
        assert "s3:GetObject" in r.text
        time.sleep(0.3)
        h = sign_request("PUT", f"{url}/iambkt/f.txt", ak, sk, body=b"x")
        assert (
            requests.put(
                f"{url}/iambkt/f.txt", data=b"x", headers=h, timeout=10
            ).status_code
            == 403
        )
        # delete the key: authentication stops working
        r = iam({"Action": "DeleteAccessKey", "AccessKeyId": ak})
        assert r.status_code == 200
        time.sleep(0.3)
        h = sign_request("GET", f"{url}/iambkt", ak, sk)
        assert requests.get(f"{url}/iambkt", headers=h, timeout=10).status_code == 403
        # delete the user
        assert iam({"Action": "DeleteUser", "UserName": "svc"}).status_code == 200
        assert (
            iam({"Action": "ListAccessKeys", "UserName": "svc"}).status_code
            == 404
        )
    finally:
        srv.stop()
        filer.close()
        vs.stop()
        master.stop()


def test_iam_api_policy_then_key_never_escalates(tmp_path):
    """Review r5: CreateAccessKey AFTER PutUserPolicy (and after a
    delete+recreate cycle) must not default the key to Admin — the
    policy travels and the coarse actions stay empty."""
    from seaweedfs_tpu.filer import MemoryStore
    from seaweedfs_tpu.s3 import iamapi

    store = MemoryStore()
    pol = {
        "Version": "2012-10-17",
        "Statement": [{
            "Effect": "Allow", "Action": "s3:GetObject", "Resource": "*",
        }],
    }
    iamapi.execute(store, {"Action": "CreateUser", "UserName": "locked"})
    iamapi.execute(store, {
        "Action": "PutUserPolicy", "UserName": "locked",
        "PolicyName": "ro", "PolicyDocument": __import__("json").dumps(pol),
    })
    import re as _re

    r = iamapi.execute(
        store, {"Action": "CreateAccessKey", "UserName": "locked"}
    ).decode()
    ak = _re.search(r"<AccessKeyId>([^<]+)", r).group(1)
    conf = iamapi._load(store)
    entry = next(i for i in conf["identities"] if i.get("accessKey") == ak)
    assert entry["actions"] == []  # NOT ["Admin"]
    assert entry["policies"] == [pol]
    # delete + recreate keeps the restriction
    iamapi.execute(store, {"Action": "DeleteAccessKey", "AccessKeyId": ak})
    r = iamapi.execute(
        store, {"Action": "GetUserPolicy", "UserName": "locked"}
    ).decode()
    assert "s3:GetObject" in r
    r = iamapi.execute(
        store, {"Action": "CreateAccessKey", "UserName": "locked"}
    ).decode()
    ak2 = _re.search(r"<AccessKeyId>([^<]+)", r).group(1)
    conf = iamapi._load(store)
    entry = next(i for i in conf["identities"] if i.get("accessKey") == ak2)
    assert entry["actions"] == [] and entry["policies"] == [pol]
