"""Kafka wire-conformance golden transcripts.

Every request here is assembled BY HAND from the Kafka protocol spec
(struct.pack field by field — deliberately NOT via the repo's own
Writer, so a shared encoding bug cannot self-validate), sent over a
real socket, and the response is matched BYTE FOR BYTE against a
spec-derived expectation. Only genuinely server-chosen values (the
ephemeral port, generated member ids) are wildcarded; everything else
— including record batches, CRCs, and flexible/tagged encodings — must
match exactly, so any response-byte divergence fails the test.

Reference: weed/mq/kafka/API_VERSION_MATRIX.md and test/kafka/ (the
reference validates against real Kafka clients; with no Kafka SDK in
this image, the spec-byte corpus is the equivalent evidence).

Spec layouts follow https://kafka.apache.org/protocol (KIP-482 for
flexible versions); zigzag varints per the protobuf encoding.
"""

from __future__ import annotations

import gzip as _gzip
import socket
import struct
import time

import pytest

from conftest import allocate_port
from seaweedfs_tpu.mq.broker import MqBrokerServer
from seaweedfs_tpu.utils.crc import crc32c

# ------------------------------------------------------------ framework


class W:
    """Wildcard: `n` bytes whose value the server legitimately chooses
    (ephemeral ports, generated member ids). `capture` names the bytes
    for later transcripts in the same session."""

    def __init__(self, n: int, label: str = "", capture: str | None = None):
        self.n = n
        self.label = label
        self.capture = capture


class Session:
    def __init__(self, port: int):
        self.port = port
        self.captured: dict[str, bytes] = {}
        self._sock = socket.create_connection(("127.0.0.1", port), timeout=30)

    def transcript(self, request: bytes, *expected) -> None:
        """Send one framed request; assert the framed response matches
        the expected segment pattern exactly."""
        self._sock.sendall(struct.pack(">i", len(request)) + request)
        (ln,) = struct.unpack(">i", self._recv(4))
        resp = self._recv(ln)
        self._match(resp, expected)

    def _recv(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            got = self._sock.recv(n - len(buf))
            if not got:
                raise AssertionError(f"connection closed ({len(buf)}/{n})")
            buf += got
        return buf

    def _match(self, resp: bytes, expected) -> None:
        pos = 0
        for i, seg in enumerate(expected):
            if isinstance(seg, W):
                got = resp[pos : pos + seg.n]
                assert len(got) == seg.n, (
                    f"segment {i} ({seg.label}): response truncated at "
                    f"byte {pos}: {resp[pos:].hex()}"
                )
                if seg.capture:
                    self.captured[seg.capture] = got
                pos += seg.n
                continue
            got = resp[pos : pos + len(seg)]
            assert got == seg, (
                f"segment {i} diverges at byte {pos}:\n"
                f"  want {seg.hex()}\n"
                f"  got  {got.hex()}\n"
                f"  full response: {resp.hex()}"
            )
            pos += len(seg)
        assert pos == len(resp), (
            f"response has {len(resp) - pos} unexpected trailing bytes: "
            f"{resp[pos:].hex()}"
        )

    def close(self) -> None:
        self._sock.close()


@pytest.fixture
def sess():
    srv = MqBrokerServer(ip="127.0.0.1", grpc_port=allocate_port(), kafka_port=0)
    srv.start()
    s = Session(srv.kafka.port)
    yield s
    s.close()
    srv.stop()


# -------------------------------------------------- spec-level builders
# (independent of seaweedfs_tpu.mq.kafka.protocol by design)


def i8(v):  # noqa: E741
    return struct.pack(">b", v)


def i16(v):
    return struct.pack(">h", v)


def i32(v):
    return struct.pack(">i", v)


def i64(v):
    return struct.pack(">q", v)


def s(v: str) -> bytes:  # STRING
    b = v.encode()
    return struct.pack(">h", len(b)) + b


def nstr_null() -> bytes:  # NULLABLE_STRING = null
    return struct.pack(">h", -1)


def uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def varint(v: int) -> bytes:  # zigzag
    return uvarint((v << 1) ^ (v >> 63))


def cstr(v: str) -> bytes:  # COMPACT_STRING
    b = v.encode()
    return uvarint(len(b) + 1) + b


def cbytes(b: bytes) -> bytes:  # COMPACT_BYTES
    return uvarint(len(b) + 1) + b


TAGS = b"\x00"  # empty tagged-field set


def hdr(api: int, ver: int, corr: int, client: str = "gold", flex=False) -> bytes:
    """Request header v1 (non-flex) / v2 (flex: tagged fields appended)."""
    b = struct.pack(">hhi", api, ver, corr) + s(client)
    return b + TAGS if flex else b


# Record batch v2, assembled per the spec (magic 2, CRC32C over the
# bytes after the crc field).
BASE_TS = 1_700_000_000_000  # fixed so every byte is deterministic


def record(offset_delta: int, ts_delta: int, key: bytes | None, value: bytes) -> bytes:
    body = (
        i8(0)  # attributes
        + varint(ts_delta)
        + varint(offset_delta)
        + (varint(-1) if key is None else varint(len(key)) + key)
        + varint(len(value))
        + value
        + varint(0)  # headers
    )
    return varint(len(body)) + body


def batch(
    records: list[bytes],
    base_offset: int = 0,
    attrs: int = 0,
    base_ts: int = BASE_TS,
    max_ts: int | None = None,
) -> bytes:
    post_crc = (
        i16(attrs)
        + i32(len(records) - 1)  # last_offset_delta
        + i64(base_ts)
        + i64(max_ts if max_ts is not None else base_ts + len(records) - 1)
        + i64(-1)  # producer_id
        + i16(-1)  # producer_epoch
        + i32(-1)  # base_sequence
        + i32(len(records))
        + b"".join(records)
    )
    body = (
        i32(-1)  # partition_leader_epoch
        + i8(2)  # magic
        + struct.pack(">I", crc32c(post_crc))
        + post_crc
    )
    return i64(base_offset) + i32(len(body)) + body


def compressed_batch(attrs: int, payload: bytes, count: int, last_delta: int, max_ts: int) -> bytes:
    """Batch whose records section is pre-compressed `payload`."""
    post_crc = (
        i16(attrs)
        + i32(last_delta)
        + i64(BASE_TS)
        + i64(max_ts)
        + i64(-1)
        + i16(-1)
        + i32(-1)
        + i32(count)
        + payload
    )
    body = i32(-1) + i8(2) + struct.pack(">I", crc32c(post_crc)) + post_crc
    return i64(0) + i32(len(body)) + body


# every broker response advertises host 127.0.0.1 + the ephemeral port
HOST = s("127.0.0.1")
PORT_W = W(4, "ephemeral port")

# The advertised version matrix — the wire CONTRACT this gateway
# publishes (api_key, min, max), hand-listed so a silent range change
# fails the corpus.
API_MATRIX = [
    (0, 3, 9),    # Produce
    (1, 4, 11),   # Fetch
    (2, 0, 5),    # ListOffsets
    (3, 0, 8),    # Metadata
    (8, 0, 7),    # OffsetCommit
    (9, 0, 5),    # OffsetFetch
    (10, 0, 2),   # FindCoordinator
    (11, 0, 5),   # JoinGroup
    (12, 0, 3),   # Heartbeat
    (13, 0, 3),   # LeaveGroup
    (14, 0, 3),   # SyncGroup
    (15, 0, 4),   # DescribeGroups
    (16, 0, 2),   # ListGroups
    (18, 0, 3),   # ApiVersions
    (19, 0, 4),   # CreateTopics
    (20, 0, 3),   # DeleteTopics
]

API_TABLE_V0 = i32(len(API_MATRIX)) + b"".join(
    i16(k) + i16(lo) + i16(hi) for k, lo, hi in API_MATRIX
)
API_TABLE_FLEX = uvarint(len(API_MATRIX) + 1) + b"".join(
    i16(k) + i16(lo) + i16(hi) + TAGS for k, lo, hi in API_MATRIX
)


# ---------------------------------------------------------- transcripts


def test_api_versions_golden(sess):
    # T1: ApiVersions v0 — empty body; response: corr, error, array
    sess.transcript(
        hdr(18, 0, corr=1),
        i32(1) + i16(0) + API_TABLE_V0,
    )
    # T2: ApiVersions v3 — flexible request (KIP-511 software name/
    # version), response header stays v0 (no tags) by spec
    sess.transcript(
        hdr(18, 3, corr=2, flex=True) + cstr("gold") + cstr("1.0") + TAGS,
        i32(2) + i16(0) + API_TABLE_FLEX + i32(0) + TAGS,
    )
    # T3: out-of-range ApiVersions v9 -> UNSUPPORTED_VERSION(35) with a
    # v0 body so any client can downgrade (KIP-511 behavior)
    sess.transcript(
        hdr(18, 9, corr=3),
        i32(3) + i16(35) + API_TABLE_V0,
    )


def test_metadata_topic_lifecycle_golden(sess):
    # T4: Metadata v0, empty topic array = all topics (none yet)
    sess.transcript(
        hdr(3, 0, corr=4) + i32(0),
        i32(4) + i32(1) + i32(0) + HOST, PORT_W, i32(0),
    )
    # T5: CreateTopics v0: 1 topic, 2 partitions, RF 1, no configs
    sess.transcript(
        hdr(19, 0, corr=5)
        + i32(1)  # topics array
        + s("golden")
        + i32(2)  # num_partitions
        + i16(1)  # replication_factor
        + i32(0)  # assignments
        + i32(0)  # configs
        + i32(30000),  # timeout_ms
        i32(5) + i32(1) + s("golden") + i16(0),
    )
    # T6: Metadata v1 for the created topic: brokers (+rack),
    # controller_id, topic (+is_internal), partitions
    part = lambda p: i16(0) + i32(p) + i32(0) + i32(1) + i32(0) + i32(1) + i32(0)  # noqa: E731
    sess.transcript(
        hdr(3, 1, corr=6) + i32(1) + s("golden"),
        i32(6)
        + i32(1) + i32(0) + HOST, PORT_W, nstr_null()  # broker + null rack
        , i32(0)  # controller_id
        + i32(1)  # topics
        + i16(0) + s("golden") + i8(0)  # error, name, is_internal
        + i32(2) + part(0) + part(1),
    )
    # T7: DeleteTopics v0
    sess.transcript(
        hdr(20, 0, corr=7) + i32(1) + s("golden") + i32(30000),
        i32(7) + i32(1) + s("golden") + i16(0),
    )


def _create(sess, topic: str, corr: int, partitions: int = 1) -> None:
    sess.transcript(
        hdr(19, 0, corr=corr)
        + i32(1) + s(topic) + i32(partitions) + i16(1) + i32(0) + i32(0)
        + i32(30000),
        i32(corr) + i32(1) + s(topic) + i16(0),
    )


def _produce_body(topic: str, b: bytes, acks: int = -1) -> bytes:
    """Produce v3-v8 request body (non-flexible)."""
    return (
        nstr_null()  # transactional_id
        + i16(acks)
        + i32(30000)  # timeout
        + i32(1) + s(topic)
        + i32(1) + i32(0)  # partition 0
        + i32(len(b)) + b  # records as BYTES
    )


def _fetch_body(topic: str, v: int, offset: int = 0) -> bytes:
    out = (
        i32(-1)  # replica_id
        + i32(100)  # max_wait_ms
        + i32(1)  # min_bytes
        + i32(1 << 20)  # max_bytes (v3+)
        + i8(0)  # isolation_level (v4+)
    )
    if v >= 7:
        out += i32(0) + i32(0)  # session_id, session_epoch
    out += i32(1) + s(topic) + i32(1) + i32(0)  # one topic, partition 0
    if v >= 9:
        out += i32(-1)  # current_leader_epoch
    out += i64(offset)
    if v >= 5:
        out += i64(0)  # log_start_offset
    out += i32(1 << 20)  # partition_max_bytes
    if v >= 7:
        out += i32(0)  # forgotten_topics_data
    if v >= 11:
        out += nstr_null()  # rack_id
    return out


def _produce_resp(topic: str, v: int, base: int = 0, corr: int = 0) -> bytes:
    out = i32(corr) + i32(1) + s(topic) + i32(1) + i32(0) + i16(0) + i64(base)
    if v >= 2:
        out += i64(-1)  # log_append_time
    if v >= 5:
        out += i64(0)  # log_start_offset
    if v >= 8:
        out += i32(0) + nstr_null()  # record_errors, error_message
    return out + i32(0)  # throttle (v1+)


def _fetch_resp(topic: str, v: int, hw: int, b: bytes, corr: int = 0) -> bytes:
    out = i32(corr) + i32(0)  # throttle
    if v >= 7:
        out += i16(0) + i32(0)  # top error, session_id
    out += i32(1) + s(topic) + i32(1)
    out += i32(0) + i16(0) + i64(hw) + i64(hw)  # partition, err, hw, lso
    if v >= 5:
        out += i64(0)  # log_start_offset
    out += i32(0)  # aborted_transactions
    if v >= 11:
        out += i32(-1)  # preferred_read_replica
    return out + i32(len(b)) + b


def test_produce_fetch_version_matrix_golden(sess):
    recs = [record(0, 0, b"k1", b"value-one"), record(1, 1, None, b"value-two")]
    wire = batch(recs)
    # echo: the broker re-encodes from stored (ts, key, value); with
    # fixed timestamps the bytes are fully deterministic and must be
    # the SAME spec batch
    for i, (pv, fv) in enumerate([(3, 4), (5, 6), (7, 8), (8, 10)]):
        topic = f"pf{pv}"
        _create(sess, topic, corr=10 + 10 * i)
        # produce at offset 0
        sess.transcript(
            hdr(0, pv, corr=11 + 10 * i) + _produce_body(topic, wire),
            _produce_resp(topic, pv, base=0, corr=11 + 10 * i),
        )
        sess.transcript(
            hdr(1, fv, corr=12 + 10 * i) + _fetch_body(topic, fv),
            _fetch_resp(topic, fv, hw=2, b=wire, corr=12 + 10 * i),
        )


def test_produce_v9_flexible_golden(sess):
    _create(sess, "flex9", corr=60)
    recs = [record(0, 0, b"k", b"flexible")]
    wire = batch(recs)
    body = (
        uvarint(0)  # null transactional_id (compact nullable)
        + i16(-1) + i32(30000)
        + uvarint(2) + cstr("flex9")  # compact topics array (1 entry)
        + uvarint(2) + i32(0)  # compact partitions array, index 0
        + cbytes(wire) + TAGS  # records + partition tags
        + TAGS  # topic tags
        + TAGS  # request tags
    )
    resp = (
        i32(61) + TAGS  # response header v1 (flexible)
        + uvarint(2) + cstr("flex9")
        + uvarint(2) + i32(0) + i16(0) + i64(0) + i64(-1) + i64(0)
        + uvarint(1)  # record_errors (empty compact array)
        + uvarint(0)  # null error_message
        + TAGS  # partition tags
        + TAGS  # topic tags
        + i32(0)  # throttle
        + TAGS  # response tags
    )
    sess.transcript(hdr(0, 9, corr=61, flex=True) + body, resp)
    # and read it back at the max fetch version
    sess.transcript(
        hdr(1, 11, corr=62) + _fetch_body("flex9", 11),
        _fetch_resp("flex9", 11, hw=1, b=wire, corr=62),
    )


def _snappy_raw(data: bytes) -> bytes:
    """Hand-built snappy block: uncompressed-length uvarint + literal
    tags (spec: tag byte (len-1)<<2 for literals <= 60 bytes)."""
    assert len(data) <= 60
    return uvarint(len(data)) + bytes([(len(data) - 1) << 2]) + data


def _lz4_frame_stored(data: bytes) -> bytes:
    """Hand-built LZ4 frame with one STORED block (spec escape hatch:
    high bit of block size = uncompressed)."""
    from seaweedfs_tpu.mq.kafka.codecs import xxh32

    flg, bd = 0x60, 0x70  # v01, block-independent; 4 MiB max block
    hc = (xxh32(bytes([flg, bd])) >> 8) & 0xFF
    return (
        struct.pack("<I", 0x184D2204)
        + bytes([flg, bd, hc])
        + struct.pack("<I", len(data) | 0x80000000)
        + data
        + struct.pack("<I", 0)
    )


def test_produce_compressed_codecs_golden(sess):
    """One transcript per codec id (1..4): the gateway must decode the
    compressed records section and ack; the fetch echo is the SAME
    records re-encoded uncompressed (deterministic bytes)."""
    plain = [record(0, 0, b"ck", b"codec-payload")]
    plain_wire = batch(plain)
    records_section = b"".join(plain)
    codecs = [
        (1, _gzip.compress(records_section, mtime=0)),  # gzip, fixed mtime
        (2, _snappy_raw(records_section)),
        (3, _lz4_frame_stored(records_section)),
    ]
    try:
        import zstandard

        codecs.append((4, zstandard.ZstdCompressor().compress(records_section)))
    except ImportError:
        pass
    for i, (codec, payload) in enumerate(codecs):
        topic = f"cz{codec}"
        _create(sess, topic, corr=70 + 10 * i)
        cb = compressed_batch(
            attrs=codec, payload=payload, count=1, last_delta=0, max_ts=BASE_TS
        )
        sess.transcript(
            hdr(0, 3, corr=71 + 10 * i) + _produce_body(topic, cb),
            _produce_resp(topic, 3, base=0, corr=71 + 10 * i),
        )
        sess.transcript(
            hdr(1, 4, corr=72 + 10 * i) + _fetch_body(topic, 4),
            _fetch_resp(topic, 4, hw=1, b=plain_wire, corr=72 + 10 * i),
        )


def _join_sync(sess, group: str, topic: str, corr: int):
    """JoinGroup (empty member id -> elected leader) + SyncGroup with a
    range assignment; returns (member_id, meta_bytes, assign_bytes) so
    callers never re-encode the wire shapes. One copy of the dance
    shared by the group-cycle and introspection tests."""
    meta = i16(0) + i32(1) + s(topic) + i32(0)  # consumer subscription v0
    member_w = W(2 + 4 + 13, "member id", capture="_js_member")
    sess.transcript(
        hdr(11, 0, corr=corr, client="gold")
        + s(group) + i32(10000) + s("") + s("consumer")
        + i32(1) + s("range") + i32(len(meta)) + meta,
        i32(corr) + i16(0) + i32(1) + s("range"),
        member_w,
        W(2 + 4 + 13, "member id"),
        i32(1),
        W(2 + 4 + 13, "member id"),
        i32(len(meta)) + meta,
    )
    member = sess.captured["_js_member"][2:].decode()
    assign = i16(0) + i32(1) + s(topic) + i32(1) + i32(0) + i32(0)
    sess.transcript(
        hdr(14, 0, corr=corr + 1)
        + s(group) + i32(1) + s(member)
        + i32(1) + s(member) + i32(len(assign)) + assign,
        i32(corr + 1) + i16(0) + i32(len(assign)) + assign,
    )
    return member, meta, assign


def test_group_cycle_golden(sess):
    _create(sess, "gt", corr=90)
    # T: FindCoordinator v0 (key only)
    sess.transcript(
        hdr(10, 0, corr=91) + s("g-gold"),
        i32(91) + i16(0) + i32(0) + HOST, PORT_W,
    )
    # T: JoinGroup v0 + SyncGroup v0 (shared wire dance; member_id =
    # "<client_id>-<12 hex>")
    member_s, _meta, _assign = _join_sync(sess, "g-gold", "gt", corr=92)
    # T: Heartbeat v0
    sess.transcript(
        hdr(12, 0, corr=94) + s("g-gold") + i32(1) + s(member_s),
        i32(94) + i16(0),
    )
    # T: OffsetCommit v2
    sess.transcript(
        hdr(8, 2, corr=95)
        + s("g-gold") + i32(1) + s(member_s) + i64(-1)
        + i32(1) + s("gt") + i32(1) + i32(0) + i64(41) + s("meta"),
        i32(95) + i32(1) + s("gt") + i32(1) + i32(0) + i16(0),
    )
    # T: OffsetFetch v1 (committed offset + metadata round-trip)
    sess.transcript(
        hdr(9, 1, corr=96) + s("g-gold") + i32(1) + s("gt") + i32(1) + i32(0),
        i32(96) + i32(1) + s("gt") + i32(1)
        + i32(0) + i64(41) + s("meta") + i16(0),
    )
    # T: LeaveGroup v0
    sess.transcript(
        hdr(13, 0, corr=97) + s("g-gold") + s(member_s),
        i32(97) + i16(0),
    )


def test_list_offsets_golden(sess):
    _create(sess, "lo", corr=100)
    wire = batch([record(0, 0, None, b"x"), record(1, 1, None, b"y")])
    sess.transcript(
        hdr(0, 3, corr=101) + _produce_body("lo", wire),
        _produce_resp("lo", 3, base=0, corr=101),
    )
    # ListOffsets v1: earliest (-2) and latest (-1)
    sess.transcript(
        hdr(2, 1, corr=102)
        + i32(-1)  # replica_id
        + i32(1) + s("lo") + i32(1) + i32(0) + i64(-2),
        i32(102) + i32(1) + s("lo") + i32(1)
        + i32(0) + i16(0) + i64(-1) + i64(0),  # ts, earliest offset
    )
    sess.transcript(
        hdr(2, 1, corr=103)
        + i32(-1)
        + i32(1) + s("lo") + i32(1) + i32(0) + i64(-1),
        i32(103) + i32(1) + s("lo") + i32(1)
        + i32(0) + i16(0) + i64(-1) + i64(2),  # latest = high watermark
    )


def test_error_paths_golden(sess):
    # unknown topic produce (auto-create may apply to metadata, not
    # produce): expect UNKNOWN_TOPIC_OR_PARTITION(3) with base -1
    wire = batch([record(0, 0, None, b"z")])
    sess.transcript(
        hdr(0, 3, corr=110) + _produce_body("nope", wire),
        i32(110) + i32(1) + s("nope") + i32(1)
        + i32(0) + i16(3) + i64(-1) + i64(-1) + i32(0),
    )
    # fetch beyond the high watermark: OFFSET_OUT_OF_RANGE(1)
    _create(sess, "oor", corr=111)
    sess.transcript(
        hdr(1, 4, corr=112) + _fetch_body("oor", 4, offset=99),
        i32(112) + i32(0) + i32(1) + s("oor") + i32(1)
        + i32(0) + i16(1) + i64(0) + i64(0) + i32(0)
        + i32(-1),  # null records
    )


def test_group_introspection_golden(sess):
    """ListGroups v1 + DescribeGroups v0: the group coordinator's
    introspection surface, byte-matched after a real join/sync."""
    _create(sess, "gi", corr=120)
    _member, meta, assign = _join_sync(sess, "g-intro", "gi", corr=121)
    # ListGroups v1: throttle, error, [(group, protocol_type)]
    sess.transcript(
        hdr(16, 1, corr=123),
        i32(123) + i32(0) + i16(0) + i32(1) + s("g-intro") + s("consumer"),
    )
    # DescribeGroups v0: Stable group with our member + assignment
    sess.transcript(
        hdr(15, 0, corr=124) + i32(1) + s("g-intro"),
        i32(124) + i32(1)
        + i16(0) + s("g-intro") + s("Stable") + s("consumer") + s("range")
        + i32(1),
        W(2 + 4 + 13, "member id"),
        s("gold")  # client_id (threaded from the request header)
        + s("/127.0.0.1")
        + i32(len(meta)) + meta
        + i32(len(assign)) + assign,
    )
    # unknown group reads as Dead, not an error
    sess.transcript(
        hdr(15, 0, corr=125) + i32(1) + s("nope"),
        i32(125) + i32(1)
        + i16(0) + s("nope") + s("Dead") + s("") + s("") + i32(0),
    )
