"""Golden-vector + property tests for the GF(256) / RS reference core.

Models the reference's ec_roundtrip_test.go and klauspost's galois_test.go
(the multiplication golden values 3*4=12, 7*7=21, 23*45=41 are from the
klauspost test suite for the 0x11D field).
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.gf256 import ReedSolomon


class TestField:
    def test_exp_table_golden(self):
        assert list(gf256.EXP_TABLE[:9]) == [1, 2, 4, 8, 16, 32, 64, 128, 29]
        assert gf256.LOG_TABLE[29] == 8
        assert gf256.EXP_TABLE[254] != 0

    def test_mul_golden(self):
        assert gf256.gal_mul(3, 4) == 12
        assert gf256.gal_mul(7, 7) == 21
        assert gf256.gal_mul(23, 45) == 41
        assert gf256.gal_mul(0, 99) == 0
        assert gf256.gal_mul(99, 0) == 0
        assert gf256.gal_mul(1, 99) == 99

    def test_mul_table_matches_scalar(self, rng):
        mt = gf256._mul_table()
        for _ in range(200):
            a, b = int(rng.integers(256)), int(rng.integers(256))
            assert mt[a, b] == gf256.gal_mul(a, b)

    def test_field_axioms(self, rng):
        for _ in range(100):
            a, b, c = (int(x) for x in rng.integers(0, 256, size=3))
            assert gf256.gal_mul(a, b) == gf256.gal_mul(b, a)
            assert gf256.gal_mul(a, gf256.gal_mul(b, c)) == gf256.gal_mul(
                gf256.gal_mul(a, b), c
            )
            assert gf256.gal_mul(a, b ^ c) == gf256.gal_mul(a, b) ^ gf256.gal_mul(a, c)

    def test_inverse(self):
        for a in range(1, 256):
            assert gf256.gal_mul(a, gf256.gal_inverse(a)) == 1

    def test_exp(self):
        assert gf256.gal_exp(2, 8) == 29
        assert gf256.gal_exp(0, 0) == 1
        assert gf256.gal_exp(0, 5) == 0
        assert gf256.gal_exp(7, 0) == 1


class TestMatrix:
    def test_vandermonde(self):
        vm = gf256.vandermonde(4, 3)
        assert vm[0].tolist() == [1, 0, 0]
        assert vm[1].tolist() == [1, 1, 1]
        assert vm[2].tolist() == [1, 2, 4]
        assert vm[3].tolist() == [1, 3, 5]  # 3*3=5 in GF(0x11D)

    def test_invert_roundtrip(self, rng):
        for n in (1, 2, 5, 10):
            # random invertible matrix via product of vandermonde rows
            while True:
                m = rng.integers(0, 256, size=(n, n)).astype(np.uint8)
                try:
                    inv = gf256.invert(m)
                    break
                except np.linalg.LinAlgError:
                    continue
            assert np.array_equal(gf256.matmul(m, inv), gf256.identity_matrix(n))

    def test_singular_raises(self):
        m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
        with pytest.raises(np.linalg.LinAlgError):
            gf256.invert(m)

    def test_build_matrix_systematic(self):
        m = gf256.build_matrix(10, 14)
        assert np.array_equal(m[:10], gf256.identity_matrix(10))
        # parity coefficients are all nonzero for the Vandermonde-derived matrix
        assert (m[10:] != 0).all()

    def test_build_matrix_mds_10_4(self):
        """Any k rows of the generator matrix must be invertible (MDS)."""
        import itertools

        m = gf256.build_matrix(10, 14)
        rng = np.random.default_rng(1)
        combos = list(itertools.combinations(range(14), 10))
        picks = rng.choice(len(combos), size=50, replace=False)
        for i in picks:
            rows = list(combos[i])
            gf256.invert(m[rows, :])  # must not raise

    def test_build_matrix_deterministic(self):
        a = gf256.build_matrix(10, 14)
        b = gf256.build_matrix(10, 14)
        assert np.array_equal(a, b)


class TestBitMatrix:
    def test_constant_bit_matrix_applies_mul(self, rng):
        for _ in range(50):
            c = int(rng.integers(256))
            mc = gf256.constant_bit_matrix(c)
            x = int(rng.integers(256))
            xbits = np.array([(x >> j) & 1 for j in range(8)], dtype=np.uint8)
            ybits = (mc @ xbits) % 2
            y = int((ybits << np.arange(8)).sum())
            assert y == gf256.gal_mul(c, x), (c, x)

    def test_expand_bit_matrix_encode_equiv(self, rng):
        k, m, n = 4, 2, 64
        coeffs = gf256.parity_rows(k, m)
        bm = gf256.expand_bit_matrix(coeffs)  # (16, 32)
        data = rng.integers(0, 256, size=(k, n)).astype(np.uint8)
        # bit-plane encode
        dbits = ((data[:, None, :] >> np.arange(8)[None, :, None]) & 1).reshape(
            8 * k, n
        )
        pbits = (bm.astype(np.int32) @ dbits.astype(np.int32)) % 2
        parity = (
            (pbits.reshape(m, 8, n) << np.arange(8)[None, :, None])
            .sum(axis=1)
            .astype(np.uint8)
        )
        assert np.array_equal(parity, gf256.matrix_apply(coeffs, data))


class TestReedSolomon:
    def test_encode_verify_roundtrip(self, rng):
        rs = ReedSolomon(10, 4)
        data = rng.integers(0, 256, size=(10, 1024)).astype(np.uint8)
        parity = rs.encode(data)
        shards = np.concatenate([data, parity])
        assert rs.verify(shards)
        shards[3, 100] ^= 1
        assert not rs.verify(shards)

    @pytest.mark.parametrize("missing", [[0], [13], [0, 13], [2, 7], [10, 11], [0, 5, 12, 13]])
    def test_reconstruct(self, rng, missing):
        rs = ReedSolomon(10, 4)
        data = rng.integers(0, 256, size=(10, 512)).astype(np.uint8)
        parity = rs.encode(data)
        full = np.concatenate([data, parity])
        present = {i: full[i] for i in range(14) if i not in missing}
        out = rs.reconstruct(present)
        assert sorted(out) == sorted(missing)
        for i in missing:
            assert np.array_equal(out[i], full[i]), f"shard {i} mismatch"

    def test_reconstruct_data_only(self, rng):
        rs = ReedSolomon(10, 4)
        data = rng.integers(0, 256, size=(10, 128)).astype(np.uint8)
        full = np.concatenate([data, rs.encode(data)])
        present = {i: full[i] for i in range(14) if i not in (1, 12)}
        out = rs.reconstruct(present, data_only=True)
        assert list(out) == [1]
        assert np.array_equal(out[1], full[1])

    def test_too_few_shards(self, rng):
        rs = ReedSolomon(4, 2)
        data = rng.integers(0, 256, size=(4, 16)).astype(np.uint8)
        full = np.concatenate([data, rs.encode(data)])
        present = {i: full[i] for i in range(3)}
        with pytest.raises(ValueError):
            rs.reconstruct(present)

    def test_custom_ratios(self, rng):
        """Custom EC ratios are first-class in the reference (.vif EcShardConfig)."""
        for k, m in [(3, 2), (9, 3), (5, 1), (12, 8)]:
            rs = ReedSolomon(k, m)
            data = rng.integers(0, 256, size=(k, 100)).astype(np.uint8)
            full = np.concatenate([data, rs.encode(data)])
            drop = set(np.random.default_rng(k * m).choice(k + m, size=min(m, k + m - k), replace=False).tolist())
            present = {i: full[i] for i in range(k + m) if i not in drop}
            out = rs.reconstruct(present)
            for i in drop:
                assert np.array_equal(out[i], full[i])
