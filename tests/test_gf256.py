"""Golden-vector + property tests for the GF(256) / RS reference core.

Models the reference's ec_roundtrip_test.go and klauspost's galois_test.go
(the multiplication golden values 3*4=12, 7*7=21, 23*45=41 are from the
klauspost test suite for the 0x11D field).
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.gf256 import ReedSolomon


class TestField:
    def test_exp_table_golden(self):
        assert list(gf256.EXP_TABLE[:9]) == [1, 2, 4, 8, 16, 32, 64, 128, 29]
        assert gf256.LOG_TABLE[29] == 8
        assert gf256.EXP_TABLE[254] != 0

    def test_mul_golden(self):
        assert gf256.gal_mul(3, 4) == 12
        assert gf256.gal_mul(7, 7) == 21
        assert gf256.gal_mul(23, 45) == 41
        assert gf256.gal_mul(0, 99) == 0
        assert gf256.gal_mul(99, 0) == 0
        assert gf256.gal_mul(1, 99) == 99

    def test_mul_table_matches_scalar(self, rng):
        mt = gf256._mul_table()
        for _ in range(200):
            a, b = int(rng.integers(256)), int(rng.integers(256))
            assert mt[a, b] == gf256.gal_mul(a, b)

    def test_field_axioms(self, rng):
        for _ in range(100):
            a, b, c = (int(x) for x in rng.integers(0, 256, size=3))
            assert gf256.gal_mul(a, b) == gf256.gal_mul(b, a)
            assert gf256.gal_mul(a, gf256.gal_mul(b, c)) == gf256.gal_mul(
                gf256.gal_mul(a, b), c
            )
            assert gf256.gal_mul(a, b ^ c) == gf256.gal_mul(a, b) ^ gf256.gal_mul(a, c)

    def test_inverse(self):
        for a in range(1, 256):
            assert gf256.gal_mul(a, gf256.gal_inverse(a)) == 1

    def test_exp(self):
        assert gf256.gal_exp(2, 8) == 29
        assert gf256.gal_exp(0, 0) == 1
        assert gf256.gal_exp(0, 5) == 0
        assert gf256.gal_exp(7, 0) == 1


class TestMatrix:
    def test_vandermonde(self):
        vm = gf256.vandermonde(4, 3)
        assert vm[0].tolist() == [1, 0, 0]
        assert vm[1].tolist() == [1, 1, 1]
        assert vm[2].tolist() == [1, 2, 4]
        assert vm[3].tolist() == [1, 3, 5]  # 3*3=5 in GF(0x11D)

    def test_invert_roundtrip(self, rng):
        for n in (1, 2, 5, 10):
            # random invertible matrix via product of vandermonde rows
            while True:
                m = rng.integers(0, 256, size=(n, n)).astype(np.uint8)
                try:
                    inv = gf256.invert(m)
                    break
                except np.linalg.LinAlgError:
                    continue
            assert np.array_equal(gf256.matmul(m, inv), gf256.identity_matrix(n))

    def test_singular_raises(self):
        m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
        with pytest.raises(np.linalg.LinAlgError):
            gf256.invert(m)

    def test_build_matrix_systematic(self):
        m = gf256.build_matrix(10, 14)
        assert np.array_equal(m[:10], gf256.identity_matrix(10))
        # parity coefficients are all nonzero for the Vandermonde-derived matrix
        assert (m[10:] != 0).all()

    def test_build_matrix_mds_10_4(self):
        """Any k rows of the generator matrix must be invertible (MDS)."""
        import itertools

        m = gf256.build_matrix(10, 14)
        rng = np.random.default_rng(1)
        combos = list(itertools.combinations(range(14), 10))
        picks = rng.choice(len(combos), size=50, replace=False)
        for i in picks:
            rows = list(combos[i])
            gf256.invert(m[rows, :])  # must not raise

    def test_build_matrix_deterministic(self):
        a = gf256.build_matrix(10, 14)
        b = gf256.build_matrix(10, 14)
        assert np.array_equal(a, b)


class TestBitMatrix:
    def test_constant_bit_matrix_applies_mul(self, rng):
        for _ in range(50):
            c = int(rng.integers(256))
            mc = gf256.constant_bit_matrix(c)
            x = int(rng.integers(256))
            xbits = np.array([(x >> j) & 1 for j in range(8)], dtype=np.uint8)
            ybits = (mc @ xbits) % 2
            y = int((ybits << np.arange(8)).sum())
            assert y == gf256.gal_mul(c, x), (c, x)

    def test_expand_bit_matrix_encode_equiv(self, rng):
        k, m, n = 4, 2, 64
        coeffs = gf256.parity_rows(k, m)
        bm = gf256.expand_bit_matrix(coeffs)  # (16, 32)
        data = rng.integers(0, 256, size=(k, n)).astype(np.uint8)
        # bit-plane encode
        dbits = ((data[:, None, :] >> np.arange(8)[None, :, None]) & 1).reshape(
            8 * k, n
        )
        pbits = (bm.astype(np.int32) @ dbits.astype(np.int32)) % 2
        parity = (
            (pbits.reshape(m, 8, n) << np.arange(8)[None, :, None])
            .sum(axis=1)
            .astype(np.uint8)
        )
        assert np.array_equal(parity, gf256.matrix_apply(coeffs, data))


class TestReedSolomon:
    def test_encode_verify_roundtrip(self, rng):
        rs = ReedSolomon(10, 4)
        data = rng.integers(0, 256, size=(10, 1024)).astype(np.uint8)
        parity = rs.encode(data)
        shards = np.concatenate([data, parity])
        assert rs.verify(shards)
        shards[3, 100] ^= 1
        assert not rs.verify(shards)

    @pytest.mark.parametrize("missing", [[0], [13], [0, 13], [2, 7], [10, 11], [0, 5, 12, 13]])
    def test_reconstruct(self, rng, missing):
        rs = ReedSolomon(10, 4)
        data = rng.integers(0, 256, size=(10, 512)).astype(np.uint8)
        parity = rs.encode(data)
        full = np.concatenate([data, parity])
        present = {i: full[i] for i in range(14) if i not in missing}
        out = rs.reconstruct(present)
        assert sorted(out) == sorted(missing)
        for i in missing:
            assert np.array_equal(out[i], full[i]), f"shard {i} mismatch"

    def test_reconstruct_data_only(self, rng):
        rs = ReedSolomon(10, 4)
        data = rng.integers(0, 256, size=(10, 128)).astype(np.uint8)
        full = np.concatenate([data, rs.encode(data)])
        present = {i: full[i] for i in range(14) if i not in (1, 12)}
        out = rs.reconstruct(present, data_only=True)
        assert list(out) == [1]
        assert np.array_equal(out[1], full[1])

    def test_too_few_shards(self, rng):
        rs = ReedSolomon(4, 2)
        data = rng.integers(0, 256, size=(4, 16)).astype(np.uint8)
        full = np.concatenate([data, rs.encode(data)])
        present = {i: full[i] for i in range(3)}
        with pytest.raises(ValueError):
            rs.reconstruct(present)

    def test_custom_ratios(self, rng):
        """Custom EC ratios are first-class in the reference (.vif EcShardConfig)."""
        for k, m in [(3, 2), (9, 3), (5, 1), (12, 8)]:
            rs = ReedSolomon(k, m)
            data = rng.integers(0, 256, size=(k, 100)).astype(np.uint8)
            full = np.concatenate([data, rs.encode(data)])
            drop = set(np.random.default_rng(k * m).choice(k + m, size=min(m, k + m - k), replace=False).tolist())
            present = {i: full[i] for i in range(k + m) if i not in drop}
            out = rs.reconstruct(present)
            for i in drop:
                assert np.array_equal(out[i], full[i])


class TestKlauspostGoldenLock:
    """Literal golden constants for klauspost/reedsolomon v1.14.1
    default-matrix compatibility (SURVEY.md §2.2: "test-locked by golden
    vectors").

    Provenance: no Go toolchain exists in this environment, so the
    constants were produced by TWO independent implementations of the
    library's published buildMatrix algorithm (vandermonde(total, k) x
    inverse of its top kxk block, over GF(2^8)/0x11D — the same
    log/exp-table field as Backblaze JavaReedSolomon): this package's
    table-driven gf256 module and a from-scratch Russian-peasant
    multiply + brute-force-inverse Gauss-Jordan derivation. Both agree
    on every byte below; the scalar products (3*4=12, 7*7=21, 23*45=41)
    additionally match the values pinned in klauspost's galois_test.go.
    """

    # The (4 x 10) parity coefficient block of reedsolomon.New(10, 4).
    PARITY_10_4 = np.array(
        [
            [129, 150, 175, 184, 210, 196, 254, 232, 3, 2],
            [150, 129, 184, 175, 196, 210, 232, 254, 2, 3],
            [191, 214, 98, 10, 6, 111, 223, 183, 5, 4],
            [214, 191, 10, 98, 111, 6, 183, 223, 4, 5],
        ],
        dtype=np.uint8,
    )

    def test_parity_matrix_bytes(self):
        assert np.array_equal(gf256.parity_rows(10, 4), self.PARITY_10_4)

    def test_full_matrix_top_identity(self):
        full = gf256.build_matrix(10, 14)
        assert np.array_equal(full[:10], np.eye(10, dtype=np.uint8))
        assert np.array_equal(full[10:], self.PARITY_10_4)

    def test_golden_parity_column(self):
        """Encode of the single byte-column [1..10]."""
        data = np.arange(1, 11, dtype=np.uint8).reshape(10, 1)
        parity = gf256.ReedSolomon(10, 4).encode(data)
        assert parity[:, 0].tolist() == [69, 242, 18, 118]

    def test_golden_parity_block_digest(self):
        """A 4KiB/shard deterministic block, digest-pinned so any drift
        in matrix or field arithmetic trips loudly."""
        import hashlib

        n = 4096
        data = (
            (np.arange(10, dtype=np.uint32)[:, None] * 131
             + np.arange(n, dtype=np.uint32)[None, :] * 7) % 256
        ).astype(np.uint8)
        parity = gf256.ReedSolomon(10, 4).encode(data)
        digest = hashlib.sha256(parity.tobytes()).hexdigest()
        assert digest == "025cb04b75d929fe6bcfbc4a2861070c64c2adce99860bf4334c48aac70e9ba5"

    def test_independent_rederivation(self):
        """The from-scratch (table-free) derivation, kept executable so
        the constants above are auditable."""

        def gmul(a, b):
            p = 0
            for _ in range(8):
                if b & 1:
                    p ^= a
                b >>= 1
                hi = a & 0x80
                a = (a << 1) & 0xFF
                if hi:
                    a ^= 0x1D
            return p

        def ginv(a):
            for x in range(1, 256):
                if gmul(a, x) == 1:
                    return x
            raise ZeroDivisionError(a)

        def gexp(a, e):
            r = 1
            for _ in range(e):
                r = gmul(r, a)
            return r

        import functools

        def mat_mul(A, B):
            return [
                [
                    functools.reduce(
                        lambda x, y: x ^ y,
                        (gmul(A[i][t], B[t][j]) for t in range(len(B))),
                        0,
                    )
                    for j in range(len(B[0]))
                ]
                for i in range(len(A))
            ]

        def mat_inv(M):
            n = len(M)
            W = [
                row[:] + [1 if i == j else 0 for j in range(n)]
                for i, row in enumerate(M)
            ]
            for c in range(n):
                if W[c][c] == 0:
                    for r in range(c + 1, n):
                        if W[r][c]:
                            W[c], W[r] = W[r], W[c]
                            break
                iv = ginv(W[c][c])
                W[c] = [gmul(iv, x) for x in W[c]]
                for r in range(n):
                    if r != c and W[r][c]:
                        f = W[r][c]
                        W[r] = [x ^ gmul(f, y) for x, y in zip(W[r], W[c])]
            return [row[n:] for row in W]

        k, m = 10, 4
        vm = [[gexp(r, c) for c in range(k)] for r in range(k + m)]
        full = mat_mul(vm, mat_inv([row[:k] for row in vm[:k]]))
        assert np.array_equal(
            np.array(full[k:], dtype=np.uint8), self.PARITY_10_4
        )
        assert gmul(3, 4) == 12 and gmul(7, 7) == 21 and gmul(23, 45) == 41
