"""Bit-exactness of the XLA RS path vs the numpy reference.

Models ec_roundtrip_test.go: encode -> drop shards -> reconstruct -> compare.
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs_jax
from seaweedfs_tpu.ops.gf256 import ReedSolomon
from seaweedfs_tpu.ops.rs_jax import RSJax


@pytest.fixture(scope="module")
def codec():
    return RSJax(10, 4)


@pytest.fixture(scope="module")
def ref():
    return ReedSolomon(10, 4)


def test_encode_bit_exact(codec, ref, rng):
    data = rng.integers(0, 256, size=(10, 4096)).astype(np.uint8)
    got = np.asarray(codec.encode(data))
    want = ref.encode(data)
    assert np.array_equal(got, want)


def test_encode_bit_exact_odd_sizes(codec, ref, rng):
    for n in (1, 7, 127, 257, 1000):
        data = rng.integers(0, 256, size=(10, n)).astype(np.uint8)
        assert np.array_equal(np.asarray(codec.encode(data)), ref.encode(data))


def test_encode_all_values(codec, ref):
    """Every byte value through every shard position."""
    data = np.tile(np.arange(256, dtype=np.uint8), (10, 1))
    for r in range(10):
        d = np.zeros((10, 256), dtype=np.uint8)
        d[r] = np.arange(256, dtype=np.uint8)
        assert np.array_equal(np.asarray(codec.encode(d)), ref.encode(d))
    assert np.array_equal(np.asarray(codec.encode(data)), ref.encode(data))


@pytest.mark.parametrize("missing", [[0], [9], [10], [13], [0, 13], [3, 7], [10, 12], [1, 2, 11, 13]])
def test_reconstruct_bit_exact(codec, ref, rng, missing):
    data = rng.integers(0, 256, size=(10, 1024)).astype(np.uint8)
    full = np.concatenate([data, ref.encode(data)])
    present = {i: full[i] for i in range(14) if i not in missing}
    out = codec.reconstruct(present)
    assert sorted(out) == sorted(missing)
    for i in missing:
        assert np.array_equal(np.asarray(out[i]), full[i])


def test_reconstruct_data_only(codec, ref, rng):
    data = rng.integers(0, 256, size=(10, 256)).astype(np.uint8)
    full = np.concatenate([data, ref.encode(data)])
    present = {i: full[i] for i in range(14) if i not in (4, 11)}
    out = codec.reconstruct(present, data_only=True)
    assert list(out) == [4]
    assert np.array_equal(np.asarray(out[4]), full[4])


def test_verify(codec, ref, rng):
    data = rng.integers(0, 256, size=(10, 64)).astype(np.uint8)
    full = np.concatenate([data, ref.encode(data)])
    assert codec.verify(full)
    full[0, 0] ^= 0x80
    assert not codec.verify(full)


def test_bitmajor_matrix_equiv(rng):
    """The bit-major layout must compute the same parity."""
    import jax.numpy as jnp

    coeffs = gf256.parity_rows(10, 4)
    bm = rs_jax.bit_matrix_bitmajor(coeffs)
    data = rng.integers(0, 256, size=(10, 512)).astype(np.uint8)
    got = np.asarray(
        rs_jax._apply_bits_bitmajor(jnp.asarray(bm, dtype=rs_jax._ACC_DTYPE), jnp.asarray(data))
    )
    want = gf256.matrix_apply(coeffs, data)
    assert np.array_equal(got, want)


def test_custom_ratios_jax(rng):
    for k, m in [(3, 2), (9, 3), (12, 8)]:
        codec = RSJax(k, m)
        ref = ReedSolomon(k, m)
        data = rng.integers(0, 256, size=(k, 200)).astype(np.uint8)
        assert np.array_equal(np.asarray(codec.encode(data)), ref.encode(data))
