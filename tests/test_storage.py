"""Storage engine tests: needle codec, needle maps, volume lifecycle.

Modeled on the reference's storage-engine unit style (fabricated volume
files, roundtrip + crash/corruption scenarios)."""

import os
import struct

import numpy as np
import pytest

from seaweedfs_tpu.storage.needle import (
    CrcError,
    Needle,
    VERSION2,
    VERSION3,
)
from seaweedfs_tpu.storage.needle_map import (
    MemDb,
    MemoryNeedleMap,
    SortedFileNeedleMap,
    walk_index_file,
)
from seaweedfs_tpu.storage.super_block import ReplicaPlacement, SuperBlock
from seaweedfs_tpu.storage.types import NeedleValue, padded_record_size
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    NotFoundError,
    ReadOnlyError,
    Volume,
)


class TestNeedleCodec:
    def test_roundtrip_minimal(self):
        n = Needle(cookie=0xDEADBEEF, needle_id=0x1234, data=b"hello world")
        for v in (VERSION2, VERSION3):
            raw = n.to_bytes(v)
            assert len(raw) % 8 == 0
            m = Needle.from_bytes(raw, v)
            assert m.cookie == n.cookie
            assert m.needle_id == n.needle_id
            assert m.data == b"hello world"

    def test_roundtrip_all_fields(self):
        n = Needle(cookie=7, needle_id=42, data=b"x" * 1000)
        n.set_name(b"file.txt")
        n.set_mime(b"text/plain")
        n.set_last_modified(1700000000)
        n.set_ttl(b"\x05m")
        n.set_pairs(b'{"k":"v"}')
        raw = n.to_bytes(VERSION3)
        m = Needle.from_bytes(raw, VERSION3)
        assert m.name == b"file.txt"
        assert m.mime == b"text/plain"
        assert m.last_modified == 1700000000
        assert m.ttl == b"\x05m"
        assert m.pairs == b'{"k":"v"}'
        assert m.append_at_ns == n.append_at_ns
        assert m.disk_size(VERSION3) == len(raw)

    def test_crc_detects_corruption(self):
        n = Needle(cookie=1, needle_id=2, data=b"payload-bytes")
        raw = bytearray(n.to_bytes(VERSION3))
        raw[20] ^= 0xFF  # flip a data byte
        with pytest.raises(CrcError):
            Needle.from_bytes(bytes(raw), VERSION3)

    def test_empty_needle_is_tombstone_shaped(self):
        n = Needle(cookie=0, needle_id=9)
        raw = n.to_bytes(VERSION3)
        _, nid, size = Needle.parse_header(raw)
        assert nid == 9 and size == 0

    def test_padding(self):
        for ln in range(0, 40):
            n = Needle(cookie=1, needle_id=1, data=b"a" * ln)
            assert len(n.to_bytes(VERSION3)) % 8 == 0


class TestSuperBlock:
    def test_roundtrip(self):
        sb = SuperBlock(
            version=3,
            replica_placement=ReplicaPlacement.parse("210"),
            ttl=b"\x03h",
            compaction_revision=7,
        )
        raw = sb.to_bytes()
        assert len(raw) == 8
        sb2 = SuperBlock.from_bytes(raw)
        assert sb2.version == 3
        assert str(sb2.replica_placement) == "210"
        assert sb2.ttl == b"\x03h"
        assert sb2.compaction_revision == 7

    def test_replica_placement_copy_count(self):
        assert ReplicaPlacement.parse("000").copy_count == 1
        assert ReplicaPlacement.parse("001").copy_count == 2
        assert ReplicaPlacement.parse("210").copy_count == 4


class TestNeedleMaps:
    def test_memory_map_replay(self, tmp_path):
        idx = str(tmp_path / "1.idx")
        m = MemoryNeedleMap(idx)
        m.put(10, 1, 100)
        m.put(20, 2, 200)
        m.delete(10)
        m.close()
        m2 = MemoryNeedleMap(idx)
        assert m2.get(10) is None
        assert m2.get(20).size == 200
        assert m2.deleted_counter == 1
        m2.close()

    def test_walk_index_file(self, tmp_path):
        idx = str(tmp_path / "2.idx")
        m = MemoryNeedleMap(idx)
        for i in range(5):
            m.put(i, i, i * 10)
        m.close()
        entries = list(walk_index_file(idx))
        assert [e.needle_id for e in entries] == list(range(5))

    def test_memdb_sorted_file(self, tmp_path):
        db = MemDb()
        for nid in (5, 1, 9, 3):
            db.put(NeedleValue(nid, nid, nid * 2))
        path = str(tmp_path / "x.ecx")
        db.write_sorted_file(path)
        sf = SortedFileNeedleMap(path)
        assert len(sf) == 4
        assert [e.needle_id for e in sf.ascending_visit()] == [1, 3, 5, 9]
        assert sf.get(9).size == 18
        assert sf.get(2) is None

    def test_sorted_file_partial_record_fatal(self, tmp_path):
        path = str(tmp_path / "bad.ecx")
        with open(path, "wb") as f:
            f.write(b"\x00" * 20)  # not a multiple of 16
        with pytest.raises(ValueError):
            SortedFileNeedleMap(path)


class TestVolume:
    def test_write_read_delete(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        n = Needle(cookie=0xABCD, needle_id=100, data=b"blob-data")
        v.write_needle(n)
        got = v.read_needle(100)
        assert got.data == b"blob-data"
        with pytest.raises(CookieMismatch):
            v.read_needle(100, cookie=0x9999)
        assert v.read_needle(100, cookie=0xABCD).data == b"blob-data"
        freed = v.delete_needle(100)
        assert freed > 0
        with pytest.raises(NotFoundError):
            v.read_needle(100)
        v.close()

    def test_reload_replays_index(self, tmp_path):
        v = Volume(str(tmp_path), 2)
        for i in range(20):
            v.write_needle(Needle(cookie=i, needle_id=i, data=bytes([i]) * 50))
        v.delete_needle(7)
        v.close()
        v2 = Volume(str(tmp_path), 2, create=False)
        assert v2.read_needle(5).data == bytes([5]) * 50
        with pytest.raises(NotFoundError):
            v2.read_needle(7)
        assert v2.stat().deleted_count == 1
        v2.close()

    def test_overwrite_appends(self, tmp_path):
        v = Volume(str(tmp_path), 3)
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"v1"))
        size_after_first = v.size
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"v2-new"))
        assert v.size > size_after_first
        assert v.read_needle(1).data == b"v2-new"
        v.close()

    def test_readonly(self, tmp_path):
        v = Volume(str(tmp_path), 4)
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"a"))
        v.set_read_only()
        with pytest.raises(ReadOnlyError):
            v.write_needle(Needle(cookie=1, needle_id=2, data=b"b"))
        with pytest.raises(ReadOnlyError):
            v.delete_needle(1)
        assert v.read_needle(1).data == b"a"
        v.close()

    def test_vacuum_reclaims_and_preserves(self, tmp_path):
        v = Volume(str(tmp_path), 5)
        keep = {}
        for i in range(50):
            data = os.urandom(100 + i)
            v.write_needle(Needle(cookie=i, needle_id=i, data=data))
            keep[i] = data
        for i in range(0, 50, 2):
            v.delete_needle(i)
            del keep[i]
        rev_before = v.super_block.compaction_revision
        reclaimed = v.vacuum()
        assert reclaimed > 0
        assert v.super_block.compaction_revision == rev_before + 1
        for i, data in keep.items():
            assert v.read_needle(i).data == data
        for i in range(0, 50, 2):
            with pytest.raises(NotFoundError):
                v.read_needle(i)
        assert v.garbage_ratio() == 0.0
        v.close()
        # reload after vacuum
        v2 = Volume(str(tmp_path), 5, create=False)
        for i, data in keep.items():
            assert v2.read_needle(i).data == data
        v2.close()

    def test_garbage_ratio(self, tmp_path):
        v = Volume(str(tmp_path), 6)
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"z" * 1000))
        assert v.garbage_ratio() == 0.0
        v.delete_needle(1)
        assert v.garbage_ratio() > 0.0
        v.close()
