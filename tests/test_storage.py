"""Storage engine tests: needle codec, needle maps, volume lifecycle.

Modeled on the reference's storage-engine unit style (fabricated volume
files, roundtrip + crash/corruption scenarios)."""

import os
import struct

import numpy as np
import pytest

from seaweedfs_tpu.storage.needle import (
    CrcError,
    Needle,
    VERSION2,
    VERSION3,
)
from seaweedfs_tpu.storage.needle_map import (
    MemDb,
    MemoryNeedleMap,
    SortedFileNeedleMap,
    walk_index_file,
)
from seaweedfs_tpu.storage.super_block import ReplicaPlacement, SuperBlock
from seaweedfs_tpu.storage.types import NeedleValue, padded_record_size
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    NotFoundError,
    ReadOnlyError,
    Volume,
    VolumeError,
)


class TestNeedleCodec:
    def test_roundtrip_minimal(self):
        n = Needle(cookie=0xDEADBEEF, needle_id=0x1234, data=b"hello world")
        for v in (VERSION2, VERSION3):
            raw = n.to_bytes(v)
            assert len(raw) % 8 == 0
            m = Needle.from_bytes(raw, v)
            assert m.cookie == n.cookie
            assert m.needle_id == n.needle_id
            assert m.data == b"hello world"

    def test_roundtrip_all_fields(self):
        n = Needle(cookie=7, needle_id=42, data=b"x" * 1000)
        n.set_name(b"file.txt")
        n.set_mime(b"text/plain")
        n.set_last_modified(1700000000)
        n.set_ttl(b"\x05m")
        n.set_pairs(b'{"k":"v"}')
        raw = n.to_bytes(VERSION3)
        m = Needle.from_bytes(raw, VERSION3)
        assert m.name == b"file.txt"
        assert m.mime == b"text/plain"
        assert m.last_modified == 1700000000
        assert m.ttl == b"\x05m"
        assert m.pairs == b'{"k":"v"}'
        assert m.append_at_ns == n.append_at_ns
        assert m.disk_size(VERSION3) == len(raw)

    def test_crc_detects_corruption(self):
        n = Needle(cookie=1, needle_id=2, data=b"payload-bytes")
        raw = bytearray(n.to_bytes(VERSION3))
        raw[20] ^= 0xFF  # flip a data byte
        with pytest.raises(CrcError):
            Needle.from_bytes(bytes(raw), VERSION3)

    def test_empty_needle_is_tombstone_shaped(self):
        n = Needle(cookie=0, needle_id=9)
        raw = n.to_bytes(VERSION3)
        _, nid, size = Needle.parse_header(raw)
        assert nid == 9 and size == 0

    def test_padding(self):
        for ln in range(0, 40):
            n = Needle(cookie=1, needle_id=1, data=b"a" * ln)
            assert len(n.to_bytes(VERSION3)) % 8 == 0


class TestSuperBlock:
    def test_roundtrip(self):
        sb = SuperBlock(
            version=3,
            replica_placement=ReplicaPlacement.parse("210"),
            ttl=b"\x03h",
            compaction_revision=7,
        )
        raw = sb.to_bytes()
        assert len(raw) == 8
        sb2 = SuperBlock.from_bytes(raw)
        assert sb2.version == 3
        assert str(sb2.replica_placement) == "210"
        assert sb2.ttl == b"\x03h"
        assert sb2.compaction_revision == 7

    def test_replica_placement_copy_count(self):
        assert ReplicaPlacement.parse("000").copy_count == 1
        assert ReplicaPlacement.parse("001").copy_count == 2
        assert ReplicaPlacement.parse("210").copy_count == 4


class TestNeedleMaps:
    def test_memory_map_replay(self, tmp_path):
        idx = str(tmp_path / "1.idx")
        m = MemoryNeedleMap(idx)
        m.put(10, 1, 100)
        m.put(20, 2, 200)
        m.delete(10)
        m.close()
        m2 = MemoryNeedleMap(idx)
        assert m2.get(10) is None
        assert m2.get(20).size == 200
        assert m2.deleted_counter == 1
        m2.close()

    def test_walk_index_file(self, tmp_path):
        idx = str(tmp_path / "2.idx")
        m = MemoryNeedleMap(idx)
        for i in range(5):
            m.put(i, i, i * 10)
        m.close()
        entries = list(walk_index_file(idx))
        assert [e.needle_id for e in entries] == list(range(5))

    def test_memdb_sorted_file(self, tmp_path):
        db = MemDb()
        for nid in (5, 1, 9, 3):
            db.put(NeedleValue(nid, nid, nid * 2))
        path = str(tmp_path / "x.ecx")
        db.write_sorted_file(path)
        sf = SortedFileNeedleMap(path)
        assert len(sf) == 4
        assert [e.needle_id for e in sf.ascending_visit()] == [1, 3, 5, 9]
        assert sf.get(9).size == 18
        assert sf.get(2) is None

    def test_sorted_file_partial_record_fatal(self, tmp_path):
        path = str(tmp_path / "bad.ecx")
        with open(path, "wb") as f:
            f.write(b"\x00" * 20)  # not a multiple of 16
        with pytest.raises(ValueError):
            SortedFileNeedleMap(path)


class TestVolume:
    def test_write_read_delete(self, tmp_path):
        v = Volume(str(tmp_path), 1)
        n = Needle(cookie=0xABCD, needle_id=100, data=b"blob-data")
        v.write_needle(n)
        got = v.read_needle(100)
        assert got.data == b"blob-data"
        with pytest.raises(CookieMismatch):
            v.read_needle(100, cookie=0x9999)
        assert v.read_needle(100, cookie=0xABCD).data == b"blob-data"
        freed = v.delete_needle(100)
        assert freed > 0
        with pytest.raises(NotFoundError):
            v.read_needle(100)
        v.close()

    def test_reload_replays_index(self, tmp_path):
        v = Volume(str(tmp_path), 2)
        for i in range(20):
            v.write_needle(Needle(cookie=i, needle_id=i, data=bytes([i]) * 50))
        v.delete_needle(7)
        v.close()
        v2 = Volume(str(tmp_path), 2, create=False)
        assert v2.read_needle(5).data == bytes([5]) * 50
        with pytest.raises(NotFoundError):
            v2.read_needle(7)
        assert v2.stat().deleted_count == 1
        v2.close()

    def test_overwrite_appends(self, tmp_path):
        v = Volume(str(tmp_path), 3)
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"v1"))
        size_after_first = v.size
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"v2-new"))
        assert v.size > size_after_first
        assert v.read_needle(1).data == b"v2-new"
        v.close()

    def test_readonly(self, tmp_path):
        v = Volume(str(tmp_path), 4)
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"a"))
        v.set_read_only()
        with pytest.raises(ReadOnlyError):
            v.write_needle(Needle(cookie=1, needle_id=2, data=b"b"))
        with pytest.raises(ReadOnlyError):
            v.delete_needle(1)
        assert v.read_needle(1).data == b"a"
        v.close()

    def test_vacuum_reclaims_and_preserves(self, tmp_path):
        v = Volume(str(tmp_path), 5)
        keep = {}
        for i in range(50):
            data = os.urandom(100 + i)
            v.write_needle(Needle(cookie=i, needle_id=i, data=data))
            keep[i] = data
        for i in range(0, 50, 2):
            v.delete_needle(i)
            del keep[i]
        rev_before = v.super_block.compaction_revision
        reclaimed = v.vacuum()
        assert reclaimed > 0
        assert v.super_block.compaction_revision == rev_before + 1
        for i, data in keep.items():
            assert v.read_needle(i).data == data
        for i in range(0, 50, 2):
            with pytest.raises(NotFoundError):
                v.read_needle(i)
        assert v.garbage_ratio() == 0.0
        v.close()
        # reload after vacuum
        v2 = Volume(str(tmp_path), 5, create=False)
        for i, data in keep.items():
            assert v2.read_needle(i).data == data
        v2.close()

    def test_garbage_ratio(self, tmp_path):
        v = Volume(str(tmp_path), 6)
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"z" * 1000))
        assert v.garbage_ratio() == 0.0
        v.delete_needle(1)
        assert v.garbage_ratio() > 0.0
        v.close()


class TestVacuumCommitFailure:
    def test_volume_serves_after_failed_commit(self, tmp_path, monkeypatch):
        """A failed .dat swap must leave the volume serving from the
        pre-vacuum files, not with closed handles (503s until restart)."""
        v = Volume(str(tmp_path), 7)
        keep = {}
        for i in range(20):
            data = os.urandom(64 + i)
            v.write_needle(Needle(cookie=i, needle_id=i, data=data))
            keep[i] = data
        for i in range(0, 20, 2):
            v.delete_needle(i)
            del keep[i]

        real_replace = os.replace

        def boom(src, dst):
            if dst.endswith(".dat"):
                raise OSError("simulated rename failure")
            return real_replace(src, dst)

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            v.vacuum()
        monkeypatch.undo()

        # still serves reads AND writes from the old files
        for i, data in keep.items():
            assert v.read_needle(i).data == data
        v.write_needle(Needle(cookie=99, needle_id=99, data=b"after-fail"))
        assert v.read_needle(99).data == b"after-fail"
        # no stale temp files left behind
        assert not os.path.exists(v.dat_path[:-4] + ".cpd")
        assert not os.path.exists(v.idx_path[:-4] + ".cpx")
        # and a later vacuum succeeds
        rev = v.super_block.compaction_revision
        assert v.vacuum() > 0
        assert v.super_block.compaction_revision == rev + 1
        for i, data in keep.items():
            assert v.read_needle(i).data == data
        v.close()

    def test_rolls_forward_when_idx_swap_fails(self, tmp_path, monkeypatch):
        """If .dat swapped but .idx failed, the commit completes via the
        marker reconcile (cpx is durable) so the pair never diverges —
        and the vacuum reports success."""
        v = Volume(str(tmp_path), 8)
        keep = {}
        for i in range(20):
            data = os.urandom(64 + i)
            v.write_needle(Needle(cookie=i, needle_id=i, data=data))
            keep[i] = data
        for i in range(0, 20, 2):
            v.delete_needle(i)
            del keep[i]

        real_replace = os.replace
        fail_once = {"armed": True}

        def boom(src, dst):
            if dst.endswith(".idx") and fail_once["armed"]:
                fail_once["armed"] = False
                raise OSError("simulated idx rename failure")
            return real_replace(src, dst)

        monkeypatch.setattr(os, "replace", boom)
        reclaimed = v.vacuum()
        monkeypatch.undo()
        assert reclaimed > 0

        # rolled forward: compacted pair is live and consistent
        for i, data in keep.items():
            assert v.read_needle(i).data == data
        assert v.garbage_ratio() == 0.0
        assert not os.path.exists(v.dat_path[:-4] + ".cpm")
        v.close()
        v2 = Volume(str(tmp_path), 8, create=False)
        for i, data in keep.items():
            assert v2.read_needle(i).data == data
        v2.close()

    def test_crash_between_swaps_heals_on_open(self, tmp_path):
        """Marker + temps on disk (crash after the commit point, before
        the swaps): the next open finishes the swap, so the compacted
        pair — not the stale one — is served."""
        import shutil

        v = Volume(str(tmp_path), 9)
        keep = {}
        for i in range(20):
            data = os.urandom(64 + i)
            v.write_needle(Needle(cookie=i, needle_id=i, data=data))
            keep[i] = data
        for i in range(0, 20, 2):
            v.delete_needle(i)
            del keep[i]
        v.close()
        base = v.dat_path[:-4]

        # Fabricate the committed-but-unswapped state: compact into a
        # scratch dir, stage the results as .cpd/.cpx + marker next to
        # the UNcompacted originals.
        scratch = str(tmp_path / "scratch")
        os.makedirs(scratch)
        for ext in (".dat", ".idx"):
            shutil.copy(base + ext, os.path.join(scratch, "9" + ext))
        sv = Volume(scratch, 9, create=False)
        assert sv.vacuum() > 0
        sv.close()
        shutil.copy(os.path.join(scratch, "9.dat"), base + ".cpd")
        shutil.copy(os.path.join(scratch, "9.idx"), base + ".cpx")
        with open(base + ".cpm", "wb"):
            pass

        v2 = Volume(str(tmp_path), 9, create=False)
        for p in (".cpm", ".cpd", ".cpx"):
            assert not os.path.exists(base + p)
        assert v2.garbage_ratio() == 0.0  # the compacted pair won
        for i, data in keep.items():
            assert v2.read_needle(i).data == data
        v2.close()

    def test_stale_temps_without_marker_are_aborted(self, tmp_path):
        """Temps with NO marker (crash before the commit point) are
        discarded on open; the original pair keeps serving."""
        v = Volume(str(tmp_path), 10)
        v.write_needle(Needle(cookie=1, needle_id=1, data=b"keep me"))
        v.close()
        base = v.dat_path[:-4]
        for ext in (".cpd", ".cpx"):
            with open(base + ext, "wb") as f:
                f.write(b"partial garbage")
        v2 = Volume(str(tmp_path), 10, create=False)
        assert not os.path.exists(base + ".cpd")
        assert not os.path.exists(base + ".cpx")
        assert v2.read_needle(1).data == b"keep me"
        v2.close()

    def test_unfinishable_commit_poisons_volume(self, tmp_path, monkeypatch):
        """.dat swapped but .idx swap fails persistently: the object is
        poisoned (clear VolumeError, no IO on the diverged pair) and a
        reopen heals from the durable marker + cpx."""
        v = Volume(str(tmp_path), 11)
        keep = {}
        for i in range(20):
            data = os.urandom(64 + i)
            v.write_needle(Needle(cookie=i, needle_id=i, data=data))
            keep[i] = data
        for i in range(0, 20, 2):
            v.delete_needle(i)
            del keep[i]

        real_replace = os.replace

        def boom(src, dst):
            if dst.endswith(".idx"):
                raise OSError("persistent idx rename failure")
            return real_replace(src, dst)

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            v.vacuum()
        monkeypatch.undo()

        assert v.broken and v.read_only
        with pytest.raises(VolumeError):
            v.read_needle(1)
        with pytest.raises(VolumeError):
            v.write_needle(Needle(cookie=5, needle_id=55, data=b"no"))
        with pytest.raises(VolumeError):
            v.vacuum()
        # marker + committed cpx survived for the heal
        base = v.dat_path[:-4]
        assert os.path.exists(base + ".cpm") and os.path.exists(base + ".cpx")

        v2 = Volume(str(tmp_path), 11, create=False)
        assert not os.path.exists(base + ".cpm")
        assert v2.garbage_ratio() == 0.0  # compacted pair live
        for i, data in keep.items():
            assert v2.read_needle(i).data == data
        v2.close()
