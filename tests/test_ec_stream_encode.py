"""PR 14 streaming EC core: encode-on-write with incremental parity
(`ec/stream_encode.py`).

Load-bearing properties:

- RS-linearity bit identity: N appends of arbitrary sizes through
  `EcStreamEncoder` produce byte-identical shard files AND sidecar
  CRCs to ONE `write_ec_files` over the concatenation — across
  CPU / single-device JAX / 8-chip mesh / FallbackBackend, with ragged
  tails, exact stripe multiples, and the empty stream;
- the stripe-cursor journal is self-checksummed (torn -> ignored) and
  only ever advances AFTER the fsync it describes;
- recovery replays the verified prefix, re-derives parity that
  disagrees with the data (data is ground truth), rolls back past the
  verified head, and is idempotent;
- time-to-durable-parity is observable: the lag histogram drains on
  flush and `parity_lag_s()` tracks the oldest un-flushed append.
"""

import os
import random

import numpy as np
import pytest

from seaweedfs_tpu.ec import (
    CpuBackend,
    ECContext,
    EcStreamEncoder,
    FallbackBackend,
    JaxBackend,
    load_stream_journal,
    recover_stream,
    write_ec_files,
)
from seaweedfs_tpu.ec.stream_encode import (
    StreamJournal,
    read_stream_data,
    stream_summary,
)

CTX = ECContext(10, 4)
SMALL_CTX = ECContext(4, 2)
BLOCK = 64 * 1024
SMALL = 4 * 1024


def _payload(n: int, seed: int = 0) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8
    ).tobytes()


def _stream_encode(base, payload, ctx=CTX, backend=None, seed=1,
                   flush_p=0.3, block=BLOCK, small=SMALL):
    rng = random.Random(seed)
    enc = EcStreamEncoder(
        base, ctx, backend=backend, block_size=block, small_block_size=small
    )
    pos = 0
    while pos < len(payload):
        n = rng.randrange(1, 48 * 1024)
        enc.append(payload[pos : pos + n])
        pos += n
        if rng.random() < flush_p:
            enc.flush()
    return enc.close()


def _batch_encode(base, payload, ctx=CTX, backend=None,
                  block=BLOCK, small=SMALL):
    with open(base + ".dat", "wb") as f:
        f.write(payload)
    return write_ec_files(
        base, ctx, backend or CpuBackend(ctx),
        large_block_size=block, small_block_size=small,
    )


def _assert_identical(b1, b2, ctx, prot1, prot2):
    for i in range(ctx.total):
        a = open(b1 + ctx.to_ext(i), "rb").read()
        b = open(b2 + ctx.to_ext(i), "rb").read()
        assert a == b, f"shard {i} differs ({len(a)} vs {len(b)} bytes)"
    assert prot1.shard_sizes == prot2.shard_sizes
    assert prot1.shard_crcs == prot2.shard_crcs
    assert prot1.shard_leaf_crcs == prot2.shard_leaf_crcs


# ------------------------------------------------------------- identity


def test_stream_vs_batch_bit_identity_cpu_ragged(tmp_path):
    """The RS-linearity identity: incremental parity over arbitrary
    append boundaries == one-shot batch encode, ragged tail included."""
    payload = _payload(3 * 10 * BLOCK + 12345)
    be = CpuBackend(CTX)
    p1 = _stream_encode(str(tmp_path / "s"), payload, backend=be)
    p2 = _batch_encode(str(tmp_path / "b"), payload, backend=be)
    _assert_identical(str(tmp_path / "s"), str(tmp_path / "b"), CTX, p1, p2)
    # finalize retires the journal: the artifact is a sealed EC layout
    assert load_stream_journal(str(tmp_path / "s")) is None
    assert os.path.exists(str(tmp_path / "s") + ".ecsum")


@pytest.mark.parametrize(
    "total",
    [
        0,  # empty stream
        10 * BLOCK,  # exactly one large stripe
        3 * 4 * SMALL,  # sub-stripe: small blocks only
        100,  # sub-small-row: one zero-padded small stripe
        2 * 10 * BLOCK + 10 * SMALL * 4 + 7,  # stripes + small + ragged
    ],
)
def test_stream_vs_batch_identity_shapes(tmp_path, total):
    payload = _payload(total, seed=total % 97)
    be = CpuBackend(CTX)
    p1 = _stream_encode(str(tmp_path / "s"), payload, backend=be)
    p2 = _batch_encode(str(tmp_path / "b"), payload, backend=be)
    _assert_identical(str(tmp_path / "s"), str(tmp_path / "b"), CTX, p1, p2)


def test_stream_identity_cross_backends(tmp_path):
    """CPU, single-device JAX, the 8-chip column mesh, and the
    CPU-fallback wrapper all stream to the SAME bytes as the batch CPU
    encode — placement/backend choice is scheduling only."""
    payload = _payload(10 * BLOCK + 3 * 4096 + 11, seed=5)
    ref = _batch_encode(str(tmp_path / "ref"), payload, backend=CpuBackend(CTX))
    backends = {
        "cpu": CpuBackend(CTX),
        "jax1": JaxBackend(CTX, impl="xla", n_devices=1),
        "mesh": JaxBackend(CTX),  # 8 virtual devices -> chip pool
        "fallback": FallbackBackend(
            JaxBackend(CTX, impl="xla", n_devices=1), CpuBackend(CTX)
        ),
    }
    for name, be in backends.items():
        base = str(tmp_path / name)
        prot = _stream_encode(base, payload, backend=be, seed=hash(name) % 999)
        _assert_identical(base, str(tmp_path / "ref"), CTX, prot, ref)


# -------------------------------------------------------------- journal


def test_journal_roundtrip_and_torn(tmp_path):
    base = str(tmp_path / "j")
    j = StreamJournal(
        uuid=b"u" * 16, meta=77, durable=1234, sealed=2, head=2222,
        block_size=BLOCK, small_block_size=SMALL,
        data_shards=4, parity_shards=2,
    )
    from seaweedfs_tpu.utils.fs import atomic_write

    atomic_write(base + ".stream", j.to_bytes())
    j2 = load_stream_journal(base)
    assert (j2.meta, j2.durable, j2.sealed, j2.head) == (77, 1234, 2, 2222)
    assert (j2.data_shards, j2.parity_shards) == (4, 2)
    # torn journal (any flipped byte) fails its checksum -> None
    raw = bytearray(j.to_bytes())
    raw[7] ^= 0xFF
    with open(base + ".stream", "wb") as f:
        f.write(bytes(raw))
    assert load_stream_journal(base) is None
    # short file -> None
    with open(base + ".stream", "wb") as f:
        f.write(b"xx")
    assert load_stream_journal(base) is None
    assert load_stream_journal(str(tmp_path / "absent")) is None


def test_journal_advances_only_on_flush(tmp_path):
    base = str(tmp_path / "s")
    enc = EcStreamEncoder(
        base, SMALL_CTX, backend=CpuBackend(SMALL_CTX),
        block_size=8192, small_block_size=1024,
    )
    enc.append(b"x" * 5000)
    j = load_stream_journal(base)
    assert j.durable == 0  # appended, not durable
    enc.flush()
    j = load_stream_journal(base)
    assert j.durable == 5000 and j.meta == 0
    enc.close(finalize=False)
    # non-finalized close keeps the journal (broker rotation path)
    assert load_stream_journal(base) is not None


# ------------------------------------------------------------- recovery


def test_recovery_replays_verified_prefix_and_rewrites_parity(tmp_path):
    base = str(tmp_path / "s")
    be = CpuBackend(SMALL_CTX)
    payload = _payload(100_000, seed=9)
    enc = EcStreamEncoder(
        base, SMALL_CTX, backend=be, block_size=8192, small_block_size=1024,
    )
    enc.append(payload[:60_000])
    enc.flush()
    enc.append(payload[60_000:])
    enc.process()  # data pwritten, parity in memory only — then "crash"
    for fd in enc._fds:
        os.close(fd)
    enc._fds = []
    enc.closed = True

    rec = recover_stream(base, SMALL_CTX, be)
    assert rec is not None
    assert rec.journal.durable == 60_000
    # data on disk extends past the cursor; recovery trusts the data
    # (ground truth) and re-derives the parity that never flushed
    assert rec.head >= 60_000
    assert rec.data == payload[: rec.head]
    assert rec.parity_rewritten >= 1
    # idempotent: a second pass verifies clean and rewrites nothing
    rec2 = recover_stream(base, SMALL_CTX, be)
    assert rec2.head == rec.head and rec2.parity_rewritten == 0
    # linear read-back serves the recovered region
    assert read_stream_data(base, SMALL_CTX, 8192, 0, rec.head) == rec.data


def test_recovery_rolls_back_past_frame_scan(tmp_path):
    """The embedder's frame scan is the head authority: bytes past it
    are rolled back (truncated) so they can never resurface."""
    base = str(tmp_path / "s")
    be = CpuBackend(SMALL_CTX)
    payload = _payload(50_000, seed=11)
    enc = EcStreamEncoder(
        base, SMALL_CTX, backend=be, block_size=8192, small_block_size=1024,
    )
    enc.append(payload)
    enc.flush()
    enc.close(finalize=False)

    cut = 30_000
    rec = recover_stream(
        base, SMALL_CTX, be, frame_scan=lambda raw: min(len(raw), cut)
    )
    assert rec.head == cut
    assert rec.data == payload[:cut]
    assert rec.rolled_back == 50_000 - cut
    # the rollback is durable: a frame-scan-free second recovery sees
    # only the trimmed extent
    rec2 = recover_stream(base, SMALL_CTX, be)
    assert rec2.head == cut and rec2.parity_rewritten == 0


def test_recovery_without_journal_recovers_nothing(tmp_path):
    base = str(tmp_path / "s")
    be = CpuBackend(SMALL_CTX)
    enc = EcStreamEncoder(
        base, SMALL_CTX, backend=be, block_size=8192, small_block_size=1024,
    )
    enc.append(b"y" * 10_000)
    enc.flush()
    enc.close(finalize=False)
    os.unlink(base + ".stream")
    assert recover_stream(base, SMALL_CTX, be) is None


# ---------------------------------------------------- lag + observability


def test_parity_lag_and_stream_summary(tmp_path):
    from seaweedfs_tpu.ec.stream_encode import _parity_lag

    base = str(tmp_path / "s")
    enc = EcStreamEncoder(
        base, SMALL_CTX, backend=CpuBackend(SMALL_CTX),
        block_size=8192, small_block_size=1024,
    )
    assert enc.parity_lag_s() == 0.0
    enc.append(b"z" * 1000)
    assert enc.parity_lag_s() > 0.0  # oldest un-durable append ages
    before = sum(t for _c, t, _s in _parity_lag.snapshot().values())
    summ = stream_summary()
    assert summ["open"] >= 1
    assert any(s["base"] == "s" for s in summ["streams"])
    enc.flush()
    assert enc.parity_lag_s() == 0.0
    after = sum(t for _c, t, _s in _parity_lag.snapshot().values())
    assert after == before + 1  # one append -> one lag observation
    enc.close()
    assert all(s["base"] != "s" for s in stream_summary()["streams"])


def test_append_after_close_refused(tmp_path):
    from seaweedfs_tpu.ec.context import ECError

    enc = EcStreamEncoder(
        str(tmp_path / "s"), SMALL_CTX, backend=CpuBackend(SMALL_CTX),
        block_size=8192, small_block_size=1024,
    )
    enc.append(b"a")
    enc.close()
    with pytest.raises(ECError):
        enc.append(b"b")
    assert enc.close() is None  # idempotent
