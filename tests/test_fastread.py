"""Bulk-read fast path (native/fastread.cpp + utils/fastread.py) —
the RDMA-sidecar analog (SURVEY §2.10).
"""

import os
import time

import pytest
import requests

from conftest import allocate_port
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.utils.fastread import (
    FastReadClient,
    FastReadError,
    start_server,
    stop_server,
)


def test_raw_server_round_trip_and_confinement(tmp_path):
    root = tmp_path / "served"
    root.mkdir()
    blob = os.urandom(300_000)
    (root / "vol.dat").write_bytes(blob)
    secret = tmp_path / "secret.txt"
    secret.write_bytes(b"never serve this")
    sock = str(root / ".fr.sock")
    start_server(sock, str(root))
    try:
        c = FastReadClient(sock)
        assert c.read(str(root / "vol.dat"), 0, len(blob)) == blob
        # ranged
        assert c.read(str(root / "vol.dat"), 1000, 50) == blob[1000:1050]
        # several requests on one connection
        for off in (0, 7, 299_000):
            assert c.read(str(root / "vol.dat"), off, 100) == blob[off : off + 100]
        # range beyond EOF
        with pytest.raises(FastReadError, match="EOF"):
            c.read(str(root / "vol.dat"), len(blob) - 10, 100)
        # root confinement: absolute path outside + traversal
        c2 = FastReadClient(sock)
        with pytest.raises(FastReadError, match="outside"):
            c2.read(str(secret), 0, 10)
        c3 = FastReadClient(sock)
        with pytest.raises(FastReadError, match="outside|open"):
            c3.read(str(root / ".." / "secret.txt"), 0, 10)
        c.close(), c2.close(), c3.close()
    finally:
        stop_server(sock)


def test_volume_server_locate_and_fast_read(tmp_path):
    mport, vport = allocate_port(), allocate_port()
    ms = MasterServer(ip="127.0.0.1", port=mport)
    ms.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"127.0.0.1:{mport}",
        ip="127.0.0.1",
        port=vport,
    )
    vs.start()
    try:
        assert vs.fastread_sockets, "sidecar should be running"
        ops = Operations(master=f"127.0.0.1:{mport}")
        payload = os.urandom(200_000)
        fid = ops.upload(payload, name="big.bin")
        # locate control plane
        url = ops.master.lookup(int(fid.split(",")[0]))[0].url
        loc = requests.get(
            f"http://{url}/{fid}?locate=true", timeout=10
        ).json()
        assert loc["size"] == len(payload)
        assert loc["socket"] and os.path.exists(loc["socket"])
        # raw bytes at (path, offset, size) must BE the payload
        with open(loc["path"], "rb") as f:
            f.seek(loc["offset"])
            assert f.read(loc["size"]) == payload
        # data plane through the sidecar
        from seaweedfs_tpu.utils.fastread import read_fid_fast

        assert read_fid_fast(loc) == payload
        # the client's fast path end-to-end (and the fallback path)
        assert ops.read(fid) == payload
        assert ops.read(fid, fast=False) == payload
        # wrong cookie is refused at locate time
        vid, rest = fid.split(",", 1)
        bad = f"{vid},{rest[:-4]}0000"
        r = requests.get(f"http://{url}/{bad}?locate=true", timeout=10)
        assert r.status_code == 404
    finally:
        vs.stop()
        ms.stop()


def test_fast_read_beats_http(tmp_path):
    """Sanity perf check on a 16MB blob: the sendfile path should not
    be slower than HTTP (usually much faster)."""
    mport, vport = allocate_port(), allocate_port()
    ms = MasterServer(ip="127.0.0.1", port=mport)
    ms.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"127.0.0.1:{mport}",
        ip="127.0.0.1",
        port=vport,
    )
    vs.start()
    try:
        ops = Operations(master=f"127.0.0.1:{mport}")
        payload = os.urandom(16 * 1024 * 1024)
        fid = ops.upload(payload, name="bulk.bin")
        url = ops.master.lookup(int(fid.split(",")[0]))[0].url
        loc = requests.get(
            f"http://{url}/{fid}?locate=true", timeout=10
        ).json()
        from seaweedfs_tpu.utils.fastread import FastReadClient

        c = FastReadClient(loc["socket"])
        c.read(loc["path"], loc["offset"], loc["size"])  # warm
        t0 = time.perf_counter()
        for _ in range(3):
            assert len(c.read(loc["path"], loc["offset"], loc["size"])) == len(payload)
        fast_t = (time.perf_counter() - t0) / 3
        c.close()
        requests.get(f"http://{url}/{fid}", timeout=30)  # warm
        t0 = time.perf_counter()
        for _ in range(3):
            assert len(requests.get(f"http://{url}/{fid}", timeout=30).content) == len(payload)
        http_t = (time.perf_counter() - t0) / 3
        print(f"fastread {len(payload)/fast_t/1e6:.0f} MB/s vs http {len(payload)/http_t/1e6:.0f} MB/s")
        assert fast_t < http_t * 1.5, (fast_t, http_t)
    finally:
        vs.stop()
        ms.stop()
