"""Offline tools + scrub tests (reference weed fix/export/compact and
volume_grpc_scrub)."""

import io
import os
import tarfile
import time

import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.storage.volume_scan import scan_volume_file
from seaweedfs_tpu.tools.__main__ import main as tools_main


def make_volume(tmp_path, vid=9):
    v = Volume(str(tmp_path), vid)
    for i in range(1, 21):
        n = Needle(cookie=i, needle_id=i, data=bytes([i]) * (i * 100))
        n.set_name(f"file{i}.bin".encode())
        v.write_needle(n)
    v.delete_needle(3)
    v.write_needle(Needle(cookie=7, needle_id=7, data=b"rewritten"))
    v.close()
    return v


def test_scan_sees_all_records(tmp_path):
    make_volume(tmp_path)
    base = str(tmp_path / "9")
    sb, items = scan_volume_file(base + ".dat")
    items = list(items)
    # 20 puts + 1 delete marker + 1 overwrite
    assert len(items) == 22
    # tombstones now carry the explicit 0x40 flag (body holds the
    # flags byte, so body_size is 5, not 0)
    assert sum(1 for i in items if i.needle.is_tombstone) == 1
    assert all(i.crc_ok for i in items)


def test_fix_rebuilds_idx(tmp_path):
    make_volume(tmp_path)
    base = str(tmp_path / "9")
    original = open(base + ".idx", "rb").read()
    os.unlink(base + ".idx")
    assert tools_main(["fix", "-dir", str(tmp_path), "-volumeId", "9"]) == 0
    v = Volume(str(tmp_path), 9, create=False)
    assert not v.has_needle(3)
    assert v.read_needle(7).data == b"rewritten"
    for i in (1, 10, 20):
        assert v.read_needle(i).data == bytes([i]) * (i * 100)
    v.close()


def test_export_tar(tmp_path):
    make_volume(tmp_path)
    out = str(tmp_path / "dump.tar")
    assert tools_main(
        ["export", "-dir", str(tmp_path), "-volumeId", "9", "-o", out]
    ) == 0
    with tarfile.open(out) as tar:
        names = tar.getnames()
        assert "file3.bin" not in names  # deleted
        assert len(names) == 19
        f = tar.extractfile("file10.bin")
        assert f.read() == bytes([10]) * 1000


def test_compact_tool(tmp_path):
    make_volume(tmp_path)
    size_before = os.path.getsize(str(tmp_path / "9.dat"))
    assert tools_main(["compact", "-dir", str(tmp_path), "-volumeId", "9"]) == 0
    assert os.path.getsize(str(tmp_path / "9.dat")) < size_before
    v = Volume(str(tmp_path), 9, create=False)
    assert v.read_needle(7).data == b"rewritten"
    v.close()


def test_incremental_backup(tmp_path):
    bdir = str(tmp_path / "bk")
    v = Volume(str(tmp_path), 11)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=i, needle_id=i, data=bytes([i]) * 5000))
    v.flush()
    assert tools_main(
        ["backup", "-dir", str(tmp_path), "-volumeId", "11", "-o", bdir]
    ) == 0
    # append more, delete one, backup incrementally
    for i in range(6, 9):
        v.write_needle(Needle(cookie=i, needle_id=i, data=bytes([i]) * 5000))
    v.delete_needle(2)
    v.flush()
    assert tools_main(
        ["backup", "-dir", str(tmp_path), "-volumeId", "11", "-o", bdir]
    ) == 0
    v.close()
    # the backup dir is a loadable volume with identical live content
    b = Volume(bdir, 11, create=False)
    assert not b.has_needle(2)
    for i in (1, 5, 8):
        assert b.read_needle(i).data == bytes([i]) * 5000
    b.close()
    # post-vacuum source forces a clean full re-backup
    v = Volume(str(tmp_path), 11, create=False)
    v.vacuum()
    v.close()
    assert tools_main(
        ["backup", "-dir", str(tmp_path), "-volumeId", "11", "-o", bdir]
    ) == 0
    b = Volume(bdir, 11, create=False)
    assert b.read_needle(8).data == bytes([8]) * 5000
    b.close()


def test_remote_tail_backup(tmp_path):
    """Incremental backup pulled from a LIVE volume server over gRPC
    (VolumeTailSender analog)."""
    from conftest import allocate_port as free_port
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.storage.file_id import FileId

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    ops = Operations(f"localhost:{mport}")
    bdir = str(tmp_path / "remote-bk")
    try:
        fids = [ops.upload(b"live-%d" % i * 400) for i in range(5)]
        vid = FileId.parse(fids[0]).volume_id
        args = [
            "backup", "-dir", str(tmp_path / "ignored"), "-volumeId",
            str(vid), "-o", bdir, "-from", f"localhost:{vs.grpc_port}",
        ]
        assert tools_main(args) == 0
        size_after_first = os.path.getsize(f"{bdir}/{vid}.dat")
        # live appends, then an incremental pull
        fids += [ops.upload(b"tail-%d" % i * 400) for i in range(3)]
        assert tools_main(args) == 0
        assert os.path.getsize(f"{bdir}/{vid}.dat") > size_after_first
        b = Volume(bdir, vid, create=False)
        for fid in fids:
            if FileId.parse(fid).volume_id != vid:
                continue
            n = b.read_needle(FileId.parse(fid).needle_id)
            assert n.data.startswith((b"live-", b"tail-"))
        b.close()
    finally:
        ops.close()
        vs.stop()
        master.stop()


def test_scrub_rpcs(tmp_path):
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.pb import cluster_pb2 as pb
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.shell.commands import ShellEnv, run_command
    from seaweedfs_tpu.storage.file_id import FileId

    from conftest import allocate_port as free_port

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    ops = Operations(f"localhost:{mport}")
    env = ShellEnv(f"localhost:{mport}")
    try:
        fid = ops.upload(b"scrub me" * 1000)
        vid = FileId.parse(fid).volume_id
        out = run_command(env, f"volume.scrub -volumeId {vid}")
        assert "all clean" in out, out
        # corrupt the needle data on disk
        v = vs.store.find_volume(vid)
        nv = v.needle_map.get(FileId.parse(fid).needle_id)
        from seaweedfs_tpu.storage.types import actual_offset

        with open(v.dat_path, "r+b") as f:
            f.seek(actual_offset(nv.offset) + 16 + 4 + 10)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0xFF]))
        out = run_command(env, f"volume.scrub -volumeId {vid}")
        assert "CORRUPT" in out, out

        # EC scrub: encode a clean volume, then flip a shard byte
        fid2 = ops.upload(b"ec scrub" * 5000)
        vid2 = FileId.parse(fid2).volume_id
        if vid2 == vid:
            # same volume: encode anyway (corrupt needle is fine for
            # shard-level scrub which checks shard CRCs vs sidecar)
            pass
        run_command(env, f"ec.encode -volumeId {vid2} -backend cpu -keepSource")
        time.sleep(0.5)
        out = run_command(env, f"ec.scrub -volumeId {vid2}")
        assert "all clean" in out, out
        base = Volume.base_file_name(str(tmp_path / "v"), "", vid2)
        with open(base + ".ec02", "r+b") as f:
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0x01]))
        out = run_command(env, f"ec.scrub -volumeId {vid2}")
        assert "BITROT in shards [2]" in out, out
    finally:
        env.close()
        ops.close()
        vs.stop()
        master.stop()
