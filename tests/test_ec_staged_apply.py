"""PR 3 staged-apply tests: `apply_staged` on every backend family
(CPU, XLA, interpret-mode Pallas, column mesh, CPU-fallback shim), the
shared `run_staged_apply` driver, the staged rebuild/decode/degraded
paths, the generation-keyed interval cache, the leaf-granular scrub
cursor, and the retry-policy sweep.

Bit-identity is the load-bearing property everywhere: the staged path
must produce byte-for-byte what the synchronous `apply` produces, on
every backend, for every batch shape — including ragged tails — and
through a mid-stream device failure.
"""

import os

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import (
    BitrotProtection,
    CpuBackend,
    ECContext,
    ECError,
    EcVolume,
    FallbackBackend,
    JaxBackend,
    ec_decode_volume,
    ec_encode_volume,
    rebuild_ec_files,
    scrub_ec_volume,
)
from seaweedfs_tpu.ec.backend import _decode_coeffs
from seaweedfs_tpu.ec.pipeline import run_staged_apply
from seaweedfs_tpu.ec.scrub import ScrubCursor
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils.retry import CircuitBreaker, RetryPolicy

CTX = ECContext(10, 4)
K = CTX.data_shards


def make_volume(tmp_path, vid=1, needles=30, seed=0):
    rng = np.random.default_rng(seed)
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, needles + 1):
        size = int(rng.integers(1, 60_000))
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x1000 + i, needle_id=i, data=data))
        payloads[i] = data
    v.close()
    return Volume.base_file_name(str(tmp_path), "", vid), payloads


def decode_coeffs(targets, src):
    rs = gf256.ReedSolomon(CTX.data_shards, CTX.parity_shards)
    return _decode_coeffs(rs.matrix, K, tuple(targets), tuple(src))


def make_backend(kind):
    if kind == "cpu":
        return CpuBackend(CTX)
    if kind == "xla":
        return JaxBackend(CTX, impl="xla", n_devices=1)
    if kind == "pallas_interpret":
        return JaxBackend(CTX, impl="pallas", interpret=True, n_devices=1)
    if kind == "mesh":
        return JaxBackend(CTX)  # conftest forces 8 virtual devices
    if kind == "fallback":
        return FallbackBackend(
            JaxBackend(CTX, impl="xla", n_devices=1), CpuBackend(CTX)
        )
    raise AssertionError(kind)


BACKENDS = ["cpu", "xla", "pallas_interpret", "mesh", "fallback"]


# ------------------------------------------------- staged apply bit-identity


@pytest.mark.parametrize("kind", BACKENDS)
def test_apply_staged_bit_identical_across_widths(kind):
    """CPU truth vs the staged path on every backend, across batch
    shapes including sub-lane and ragged widths."""
    be = make_backend(kind)
    cpu = CpuBackend(CTX)
    rng = np.random.default_rng(42)
    coeffs = decode_coeffs((0, 13), tuple(range(1, 11)))
    for width in (1, 127, 1000, 4096, 65_536 + 13):
        data = rng.integers(0, 256, (K, width), dtype=np.uint8)
        want = cpu.apply(coeffs, data)
        got = be.to_host(be.apply_staged(coeffs, be.to_device(data)))
        assert got.dtype == np.uint8 and got.shape == want.shape
        assert np.array_equal(got, want), (kind, width)


@pytest.mark.parametrize("kind", ["cpu", "xla", "mesh", "fallback"])
def test_run_staged_apply_driver_ragged_tail(kind):
    """The shared driver over multiple batches with a ragged tail must
    concatenate to exactly the single-shot apply output, with tags
    delivered in order."""
    be = make_backend(kind)
    cpu = CpuBackend(CTX)
    rng = np.random.default_rng(7)
    coeffs = decode_coeffs((2,), tuple(i for i in range(14) if i != 2)[:K])
    src = tuple(i for i in range(14) if i != 2)[:K]
    total = 3 * 4096 + 1234  # ragged final batch
    data = rng.integers(0, 256, (K, total), dtype=np.uint8)
    want = cpu.apply(coeffs, data)

    out = np.zeros((1, total), dtype=np.uint8)
    tags = []

    def produce():
        for off in range(0, total, 4096):
            yield off, data[:, off : off + 4096]

    def consume(off, rec):
        tags.append(off)
        out[:, off : off + rec.shape[1]] = rec

    run_staged_apply(be, coeffs, produce, consume, describe="test staged")
    assert tags == sorted(tags) == list(range(0, total, 4096))
    assert np.array_equal(out, want)
    assert src  # silence linters: src documents the coeff layout


def test_run_staged_apply_passthrough():
    """coeffs=None is the decode configuration: items flow through
    untouched (no device round-trip), order preserved."""
    items = [(i, bytes([i]) * 100) for i in range(20)]
    got = []
    run_staged_apply(
        None, None, lambda: iter(items), lambda tag, b: got.append((tag, b))
    )
    assert got == items


# ------------------------------------------------------------ staged rebuild


@pytest.mark.parametrize("kind", ["cpu", "xla", "fallback", "mesh"])
def test_rebuild_staged_equals_sync(tmp_path, kind):
    """staged=True and staged=False publish byte-identical shards on
    every backend family (and both verify against the sidecar)."""
    base, _ = make_volume(tmp_path, needles=20, seed=3)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    missing = [1, K + 1]
    originals = {}
    for i in missing:
        with open(base + CTX.to_ext(i), "rb") as f:
            originals[i] = f.read()

    be = make_backend(kind)
    for staged in (False, True):
        for i in missing:
            os.unlink(base + CTX.to_ext(i))
        assert rebuild_ec_files(
            base, backend=be, staged=staged, batch_size=100_000
        ) == sorted(missing)
        for i in missing:
            with open(base + CTX.to_ext(i), "rb") as f:
                assert f.read() == originals[i], (kind, staged, i)


# ----------------------------------------- chaos: device fault mid-staged


@pytest.mark.chaos
def test_apply_staged_fault_falls_back_bit_identical(tmp_path):
    """A device fault fired at ec.backend.device.apply_staged mid-rebuild:
    the batch degrades to CPU through the carried host copy, the rebuilt
    shards are bit-identical, and the window is not lost."""
    base, _ = make_volume(tmp_path, needles=20, seed=4)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    missing = [2, 12]
    originals = {}
    for i in missing:
        with open(base + CTX.to_ext(i), "rb") as f:
            originals[i] = f.read()
        os.unlink(base + CTX.to_ext(i))

    fb = FallbackBackend(
        JaxBackend(CTX, impl="xla", n_devices=1),
        CpuBackend(CTX),
        breaker=CircuitBreaker(failure_threshold=3, reset_timeout=9999.0),
    )
    with faults.injected(
        "ec.backend.device.apply_staged",
        faults.io_error("device lost mid-apply"),
        when=faults.nth_call(2),
        count=1,
    ):
        # chaos-armed registries route rebuild through the byte path;
        # drive the staged surface directly instead
        coeffs = decode_coeffs((0,), tuple(range(1, 11)))
        rng = np.random.default_rng(0)
        outs = []
        for _ in range(4):
            data = rng.integers(0, 256, (K, 8192), dtype=np.uint8)
            outs.append(
                (data, fb.to_host(fb.apply_staged(coeffs, fb.to_device(data))))
            )
    cpu = CpuBackend(CTX)
    for data, got in outs:
        assert np.array_equal(got, cpu.apply(coeffs, data))
    assert fb.fallback_batches >= 1, "fault never engaged the fallback"
    # registry is clean again: the real rebuild takes the fused path
    assert rebuild_ec_files(base, backend=fb) == sorted(missing)
    for i in missing:
        with open(base + CTX.to_ext(i), "rb") as f:
            assert f.read() == originals[i]


@pytest.mark.chaos
def test_apply_staged_repeated_faults_open_breaker():
    """Every staged dispatch failing opens the breaker; output stays
    bit-identical throughout (CPU serves)."""
    fb = FallbackBackend(
        JaxBackend(CTX, impl="xla", n_devices=1),
        CpuBackend(CTX),
        breaker=CircuitBreaker(failure_threshold=3, reset_timeout=9999.0),
    )
    cpu = CpuBackend(CTX)
    coeffs = decode_coeffs((5,), tuple(i for i in range(14) if i != 5)[:K])
    rng = np.random.default_rng(1)
    with faults.injected(
        "ec.backend.device.apply_staged", faults.io_error("device dead")
    ):
        for _ in range(5):
            data = rng.integers(0, 256, (K, 2048), dtype=np.uint8)
            got = fb.to_host(fb.apply_staged(coeffs, fb.to_device(data)))
            assert np.array_equal(got, cpu.apply(coeffs, data))
    assert fb.breaker.state == "open"
    assert fb.fallback_batches >= 3


@pytest.mark.chaos
def test_staged_to_host_fault_recomputes_apply_not_encode():
    """A to_host failure on an APPLY handle must replay the apply (with
    its coefficients), not an encode — the handle kind is load-bearing."""
    fb = FallbackBackend(
        JaxBackend(CTX, impl="xla", n_devices=1),
        CpuBackend(CTX),
        breaker=CircuitBreaker(failure_threshold=99, reset_timeout=9999.0),
    )
    cpu = CpuBackend(CTX)
    coeffs = decode_coeffs((3, 7), tuple(i for i in range(14) if i not in (3, 7))[:K])
    data = np.random.default_rng(2).integers(0, 256, (K, 4096), dtype=np.uint8)
    with faults.injected(
        "ec.backend.device.to_host", faults.io_error("drain failed"), count=1
    ):
        got = fb.to_host(fb.apply_staged(coeffs, fb.to_device(data)))
    assert fb.fallback_batches == 1
    assert np.array_equal(got, cpu.apply(coeffs, data))
    # and an encode handle still re-encodes
    with faults.injected(
        "ec.backend.device.to_host", faults.io_error("drain failed"), count=1
    ):
        got = fb.to_host(fb.encode_staged(fb.to_device(data)))
    assert np.array_equal(got, cpu.encode(data))


# -------------------------------------------------- staged degraded reads


def test_degraded_reads_use_staged_path_bit_exact(tmp_path, monkeypatch):
    """Wide degraded extents go through run_staged_apply (batched); all
    payloads must come back bit-exact. Shrinking the batch threshold
    forces every reconstruction through the staged path."""
    import seaweedfs_tpu.ec.ec_volume as ecv

    base, payloads = make_volume(tmp_path, needles=12, seed=5)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    os.unlink(base + CTX.to_ext(0))
    monkeypatch.setattr(ecv, "STAGED_RECOVERY_BATCH", 2048)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        for i, data in payloads.items():
            assert ev.read_needle(i, cookie=0x1000 + i).data == data
    finally:
        ev.close()


# ------------------------------------------------- degraded decode self-heal


def test_decode_with_missing_data_shard_self_heals(tmp_path):
    """ec_decode_volume with a lost DATA shard regenerates it through
    the staged rebuild (instead of refusing) and the decoded .dat is
    byte-identical to the original volume."""
    base, _ = make_volume(tmp_path, needles=15, seed=6)
    with open(base + ".dat", "rb") as f:
        original_dat = f.read()
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    os.unlink(base + ".dat")
    os.unlink(base + CTX.to_ext(3))  # a data shard
    assert ec_decode_volume(base, CTX, backend=CpuBackend(CTX)) is True
    with open(base + ".dat", "rb") as f:
        decoded = f.read()
    assert decoded == original_dat[: len(decoded)]
    assert len(decoded) >= len(original_dat) - 8  # padding-trim envelope
    # the regenerated shard was published (self-heal side effect)
    assert os.path.exists(base + CTX.to_ext(3))


def test_decode_repairs_rotten_present_shard(tmp_path):
    """A data shard present ON DISK but bitrotten must not be de-striped
    into the .dat: decode's upfront rebuild pass verifies every present
    shard against the sidecar, replaces the rotten one, and the decoded
    volume is bit-exact."""
    base, _ = make_volume(tmp_path, needles=15, seed=9)
    with open(base + ".dat", "rb") as f:
        original_dat = f.read()
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    os.unlink(base + ".dat")
    flip_byte(base + CTX.to_ext(2), 12345, 0x40)  # rot a DATA shard
    assert ec_decode_volume(base, CTX, backend=CpuBackend(CTX)) is True
    with open(base + ".dat", "rb") as f:
        decoded = f.read()
    assert decoded == original_dat[: len(decoded)]


def test_decode_below_k_still_fails_closed(tmp_path):
    base, _ = make_volume(tmp_path, needles=10, seed=7)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    for i in range(CTX.parity_shards + 1):  # > parity losses
        os.unlink(base + CTX.to_ext(i))
    with pytest.raises(ECError):
        ec_decode_volume(base, CTX, backend=CpuBackend(CTX))


# -------------------------------------------- generation-keyed interval cache


def degraded_volume(tmp_path, lost=0):
    base, payloads = make_volume(tmp_path, needles=30, seed=8)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    os.unlink(base + CTX.to_ext(lost))
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    return base, payloads, ev


def test_unrelated_shard_remount_keeps_cache(tmp_path):
    """Remounting a shard UNRELATED to the cached extents must keep
    them (the wholesale clear() this replaces dropped everything) —
    repeats still hit the cache and re-read zero sibling bytes."""
    base, payloads, ev = degraded_volume(tmp_path)
    try:
        for i, data in payloads.items():
            assert ev.read_needle(i, cookie=0x1000 + i).data == data
        cached = ev.interval_cache.size_bytes
        assert cached > 0
        ev.reopen_shards([5])  # unrelated, live shard
        assert ev.interval_cache.size_bytes == cached
        h0, b0 = ev.interval_cache.hits, ev.bytes_read
        for i, data in payloads.items():
            assert ev.read_needle(i, cookie=0x1000 + i).data == data
        assert ev.interval_cache.hits > h0
        # lost-shard extents all served from cache: no sibling re-reads
        # beyond the live-shard intervals of each needle
        assert ev.bytes_read - b0 < b0
    finally:
        ev.close()


def test_affected_shard_events_still_invalidate(tmp_path):
    """The existing invalidation contract holds when the AFFECTED shard
    is the one remounted/unmounted, and deletes stay wholesale."""
    base, payloads, ev = degraded_volume(tmp_path)
    try:
        nid = next(iter(payloads))
        ev.read_needle(nid, cookie=0x1000 + nid)
        assert ev.interval_cache.size_bytes > 0
        gen0 = ev._shard_gen.get(0, 0)
        ev.reopen_shards([0])  # the lost shard (e.g. post-rebuild)
        assert ev.interval_cache.size_bytes == 0
        assert ev._shard_gen[0] == gen0 + 1
        ev.read_needle(nid, cookie=0x1000 + nid)
        assert ev.interval_cache.size_bytes > 0
        ev.delete_needle(max(payloads))  # content change: wholesale
        assert ev.interval_cache.size_bytes == 0
    finally:
        ev.close()


def test_stale_generation_put_is_invisible(tmp_path):
    """An in-flight reconstruction that populates under a pre-bump
    generation must be invisible to post-bump reads (the race the
    generation key closes)."""
    base, payloads, ev = degraded_volume(tmp_path)
    try:
        nid = next(iter(payloads))
        ev.read_needle(nid, cookie=0x1000 + nid)
        keys0 = {k for k in ev.interval_cache._data}
        # keys are "<ns><sid>:<gen>:<lo>:<hi>" with ns = "<vid>:"
        assert all(k.split(":")[2] == "0" for k in keys0)
        ev.unmount_shards([0])  # bump shard 0's generation
        # simulate the in-flight put landing late under the old gen
        stale = "1:0:0:0:4096"
        ev.interval_cache.put(stale, b"x" * 4096)
        h0 = ev.interval_cache.hits
        ev.read_needle(nid, cookie=0x1000 + nid)  # re-reconstructs
        new_keys = {k for k in ev.interval_cache._data if k != stale}
        assert all(k.split(":")[2] == "1" for k in new_keys)
        assert ev.interval_cache.hits == h0  # stale entry never hit
    finally:
        ev.close()


# ------------------------------------------------ leaf-granular scrub cursor


def synth_leafy_shards(tmp_path, shard_size=8 * 4096, block_size=4 * 4096,
                       leaf_size=4096, seed=0):
    """RS-consistent shards + v2 sidecar with small blocks/leaves so the
    cursor logic is exercised with real data (2 blocks x 4 leaves)."""
    from seaweedfs_tpu.ec import ShardChecksumBuilder

    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (CTX.data_shards, shard_size), dtype=np.uint8)
    parity = CpuBackend(CTX).encode(data)
    shards = np.concatenate([data, parity], axis=0)
    base = str(tmp_path / "1")
    builders = [
        ShardChecksumBuilder(block_size, leaf_size) for _ in range(CTX.total)
    ]
    for i in range(CTX.total):
        b = shards[i].tobytes()
        with open(base + CTX.to_ext(i), "wb") as f:
            f.write(b)
        builders[i].write(b)
    prot = BitrotProtection.from_builders(CTX, builders, generation=9)
    prot.save(base + ".ecsum")
    return base, shards


def flip_byte(path, offset, mask=0x01):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ mask]))


def test_scrub_walks_leaves_and_pins_corrupt_leaf(tmp_path):
    base, shards = synth_leafy_shards(tmp_path)
    # corrupt leaf 5 (block 1, leaf 1) of shard 2
    flip_byte(base + CTX.to_ext(2), 5 * 4096 + 17)
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert r.complete and not r.refused
    # the walk pinned the rot to its leaf; with k verified sources the
    # shard is LEAF-REPAIRED in place (PR 8) — no quarantine, no
    # forensic copy, no whole-shard rebuild
    assert r.leaf_repaired == {2: [5]}, r
    assert r.checked_leaves > 0
    assert not r.quarantined and not r.rebuilt
    assert not os.path.exists(base + CTX.to_ext(2) + ".bad")
    # repair landed bit-exact
    with open(base + CTX.to_ext(2), "rb") as f:
        assert f.read() == shards[2].tobytes()


def test_scrub_budget_resumes_mid_block(tmp_path):
    """A leaf-denominated budget pause must land MID-block (cursor.leaf
    > 0 at some point) and the sliced pass must converge to the same
    verdict as an unbudgeted one."""
    base, shards = synth_leafy_shards(tmp_path)
    flip_byte(base + CTX.to_ext(3), 6 * 4096 + 3)  # block 1, leaf 2
    # 0.75 of a block per call = 3 leaves, so pauses land MID-block
    # (the budget is byte-denominated and may be fractional)
    mid_block_seen = False
    for _ in range(80):
        r = scrub_ec_volume(
            base, CTX, backend=CpuBackend(CTX), repair=True, max_blocks=0.75
        )
        cur = ScrubCursor.load(base)
        if cur is not None and cur.leaf > 0:
            mid_block_seen = True
        if r.complete:
            break
    assert r.complete and not r.refused
    assert (
        r.corrupt_leaves.get(3) == [6]
        or r.rebuilt == [3]
        or r.leaf_repaired.get(3) == [6]
    )
    with open(base + CTX.to_ext(3), "rb") as f:
        assert f.read() == shards[3].tobytes()
    assert not os.path.exists(base + ".scrubpos")
    assert mid_block_seen, "budget pause never landed mid-block"


def test_scrub_reverify_catches_new_rot_after_repair(tmp_path):
    """A shard repaired between budget slices but re-corrupted at a
    DIFFERENT leaf must not be cleared by the flagged-leaf fast path:
    clearing a verdict requires a full verify."""
    base, shards = synth_leafy_shards(tmp_path)
    flip_byte(base + CTX.to_ext(1), 0 * 4096 + 9)  # leaf 0 of shard 1
    # slice 1: walk exactly shard 0 + shard 1's first (corrupt) leaf,
    # carrying the verdict into the cursor
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=False, max_blocks=2.25
    )
    assert not r.complete
    cur = ScrubCursor.load(base)
    assert cur is not None and cur.corrupt_leaves.get(1) == [0]
    # "repair" shard 1 (restore pristine bytes), then rot a LATER leaf
    with open(base + CTX.to_ext(1), "wb") as f:
        f.write(shards[1].tobytes())
    flip_byte(base + CTX.to_ext(1), 7 * 4096 + 100)  # last leaf
    # finish the pass unbudgeted: the flagged leaf (0) now reads clean,
    # so the completion re-verify must full-scan and find leaf 7's rot
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert r.complete and not r.refused
    assert 1 in (
        set(r.corrupt_shards) | set(r.rebuilt) | set(r.leaf_repaired)
    )
    with open(base + CTX.to_ext(1), "rb") as f:
        assert f.read() == shards[1].tobytes()


def test_scrub_pause_carried_leaves_cleared_after_repair(tmp_path):
    """A shard condemned only by leaves carried from a PAUSED slice
    (never in cursor.corrupt) must still pass through the completion
    re-verify: repairing it between slices clears the verdict instead
    of quarantining a healthy shard."""
    base, shards = synth_leafy_shards(tmp_path)
    flip_byte(base + CTX.to_ext(1), 0 * 4096 + 9)  # leaf 0 of shard 1
    # budget 2.25 blocks = shard 0 (2.0) + shard 1's leaf 0, pausing
    # MID-shard-1 with the verdict only in corrupt_leaves
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=False, max_blocks=2.25
    )
    assert not r.complete
    cur = ScrubCursor.load(base)
    assert cur.corrupt_leaves.get(1) == [0] and 1 not in cur.corrupt
    # full repair lands between slices (e.g. ec.rebuild)
    with open(base + CTX.to_ext(1), "wb") as f:
        f.write(shards[1].tobytes())
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert r.complete and not r.refused
    assert 1 not in r.corrupt_shards and r.rebuilt == []
    assert not os.path.exists(base + CTX.to_ext(1) + ".bad")


def test_rebuild_noop_never_resolves_device_backend(tmp_path, monkeypatch):
    """rebuild of a healthy volume (the scrub-daemon and decode verify
    shape) is pure CRC work: it must not resolve get_backend('auto'),
    which on a dead-TPU-relay host would hang in device init."""
    import seaweedfs_tpu.ec.rebuild as rb

    base, _ = make_volume(tmp_path, needles=8, seed=10)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))

    def boom(*a, **kw):
        raise AssertionError("backend resolved on the no-op path")

    monkeypatch.setattr(rb, "get_backend", boom)
    assert rebuild_ec_files(base) == []  # verify-only, no device touch
    os.unlink(base + CTX.to_ext(0))
    with pytest.raises(AssertionError, match="backend resolved"):
        rebuild_ec_files(base)  # an actual target DOES resolve


def test_scrub_budget_fractional_leaves(tmp_path):
    """Leaf reads consume budget byte-proportionally: a 1-block budget
    admits a full block's worth of leaves per slice, not one leaf."""
    base, _ = synth_leafy_shards(tmp_path)
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=False, max_blocks=1,
        resumable=False,
    )
    assert not r.complete
    assert r.checked_leaves == 4  # one block's worth (4 leaves), not 1


def test_v1_sidecar_keeps_block_walk(tmp_path):
    """No leaves in the sidecar -> identical block-granular behavior
    (checked_blocks counts blocks, checked_leaves stays 0)."""
    from seaweedfs_tpu.ec import ShardChecksumBuilder

    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (CTX.data_shards, 4 * 4096), dtype=np.uint8)
    parity = CpuBackend(CTX).encode(data)
    shards = np.concatenate([data, parity], axis=0)
    base = str(tmp_path / "1")
    builders = [ShardChecksumBuilder(4096) for _ in range(CTX.total)]
    for i in range(CTX.total):
        b = shards[i].tobytes()
        with open(base + CTX.to_ext(i), "wb") as f:
            f.write(b)
        builders[i].write(b)
    BitrotProtection.from_builders(CTX, builders, generation=3).save(
        base + ".ecsum"
    )
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX))
    assert r.complete and r.healthy
    assert r.checked_blocks == CTX.total * 4
    assert r.checked_leaves == 0 and r.corrupt_leaves == {}


# ------------------------------------------------------------- retry sweep


def test_notifier_delivery_rides_retry_policy():
    """Transient sink failures retry on the policy schedule; permanent
    rejections do not retry; exhaustion drops."""
    from seaweedfs_tpu.filer.notification import _AsyncNotifier

    class Sink(_AsyncNotifier):
        def __init__(self, outcomes):
            self.outcomes = list(outcomes)
            self.calls = 0
            super().__init__(
                policy=RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0)
            )

        def _deliver(self, payload):
            self.calls += 1
            out = self.outcomes.pop(0)
            if isinstance(out, Exception):
                raise out
            return out

    s = Sink([RuntimeError("blip"), True])
    assert s._deliver_with_retry({"x": 1}) is True
    assert s.calls == 2
    s.close()

    s = Sink([False])  # permanent rejection: exactly one attempt
    assert s._deliver_with_retry({"x": 1}) is False
    assert s.calls == 1
    s.close()

    s = Sink([RuntimeError("a"), RuntimeError("b"), RuntimeError("c")])
    assert s._deliver_with_retry({"x": 1}) is False
    assert s.calls == 3
    s.close()


def test_upload_retries_transients_and_raises_permanent(monkeypatch):
    """Operations.upload: 5xx/transport errors re-assign + retry under
    the policy; 4xx raises immediately without another attempt."""
    import requests

    from seaweedfs_tpu.client.operations import Operations

    class FakeAssign:
        url = "localhost:1"
        fid = "1,abc"
        jwt = ""

    class R:
        def __init__(self, code):
            self.status_code = code
            self.text = "nope"

    ops = Operations.__new__(Operations)
    ops.jwt_key = ""
    assigns = []

    class FakeMaster:
        def assign(self, **kw):
            assigns.append(1)
            return FakeAssign()

    ops.master = FakeMaster()
    monkeypatch.setattr(
        Operations, "_UPLOAD_POLICY",
        RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0,
                    retry_on=(requests.RequestException, RuntimeError)),
    )

    class FlakyHttp:
        def __init__(self, codes):
            self.codes = list(codes)

        def post(self, *a, **kw):
            return R(self.codes.pop(0))

    ops._http = FlakyHttp([503, 200])
    assert ops.upload(b"data") == "1,abc"
    assert len(assigns) == 2  # re-assigned before the retry

    ops._http = FlakyHttp([403])
    assigns.clear()
    with pytest.raises(requests.HTTPError):
        ops.upload(b"data")
    assert len(assigns) == 1  # permanent: no retry

    ops._http = FlakyHttp([503, 503, 503])
    with pytest.raises(requests.HTTPError):
        ops.upload(b"data")
    assert not ops._http.codes  # all attempts consumed


def test_peer_cache_announce_backoff_policy():
    """The announce policy walks up from the normal cadence and caps at
    the peer TTL (a recovered filer is re-learned before peers expire
    this mount)."""
    from seaweedfs_tpu.mount.peer_cache import (
        ANNOUNCE_INTERVAL,
        ANNOUNCE_POLICY,
        PEER_TTL,
    )
    from seaweedfs_tpu.utils.retry import Backoff

    b = Backoff(ANNOUNCE_POLICY, rng=None)
    d1 = ANNOUNCE_POLICY.delay(1)
    assert d1 == ANNOUNCE_INTERVAL
    delays = [b.next_delay() for _ in range(6)]
    assert max(delays) <= PEER_TTL * (1 + ANNOUNCE_POLICY.jitter)
    assert delays[-1] >= delays[0]
    b.reset()
    assert b.failures == 0
