"""Chaos harness: encode → inject faults → scrub → rebuild → read
lifecycles under seeded, deterministic fault schedules.

Every lifecycle must end in exactly one of two states:
  - bit-exact recovery (every payload reads back identical), or
  - clean fail-closed refusal (ECError/CrcError/refused report).
A read that RETURNS wrong bytes anywhere is a silent-corruption bug and
fails the suite.

The deterministic fixed-seed subset runs in tier-1; the wide randomized
soak is marked slow. Crash-window tests (satellite: kill between
temp-write / fsync / rename) fork a child that os._exit()s at the fault
point — a faithful power-loss model where no cleanup handler runs.
"""

from __future__ import annotations

import multiprocessing
import os

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import (
    BitrotProtection,
    CpuBackend,
    ECContext,
    ECError,
    EcVolume,
    FallbackBackend,
    JaxBackend,
    ShardChecksumBuilder,
    ec_decode_volume,
    ec_encode_volume,
    rebuild_ec_files,
    scrub_ec_volume,
    write_ec_files,
)
from seaweedfs_tpu.ec.scrub import (
    QUARANTINE_SUFFIX,
    RateLimiter,
    ScrubCursor,
    ScrubDaemon,
)
from seaweedfs_tpu.storage.needle import CrcError, Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils.retry import CircuitBreaker

CTX = ECContext(10, 4)

pytestmark = pytest.mark.chaos


def make_volume(tmp_path, vid=1, needles=40, seed=0):
    rng = np.random.default_rng(seed)
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, needles + 1):
        size = int(rng.integers(1, 40_000))
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x1000 + i, needle_id=i, data=data))
        payloads[i] = data
    v.close()
    return Volume.base_file_name(str(tmp_path), "", vid), payloads


def synth_shards(tmp_path, ctx=CTX, shard_size=4 * 4096, block_size=4096, seed=0):
    """RS-consistent shard files + multi-block .ecsum, no volume needed:
    lets scrub walk several blocks per shard (the real sidecar block is
    16 MiB — too big to exercise cursor/budget logic with real data)."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (ctx.data_shards, shard_size), dtype=np.uint8)
    parity = CpuBackend(ctx).encode(data)
    shards = np.concatenate([data, parity], axis=0)
    base = str(tmp_path / "1")
    builders = [ShardChecksumBuilder(block_size) for _ in range(ctx.total)]
    for i in range(ctx.total):
        b = shards[i].tobytes()
        with open(base + ctx.to_ext(i), "wb") as f:
            f.write(b)
        builders[i].write(b)
    prot = BitrotProtection.from_builders(ctx, builders, generation=7)
    prot.save(base + ".ecsum")
    return base, shards


def flip_byte(path: str, offset: int, mask: int = 0x01) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ mask]))


def read_all_or_refuse(tmp_path, payloads, vid=1) -> tuple[int, int]:
    """Read every needle; returns (bit_exact, refused). Any wrong-bytes
    return raises AssertionError — the zero-silent-corruption gate."""
    ev = EcVolume(str(tmp_path), vid, backend_name="cpu")
    exact = refused = 0
    try:
        for i, want in payloads.items():
            try:
                got = ev.read_needle(i, cookie=0x1000 + i).data
            except (ECError, CrcError, OSError):
                refused += 1
                continue
            assert got == want, f"SILENT CORRUPTION on needle {i}"
            exact += 1
    finally:
        ev.close()
    return exact, refused


# ------------------------------------------------------- registry basics


def test_disabled_registry_is_noop(tmp_path):
    """Empty registry = no trigger evaluation, no behavior change."""
    assert not faults.active()
    faults.fire("some.point", x=1)  # must be a no-op, not a KeyError
    assert faults.mutate("some.point", b"abc") == b"abc"

    evaluated = []

    def counting_trigger():
        evaluated.append(1)
        return False

    h = faults.inject("some.point", faults.io_error(), when=counting_trigger)
    assert faults.active()
    h.remove()
    assert not faults.active()
    faults.fire("some.point")
    assert evaluated == [], "disarmed registry must not evaluate triggers"

    # encode byte-identity with the registry empty vs cleared-after-use
    base, _ = make_volume(tmp_path, needles=8, seed=2)
    write_ec_files(base, CTX, CpuBackend(CTX))
    first = {i: open(base + CTX.to_ext(i), "rb").read() for i in range(CTX.total)}
    with faults.injected("never.hit", faults.io_error()):
        pass  # armed and removed: must leave zero residue
    write_ec_files(base, CTX, CpuBackend(CTX))
    for i in range(CTX.total):
        assert open(base + CTX.to_ext(i), "rb").read() == first[i]


def test_triggers_and_actions_deterministic():
    fires = []
    h = faults.inject(
        "p", lambda ctx: fires.append(1), when=faults.nth_call(3)
    )
    for _ in range(6):
        faults.fire("p")
    assert len(fires) == 1 and h.fired == 1 and h.hits == 6
    faults.clear()

    # probability trigger replays identically from its seed
    def run(seed):
        out = []
        h = faults.inject(
            "q", lambda ctx: out.append(1), when=faults.probability(0.5, seed=seed)
        )
        for _ in range(32):
            faults.fire("q")
        faults.clear()
        return h.fired

    assert run(11) == run(11)

    # bit_flip replays identically from its seed
    a = faults.bit_flip(seed=3, flips=4)({}, b"\x00" * 64)
    b = faults.bit_flip(seed=3, flips=4)({}, b"\x00" * 64)
    assert a == b != b"\x00" * 64
    assert faults.truncate(0.25)({}, b"x" * 100) == b"x" * 25
    assert faults.zero_fill()({}, b"xyz") == b"\x00\x00\x00"


def test_injected_io_error_is_an_io_error():
    with pytest.raises(IOError):
        with faults.injected("p", faults.io_error()):
            faults.fire("p")
    with pytest.raises(BaseException) as ei:
        with faults.injected("p", faults.crash()):
            faults.fire("p")
    assert not isinstance(ei.value, Exception), "crash must evade except Exception"


def test_every_and_count_caps():
    seen = []
    faults.inject("p", lambda ctx: seen.append(1), when=faults.every(2), count=2)
    for _ in range(10):
        faults.fire("p")
    assert len(seen) == 2  # fires at calls 2 and 4, then capped
    faults.clear()


# ---------------------------------------------- seeded chaos lifecycles


def _apply_schedule(base, rng) -> tuple[list[int], int]:
    """Seeded fault schedule against on-disk shards: flips, torn
    truncations, deletions. Returns (damaged shard ids, n_deleted)."""
    n_damaged = int(rng.integers(1, CTX.parity_shards + 1))  # survivable
    damaged = sorted(
        int(x) for x in rng.choice(CTX.total, size=n_damaged, replace=False)
    )
    deleted = 0
    for sid in damaged:
        path = base + CTX.to_ext(sid)
        size = os.path.getsize(path)
        kind = int(rng.integers(0, 3))
        if kind == 0:  # bit flip(s)
            for _ in range(int(rng.integers(1, 4))):
                flip_byte(path, int(rng.integers(0, size)), 1 << int(rng.integers(0, 8)))
        elif kind == 1:  # torn write: truncate a suffix
            with open(path, "r+b") as f:
                f.truncate(int(rng.integers(0, size)))
        else:  # lost shard
            os.unlink(path)
            deleted += 1
    return damaged, deleted


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_chaos_lifecycle_recovers_bit_exact(tmp_path, seed):
    """encode → seeded damage (≤ parity shards) → scrub/self-heal →
    read: every payload must come back bit-exact, shards byte-identical
    to the originals."""
    rng = np.random.default_rng(seed)
    base, payloads = make_volume(tmp_path, needles=30, seed=seed)
    ec_encode_volume(base, CTX)
    originals = {
        i: open(base + CTX.to_ext(i), "rb").read() for i in range(CTX.total)
    }
    damaged, _ = _apply_schedule(base, rng)

    report = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert report.complete and not report.refused, report
    # every damaged shard is accounted for: leaf-localized bitrot is
    # patched IN PLACE (leaf_repaired, no quarantine), anything else
    # (deleted/truncated shards) goes corrupt/missing -> rebuild
    assert sorted(
        set(report.corrupt_shards)
        | set(report.missing_shards)
        | set(report.leaf_repaired)
    ) == damaged
    assert sorted(set(report.rebuilt) | set(report.leaf_repaired)) == damaged
    for sid in report.leaf_repaired:
        # in-place repair never quarantines
        assert not os.path.exists(base + CTX.to_ext(sid) + QUARANTINE_SUFFIX)
    for dest in report.quarantined:
        assert dest.endswith(QUARANTINE_SUFFIX) and os.path.exists(dest)

    for i in range(CTX.total):
        assert (
            open(base + CTX.to_ext(i), "rb").read() == originals[i]
        ), f"shard {i} not bit-exact after self-heal"
    exact, refused = read_all_or_refuse(tmp_path, payloads)
    assert refused == 0 and exact == len(payloads)

    # the healed volume scrubs clean
    clean = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX))
    assert clean.healthy, clean


@pytest.mark.parametrize("seed", [6, 7])
def test_chaos_lifecycle_beyond_parity_fails_closed(tmp_path, seed):
    """Damage > parity shards: scrub must refuse wholesale quarantine
    (sidecar-suspect rule) and reads must refuse rather than lie."""
    rng = np.random.default_rng(seed)
    base, payloads = make_volume(tmp_path, needles=10, seed=seed)
    ec_encode_volume(base, CTX)
    victims = sorted(
        int(x) for x in rng.choice(CTX.total, size=CTX.parity_shards + 2, replace=False)
    )
    for sid in victims:
        path = base + CTX.to_ext(sid)
        flip_byte(path, int(rng.integers(0, os.path.getsize(path))))
    report = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert report.refused and "suspect" in report.refused
    assert not report.quarantined and not report.rebuilt
    # reads: either bit-exact (undamaged extents) or refused — never wrong
    read_all_or_refuse(tmp_path, payloads)


@pytest.mark.slow
def test_chaos_lifecycle_randomized_soak(tmp_path):
    """Wide seed sweep of the same lifecycle (excluded from tier-1)."""
    for seed in range(100, 140):
        d = tmp_path / f"s{seed}"
        d.mkdir()
        rng = np.random.default_rng(seed)
        base, payloads = make_volume(d, needles=12, seed=seed)
        ec_encode_volume(base, CTX)
        _apply_schedule(base, rng)
        report = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
        assert report.complete and not report.refused, (seed, report)
        exact, refused = read_all_or_refuse(d, payloads)
        assert refused == 0 and exact == len(payloads), seed


# ------------------------------------------------ scrub daemon mechanics


def test_scrub_budget_pause_and_cursor_resume(tmp_path):
    base, shards = synth_shards(tmp_path)
    flip_byte(base + CTX.to_ext(3), 9000)  # block 2 of shard 3
    total_blocks = CTX.total * 4
    reports = []
    for _ in range(50):
        r = scrub_ec_volume(
            base, CTX, backend=CpuBackend(CTX), repair=True, max_blocks=5
        )
        reports.append(r)
        if r.complete:
            break
    assert reports[-1].complete and not reports[-1].refused
    assert [r.complete for r in reports[:-1]] == [False] * (len(reports) - 1)
    assert not os.path.exists(base + ".scrubpos")  # cursor dropped on completion
    assert reports[-1].rebuilt == [3]
    with open(base + CTX.to_ext(3), "rb") as f:
        assert f.read() == shards[3].tobytes()
    # corruption found in an early slice survived the pauses
    assert 3 in reports[-1].corrupt_shards
    # budget actually sliced the walk: strictly more than one pass ran
    assert len(reports) > 2
    checked = sum(r.checked_blocks for r in reports)
    assert checked <= total_blocks + 5


def test_scrub_cursor_restarts_on_generation_change(tmp_path):
    base, _ = synth_shards(tmp_path)
    ScrubCursor(generation=999, shard=12, block=3, corrupt=[2]).save(base)
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX))
    # stale-generation cursor is discarded: full walk, no phantom corrupt
    assert r.complete and r.checked_blocks == CTX.total * 4
    assert r.corrupt_shards == []


def test_scrub_refuses_without_sidecar(tmp_path):
    base, _ = synth_shards(tmp_path)
    os.unlink(base + ".ecsum")
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert r.refused and "sidecar" in r.refused


def test_scrub_refuses_malformed_sidecar(tmp_path):
    base, _ = synth_shards(tmp_path)
    with open(base + ".ecsum", "r+b") as f:
        f.seek(16)
        f.write(b"\xde\xad\xbe\xef")
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert r.refused and "malformed" in r.refused
    # the corrupt sidecar quarantined nothing
    assert all(os.path.exists(base + CTX.to_ext(i)) for i in range(CTX.total))


def test_scrub_below_rebuild_floor_refuses_quarantine(tmp_path):
    """k-1 shards already gone + 1 corrupt: quarantining would drop the
    set below reconstruction; scrub must keep its hands off."""
    ctx = ECContext(4, 2)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (4, 4096), dtype=np.uint8)
    parity = CpuBackend(ctx).encode(data)
    shards = np.concatenate([data, parity])
    base = str(tmp_path / "1")
    builders = [ShardChecksumBuilder(1024) for _ in range(ctx.total)]
    for i in range(ctx.total):
        with open(base + ctx.to_ext(i), "wb") as f:
            f.write(shards[i].tobytes())
        builders[i].write(shards[i].tobytes())
    BitrotProtection.from_builders(ctx, builders).save(base + ".ecsum")
    for i in (0, 1, 2):
        os.unlink(base + ctx.to_ext(i))
    flip_byte(base + ctx.to_ext(3), 10)
    r = scrub_ec_volume(base, ctx, backend=CpuBackend(ctx), repair=True)
    assert r.refused and "floor" in r.refused
    assert os.path.exists(base + ctx.to_ext(3))  # NOT quarantined


def test_rate_limiter_paces_reads():
    sleeps = []
    t = [0.0]
    rl = RateLimiter(
        1000.0, burst=1000.0, clock=lambda: t[0], sleep=sleeps.append
    )
    rl.consume(1000)  # drains the burst, no sleep yet
    assert sleeps == []
    rl.consume(500)  # 500 tokens over: sleep 0.5s at 1000 B/s
    assert sleeps == [pytest.approx(0.5)]
    t[0] += 10.0  # bucket refills (capped at burst)
    rl.consume(800)
    assert len(sleeps) == 1  # within burst again


def test_scrub_daemon_heals_store_volume(tmp_path):
    from seaweedfs_tpu.storage.store import Store

    d = tmp_path / "v"
    d.mkdir()
    base, payloads = make_volume(d, needles=10, seed=4)
    ec_encode_volume(base, CTX)
    store = Store([str(d)], ec_backend="cpu")
    try:
        ev = store.find_ec_volume(1)
        assert ev is not None
        original = open(base + CTX.to_ext(5), "rb").read()
        flip_byte(base + CTX.to_ext(5), 777)
        daemon = ScrubDaemon(store, interval=3600.0, repair=True)
        reports = daemon.scrub_once()
        # leaf-localized bitrot is patched IN PLACE under the repair
        # journal: no quarantine, no whole-shard rebuild, no unmount
        assert 5 in reports[1].leaf_repaired, reports[1]
        assert not reports[1].rebuilt and not reports[1].quarantined
        assert not os.path.exists(base + CTX.to_ext(5) + QUARANTINE_SUFFIX)
        assert open(base + CTX.to_ext(5), "rb").read() == original
        # the live EcVolume keeps serving (same inode — the fd never
        # went stale), and every payload is bit-exact
        assert 5 in ev.shard_ids
        for i, want in payloads.items():
            assert ev.read_needle(i).data == want
        # second pass is clean
        assert daemon.scrub_once()[1].healthy
    finally:
        store.close()


def test_scrub_subset_holder_skips_peer_shards(tmp_path):
    """A balanced-cluster server holding 5 of 14 shards: absent peer
    shards are NOT 'missing', no rebuild storm, no duplicate minting —
    and a rebuild for a local corrupt shard must not regenerate peers'
    shards as local files (only_shards)."""
    base, shards = synth_shards(tmp_path)
    local = [0, 3, 5, 9, 12]
    for i in range(CTX.total):
        if i not in local:
            os.unlink(base + CTX.to_ext(i))
    r = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=True, expected_shards=local
    )
    assert r.complete and r.healthy, r
    assert r.missing_shards == [] and r.rebuilt == []

    # now the subset server loses one of ITS shards: only that one is
    # rebuilt, peers' shards stay absent
    full = tmp_path / "full"
    full.mkdir()
    base2, _ = synth_shards(full)
    for i in (1, 2):
        os.unlink(base2 + CTX.to_ext(i))  # peers' shards, absent here
    os.unlink(base2 + CTX.to_ext(5))  # OUR shard, lost
    mine = [i for i in range(CTX.total) if i not in (1, 2)]
    r2 = scrub_ec_volume(
        base2, CTX, backend=CpuBackend(CTX), repair=True, expected_shards=mine
    )
    assert r2.rebuilt == [5], r2
    assert os.path.exists(base2 + CTX.to_ext(5))
    assert not os.path.exists(base2 + CTX.to_ext(1))
    assert not os.path.exists(base2 + CTX.to_ext(2))


def test_scrub_daemon_remembers_quarantined_shard_after_failed_rebuild(tmp_path):
    """Quarantine unmounts the shard; if the rebuild then fails, the
    NEXT pass must still treat it as missing (via the on-disk .bad
    marker) instead of reporting healthy with redundancy silently lost."""
    from seaweedfs_tpu.storage.store import Store

    d = tmp_path / "v"
    d.mkdir()
    base, payloads = make_volume(d, needles=8, seed=20)
    ec_encode_volume(base, CTX)
    store = Store([str(d)], ec_backend="cpu")
    try:
        ev = store.find_ec_volume(1)
        # SIZE rot (truncation), not a bit flip: leaf repair cannot
        # patch a resized file in place, so this still exercises the
        # quarantine + rebuild path
        path6 = base + CTX.to_ext(6)
        os.truncate(path6, os.path.getsize(path6) - 100)
        daemon = ScrubDaemon(store, interval=3600.0, repair=True)
        # wedge vol 1's breaker: pass 1 quarantines but cannot rebuild
        b = daemon.breaker_for(1)
        for _ in range(b.failure_threshold):
            b.record_failure()
        r1 = daemon.scrub_once()[1]
        assert r1.quarantined and not r1.rebuilt and "skipped" in r1.refused, r1
        assert 6 not in ev.shard_ids  # unmounted, serving degraded
        # pass 2 with the breaker healed: the shard is NOT forgotten
        b.record_success()
        r2 = daemon.scrub_once()[1]
        assert r2.missing_shards == [6] and r2.rebuilt == [6], r2
        assert 6 in ev.shard_ids  # remounted
        for i, want in payloads.items():
            assert ev.read_needle(i).data == want
    finally:
        store.close()


def test_scrub_daemon_breaker_stops_rebuild_storm(tmp_path):
    """Rebuild impossible (too few shards): the breaker opens after
    repeated failures and later passes skip the rebuild attempt."""
    base, _ = synth_shards(tmp_path)
    for i in range(CTX.parity_shards + 1):
        os.unlink(base + CTX.to_ext(i))  # 9 shards left < k=10: rebuild must fail
    breaker = CircuitBreaker(failure_threshold=1, reset_timeout=9999.0)
    r1 = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True, breaker=breaker)
    assert r1.refused.startswith("rebuild failed")
    assert breaker.state == "open"
    r2 = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True, breaker=breaker)
    assert r2.refused.startswith("rebuild skipped")


# -------------------------------------------- crash-window (satellite 3)


def _crashing_child(base, point, nth, conn):
    """Runs rebuild with a hard-exit fault armed; never returns."""
    faults.inject(point, faults.hard_exit(137), when=faults.nth_call(nth))
    try:
        rebuild_ec_files(base, CTX, backend=CpuBackend(CTX))
    except BaseException as e:  # pragma: no cover - only on fault miss
        conn.send(repr(e))
    conn.send("no crash")


def _run_crash(base, point, nth=1):
    mp = multiprocessing.get_context("fork")
    parent, child = mp.Pipe()
    p = mp.Process(target=_crashing_child, args=(base, point, nth, child))
    p.start()
    p.join(timeout=120)
    assert not p.is_alive(), "crash child hung"
    assert p.exitcode == 137, f"expected hard crash, got {p.exitcode}"
    assert not parent.poll(), "child survived past the crash point"


@pytest.mark.parametrize(
    "point",
    ["ec.rebuild.before_fsync", "ec.rebuild.before_rename", "ec.rebuild.after_rename"],
)
def test_rebuild_crash_window_then_recover(tmp_path, point):
    """Kill the rebuild between temp-write, fsync and each atomic
    rename; a restarted rebuild must converge to bit-exact shards."""
    base, payloads = make_volume(tmp_path, needles=15, seed=8)
    ec_encode_volume(base, CTX)
    originals = {
        i: open(base + CTX.to_ext(i), "rb").read() for i in range(CTX.total)
    }
    for sid in (2, 11):
        os.unlink(base + CTX.to_ext(sid))

    _run_crash(base, point)

    # crash left either nothing, temps, or a partial publish — never a
    # wrong published shard
    for sid in (2, 11):
        p = base + CTX.to_ext(sid)
        if os.path.exists(p):
            assert open(p, "rb").read() == originals[sid]

    # restart heals to bit-exact
    rebuilt = rebuild_ec_files(base, CTX, backend=CpuBackend(CTX))
    if point == "ec.rebuild.after_rename":
        # first rename may have landed before the crash
        assert set(rebuilt) <= {2, 11}
    else:
        assert rebuilt == [2, 11]
    for i in range(CTX.total):
        assert open(base + CTX.to_ext(i), "rb").read() == originals[i]
    exact, refused = read_all_or_refuse(tmp_path, payloads)
    assert refused == 0 and exact == len(payloads)


def _crashing_decode_child(base, point):
    faults.inject(point, faults.hard_exit(137))
    ec_decode_volume(base)


@pytest.mark.parametrize(
    "point",
    [
        "ec.decode.idx.before_rename",
        "ec.decode.dat.before_fsync",
        "ec.decode.dat.before_rename",
    ],
)
def test_decode_crash_window_then_recover(tmp_path, point):
    base, payloads = make_volume(tmp_path, needles=12, seed=9)
    with open(base + ".dat", "rb") as f:
        original_dat = f.read()
    ec_encode_volume(base, CTX)
    os.unlink(base + ".dat")
    os.unlink(base + ".idx")

    mp = multiprocessing.get_context("fork")
    p = mp.Process(target=_crashing_decode_child, args=(base, point))
    p.start()
    p.join(timeout=120)
    assert p.exitcode == 137, f"expected hard crash, got {p.exitcode}"
    # the published .dat either does not exist yet or is complete —
    # atomic rename means never a half-written one
    if os.path.exists(base + ".dat"):
        assert open(base + ".dat", "rb").read() == original_dat

    assert ec_decode_volume(base) is True
    assert open(base + ".dat", "rb").read() == original_dat
    v = Volume(str(tmp_path), 1, create=False)
    for i, want in payloads.items():
        assert v.read_needle(i).data == want
    v.close()


def test_encode_crash_before_ecsum_scrub_refuses_reencode_heals(tmp_path):
    """In-process InjectedCrash between shard publish and sidecar write:
    shards exist with no .ecsum — reads work, scrub refuses (no ground
    truth), re-encode writes the sidecar and heals the volume."""
    base, payloads = make_volume(tmp_path, needles=10, seed=10)
    with faults.injected("ec.encode.before_ecsum", faults.crash()):
        with pytest.raises(BaseException) as ei:
            ec_encode_volume(base, CTX)
        assert isinstance(ei.value, faults.InjectedCrash)
    assert os.path.exists(base + CTX.to_ext(0))
    assert not os.path.exists(base + ".ecsum")
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    assert r.refused and "sidecar" in r.refused
    ec_encode_volume(base, CTX)  # heal
    assert os.path.exists(base + ".ecsum")
    assert scrub_ec_volume(base, CTX, backend=CpuBackend(CTX)).healthy
    exact, refused = read_all_or_refuse(tmp_path, payloads)
    assert refused == 0 and exact == len(payloads)


# --------------------------- rebuild fed corrupt inputs must fail closed


def test_rebuild_with_corrupt_sibling_read_fails_closed(tmp_path):
    """Bit-flip a sibling read DURING rebuild (post-sidecar-verify TOCTOU
    rot): the regenerated shard fails output verification and nothing is
    published."""
    base, _ = make_volume(tmp_path, needles=15, seed=11)
    ec_encode_volume(base, CTX)
    os.unlink(base + CTX.to_ext(1))
    with faults.injected(
        "ec.rebuild.read_shard", faults.bit_flip(seed=5), when=faults.nth_call(3)
    ):
        with pytest.raises(ECError, match="sidecar verification"):
            rebuild_ec_files(base, CTX, backend=CpuBackend(CTX))
    assert not os.path.exists(base + CTX.to_ext(1)), "corrupt shard published!"
    assert not os.path.exists(base + CTX.to_ext(1) + ".rebuilding"), "temp leaked"
    # clean retry succeeds
    assert rebuild_ec_files(base, CTX, backend=CpuBackend(CTX)) == [1]


def test_rebuild_with_corrupt_output_fails_closed(tmp_path):
    base, _ = make_volume(tmp_path, needles=15, seed=12)
    ec_encode_volume(base, CTX)
    os.unlink(base + CTX.to_ext(13))
    with faults.injected("ec.rebuild.shard_bytes", faults.bit_flip(seed=6)):
        with pytest.raises(ECError, match="sidecar verification"):
            rebuild_ec_files(base, CTX, backend=CpuBackend(CTX))
    assert not os.path.exists(base + CTX.to_ext(13))


# ------------------------------------ device-failure fallback (tentpole)


def _fallback_backend():
    return FallbackBackend(
        JaxBackend(CTX, impl="xla", n_devices=1),
        CpuBackend(CTX),
        breaker=CircuitBreaker(failure_threshold=3, reset_timeout=9999.0),
    )


def test_jax_midbatch_failure_falls_back_bit_identical(tmp_path):
    base, _ = make_volume(tmp_path, needles=25, seed=13)
    write_ec_files(base, CTX, CpuBackend(CTX), batch_size=100_000)
    want = {i: open(base + CTX.to_ext(i), "rb").read() for i in range(CTX.total)}

    fb = _fallback_backend()
    with faults.injected(
        "ec.backend.device.to_host", faults.io_error("device lost"),
        when=faults.nth_call(2), count=1,
    ):
        write_ec_files(base, CTX, fb, batch_size=100_000)
    assert fb.fallback_batches >= 1, "fallback path never engaged"
    for i in range(CTX.total):
        assert open(base + CTX.to_ext(i), "rb").read() == want[i], (
            f"shard {i} differs after mid-batch CPU failover"
        )


def test_midkernel_device_reset_falls_back_bit_identical(tmp_path):
    """TPU-side chaos hook (PR 1 carried item): the fault point sits
    INSIDE the device backend between kernel launch and result fetch
    (`ec.device.kernel_fetch` in JaxBackend.to_host), so this exercises
    a device reset AFTER the kernel was dispatched — the spot a
    hung/reset TPU actually surfaces — not just pre-dispatch death.
    FallbackBackend must replay the in-flight batch on CPU
    bit-identically."""
    base, _ = make_volume(tmp_path, needles=25, seed=15)
    write_ec_files(base, CTX, CpuBackend(CTX), batch_size=100_000)
    want = {i: open(base + CTX.to_ext(i), "rb").read() for i in range(CTX.total)}

    fb = _fallback_backend()
    with faults.injected(
        "ec.device.kernel_fetch", faults.io_error("device reset mid-kernel"),
        when=faults.nth_call(2), count=1,
    ) as h:
        write_ec_files(base, CTX, fb, batch_size=100_000)
    assert h.fired == 1, "mid-kernel fault point never armed"
    assert fb.fallback_batches >= 1, "mid-kernel failover never engaged"
    for i in range(CTX.total):
        assert open(base + CTX.to_ext(i), "rb").read() == want[i], (
            f"shard {i} differs after mid-kernel CPU failover"
        )


def test_breaker_health_gauge_and_queue_snapshot():
    """Pod health surface (PR 5 carried item): an open per-chip breaker
    shows as sw_ec_chip_breaker_open=1 at /metrics scrape time, and the
    queue stats snapshot carries the breaker state for /status's
    `degraded` flag."""
    from seaweedfs_tpu.ec.device_queue import QueueScope
    from seaweedfs_tpu.utils.metrics import REGISTRY

    fb = _fallback_backend()
    scope = QueueScope()
    q = scope.for_backend(fb)
    assert q is not None
    snap = scope.stats_snapshot()
    assert snap and snap[0]["breaker"] == "closed"
    for _ in range(3):
        fb.breaker.record_failure()
    assert fb.breaker.state == "open"
    snap = scope.stats_snapshot()
    assert snap[0]["breaker"] == "open"
    label = f"JaxBackend@{fb._seq}"  # no chip pool: instance-tag label

    def gauge_value() -> str:
        for l in REGISTRY.render().decode().splitlines():
            if l.startswith("sw_ec_chip_breaker_open") and label in l:
                return l.rsplit(" ", 1)[1]
        return ""

    assert gauge_value() == "1"
    fb.breaker.record_success()
    assert gauge_value() == "0"


def test_fallback_breaker_opens_and_cpu_serves(tmp_path):
    base, _ = make_volume(tmp_path, needles=20, seed=14)
    write_ec_files(base, CTX, CpuBackend(CTX), batch_size=100_000)
    want = {i: open(base + CTX.to_ext(i), "rb").read() for i in range(CTX.total)}

    fb = _fallback_backend()
    with faults.injected(
        "ec.backend.device.encode_staged", faults.io_error("device dead")
    ):
        write_ec_files(base, CTX, fb, batch_size=100_000)
    assert fb.breaker.state == "open"
    assert fb.fallback_batches >= 3
    for i in range(CTX.total):
        assert open(base + CTX.to_ext(i), "rb").read() == want[i]
    # device recovery: breaker half-open probe succeeds and closes it
    fb.breaker.reset_timeout = 0.0
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (CTX.data_shards, 1024), dtype=np.uint8)
    assert np.array_equal(fb.encode(data), CpuBackend(CTX).encode(data))
    assert fb.breaker.state == "closed"


def test_fallback_caller_errors_pass_through_without_demotion():
    """Bad input fails identically on CPU: it must re-raise, not count
    as a device failure (a healthy TPU must not be demoted by typos)."""
    fb = _fallback_backend()
    with pytest.raises((ECError, ValueError, TypeError)):
        fb.reconstruct({0: np.zeros(8, np.uint8)})  # < k shards
    assert fb.breaker.state == "closed" and fb.fallback_batches == 0


def test_injected_crash_not_absorbed_by_fallback():
    fb = _fallback_backend()
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (CTX.data_shards, 512), dtype=np.uint8)
    with faults.injected("ec.backend.device.encode", faults.crash()):
        with pytest.raises(faults.InjectedCrash):
            fb.encode(data)


# ----------------------- degraded reads verified against the sidecar


def test_degraded_read_excludes_rotten_sibling_and_heals(tmp_path):
    """Missing shard + a silently-rotten sibling: the sidecar identifies
    the rotten source, reconstruction uses the clean k, and every read
    is bit-exact (satellite: backend.reconstruct inputs/outputs were
    previously trusted unverified)."""
    base, payloads = make_volume(tmp_path, needles=20, seed=15)
    ec_encode_volume(base, CTX)
    os.unlink(base + CTX.to_ext(0))
    # rot a sibling data shard ON DISK (sidecar knows the truth, the
    # serving fd does not)
    path = base + CTX.to_ext(1)
    for off in range(0, os.path.getsize(path), 997):
        flip_byte(path, off)
    exact, refused = read_all_or_refuse(tmp_path, payloads)
    assert refused == 0 and exact == len(payloads), (
        "verified recovery should exclude the rotten source and heal"
    )


def test_degraded_read_refuses_below_k_clean_sources(tmp_path):
    """Missing shard + enough rotten siblings that fewer than k clean
    sources exist: reads refuse (ECError), never serve garbage."""
    base, payloads = make_volume(tmp_path, needles=12, seed=19)
    ec_encode_volume(base, CTX)
    os.unlink(base + CTX.to_ext(0))
    for sid in (1, 2, 3, 4, 5):  # 8 clean siblings remain < k=10
        path = base + CTX.to_ext(sid)
        for off in range(0, os.path.getsize(path), 991):
            flip_byte(path, off)
    read_all_or_refuse(tmp_path, payloads)  # the no-silent-corruption gate
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        with pytest.raises((ECError, CrcError)):
            for i in payloads:
                ev.read_needle(i)
    finally:
        ev.close()


def test_local_bitflip_self_heals_on_read(tmp_path):
    """A bit-flipped LOCAL shard read (fault point, disk rot model)
    trips the needle CRC and the read retries via sidecar-verified
    reconstruction — the client still gets bit-exact bytes."""
    base, payloads = make_volume(tmp_path, needles=6, seed=16)
    ec_encode_volume(base, CTX)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        with faults.injected(
            "ec.volume.shard_read", faults.bit_flip(seed=9), count=1
        ):
            for i, want in payloads.items():
                assert ev.read_needle(i).data == want
    finally:
        ev.close()


def test_local_io_error_degrades_to_reconstruction(tmp_path):
    base, payloads = make_volume(tmp_path, needles=6, seed=17)
    ec_encode_volume(base, CTX)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        with faults.injected("ec.volume.shard_read", faults.io_error()):
            for i, want in payloads.items():
                assert ev.read_needle(i).data == want
    finally:
        ev.close()


def test_corrupting_remote_reader_never_serves_rot(tmp_path):
    """A peer streaming corrupted shard bytes (server.ec_shard_read
    bit-flip model, exercised here via the remote_reader seam): needle
    CRC catches it and verified local reconstruction serves truth."""
    base, payloads = make_volume(tmp_path, needles=6, seed=18)
    ec_encode_volume(base, CTX)
    corruptor = faults.bit_flip(seed=4)

    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        victim = sorted(ev.shard_ids)[0]
        orig_fd = ev.shard_fds.pop(victim)  # shard "not local" anymore
        os.close(orig_fd)

        def evil_remote(shard_id, offset, size, generation):
            with open(base + CTX.to_ext(shard_id), "rb") as f:
                f.seek(offset)
                return corruptor({}, f.read(size))

        ev.remote_reader = evil_remote
        for i, want in payloads.items():
            assert ev.read_needle(i).data == want
    finally:
        ev.close()


# ------------------------------------------- storage backend fault seams


def test_disk_file_read_faults(tmp_path):
    from seaweedfs_tpu.storage.backend import DiskFile

    p = str(tmp_path / "f")
    with open(p, "wb") as f:
        f.write(b"0123456789")
    df = DiskFile(p)
    try:
        assert df.read_at(2, 4) == b"2345"
        with faults.injected("storage.disk.read_at", faults.io_error()):
            with pytest.raises(IOError):
                df.read_at(0, 4)
        with faults.injected("storage.disk.read_at", faults.truncate(0.5)):
            assert df.read_at(0, 8) == b"0123"  # torn read
        with faults.injected("storage.disk.read_at", faults.bit_flip(seed=1)):
            assert df.read_at(0, 4) != b"0123"
        assert df.read_at(0, 4) == b"0123"  # registry cleared by ctx mgr
    finally:
        df.close()
