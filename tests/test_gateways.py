"""WebDAV gateway + filer notification + benchmark CLI tests."""

import json
import threading
import time
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.notification import MqNotifier, WebhookNotifier
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.server.webdav_server import WebDavServer


from conftest import allocate_port as free_port


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gw")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


def test_webdav_crud_and_propfind(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    port = free_port()
    srv = WebDavServer(filer, ip="localhost", port=port)
    srv.start()
    base = f"http://localhost:{port}"
    try:
        r = requests.request("OPTIONS", base + "/")
        assert "PROPFIND" in r.headers["Allow"]
        assert requests.request("MKCOL", f"{base}/docs").status_code == 201
        data = b"dav content" * 1000
        assert requests.put(f"{base}/docs/a.txt", data=data,
                            headers={"Content-Type": "text/plain"}).status_code == 201
        r = requests.get(f"{base}/docs/a.txt")
        assert r.content == data
        # PROPFIND depth 1 lists the collection
        r = requests.request("PROPFIND", f"{base}/docs", headers={"Depth": "1"})
        assert r.status_code == 207
        root = ET.fromstring(r.content)
        hrefs = [e.text for e in root.iter("{DAV:}href")]
        assert "/docs/" in hrefs and "/docs/a.txt" in hrefs
        sizes = [e.text for e in root.iter("{DAV:}getcontentlength")]
        assert str(len(data)) in sizes
        # MOVE
        r = requests.request(
            "MOVE", f"{base}/docs/a.txt",
            headers={"Destination": f"{base}/docs/b.txt"},
        )
        assert r.status_code == 201
        assert requests.get(f"{base}/docs/b.txt").content == data
        assert requests.get(f"{base}/docs/a.txt").status_code == 404
        # COPY
        r = requests.request(
            "COPY", f"{base}/docs/b.txt",
            headers={"Destination": f"{base}/docs/c.txt"},
        )
        assert r.status_code == 201
        assert requests.get(f"{base}/docs/c.txt").content == data
        # same-path MOVE is forbidden and must not destroy the file
        r = requests.request(
            "MOVE", f"{base}/docs/b.txt",
            headers={"Destination": f"{base}/docs/b.txt"},
        )
        assert r.status_code == 403
        assert requests.get(f"{base}/docs/b.txt").content == data
        # Overwrite: F protects an existing destination
        r = requests.request(
            "MOVE", f"{base}/docs/b.txt",
            headers={"Destination": f"{base}/docs/c.txt", "Overwrite": "F"},
        )
        assert r.status_code == 412
        assert requests.get(f"{base}/docs/c.txt").content == data
        # chunked PUT (no Content-Length)
        def gen():
            yield b"chunked "
            yield b"body"
        r = requests.put(f"{base}/docs/chunked.txt", data=gen())
        assert r.status_code == 201
        assert requests.get(f"{base}/docs/chunked.txt").content == b"chunked body"
        # percent-encoded hrefs for awkward names
        requests.put(f"{base}/docs/a%20b%23c.txt", data=b"x")
        r = requests.request("PROPFIND", f"{base}/docs", headers={"Depth": "1"})
        assert "/docs/a%20b%23c.txt" in r.text
        # DELETE collection
        assert requests.delete(f"{base}/docs").status_code == 204
        assert requests.get(f"{base}/docs/b.txt").status_code == 404
    finally:
        srv.stop()
        filer.close()


def test_webhook_notifier(cluster):
    received = []

    class Hook(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0"))
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    hport = free_port()
    hook_srv = ThreadingHTTPServer(("localhost", hport), Hook)
    threading.Thread(target=hook_srv.serve_forever, daemon=True).start()

    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    notifier = WebhookNotifier(f"http://localhost:{hport}/events")
    filer.subscribe(notifier)
    try:
        filer.write_file("/n/x.bin", b"notify me")
        filer.delete_entry("/n/x.bin")
        deadline = time.time() + 5
        while len(received) < 3 and time.time() < deadline:  # mkdir + create + delete
            time.sleep(0.05)
        assert notifier.delivered >= 3
        creates = [e for e in received if e["newEntry"] and e["newEntry"]["name"] == "x.bin"]
        deletes = [e for e in received if e["oldEntry"] and not e["newEntry"]]
        assert creates and deletes
        assert creates[0]["directory"] == "/n"
    finally:
        notifier.close()
        hook_srv.shutdown()
        hook_srv.server_close()
        filer.close()


def test_mq_notifier(cluster):
    from seaweedfs_tpu.mq import MqBrokerServer, MqClient

    broker = MqBrokerServer(ip="localhost", grpc_port=free_port())
    broker.start()
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    notifier = MqNotifier(f"localhost:{broker.grpc_port}")
    filer.subscribe(notifier)
    try:
        filer.write_file("/mq/y.bin", b"event")
        c = MqClient(f"localhost:{broker.grpc_port}")
        # The notifier publishes asynchronously; poll with a deadline
        # instead of a one-shot read (the one-shot raced delivery).
        deadline = time.monotonic() + 10.0
        found = False
        while not found and time.monotonic() < deadline:
            events = []
            for p in range(4):
                for rec in c.subscribe("filer-events", p, start_offset=0):
                    events.append(json.loads(rec.message.value))
            found = any(
                e["newEntry"] and e["newEntry"]["name"] == "y.bin"
                for e in events
            )
            if not found:
                time.sleep(0.05)
        c.close()
        assert found
    finally:
        notifier.close()
        filer.close()
        broker.stop()


def test_benchmark_cli(cluster):
    from seaweedfs_tpu.benchmark.__main__ import main as bench_main

    assert bench_main(
        ["-master", f"localhost:{cluster}", "-n", "40", "-size", "500", "-c", "4"]
    ) == 0


def test_webdav_class2_locks(cluster):
    """RFC 4918 class 2: LOCK/UNLOCK with If-token enforcement,
    refresh, depth-infinity collection locks, unmapped-URL creation."""
    from seaweedfs_tpu.server.webdav_server import WebDavServer

    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    dav = WebDavServer(filer, ip="localhost", port=free_port())
    dav.start()
    base = f"http://localhost:{dav.port}"
    try:
        opts = requests.options(f"{base}/")
        assert "2" in opts.headers["DAV"]
        assert "LOCK" in opts.headers["Allow"]

        lockinfo = (
            '<?xml version="1.0"?><D:lockinfo xmlns:D="DAV:">'
            "<D:lockscope><D:exclusive/></D:lockscope>"
            "<D:locktype><D:write/></D:locktype>"
            "<D:owner>alice</D:owner></D:lockinfo>"
        )
        # LOCK on an unmapped URL creates the resource (201)
        r = requests.request(
            "LOCK", f"{base}/doc.txt", data=lockinfo,
            headers={"Timeout": "Second-60"},
        )
        assert r.status_code == 201, r.status_code
        token = r.headers["Lock-Token"].strip("<>")
        assert token.startswith("opaquelocktoken:")
        assert "lockdiscovery" in r.text

        # mutations without the token are 423; with it they pass
        assert requests.put(f"{base}/doc.txt", data=b"x").status_code == 423
        assert requests.delete(f"{base}/doc.txt").status_code == 423
        r = requests.put(
            f"{base}/doc.txt", data=b"locked write",
            headers={"If": f"(<{token}>)"},
        )
        assert r.status_code == 201
        assert requests.get(f"{base}/doc.txt").content == b"locked write"

        # second LOCK on the same resource conflicts
        r2 = requests.request("LOCK", f"{base}/doc.txt", data=lockinfo)
        assert r2.status_code == 423

        # refresh (empty body + If header)
        r3 = requests.request(
            "LOCK", f"{base}/doc.txt",
            headers={"If": f"(<{token}>)", "Timeout": "Second-120"},
        )
        assert r3.status_code == 200 and "Second-120" in r3.text

        # PROPFIND shows the active lock
        pf = requests.request(
            "PROPFIND", f"{base}/doc.txt", headers={"Depth": "0"}
        )
        assert "lockdiscovery" in pf.text and "supportedlock" in pf.text

        # UNLOCK frees it
        assert (
            requests.request(
                "UNLOCK", f"{base}/doc.txt",
                headers={"Lock-Token": f"<{token}>"},
            ).status_code
            == 204
        )
        assert requests.put(f"{base}/doc.txt", data=b"free").status_code == 201

        # depth-infinity collection lock protects children
        requests.request("MKCOL", f"{base}/proj")
        r = requests.request("LOCK", f"{base}/proj", data=lockinfo)
        assert r.status_code == 200
        ctoken = r.headers["Lock-Token"].strip("<>")
        assert (
            requests.put(f"{base}/proj/child.txt", data=b"y").status_code
            == 423
        )
        assert (
            requests.put(
                f"{base}/proj/child.txt", data=b"y",
                headers={"If": f"(<{ctoken}>)"},
            ).status_code
            == 201
        )
        # a MOVE of a locked subtree without the token is refused
        requests.put(f"{base}/other.txt", data=b"z")
        assert (
            requests.request(
                "MOVE", f"{base}/proj/child.txt",
                headers={"Destination": f"{base}/elsewhere.txt"},
            ).status_code
            == 423
        )
    finally:
        dav.stop()
        filer.close()


def test_kafka_notifier(cluster):
    """Filer events flow to a Kafka-protocol broker (the reference's
    weed/notification/kafka sink) and are consumable with any client."""
    from seaweedfs_tpu.filer.notification import make_notifier
    from seaweedfs_tpu.mq.broker import MqBrokerServer
    from seaweedfs_tpu.mq.kafka.client import KafkaClient

    broker = MqBrokerServer(
        ip="localhost", grpc_port=free_port(), kafka_port=0,
        archive_interval=0,
    )
    broker.start()
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    notifier = make_notifier(
        "kafka", f"localhost:{broker.kafka.port}", topic="filer-ev"
    )
    filer.subscribe(notifier)
    try:
        filer.write_file("/kn/z.bin", b"kafka event")
        c = KafkaClient("127.0.0.1", broker.kafka.port)
        deadline = time.monotonic() + 10
        found = False
        while not found and time.monotonic() < deadline:
            _, recs = c.fetch("filer-ev", 0, 0)
            for r in recs:
                ev = json.loads(r.value)
                if ev.get("newEntry") and ev["newEntry"]["name"] == "z.bin":
                    found = True
            if not found:
                time.sleep(0.05)
        c.close()
        assert found
    finally:
        notifier.close()
        filer.close()
        broker.stop()


def test_gated_cloud_sinks_fail_loudly():
    from seaweedfs_tpu.filer.notification import make_notifier

    import pytest as _pytest

    with _pytest.raises((RuntimeError, NotImplementedError)):
        make_notifier("sqs", "https://sqs.region.amazonaws.com/q")
    with _pytest.raises((RuntimeError, NotImplementedError)):
        make_notifier("pubsub", "projects/p/topics/t")
    with _pytest.raises(ValueError):
        make_notifier("bogus", "x")
