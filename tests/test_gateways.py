"""WebDAV gateway + filer notification + benchmark CLI tests."""

import json
import threading
import time
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.notification import MqNotifier, WebhookNotifier
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.server.webdav_server import WebDavServer


from conftest import allocate_port as free_port


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gw")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


def test_webdav_crud_and_propfind(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    port = free_port()
    srv = WebDavServer(filer, ip="localhost", port=port)
    srv.start()
    base = f"http://localhost:{port}"
    try:
        r = requests.request("OPTIONS", base + "/")
        assert "PROPFIND" in r.headers["Allow"]
        assert requests.request("MKCOL", f"{base}/docs").status_code == 201
        data = b"dav content" * 1000
        assert requests.put(f"{base}/docs/a.txt", data=data,
                            headers={"Content-Type": "text/plain"}).status_code == 201
        r = requests.get(f"{base}/docs/a.txt")
        assert r.content == data
        # PROPFIND depth 1 lists the collection
        r = requests.request("PROPFIND", f"{base}/docs", headers={"Depth": "1"})
        assert r.status_code == 207
        root = ET.fromstring(r.content)
        hrefs = [e.text for e in root.iter("{DAV:}href")]
        assert "/docs/" in hrefs and "/docs/a.txt" in hrefs
        sizes = [e.text for e in root.iter("{DAV:}getcontentlength")]
        assert str(len(data)) in sizes
        # MOVE
        r = requests.request(
            "MOVE", f"{base}/docs/a.txt",
            headers={"Destination": f"{base}/docs/b.txt"},
        )
        assert r.status_code == 201
        assert requests.get(f"{base}/docs/b.txt").content == data
        assert requests.get(f"{base}/docs/a.txt").status_code == 404
        # COPY
        r = requests.request(
            "COPY", f"{base}/docs/b.txt",
            headers={"Destination": f"{base}/docs/c.txt"},
        )
        assert r.status_code == 201
        assert requests.get(f"{base}/docs/c.txt").content == data
        # same-path MOVE is forbidden and must not destroy the file
        r = requests.request(
            "MOVE", f"{base}/docs/b.txt",
            headers={"Destination": f"{base}/docs/b.txt"},
        )
        assert r.status_code == 403
        assert requests.get(f"{base}/docs/b.txt").content == data
        # Overwrite: F protects an existing destination
        r = requests.request(
            "MOVE", f"{base}/docs/b.txt",
            headers={"Destination": f"{base}/docs/c.txt", "Overwrite": "F"},
        )
        assert r.status_code == 412
        assert requests.get(f"{base}/docs/c.txt").content == data
        # chunked PUT (no Content-Length)
        def gen():
            yield b"chunked "
            yield b"body"
        r = requests.put(f"{base}/docs/chunked.txt", data=gen())
        assert r.status_code == 201
        assert requests.get(f"{base}/docs/chunked.txt").content == b"chunked body"
        # percent-encoded hrefs for awkward names
        requests.put(f"{base}/docs/a%20b%23c.txt", data=b"x")
        r = requests.request("PROPFIND", f"{base}/docs", headers={"Depth": "1"})
        assert "/docs/a%20b%23c.txt" in r.text
        # DELETE collection
        assert requests.delete(f"{base}/docs").status_code == 204
        assert requests.get(f"{base}/docs/b.txt").status_code == 404
    finally:
        srv.stop()
        filer.close()


def test_webhook_notifier(cluster):
    received = []

    class Hook(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0"))
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    hport = free_port()
    hook_srv = ThreadingHTTPServer(("localhost", hport), Hook)
    threading.Thread(target=hook_srv.serve_forever, daemon=True).start()

    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    notifier = WebhookNotifier(f"http://localhost:{hport}/events")
    filer.subscribe(notifier)
    try:
        filer.write_file("/n/x.bin", b"notify me")
        filer.delete_entry("/n/x.bin")
        deadline = time.time() + 5
        while len(received) < 3 and time.time() < deadline:  # mkdir + create + delete
            time.sleep(0.05)
        assert notifier.delivered >= 3
        creates = [e for e in received if e["newEntry"] and e["newEntry"]["name"] == "x.bin"]
        deletes = [e for e in received if e["oldEntry"] and not e["newEntry"]]
        assert creates and deletes
        assert creates[0]["directory"] == "/n"
    finally:
        notifier.close()
        hook_srv.shutdown()
        hook_srv.server_close()
        filer.close()


def test_mq_notifier(cluster):
    from seaweedfs_tpu.mq import MqBrokerServer, MqClient

    broker = MqBrokerServer(ip="localhost", grpc_port=free_port())
    broker.start()
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    notifier = MqNotifier(f"localhost:{broker.grpc_port}")
    filer.subscribe(notifier)
    try:
        filer.write_file("/mq/y.bin", b"event")
        c = MqClient(f"localhost:{broker.grpc_port}")
        # The notifier publishes asynchronously; poll with a deadline
        # instead of a one-shot read (the one-shot raced delivery).
        deadline = time.monotonic() + 10.0
        found = False
        while not found and time.monotonic() < deadline:
            events = []
            for p in range(4):
                for rec in c.subscribe("filer-events", p, start_offset=0):
                    events.append(json.loads(rec.message.value))
            found = any(
                e["newEntry"] and e["newEntry"]["name"] == "y.bin"
                for e in events
            )
            if not found:
                time.sleep(0.05)
        c.close()
        assert found
    finally:
        notifier.close()
        filer.close()
        broker.stop()


def test_benchmark_cli(cluster):
    from seaweedfs_tpu.benchmark.__main__ import main as bench_main

    assert bench_main(
        ["-master", f"localhost:{cluster}", "-n", "40", "-size", "500", "-c", "4"]
    ) == 0
