"""PR 4 device-queue scheduler tests: the shared per-chip priority
scheduler (ec/device_queue.py) multiplexing encode / degraded-read /
rebuild / scrub streams, plus the store-level shared interval cache.

Load-bearing properties:

- bit-identity: every stream's output through the queue equals the
  synchronous apply, on every backend family, under interleaving;
- fairness: a saturating recovery stream cannot starve foreground
  (bounded foreground wait), and foreground cannot starve recovery
  below its configured minimum share (no starvation either way);
- fault isolation: a mid-stream device death replays only the victim
  stream's in-flight batches on CPU; other streams keep the device
  until the shared breaker trips; a dying stream never leaks window
  slots;
- one byte budget: all EcVolumes of a Store share one interval cache
  with volume-namespaced invalidation.
"""

import os
import threading

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import (
    CpuBackend,
    ECContext,
    ECError,
    FallbackBackend,
    JaxBackend,
    ec_encode_volume,
)
from seaweedfs_tpu.ec.backend import _decode_coeffs
from seaweedfs_tpu.ec.device_queue import (
    DEFAULT_SHARES,
    DeviceQueue,
    configure,
    for_backend,
    stats_snapshot,
)
from seaweedfs_tpu.ec.pipeline import run_staged_apply
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils.retry import CircuitBreaker

CTX = ECContext(10, 4)
K = CTX.data_shards


def decode_coeffs(targets, src):
    rs = gf256.ReedSolomon(CTX.data_shards, CTX.parity_shards)
    return _decode_coeffs(rs.matrix, K, tuple(targets), tuple(src))


def make_backend(kind):
    if kind == "cpu":
        return CpuBackend(CTX)
    if kind == "xla":
        return JaxBackend(CTX, impl="xla", n_devices=1)
    if kind == "pallas_interpret":
        return JaxBackend(CTX, impl="pallas", interpret=True, n_devices=1)
    if kind == "mesh":
        return JaxBackend(CTX)  # conftest forces 8 virtual devices
    if kind == "fallback":
        return FallbackBackend(
            JaxBackend(CTX, impl="xla", n_devices=1), CpuBackend(CTX)
        )
    raise AssertionError(kind)


BACKENDS = ["cpu", "xla", "pallas_interpret", "mesh", "fallback"]


def staged_through_queue(be, queue, coeffs, data, priority, batch=4096):
    """Run `data` through run_staged_apply on `queue`; returns output."""
    total = data.shape[1]
    out = np.zeros((coeffs.shape[0], total), dtype=np.uint8)

    def produce():
        for off in range(0, total, batch):
            yield off, data[:, off : off + batch]

    def consume(off, rec):
        out[:, off : off + rec.shape[1]] = rec

    run_staged_apply(
        be, coeffs, produce, consume,
        priority=priority, device_queue=queue, describe="test stream",
    )
    return out


# --------------------------------------------------- queue bit-identity


@pytest.mark.parametrize("kind", BACKENDS)
def test_queue_staged_apply_bit_identical(kind):
    """The scheduler path must be byte-for-byte the synchronous apply on
    every backend family, ragged tail included (acceptance criterion:
    XLA, interpret-mode Pallas, mesh, CPU, fallback)."""
    be = make_backend(kind)
    cpu = CpuBackend(CTX)
    q = DeviceQueue()
    coeffs = decode_coeffs((0, 13), tuple(range(1, 11)))
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (K, 3 * 4096 + 1217), dtype=np.uint8)
    got = staged_through_queue(be, q, coeffs, data, "foreground")
    assert np.array_equal(got, cpu.apply(coeffs, data)), kind
    assert q.inflight == 0


def test_concurrent_streams_interleave_bit_exact():
    """Three classes on ONE queue and ONE backend, concurrently: every
    stream's output is bit-exact and delivered in its own order (the
    interleaving correctness the tentpole must hold)."""
    be = CpuBackend(CTX)
    q = DeviceQueue(window=2)
    rng = np.random.default_rng(12)
    jobs = {
        "foreground": decode_coeffs((0,), tuple(range(1, 11))),
        "recovery": decode_coeffs((13,), tuple(range(10))),
        "scrub": decode_coeffs((2, 12), tuple(i for i in range(14) if i not in (2, 12))[:K]),
    }
    datas = {
        cls: rng.integers(0, 256, (K, 64 * 1024 + 321), dtype=np.uint8)
        for cls in jobs
    }
    results: dict = {}
    errors: list = []

    def run(cls):
        try:
            results[cls] = staged_through_queue(
                be, q, jobs[cls], datas[cls], cls, batch=4096
            )
        except BaseException as e:  # pragma: no cover
            errors.append((cls, e))

    threads = [threading.Thread(target=run, args=(c,)) for c in jobs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for cls, coeffs in jobs.items():
        assert np.array_equal(results[cls], be.apply(coeffs, datas[cls])), cls
    st = q.stats()
    assert all(st[c]["admitted"] == st[c]["drained"] > 0 for c in jobs)
    assert q.inflight == 0


# -------------------------------------------------------- policy / fairness


def _drive(q, cls, n, order, hold=None):
    s = q.stream(cls)
    try:
        for i in range(n):
            t, _ = s.dispatch(lambda: None, 10_000)
            order.append(cls)
            if hold is not None:
                hold()
            s.release(t)
    finally:
        s.close()


@pytest.mark.chaos
def test_saturating_recovery_cannot_starve_foreground():
    """window=1 + a recovery stream that always has work queued: an
    arriving foreground batch is admitted within a bounded number of
    admissions (batch-granularity preemption — the recovery stream
    yields the H2D slot), and foreground p99 wait stays bounded by a
    couple of batch times, not the rebuild's remaining length."""
    q = DeviceQueue(window=1, shares={"recovery": 0.10})
    order: list = []
    stop = threading.Event()

    def recovery_forever():
        s = q.stream("recovery")
        try:
            while not stop.is_set():
                t, _ = s.dispatch(lambda: None, 10_000)
                order.append("recovery")
                stop.wait(0.001)  # drain latency holding the slot
                s.release(t)
        finally:
            s.close()

    rt = threading.Thread(target=recovery_forever)
    rt.start()
    try:
        # let the rebuild saturate the chip first
        while len(order) < 5:
            stop.wait(0.001)
        _drive(q, "foreground", 30, order, hold=lambda: stop.wait(0.001))
    finally:
        stop.set()
        rt.join(timeout=30)
    idx = [i for i, c in enumerate(order) if c == "foreground"]
    gaps = [b - a for a, b in zip(idx, idx[1:])]
    # between consecutive foreground admissions at most 1-2 recovery
    # batches squeeze in (the 10% minimum share) — never a long run
    assert max(gaps) <= 3, gaps
    st = q.stats()
    # bounded foreground wait: admission never waited for more than a
    # few held batches (each held ~1ms; a starved stream would show a
    # wait comparable to the whole recovery run)
    assert st["foreground"]["wait_s_max"] < 1.0, st["foreground"]
    # no starvation the other way: recovery kept making progress while
    # foreground was active (non-zero share)
    assert any(c == "recovery" for c in order[idx[0] : idx[-1]])
    assert q.inflight == 0


def _contended_run(q, fg_cls, bg_cls, fg_batches=30):
    """Saturate `bg_cls`, then drive `fg_batches` of `fg_cls` through
    the contended queue; returns the admission order inside the
    foreground span."""
    order: list = []
    stop = threading.Event()

    def background():
        s = q.stream(bg_cls)
        try:
            while not stop.is_set():
                t, _ = s.dispatch(lambda: None, 10_000)
                order.append(bg_cls)
                stop.wait(0.001)
                s.release(t)
        finally:
            s.close()

    bt = threading.Thread(target=background)
    bt.start()
    try:
        while len(order) < 5:  # background saturates first
            stop.wait(0.001)
        _drive(q, fg_cls, fg_batches, order, hold=lambda: stop.wait(0.001))
    finally:
        stop.set()
        bt.join(timeout=30)
    span = [i for i, c in enumerate(order) if c == fg_cls]
    return order[span[0] : span[-1] + 1]


def test_background_minimum_share_and_work_conservation():
    """With foreground saturating, recovery still gets roughly its
    configured share of admissions (non-zero, clear minority); with no
    foreground at all, recovery runs at full speed (work-conserving,
    no pacing)."""
    q = DeviceQueue(window=1, shares={"recovery": 0.2})
    span = _contended_run(q, "foreground", "recovery")
    rec_during = sum(1 for c in span if c == "recovery")
    # share 0.2 -> roughly 1 recovery per 4 foreground inside the
    # contended span; wide slack, but BOTH non-zero and a minority
    assert rec_during > 0
    assert rec_during <= len(span) * 0.5
    # work conservation: alone, recovery admits immediately
    order2: list = []
    _drive(q, "recovery", 10, order2)
    assert order2 == ["recovery"] * 10
    assert q.stats()["recovery"]["wait_s_max"] < 1.0


def test_scrub_yields_to_recovery_but_not_starved():
    q = DeviceQueue(window=1, shares={"recovery": 0.2, "scrub": 0.1})
    span = _contended_run(q, "recovery", "scrub")
    scrub_during = sum(1 for c in span if c == "scrub")
    assert scrub_during > 0  # minimum share held against recovery
    assert scrub_during < len(span) * 0.5


def test_configure_knobs_and_registry():
    """configure() flips the process-wide enable + shares; for_backend
    returns one queue per backend instance; stats_snapshot surfaces
    per-class counters (the /status payload)."""
    be = CpuBackend(CTX)
    try:
        cfg = configure(enabled=True, window=6, shares={"recovery": 0.3})
        assert cfg["window"] == 6 and cfg["shares"]["recovery"] == 0.3
        q = for_backend(be)
        assert q is not None and for_backend(be) is q
        assert q.window == 6 and q.shares["recovery"] == 0.3
        # a shares dict REPLACES the whole map: omitted classes return
        # to defaults (one caller's override never sticks to the next)
        cfg = configure(shares={})
        assert cfg["shares"] == DEFAULT_SHARES
        assert q.shares == DEFAULT_SHARES
        assert for_backend(None) is None
        configure(enabled=False)
        assert for_backend(be) is None
        configure(enabled=True)
        q2 = for_backend(be)
        assert q2 is not None
        snap = stats_snapshot()
        assert any(s["backend"] == "CpuBackend" for s in snap)
        with pytest.raises(ECError):
            q2.stream("urgent")
        with pytest.raises(ECError):
            configure(shares={"bogus": 0.5})
    finally:
        # restore process-wide defaults for the rest of the suite
        configure(enabled=True, window=4, shares=dict(DEFAULT_SHARES))


# ------------------------------------------------- fault isolation (chaos)


@pytest.mark.chaos
def test_mid_stream_device_death_replays_only_victim_batches():
    """Two streams on one FallbackBackend queue; two injected to_host
    faults: exactly the faulted batches replay on CPU (bit-identical),
    the breaker stays closed (below threshold), later batches keep the
    device, and no window slot leaks."""
    fb = FallbackBackend(
        JaxBackend(CTX, impl="xla", n_devices=1),
        CpuBackend(CTX),
        breaker=CircuitBreaker(failure_threshold=50, reset_timeout=9999.0),
    )
    cpu = CpuBackend(CTX)
    q = DeviceQueue(window=2)
    rng = np.random.default_rng(21)
    c_fg = decode_coeffs((0,), tuple(range(1, 11)))
    c_rec = decode_coeffs((13,), tuple(range(10)))
    d_fg = rng.integers(0, 256, (K, 16 * 4096), dtype=np.uint8)
    d_rec = rng.integers(0, 256, (K, 16 * 4096), dtype=np.uint8)
    results: dict = {}
    errors: list = []

    def run(cls, coeffs, data):
        try:
            results[cls] = staged_through_queue(fb, q, coeffs, data, cls)
        except BaseException as e:  # pragma: no cover
            errors.append((cls, e))

    with faults.injected(
        "ec.backend.device.to_host",
        faults.io_error("device lost mid-drain"),
        when=faults.every(3),
        count=2,
    ):
        ts = [
            threading.Thread(target=run, args=("foreground", c_fg, d_fg)),
            threading.Thread(target=run, args=("recovery", c_rec, d_rec)),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    assert not errors, errors
    # every byte of BOTH streams is bit-identical regardless of which
    # stream's batches the death hit (per-stream carried host copies)
    assert np.array_equal(results["foreground"], cpu.apply(c_fg, d_fg))
    assert np.array_equal(results["recovery"], cpu.apply(c_rec, d_rec))
    # only the in-flight faulted batches fell back; the device kept
    # serving everyone else (breaker never opened)
    assert fb.fallback_batches == 2
    assert fb.breaker.state == "closed"
    assert q.inflight == 0


@pytest.mark.chaos
def test_admission_timeout_fails_loudly_on_wedged_chip():
    """Slots held forever (a stream wedged in to_host against a hung
    device): another stream's admission must not freeze silently — past
    the admit deadline it raises ECError, the timed-out waiter leaves
    the queue, and the queue serves normally once the slot frees."""
    q = DeviceQueue(window=1, admit_timeout=0.2)
    hog = q.stream("recovery")
    ticket, _ = hog.dispatch(lambda: None, 1000)  # holds the only slot
    fg = q.stream("foreground")
    try:
        with pytest.raises(ECError, match="admission timed out"):
            fg.dispatch(lambda: None, 1000)
        assert q.stats()["foreground"]["depth"] == 0  # waiter removed
        hog.release(ticket)  # chip recovers -> service resumes
        t2, _ = fg.dispatch(lambda: None, 1000)
        fg.release(t2)
    finally:
        fg.close()
        hog.close()
    assert q.inflight == 0


@pytest.mark.chaos
def test_dying_stream_releases_slots_for_survivors():
    """A stream whose backend dies mid-pipeline (raw device error, no
    fallback) aborts alone: its window slots are released and another
    stream completes normally on the same queue afterwards."""

    class DyingBackend(CpuBackend):
        def __init__(self, ctx, die_after):
            super().__init__(ctx)
            self.calls = 0
            self.die_after = die_after

        def to_host(self, result):
            self.calls += 1
            if self.calls > self.die_after:
                raise OSError("device vanished")
            return super().to_host(result)

    q = DeviceQueue(window=2)
    dying = DyingBackend(CTX, die_after=2)
    healthy = CpuBackend(CTX)
    coeffs = decode_coeffs((1,), tuple(i for i in range(14) if i != 1)[:K])
    rng = np.random.default_rng(31)
    data = rng.integers(0, 256, (K, 12 * 4096), dtype=np.uint8)
    with pytest.raises(OSError):
        staged_through_queue(dying, q, coeffs, data, "recovery")
    assert q.inflight == 0, "dying stream leaked window slots"
    got = staged_through_queue(healthy, q, coeffs, data, "foreground")
    assert np.array_equal(got, healthy.apply(coeffs, data))
    assert q.inflight == 0


@pytest.mark.chaos
def test_queue_breaker_gating_preserved():
    """Every dispatch failing opens the breaker THROUGH the queue path;
    output stays bit-identical (CPU serves) — the PR 3 fail-closed
    semantics survive the scheduler."""
    fb = FallbackBackend(
        JaxBackend(CTX, impl="xla", n_devices=1),
        CpuBackend(CTX),
        breaker=CircuitBreaker(failure_threshold=3, reset_timeout=9999.0),
    )
    cpu = CpuBackend(CTX)
    q = DeviceQueue()
    coeffs = decode_coeffs((5,), tuple(i for i in range(14) if i != 5)[:K])
    data = np.random.default_rng(41).integers(
        0, 256, (K, 8 * 4096), dtype=np.uint8
    )
    with faults.injected(
        "ec.backend.device.apply_staged", faults.io_error("device dead")
    ):
        got = staged_through_queue(fb, q, coeffs, data, "recovery")
    assert np.array_equal(got, cpu.apply(coeffs, data))
    assert fb.breaker.state == "open"
    assert fb.fallback_batches >= 3


# ------------------------------------------- store-level shared cache


def make_ec_volume_files(tmp_path, vid, needles=16, seed=0):
    rng = np.random.default_rng(seed)
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, needles + 1):
        size = int(rng.integers(1, 40_000))
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x1000 + i, needle_id=i, data=data))
        payloads[i] = data
    v.close()
    base = Volume.base_file_name(str(tmp_path), "", vid)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    # degrade: lose shard 0 so reads reconstruct (and populate the cache)
    os.unlink(base + CTX.to_ext(0))
    os.unlink(base + ".dat")  # EC-only volume (store mounts the .ecx)
    os.unlink(base + ".idx")
    return base, payloads


def test_store_level_shared_interval_cache(tmp_path):
    """One byte budget across all EcVolumes: both volumes populate the
    SAME ChunkCache under volume-namespaced keys; invalidating one
    volume's shard keeps the other volume's extents; unmounting a
    volume frees only its own entries."""
    _, p1 = make_ec_volume_files(tmp_path, 1, seed=1)
    _, p2 = make_ec_volume_files(tmp_path, 2, seed=2)
    store = Store([str(tmp_path)], ec_backend="cpu")
    try:
        ev1 = store.find_ec_volume(1)
        ev2 = store.find_ec_volume(2)
        assert ev1 is not None and ev2 is not None
        assert ev1.interval_cache is store.ec_interval_cache
        assert ev2.interval_cache is ev1.interval_cache
        for i, data in p1.items():
            assert ev1.read_needle(i, cookie=0x1000 + i).data == data
        for i, data in p2.items():
            assert ev2.read_needle(i, cookie=0x1000 + i).data == data
        cache = store.ec_interval_cache
        keys = list(cache._data)
        assert any(k.startswith("1:") for k in keys)
        assert any(k.startswith("2:") for k in keys)
        assert cache.size_bytes <= cache.capacity
        # invalidate vol 1 shard 0: vol 2's extents survive
        v2_bytes = sum(
            len(v) for k, v in cache._data.items() if k.startswith("2:")
        )
        ev1.reopen_shards([0])
        assert not any(k.startswith("1:0:") for k in cache._data)
        assert sum(
            len(v) for k, v in cache._data.items() if k.startswith("2:")
        ) == v2_bytes
        # unmount vol 2: its namespace drains, budget freed, vol 1 reads
        # still serve (and re-populate under the shared budget)
        store.unmount_ec_volume(2)
        assert not any(k.startswith("2:") for k in cache._data)
        nid = next(iter(p1))
        assert ev1.read_needle(nid, cookie=0x1000 + nid).data == p1[nid]
    finally:
        store.close()


def test_store_cache_budget_zero_disables(tmp_path):
    make_ec_volume_files(tmp_path, 1, seed=3)
    store = Store([str(tmp_path)], ec_backend="cpu", ec_interval_cache_bytes=0)
    try:
        assert store.ec_interval_cache is None
        ev = store.find_ec_volume(1)
        assert ev is not None and ev.interval_cache is None
    finally:
        store.close()


def test_standalone_ec_volume_keeps_private_cache(tmp_path):
    """EcVolume constructed without a Store keeps its own budget (the
    embedded / test shape) — namespacing is harmless there."""
    from seaweedfs_tpu.ec import EcVolume

    _, payloads = make_ec_volume_files(tmp_path, 1, seed=4)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        assert ev._shared_cache is False
        nid = next(iter(payloads))
        assert ev.read_needle(nid, cookie=0x1000 + nid).data == payloads[nid]
        assert ev.interval_cache.size_bytes > 0
        assert all(k.startswith("1:") for k in ev.interval_cache._data)
    finally:
        ev.close()
