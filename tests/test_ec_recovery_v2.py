"""PR 2 recovery-path tests: .ecsum v2 (sub-block leaf CRCs), the
shared recovery pipeline, the reconstructed-interval cache, scrub
quarantine aging, and the unified retry helpers.

Scenario-dense like the reference's erasure_coding suites; chaos-marker
cases ride the deterministic fault registry from PR 1.
"""

import os

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import (
    BITROT_LEAF_SIZE,
    BitrotError,
    BitrotProtection,
    CpuBackend,
    ECContext,
    ECError,
    EcVolume,
    ShardChecksumBuilder,
    ec_encode_volume,
    fold_leaf_crcs,
    rebuild_ec_files,
    scrub_ec_volume,
    write_ec_files,
)
from seaweedfs_tpu.ec.pipeline import FusedShardSink, PyShardSink, run_pipeline
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils.crc import crc32c, crc32c_combine

CTX = ECContext(10, 4)


def make_volume(tmp_path, vid=1, needles=40, seed=0):
    rng = np.random.default_rng(seed)
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, needles + 1):
        size = int(rng.integers(1, 60_000))
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x1000 + i, needle_id=i, data=data))
        payloads[i] = data
    v.close()
    return Volume.base_file_name(str(tmp_path), "", vid), payloads


# ------------------------------------------------------------ crc combine


def test_crc32c_combine_matches_direct():
    rng = np.random.default_rng(7)
    for _ in range(20):
        a = rng.integers(0, 256, int(rng.integers(0, 50_000)), np.uint8).tobytes()
        b = rng.integers(0, 256, int(rng.integers(0, 50_000)), np.uint8).tobytes()
        assert crc32c(a + b) == crc32c_combine(crc32c(a), crc32c(b), len(b))


def test_fold_leaf_crcs_matches_block_crcs():
    rng = np.random.default_rng(8)
    data = rng.integers(0, 256, (1 << 20) + 12345, np.uint8).tobytes()
    bs, ls = 1 << 18, 1 << 14
    leaves = [crc32c(data[o : o + ls]) for o in range(0, len(data), ls)]
    blocks = [crc32c(data[o : o + bs]) for o in range(0, len(data), bs)]
    assert fold_leaf_crcs(leaves, len(data), ls, bs) == blocks


# ------------------------------------------------------- sidecar v1 <-> v2


def test_sidecar_v2_round_trip_and_v1_compat(tmp_path):
    base, _ = make_volume(tmp_path, needles=20)
    ec_encode_volume(base, CTX)  # default: v2 with leaves
    prot = BitrotProtection.load(base + ".ecsum")
    assert prot.has_leaves and prot.leaf_size == BITROT_LEAF_SIZE
    assert BitrotProtection.from_bytes(prot.to_bytes()) == prot
    # v2 header advertises format version 2
    raw = prot.to_bytes()
    assert raw[4:6] == (2).to_bytes(2, "little")

    # leaves are consistent with blocks (the fold identity) and with
    # the actual shard bytes
    for i in range(CTX.total):
        with open(base + CTX.to_ext(i), "rb") as f:
            sd = f.read()
        assert prot.shard_crcs[i] == [
            crc32c(sd[o : o + prot.block_size])
            for o in range(0, len(sd), prot.block_size)
        ]
        assert prot.shard_leaf_crcs[i] == [
            crc32c(sd[o : o + prot.leaf_size])
            for o in range(0, len(sd), prot.leaf_size)
        ]

    # a v1 sidecar (leaves stripped) still loads and verifies
    from dataclasses import replace

    v1 = replace(prot, leaf_size=0, shard_leaf_crcs=[])
    raw1 = v1.to_bytes()
    assert raw1[4:6] == (1).to_bytes(2, "little")
    back = BitrotProtection.from_bytes(raw1)
    assert not back.has_leaves
    assert back.shard_crcs == prot.shard_crcs


def test_sidecar_v2_corrupt_payload_fails_closed(tmp_path):
    base, _ = make_volume(tmp_path, needles=10)
    ec_encode_volume(base, CTX)
    with open(base + ".ecsum", "r+b") as f:
        f.seek(-3, os.SEEK_END)  # inside the v2 leaf tail
        f.write(b"\xff\xff\xff")
    with pytest.raises(BitrotError):
        BitrotProtection.load(base + ".ecsum")


def test_builders_and_fused_sink_agree(tmp_path):
    """The Python builder path and the fused native sink must produce
    identical v2 sidecars for identical bytes."""
    pytest.importorskip("seaweedfs_tpu.utils.native")
    rng = np.random.default_rng(9)
    rows = [
        rng.integers(0, 256, 300_000 + 17 * i, np.uint8) for i in range(4)
    ]
    bs, ls = 1 << 17, 1 << 14
    ctx = ECContext(2, 2)

    fused_files = [
        open(tmp_path / f"f{i}", "wb", buffering=0) for i in range(4)
    ]
    fused = FusedShardSink(fused_files, block_size=bs, leaf_size=ls)
    width = min(len(r) for r in rows)
    # equal-width batches (sinks require it); tail handled separately
    for off in range(0, width, 37_000):
        w = min(37_000, width - off)
        fused.append_rows([np.ascontiguousarray(r[off : off + w]) for r in rows])
    for f in fused_files:
        f.close()

    builders = [ShardChecksumBuilder(bs, ls) for _ in rows]
    for b, r in zip(builders, rows):
        b.write(r[:width].tobytes())
    p_fused = fused.to_protection(ctx)
    p_py = BitrotProtection.from_builders(ctx, builders)
    assert p_fused.shard_crcs == p_py.shard_crcs
    assert p_fused.shard_leaf_crcs == p_py.shard_leaf_crcs
    assert p_fused.shard_sizes == p_py.shard_sizes


# ------------------------------------------------- mixed-version recovery


@pytest.mark.parametrize("leaf_size", [0, BITROT_LEAF_SIZE])
def test_rebuild_bit_exact_under_both_sidecar_versions(tmp_path, leaf_size):
    base, _ = make_volume(tmp_path)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX), leaf_size=leaf_size)
    prot = BitrotProtection.load(base + ".ecsum")
    assert prot.has_leaves == (leaf_size > 0)
    originals = {}
    for i in (2, 11):
        with open(base + CTX.to_ext(i), "rb") as f:
            originals[i] = f.read()
        os.unlink(base + CTX.to_ext(i))
    assert rebuild_ec_files(base, backend=CpuBackend(CTX)) == [2, 11]
    for i in (2, 11):
        with open(base + CTX.to_ext(i), "rb") as f:
            assert f.read() == originals[i]


@pytest.mark.parametrize("leaf_size", [0, BITROT_LEAF_SIZE])
def test_scrub_healthy_under_both_sidecar_versions(tmp_path, leaf_size):
    base, _ = make_volume(tmp_path, needles=15)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX), leaf_size=leaf_size)
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX))
    assert r.healthy
    assert r.checked_shards == list(range(CTX.total))


def test_degraded_reads_verified_under_v1_and_v2(tmp_path):
    """Same shards, both sidecar versions: every degraded read is
    bit-exact, and the v2 leaf level reads far fewer sibling bytes."""
    base, payloads = make_volume(tmp_path)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    prot_v2 = BitrotProtection.load(base + ".ecsum")
    os.unlink(base + CTX.to_ext(0))

    def read_all(cache_bytes=0):
        ev = EcVolume(
            str(tmp_path), 1, backend_name="cpu",
            interval_cache_bytes=cache_bytes,
        )
        b0 = ev.bytes_read
        for i, data in payloads.items():
            assert ev.read_needle(i, cookie=0x1000 + i).data == data
        used = ev.bytes_read - b0
        ev.close()
        return used

    v2_bytes = read_all()
    from dataclasses import replace

    replace(prot_v2, leaf_size=0, shard_leaf_crcs=[]).save(base + ".ecsum")
    v1_bytes = read_all()
    prot_v2.save(base + ".ecsum")
    # leaf-granular recovery reads far fewer sibling bytes than
    # block-granular (the needles here are ~KBs vs 16 MiB blocks)
    assert v2_bytes * 4 < v1_bytes


def test_rebuild_reclassifies_on_disk_rot_in_source(tmp_path):
    """Fast-path inline source verification: a source shard rotten ON
    DISK is confirmed, excluded, regenerated — same end state as the
    old upfront verify-and-exclude."""
    base, _ = make_volume(tmp_path)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    with open(base + CTX.to_ext(3), "rb") as f:
        original3 = f.read()
    os.unlink(base + CTX.to_ext(13))  # one missing -> shard 3 is a source
    with open(base + CTX.to_ext(3), "r+b") as f:
        f.seek(4321)
        b = f.read(1)
        f.seek(4321)
        f.write(bytes([b[0] ^ 0x40]))
    assert not faults.active()  # fast path
    regenerated = rebuild_ec_files(base, backend=CpuBackend(CTX))
    assert regenerated == [3, 13]
    with open(base + CTX.to_ext(3), "rb") as f:
        assert f.read() == original3


# ------------------------------------------------------ interval cache


def degraded_volume(tmp_path, lost=0):
    base, payloads = make_volume(tmp_path)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    os.unlink(base + CTX.to_ext(lost))
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    return base, payloads, ev


def test_interval_cache_hit_on_repeat_reads(tmp_path):
    base, payloads, ev = degraded_volume(tmp_path)
    for i, data in payloads.items():
        assert ev.read_needle(i, cookie=0x1000 + i).data == data
    first_pass = ev.bytes_read
    h0 = ev.interval_cache.hits
    for i, data in payloads.items():
        assert ev.read_needle(i, cookie=0x1000 + i).data == data
    assert ev.interval_cache.hits > h0
    # repeats re-read only live-shard intervals, never re-reconstruct
    assert ev.bytes_read - first_pass < first_pass / 4
    ev.close()


def test_interval_cache_invalidated_on_remount_rebuild_delete(tmp_path):
    base, payloads, ev = degraded_volume(tmp_path)
    nid = next(iter(payloads))
    ev.read_needle(nid, cookie=0x1000 + nid)
    assert ev.interval_cache.size_bytes > 0

    # delete invalidates
    ev.delete_needle(max(payloads))
    assert ev.interval_cache.size_bytes == 0

    ev.read_needle(nid, cookie=0x1000 + nid)
    assert ev.interval_cache.size_bytes > 0
    # rebuild + remount invalidates (the daemon's on_rebuilt hook calls
    # reopen_shards; do the same here)
    rebuild_ec_files(base, backend=CpuBackend(CTX))
    ev.reopen_shards([0])
    assert ev.interval_cache.size_bytes == 0
    # ...and the restored shard now serves directly: no new cache fill
    b0 = ev.bytes_read
    assert ev.read_needle(nid, cookie=0x1000 + nid).data == payloads[nid]
    assert ev.interval_cache.size_bytes == 0

    # unmount invalidates too
    ev.read_needle(nid, cookie=0x1000 + nid)
    ev.unmount_shards([0])
    assert ev.interval_cache.size_bytes == 0
    ev.close()


def test_interval_cache_disabled(tmp_path):
    base, payloads, _ = degraded_volume(tmp_path)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu", interval_cache_bytes=0)
    assert ev.interval_cache is None
    nid = next(iter(payloads))
    assert ev.read_needle(nid, cookie=0x1000 + nid).data == payloads[nid]
    ev.close()


@pytest.mark.chaos
def test_cached_degraded_reads_survive_live_shard_rot(tmp_path):
    """Chaos: prime the cache on a lost shard, then arm bit-flips on
    every direct shard read. Repeats must still come back bit-exact —
    lost-shard extents from the (verified) cache, rotten live-shard
    reads self-healed through verified reconstruction."""
    base, payloads, ev = degraded_volume(tmp_path)
    ids = list(payloads)[:6]
    for i in ids:
        assert ev.read_needle(i, cookie=0x1000 + i).data == payloads[i]
    h0 = ev.interval_cache.hits
    with faults.injected(
        "ec.volume.shard_read", faults.bit_flip(seed=3), mutates=True
    ):
        for i in ids:
            assert ev.read_needle(i, cookie=0x1000 + i).data == payloads[i]
    assert ev.interval_cache.hits > h0
    ev.close()


@pytest.mark.chaos
def test_cache_invalidation_then_chaos_reread_is_bit_exact(tmp_path):
    """Chaos: invalidate the cache mid-storm; the re-reconstruction
    excludes the rotten sibling (sidecar-verified sources) and still
    serves bit-exact."""
    base, payloads, ev = degraded_volume(tmp_path)
    nid = next(iter(payloads))
    assert ev.read_needle(nid, cookie=0x1000 + nid).data == payloads[nid]
    ev._drop_interval_cache()
    with faults.injected(
        "ec.volume.shard_read",
        faults.bit_flip(seed=5),
        when=faults.every(2),
        mutates=True,
    ):
        for _ in range(4):
            assert (
                ev.read_needle(nid, cookie=0x1000 + nid).data == payloads[nid]
            )
    ev.close()


# ----------------------------------------------------- scrub .bad aging


def _corrupt_shard(base, sid, at=2048):
    with open(base + CTX.to_ext(sid), "r+b") as f:
        f.seek(at)
        b = f.read(1)
        f.seek(at)
        f.write(bytes([b[0] ^ 0x80]))


def test_scrub_ages_out_bad_after_verified_replacement(tmp_path):
    base, _ = make_volume(tmp_path, needles=15)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    # SIZE rot: a truncated shard cannot be leaf-repaired in place, so
    # this still mints the .bad quarantine whose aging is under test
    path4 = base + CTX.to_ext(4)
    os.truncate(path4, os.path.getsize(path4) - 64)
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=True)
    bad_path = base + CTX.to_ext(4) + ".bad"
    assert r.rebuilt == [4] and os.path.exists(bad_path)

    # default: kept forever
    r2 = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX))
    assert r2.healthy and os.path.exists(bad_path) and not r2.aged_out

    # long retention: still kept
    r3 = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), bad_retention_s=3600.0
    )
    assert os.path.exists(bad_path) and not r3.aged_out

    # expired retention: retired, because the replacement verified
    r4 = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), bad_retention_s=0.0
    )
    assert r4.aged_out == [bad_path]
    assert not os.path.exists(bad_path)


def test_scrub_never_ages_bad_without_verified_replacement(tmp_path):
    base, _ = make_volume(tmp_path, needles=15)
    ec_encode_volume(base, CTX, backend=CpuBackend(CTX))
    _corrupt_shard(base, 4)
    # quarantine WITHOUT repair: no verified replacement exists
    r = scrub_ec_volume(base, CTX, backend=CpuBackend(CTX), repair=False)
    bad_path = base + CTX.to_ext(4) + ".bad"
    assert os.path.exists(bad_path) and not r.rebuilt
    r2 = scrub_ec_volume(
        base, CTX, backend=CpuBackend(CTX), repair=False, bad_retention_s=0.0
    )
    # shard 4 is missing (quarantined), not verified: .bad survives
    assert 4 in r2.missing_shards
    assert os.path.exists(bad_path) and not r2.aged_out


# --------------------------------------------- checked_shards proto field


def test_scrub_response_checked_shards_round_trip():
    from seaweedfs_tpu.pb import cluster_pb2 as pb

    m = pb.ScrubResponse(checked=3, bad_shards=[2], checked_shards=[0, 2, 9])
    back = pb.ScrubResponse.FromString(m.SerializeToString())
    assert list(back.checked_shards) == [0, 2, 9]
    # old writers (no field) still parse: absent = empty
    old = pb.ScrubResponse(checked=1).SerializeToString()
    assert list(pb.ScrubResponse.FromString(old).checked_shards) == []


# ------------------------------------------------------- shared pipeline


def test_run_pipeline_orders_and_propagates():
    seen = []
    run_pipeline(
        lambda: iter(range(50)),
        lambda x: x * 2,
        seen.append,
    )
    assert seen == [x * 2 for x in range(50)]

    with pytest.raises(RuntimeError, match="boom"):
        def produce():
            yield 1
            raise RuntimeError("boom")

        run_pipeline(produce, lambda x: x, lambda x: None)

    with pytest.raises(RuntimeError, match="sink"):
        def bad_sink(_):
            raise RuntimeError("sink")

        run_pipeline(lambda: iter(range(10)), lambda x: x, bad_sink)


def test_py_shard_sink_accepts_bytes_and_arrays(tmp_path):
    files = [open(tmp_path / f"s{i}", "wb") for i in range(2)]
    sink = PyShardSink(files, block_size=1 << 16)
    sink.append_rows([b"abc", np.frombuffer(b"xyz", dtype=np.uint8)])
    for f in files:
        f.close()
    assert open(tmp_path / "s0", "rb").read() == b"abc"
    assert open(tmp_path / "s1", "rb").read() == b"xyz"
    assert sink.sizes == [3, 3]


# ------------------------------------------------------------ retry bits


def test_backoff_follows_policy_and_resets():
    from seaweedfs_tpu.utils.retry import Backoff, RetryPolicy

    import random

    policy = RetryPolicy(
        max_attempts=3, base_delay=1.0, multiplier=2.0, max_delay=10.0,
        jitter=0.0,
    )
    b = Backoff(policy, rng=random.Random(0))
    assert b.next_delay() == 1.0
    assert b.next_delay() == 2.0
    assert b.next_delay() == 4.0
    assert b.next_delay() == 4.0  # saturates at the policy tail
    b.reset()
    assert b.next_delay() == 1.0


def test_s3_client_retries_transient_then_gives_up(monkeypatch):
    import requests as _requests

    from seaweedfs_tpu.remote.s3_client import (
        RemoteS3Client,
        RemoteStorageError,
        TransientRemoteError,
    )
    from seaweedfs_tpu.utils.retry import RetryPolicy

    calls = {"n": 0}

    class FakeResp:
        def __init__(self, code):
            self.status_code = code
            self.text = "err"
            self.headers = {}
            self.content = b""

    client = RemoteS3Client(
        "http://example.invalid",
        retry_policy=RetryPolicy(
            max_attempts=3, base_delay=0.0, max_delay=0.0, jitter=0.0,
            retry_on=(TransientRemoteError, _requests.ConnectionError),
        ),
    )

    def fake_request(method, url, **kw):
        calls["n"] += 1
        return FakeResp(500 if calls["n"] < 3 else 200)

    monkeypatch.setattr(client._http, "request", fake_request)
    r = client._request("GET", "/bucket/key")
    assert r.status_code == 200 and calls["n"] == 3

    # permanent 4xx: no retry
    calls["n"] = 0

    def fake_403(method, url, **kw):
        calls["n"] += 1
        return FakeResp(403)

    monkeypatch.setattr(client._http, "request", fake_403)
    with pytest.raises(RemoteStorageError):
        client._request("GET", "/bucket/key")
    assert calls["n"] == 1
