"""Mount P2P chunk-cache sharing (reference weed/mount/peer_hrw.go +
pb/mount_peer.proto): two mounts over one filer route chunk fetches to
their HRW owner's cache, measurably reducing volume-server reads.

The FilerMount objects are driven directly (no kernel FUSE needed —
the P2P path lives in _read_range, below the FUSE layer)."""

from __future__ import annotations

import time

import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.mount.peer_cache import hrw_owner
from seaweedfs_tpu.mount.weed_mount import FilerMount
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture
def stack(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    deadline = time.time() + 10
    while not master.topo.nodes:
        assert time.time() < deadline
        time.sleep(0.05)
    filer = Filer(
        MemoryStore(), master=f"localhost:{mport}", chunk_size=64 * 1024
    )
    fport = free_port()
    fsrv = FilerServer(
        filer, ip="localhost", port=fport, grpc_port=fport + 10000
    )
    fsrv.start()
    yield filer, fsrv
    fsrv.stop()
    filer.close()
    vs.stop()
    master.stop()


def test_hrw_owner_is_stable_and_balanced():
    peers = ["m-a", "m-b", "m-c"]
    fids = [f"3,{i:x}00deadbeef" for i in range(300)]
    owners = [hrw_owner(f, peers) for f in fids]
    assert owners == [hrw_owner(f, list(reversed(peers))) for f in fids]
    per = {p: owners.count(p) for p in peers}
    assert all(40 <= n <= 160 for n in per.values()), per


def test_two_mounts_share_chunk_fetches(stack):
    filer, fsrv = stack
    # 8 chunks of 64 KiB
    data = bytes(range(256)) * 2048  # 512 KiB
    filer.write_file("/p2p/big.bin", data, inline=False)

    a = FilerMount(f"localhost:{fsrv.port}", peer_cache=True)
    b = FilerMount(f"localhost:{fsrv.port}", peer_cache=True)
    try:
        # both mounts see each other's announcements
        deadline = time.time() + 10
        while len(a.peer.peers()) < 2 or len(b.peer.peers()) < 2:
            assert time.time() < deadline, (a.peer.peers(), b.peer.peers())
            time.sleep(0.2)

        got = a._read_range("/p2p/big.bin", 0, len(data))
        assert got == data
        n_chunks = 8
        a_fetches = a.peer.stats.get("volume_fetches", 0)
        assert a_fetches == n_chunks  # cold cluster: all from volume tier

        got = b._read_range("/p2p/big.bin", 0, len(data))
        assert got == data
        b_stats = b.peer.stats
        # B pulled the A-owned chunks from A's cache, not the volume tier
        assert b_stats["peer_hits"] > 0, b_stats
        assert b_stats.get("volume_fetches", 0) < n_chunks, b_stats
        total_volume_reads = a_fetches + b_stats.get("volume_fetches", 0)
        assert total_volume_reads < 2 * n_chunks  # the P2P win, measured
        assert a.peer.stats["served"] == b_stats["peer_hits"]

        # a re-read on B is now fully local: zero new fetches anywhere
        before = (
            b_stats.get("volume_fetches", 0),
            b_stats["peer_hits"],
        )
        assert b._read_range("/p2p/big.bin", 0, len(data)) == data
        assert (
            b_stats.get("volume_fetches", 0),
            b_stats["peer_hits"],
        ) == before

        # partial range reads assemble correctly through the cache
        assert (
            a._read_range("/p2p/big.bin", 100_000, 50_000)
            == data[100_000:150_000]
        )
        # reads past EOF come back short, like the filer path
        tail = b._read_range("/p2p/big.bin", len(data) - 10, 100)
        assert tail == data[-10:]
    finally:
        a.peer.close()
        b.peer.close()


def test_peer_loss_falls_through_to_volume(stack):
    filer, fsrv = stack
    data = b"x" * (3 * 64 * 1024)
    filer.write_file("/p2p/f2.bin", data, inline=False)
    a = FilerMount(f"localhost:{fsrv.port}", peer_cache=True)
    b = FilerMount(f"localhost:{fsrv.port}", peer_cache=True)
    try:
        deadline = time.time() + 10
        while len(b.peer.peers()) < 2:
            assert time.time() < deadline
            time.sleep(0.2)
        a.peer.close()  # peer dies without un-announcing
        got = b._read_range("/p2p/f2.bin", 0, len(data))
        assert got == data  # dead-peer timeouts fall through, no EIO
    finally:
        b.peer.close()
