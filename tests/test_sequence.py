"""Sequencer tests: snowflake uniqueness across threads and restarts
(reference weed/sequence)."""

import threading
import time

from seaweedfs_tpu.utils.sequence import CounterSequencer, SnowflakeSequencer


def test_snowflake_unique_under_concurrency():
    s = SnowflakeSequencer(node_id=1)
    ids = set()
    lock = threading.Lock()

    def gen():
        local = [s.next_id() for _ in range(5000)]
        with lock:
            ids.update(local)

    ts = [threading.Thread(target=gen) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(ids) == 20000


def test_snowflake_restart_disjoint():
    """A master restart must not reuse ids — reuse overwrites blobs."""
    s = SnowflakeSequencer(node_id=1)
    before = {s.next_id() for _ in range(2000)}
    time.sleep(0.05)  # a real restart takes far longer than spin-ahead
    s2 = SnowflakeSequencer(node_id=1)
    after = {s2.next_id() for _ in range(2000)}
    assert not (before & after)


def test_snowflake_node_disjoint():
    a = SnowflakeSequencer(node_id=1)
    b = SnowflakeSequencer(node_id=2)
    assert not (
        {a.next_id() for _ in range(2000)} & {b.next_id() for _ in range(2000)}
    )


def test_snowflake_monotonic():
    s = SnowflakeSequencer()
    prev = 0
    for _ in range(10000):
        n = s.next_id()
        assert n > prev
        prev = n


def test_counter_sequencer():
    c = CounterSequencer()
    assert [c.next_id() for _ in range(3)] == [1, 2, 3]
