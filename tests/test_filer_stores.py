"""FilerStore matrix: every backend passes the same behavioral suite.

Reference: weed/filer has ~24 stores behind one SPI
(filerstore.go); the suite here is what keeps this repo's SPI honest
across backends — memory, sqlite-on-abstract-sql (qmark), a second
abstract-sql dialect (named paramstyle, different SQL text), and the
embedded SSTable+WAL engine in two configurations (normal and
tiny-memtable, which forces segment flushes + compaction mid-test).
"""

from __future__ import annotations

import sqlite3

import pytest

from seaweedfs_tpu.filer import (
    AbstractSqlStore,
    MemoryStore,
    NotFound,
    SqlDialect,
    SqliteStore,
    SSTableStore,
    new_entry,
)
from seaweedfs_tpu.filer.sstable_store import _Segment


def _named_sqlite(p):
    """AbstractSqlStore proof that a second dialect drops in: named
    paramstyle generates different SQL text against the same driver."""
    path = str(p / "named.db")
    return AbstractSqlStore(
        lambda: sqlite3.connect(path, timeout=30),
        dialect=SqlDialect(paramstyle="named"),
    )


STORES = [
    pytest.param(lambda p: MemoryStore(), id="memory"),
    pytest.param(lambda p: SqliteStore(str(p / "f.db")), id="sqlite"),
    pytest.param(_named_sqlite, id="abstract-sql-named"),
    pytest.param(lambda p: SSTableStore(str(p / "sst")), id="sstable"),
    pytest.param(
        lambda p: SSTableStore(
            str(p / "sst-tiny"), memtable_limit=256, compact_at=3
        ),
        id="sstable-tiny",
    ),
]


@pytest.mark.parametrize("mk", STORES)
def test_crud_listing_matrix(tmp_path, mk):
    st = mk(tmp_path)
    for name in ("b", "a", "c", "sub"):
        st.insert(new_entry(f"/dir/{name}", is_directory=(name == "sub")))
    assert st.find("/dir", "a").name == "a"
    assert [e.name for e in st.list("/dir")] == ["a", "b", "c", "sub"]
    assert [e.name for e in st.list("/dir", start_from="a", limit=2)] == [
        "b", "c",
    ]
    assert [e.name for e in st.list("/dir", prefix="s")] == ["sub"]
    st.delete("/dir", "b")
    with pytest.raises(NotFound):
        st.find("/dir", "b")
    st.close()


@pytest.mark.parametrize("mk", STORES)
def test_overwrite_and_kv_matrix(tmp_path, mk):
    st = mk(tmp_path)
    e = new_entry("/d/f")
    st.insert(e)
    e2 = new_entry("/d/f", mime="text/x-new")
    st.update(e2)
    assert st.find("/d", "f").attr.mime == "text/x-new"
    st.kv_put(b"k1", b"v1")
    st.kv_put(b"k1", b"v2")
    assert st.kv_get(b"k1") == b"v2"
    st.kv_delete(b"k1")
    assert st.kv_get(b"k1") is None
    assert st.kv_put_if_absent(b"k2", b"first") == b"first"
    assert st.kv_put_if_absent(b"k2", b"second") == b"first"
    st.close()


@pytest.mark.parametrize("mk", STORES)
def test_delete_folder_children_matrix(tmp_path, mk):
    st = mk(tmp_path)
    for path in (
        "/a/x", "/a/y", "/a/sub/one", "/a/sub/deep/two", "/ab/keep", "/b/z",
    ):
        st.insert(new_entry(path))
    st.delete_folder_children("/a")
    for d, n in (("/a", "x"), ("/a/sub", "one"), ("/a/sub/deep", "two")):
        with pytest.raises(NotFound):
            st.find(d, n)
    # /ab is NOT under /a (string-prefix trap)
    assert st.find("/ab", "keep").name == "keep"
    assert st.find("/b", "z").name == "z"
    st.close()


@pytest.mark.parametrize("mk", STORES)
def test_many_entries_pagination_matrix(tmp_path, mk):
    st = mk(tmp_path)
    names = [f"f{i:04d}" for i in range(300)]
    for n in names:
        st.insert(new_entry(f"/big/{n}"))
    got, last = [], ""
    while True:
        page = [e.name for e in st.list("/big", start_from=last, limit=64)]
        if not page:
            break
        got += page
        last = page[-1]
    assert got == names
    st.close()


# -------------------------------------------------- persistence / reopen


@pytest.mark.parametrize(
    "mk",
    [
        pytest.param(lambda p: SqliteStore(str(p / "f.db")), id="sqlite"),
        pytest.param(lambda p: SSTableStore(str(p / "sst")), id="sstable"),
        pytest.param(
            lambda p: SSTableStore(
                str(p / "sst-tiny"), memtable_limit=256, compact_at=3
            ),
            id="sstable-tiny",
        ),
    ],
)
def test_reopen_persists_matrix(tmp_path, mk):
    st = mk(tmp_path)
    for i in range(50):
        st.insert(new_entry(f"/p/e{i:03d}"))
    st.delete("/p", "e007")
    st.kv_put(b"key", b"val")
    st.close()

    st = mk(tmp_path)
    assert len(list(st.list("/p", limit=100))) == 49
    with pytest.raises(NotFound):
        st.find("/p", "e007")
    assert st.kv_get(b"key") == b"val"
    st.close()


# --------------------------------------------------- sstable internals


def test_sstable_wal_replay_without_close(tmp_path):
    """SIGKILL model: writes journaled to the WAL but never flushed to
    a segment must survive a dirty reopen."""
    st = SSTableStore(str(tmp_path / "s"))
    st.insert(new_entry("/w/a"))
    st.kv_put(b"k", b"v")
    # simulate a crash: drop the object without close()/flush()
    st._wal.close()
    st2 = SSTableStore(str(tmp_path / "s"))
    assert st2.find("/w", "a").name == "a"
    assert st2.kv_get(b"k") == b"v"
    st2.close()


def test_sstable_torn_wal_tail(tmp_path):
    st = SSTableStore(str(tmp_path / "s"))
    st.insert(new_entry("/w/a"))
    st.insert(new_entry("/w/b"))
    st._wal.close()
    # corrupt the tail: truncate mid-record
    wal = str(tmp_path / "s" / "wal.log")
    import os

    sz = os.path.getsize(wal)
    with open(wal, "r+b") as f:
        f.truncate(sz - 3)
    st2 = SSTableStore(str(tmp_path / "s"))
    assert st2.find("/w", "a").name == "a"  # intact prefix replayed
    with pytest.raises(NotFound):
        st2.find("/w", "b")  # torn record dropped, not garbage
    st2.close()


def test_sstable_compaction_drops_tombstones(tmp_path):
    st = SSTableStore(str(tmp_path / "s"), memtable_limit=128, compact_at=2)
    for i in range(40):
        st.insert(new_entry(f"/c/e{i:02d}"))
    for i in range(0, 40, 2):
        st.delete("/c", f"e{i:02d}")
    st.flush()
    # force compaction to a single segment
    while len(st._segments) > 1:
        st._compact_locked()
    names = [e.name for e in st.list("/c", limit=100)]
    assert names == [f"e{i:02d}" for i in range(1, 40, 2)]
    # deleted keys are truly gone from the merged segment, not masked
    seg: _Segment = st._segments[0]
    keys = [k for k, v in seg.items()]
    assert all(b"e00" not in k for k in keys)
    assert all(v is not None for _k, v in seg.items())
    st.close()


def test_sstable_newest_layer_wins(tmp_path):
    st = SSTableStore(str(tmp_path / "s"), memtable_limit=64, compact_at=99)
    st.insert(new_entry("/n/f", mime="v1"))
    st.flush()
    st.insert(new_entry("/n/f", mime="v2"))
    st.flush()
    st.insert(new_entry("/n/f", mime="v3"))  # memtable only
    assert st.find("/n", "f").attr.mime == "v3"
    assert len(st._segments) >= 2
    assert [e.attr.mime for e in st.list("/n")] == ["v3"]
    st.close()


def test_sstable_writes_after_torn_tail_survive_second_reopen(tmp_path):
    """Review r5: the torn record must be truncated at replay —
    otherwise post-crash writes append BEHIND it and are acked but
    unreachable on the reopen after next."""
    import os

    st = SSTableStore(str(tmp_path / "s"))
    st.insert(new_entry("/w/a"))
    st._wal.close()
    wal = str(tmp_path / "s" / "wal.log")
    with open(wal, "r+b") as f:
        f.truncate(os.path.getsize(wal) - 3)  # torn tail
    st2 = SSTableStore(str(tmp_path / "s"))
    st2.insert(new_entry("/w/post-crash"))  # acked after dirty reopen
    st2._wal.close()  # crash again without flush
    st3 = SSTableStore(str(tmp_path / "s"))
    assert st3.find("/w", "post-crash").name == "post-crash"
    st3.close()


def test_tombstone_flag_disambiguates_empty_put(tmp_path):
    """An empty-body put with cookie 0 is NOT a delete: only records
    carrying the 0x40 tombstone flag are (review r5)."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(str(tmp_path), 31)
    v.write_needle(Needle(cookie=0, needle_id=5, data=b""))  # legit empty put
    v.write_needle(Needle(cookie=1, needle_id=6, data=b"x"))
    v.delete_needle(6)
    recs = list(v.scan_raw_since(0))
    flags = {n.needle_id: n.is_tombstone for n, _, _ in recs}
    assert flags[5] is False
    assert any(n.is_tombstone and n.needle_id == 6 for n, _, _ in recs)
    v.close()
