"""Filer tests: store backends, chunk interval resolution, namespace ops,
and the chunked write/read path against a live in-process cluster.

Reference models: weed/filer/filechunks_test.go (overlap resolution),
filer store suites, filer_server handler tests.
"""

import time

import pytest
import requests

from seaweedfs_tpu.filer import (
    Entry,
    Filer,
    FilerError,
    MemoryStore,
    NotFound,
    SqliteStore,
    new_entry,
    read_chunk_views,
    visible_intervals,
)
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


from conftest import allocate_port as free_port


# ------------------------------------------------------------------ stores


@pytest.mark.parametrize("mk", [lambda p: MemoryStore(), lambda p: SqliteStore(str(p / "f.db"))])
def test_store_crud_and_listing(tmp_path, mk):
    st = mk(tmp_path)
    for name in ("b", "a", "c", "sub"):
        e = new_entry(f"/dir/{name}", is_directory=(name == "sub"))
        st.insert(e)
    assert st.find("/dir", "a").name == "a"
    names = [e.name for e in st.list("/dir")]
    assert names == ["a", "b", "c", "sub"]
    # pagination
    names = [e.name for e in st.list("/dir", start_from="a", limit=2)]
    assert names == ["b", "c"]
    # prefix
    names = [e.name for e in st.list("/dir", prefix="s")]
    assert names == ["sub"]
    st.delete("/dir", "b")
    with pytest.raises(NotFound):
        st.find("/dir", "b")
    # kv
    st.kv_put(b"k1", b"v1")
    assert st.kv_get(b"k1") == b"v1"
    assert st.kv_get(b"nope") is None
    st.close()


def test_entry_codec_roundtrip():
    e = new_entry("/a/b/file.txt", mime="text/plain")
    e.chunks.append(fpb.FileChunk(fid="3,1ab", offset=0, size=100, modified_ts_ns=5))
    e.extended["x-test"] = b"yes"
    raw = e.to_bytes()
    back = Entry.from_bytes("/a/b", raw)
    assert back.full_path == "/a/b/file.txt"
    assert back.chunks[0].fid == "3,1ab"
    assert back.extended["x-test"] == b"yes"
    assert back.attr.mime == "text/plain"


# ------------------------------------------------------------------ chunks


def _chunk(fid, offset, size, ts):
    return fpb.FileChunk(fid=fid, offset=offset, size=size, modified_ts_ns=ts)


def test_visible_intervals_overlap_resolution():
    # later write wins over the overlapped region
    chunks = [
        _chunk("a", 0, 100, ts=1),
        _chunk("b", 50, 100, ts=2),
    ]
    iv = visible_intervals(chunks)
    assert [(s, e, c.fid) for s, e, c in iv] == [(0, 50, "a"), (50, 150, "b")]
    # reversed times: the earlier-offset chunk is newer
    chunks = [
        _chunk("a", 0, 100, ts=2),
        _chunk("b", 50, 100, ts=1),
    ]
    iv = visible_intervals(chunks)
    assert [(s, e, c.fid) for s, e, c in iv] == [(0, 100, "a"), (100, 150, "b")]
    # full overwrite hides the old chunk
    chunks = [
        _chunk("a", 10, 20, ts=1),
        _chunk("b", 0, 100, ts=2),
    ]
    iv = visible_intervals(chunks)
    assert [(s, e, c.fid) for s, e, c in iv] == [(0, 100, "b")]
    # middle overwrite splits the old chunk
    chunks = [
        _chunk("a", 0, 100, ts=1),
        _chunk("b", 40, 20, ts=2),
    ]
    iv = visible_intervals(chunks)
    assert [(s, e, c.fid) for s, e, c in iv] == [
        (0, 40, "a"),
        (40, 60, "b"),
        (60, 100, "a"),
    ]


def test_read_chunk_views_clipping():
    chunks = [_chunk("a", 0, 100, 1), _chunk("b", 100, 100, 1)]
    views = read_chunk_views(chunks, 90, 20)
    assert [(v.fid, v.offset_in_chunk, v.size, v.logical_offset) for v in views] == [
        ("a", 90, 10, 90),
        ("b", 0, 10, 100),
    ]


# ------------------------------------------------------- cluster-backed


@pytest.fixture
def cluster(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    deadline = time.time() + 10
    while not master.topo.nodes:
        assert time.time() < deadline
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


def test_filer_write_read_chunked(cluster, tmp_path):
    f = Filer(
        MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024
    )
    try:
        import numpy as np

        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, 300_000, np.uint8).tobytes()  # 5 chunks
        entry = f.write_file("/docs/report.bin", data, mime="application/x-bin")
        assert len(entry.chunks) == 5
        assert f.read_file("/docs/report.bin") == data
        # ranged reads across chunk boundaries
        assert f.read_file("/docs/report.bin", 60_000, 10_000) == data[60_000:70_000]
        assert f.read_file("/docs/report.bin", 299_000, 5_000) == data[299_000:]
        # parents auto-created
        assert f.find_entry("/docs").is_directory
        # overwrite GCs old chunks
        old_fids = [c.fid for c in entry.chunks]
        f.write_file("/docs/report.bin", b"tiny")
        assert f.read_file("/docs/report.bin") == b"tiny"
        f.flush_gc()
        time.sleep(0.3)
        for fid in old_fids:
            with pytest.raises(LookupError):
                f.ops.read(fid)
        # rename
        f.rename("/docs/report.bin", "/archive/2026/report.bin")
        assert f.read_file("/archive/2026/report.bin") == b"tiny"
        assert not f.exists("/docs/report.bin")
        # delete dir recursively
        f.delete_entry("/archive", recursive=True)
        assert not f.exists("/archive/2026/report.bin")
        with pytest.raises(FilerError):
            f.create_entry(new_entry("/docs", is_directory=False))
    finally:
        f.close()


def test_small_content_inlining(cluster):
    f = Filer(MemoryStore(), master=f"localhost:{cluster}")
    try:
        e = f.write_file("/tiny/note.txt", b"inline me", mime="text/plain")
        assert e.content == b"inline me" and not e.chunks
        assert f.read_file("/tiny/note.txt") == b"inline me"
        assert f.read_file("/tiny/note.txt", 2, 4) == b"line"
        # growing past the limit switches to chunks
        big = b"B" * 10_000
        e2 = f.write_file("/tiny/note.txt", big)
        assert e2.chunks and not e2.content
        assert f.read_file("/tiny/note.txt") == big
        # shrinking back inlines again and GCs the chunks
        old_fids = [c.fid for c in e2.chunks]
        f.write_file("/tiny/note.txt", b"small again")
        assert f.read_file("/tiny/note.txt") == b"small again"
        f.flush_gc()
        import time as _t

        _t.sleep(0.3)
        import pytest as _pytest

        for fid in old_fids:
            with _pytest.raises(LookupError):
                f.ops.read(fid)
    finally:
        f.close()


@pytest.mark.parametrize(
    "store_mk",
    [
        pytest.param(
            lambda p: SqliteStore(str(p / "fdb" / "filer.db")), id="sqlite"
        ),
        pytest.param(
            lambda p: __import__(
                "seaweedfs_tpu.filer.sstable_store", fromlist=["SSTableStore"]
            ).SSTableStore(str(p / "fdb" / "filer.sst")),
            id="sstable",
        ),
    ],
)
def test_filer_http_server(cluster, tmp_path, store_mk):
    fport = free_port()
    f = Filer(
        store_mk(tmp_path),
        master=f"localhost:{cluster}",
        chunk_size=32 * 1024,
    )
    srv = FilerServer(f, ip="localhost", port=fport)
    srv.start()
    base = f"http://localhost:{fport}"
    try:
        data = b"filer http payload " * 5000  # ~95KB -> 3 chunks
        r = requests.post(f"{base}/media/x/y/file.txt", files={"file": ("file.txt", data, "text/plain")})
        assert r.status_code == 201, r.text
        r = requests.get(f"{base}/media/x/y/file.txt")
        assert r.content == data and r.headers["Content-Type"] == "text/plain"
        # range
        r = requests.get(
            f"{base}/media/x/y/file.txt", headers={"Range": "bytes=10-29"}
        )
        assert r.status_code == 206 and r.content == data[10:30]
        # listing
        r = requests.get(f"{base}/media/x/y")
        assert r.json()["Entries"][0]["FullPath"] == "/media/x/y/file.txt"
        # rename via mv.from
        r = requests.post(f"{base}/media/renamed.txt?mv.from=/media/x/y/file.txt")
        assert r.status_code == 200
        assert requests.get(f"{base}/media/renamed.txt").content == data
        assert requests.get(f"{base}/media/x/y/file.txt").status_code == 404
        # HEAD serves metadata without touching the data plane
        r = requests.head(f"{base}/media/renamed.txt")
        assert r.status_code == 200
        assert int(r.headers["Content-Length"]) == len(data)
        # malformed Range degrades to full content; out-of-range -> 416
        r = requests.get(
            f"{base}/media/renamed.txt", headers={"Range": "bytes=abc-def"}
        )
        assert r.status_code == 200 and r.content == data
        r = requests.get(
            f"{base}/media/renamed.txt",
            headers={"Range": f"bytes={len(data) + 10}-"},
        )
        assert r.status_code == 416
        # mkdir via trailing slash
        r = requests.post(f"{base}/media/emptydir/")
        assert r.status_code == 201
        assert requests.get(f"{base}/media/emptydir").json()["Entries"] == []
        # rename onto a directory refuses
        r = requests.post(f"{base}/media/emptydir?mv.from=/media/renamed.txt")
        assert r.status_code == 409
        # 204 on a keep-alive session must not desync the connection
        s = requests.Session()
        assert s.delete(f"{base}/media/emptydir").status_code == 204
        assert s.get(f"{base}/media/renamed.txt").content == data
        s.close()
        # delete non-empty without recursive -> 409
        r = requests.delete(f"{base}/media")
        assert r.status_code == 409
        r = requests.delete(f"{base}/media?recursive=true")
        assert r.status_code == 204
        assert requests.get(f"{base}/media/renamed.txt").status_code == 404
    finally:
        srv.stop()


def test_sqlite_prefix_literal_matching(tmp_path):
    st = SqliteStore(str(tmp_path / "p.db"))
    for name in ("apple", "Apple", "a_b", "axb", "a%c"):
        st.insert(new_entry(f"/d/{name}"))
    assert [e.name for e in st.list("/d", prefix="a")] == ["a%c", "a_b", "apple", "axb"]
    assert [e.name for e in st.list("/d", prefix="A")] == ["Apple"]
    assert [e.name for e in st.list("/d", prefix="a_")] == ["a_b"]
    assert [e.name for e in st.list("/d", prefix="a%")] == ["a%c"]
    st.close()
