"""Round-5 shell command family: each command exercised through its
RPCs against live servers (not just argument parsing).

Reference: weed/shell/command_volume_*.go, command_mq_*.go,
command_fs_configure.go, command_cluster_ps.go.
"""

from __future__ import annotations

import json
import time

import grpc
import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import COMMANDS, ShellEnv, run_command


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


@pytest.fixture
def pair(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    wait_for(lambda: len(master.topo.nodes) >= 2, msg="registration")
    env = ShellEnv(f"localhost:{mport}")
    yield master, vols, env
    env.close()
    for vs in vols:
        vs.stop()
    master.stop()


def _mk_volume(vs, vid, data=b"x"):
    with grpc.insecure_channel(f"localhost:{vs.grpc_port}") as ch:
        stub = rpc.volume_stub(ch)
        stub.AllocateVolume(
            pb.AllocateVolumeRequest(volume_id=vid, replication="000"),
            timeout=10,
        )
        stub.WriteNeedle(
            pb.WriteNeedleRequest(
                volume_id=vid, needle_id=1, cookie=3, data=data,
                is_replicate=True,
            ),
            timeout=10,
        )


def test_command_count_at_least_90():
    assert len(COMMANDS) >= 90, sorted(COMMANDS)


def test_volume_copy_unmount_mount_cycle(pair, tmp_path):
    master, (a, b), env = pair
    _mk_volume(a, 41, b"copy-me")
    wait_for(lambda: env.master.lookup(41, refresh=True), msg="master sees 41")
    out = run_command(
        env,
        f"volume.copy -volumeId 41 -target localhost:{b.grpc_port} "
        f"-source localhost:{a.grpc_port}",
    )
    assert "copied volume 41" in out, out
    assert b.store.find_volume(41).read_needle(1).data == b"copy-me"
    # unmount on b: files stay, volume unregistered
    out = run_command(
        env, f"volume.unmount -volumeId 41 -node localhost:{b.grpc_port}"
    )
    assert "unmounted" in out, out
    assert b.store.find_volume(41) is None
    # remount: files load back
    out = run_command(
        env, f"volume.mount -volumeId 41 -node localhost:{b.grpc_port}"
    )
    assert "mounted" in out, out
    assert b.store.find_volume(41).read_needle(1).data == b"copy-me"


def test_volume_configure_replication(pair):
    master, (a, _b), env = pair
    _mk_volume(a, 43)
    wait_for(lambda: env.master.lookup(43, refresh=True), msg="lookup 43")
    out = run_command(
        env, "volume.configure.replication -volumeId 43 -replication 001"
    )
    assert "replication -> 001" in out, out
    v = a.store.find_volume(43)
    assert str(v.super_block.replica_placement) == "001"
    # persisted: survives a reopen of the superblock from disk
    from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE, SuperBlock

    with open(v.dat_path, "rb") as f:
        sb = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))
    assert str(sb.replica_placement) == "001"


def test_volume_vacuum_toggle(pair):
    master, (a, _b), env = pair
    _mk_volume(a, 45, b"payload")
    with grpc.insecure_channel(f"localhost:{a.grpc_port}") as ch:
        rpc.volume_stub(ch).DeleteNeedle(
            pb.DeleteNeedleRequest(volume_id=45, needle_id=1, is_replicate=True),
            timeout=10,
        )
    a.store.find_volume(45).flush()
    a.notify_new_volume(45)
    wait_for(
        lambda: any(
            45 in n.volumes and n.volumes[45].deleted_bytes > 0
            for n in master.topo.nodes.values()
        ),
        msg="master sees garbage",
    )
    assert any(
        vid == 45 for vid, _, _ in master.topo.garbage_candidates(0.01)
    )
    out = run_command(env, "volume.vacuum.disable -volumeId 45")
    assert "disabled" in out, out
    assert not any(
        vid == 45 for vid, _, _ in master.topo.garbage_candidates(0.01)
    )
    out = run_command(env, "volume.vacuum.enable -volumeId 45")
    assert "enabled" in out, out
    assert any(
        vid == 45 for vid, _, _ in master.topo.garbage_candidates(0.01)
    )


def test_cluster_ps_and_worker_list(pair):
    master, _vols, env = pair
    out = run_command(env, "cluster.ps")
    assert "master" in out and out.count("volumeServer") == 2, out
    out = run_command(env, "worker.list")
    assert "no workers connected" in out


def test_maintenance_config_roundtrip(pair):
    master, _vols, env = pair
    out = run_command(
        env,
        "maintenance.config -set balance_spread=3 "
        "-set lifecycle_interval_seconds=60 -set lifecycle_filer=f:123 "
        "-set ec_balance_interval_seconds=45 "
        "-set ec_scrub_interval_seconds=3600 "
        "-set ec_rebalance_interval_seconds=120",
    )
    doc = json.loads(out)
    assert doc["balance_spread"] == 3.0
    assert doc["lifecycle_interval_seconds"] == 60.0
    assert doc["lifecycle_filer"] == "f:123"
    assert doc["ec_balance_interval_seconds"] == 45.0
    assert doc["ec_scrub_interval_seconds"] == 3600.0
    assert doc["ec_rebalance_interval_seconds"] == 120.0
    assert master.balance_spread == 3.0
    assert master.lifecycle_filer == "f:123"
    assert master.ec_balance_interval == 45.0
    # the carried ROADMAP knob: fleet scrub period is now runtime-
    # settable over the RPC, not constructor-only — and 0 turns the
    # scanner back off without touching the other knobs
    assert master.ec_scrub_interval == 3600.0
    # the PR 15 carried knob: gravity-rebalance cadence is runtime-
    # settable too (proto3-optional field, read-modify-write semantics)
    assert master.ec_rebalance_interval == 120.0
    out = run_command(env, "maintenance.config -set ec_scrub_interval_seconds=0")
    assert json.loads(out)["ec_scrub_interval_seconds"] == 0.0
    assert master.ec_scrub_interval == 0.0
    assert master.ec_balance_interval == 45.0  # partial update untouched
    assert master.ec_rebalance_interval == 120.0  # partial update untouched
    out = run_command(env, "maintenance.config -set ec_scrub_interval_seconds=-5")
    assert "error" in out
    out = run_command(
        env, "maintenance.config -set ec_rebalance_interval_seconds=-1"
    )
    assert "error" in out


# --------------------------------------------------------------- MQ ops


@pytest.fixture
def broker():
    from seaweedfs_tpu.mq.broker import MqBrokerServer

    srv = MqBrokerServer(ip="127.0.0.1", grpc_port=free_port(), kafka_port=0)
    srv.start()
    yield srv
    srv.stop()


def test_mq_truncate_and_delete(broker):
    from seaweedfs_tpu.mq.client import MqClient

    env = ShellEnv("localhost:9333")
    c = MqClient(f"127.0.0.1:{broker.grpc_port}")
    c.configure_topic("trunc", partitions=1)
    for i in range(10):
        c.publish("trunc", key=b"k", value=f"v{i}".encode())
    out = run_command(
        env,
        f"mq.topic.truncate -broker 127.0.0.1:{broker.grpc_port} "
        "-topic trunc -beforeOffset 7",
    )
    assert "truncated 1 partition" in out, out
    log = broker.broker.topic("default", "trunc").logs[0]
    assert log.earliest_offset == 7
    assert log.next_offset == 10
    out = run_command(
        env,
        f"mq.topic.delete -broker 127.0.0.1:{broker.grpc_port} -topic trunc",
    )
    assert "deleted topic" in out, out
    with pytest.raises(KeyError):
        broker.broker.topic("default", "trunc")


def test_mq_compact_archives_segments(tmp_path):
    """compact with a filer-backed broker: sealed raw segments become
    parquet files."""
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.mq.broker import MqBrokerServer
    from seaweedfs_tpu.mq.client import MqClient
    from seaweedfs_tpu.server.filer_server import FilerServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    wait_for(lambda: master.topo.nodes, msg="vs registers")
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    fsrv = FilerServer(filer, ip="localhost", port=free_port())
    fsrv.start()
    broker = MqBrokerServer(
        ip="127.0.0.1", grpc_port=free_port(), kafka_port=0,
        filer=f"localhost:{fsrv.port}", segment_records=8,
    )
    broker.start()
    try:
        c = MqClient(f"127.0.0.1:{broker.grpc_port}")
        c.configure_topic("arch", partitions=1)
        for i in range(40):  # 5 sealed segments of 8
            c.publish("arch", key=b"k", value=f"v{i}".encode())
        env = ShellEnv(f"localhost:{mport}")
        out = run_command(
            env,
            f"mq.topic.compact -broker 127.0.0.1:{broker.grpc_port} "
            "-topic arch",
        )
        assert "archived" in out, out
        n = int(out.split("archived ")[1].split(" ")[0])
        assert n >= 1
        # parquet files now exist in the topic directory
        from seaweedfs_tpu.client.filer_client import list_dir

        names = [
            e["FullPath"]
            for e in list_dir(f"localhost:{fsrv.port}", "/topics/default/arch/0000")
        ]
        assert any(p.endswith(".parquet") for p in names), names
        # records still readable end to end (parquet fallback load)
        got = list(c.subscribe("arch", partition=0, start_offset=0))
        assert len(got) == 40
    finally:
        broker.stop()
        fsrv.stop()
        filer.close()
        vs.stop()
        master.stop()


# ----------------------------------------------------- filer-side config


def test_fs_configure_rules_apply(tmp_path):
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.server.filer_server import FilerServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    wait_for(lambda: master.topo.nodes, msg="vs registers")
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    fport = free_port()
    # the shell derives filer gRPC as HTTP+10000 (the CLI convention)
    fsrv = FilerServer(filer, ip="localhost", port=fport, grpc_port=fport + 10000)
    fsrv.start()
    try:
        env = ShellEnv(f"localhost:{mport}", filer=f"localhost:{fsrv.port}")
        out = run_command(
            env,
            "fs.configure -locationPrefix /hot/ -collection fast "
            "-ttlSec 3600",
        )
        assert "configured /hot/" in out, out
        rule = filer.path_conf("/hot/a.txt")
        assert rule["collection"] == "fast"
        assert rule["ttl_sec"] == 3600
        assert filer.path_conf("/cold/b.txt") == {}
        # writes under the prefix pick the rule's ttl up
        e = filer.write_file("/hot/a.txt", b"abc")
        assert e.attr.ttl_sec == 3600
        e2 = filer.write_file("/cold/b.txt", b"abc")
        assert e2.attr.ttl_sec == 0
        # show + delete
        assert "/hot/" in run_command(env, "fs.configure -show")
        run_command(env, "fs.configure -locationPrefix /hot/ -delete")
        assert filer.path_conf("/hot/a.txt") == {}
    finally:
        fsrv.stop()
        filer.close()
        vs.stop()
        master.stop()


def test_mount_configure_applies_to_new_mounts(tmp_path):
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.mount.weed_mount import FilerMount
    from seaweedfs_tpu.server.filer_server import FilerServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    wait_for(lambda: master.topo.nodes, msg="vs registers")
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    fport = free_port()
    fsrv = FilerServer(filer, ip="localhost", port=fport, grpc_port=fport + 10000)
    fsrv.start()
    try:
        env = ShellEnv(f"localhost:{mport}", filer=f"localhost:{fsrv.port}")
        out = run_command(
            env, "mount.configure -attrTtl 0.25 -readonly true"
        )
        assert "applies to newly started mounts" in out
        fm = FilerMount(f"localhost:{fsrv.port}")
        assert fm.attr_ttl == 0.25
        assert fm.readonly is True

        class _FI:
            class contents:
                flags = 0x1  # O_WRONLY

        import errno as _errno

        assert fm.open("/x", _FI) == -_errno.EROFS
        assert fm.mkdir("/d", 0o755) == -_errno.EROFS
        run_command(env, "mount.configure -readonly false")
        fm2 = FilerMount(f"localhost:{fsrv.port}")
        assert fm2.readonly is False
    finally:
        fsrv.stop()
        filer.close()
        vs.stop()
        master.stop()


def test_volume_tier_move_command(pair):
    """tier.move resolves a target node and rides volume.move through
    the real RPC chain."""
    master, (a, b), env = pair
    _mk_volume(a, 47, b"tiered")
    wait_for(lambda: env.master.lookup(47, refresh=True), msg="lookup 47")
    out = run_command(
        env, "volume.tier.move -volumeId 47 -targetDiskType hdd"
    )
    assert "moved volume 47" in out, out
    assert a.store.find_volume(47) is None
    assert b.store.find_volume(47).read_needle(1).data == b"tiered"


def test_volume_scrub_and_ec_scrub_repair_smoke(pair, tmp_path):
    """weed shell volume.scrub / ec.scrub -repair smoke: clean scrub,
    injected bitrot detected, -repair rebuilds, second scrub clean."""
    import os

    from seaweedfs_tpu.storage.volume import Volume

    master, (a, _b), env = pair
    _mk_volume(a, 61, b"scrub-payload" * 500)
    wait_for(lambda: env.master.lookup(61, refresh=True), msg="lookup 61")
    out = run_command(env, "volume.scrub -volumeId 61")
    assert "all clean" in out, out

    out = run_command(env, "ec.encode -volumeId 61 -backend cpu -keepSource")
    assert "encoded" in out or "ec" in out, out

    def ec_visible():
        # lookup_ec raises (rather than returning empty) until the
        # heartbeat registers the shards — treat that as "not yet"
        try:
            return env.master.lookup_ec(61, refresh=True)
        except LookupError:
            return False

    wait_for(ec_visible, msg="ec shards visible")
    out = run_command(env, "ec.scrub -volumeId 61")
    assert "all clean" in out, out

    # bit-flip one shard on disk, then scrub with -repair
    base = Volume.base_file_name(str(tmp_path / "v0"), "", 61)
    shard = base + ".ec03"
    assert os.path.exists(shard)
    with open(shard, "r+b") as f:
        f.seek(64)
        b = f.read(1)
        f.seek(64)
        f.write(bytes([b[0] ^ 0x10]))
    out = run_command(env, "ec.scrub -volumeId 61 -repair")
    assert "BITROT in shards [3]" in out, out
    assert "rebuilt shards [3]" in out, out
    out = run_command(env, "ec.scrub -volumeId 61")
    assert "all clean" in out, out

    # delete a shard file out from under the server: scrub flags the
    # advertised-but-missing file and -repair regenerates it
    os.unlink(base + ".ec07")
    out = run_command(env, "ec.scrub -volumeId 61 -repair")
    assert "MISSING" in out, out
    assert "rebuilt shards [7]" in out, out
    assert os.path.exists(base + ".ec07")
    out = run_command(env, "ec.scrub -volumeId 61")
    assert "all clean" in out and "MISSING" not in out, out


def test_truncate_read_clamps_to_earliest(broker):
    """Reads below the truncation point clamp UP to earliest instead of
    skipping the retained partial segment (review r5)."""
    from seaweedfs_tpu.mq.client import MqClient

    c = MqClient(f"127.0.0.1:{broker.grpc_port}")
    c.configure_topic("clamp", partitions=1)
    for i in range(10):
        c.publish("clamp", key=b"k", value=f"v{i}".encode())
    broker.broker.truncate_topic("default", "clamp", before_offset=6)
    got = list(c.subscribe("clamp", partition=0, start_offset=0))
    assert [r.offset for r in got] == list(range(6, 10))
