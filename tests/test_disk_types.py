"""Per-disk-type locations + crowded-state volume layout.

References: weed/storage store per-disk-type DiskLocations,
weed/topology/volume_layout.go crowded/full transitions.
"""

import time

import pytest

from conftest import allocate_port
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import VolumeError


def test_store_disk_type_tagging_and_allocation(tmp_path):
    st = Store(
        [str(tmp_path / "hdd1"), f"{tmp_path}/fast:ssd"],
        ip="localhost",
        port=0,
    )
    types = {loc.disk_type for loc in st.locations}
    assert types == {"hdd", "ssd"}
    v_ssd = st.allocate_volume(1, disk_type="ssd")
    assert "/fast/" in v_ssd.dat_path
    v_any = st.allocate_volume(2)
    assert v_any is not None
    with pytest.raises(VolumeError, match="nvme"):
        st.allocate_volume(3, disk_type="nvme")


def test_assign_honors_disk_type(tmp_path):
    mport = allocate_port()
    ms = MasterServer(ip="localhost", port=mport)
    ms.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "hdd"), f"{tmp_path}/ssd:ssd"],
        master=f"localhost:{mport}",
        ip="localhost",
        port=allocate_port(),
    )
    vs.start()
    try:
        while not ms.topo.nodes:
            time.sleep(0.05)
        ops = Operations(master=f"localhost:{mport}")
        a_ssd = ops.master.assign(disk_type="ssd")
        vid_ssd = int(a_ssd.fid.split(",")[0])
        vol = vs.store.find_volume(vid_ssd)
        assert f"{tmp_path}/ssd/" in vol.dat_path
        # heartbeats report the type; later typed assigns reuse it
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            node = next(iter(ms.topo.nodes.values()))
            vmeta = node.volumes.get(vid_ssd)
            if vmeta is not None and vmeta.disk_type == "ssd":
                break
            time.sleep(0.1)
        assert vmeta.disk_type == "ssd"
        a2 = ops.master.assign(disk_type="ssd")
        assert int(a2.fid.split(",")[0]) == vid_ssd
        # untyped assigns may land anywhere
        a3 = ops.master.assign()
        assert a3.fid
    finally:
        vs.stop()
        ms.stop()


def test_crowded_volumes_are_avoided_then_grown(tmp_path):
    """pick_for_write prefers roomy volumes; when every candidate is
    crowded, assignment still succeeds but growth kicks in."""
    mport = allocate_port()
    # tiny limit so a single write crowds the volume
    ms = MasterServer(
        ip="localhost", port=mport, volume_size_limit=64 * 1024
    )
    ms.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=allocate_port(),
        max_volume_count=4,
    )
    vs.start()
    try:
        while not ms.topo.nodes:
            time.sleep(0.05)
        ops = Operations(master=f"localhost:{mport}")
        fid1 = ops.upload(b"x" * 60 * 1024)  # crowds its volume
        vid1 = int(fid1.split(",")[0])
        # wait for the heartbeat to report the size
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            node = next(iter(ms.topo.nodes.values()))
            v = node.volumes.get(vid1)
            if v is not None and v.size >= 55 * 1024:
                break
            time.sleep(0.1)
        assert ms.topo.all_crowded("", "")
        assert ms.topo._is_crowded(
            vid1, [next(iter(ms.topo.nodes.values()))]
        )
        # assigning against the crowded bucket still works AND triggers
        # background growth; eventually a roomy volume appears and is
        # preferred
        ops.master.assign()
        deadline = time.monotonic() + 10
        grew = False
        while time.monotonic() < deadline:
            if len(vs.store.volume_ids()) > 1:
                grew = True
                break
            ops.master.assign()
            time.sleep(0.2)
        assert grew, "crowded bucket should trigger proactive growth"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            picked = ms.topo.pick_for_write("", "")
            if picked and picked[0] != vid1:
                break
            time.sleep(0.1)
        assert picked[0] != vid1, "roomy volume should be preferred"
    finally:
        vs.stop()
        ms.stop()
