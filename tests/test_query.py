"""SQL engine over topics + PostgreSQL wire server (query/).

Mirrors the reference's weed/query/engine tests and
weed/server/postgres: parse/execute coverage on the engine, then a live
PG server driven over real sockets by the in-repo v3 client.
"""

import json
import time

import pytest

from conftest import allocate_port
from seaweedfs_tpu.mq.broker import MqBroker, MqBrokerServer
from seaweedfs_tpu.query.engine import QueryEngine, QueryError, parse
from seaweedfs_tpu.query.pg_client import PgClient, PgError


def _broker_with_data() -> MqBroker:
    b = MqBroker()
    b.configure_topic("default", "events", 2)
    st = b.topic("default", "events")
    rows = [
        {"user": "alice", "action": "login", "bytes": 120, "ok": True},
        {"user": "bob", "action": "upload", "bytes": 4096, "ok": True},
        {"user": "alice", "action": "upload", "bytes": 2048, "ok": False},
        {"user": "carol", "action": "login", "bytes": 80, "ok": True},
        {"user": "bob", "action": "delete", "bytes": 0, "ok": True},
    ]
    for i, row in enumerate(rows):
        st.logs[i % 2].append(
            (1_700_000_000_000 + i) * 1_000_000,
            b"k%d" % i,
            json.dumps(row).encode(),
        )
    b.configure_topic("default", "plain", 1)
    b.topic("default", "plain").logs[0].append(
        time.time_ns(), b"", b"not json at all"
    )
    return b


# --------------------------------------------------------------- parser


def test_parser_rejects_garbage():
    for bad in (
        "DELETE FROM events",
        "SELECT FROM",
        "SELECT * FROM events WHERE",
        "SELECT * FROM events LIMIT x",
        "SELECT nosuchfn(x) FROM events",
    ):
        with pytest.raises(QueryError):
            parse(bad)


def test_parser_accepts_quoting_and_case():
    s = parse("select USER, bytes from events where user = 'o''brien' limit 5")
    assert s.table == "events"
    assert s.limit == 5
    assert s.where == ("cmp", "=", "user", "o'brien")


# --------------------------------------------------------------- engine


@pytest.fixture
def engine():
    return QueryEngine(_broker_with_data())


def test_show_tables_and_describe(engine):
    res = engine.execute("SHOW TABLES")
    names = {r[1] for r in res.rows}
    assert {"events", "plain"} <= names
    res = engine.execute("DESCRIBE events")
    cols = dict(res.rows)
    assert cols["user"] == "text"
    assert cols["bytes"] == "bigint"
    assert cols["ok"] == "boolean"
    assert cols["_offset"] == "bigint"


def test_select_where_order_limit(engine):
    res = engine.execute(
        "SELECT user, bytes FROM events WHERE action = 'upload'"
        " ORDER BY bytes DESC"
    )
    assert res.columns == ["user", "bytes"]
    assert res.rows == [["bob", 4096], ["alice", 2048]]
    res = engine.execute(
        "SELECT user FROM events WHERE bytes > 100 AND ok = TRUE"
        " ORDER BY user ASC LIMIT 1"
    )
    assert res.rows == [["alice"]]
    res = engine.execute(
        "SELECT user FROM events WHERE action LIKE 'log%' ORDER BY user"
    )
    assert [r[0] for r in res.rows] == ["alice", "carol"]
    # OFFSET pagination
    res = engine.execute(
        "SELECT user FROM events ORDER BY _offset LIMIT 2 OFFSET 1"
    )
    assert len(res.rows) == 2


def test_aggregates(engine):
    res = engine.execute(
        "SELECT COUNT(*), SUM(bytes), MIN(bytes), MAX(bytes), AVG(bytes)"
        " FROM events"
    )
    assert res.rows == [[5, 6344.0, 0, 4096, 6344.0 / 5]]
    res = engine.execute(
        "SELECT COUNT(*) AS n FROM events WHERE user = 'alice'"
    )
    assert res.columns == ["n"]
    assert res.rows == [[2]]


def test_system_columns_and_non_json(engine):
    res = engine.execute(
        "SELECT _key, _partition FROM events WHERE _offset = 0 ORDER BY _key"
    )
    assert len(res.rows) == 2  # offset 0 exists in both partitions
    res = engine.execute("SELECT _value FROM plain")
    assert res.rows == [["not json at all"]]
    with pytest.raises(QueryError):
        engine.execute("SELECT * FROM nonexistent")


def test_null_semantics(engine):
    # a column absent from some rows: IS NULL / IS NOT NULL
    res = engine.execute(
        "SELECT COUNT(*) FROM events WHERE nosuch IS NULL"
    )
    assert res.rows == [[5]]
    res = engine.execute(
        "SELECT COUNT(*) FROM events WHERE nosuch IS NOT NULL"
    )
    assert res.rows == [[0]]
    # comparisons against missing columns are false, not errors
    res = engine.execute("SELECT COUNT(*) FROM events WHERE nosuch = 3")
    assert res.rows == [[0]]


# ----------------------------------------------------------- pg server


@pytest.fixture
def pg_broker():
    srv = MqBrokerServer(
        ip="127.0.0.1", grpc_port=allocate_port(), pg_port=0
    )
    # seed data through the broker object directly
    srv.broker.configure_topic("default", "events", 1)
    st = srv.broker.topic("default", "events")
    for i in range(4):
        st.logs[0].append(
            time.time_ns(),
            b"k%d" % i,
            json.dumps({"n": i, "tag": f"t{i % 2}"}).encode(),
        )
    srv.start()
    yield srv
    srv.stop()


def test_pg_simple_query_round_trip(pg_broker):
    c = PgClient("127.0.0.1", pg_broker.pg.port)
    try:
        assert "server_version" in c.parameters
        cols, rows = c.query("SELECT n, tag FROM events ORDER BY n")
        assert cols == ["n", "tag"]
        assert rows == [
            ["0", "t0"], ["1", "t1"], ["2", "t0"], ["3", "t1"],
        ]
        cols, rows = c.query("SELECT COUNT(*) AS n FROM events WHERE tag = 't0'")
        assert rows == [["2"]]
        cols, rows = c.query("SHOW TABLES")
        assert ["default", "events", "1"] in rows
        # driver session noise is tolerated
        c.query("SET client_encoding TO 'UTF8'")
        # errors arrive as ErrorResponse, session stays usable
        with pytest.raises(PgError) as ei:
            c.query("SELECT * FROM missing_table")
        assert ei.value.code == "42601"
        _, rows = c.query("SELECT n FROM events WHERE n >= 3")
        assert rows == [["3"]]
    finally:
        c.close()


def test_pg_password_auth():
    srv = MqBrokerServer(
        ip="127.0.0.1",
        grpc_port=allocate_port(),
        pg_port=0,
        pg_users={"admin": "sekrit"},
    )
    srv.start()
    try:
        with pytest.raises(PgError):
            PgClient(
                "127.0.0.1", srv.pg.port, user="admin", password="wrong"
            )
        c = PgClient(
            "127.0.0.1", srv.pg.port, user="admin", password="sekrit"
        )
        cols, rows = c.query("SHOW TABLES")
        c.close()
    finally:
        srv.stop()


def test_pg_null_rendering(pg_broker):
    c = PgClient("127.0.0.1", pg_broker.pg.port)
    try:
        # a column that exists in no row renders as SQL NULL (None)
        cols, rows = c.query("SELECT nosuch FROM events LIMIT 1")
        assert rows == [[None]]
    finally:
        c.close()


def test_pg_via_spawned_process():
    import subprocess
    import sys

    gport, pgport = allocate_port(), allocate_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "seaweedfs_tpu.server", "mq.broker",
            "-ip", "127.0.0.1", "-port", str(gport),
            "-pgPort", str(pgport), "-kafkaPort", "0",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        c = None
        for _ in range(100):
            try:
                c = PgClient("127.0.0.1", pgport)
                break
            except OSError:
                time.sleep(0.1)
        assert c is not None
        cols, rows = c.query("SHOW TABLES")
        assert cols == ["namespace", "table", "partitions"]
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# --------------------------------------------- GROUP BY / HAVING / r5


def test_group_by_aggregates(engine):
    r = engine.execute(
        "SELECT user, COUNT(*) AS n, SUM(bytes) AS total FROM events "
        "GROUP BY user ORDER BY n DESC, user LIMIT 10"
    )
    assert r.columns == ["user", "n", "total"]
    assert r.rows == [
        ["alice", 2, 2168.0],
        ["bob", 2, 4096.0],
        ["carol", 1, 80.0],
    ]


def test_group_by_having(engine):
    r = engine.execute(
        "SELECT action, COUNT(*) AS n FROM events "
        "GROUP BY action HAVING n >= 2 ORDER BY action"
    )
    assert r.rows == [["login", 2], ["upload", 2]]


def test_group_by_rejects_ungrouped_column(engine):
    with pytest.raises(QueryError):
        engine.execute("SELECT user, COUNT(*) AS n FROM events")


def test_multi_column_order_by(engine):
    r = engine.execute(
        "SELECT user, action FROM events ORDER BY user, action DESC"
    )
    assert r.rows[:2] == [["alice", "upload"], ["alice", "login"]]


def test_group_by_via_pg_wire(pg_broker):
    c = PgClient("127.0.0.1", pg_broker.pg.port)
    try:
        cols, rows = c.query(
            "SELECT tag, COUNT(*) AS c FROM events GROUP BY tag "
            "ORDER BY tag"
        )
        assert cols == ["tag", "c"]
        assert rows == [["t0", "2"], ["t1", "2"]]
    finally:
        c.close()


def test_parquet_pushdown_prunes_segments(tmp_path):
    """Aggregation over a parquet-archived topic: the stats sidecars
    let the scan SKIP whole segments outside the _ts bound — proven by
    the Result's scan counters."""
    import time as _time

    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.mq.broker import MqBroker
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    from conftest import allocate_port as free_port

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        _time.sleep(0.05)
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    fsrv = FilerServer(filer, ip="localhost", port=free_port())
    fsrv.start()
    try:
        b = MqBroker(filer=f"localhost:{fsrv.port}", segment_records=8)
        b.configure_topic("default", "metrics", 1)
        st = b.topic("default", "metrics")
        base_ms = 1_700_000_000_000
        for i in range(64):  # 8 sealed segments at 8 records each
            st.logs[0].append(
                (base_ms + i * 1000) * 1_000_000,
                b"k",
                json.dumps({"v": i, "bucket": i // 16}).encode(),
            )
        st.logs[0].flush()
        archived = b.compact_topic("default", "metrics")
        assert archived >= 7
        eng = QueryEngine(b)

        # unbounded scan touches every archived segment
        r_all = eng.execute(
            "SELECT COUNT(*) AS n, SUM(v) AS s FROM metrics"
        )
        assert r_all.rows == [[64, sum(range(64))]]
        full_scanned = r_all.stats["segments_scanned"]
        assert full_scanned >= 7
        assert r_all.stats["segments_skipped"] == 0

        # a _ts lower bound prunes the early segments WITHOUT fetching
        cut = base_ms + 40 * 1000
        r = eng.execute(
            "SELECT bucket, COUNT(*) AS n FROM metrics "
            f"WHERE _ts >= {cut} GROUP BY bucket ORDER BY bucket"
        )
        assert r.rows == [[2, 8], [3, 16]]
        assert r.stats["segments_skipped"] >= 4, r.stats
        assert (
            r.stats["segments_scanned"]
            + r.stats["segments_skipped"]
            == full_scanned
        )
        assert r.stats["rows_scanned"] < 64

        # offset pushdown: equality/range on _offset skips by stats too
        r2 = eng.execute(
            "SELECT COUNT(*) AS n FROM metrics WHERE _offset >= 56"
        )
        assert r2.rows == [[8]]
        assert r2.stats["segments_skipped"] >= 6, r2.stats
    finally:
        fsrv.stop()
        filer.close()
        vs.stop()
        master.stop()
