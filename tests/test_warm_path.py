"""Warm-path control-plane fast paths (ISSUE 13).

Coherence contracts for the two caches the warm S3 GET now rides —
the SigV4 verdict memo (s3/auth.py) and the filer entry-lookup cache
(tier="filer_entry") — plus the end-to-end identity of the
chunk-fetch-over-net-plane byte path:

- a memo/cache HIT must be bit-identical to a full recomputation;
- key rotation, permanent 403s, deletes, renames, and replicated
  meta-log events must NEVER be served stale;
- presigned/streaming auth bypasses the memo untouched;
- concurrent warm misses on one entry collapse to ONE store.find.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import threading
import time
import urllib.parse

import pytest

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.filer_store import NotFound
from seaweedfs_tpu.s3 import auth as s3auth
from seaweedfs_tpu.s3.auth import (
    Identity,
    IdentityStore,
    S3AuthError,
    verify_v4_ex,
)
from seaweedfs_tpu.utils import metrics as M

ACCESS = "AKIDWARM"
SECRET = "warm-secret-1"


@pytest.fixture(autouse=True)
def _clean_auth_caches():
    s3auth.auth_cache_clear()
    yield
    s3auth.auth_cache_clear()


def _sign(
    method: str,
    path: str,
    query: str = "",
    secret: str = SECRET,
    access: str = ACCESS,
    payload: bytes = b"",
    payload_hash: str | None = None,
    extra_headers: dict | None = None,
    sign_extra: bool = True,
    region: str = "us-east-1",
    amz_date: str | None = None,
):
    """Build (headers, payload_hash) for a header-auth SigV4 request
    via the shared signer next to the verifier (s3/auth.sign_v4) —
    tests/test_s3.py keeps an independent hand-rolled signer as the
    cross-implementation check."""
    if payload_hash is None:
        payload_hash = hashlib.sha256(payload).hexdigest()
    base = {"host": "localhost:8333"}
    if extra_headers and sign_extra:
        base.update(extra_headers)
    headers = s3auth.sign_v4(
        method, path, query,
        access_key=access, secret_key=secret,
        headers=base, payload_hash=payload_hash,
        region=region, amz_date=amz_date,
    )
    if extra_headers and not sign_extra:
        # header present on the request but NOT part of the signature
        headers.update(extra_headers)
    return headers, payload_hash


def _store(ident: Identity | None = None) -> IdentityStore:
    s = IdentityStore()
    s.add(ident or Identity("warm", ACCESS, SECRET))
    return s


def _memo_counts() -> dict:
    return {
        k[0]: int(v) for k, v in M.s3_auth_memo_total.snapshot().items()
    }


# ------------------------------------------------------------- auth memo


def test_auth_memo_hit_bit_identical():
    """The second identical request is a memo HIT and returns the same
    identity and a SigningContext equal field-for-field to the full
    verification's."""
    store = _store()
    hdrs, ph = _sign("GET", "/bench/obj")
    c0 = _memo_counts()
    id1, ctx1 = verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
    id2, ctx2 = verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
    c1 = _memo_counts()
    assert c1.get("miss", 0) - c0.get("miss", 0) == 1
    assert c1.get("hit", 0) - c0.get("hit", 0) == 1
    assert id1 is id2  # same stored Identity from a fresh lookup
    assert ctx1 == ctx2  # dataclass equality: key, date, scope, seed sig
    assert s3auth.auth_cache_stats()["verdicts"] == 1


def test_auth_memo_key_rotation_never_served():
    """Rotating the secret invalidates BY CONSTRUCTION (the secret is
    part of the memo digest): the old signed request must 403, never
    replay from the memo."""
    store = _store()
    hdrs, ph = _sign("GET", "/bench/obj")
    verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)  # memoized
    store.add(Identity("warm", ACCESS, "rotated-secret-2"))
    with pytest.raises(S3AuthError) as ei:
        verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
    assert ei.value.code == "SignatureDoesNotMatch"
    # re-signed with the new secret: verifies and memoizes separately
    hdrs2, ph2 = _sign("GET", "/bench/obj", secret="rotated-secret-2")
    ident, _ = verify_v4_ex(store, "GET", "/bench/obj", "", hdrs2, ph2)
    assert ident.secret_key == "rotated-secret-2"


def test_auth_permanent_403_never_cached():
    """Failed verifications are recomputed every time — only successes
    are admitted to the memo."""
    store = _store()
    hdrs, ph = _sign("GET", "/bench/obj", secret="wrong-secret")
    for _ in range(2):
        with pytest.raises(S3AuthError) as ei:
            verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
        assert ei.value.code == "SignatureDoesNotMatch"
    assert s3auth.auth_cache_stats()["verdicts"] == 0


def test_auth_memo_tamper_is_a_miss():
    """Any changed verification input (here: the path) is a different
    digest — the memo can never validate a tampered request."""
    store = _store()
    hdrs, ph = _sign("GET", "/bench/obj")
    verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
    with pytest.raises(S3AuthError) as ei:
        verify_v4_ex(store, "GET", "/bench/OTHER", "", hdrs, ph)
    assert ei.value.code == "SignatureDoesNotMatch"


def test_auth_streaming_bypasses_memo():
    store = _store()
    ph = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
    hdrs, _ = _sign("PUT", "/bench/obj", payload_hash=ph)
    c0 = _memo_counts()
    _, ctx = verify_v4_ex(store, "PUT", "/bench/obj", "", hdrs, ph)
    assert ctx is not None  # streaming auth still yields the seed ctx
    c1 = _memo_counts()
    assert c1.get("bypass", 0) - c0.get("bypass", 0) == 1
    assert s3auth.auth_cache_stats()["verdicts"] == 0


def test_auth_presigned_bypasses_memo():
    """Presigned-URL auth never touches the memo (its own code path,
    byte-for-byte untouched)."""
    store = _store()
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    scope = f"{date}/us-east-1/s3/aws4_request"
    q = {
        "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
        "X-Amz-Credential": f"{ACCESS}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": "3600",
        "X-Amz-SignedHeaders": "host",
    }
    cq = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q.items())
    )
    creq = "\n".join(
        ["GET", "/bench/obj", cq, "host:localhost:8333\n", "host",
         "UNSIGNED-PAYLOAD"]
    )
    sts = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope,
         hashlib.sha256(creq.encode()).hexdigest()]
    )

    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = h(
        h(h(h(("AWS4" + SECRET).encode(), date), "us-east-1"), "s3"),
        "aws4_request",
    )
    sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    query = f"{cq}&X-Amz-Signature={sig}"
    headers = {"host": "localhost:8333"}
    c0 = _memo_counts()
    ident, ctx = verify_v4_ex(
        store, "GET", "/bench/obj", query, headers, "UNSIGNED-PAYLOAD"
    )
    assert ident.access_key == ACCESS and ctx is None
    c1 = _memo_counts()
    assert c1.get("hit", 0) == c0.get("hit", 0)
    assert c1.get("miss", 0) == c0.get("miss", 0)
    assert s3auth.auth_cache_stats()["verdicts"] == 0


def test_auth_memo_disabled_by_env(monkeypatch):
    monkeypatch.setenv("SEAWEED_S3_AUTH_MEMO", "0")
    store = _store()
    hdrs, ph = _sign("GET", "/bench/obj")
    c0 = _memo_counts()
    verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
    verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
    c1 = _memo_counts()
    assert c1.get("hit", 0) == c0.get("hit", 0)
    assert c1.get("bypass", 0) - c0.get("bypass", 0) == 2
    assert s3auth.auth_cache_stats()["verdicts"] == 0


def test_auth_memo_session_token_rechecked_on_hit():
    """The session token may ride an UNSIGNED header (outside the memo
    digest): a hit must still re-compare it — a revoked/garbled token
    is refused even when the signature memo matches."""
    ident = Identity(
        "sts", ACCESS, SECRET, actions=("Admin",), session_token="tok-1"
    )
    store = _store(ident)
    hdrs, ph = _sign(
        "GET", "/bench/obj",
        extra_headers={"x-amz-security-token": "tok-1"},
        sign_extra=False,
    )
    id1, _ = verify_v4_ex(store, "GET", "/bench/obj", "", hdrs, ph)
    assert id1.session_token == "tok-1"
    bad = dict(hdrs)
    bad["x-amz-security-token"] = "tok-FORGED"
    with pytest.raises(S3AuthError) as ei:
        verify_v4_ex(store, "GET", "/bench/obj", "", bad, ph)
    assert ei.value.code == "InvalidToken"


def test_signing_key_cache_pure():
    """signing_key is memoized but stays a pure function of its
    arguments — distinct scopes derive distinct keys."""
    k1 = s3auth.signing_key("s", "20260804", "us-east-1")
    k2 = s3auth.signing_key("s", "20260804", "us-east-1")
    k3 = s3auth.signing_key("s", "20260805", "us-east-1")
    k4 = s3auth.signing_key("OTHER", "20260804", "us-east-1")
    assert k1 == k2 and k1 != k3 and k1 != k4
    assert s3auth.auth_cache_stats()["signing_keys"] == 3


# ------------------------------------------------------ entry-lookup cache


@pytest.fixture
def filer():
    f = Filer(MemoryStore(), master="localhost:1")
    yield f
    f.close()


def test_entry_cache_hit_bit_identical(filer):
    filer.write_file("/dir/a.txt", b"hello")  # inlined: no volume I/O
    e1 = filer.find_entry("/dir/a.txt")
    s0 = filer.entry_cache.stats()
    e2 = filer.find_entry("/dir/a.txt")
    s1 = filer.entry_cache.stats()
    assert s1["hits"] - s0["hits"] == 1
    assert e1.to_bytes() == e2.to_bytes()
    assert e2.content == b"hello"
    assert e1 is not e2  # decoded per hit: callers may mutate freely


def test_entry_cache_invalidated_on_overwrite(filer):
    filer.write_file("/dir/a.txt", b"v1")
    assert filer.find_entry("/dir/a.txt").content == b"v1"
    filer.write_file("/dir/a.txt", b"v2-new")
    assert filer.find_entry("/dir/a.txt").content == b"v2-new"


def test_entry_cache_invalidated_on_mutate(filer):
    filer.write_file("/dir/a.txt", b"x")
    filer.find_entry("/dir/a.txt")

    def set_mime(e):
        e.attr.mime = "text/warm"

    filer.mutate_entry("/dir/a.txt", set_mime)
    assert filer.find_entry("/dir/a.txt").attr.mime == "text/warm"


def test_entry_cache_stale_never_served_after_delete(filer):
    filer.write_file("/dir/a.txt", b"gone soon")
    filer.find_entry("/dir/a.txt")  # cached
    filer.delete_entry("/dir/a.txt")
    with pytest.raises(NotFound):
        filer.find_entry("/dir/a.txt")


def test_entry_cache_invalidated_on_rename(filer):
    filer.write_file("/dir/a.txt", b"moving")
    filer.find_entry("/dir/a.txt")  # cache the old path
    with pytest.raises(NotFound):
        filer.find_entry("/dir/b.txt")  # NotFound is not cached
    filer.rename("/dir/a.txt", "/dir/b.txt")
    with pytest.raises(NotFound):
        filer.find_entry("/dir/a.txt")
    assert filer.find_entry("/dir/b.txt").content == b"moving"


def test_entry_cache_invalidated_by_remote_meta_event():
    """A replicated meta-log event (multi-filer aggregation) must
    invalidate like a local write: the follower filer serves the
    replicated content, not its cached pre-event entry."""
    origin = Filer(MemoryStore(), master="localhost:1")
    follower = Filer(MemoryStore(), master="localhost:1")
    events = []
    origin.subscribe(events.append)
    try:
        origin.write_file("/r/x", b"v1")
        for ev in list(events):
            follower.apply_remote_event(ev)
        assert follower.find_entry("/r/x").content == b"v1"  # cached
        events.clear()
        origin.write_file("/r/x", b"v2-replicated")
        for ev in list(events):
            follower.apply_remote_event(ev)
        assert follower.find_entry("/r/x").content == b"v2-replicated"
    finally:
        origin.close()
        follower.close()


def test_entry_cache_hardlinked_names_never_stale(filer):
    """Hardlinked entries are never admitted: a write through one name
    is visible through every sibling immediately."""
    filer.write_file("/hl/a", b"shared-v1")
    filer.hard_link("/hl/a", "/hl/b")
    assert filer.find_entry("/hl/a").content == b"shared-v1"
    assert filer.find_entry("/hl/b").content == b"shared-v1"
    # write through b; a must observe it (no cached pre-link snapshot)
    filer.write_file("/hl/b", b"shared-v2!")
    assert filer.find_entry("/hl/a").content == b"shared-v2!"
    assert filer.find_entry("/hl/b").content == b"shared-v2!"


def test_entry_cache_respects_ttl_expiry(filer):
    filer.write_file("/ttl/x", b"short-lived", ttl_sec=1)
    assert filer.find_entry("/ttl/x").content == b"short-lived"

    def age(e):
        e.attr.crtime = int(time.time()) - 10

    filer.mutate_entry("/ttl/x", age)
    # cached or not, the TTL check runs on every return
    with pytest.raises(NotFound):
        filer.find_entry("/ttl/x")


def test_entry_lookup_singleflight_one_store_find(filer):
    """ISSUE 13 acceptance: N concurrent warm misses on one entry
    collapse to ONE store.find."""
    filer.write_file("/sf/obj", b"collapse me")
    filer.entry_cache.clear()
    finds = [0]
    lock = threading.Lock()
    real_find = filer.store.find

    def slow_counting_find(directory, name):
        with lock:
            finds[0] += 1
        time.sleep(0.05)  # hold the flight open so others join
        return real_find(directory, name)

    filer.store.find = slow_counting_find
    try:
        results = []
        errs = []

        def reader():
            try:
                results.append(filer.find_entry("/sf/obj").to_bytes())
            except Exception as e:  # pragma: no cover - fail the assert
                errs.append(e)

        threads = [threading.Thread(target=reader) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert finds[0] == 1, f"{finds[0]} store.find calls for 8 readers"
        assert len(set(results)) == 1  # everyone got the leader's bytes
        s = filer.entry_cache.stats()
        assert s["singleflight_waits"] >= 1
    finally:
        filer.store.find = real_find


def test_entry_cache_disabled_is_passthrough():
    f = Filer(MemoryStore(), master="localhost:1", entry_cache_bytes=0)
    try:
        f.write_file("/p/x", b"no cache")
        assert f.find_entry("/p/x").content == b"no cache"
        assert f.entry_cache.stats()["entries"] == 0
    finally:
        f.close()


# ----------------------------------------- chunk fetch over the net plane


def test_warm_gateway_chunk_fetch_rides_native_plane(tmp_path):
    """End to end: a warm S3 GET with the filer chunk cache OFF moves
    its volume chunk bytes over the shard net plane's needle opcode
    (sw_net_bytes_received{plane=native} grows by the body size), and
    the body is bit-identical with the plane disabled."""
    import os

    import requests

    from conftest import allocate_port as free_port
    from seaweedfs_tpu.s3 import S3Server
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    filer = srv = None
    try:
        deadline = time.time() + 20
        while not master.topo.nodes:
            assert time.time() < deadline, "volume never registered"
            time.sleep(0.05)
        # chunk cache off: every GET pays the filer->volume fetch —
        # exactly the path ISSUE 13 moves onto the net plane
        filer = Filer(
            MemoryStore(), master=f"localhost:{mport}",
            chunk_size=128 * 1024, chunk_cache_bytes=0,
        )
        srv = S3Server(filer, ip="localhost", port=free_port())
        srv.start()
        base = f"http://localhost:{srv.port}"
        data = os.urandom(300 * 1024)  # 3 chunks
        assert requests.put(f"{base}/warm").status_code == 200
        assert requests.put(
            f"{base}/warm/obj", data=data
        ).status_code == 200
        def by_plane() -> dict:
            out: dict = {}
            for k, v in M.net_bytes_received_total.snapshot().items():
                out[k[0]] = out.get(k[0], 0) + v
            return out

        r0 = by_plane()
        r = requests.get(f"{base}/warm/obj", timeout=30)
        assert r.status_code == 200 and r.content == data
        r1 = by_plane()
        native_delta = r1.get("native", 0) - r0.get("native", 0)
        assert native_delta >= len(data), (
            f"chunk bytes did not ride the native plane: {native_delta}"
        )
        assert vs.net_plane.needle_requests >= 3
        # plane off: the Python-HTTP fallback serves identical bytes
        os.environ["SEAWEED_CHUNK_NET_PLANE"] = "0"
        try:
            r = requests.get(f"{base}/warm/obj", timeout=30)
            assert r.status_code == 200 and r.content == data
        finally:
            os.environ.pop("SEAWEED_CHUNK_NET_PLANE", None)
    finally:
        for closer in (
            (lambda: srv.stop()) if srv is not None else None,
            (lambda: filer.close()) if filer is not None else None,
            vs.stop,
            master.stop,
        ):
            if closer is None:
                continue
            try:
                closer()
            except Exception:
                pass


def test_amz_date_parse_stays_strict():
    """The fast fixed-layout date parse must refuse everything strptime
    refused: signs, padding, non-ASCII digits, wrong separators."""
    ok = s3auth._parse_amz_date("20260804T120000Z")
    assert (ok.year, ok.hour) == (2026, 12)
    for bad in (
        "2026080aT120000Z",      # non-digit
        "20260804 120000Z",      # wrong separator
        "20260804T120000z",      # wrong terminator
        "20260804T1200007",      # no Z
        " 0260804T120000Z",      # padding int() would accept
        "+026080,T120000Z",      # sign int() would accept
        "２０２６０８０４T120000Z",  # full-width digits
        "20260804T120000ZZ",     # wrong length
        "20261304T120000Z",      # month 13: range check
    ):
        with pytest.raises(ValueError):
            s3auth._parse_amz_date(bad)
