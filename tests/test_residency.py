"""Multi-tenant overload safety (PR 16): the process-wide per-chip
residency ledger, tenant fairness, and graceful shedding.

Load-bearing properties:

- residency invariant: N scopes sharing one physical chip never hold
  more concurrent device slots than the chip budget — proven from the
  ledger's own high-watermark ground truth AND an independent
  occupancy counter, including under the chaos matrix (breaker flap,
  scope churn, armed fault points);
- wide streams charge every chip: a mesh backend's batch holds a slot
  on EACH device it spans, atomically;
- fairness: deficit-weighted ranking bounds the well-behaved tenant's
  wait under a storm, and the starvation bound guarantees background
  classes are slowed, never starved;
- graceful shedding: background defers first (scrub before recovery),
  foreground is never deferred at the ledger, and shed_advice names
  ONLY the over-share tenant (per-tenant, not per-server) — with open
  breakers escalating the shed level;
- front-end propagation: the S3 gateway turns shed advice into the
  SlowDown + Retry-After contract before auth;
- heat persistence: per-volume heat counters survive a clean restart
  behind a generation fence (the PR 15 carried item).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import EcVolume, ECContext, ec_encode_volume
from seaweedfs_tpu.ec.device_queue import (
    DEFAULT_WINDOW,
    QueueScope,
    ResidencyLedger,
    _residency_keys,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


class FakeChip:
    """Pinned-backend stand-in: instances sharing a label share one
    physical residency key, exactly like two scopes' queues on one
    pooled chip."""

    def __init__(self, label="chip:0", breaker=None):
        self.chip_label = label
        if breaker is not None:
            self.breaker = breaker


class FakeBreaker:
    def __init__(self, state="closed"):
        self.state = state


class FakeMeshRS:
    def __init__(self, labels):
        self._labels = tuple(labels)

    def device_labels(self):
        return self._labels


class FakeMeshBackend:
    """Mesh-backend stand-in: no chip_label, a _mesh_rs spanning many
    devices — _residency_keys must charge them all."""

    chip_label = ""

    def __init__(self, labels):
        self._mesh_rs = FakeMeshRS(labels)


def _storm(scopes, stop, device_work, errors):
    """One storm worker: dispatch foreground batches on a fresh queue
    under each scope until stopped."""
    for scope in scopes:
        if stop.is_set():
            break
        q = scope.for_backend(FakeChip())
        s = q.stream("foreground")
        try:
            while not stop.is_set():
                try:
                    t, _ = s.dispatch(device_work, 1)
                except faults.InjectedIOError:
                    continue  # armed chaos fault: retry like a caller
                s.release(t)
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)
        finally:
            s.close()


# ------------------------------------------------------------ invariant


def test_two_scopes_one_chip_respect_shared_budget():
    """The tentpole contract: per-scope windows become SUB-budgets —
    two scopes with window 4 each on one chip never exceed the chip's
    physical budget of 2, proven by the ledger watermark and an
    independent occupancy counter."""
    ledger = ResidencyLedger(budget=2)
    occ = {"now": 0, "peak": 0}
    occ_lock = threading.Lock()

    def device_work():
        with occ_lock:
            occ["now"] += 1
            occ["peak"] = max(occ["peak"], occ["now"])
        time.sleep(0.002)
        with occ_lock:
            occ["now"] -= 1

    scopes = [
        QueueScope(window=4, tenant=t, residency=ledger)
        for t in ("a", "b")
    ]
    streams = []
    for scope in scopes:
        q = scope.for_backend(FakeChip())
        assert q.res_keys == ("chip:0",)
        streams.append(q.stream("foreground"))
    threads = []
    for s in streams:
        def run(s=s):
            for _ in range(15):
                t, _ = s.dispatch(device_work, 1)
                s.release(t)
        for _ in range(4):
            th = threading.Thread(target=run)
            th.start()
            threads.append(th)
    for th in threads:
        th.join(timeout=30)
    for s in streams:
        s.close()
    snap = ledger.snapshot()
    chip = snap["chips"]["chip:0"]
    assert chip["max_inflight"] <= 2, snap
    assert occ["peak"] <= 2, occ
    assert chip["inflight"] == 0 and snap["waiters"] == 0  # no leak
    assert chip["admitted"] == 2 * 4 * 15
    assert set(snap["tenants"]) >= {"a", "b"}


def test_mesh_backend_charges_every_chip_atomically():
    """A wide (mesh) stream's batch holds a slot on EVERY chip it
    spans: it cannot admit while any spanned chip is full, and while
    in flight it counts against each chip's budget."""
    ledger = ResidencyLedger(budget=1)
    scope = QueueScope(window=4, tenant="wide", residency=ledger)
    mesh = FakeMeshBackend(["c0", "c1"])
    assert _residency_keys(mesh) == ("c0", "c1")
    q = scope.for_backend(mesh)
    assert q.res_keys == ("c0", "c1")

    # pin c1: the mesh admit must block even though c0 is free
    pin = ledger.acquire(("c1",), "other", "foreground", 1)
    s = q.stream("foreground")
    admitted = threading.Event()
    holder = {}

    def wide():
        t, _ = s.dispatch(lambda: None, 3)
        holder["t"] = t
        admitted.set()

    th = threading.Thread(target=wide, daemon=True)
    th.start()
    assert not admitted.wait(timeout=0.3), "admitted past a full chip"
    ledger.release(pin)
    assert admitted.wait(timeout=10), "mesh admit never granted"
    loads = ledger.loads()
    assert loads["c0"] == 3 and loads["c1"] == 3  # charged on BOTH
    s.release(holder["t"])
    th.join(timeout=5)
    assert all(v == 0 for v in ledger.loads().values())
    s.close()


@pytest.mark.parametrize("seed", [0x16A, 0x16B, 0x16C])
def test_property_seeded_arrivals_budget_and_no_starvation(seed):
    """Property over seeded multi-tenant arrival orders: for random
    tenants/priorities/costs/chips, (1) per-chip in-flight never
    exceeds the budget and (2) no tenant starves — every tenant's
    batches all complete, none waiting past the fairness bound."""
    rng = np.random.default_rng(seed)
    budget = int(rng.integers(1, 4))
    ledger = ResidencyLedger(budget=budget, starve_s=5.0)
    tenants = [f"t{i}" for i in range(int(rng.integers(2, 5)))]
    chips = [f"chip:{i}" for i in range(int(rng.integers(1, 3)))]
    priorities = ["foreground", "recovery", "scrub"]
    scopes = {
        t: QueueScope(window=DEFAULT_WINDOW, tenant=t, residency=ledger)
        for t in tenants
    }
    ops = [
        (
            tenants[int(rng.integers(len(tenants)))],
            chips[int(rng.integers(len(chips)))],
            priorities[int(rng.integers(len(priorities)))],
            int(rng.integers(1, 50)),
        )
        for _ in range(60)
    ]
    waits: dict[str, list[float]] = {t: [] for t in tenants}
    waits_lock = threading.Lock()
    errors: list[Exception] = []

    def run_op(tenant, chip, priority, cost):
        try:
            q = scopes[tenant].for_backend(FakeChip(chip))
            s = q.stream(priority)
            try:
                t0 = time.perf_counter()
                t, _ = s.dispatch(lambda: time.sleep(0.001), cost)
                with waits_lock:
                    waits[tenant].append(time.perf_counter() - t0)
                s.release(t)
            finally:
                s.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = []
    for op in ops:
        th = threading.Thread(target=run_op, args=op)
        th.start()
        threads.append(th)
        if rng.random() < 0.3:
            time.sleep(0.001)  # jittered arrival order
    for th in threads:
        th.join(timeout=60)
    assert not errors, errors
    snap = ledger.snapshot()
    for chip, st in snap["chips"].items():
        assert st["max_inflight"] <= budget, (chip, st)
        assert st["inflight"] == 0, (chip, st)  # no leak
    done = {t for t, ws in waits.items() if ws}
    submitted = {t for t, _c, _p, _cost in ops}
    assert done == submitted  # every tenant's work completed
    worst = max(w for ws in waits.values() for w in ws)
    # the starvation bound (plus scheduling slack) caps every wait
    assert worst < ledger.starve_s + 10.0, worst


# ------------------------------------------------------------- shedding


def test_background_defers_before_foreground_and_never_starves():
    """Graceful shedding order: at shed level 1+ a scrub waiter yields
    the freed slot to a LATER foreground waiter; the starvation bound
    then gets scrub in anyway."""
    ledger = ResidencyLedger(
        budget=1, shed_after_s=0.05, starve_s=1.0, tenant_window_s=10.0
    )
    hold = ledger.acquire(("c0",), "fg", "foreground", 1)
    got: list[str] = []
    lock = threading.Lock()

    def take(priority, tag):
        t = ledger.acquire(("c0",), tag, priority, 1, timeout=30.0)
        with lock:
            got.append(tag)
        ledger.release(t)

    scrub_th = threading.Thread(target=take, args=("scrub", "scrub"))
    scrub_th.start()
    time.sleep(0.2)  # chip full + waiter: level reaches 1 (scrub defers)
    assert ledger.shed_level() >= 1
    fg_th = threading.Thread(target=take, args=("foreground", "fg2"))
    fg_th.start()
    time.sleep(0.05)
    ledger.release(hold)
    fg_th.join(timeout=10)
    scrub_th.join(timeout=10)
    # foreground (arrived later) got the slot first; scrub still ran
    assert got == ["fg2", "scrub"], got


def test_open_breaker_escalates_and_starvation_bound_escapes():
    """A chip whose fallback breaker is OPEN is already degraded:
    background admission defers there even with free slots, until the
    starvation bound lets it through; foreground is untouched."""
    ledger = ResidencyLedger(budget=4, starve_s=0.15)
    brk = FakeBreaker("open")
    ledger.register_breaker("c0", brk)
    t_fg = ledger.acquire(("c0",), "t", "foreground", 1)
    assert t_fg.wait_s < 0.1  # foreground admits immediately
    ledger.release(t_fg)
    t0 = time.perf_counter()
    t_scrub = ledger.acquire(("c0",), "t", "scrub", 1, timeout=30.0)
    waited = time.perf_counter() - t0
    ledger.release(t_scrub)
    # deferred by the open breaker, released by the starvation bound
    assert 0.1 <= waited < 5.0, waited
    brk.state = "closed"
    t2 = ledger.acquire(("c0",), "t", "scrub", 1)
    assert t2.wait_s < 0.1  # breaker closed: no deferral
    ledger.release(t2)


def test_shed_advice_names_only_the_overshare_tenant():
    """Per-tenant, not per-server: at full shed the storm tenant gets
    Retry-After advice while the victim keeps serving, and the shed
    counter lands in the snapshot."""
    ledger = ResidencyLedger(
        budget=1, shed_after_s=0.02, shed_retry_s=3.0,
        tenant_window_s=30.0, starve_s=60.0,
    )
    # storm builds windowed admitted cost; victim a sliver
    for _ in range(5):
        ledger.release(ledger.acquire(("c0",), "storm", "foreground", 100))
    ledger.release(ledger.acquire(("c0",), "victim", "foreground", 1))
    # chip full + a queued waiter long enough for level 3
    hold = ledger.acquire(("c0",), "storm", "foreground", 100)
    waiter_done = threading.Event()

    def waiter():
        t = ledger.acquire(("c0",), "storm", "foreground", 1, timeout=30.0)
        ledger.release(t)
        waiter_done.set()

    th = threading.Thread(target=waiter, daemon=True)
    th.start()
    deadline = time.time() + 5.0
    while ledger.shed_level() < 3:
        assert time.time() < deadline, "never reached full shed"
        time.sleep(0.01)
    assert ledger.shed_advice("storm") == 3.0
    assert ledger.shed_advice("victim") is None
    assert ledger.shed_advice("idle-tenant") is None
    snap = ledger.snapshot()
    assert snap["tenants"]["storm"]["shed"] >= 1
    assert snap["chips"]["c0"]["pressure"] == 3
    ledger.release(hold)
    assert waiter_done.wait(timeout=10)


# ---------------------------------------------------------- chaos matrix


def test_tenant_storm_chaos_matrix():
    """The fault-injected tier-1 storm: a storm tenant saturates one
    chip through churning scopes (created/destroyed mid-storm), the
    chip's breaker flaps, and the `ec.residency.acquire` fault point
    is armed with injected IOErrors. Afterwards the ledger's own stats
    must prove the residency invariant, the victim's p99 must be
    bounded, and no slot may leak."""
    ledger = ResidencyLedger(budget=3, shed_after_s=0.05)
    brk = FakeBreaker("closed")
    ledger.register_breaker("chip:0", brk)
    stop = threading.Event()
    errors: list[Exception] = []

    def device_work():
        time.sleep(0.001)

    # chaos 1: armed fault point on the acquire seam (every 13th admit
    # anywhere raises before any charge — callers retry, nothing leaks)
    h = faults.inject(
        "ec.residency.acquire", faults.io_error(), when=faults.every(13)
    )
    # chaos 2: breaker flap
    def flap():
        while not stop.is_set():
            brk.state = "open" if brk.state == "closed" else "closed"
            time.sleep(0.02)

    flapper = threading.Thread(target=flap, daemon=True)
    flapper.start()
    # chaos 3: scope churn — each storm worker walks a list of scopes,
    # and fresh scopes keep being created (old ones dropped) mid-storm
    storm_scopes = [
        QueueScope(window=4, tenant="storm", residency=ledger)
        for _ in range(20)
    ]
    storm_threads = [
        threading.Thread(
            target=_storm,
            args=(storm_scopes[i::4], stop, device_work, errors),
            daemon=True,
        )
        for i in range(4)
    ]
    try:
        for th in storm_threads:
            th.start()
        victim_scope = QueueScope(
            window=4, tenant="victim", residency=ledger
        )
        vq = victim_scope.for_backend(FakeChip())
        vs = vq.stream("foreground")
        lat = []
        try:
            for _ in range(40):
                t0 = time.perf_counter()
                try:
                    t, _ = vs.dispatch(device_work, 1)
                except faults.InjectedIOError:
                    continue
                lat.append(time.perf_counter() - t0)
                vs.release(t)
        finally:
            vs.close()
    finally:
        stop.set()
        h.remove()
        for th in storm_threads:
            th.join(timeout=15)
        flapper.join(timeout=5)
    assert not errors, errors
    assert h.fired > 0, "chaos fault point never fired"
    snap = ledger.snapshot()
    chip = snap["chips"]["chip:0"]
    # the invariant, from ledger-stats ground truth
    assert chip["max_inflight"] <= 3, snap
    assert chip["inflight"] == 0 and snap["waiters"] == 0, snap
    assert len(lat) >= 30
    p99 = sorted(lat)[max(int(len(lat) * 0.99) - 1, 0)]
    assert p99 < 2.0, f"victim p99 {p99:.3f}s unbounded under storm"


# ----------------------------------------------------- front-end + obs


def test_s3_gateway_sheds_per_tenant(monkeypatch, tmp_path):
    """Foreground backpressure reaches the PR 11 front end: when shed
    advice names THIS gateway's tenant, object data-plane requests get
    503 SlowDown + Retry-After before auth; bucket/control ops and
    other tenants keep serving."""
    import requests

    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.s3.server import S3Server
    from conftest import allocate_port

    from seaweedfs_tpu.ec import device_queue as dq

    filer = Filer(MemoryStore(), master="localhost:1")
    srv = S3Server(
        filer, ip="localhost", port=allocate_port(), tenant="tester"
    )
    srv.start()
    base = f"http://localhost:{srv.port}"
    try:
        monkeypatch.setattr(
            dq, "shed_advice", lambda t: 2.5 if t == "tester" else None
        )
        r = requests.get(f"{base}/b/obj", timeout=10)
        assert r.status_code == 503
        assert r.headers.get("Retry-After") == "2"
        assert "SlowDown" in r.text and "tester" in r.text
        # control plane stays up mid-storm
        r = requests.get(f"{base}/", timeout=10)
        assert r.status_code != 503
        # advice cleared: the object plane serves again (404: no data)
        monkeypatch.setattr(dq, "shed_advice", lambda t: None)
        r = requests.get(f"{base}/b/obj", timeout=10)
        assert r.status_code == 404
    finally:
        srv.stop()
        filer.close()


def test_residency_observability_surfaces():
    """residency_snapshot() is wired into the gateway debug summary,
    and the sw_ec_residency_* metrics exist in the registry (the
    metrics lint covers naming; this covers presence)."""
    from seaweedfs_tpu.ec.device_queue import residency_snapshot
    from seaweedfs_tpu.utils import metrics as M

    snap = residency_snapshot()
    assert isinstance(snap, dict)
    assert "residency" in M.gateway_summary()
    rendered = M.REGISTRY.render().decode()
    for name in (
        "sw_ec_residency_budget",
        "sw_ec_residency_inflight",
        "sw_ec_residency_pressure",
        "sw_ec_residency_admitted_total",
        "sw_ec_residency_shed_total",
        "sw_ec_residency_wait_seconds_total",
    ):
        assert name in rendered, name


# ------------------------------------------------------ heat persistence


CTX = ECContext(4, 2)


def _make_ec_volume(tmp_path, vid=1):
    rng = np.random.default_rng(0x4EA7)
    v = Volume(str(tmp_path), vid)
    for i in range(1, 6):
        data = rng.integers(0, 256, 20_000, dtype=np.uint8).tobytes()
        v.write_needle(Needle(cookie=0x100 + i, needle_id=i, data=data))
    v.close()
    base = Volume.base_file_name(str(tmp_path), "", vid)
    ec_encode_volume(base, CTX)
    return base


def test_heat_counters_survive_restart(tmp_path):
    """PR 15 carried item (b): lifetime heat counters persist across a
    clean close/reopen, so the master's first post-restart delta window
    sees a monotonic counter instead of a reset."""
    base = _make_ec_volume(tmp_path)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    ev.bytes_read = 123_456
    ev.bytes_reconstructed = 7_890
    ev.close()
    assert os.path.exists(base + ".heat")
    ev2 = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        assert ev2.bytes_read == 123_456
        assert ev2.bytes_reconstructed == 7_890
    finally:
        ev2.close()


def test_heat_sidecar_generation_fence(tmp_path):
    """A .heat blob from a different encode generation (re-created
    volume) must never resurrect: counters start cold."""
    base = _make_ec_volume(tmp_path)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    ev.bytes_read = 999
    ev.close()
    blob = json.load(open(base + ".heat"))
    blob["gen"] = (blob.get("gen") or 0) + 1
    with open(base + ".heat", "w") as f:
        json.dump(blob, f)
    ev2 = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        assert ev2.bytes_read == 0 and ev2.bytes_reconstructed == 0
    finally:
        ev2.close()


def test_heat_sidecar_corrupt_is_cold_start(tmp_path):
    base = _make_ec_volume(tmp_path)
    with open(base + ".heat", "w") as f:
        f.write("{not json")
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        assert ev.bytes_read == 0
    finally:
        ev.close()
