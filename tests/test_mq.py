"""MQ tests: partition log, pub/sub, offsets, segment spill + recovery.

Reference models: weed/mq broker pub/sub suites and log_buffer tests.
"""

import threading
import time

import pytest

from seaweedfs_tpu.mq import MqBrokerServer, MqClient, PartitionLog
from seaweedfs_tpu.mq.log_buffer import decode_records, encode_record


from conftest import allocate_port as free_port


# ---------------------------------------------------------------- log unit


def test_partition_log_append_read():
    log = PartitionLog(segment_records=10)
    for i in range(25):
        assert log.append(i, b"k%d" % i, b"v%d" % i) == i
    recs = log.read_from(0, max_records=100)
    assert [r[0] for r in recs] == list(range(25))
    recs = log.read_from(20)
    assert [r[0] for r in recs] == [20, 21, 22, 23, 24]
    assert log.read_from(25) == []


def test_partition_log_spill_and_load():
    segments: dict[int, bytes] = {}
    log = PartitionLog(
        segment_records=4,
        spill=lambda seg, raw: segments.__setitem__(seg, raw),
        load=segments.get,
    )
    for i in range(11):
        log.append(i * 10, b"", b"v%d" % i)
    assert sorted(segments) == [0, 1]  # two sealed segments, 3 in tail
    # reads spanning sealed + tail
    recs = log.read_from(2, max_records=100)
    assert [r[0] for r in recs] == list(range(2, 11))
    assert recs[0][3] == b"v2"
    # record codec roundtrip
    raw = encode_record(7, 123, b"key", b"value")
    assert list(decode_records(raw)) == [(7, 123, b"key", b"value")]


def test_partition_log_wait():
    log = PartitionLog()
    hit = []

    def waiter():
        hit.append(log.wait_for(0, timeout=5.0))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    log.append(1, b"", b"x")
    t.join(timeout=2)
    assert hit == [True]


# ------------------------------------------------------------- broker e2e


@pytest.fixture
def broker():
    srv = MqBrokerServer(ip="localhost", grpc_port=free_port())
    srv.start()
    c = MqClient(f"localhost:{srv.grpc_port}")
    yield srv, c
    c.close()
    srv.stop()


def test_pub_sub_roundtrip(broker):
    srv, c = broker
    c.configure_topic("events", partitions=4)
    assert ("default", "events", 4) in c.topics()
    # keyed publishes land deterministically on one partition
    parts = {c.publish("events", b"m%d" % i, key=b"user-42")[0] for i in range(5)}
    assert len(parts) == 1
    part = parts.pop()
    got = [r.message.value for r in c.subscribe("events", part, start_offset=0)]
    assert got == [b"m0", b"m1", b"m2", b"m3", b"m4"]
    # explicit partition
    p, off = c.publish("events", b"direct", partition=2)
    assert p == 2 and off == (0 if part != 2 else 5)
    # unknown topic errors
    with pytest.raises(RuntimeError):
        c.publish("nope", b"x")


def test_consumer_group_offsets(broker):
    srv, c = broker
    c.configure_topic("work", partitions=1)
    for i in range(10):
        c.publish("work", b"job%d" % i, partition=0)
    recs = list(c.subscribe("work", 0, start_offset=0))
    assert len(recs) == 10
    c.commit("work", 0, "workers", recs[4].offset + 1)
    assert c.committed("work", 0, "workers") == 5
    # resuming from the committed offset via consumer_group
    rest = [
        r.message.value
        for r in c.subscribe("work", 0, start_offset=-1, consumer_group="workers")
    ]
    assert rest == [b"job5", b"job6", b"job7", b"job8", b"job9"]


def test_follow_streams_new_messages(broker):
    srv, c = broker
    c.configure_topic("live", partitions=1)
    got = []

    def consume():
        for r in c.subscribe("live", 0, start_offset=0, follow=True, timeout=10):
            got.append(r.message.value)
            if len(got) == 3:
                return

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.2)
    for i in range(3):
        c.publish("live", b"tick%d" % i, partition=0)
        time.sleep(0.05)
    t.join(timeout=10)
    assert got == [b"tick0", b"tick1", b"tick2"]


def test_partial_segment_flush_then_append():
    """A mid-segment flush (shutdown) followed by appends must not lose
    the flushed records when the segment slot is resealed."""
    segments: dict[int, bytes] = {}
    log = PartitionLog(
        segment_records=4,
        spill=lambda seg, raw: segments.__setitem__(seg, raw),
        load=segments.get,
    )
    for i in range(9):  # segs 0,1 sealed; record 8 in tail
        log.append(i, b"", b"v%d" % i)
    log.flush()  # partial seg 2 holds record 8
    # simulate restart: new log resumes at offset 9
    log2 = PartitionLog(
        segment_records=4,
        spill=lambda seg, raw: segments.__setitem__(seg, raw),
        load=segments.get,
        next_offset=9,
        earliest_offset=0,
    )
    for i in range(9, 14):  # crosses the seg-2/seg-3 boundary
        log2.append(i, b"", b"v%d" % i)
    log2.flush()
    recs = log2.read_from(0, max_records=100)
    assert [r[0] for r in recs] == list(range(14))
    assert [r[3] for r in recs] == [b"v%d" % i for i in range(14)]


def test_broker_persistence_via_filer(tmp_path):
    """Segments + offsets survive a broker restart when filer-backed."""
    from seaweedfs_tpu.filer import Filer, SqliteStore
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    fport = free_port()
    filer = Filer(SqliteStore(str(tmp_path / "f.db")), master=f"localhost:{mport}")
    fsrv = FilerServer(filer, ip="localhost", port=fport)
    fsrv.start()
    try:
        srv = MqBrokerServer(
            ip="localhost",
            grpc_port=free_port(),
            filer=f"localhost:{fport}",
            segment_records=4,
        )
        srv.start()
        c = MqClient(f"localhost:{srv.grpc_port}")
        c.configure_topic("durable", partitions=2)
        for i in range(9):
            c.publish("durable", b"msg%d" % i, partition=0)
        c.commit("durable", 0, "g1", 3)
        c.close()
        srv.stop()  # flushes the tail segment

        srv2 = MqBrokerServer(
            ip="localhost",
            grpc_port=free_port(),
            filer=f"localhost:{fport}",
            segment_records=4,
        )
        srv2.start()
        c2 = MqClient(f"localhost:{srv2.grpc_port}")
        assert ("default", "durable", 2) in c2.topics()
        assert c2.committed("durable", 0, "g1") == 3
        info = {p.partition: p.next_offset for p in c2.partition_info("durable")}
        assert info[0] == 9
        got = [r.message.value for r in c2.subscribe("durable", 0, start_offset=0)]
        assert got == [b"msg%d" % i for i in range(9)]
        # appends continue with dense offsets
        _, off = c2.publish("durable", b"after-restart", partition=0)
        assert off == 9
        c2.close()
        srv2.stop()
    finally:
        fsrv.stop()
        vs.stop()
        master.stop()


def test_mq_agent_sessions():
    """MQ agent (reference weed/mq/agent): session facade — start a
    publish session (auto-configures the topic), stream records with
    per-record offset acks, stream a subscription, commit acks as
    group offsets, refuse unknown sessions."""
    import threading

    import grpc as _grpc

    from conftest import allocate_port
    from seaweedfs_tpu.mq.agent import MqAgentServer
    from seaweedfs_tpu.mq.broker import MqBrokerServer
    from seaweedfs_tpu.pb import mq_pb2 as amq
    from seaweedfs_tpu.pb import rpc as _rpc

    broker = MqBrokerServer(ip="127.0.0.1", grpc_port=allocate_port())
    broker.start()
    agent = MqAgentServer(f"127.0.0.1:{broker.grpc_port}", ip="127.0.0.1")
    agent.start()
    try:
        ch = _grpc.insecure_channel(f"127.0.0.1:{agent.port}")
        stub = _rpc.Stub(ch, _rpc.MQ_AGENT_SERVICE)
        r = stub.StartPublishSession(
            amq.AgentStartPublishRequest(
                name="agented", partition_count=1, publisher_name="t"
            ),
            timeout=10,
        )
        assert not r.error and r.session_id > 0
        sid = r.session_id

        def pubs():
            for i in range(10):
                yield amq.AgentPublishRequest(
                    session_id=sid if i == 0 else 0,
                    key=b"k%d" % i,
                    value=b"v%d" % i,
                )

        acks = list(stub.PublishRecord(pubs(), timeout=30))
        assert [a.ack_sequence for a in acks] == list(range(1, 11))
        assert all(not a.error for a in acks)
        assert [a.offset for a in acks] == list(range(10))

        # subscribe from 0, ack the last offset as the group position
        import queue as _q

        reqs: "_q.Queue" = _q.Queue()
        reqs.put(
            amq.AgentSubscribeRequest(
                init=amq.AgentSubscribeInit(
                    consumer_group="g1", name="agented", partition=0,
                    start_offset=0,
                )
            )
        )

        def req_iter():
            while True:
                item = reqs.get()
                if item is None:
                    return
                yield item

        got = []
        # Consume to NATURAL completion (no break): abandoning the
        # response iterator cancels the RPC, and under load the
        # cancellation can outrun gRPC's sender thread — discarding the
        # queued final ack before it ever hits the wire (the "ack never
        # committed" flake). Half-close promptly after the final ack so
        # the agent's ack pump drains, commits, and returns.
        for resp in stub.SubscribeRecord(req_iter(), timeout=30):
            if resp.is_end_of_stream:
                continue
            got.append((resp.offset, bytes(resp.value)))
            if resp.offset == 9:
                reqs.put(amq.AgentSubscribeRequest(ack_sequence=10))
                reqs.put(None)
        assert [o for o, _ in got] == list(range(10))
        assert got[3][1] == b"v3"
        # the ack committed the group offset on the broker. The wait is
        # load-tolerant (a loaded tier-1 run schedules the agent's ack
        # pump late); the agent side no longer drops an in-flight final
        # ack after a fixed 2 s grace, so this converges.
        deadline = time.time() + 30
        while (
            broker.broker.fetch_offset("default", "agented", 0, "g1") != 10
        ):
            assert time.time() < deadline, "ack never committed"
            time.sleep(0.1)

        # close + unknown-session refusal
        assert not stub.ClosePublishSession(
            amq.AgentClosePublishRequest(session_id=sid), timeout=10
        ).error
        bad = list(
            stub.PublishRecord(
                iter([amq.AgentPublishRequest(session_id=sid, value=b"x")]),
                timeout=10,
            )
        )
        assert bad and "unknown session" in bad[0].error
        ch.close()
    finally:
        agent.stop()
        broker.stop()


def test_mq_agent_ackless_half_close():
    """An ack-less consumer that sends ONLY init and half-closes its
    request stream must still receive every record (review r5: the ack
    pump ending is a normal half-close, not a disconnect)."""
    import grpc as _grpc

    from conftest import allocate_port
    from seaweedfs_tpu.mq.agent import MqAgentServer
    from seaweedfs_tpu.mq.broker import MqBrokerServer
    from seaweedfs_tpu.mq.client import MqClient
    from seaweedfs_tpu.pb import mq_pb2 as amq
    from seaweedfs_tpu.pb import rpc as _rpc

    broker = MqBrokerServer(ip="127.0.0.1", grpc_port=allocate_port())
    broker.start()
    agent = MqAgentServer(f"127.0.0.1:{broker.grpc_port}", ip="127.0.0.1")
    agent.start()
    try:
        c = MqClient(f"127.0.0.1:{broker.grpc_port}")
        c.configure_topic("halfclose", partitions=1)
        for i in range(10):
            c.publish("halfclose", key=b"", value=b"r%d" % i)
        ch = _grpc.insecure_channel(f"127.0.0.1:{agent.port}")
        stub = _rpc.Stub(ch, _rpc.MQ_AGENT_SERVICE)
        got = []
        for resp in stub.SubscribeRecord(
            iter([amq.AgentSubscribeRequest(
                init=amq.AgentSubscribeInit(
                    name="halfclose", partition=0, start_offset=0
                )
            )]),
            timeout=30,
        ):
            if resp.is_end_of_stream:
                break
            got.append(resp.offset)
        assert got == list(range(10)), got
        ch.close()
    finally:
        agent.stop()
        broker.stop()
