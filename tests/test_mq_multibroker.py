"""Multi-broker MQ: partition balancing, transparent forwarding,
follower replication, leader-death failover.

Reference: weed/mq/pub_balancer + broker_grpc_pub_follow.go.
"""

import time

import grpc
import pytest

from conftest import allocate_port
from seaweedfs_tpu.mq.balancer import BrokerBalancer
from seaweedfs_tpu.mq.broker import MqBrokerServer
from seaweedfs_tpu.pb import mq_pb2 as mq
from seaweedfs_tpu.pb import rpc


def _stub(port: int):
    return rpc.mq_stub(grpc.insecure_channel(f"localhost:{port}"))


@pytest.fixture
def trio():
    ports = [allocate_port() for _ in range(3)]
    peers = [f"localhost:{p}" for p in ports]
    brokers = [
        MqBrokerServer(
            ip="localhost", grpc_port=p, peers=peers,
        )
        for p in ports
    ]
    for b in brokers:
        b.balancer.ping_interval = 0.2
        b.start()
    yield brokers, ports
    for b in brokers:
        try:
            b.stop()
        except Exception:
            pass


def test_hrw_assignment_is_consistent_and_spread():
    peers = ["h1:1", "h2:2", "h3:3"]
    bals = [BrokerBalancer(p, peers) for p in peers]
    a0 = bals[0].assignments("default", "t", 16)
    for b in bals[1:]:
        assert b.assignments("default", "t", 16) == a0
    leaders = {leader for _p, leader, _f in a0}
    assert len(leaders) >= 2, "HRW should spread partitions across brokers"
    for _p, leader, follower in a0:
        assert follower and follower != leader
    # removing the leader promotes exactly the old follower
    for p, leader, follower in a0:
        survivor = BrokerBalancer(
            "x:0", [b for b in peers if b != leader] + ["x:0"]
        )
        survivor._live = set(b for b in peers if b != leader)
        new_leader, _nf = survivor.assignment("default", "t", p)
        assert new_leader == follower


def test_publish_forwarding_and_replication(trio):
    brokers, ports = trio
    stubs = [_stub(p) for p in ports]
    stubs[0].ConfigureTopic(
        mq.ConfigureTopicRequest(
            topic=mq.Topic(name="spread"), partition_count=6
        )
    )
    # configure broadcast: every broker knows the topic
    for s in stubs:
        topics = s.ListTopics(mq.ListTopicsRequest())
        assert any(t.topic.name == "spread" for t in topics.topics)
    # publish every partition through broker 0 only — forwarding must
    # land each on its HRW leader
    for part in range(6):
        r = stubs[0].Publish(
            mq.PublishRequest(
                topic=mq.Topic(name="spread"),
                partition=part,
                message=mq.DataMessage(key=b"k", value=b"v%d" % part),
            )
        )
        assert not r.error
        assert r.offset == 0
    lookup = stubs[1].LookupTopicBrokers(
        mq.LookupTopicBrokersRequest(topic=mq.Topic(name="spread"))
    )
    assert len(lookup.assignments) == 6
    by_part = {a.partition: a for a in lookup.assignments}
    # each partition's record lives on its leader AND its follower
    for part in range(6):
        a = by_part[part]
        leader_idx = ports.index(int(a.leader.rsplit(":", 1)[1]))
        follower_idx = ports.index(int(a.follower.rsplit(":", 1)[1]))
        for idx in (leader_idx, follower_idx):
            st = brokers[idx].broker.topic("default", "spread")
            recs = st.logs[part].read_from(0)
            assert [v for _o, _t, _k, v in recs] == [b"v%d" % part], (
                f"partition {part} missing on broker {idx}"
            )
        # and is absent from the third broker
        third = ({0, 1, 2} - {leader_idx, follower_idx}).pop()
        st = brokers[third].broker.topic("default", "spread")
        assert st.logs[part].read_from(0) == []


def test_subscribe_proxies_to_leader(trio):
    brokers, ports = trio
    stubs = [_stub(p) for p in ports]
    stubs[0].ConfigureTopic(
        mq.ConfigureTopicRequest(
            topic=mq.Topic(name="sub"), partition_count=3
        )
    )
    for i in range(9):
        stubs[i % 3].Publish(
            mq.PublishRequest(
                topic=mq.Topic(name="sub"),
                partition=i % 3,
                message=mq.DataMessage(value=b"m%d" % i),
            )
        )
    # subscribe to every partition through ONE broker; streams proxy
    got = []
    for part in range(3):
        for rec in stubs[2].Subscribe(
            mq.SubscribeRequest(
                topic=mq.Topic(name="sub"), partition=part, start_offset=0
            )
        ):
            if rec.end_of_stream:
                break
            got.append(rec.message.value)
    assert sorted(got) == [b"m%d" % i for i in range(9)]


def test_replica_gap_is_backfilled(trio):
    """A follower that missed records (down/partitioned) reports the
    gap and the leader backfills — silent holes would be lost acked
    records after promotion."""
    brokers, ports = trio
    stubs = [_stub(p) for p in ports]
    stubs[0].ConfigureTopic(
        mq.ConfigureTopicRequest(
            topic=mq.Topic(name="gap"), partition_count=1
        )
    )
    lookup = stubs[0].LookupTopicBrokers(
        mq.LookupTopicBrokersRequest(topic=mq.Topic(name="gap"))
    )
    a = lookup.assignments[0]
    leader_idx = ports.index(int(a.leader.rsplit(":", 1)[1]))
    follower_idx = ports.index(int(a.follower.rsplit(":", 1)[1]))
    # simulate missed replication: append directly on the leader's log
    st = brokers[leader_idx].broker.topic("default", "gap")
    for i in range(5):
        st.logs[0].append(1, b"", b"missed%d" % i)
    # a normal publish now hits the follower with offset 5; the
    # follower reports gap:0 and the leader must backfill 0..4
    r = stubs[leader_idx].Publish(
        mq.PublishRequest(
            topic=mq.Topic(name="gap"),
            partition=0,
            message=mq.DataMessage(value=b"live"),
        )
    )
    assert not r.error and r.offset == 5
    fst = brokers[follower_idx].broker.topic("default", "gap")
    recs = fst.logs[0].read_from(0)
    assert [v for _o, _t, _k, v in recs] == [
        b"missed0", b"missed1", b"missed2", b"missed3", b"missed4", b"live",
    ]


def test_consumer_offsets_route_to_leader(trio):
    brokers, ports = trio
    stubs = [_stub(p) for p in ports]
    stubs[0].ConfigureTopic(
        mq.ConfigureTopicRequest(
            topic=mq.Topic(name="offs"), partition_count=1
        )
    )
    # commit through one broker, fetch through another: same value
    stubs[0].CommitOffset(
        mq.CommitOffsetRequest(
            topic=mq.Topic(name="offs"),
            partition=0,
            consumer_group="g",
            offset=42,
        )
    )
    for s in stubs:
        r = s.FetchOffset(
            mq.FetchOffsetRequest(
                topic=mq.Topic(name="offs"), partition=0, consumer_group="g"
            )
        )
        assert r.offset == 42


def test_leader_death_failover_preserves_data(trio):
    brokers, ports = trio
    stubs = [_stub(p) for p in ports]
    stubs[0].ConfigureTopic(
        mq.ConfigureTopicRequest(
            topic=mq.Topic(name="ha"), partition_count=1
        )
    )
    lookup = stubs[0].LookupTopicBrokers(
        mq.LookupTopicBrokersRequest(topic=mq.Topic(name="ha"))
    )
    leader = lookup.assignments[0].leader
    follower = lookup.assignments[0].follower
    leader_idx = ports.index(int(leader.rsplit(":", 1)[1]))
    follower_idx = ports.index(int(follower.rsplit(":", 1)[1]))
    for i in range(20):
        r = stubs[leader_idx].Publish(
            mq.PublishRequest(
                topic=mq.Topic(name="ha"),
                partition=0,
                message=mq.DataMessage(value=b"ha%d" % i),
            )
        )
        assert not r.error
    # kill the leader
    brokers[leader_idx].stop()
    survivor = ({0, 1, 2} - {leader_idx}).pop()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        lookup = stubs[survivor].LookupTopicBrokers(
            mq.LookupTopicBrokersRequest(topic=mq.Topic(name="ha"))
        )
        if lookup.assignments[0].leader == follower:
            break
        time.sleep(0.2)
    assert lookup.assignments[0].leader == follower, (
        "old follower should be promoted"
    )
    # all 20 records are served by the promoted follower
    got = []
    for rec in stubs[follower_idx].Subscribe(
        mq.SubscribeRequest(
            topic=mq.Topic(name="ha"), partition=0, start_offset=0
        )
    ):
        if rec.end_of_stream:
            break
        got.append(rec.message.value)
    assert got == [b"ha%d" % i for i in range(20)]
    # and new publishes keep working through any surviving broker
    r = stubs[survivor].Publish(
        mq.PublishRequest(
            topic=mq.Topic(name="ha"),
            partition=0,
            message=mq.DataMessage(value=b"post-failover"),
        )
    )
    assert not r.error and r.offset == 20
