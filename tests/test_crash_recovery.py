"""Crash recovery: SIGKILL a volume server mid-write-stream, restart it
on the same directory, and verify every acknowledged write survives
(the .idx journal replay + append-only .dat tail discipline)."""

import os
import signal
import subprocess
import sys
import time

import pytest
import requests

from conftest import allocate_port as free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _start_volume(port, mport, data_dir, env):
    return subprocess.Popen(
        [
            sys.executable, "-m", "seaweedfs_tpu.server", "volume",
            "-port", str(port), "-master", f"localhost:{mport}",
            "-dir", data_dir, "-ec.backend", "cpu",
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def test_volume_server_sigkill_recovery(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    mport, vport = free_port(), free_port()
    master = subprocess.Popen(
        [
            sys.executable, "-m", "seaweedfs_tpu.server", "master",
            "-port", str(mport),
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    data_dir = str(tmp_path / "data")
    vol = _start_volume(vport, mport, data_dir, env)
    try:
        deadline = time.time() + 40
        while True:
            try:
                r = requests.get(f"http://localhost:{mport}/cluster/status", timeout=1)
                if r.ok and r.json()["DataNodes"]:
                    break
            except requests.RequestException:
                pass
            assert time.time() < deadline
            time.sleep(0.2)

        # acknowledged writes before the crash
        acked = {}
        for i in range(50):
            a = requests.get(f"http://localhost:{mport}/dir/assign").json()
            data = os.urandom(4000 + i * 37)
            r = requests.post(
                f"http://{a['url']}/{a['fid']}", files={"file": ("x", data)}
            )
            if r.status_code == 201:
                acked[a["fid"]] = data
        # the recovery assertion must never pass vacuously
        assert len(acked) >= 40, f"only {len(acked)}/50 writes acked"

        vol.send_signal(signal.SIGKILL)  # no flush, no goodbye
        vol.wait(timeout=10)

        vol = _start_volume(vport, mport, data_dir, env)
        deadline = time.time() + 40
        while True:
            try:
                r = requests.get(f"http://localhost:{vport}/status", timeout=1)
                if r.ok and r.json()["volumes"]:
                    break
            except requests.RequestException:
                pass
            assert time.time() < deadline and vol.poll() is None
            time.sleep(0.2)

        lost = []
        for fid, data in acked.items():
            r = requests.get(f"http://localhost:{vport}/{fid}")
            if r.status_code != 200 or r.content != data:
                lost.append(fid)
        assert not lost, f"{len(lost)}/{len(acked)} acknowledged writes lost"

        # the reborn server accepts new writes on the recovered volume
        a = requests.get(f"http://localhost:{mport}/dir/assign").json()
        r = requests.post(
            f"http://{a['url']}/{a['fid']}", files={"file": ("x", b"post-crash")}
        )
        assert r.status_code == 201
        assert requests.get(f"http://{a['url']}/{a['fid']}").content == b"post-crash"
    finally:
        for p in (vol, master):
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
