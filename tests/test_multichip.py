"""Multi-device sharding tests on the 8-device virtual CPU platform.

conftest.py forces `--xla_force_host_platform_device_count=8` +
`jax_platforms=cpu` before any backend init, so every suite run exercises
the same Mesh/shard_map path the driver validates via
`__graft_entry__.dryrun_multichip`.

Semantics mirrored: the reference's encode hot loop
(weed/storage/erasure_coding/ec_encoder.go:427 encodeDataOneBatch) is
embarrassingly parallel over block columns; the distributed analog shards
the column dimension over devices (DP-over-blocks) with the bit-matrix
replicated, and only CRC-sized reductions cross the ICI.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.8 jax
    from jax.experimental.shard_map import shard_map

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_jax import RSJax, _apply_bits

K, M = 10, 4


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provide >=8 virtual devices"
    return Mesh(np.array(devs[:8]), ("blocks",))


def test_virtual_platform_is_8_cpu_devices():
    devs = jax.devices()
    assert len(devs) >= 8
    assert all(d.platform == "cpu" for d in devs[:8])


def test_mesh_sharded_encode_bit_exact(mesh, rng):
    """Column-sharded encode over an 8-device mesh == CPU reference."""
    rs = RSJax(K, M)
    n = 8 * 512
    data = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
    by_block = NamedSharding(mesh, P(None, "blocks"))
    ddata = jax.device_put(data, by_block)
    pbits = jax.device_put(rs._parity_bits, NamedSharding(mesh, P()))

    parity = jax.jit(
        _apply_bits, out_shardings=by_block
    )(pbits, ddata)
    np.testing.assert_array_equal(
        np.asarray(parity), gf256.ReedSolomon(K, M).encode(data)
    )
    # the output really is distributed: one shard per device
    assert len(parity.addressable_shards) == 8
    assert parity.addressable_shards[0].data.shape == (M, n // 8)


def test_mesh_reconstruct_two_lost_shards(mesh, rng):
    """Regenerate shards 3 and 11 on-device, sharded over blocks."""
    rs = RSJax(K, M)
    n = 8 * 256
    data = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
    all_shards = np.concatenate([data, gf256.ReedSolomon(K, M).encode(data)])

    src_rows = tuple(i for i in range(K + M) if i not in (3, 11))[:K]
    rbits = rs._rows_bits((3, 11), src_rows)
    by_block = NamedSharding(mesh, P(None, "blocks"))
    src = jax.device_put(all_shards[list(src_rows)], by_block)

    rec = jax.jit(_apply_bits, out_shardings=by_block)(
        jax.device_put(rbits, NamedSharding(mesh, P())), src
    )
    np.testing.assert_array_equal(np.asarray(rec)[0], all_shards[3])
    np.testing.assert_array_equal(np.asarray(rec)[1], all_shards[11])


def test_shard_map_psum_checksum(mesh, rng):
    """Global verify reduction rides the mesh (psum), matching how the
    reference shares only per-shard CRCs between encoder workers."""
    rs = RSJax(K, M)
    n = 8 * 128
    data = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
    by_block = NamedSharding(mesh, P(None, "blocks"))
    parity = jax.jit(_apply_bits, out_shardings=by_block)(
        jax.device_put(rs._parity_bits, NamedSharding(mesh, P())),
        jax.device_put(data, by_block),
    )

    def local_sum(x):
        return jax.lax.psum(jnp.sum(x.astype(jnp.uint32)), "blocks")

    checksum = shard_map(
        local_sum, mesh=mesh, in_specs=P(None, "blocks"), out_specs=P()
    )(parity)
    expected = gf256.ReedSolomon(K, M).encode(data).astype(np.uint64).sum()
    assert int(checksum) == int(expected % (1 << 32))


def test_dryrun_multichip_entrypoint():
    """The exact function the driver records in MULTICHIP_r{N}.json."""
    import importlib
    import sys
    import pathlib

    root = str(pathlib.Path(__file__).resolve().parent.parent)
    if root not in sys.path:
        sys.path.insert(0, root)
    mod = importlib.import_module("__graft_entry__")
    mod.dryrun_multichip(8)


def test_production_encoder_on_mesh_bit_exact(tmp_path):
    """r3 verdict #10 done-criterion: the PRODUCTION encoder
    (ec_encode_volume via JaxBackend) shards batch columns across the
    virtual 8-device mesh and produces a bit-identical .ecsum to the
    single-device CPU backend (shared impl with dryrun_multichip)."""
    from seaweedfs_tpu.ec.selfcheck import mesh_encode_selfcheck

    mesh_encode_selfcheck(str(tmp_path), 8)


def test_mesh_backend_rejects_impossible_device_count():
    import pytest as _pytest

    from seaweedfs_tpu.ec.backend import JaxBackend
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT

    with _pytest.raises(RuntimeError, match="need 64 devices"):
        JaxBackend(DEFAULT_EC_CONTEXT, impl="xla", n_devices=64)


def test_parallel_pkg_mesh_helpers(mesh, rng):
    """parallel/ helpers: sharded encode + psum checksum round trip."""
    import numpy as np

    from seaweedfs_tpu.ops import gf256
    from seaweedfs_tpu.ops.rs_jax import RSJax
    from seaweedfs_tpu.parallel import MeshRS, pad_cols

    rs = RSJax(10, 4, impl="xla")
    mrs = MeshRS(rs, mesh)
    data = rng.integers(0, 256, size=(10, 8 * 1024 + 3), dtype=np.uint8)
    padded, n = pad_cols(data, mrs.n_devices)
    handle = mrs.encode(mrs.put(padded))
    parity = np.asarray(handle)[:, :n]
    expected = gf256.ReedSolomon(10, 4).encode(data)
    np.testing.assert_array_equal(parity, expected)
    cks = mrs.global_checksum(handle)
    assert cks == int(expected.astype(np.uint64).sum() % (1 << 32))
