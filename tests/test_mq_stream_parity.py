"""PR 14 durable-parity MQ log segments (`mq/stream_parity.py` +
broker wiring): parity trails the append head by a bounded lag instead
of waiting for segment seal, and the unsealed tail is crash-recovered
from the EC stream.

Load-bearing properties:

- a durable-parity topic's records survive a broker "crash" (memory-only
  broker: the EC stream is the ONLY durability) and a real process kill
  (forked child, armed hard_exit at every stream crash window);
- recovery never publishes a stripe whose parity disagrees with its
  data: post-recovery, every retained generation verifies clean;
- replayed tails merge with filer-durable segments without duplicate or
  missing offsets, and the topic stays appendable;
- generations rotate at the size bound and prune below the durability
  floor.
"""

import multiprocessing
import os
import time

import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec.backend import CpuBackend
from seaweedfs_tpu.ec.stream_encode import load_stream_journal, recover_stream
from seaweedfs_tpu.mq.broker import MqBroker
from seaweedfs_tpu.mq.stream_parity import (
    GEN_PREFIX,
    PartitionParity,
    dense_frame_scan,
    decode_dense,
    parity_context,
)


@pytest.fixture(autouse=True)
def _small_stripes(monkeypatch):
    """Small stripes + a tight lag deadline so tests exercise seals,
    rotation, and the flusher without megabytes of traffic."""
    monkeypatch.setenv("SEAWEED_EC_STREAM_BLOCK_KB", "16")
    monkeypatch.setenv("SEAWEED_EC_STREAM_SMALL_KB", "4")
    monkeypatch.setenv("SEAWEED_EC_STREAM_MAX_LAG_MS", "40")
    monkeypatch.setenv("SEAWEED_EC_STREAM_BACKEND", "cpu")
    yield


def _msg(i: int) -> tuple[bytes, bytes]:
    # ~1 KiB values: a few hundred records span several 16 KiB-block
    # stripes, so seal/flush crash windows genuinely arm
    return (b"k%06d" % i, b"value-%06d-" % i + b"x" * (900 + i % 191))


def _drain(broker: MqBroker, ns="default", topic="t", timeout=8.0):
    st = broker.topic(ns, topic)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(p.pending_bytes() == 0 for p in st.parity.values()):
            return
        time.sleep(0.02)
    raise AssertionError(
        f"parity flusher never drained: {broker.parity_status()}"
    )


def test_durable_parity_bounded_lag_and_restart_replay(tmp_path):
    """Memory-only broker + parity_dir: the background flusher bounds
    the parity lag, and a restart replays every record from the EC
    streams alone — the tail the old broker held only in memory."""
    pdir = str(tmp_path / "parity")
    br = MqBroker(parity_dir=pdir)
    br.configure_topic("default", "t", 2)
    st = br.topic("default", "t")
    assert st.durable_parity and set(st.parity) == {0, 1}
    for i in range(400):
        k, v = _msg(i)
        st.logs[i % 2].append(1_000_000 + i, k, v)
    _drain(br)
    assert br.parity_status()["default/t"][0]["pending_bytes"] == 0
    br.close()

    br2 = MqBroker(parity_dir=pdir)
    st2 = br2.topic("default", "t")
    assert st2.partition_count == 2
    for part in (0, 1):
        recs = st2.logs[part].read_from(0, max_records=1000)
        want = [
            (1_000_000 + i, *_msg(i)) for i in range(400) if i % 2 == part
        ]
        assert [(ts, k, v) for (_o, ts, k, v) in recs] == want
        # offsets stay dense for new appends
        off = st2.logs[part].append(5, b"post", b"restart")
        assert off == recs[-1][0] + 1
    br2.close()


def test_parity_off_topic_and_no_parity_dir(tmp_path):
    # no parity_dir: durable_parity requests degrade to plain topics
    br = MqBroker()
    br.configure_topic("default", "t", 1, durable_parity=True)
    assert not br.topic("default", "t").parity
    br.close()
    # parity_dir but topic opts out
    br2 = MqBroker(parity_dir=str(tmp_path / "p"))
    br2.configure_topic("default", "plain", 1, durable_parity=False)
    br2.configure_topic("default", "dp", 1)
    assert not br2.topic("default", "plain").parity
    assert br2.topic("default", "dp").parity
    br2.close()


def test_delete_topic_removes_parity_dir(tmp_path):
    pdir = str(tmp_path / "parity")
    br = MqBroker(parity_dir=pdir)
    br.configure_topic("default", "t", 1)
    st = br.topic("default", "t")
    st.logs[0].append(1, b"k", b"v")
    br.flush()
    assert os.path.isdir(os.path.join(pdir, "default", "t"))
    br.delete_topic("default", "t")
    assert not os.path.exists(os.path.join(pdir, "default", "t"))
    # a fresh broker does not resurrect it
    br2 = MqBroker(parity_dir=pdir)
    with pytest.raises(KeyError):
        br2.topic("default", "t")
    br2.close()
    br.close()


def test_generation_rotation_and_prune(tmp_path, monkeypatch):
    """Streams rotate at the size bound; generations wholly below the
    durability floor are pruned by the sweep."""
    monkeypatch.setenv("SEAWEED_EC_STREAM_ROTATE_MB", "1")
    pdir = str(tmp_path / "parity")
    # small memory ring: records fall out of the bounded tail quickly,
    # advancing the prune floor (memory-only durability window)
    br = MqBroker(parity_dir=pdir, segment_records=64)
    br.configure_topic("default", "t", 1)
    st = br.topic("default", "t")
    payload = b"p" * 4096
    # two waves with a drain + explicit flush between: wave 1
    # (~1.4 MiB) crosses the rotate bound, the explicit flush makes
    # the rotation point deterministic (the background flusher's
    # rotation can otherwise race wave 2's appends into the closing
    # generation — documented, data-safe), wave 2 then materializes
    # the next generation
    for i in range(350):
        st.logs[0].append(i, b"k%d" % i, payload)
    _drain(br)
    st.parity[0].flush()  # idempotent; guarantees the rotation ran
    for i in range(350, 700):
        st.logs[0].append(i, b"k%d" % i, payload)
    _drain(br)
    st.parity[0].flush()
    br.parity_sweep()  # floor = earliest_offset (memory-only)
    part_dir = os.path.join(pdir, "default", "t", "0000")
    kept = sorted(
        {
            int(n[len(GEN_PREFIX) :].split(".", 1)[0])
            for n in os.listdir(part_dir)
            if n.startswith(GEN_PREFIX)
        }
    )
    # rotation happened: the surviving generation number is past 0;
    # prune happened: generation 0 (wholly below the memory ring's
    # earliest offset) is gone
    assert kept and kept[-1] >= 1, f"expected rotation, got {kept}"
    assert kept[0] >= 1, f"expected gen 0 pruned, got {kept}"
    # the retained window still recovers
    br.close()
    br2 = MqBroker(parity_dir=pdir)
    recs = br2.topic("default", "t").logs[0].read_from(0, max_records=10_000)
    assert recs, "retained generations must replay"
    offs = [r[0] for r in recs]
    assert offs == list(range(offs[0], offs[0] + len(offs)))  # dense
    assert all(r[3] == payload for r in recs)
    br2.close()


# ------------------------------------------------------------ chaos


def _crashing_broker_child(pdir: str, point: str, n_records: int) -> None:
    faults.inject(point, faults.hard_exit(137))
    br = MqBroker(parity_dir=pdir)
    br.configure_topic("default", "t", 1)
    st = br.topic("default", "t")
    parity = st.parity[0]
    for i in range(n_records):
        k, v = _msg(i)
        st.logs[0].append(1_000_000 + i, k, v)
        # deterministic flush cadence: the armed point fires inside
        # one of these (seal fires from process() when a stripe fills)
        parity.flush()
    # not reached with an armed point on the flush path
    os._exit(0)


@pytest.mark.chaos
@pytest.mark.parametrize(
    "point",
    [
        "ec.stream.seal",  # mid-seal: final parity rows half-written
        "ec.stream.before_fsync",  # mid-flush: data written, not synced
        "ec.stream.before_journal",  # fsynced but cursor not advanced
    ],
)
def test_kill_at_stream_crash_windows_recovers_clean(tmp_path, point):
    """Hard-kill the broker inside every streaming-EC crash window:
    recovery replays a dense verified prefix (or rolls the tail back),
    the topic stays readable and appendable, and NO retained generation
    carries parity that disagrees with its data."""
    pdir = str(tmp_path / "parity")
    mp = multiprocessing.get_context("fork")
    p = mp.Process(
        target=_crashing_broker_child, args=(pdir, point, 300)
    )
    p.start()
    p.join(timeout=120)
    assert p.exitcode == 137, f"expected hard crash, got {p.exitcode}"

    br = MqBroker(parity_dir=pdir)
    st = br.topic("default", "t")
    recs = st.logs[0].read_from(0, max_records=1000)
    # replay-or-rollback: whatever came back is a DENSE prefix of what
    # the child appended (offsets from 0), byte-exact
    for n, (off, ts, k, v) in enumerate(recs):
        assert off == n, f"replay not dense from 0: {off} at {n}"
        assert (k, v) == _msg(n), f"record {n} corrupted"
        assert ts == 1_000_000 + n
    # the broker keeps serving: appends continue dense after the tail
    next_off = st.logs[0].append(7, b"post", b"crash")
    assert next_off == len(recs)
    # parity-data agreement: every retained OLD generation verifies
    # clean on a second recovery pass (recovery already repaired any
    # disagreement before publishing)
    part_dir = os.path.join(pdir, "default", "t", "0000")
    ctx = parity_context()
    be = CpuBackend(ctx)
    checked = 0
    for name in sorted(os.listdir(part_dir)):
        if not name.startswith(GEN_PREFIX) or not name.endswith(".stream"):
            continue
        gen_base = os.path.join(part_dir, name[: -len(".stream")])
        j = load_stream_journal(gen_base)
        if j is None:
            continue
        rec2 = recover_stream(
            gen_base, ctx, be, frame_scan=dense_frame_scan(j.meta)
        )
        if rec2 is None:
            continue
        assert rec2.parity_rewritten == 0, (
            f"gen {name}: parity disagreed with data after recovery"
        )
        for off, _ts, k, v in decode_dense(rec2.data, rec2.journal.meta):
            if off < len(recs):
                assert (k, v) == _msg(off)
        checked += 1
    assert checked >= 1, "no generation was verified"
    br.close()


def test_partition_parity_direct_recover_roundtrip(tmp_path):
    """PartitionParity without a broker: feed, flush, recover."""
    pp = PartitionParity(str(tmp_path), "ns", "t", 0)
    msgs = [(i, 10 + i, *_msg(i)) for i in range(50)]
    for off, ts, k, v in msgs:
        pp.append_record(off, ts, k, v)
    pp.flush()
    pp.close()
    pp2 = PartitionParity(str(tmp_path), "ns", "t", 0)
    got = pp2.recover()
    assert got == msgs
    # recovery leaves the partition on a fresh generation: new records
    # append cleanly at any offset
    pp2.append_record(50, 60, b"k", b"v")
    pp2.flush()
    pp2.close()
    pp3 = PartitionParity(str(tmp_path), "ns", "t", 0)
    assert pp3.recover()[-1] == (50, 60, b"k", b"v")
    pp3.close()


# ---------------------------------------------- ISSUE 15 satellites


def test_configure_topic_grpc_durable_parity_field(tmp_path):
    """PR 14 carried (c): the ConfigureTopic RPC carries durable_parity
    (tri-state int32, descriptor surgery) so a REMOTE client gets the
    same opt-in/out the Python API has."""
    from conftest import allocate_port as free_port

    from seaweedfs_tpu.mq import MqBrokerServer, MqClient

    srv = MqBrokerServer(
        ip="localhost", grpc_port=free_port(),
        parity_dir=str(tmp_path / "parity"),
    )
    srv.start()
    c = MqClient(f"localhost:{srv.grpc_port}")
    try:
        c.configure_topic("on-default", partitions=1)          # 0 = default
        c.configure_topic("forced-off", partitions=1,
                          durable_parity=False)                 # 2 = off
        c.configure_topic("forced-on", partitions=1,
                          durable_parity=True)                  # 1 = on
        topics = srv.broker._topics
        assert topics[("default", "on-default")].durable_parity is True
        assert topics[("default", "forced-off")].durable_parity is False
        assert topics[("default", "forced-on")].durable_parity is True
        # parity actually engages only where configured
        c.publish("forced-off", b"v", key=b"k")
        c.publish("forced-on", b"v", key=b"k")
        srv.broker.parity_sweep()
        assert "default/forced-on" in srv.broker.parity_status()
        assert "default/forced-off" not in srv.broker.parity_status()
    finally:
        c.close()
        srv.stop()


def test_remote_roots_place_stream_shards_and_recover(tmp_path, monkeypatch):
    """PR 14 carried (b), scoped: with SEAWEED_EC_STREAM_REMOTE_ROOTS
    set, a durable-parity partition's stream shards spread across the
    remote roots via plan_shard_placement headroom (symlinked targets);
    recovery reads through them, pruning removes the remote bytes, and
    a root without headroom is never chosen. Default (unset) keeps
    every shard local."""
    r1 = tmp_path / "hostA"
    r2 = tmp_path / "hostB"
    monkeypatch.setenv(
        "SEAWEED_EC_STREAM_REMOTE_ROOTS", f"hostA={r1},hostB={r2}"
    )
    pp = PartitionParity(str(tmp_path / "local"), "ns", "t", 0)
    msgs = [(i, 10 + i, *_msg(i)) for i in range(40)]
    for off, ts, k, v in msgs:
        pp.append_record(off, ts, k, v)
    pp.flush()
    pp.close()
    links = [
        n
        for n in os.listdir(pp.dir)
        if n.startswith(GEN_PREFIX) and os.path.islink(
            os.path.join(pp.dir, n)
        )
    ]
    assert links, "no shard was placed on a remote root"
    remote_files = [
        p
        for root in (r1, r2)
        for dirpath, _d, names in os.walk(root)
        for p in [os.path.join(dirpath, n) for n in names]
    ]
    assert remote_files, "remote roots hold no shard bytes"
    # recovery reads through the symlinks bit-exactly
    pp2 = PartitionParity(str(tmp_path / "local"), "ns", "t", 0)
    assert pp2.recover() == msgs
    pp2.delete()
    assert not [
        p
        for root in (r1, r2)
        for dirpath, _d, names in os.walk(root)
        for p in [os.path.join(dirpath, n) for n in names]
    ], "delete left orphaned remote shard bytes"
    # unset (the default) = all-local
    monkeypatch.delenv("SEAWEED_EC_STREAM_REMOTE_ROOTS")
    pp3 = PartitionParity(str(tmp_path / "plain"), "ns", "t", 0)
    for off, ts, k, v in msgs:
        pp3.append_record(off, ts, k, v)
    pp3.flush()
    pp3.close()
    assert not any(
        os.path.islink(os.path.join(pp3.dir, n))
        for n in os.listdir(pp3.dir)
    )


def test_net_remote_roots_push_shards_over_write_plane(tmp_path, monkeypatch):
    """ISSUE 18: a ``net:host:grpcport/sub`` remote root replaces the
    shared-mount assumption — planned shards stay LOCAL files and every
    flush pushes the newly-durable extent to the peer's write plane
    (kind=blob, fsync-before-ACK). Recovery stays purely local; delete
    unlinks the remote replicas."""
    from conftest import allocate_port as free_port

    from seaweedfs_tpu.ec import net_plane
    from seaweedfs_tpu.mq.stream_parity import PartitionParity as PP

    remote_root = tmp_path / "peer_blobs"
    served: list[tuple] = []

    def resolve_blob(path, op, md):
        served.append((op, path))
        full = os.path.join(str(remote_root), path)
        if op == "unlink":
            try:
                os.unlink(full)
            except FileNotFoundError:
                pass
            return None
        os.makedirs(os.path.dirname(full), exist_ok=True)
        return os.open(full, os.O_CREAT | os.O_RDWR, 0o644)

    def _refuse(vid, sid, gen):
        raise net_plane.NetPlaneError("no shards here")

    gport = free_port()
    srv = net_plane.ShardNetPlane(
        "127.0.0.1", net_plane.derive_port(gport), _refuse,
        resolve_blob=resolve_blob,
    )
    srv.start()
    monkeypatch.setenv(
        "SEAWEED_EC_STREAM_REMOTE_ROOTS", f"peer=net:127.0.0.1:{gport}/sub"
    )
    try:
        pp = PP(str(tmp_path / "local"), "ns", "t", 0)
        msgs = [(i, 10 + i, *_msg(i)) for i in range(40)]
        for off, ts, k, v in msgs:
            pp.append_record(off, ts, k, v)
        pp.flush()
        plans = {
            path: rpath
            for plan in pp._net_shards.values()
            for path, (addr, rpath) in plan.items()
        }
        assert plans, "no shard was planned onto the net: root"
        # shards stay plain local files — no symlinks involved
        assert all(not os.path.islink(p) for p in plans)
        pp.close()
        for path, rpath in plans.items():
            assert rpath.startswith("sub/ns/t/0000/")
            with open(path, "rb") as f:
                local = f.read()
            with open(os.path.join(str(remote_root), rpath), "rb") as f:
                assert f.read() == local, f"remote replica diverged: {rpath}"
        assert any(op == "write" for op, _ in served)
        # recovery is purely local (the peer could be down)
        pp2 = PP(str(tmp_path / "local"), "ns", "t", 0)
        assert pp2.recover() == msgs
        pp2.delete()
        for rpath in plans.values():
            assert not os.path.exists(
                os.path.join(str(remote_root), rpath)
            ), "delete left remote shard bytes"
        assert any(op == "unlink" for op, _ in served)
    finally:
        srv.stop()
