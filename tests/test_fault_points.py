"""Fault-point registry lint: the README's canonical fault-point table
and the `ec.*` / `mq.*` point literals in the code must agree exactly,
in both directions. A new seam can't ship undocumented; a renamed or
deleted point can't leave a stale README row behind."""

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent

# fire()/inject()/injected()/mutate() all take the point literal as
# their first argument; the literal may start on the next line (black
# wraps long calls), so the regex tolerates one newline after the
# paren. Only ec.* / mq.* namespaces are governed by the registry —
# local test-only namespaces (e.g. "storage.*") are out of scope.
POINT_RE = re.compile(
    r'(?:fire|inject|injected|mutate)\(\s*\n?\s*"((?:ec|mq)\.[a-z0-9_.]+)"'
)

ROW_RE = re.compile(r"^\|\s*`((?:ec|mq)\.[a-z0-9_.]+)`\s*\|", re.MULTILINE)


def _code_points() -> set[str]:
    pts: set[str] = set()
    for root in ("seaweedfs_tpu", "tests"):
        for f in (REPO / root).rglob("*.py"):
            pts |= set(POINT_RE.findall(f.read_text(encoding="utf-8")))
    return pts


def _readme_points() -> set[str]:
    return set(ROW_RE.findall((REPO / "README.md").read_text("utf-8")))


def test_every_code_fault_point_is_documented():
    code, readme = _code_points(), _readme_points()
    missing = code - readme
    assert not missing, (
        "fault points used in code but absent from the README "
        f"fault-point registry table: {sorted(missing)}"
    )


def test_every_documented_fault_point_exists_in_code():
    code, readme = _code_points(), _readme_points()
    stale = readme - code
    assert not stale, (
        "README fault-point registry rows with no matching point in "
        f"code (renamed or removed?): {sorted(stale)}"
    )


def test_registry_is_not_vacuous():
    """Guard the lint itself: if the regexes rot, both sets go empty
    and the equality tests pass trivially. Pin a floor and known
    points, including multi-line call sites."""
    code = _code_points()
    assert len(code) >= 30, sorted(code)
    # ec.residency.acquire's fire() call spans lines — a single-line
    # regex would drop it silently
    for required in (
        "ec.residency.acquire",
        "ec.encode.before_fsync",
        "ec.scrub.read_block",
        "ec.stream.seal",
        "ec.volume.shard_read",
    ):
        assert required in code, required
    assert _readme_points() == code
