"""Metadata log + subscription + cross-cluster sync tests
(reference filer meta log / SubscribeMetadata / filer.sync)."""

import json
import threading
import time

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.meta_log import MetaLog
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.replication import FilerSync
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


from conftest import allocate_port as free_port


def make_event(directory: str, name: str, ts_ns: int) -> fpb.FullEventNotification:
    ev = fpb.FullEventNotification(directory=directory, ts_ns=ts_ns)
    ev.event.new_entry.name = name
    return ev


def test_meta_log_append_read_rotation(tmp_path):
    import seaweedfs_tpu.filer.meta_log as ml

    log = MetaLog(str(tmp_path / "log"))
    for i in range(1, 101):
        log(make_event("/d", f"f{i}", ts_ns=i))
    events = log.read_since(0)
    assert len(events) == 100
    assert [e["tsNs"] for e in events] == list(range(1, 101))
    assert len(log.read_since(90)) == 10
    # rotation: shrink the segment cap temporarily
    old = ml.SEGMENT_BYTES
    ml.SEGMENT_BYTES = 512
    try:
        for i in range(101, 161):
            log(make_event("/d", f"f{i}", ts_ns=i))
    finally:
        ml.SEGMENT_BYTES = old
    import os

    assert any(f.startswith("meta-") for f in os.listdir(tmp_path / "log"))
    # retention keeps a bounded contiguous suffix ending at the newest event
    got = [e["tsNs"] for e in log.read_since(95)]
    assert got == list(range(got[0], 161))
    assert got[0] > 96, "old segments beyond retention are dropped"
    log.close()


def test_meta_log_wait(tmp_path):
    log = MetaLog(str(tmp_path / "log"))
    hit = []
    t = threading.Thread(target=lambda: hit.append(log.wait_for_events(0, 5.0)))
    t.start()
    time.sleep(0.1)
    log(make_event("/d", "x", ts_ns=time.time_ns()))
    t.join(timeout=2)
    assert hit == [True]
    log.close()


@pytest.fixture
def two_clusters(tmp_path):
    """Two independent single-node clusters, each with a filer."""
    out = []
    for i in range(2):
        mport = free_port()
        master = MasterServer(ip="localhost", port=mport)
        master.start()
        vs = VolumeServer(
            directories=[str(tmp_path / f"c{i}v")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        while not master.topo.nodes:
            time.sleep(0.05)
        filer = Filer(MemoryStore(), master=f"localhost:{mport}")
        fport = free_port()
        fsrv = FilerServer(
            filer,
            ip="localhost",
            port=fport,
            meta_log=MetaLog(str(tmp_path / f"c{i}meta")),
        )
        fsrv.start()
        out.append((master, vs, filer, fsrv, fport))
    yield out
    for master, vs, filer, fsrv, _ in out:
        fsrv.stop()
        vs.stop()
        master.stop()


def test_meta_tail_endpoint(two_clusters):
    _, _, _, _, fport = two_clusters[0]
    base = f"http://localhost:{fport}"
    r = requests.get(f"{base}/~meta/tail?sinceNs=0")
    body = r.json()
    assert body["events"] == [] and body["lastTsNs"] == 0
    assert body["droppedBeforeTsNs"] == 0 and body["nowNs"] > 0
    requests.post(f"{base}/a/b.txt", data=b"hello")
    r = requests.get(f"{base}/~meta/tail?sinceNs=0")
    body = r.json()
    names = [
        e["newEntry"]["name"] for e in body["events"] if e.get("newEntry")
    ]
    assert "b.txt" in names and "a" in names
    # watermark pagination: nothing after lastTsNs
    r2 = requests.get(f"{base}/~meta/tail?sinceNs={body['lastTsNs']}")
    assert r2.json()["events"] == []


def test_fs_meta_save_load(two_clusters, tmp_path):
    from seaweedfs_tpu.shell.commands import ShellEnv, run_command

    master0 = two_clusters[0][0]
    fport = two_clusters[0][4]
    base = f"http://localhost:{fport}"
    requests.post(f"{base}/tree/a/file1.txt", data=b"one")
    requests.post(f"{base}/tree/b/c/file2.txt", data=b"two")
    env = ShellEnv(f"localhost:{master0.port}", filer=f"localhost:{fport}")
    try:
        out = run_command(env, f"fs.meta.save /tree -o {tmp_path}/meta.jsonl")
        assert "saved 5 entries" in out, out  # a, b, c + 2 files
        # missing path errors instead of claiming success
        out = run_command(env, f"fs.meta.save /nope -o {tmp_path}/x.jsonl")
        assert "error" in out
        # load recreates the directory skeleton on the second cluster
        fport2 = two_clusters[1][4]
        env2 = ShellEnv(
            f"localhost:{two_clusters[1][0].port}", filer=f"localhost:{fport2}"
        )
        try:
            out = run_command(env2, f"fs.meta.load {tmp_path}/meta.jsonl")
            assert "recreated 3 directories" in out, out
            r = requests.get(f"http://localhost:{fport2}/tree/b/c")
            assert r.headers.get("X-Filer-Listing") == "true"
        finally:
            env2.close()
    finally:
        env.close()


def test_fs_tree_du_fsck(two_clusters, tmp_path):
    from seaweedfs_tpu.shell.commands import ShellEnv, run_command

    master0 = two_clusters[0][0]
    fport = two_clusters[0][4]
    base = f"http://localhost:{fport}"
    requests.post(f"{base}/proj/src/a.py", data=b"x" * 4000)
    requests.post(f"{base}/proj/src/lib/b.py", data=b"y" * 6000)
    env = ShellEnv(f"localhost:{master0.port}", filer=f"localhost:{fport}")
    try:
        out = run_command(env, "fs.tree /proj")
        assert "src/" in out and "a.py" in out and "b.py" in out
        out = run_command(env, "fs.du /proj")
        assert "10,000 bytes in 2 files" in out, out
        out = run_command(env, "volume.fsck -path /proj")
        assert "no broken chunk references" in out, out
        # break a reference: delete the chunk blob behind a.py directly
        r = requests.get(f"{base}/proj/src/a.py?chunks=true")
        assert r.headers.get("X-Filer-Chunks") == "true"
        fid = r.json()["chunks"][0]
        vs = two_clusters[0][1]
        from seaweedfs_tpu.storage.file_id import FileId

        f = FileId.parse(fid)
        vs.store.delete_needle(f.volume_id, f.needle_id)
        out = run_command(env, "volume.fsck -path /proj")
        assert "BROKEN" in out, out
    finally:
        env.close()


def test_filer_sync_full_and_tail(two_clusters):
    src = two_clusters[0][4]
    dst = two_clusters[1][4]
    sbase, dbase = f"http://localhost:{src}", f"http://localhost:{dst}"
    # pre-existing state
    requests.post(f"{sbase}/docs/one.txt", data=b"first")
    requests.post(f"{sbase}/docs/sub/two.txt", data=b"second")

    sync = FilerSync(f"localhost:{src}", f"localhost:{dst}")
    sync.watermark = time.time_ns() - 1
    assert sync.full_sync() == 2
    assert requests.get(f"{dbase}/docs/one.txt").content == b"first"
    assert requests.get(f"{dbase}/docs/sub/two.txt").content == b"second"

    # live events: create, overwrite, delete
    requests.post(f"{sbase}/docs/three.txt", data=b"third")
    requests.post(f"{sbase}/docs/one.txt", data=b"first-v2")
    requests.delete(f"{sbase}/docs/sub/two.txt")
    deadline = time.time() + 10
    while time.time() < deadline:
        sync.tail_once(wait_seconds=0.5)
        if (
            requests.get(f"{dbase}/docs/three.txt").status_code == 200
            and requests.get(f"{dbase}/docs/one.txt").content == b"first-v2"
            and requests.get(f"{dbase}/docs/sub/two.txt").status_code == 404
        ):
            break
    assert requests.get(f"{dbase}/docs/three.txt").content == b"third"
    assert requests.get(f"{dbase}/docs/one.txt").content == b"first-v2"
    assert requests.get(f"{dbase}/docs/sub/two.txt").status_code == 404
    # idempotent: re-tailing applies nothing new
    assert sync.tail_once(wait_seconds=0.2) == 0


def test_filer_backup_to_local_dir(tmp_path):
    """filer.backup (reference weed/command/filer_backup.go): full copy
    then live tail into a local tree — adds, updates, renames, deletes
    — with watermark resume across a restart."""
    import os
    import threading
    import time as _time

    import requests as rq

    from conftest import allocate_port as free_port
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.replication.backup import FilerBackup
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")], master=f"localhost:{mport}",
        ip="localhost", port=free_port(), ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        _time.sleep(0.05)
    from seaweedfs_tpu.filer.meta_log import MetaLog

    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    fsrv = FilerServer(
        filer, ip="localhost", port=free_port(),
        meta_log=MetaLog(str(tmp_path / "meta")),
    )
    fsrv.start()
    base = f"http://localhost:{fsrv.port}"
    dest = str(tmp_path / "backup")
    state = str(tmp_path / "bk.state")
    try:
        # pre-existing content for the full copy
        rq.post(f"{base}/docs/a.txt", files={"f": ("a.txt", b"alpha")})
        rq.post(f"{base}/docs/sub/b.txt", files={"f": ("b.txt", b"beta")})
        bk = FilerBackup(
            f"localhost:{fsrv.port}", dest, path="/docs",
            state_path=state,
        )
        t = threading.Thread(target=bk.run, daemon=True)
        t.start()

        def wait_file(rel, content, timeout=15):
            deadline = _time.time() + timeout
            p = os.path.join(dest, rel)
            while _time.time() < deadline:
                if os.path.exists(p) and open(p, "rb").read() == content:
                    return
                _time.sleep(0.1)
            raise AssertionError(f"{rel} never reached {content!r}")

        wait_file("a.txt", b"alpha")
        wait_file("sub/b.txt", b"beta")

        # live adds + updates + deletes flow through the tail
        rq.post(f"{base}/docs/c.txt", files={"f": ("c.txt", b"gamma")})
        wait_file("c.txt", b"gamma")
        rq.post(f"{base}/docs/a.txt", files={"f": ("a.txt", b"alpha-2")})
        wait_file("a.txt", b"alpha-2")
        rq.delete(f"{base}/docs/sub/b.txt")
        deadline = _time.time() + 15
        while os.path.exists(os.path.join(dest, "sub/b.txt")):
            assert _time.time() < deadline, "delete never propagated"
            _time.sleep(0.1)
        # out-of-scope writes never appear
        rq.post(f"{base}/other/x.txt", files={"f": ("x.txt", b"no")})
        _time.sleep(1.0)
        assert not os.path.exists(os.path.join(dest, "x.txt"))

        # restart resumes from the watermark (no full recopy)
        bk.stop()
        t.join(timeout=15)
        rq.post(f"{base}/docs/d.txt", files={"f": ("d.txt", b"delta")})
        bk2 = FilerBackup(
            f"localhost:{fsrv.port}", dest, path="/docs",
            state_path=state,
        )
        assert bk2.watermark > 0  # state restored
        t2 = threading.Thread(target=bk2.run, daemon=True)
        t2.start()
        wait_file("d.txt", b"delta")
        bk2.stop()
        t2.join(timeout=15)
    finally:
        fsrv.stop()
        filer.close()
        vs.stop()
        master.stop()
