"""Pallas fused RS kernel, interpret mode (CPU). Bit-exactness only;
throughput is covered by bench.py on real TPU hardware."""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs_jax, rs_pallas
from seaweedfs_tpu.ops.gf256 import ReedSolomon


@pytest.fixture(scope="module")
def ref():
    return ReedSolomon(10, 4)


@pytest.mark.parametrize("pack_width", [1, 2])
def test_pallas_encode_bit_exact(ref, rng, pack_width):
    import jax.numpy as jnp

    coeffs = gf256.parity_rows(10, 4)
    bm = jnp.asarray(rs_jax.bit_matrix_bitmajor(coeffs), jnp.float32)
    data = rng.integers(0, 256, size=(10, 600)).astype(np.uint8)
    got = np.asarray(
        rs_pallas.apply_bitmajor_pallas(
            bm,
            jnp.asarray(data),
            k=10,
            m=4,
            tile_n=128,
            pack_width=pack_width,
            interpret=True,
        )
    )
    want = ref.encode(data)
    assert np.array_equal(got, want)


def test_pack_width_4_rejected(ref, rng):
    """pw=4 sums exceed 24-bit exact matmul accumulation; the kernel
    refuses rather than silently corrupting (the MXU runs 'f32' dots as
    bf16 passes on real hardware — measured on v5e, where default-
    precision pw=2 corrupted the low byte of every output word)."""
    import jax.numpy as jnp

    coeffs = gf256.parity_rows(10, 4)
    bm = jnp.asarray(rs_jax.bit_matrix_bitmajor(coeffs), jnp.float32)
    data = rng.integers(0, 256, size=(10, 512)).astype(np.uint8)
    with pytest.raises(NotImplementedError):
        rs_pallas.apply_bitmajor_pallas(
            bm, jnp.asarray(data), k=10, m=4, tile_n=128, pack_width=4,
            interpret=True,
        )


def test_rsjax_pallas_impl_roundtrip(ref, rng):
    codec = rs_jax.RSJax(10, 4, impl="pallas", interpret=True, tile_n=128)
    data = rng.integers(0, 256, size=(10, 512)).astype(np.uint8)
    parity = np.asarray(codec.encode(data))
    assert np.array_equal(parity, ref.encode(data))
    full = np.concatenate([data, parity])
    present = {i: full[i] for i in range(14) if i not in (0, 12)}
    out = codec.reconstruct(present)
    for i in (0, 12):
        assert np.array_equal(np.asarray(out[i]), full[i])


def test_pallas_pad_edge(ref, rng):
    """Sizes not divisible by tile*pack_width exercise the pad path."""
    import jax.numpy as jnp

    coeffs = gf256.parity_rows(4, 2)
    bm = jnp.asarray(rs_jax.bit_matrix_bitmajor(coeffs), jnp.float32)
    ref42 = ReedSolomon(4, 2)
    for n in (1, 255, 513):
        data = rng.integers(0, 256, size=(4, n)).astype(np.uint8)
        got = np.asarray(
            rs_pallas.apply_bitmajor_pallas(
                bm, jnp.asarray(data), k=4, m=2, tile_n=128, pack_width=2,
                interpret=True,
            )
        )
        assert np.array_equal(got, ref42.encode(data)), n


# ---------------------------------------------------------------- aligned


@pytest.mark.parametrize("pack_width", [1, 2])
def test_aligned_encode_bit_exact(ref, rng, pack_width):
    import jax.numpy as jnp

    coeffs = gf256.parity_rows(10, 4)
    planes = jnp.asarray(rs_pallas.bit_matrix_planes(coeffs, pack_width=pack_width))
    data = rng.integers(0, 256, size=(10, 600)).astype(np.uint8)
    got = np.asarray(
        rs_pallas.apply_planes_pallas(
            planes,
            jnp.asarray(data),
            k=10,
            m=4,
            tile_n=128,
            pack_width=pack_width,
            interpret=True,
        )
    )
    assert np.array_equal(got, ref.encode(data))


def test_aligned_rsjax_impl_roundtrip(ref, rng):
    codec = rs_jax.RSJax(10, 4, impl="pallas_aligned", interpret=True, tile_n=128)
    data = rng.integers(0, 256, size=(10, 512)).astype(np.uint8)
    parity = np.asarray(codec.encode(data))
    assert np.array_equal(parity, ref.encode(data))
    full = np.concatenate([data, parity])
    present = {i: full[i] for i in range(14) if i not in (0, 12)}
    out = codec.reconstruct(present)
    for i in (0, 12):
        assert np.array_equal(np.asarray(out[i]), full[i])


def test_aligned_pad_edge(rng):
    import jax.numpy as jnp

    for k, m in ((4, 2), (17, 5)):
        refkm = ReedSolomon(k, m)
        planes = jnp.asarray(rs_pallas.bit_matrix_planes(gf256.parity_rows(k, m)))
        for n in (1, 255, 513):
            data = rng.integers(0, 256, size=(k, n)).astype(np.uint8)
            got = np.asarray(
                rs_pallas.apply_planes_pallas(
                    planes, jnp.asarray(data), k=k, m=m, tile_n=128,
                    pack_width=2, interpret=True,
                )
            )
            assert np.array_equal(got, refkm.encode(data)), (k, m, n)


def test_aligned_lane_shapes():
    """The whole point of the layout: every lane dim a 128 multiple and
    the out block height sublane-legal for the chosen word width."""
    for k, m in ((10, 4), (17, 5), (20, 12)):
        for pw, min_rows in ((1, 32), (2, 16), (4, 16)):
            planes = rs_pallas.bit_matrix_planes(
                gf256.parity_rows(k, m), pack_width=pw
            )
            assert planes.shape[0] == 8 and planes.shape[1] == k
            assert planes.shape[2] % 128 == 0
            assert (planes.shape[2] // 8) % min_rows == 0


def test_aligned_rejects_mismatched_planes():
    """pack_width=1 needs 32-row blocks; planes built for 16 must be
    refused, not silently fed to Mosaic."""
    import jax.numpy as jnp

    planes = rs_pallas.bit_matrix_planes(gf256.parity_rows(10, 4), pack_width=2)
    data = jnp.zeros((10, 256), jnp.uint8)
    with pytest.raises(ValueError, match="sublane-legal"):
        rs_pallas.apply_planes_pallas(
            planes, data, k=10, m=4, tile_n=128, pack_width=1, interpret=True
        )
