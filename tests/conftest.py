"""Test harness: force an 8-device virtual CPU platform before jax imports.

Multi-chip hardware is not available in CI; sharding tests run on a
virtual 8-device CPU mesh (the driver separately dry-runs the multi-chip
path via __graft_entry__.dryrun_multichip).
"""

import os

# Force, don't setdefault: the session profile sets JAX_PLATFORMS=axon
# (the real TPU tunnel); unit tests must stay on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin's sitecustomize imports jax at interpreter startup,
# which freezes jax_platforms to "axon" before this file runs; if the TPU
# relay is down, any backend init then hangs forever. Overriding the env
# var is too late — update the live jax config instead.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    import numpy as np

    return np.random.default_rng(0x5EAD)
