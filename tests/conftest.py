"""Test harness: force an 8-device virtual CPU platform before jax imports.

Multi-chip hardware is not available in CI; sharding tests run on a
virtual 8-device CPU mesh (the driver separately dry-runs the multi-chip
path via __graft_entry__.dryrun_multichip).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    import numpy as np

    return np.random.default_rng(0x5EAD)
