"""Test harness: force an 8-device virtual CPU platform before jax imports.

Multi-chip hardware is not available in CI; sharding tests run on a
virtual 8-device CPU mesh (the driver separately dry-runs the multi-chip
path via __graft_entry__.dryrun_multichip).
"""

import os
import pathlib
import sys

# Force, don't setdefault: the session profile sets JAX_PLATFORMS=axon
# (the real TPU tunnel); unit tests must stay on the virtual CPU mesh.
# Spawned-server subprocesses inherit this env and come up on CPU too.
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from __graft_entry__ import _force_virtual_cpu_mesh  # noqa: E402

# Sets XLA_FLAGS device count AND flips the live jax config (the axon
# sitecustomize imports jax at interpreter startup, freezing the
# env-derived platform default before this file runs).
_force_virtual_cpu_mesh(8)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection lifecycle tests "
        "(fixed-seed subset stays in tier-1; randomized soaks are slow)",
    )
    config.addinivalue_line(
        "markers", "slow: long soak tests excluded from tier-1 (-m 'not slow')"
    )


@pytest.fixture(autouse=True)
def _fault_registry_hygiene():
    """A test that armed fault points must never leak them into the next
    test — chaos determinism depends on a clean registry per test."""
    yield
    from seaweedfs_tpu import faults

    if faults.active():
        faults.clear()


@pytest.fixture(scope="session")
def rng():
    import numpy as np

    return np.random.default_rng(0x5EAD)


# ---------------------------------------------------------------- ports

_issued_ports: set[int] = set()


def allocate_port() -> int:
    """Ephemeral port that avoids previously issued ports AND their
    +10000 shadows (servers bind grpc on port+10000)."""
    import socket as _socket

    while True:
        with _socket.socket() as s:
            s.bind(("localhost", 0))
            p = s.getsockname()[1]
        if p + 10000 > 65535:
            continue  # grpc shadow would not be bindable
        if (
            p in _issued_ports
            or (p + 10000) in _issued_ports
            or (p - 10000) in _issued_ports
        ):
            continue
        # the shadow must actually be free right now too
        try:
            with _socket.socket() as s2:
                s2.bind(("localhost", p + 10000))
        except OSError:
            continue
        _issued_ports.add(p)
        _issued_ports.add(p + 10000)
        return p


def wait_for(cond, timeout=15.0, msg="condition"):
    """Poll until cond() is true or fail with msg — the one wait loop
    shared by worker/soak/cluster tests."""
    import time as _time

    deadline = _time.time() + timeout
    while not cond():
        if _time.time() > deadline:
            raise TimeoutError(msg)
        _time.sleep(0.05)
