"""SqliteNeedleMap (LevelDB-class durable map) + live-vacuum tests.

Reference models: weed/storage/needle_map_leveldb.go (durable map with
O(delta) reopen) and volume_vacuum.go:74-316 (compaction with live
catch-up from the journal)."""

import os
import threading
import time

import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.needle_map import SqliteNeedleMap, walk_index_file
from seaweedfs_tpu.storage.types import NeedleValue
from seaweedfs_tpu.storage.volume import Volume


# ---------------------------------------------------------- sqlite map


def test_sqlite_map_basic(tmp_path):
    idx = str(tmp_path / "1.idx")
    m = SqliteNeedleMap(idx)
    for i in range(1, 101):
        m.put(i, offset=i * 8, size=100 + i)
    assert m.get(50) == NeedleValue(50, 400, 150)
    assert m.get(999) is None
    assert m.delete(50) == 150
    assert m.get(50) is None
    assert m.deleted_counter == 1 and m.deleted_bytes == 150
    assert len(m) == 99
    ids = [nv.needle_id for nv in m.ascending_visit()]
    assert ids == sorted(ids) and 50 not in ids
    m.close()
    # the .idx journal has every operation (still the wire format)
    entries = list(walk_index_file(idx))
    assert len(entries) == 101  # 100 puts + 1 tombstone


def test_sqlite_map_reopen_is_o_delta(tmp_path):
    idx = str(tmp_path / "2.idx")
    m = SqliteNeedleMap(idx)
    for i in range(1, 1001):
        m.put(i, offset=i * 8, size=10)
    m.flush()
    watermark = os.path.getsize(idx)
    m.close()
    # append 5 more entries directly to the journal (simulating a crash
    # after .idx writes but before the sqlite commit)
    with open(idx, "ab") as f:
        for i in range(2001, 2006):
            f.write(NeedleValue(i, i * 8, 20).to_bytes())
    m2 = SqliteNeedleMap(idx)
    # only the tail was replayed: the stored watermark covered the rest
    assert m2._meta("watermark") >= watermark
    assert m2.get(500) == NeedleValue(500, 4000, 10)
    assert m2.get(2003) == NeedleValue(2003, 2003 * 8, 20)
    assert len(m2) == 1005
    m2.close()


def test_sqlite_map_generation_change_rebuilds(tmp_path):
    idx = str(tmp_path / "3.idx")
    m = SqliteNeedleMap(idx, generation=1)
    m.put(1, 8, 10)
    m.flush()
    m.close()
    # journal replaced by a vacuum (same size, new content, new gen)
    with open(idx, "wb") as f:
        f.write(NeedleValue(7, 16, 30).to_bytes())
    m2 = SqliteNeedleMap(idx, generation=2)
    assert m2.get(1) is None
    assert m2.get(7) == NeedleValue(7, 16, 30)
    m2.close()


def test_volume_with_sqlite_map(tmp_path):
    v = Volume(str(tmp_path), 11, needle_map_kind="sqlite")
    payloads = {}
    for i in range(1, 51):
        data = bytes((i * 3 + j) % 256 for j in range(500))
        v.write_needle(Needle(cookie=i, needle_id=i, data=data))
        payloads[i] = data
    v.delete_needle(10)
    v.close()
    v2 = Volume(str(tmp_path), 11, create=False, needle_map_kind="sqlite")
    assert v2.read_needle(30).data == payloads[30]
    assert not v2.has_needle(10)
    # vacuum reclaims and the rebuilt sqlite map still serves
    reclaimed = v2.vacuum()
    assert reclaimed > 0
    assert v2.read_needle(30).data == payloads[30]
    assert not v2.has_needle(10)
    v2.close()
    v3 = Volume(str(tmp_path), 11, create=False, needle_map_kind="sqlite")
    assert v3.read_needle(49).data == payloads[49]
    v3.close()


# ---------------------------------------------------------- live vacuum


def test_vacuum_accepts_writes_during_compaction(tmp_path):
    """The VERDICT item: vacuum no longer freezes the volume for the
    whole compaction — writes landing mid-vacuum survive via the
    journal catch-up."""
    v = Volume(str(tmp_path), 21)
    blob = b"z" * 2048
    for i in range(1, 5001):
        v.write_needle(Needle(cookie=1, needle_id=i, data=blob))
    for i in range(1, 2500):  # ~50% garbage
        v.delete_needle(i)

    written_during: list[int] = []
    rejected = 0
    stop = threading.Event()

    def writer():
        nonlocal rejected
        nid = 100_000
        while not stop.is_set():
            nid += 1
            try:
                v.write_needle(Needle(cookie=2, needle_id=nid, data=b"live-" + str(nid).encode()))
                written_during.append(nid)
            except Exception:
                rejected += 1  # the brief freeze window
                time.sleep(0.001)

    t = threading.Thread(target=writer)
    t.start()
    time.sleep(0.01)  # let the writer get going
    reclaimed = v.vacuum()
    stop.set()
    t.join()

    assert reclaimed > 0
    # the volume accepted writes while vacuuming
    assert len(written_during) > 0, "no write landed during vacuum"
    # every mid-vacuum write survived the compaction commit
    for nid in written_during:
        assert v.read_needle(nid).data == b"live-" + str(nid).encode()
    # old live needles survived, deleted ones are gone
    assert v.read_needle(4000).data == blob
    assert not v.has_needle(100)
    # and everything still holds after a reopen (journal consistent)
    v.close()
    v2 = Volume(str(tmp_path), 21, create=False)
    for nid in written_during[-5:]:
        assert v2.read_needle(nid).data == b"live-" + str(nid).encode()
    assert not v2.has_needle(100)
    v2.close()


def test_vacuum_catchup_applies_mid_vacuum_deletes(tmp_path):
    """A delete issued during compaction must not resurrect on commit."""
    v = Volume(str(tmp_path), 22)
    for i in range(1, 2001):
        v.write_needle(Needle(cookie=1, needle_id=i, data=b"d" * 1024))
    v.delete_needle(1)  # some garbage so vacuum does work

    deleted_mid: list[int] = []
    stop = threading.Event()

    def deleter():
        nid = 1000
        while not stop.is_set() and nid < 1050:
            try:
                v.delete_needle(nid)
                deleted_mid.append(nid)
                nid += 1
            except Exception:
                time.sleep(0.001)

    t = threading.Thread(target=deleter)
    t.start()
    v.vacuum()
    stop.set()
    t.join()
    for nid in deleted_mid:
        assert not v.has_needle(nid), f"needle {nid} resurrected by vacuum"
    assert v.read_needle(500).data == b"d" * 1024
    v.close()


def test_vacuum_still_readonly_volume_restored(tmp_path):
    """A volume that was readonly before vacuum stays readonly after."""
    v = Volume(str(tmp_path), 23)
    v.write_needle(Needle(cookie=1, needle_id=1, data=b"x"))
    v.delete_needle(1)
    v.set_read_only(True)
    v.vacuum()
    assert v.read_only
    v.close()


def test_sorted_file_lookup_scalar_fast_path(tmp_path):
    """Regression (round-5 benchmark finding): searchsorted with a
    PYTHON int on a uint64 column routes through a ~200us casting slow
    path; the typed-scalar fix must keep lookups in single-digit
    microseconds. Generous 10x bound so CI noise never flakes it."""
    import time

    import numpy as np

    from seaweedfs_tpu.storage.needle_map import (
        MemDb,
        SortedFileNeedleMap,
    )
    from seaweedfs_tpu.storage.types import NeedleValue

    db = MemDb()
    n = 100_000
    for i in range(1, n + 1):
        db.put(NeedleValue(i * 7, i, 1024))
    path = str(tmp_path / "s.sorted")
    db.write_sorted_file(path)
    sf = SortedFileNeedleMap(path)
    try:
        picks = np.random.default_rng(3).integers(1, n, 5000)
        # correctness
        for i in picks[:100]:
            assert sf.get(int(i) * 7).offset == int(i)
        assert sf.get(3) is None  # 3 is not a multiple of 7 in range
        t0 = time.perf_counter()
        for i in picks:
            sf.get(int(i) * 7)
        per = (time.perf_counter() - t0) / len(picks)
        assert per < 100e-6, f"sorted lookup {per*1e6:.1f}us: slow path?"
    finally:
        sf.close()
