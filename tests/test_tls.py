"""TLS listeners + certificate hot-reload (utils/tls.py).

Mirrors the reference's weed/security/tls.go + test/tls_rotation: an
https master keeps serving across a cert rotation without restart, and
an mTLS listener rejects clients without a certificate.
"""

import ssl
import urllib.request

import pytest

from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.utils.tls import TlsConfig, generate_self_signed

from conftest import allocate_port as free_port


def _get(url: str, ctx: ssl.SSLContext) -> bytes:
    with urllib.request.urlopen(url, context=ctx, timeout=10) as r:
        return r.read()


@pytest.fixture
def certs(tmp_path):
    return generate_self_signed(str(tmp_path / "tls"))


def test_https_master_round_trip(tmp_path, certs):
    port = free_port()
    ms = MasterServer(ip="127.0.0.1", port=port, tls=certs)
    ms.start()
    try:
        body = _get(
            f"https://127.0.0.1:{port}/dir/status", certs.client_context()
        )
        assert b"topology" in body.lower() or b"{" in body
        # plaintext client against the TLS port must fail
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/dir/status", timeout=5
            )
    finally:
        ms.stop()


def test_cert_hot_reload(tmp_path, certs):
    port = free_port()
    ms = MasterServer(ip="127.0.0.1", port=port, tls=certs)
    ms.start()
    try:
        ctx = certs.client_context()
        _get(f"https://127.0.0.1:{port}/dir/status", ctx)
        old_serial = ssl.get_server_certificate(("127.0.0.1", port))
        # rotate the leaf (same CA, same paths) — no server restart
        generate_self_signed(str(tmp_path / "tls"))
        _get(f"https://127.0.0.1:{port}/dir/status", ctx)
        new_serial = ssl.get_server_certificate(("127.0.0.1", port))
        assert new_serial != old_serial, "rotated cert was not picked up"
    finally:
        ms.stop()


def test_stalled_client_does_not_block_listener(tmp_path, certs):
    """A client that connects and never handshakes must not stall other
    connections (the handshake runs per-connection, off the accept
    loop)."""
    import socket

    port = free_port()
    ms = MasterServer(ip="127.0.0.1", port=port, tls=certs)
    ms.start()
    stalled = socket.create_connection(("127.0.0.1", port), timeout=10)
    try:
        # with the stalled socket open and silent, a real client works
        body = _get(
            f"https://127.0.0.1:{port}/dir/status", certs.client_context()
        )
        assert body
    finally:
        stalled.close()
        ms.stop()


def test_cluster_internal_hops_over_https(tmp_path, certs, monkeypatch):
    """enable_https() routes client→volume uploads/reads through https
    (the service_url seam used by every internal hop)."""
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.utils import urls

    monkeypatch.setattr(urls, "_scheme", "http")  # restore after test
    monkeypatch.setenv("REQUESTS_CA_BUNDLE", "")
    urls.enable_https(certs.ca_file)
    mport, vport = free_port(), free_port()
    ms = MasterServer(ip="127.0.0.1", port=mport, tls=certs)
    ms.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"127.0.0.1:{mport}",
        ip="127.0.0.1",
        port=vport,
        tls=certs,
    )
    vs.start()
    try:
        ops = Operations(master=f"127.0.0.1:{mport}")
        fid = ops.upload(b"tls payload", name="t.txt")
        assert ops.read(fid) == b"tls payload"
    finally:
        vs.stop()
        ms.stop()
        urls._scheme = "http"


def test_mutual_tls_requires_client_cert(tmp_path):
    dir_ = str(tmp_path / "mtls")
    server_cfg = generate_self_signed(dir_, name="server")
    client_cfg = generate_self_signed(dir_, name="client")
    server_cfg.client_auth = True
    port = free_port()
    ms = MasterServer(ip="127.0.0.1", port=port, tls=server_cfg)
    ms.start()
    try:
        # without a client cert: handshake refused
        bare = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        bare.load_verify_locations(server_cfg.ca_file)
        with pytest.raises(Exception):
            _get(f"https://127.0.0.1:{port}/dir/status", bare)
        # with the CA-signed client cert: accepted
        body = _get(
            f"https://127.0.0.1:{port}/dir/status",
            client_cfg.client_context(),
        )
        assert body
    finally:
        ms.stop()
