"""Headline benchmark: RS 10+4 erasure-coding, kernel AND end-to-end.

Mirrors the reference's hot loop (weed/storage/erasure_coding/ec_encoder.go
encodeDataOneBatch: klauspost/reedsolomon SIMD GF(2^8) encode) against this
framework's device path (XLA/Pallas bit-matmul encode, seaweedfs_tpu/ops),
and BASELINE.json configs 1-2 end-to-end: `ec.encode` of a fabricated
volume disk->shards+.ecsum, and a 2-shard `ec.rebuild`.

Headline (ISSUE 10 / ROADMAP direction 1): ec_encode_e2e — the
end-to-end disk->shards encode on the zero-copy NATIVE data plane
(native batched reads + fused write+CRC sink, ec/native_io.py), with
the pure-Python byte path re-measured on the same volume as the
vs_baseline denominator and bit-identity (shard + v2 leaf CRCs)
asserted in-line. The kernel-only and disk-independent-pipeline
figures remain as sub-fields (kernel_gbs / pipeline_gbs) — context,
never the headline.

Self-verification (every device number is evidence, not vibes):
- the kernel loop encodes a DIFFERENT pre-staged buffer each rep, and every
  device output is CRC-checked against the C++ AVX2 encoder's result;
- a physical-consistency guard flags any kernel rate whose implied HBM
  traffic exceeds the chip's bandwidth (a broken block_until_ready cannot
  produce a "valid" number);
- the end-to-end device encode must reproduce the CPU run's .ecsum shard
  CRCs bit-exactly, and the rebuild re-verifies against the sidecar.

Baseline = the C++ AVX2 PSHUFB encoder (native/seaweed_native.cpp), the same
nibble-table technique klauspost uses on amd64, multi-threaded across all
host cores (ctypes releases the GIL). vs_baseline = device / CPU end-to-end.

Prints exactly ONE JSON line, e.g.:
  {"metric": "ec_encode_e2e_10p4[...]", "value": N, "unit": "GB/s",
   "vs_baseline": N, "kernel_gbs": ..., "kernel_verified": true, ...}
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import sys
import tempfile
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor

import numpy as np

K, M = 10, 4
BLOCK = 32 << 20  # bytes per data shard => 320 MiB data per kernel pass
SMALL_WIDTH = 1 << 22  # first-landing kernel stage: seconds, not minutes
REPS = 3  # distinct input buffers, one per timed rep
SEEDS = [0x5EAD + i for i in range(REPS)]
# slice widths a kernel stage may use (CPU truth precomputed for each)
VERIFY_WIDTHS = [1 << 20, SMALL_WIDTH, 1 << 23, BLOCK]

# Advertised HBM bandwidth ceilings (GB/s) by device_kind substring.
# Generous: used only to flag IMPOSSIBLE numbers, not to grade real ones.
_HBM_GBS = [
    ("v6e", 1640), ("v6 lite", 1640), ("v5p", 2765), ("v5e", 819),
    ("v5 lite", 819), ("v4", 1228), ("v3", 900), ("v2", 700),
]
_HBM_DEFAULT = 5000.0


def _hbm_ceiling(kind: str) -> float:
    k = kind.lower()
    for sub, gbs in _HBM_GBS:
        if sub in k:
            return float(gbs)
    return _HBM_DEFAULT


def _gen(seed: int, width: int) -> np.ndarray:
    return np.random.default_rng(seed).integers(
        0, 256, size=(K, width), dtype=np.uint8
    )


def _crc_rows(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


# --------------------------------------------------------------------------
# CPU phase (parent process)
# --------------------------------------------------------------------------

def _cpu_kernel_gbs(data: np.ndarray, coeffs: np.ndarray, threads: int) -> float:
    """Multi-threaded native AVX2 encode throughput (data bytes / s)."""
    from seaweedfs_tpu.utils import native

    n = data.shape[1]
    chunk = max(1 << 20, n // max(threads, 1))
    chunks = [
        np.ascontiguousarray(data[:, lo : min(lo + chunk, n)])
        for lo in range(0, n, chunk)
    ]

    def run_chunk(c):
        native.rs_apply(coeffs, c)

    with ThreadPoolExecutor(max_workers=threads) as ex:
        list(ex.map(run_chunk, chunks))  # warmup (tables + page-in)
        t0 = time.perf_counter()
        for _ in range(REPS):
            list(ex.map(run_chunk, chunks))
        dt = (time.perf_counter() - t0) / REPS
    return data.nbytes / dt / 1e9


def _expected_kernel_crcs(coeffs: np.ndarray) -> dict[str, dict[str, int]]:
    """CPU-truth parity CRCs per (seed, width). A (K, w) buffer is NOT a
    column-prefix of the (K, BLOCK) buffer for the same seed (the RNG
    fills row-major), so each width the device phase might pick is
    generated and encoded at that exact width."""
    from seaweedfs_tpu.utils import native

    out: dict[str, dict[str, int]] = {}
    for seed in SEEDS:
        out[str(seed)] = {
            str(w): _crc_rows(native.rs_apply(coeffs, _gen(seed, w)))
            for w in VERIFY_WIDTHS
        }
    return out


def _fabricate_volume(base_dir: str, target_bytes: int) -> str:
    """Create a real .dat/.idx volume of >= target_bytes; returns base path."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    vol = Volume(base_dir, 1, needle_map_kind="memory")
    rng = np.random.default_rng(0xB0B)
    blob = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    nid = 1
    while vol.size < target_bytes:
        # vary content so shards aren't trivially compressible/repetitive
        n = Needle(cookie=0x1234, needle_id=nid, data=blob[nid % 1024 :] + blob[: nid % 1024])
        vol.write_needle(n)
        nid += 1
    vol.flush()
    base = vol.base_file_name(base_dir, "", 1)
    vol.close()
    return base


def _clear_shards(base: str) -> None:
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT

    for i in range(DEFAULT_EC_CONTEXT.total):
        p = base + DEFAULT_EC_CONTEXT.to_ext(i)
        if os.path.exists(p):
            os.unlink(p)
    for ext in (".ecx", ".ecsum", ".vif"):
        if os.path.exists(base + ext):
            os.unlink(base + ext)


def _cpu_e2e(
    base: str, force_python: bool = False
) -> tuple[float, list[list[int]], int]:
    """Timed CPU disk->shards encode; returns (gbs, shard_crcs, dat_size).
    `force_python` pins the pure-Python source/sink plane
    (SEAWEED_EC_NATIVE=0) so the headline native-plane number ships with
    its own bit-identity evidence and speedup ratio."""
    from seaweedfs_tpu.ec.backend import CpuBackend
    from seaweedfs_tpu.ec.bitrot import BitrotProtection
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ec.encoder import ec_encode_volume

    dat_size = os.path.getsize(base + ".dat")
    prev = os.environ.get("SEAWEED_EC_NATIVE")
    if force_python:
        os.environ["SEAWEED_EC_NATIVE"] = "0"
    try:
        t0 = time.perf_counter()
        ec_encode_volume(base, backend=CpuBackend(DEFAULT_EC_CONTEXT))
        dt = time.perf_counter() - t0
    finally:
        if force_python:
            if prev is None:
                os.environ.pop("SEAWEED_EC_NATIVE", None)
            else:
                os.environ["SEAWEED_EC_NATIVE"] = prev
    prot = BitrotProtection.load(base + ".ecsum")
    return dat_size / dt / 1e9, prot.shard_crcs, dat_size


def _cpu_rebuild_bench(base: str, dat_size: int) -> dict:
    """BASELINE config 2 on the CPU backend: rebuild 2 missing shards
    (one data, one parity), serial baseline vs the shared recovery
    pipeline, bit-identical outputs enforced both ways."""
    from seaweedfs_tpu.ec.backend import CpuBackend
    from seaweedfs_tpu.ec.bitrot import BitrotProtection, ShardChecksumBuilder
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ec.rebuild import rebuild_ec_files

    ctx = DEFAULT_EC_CONTEXT
    backend = CpuBackend(ctx)
    prot = BitrotProtection.load(base + ".ecsum")
    missing = [1, K + 1]
    batch = 16 << 20

    # --- serial baseline: the pre-pipeline implementation in full —
    # upfront whole-shard sidecar verify of every present shard, then a
    # strictly sequential read -> reconstruct -> write loop with
    # Python-side CRC + tobytes per batch. Runs against temp outputs
    # with the missing shards simulated so the volume is untouched.
    present = [
        i
        for i in range(ctx.total)
        if i not in missing and os.path.exists(base + ctx.to_ext(i))
    ]
    t_verify0 = time.perf_counter()
    for i in present:
        prot.verify_shard_file(base + ctx.to_ext(i), i)
    serial_verify_dt = time.perf_counter() - t_verify0
    src = sorted(present)[: ctx.data_shards]
    shard_size = os.path.getsize(base + ctx.to_ext(src[0]))
    tmp_paths = {i: base + ctx.to_ext(i) + ".serialbench" for i in missing}
    serial_ok = True

    def serial_once() -> float:
        nonlocal serial_ok
        fds = {i: os.open(base + ctx.to_ext(i), os.O_RDONLY) for i in src}
        outs = {i: open(p, "wb") for i, p in tmp_paths.items()}
        builders = {i: ShardChecksumBuilder(prot.block_size) for i in missing}
        t0 = time.perf_counter()
        try:
            for off in range(0, shard_size, batch):
                width = min(batch, shard_size - off)
                block = {
                    i: np.frombuffer(os.pread(fds[i], width, off), dtype=np.uint8)
                    for i in src
                }
                rec = backend.reconstruct(block, want=missing)
                for i in missing:
                    b = np.asarray(rec[i], dtype=np.uint8).tobytes()
                    outs[i].write(b)
                    builders[i].write(b)
            for f in outs.values():
                f.flush()
                os.fsync(f.fileno())
        finally:
            for fd in fds.values():
                os.close(fd)
            for f in outs.values():
                f.close()
        dt = time.perf_counter() - t0
        serial_ok = serial_ok and all(
            builders[i].total == prot.shard_sizes[i]
            and builders[i].finish() == prot.shard_crcs[i]
            for i in missing
        )
        return dt

    # Best-of-2, matching the warm best-of-N treatment the pipelined and
    # staged variants get below — all three numbers are page-cache-warm
    # floors, so the ratios compare algorithms, not cache states.
    serial_dt = min(serial_once(), serial_once()) + serial_verify_dt
    for p in tmp_paths.values():
        os.unlink(p)

    # --- pipelined (PR 2 shape, synchronous apply) vs staged (PR 3,
    # async H2D/compute/D2H through the backend staging hooks): actually
    # lose the shards, rebuild_ec_files them back (publishes
    # temp+fsync+rename, sidecar-verified), compare bit-for-bit against
    # the originals. Two timed reps per variant, best-of: the variants
    # do IDENTICAL I/O and GF math on CPU, so min-dt is the honest
    # comparison (staged must be parity-not-regression here; the
    # overlap win only exists where D2H actually blocks — on a device).
    originals = {}
    for i in missing:
        with open(base + ctx.to_ext(i), "rb") as f:
            originals[i] = f.read()

    identical = True

    def one_rebuild(staged: bool) -> float:
        nonlocal identical
        for i in missing:
            if os.path.exists(base + ctx.to_ext(i)):
                os.unlink(base + ctx.to_ext(i))
        t0 = time.perf_counter()
        rebuilt = rebuild_ec_files(base, backend=backend, staged=staged)
        dt = time.perf_counter() - t0
        if sorted(rebuilt) != sorted(missing):
            identical = False
        for i in missing:
            with open(base + ctx.to_ext(i), "rb") as f:
                if f.read() != originals[i]:
                    identical = False
        return dt

    # Interleaved best-of-3 after a warmup (page cache + fsync drift
    # dominate at small volume sizes; interleaving decorrelates it and
    # min-of-N converges both variants to their I/O floor).
    one_rebuild(staged=True)
    times = {False: float("inf"), True: float("inf")}
    for _ in range(3):
        for staged in (False, True):
            times[staged] = min(times[staged], one_rebuild(staged))
    pipe_dt, staged_dt = times[False], times[True]
    return {
        "rebuild_serial_gbs": round(dat_size / serial_dt / 1e9, 3),
        "rebuild_pipeline_gbs": round(dat_size / pipe_dt / 1e9, 3),
        "rebuild_staged_gbs": round(dat_size / staged_dt / 1e9, 3),
        "rebuild_vs_serial": round(serial_dt / pipe_dt, 3),
        "rebuild_staged_vs_sync": round(pipe_dt / staged_dt, 3),
        "rebuild_bit_identical": bool(serial_ok and identical),
    }


def _colocated_bench(
    batch: int = 1 << 20, fg_batches: int = 48, reps: int = 3
) -> dict:
    """encode_vs_rebuild_colocated: foreground encode throughput with
    and without a concurrent saturating recovery stream multiplexed on
    the SAME device queue, interleaved best-of-N (isolated/colocated
    alternate so drift hits both variants equally).

    Runs on the CPU backend through a private DeviceQueue with window=1
    so admission order IS the compute schedule (on a real chip the
    device serializes compute the same way): the ratio measures the
    scheduler's priority policy — foreground keeps >= (1 - recovery
    share) of the chip while the recovery stream keeps a non-zero
    batches/s floor (the no-starvation guarantee), instead of the two
    streams fighting or serializing FIFO."""
    import threading as _threading

    from seaweedfs_tpu.ec.backend import CpuBackend, _decode_coeffs
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ec.device_queue import DeviceQueue
    from seaweedfs_tpu.ops import gf256

    ctx = DEFAULT_EC_CONTEXT
    k = ctx.data_shards
    be = CpuBackend(ctx)
    q = DeviceQueue(window=1)
    rng = np.random.default_rng(0xC0)
    data = rng.integers(0, 256, (k, batch), dtype=np.uint8)
    rs = gf256.ReedSolomon(k, ctx.parity_shards)
    rec_coeffs = _decode_coeffs(
        rs.matrix, k, (0, 1), tuple(range(2, 2 + k))
    )

    from seaweedfs_tpu.ec.device_queue import batch_cost

    fg_cost = batch_cost(ctx.parity_shards, batch)  # encode: m rows out
    rec_cost = batch_cost(rec_coeffs.shape[0], batch)

    def fg_pass() -> float:
        # Same two-thread shape as the production encoder (dispatch in
        # the calling thread, to_host+release in a drain thread behind a
        # bounded queue): the NEXT batch's admission request is queued
        # before the current slot releases, so the scheduler sees a
        # continuous foreground stream — a serial dispatch/drain loop
        # would hand every released slot to the work-conserving
        # recovery class and measure the loop's own gaps, not the
        # policy.
        import queue as _q

        s = q.stream("foreground", "bench encode")
        outq: "_q.Queue" = _q.Queue(maxsize=2)
        drain_errors: list = []

        def drain():
            try:
                while True:
                    item = outq.get()
                    if item is None:
                        return
                    t, h = item
                    try:
                        np.asarray(be.to_host(h))
                    finally:
                        s.release(t)
            except BaseException as e:  # noqa: BLE001
                # Keep draining (releasing window slots!) so the
                # producer's bounded put and its next admission never
                # block against a dead consumer (the same discipline as
                # run_pipeline's writer) — the error resurfaces in the
                # producer below.
                drain_errors.append(e)
                while True:
                    item = outq.get()
                    if item is None:
                        return
                    s.release(item[0])

        th = _threading.Thread(target=drain, daemon=True)
        t0 = time.perf_counter()
        th.start()
        try:
            for _ in range(fg_batches):
                t, h = s.dispatch(
                    lambda: be.encode_staged(be.to_device(data)), fg_cost
                )
                outq.put((t, h))
        finally:
            outq.put(None)
            th.join(timeout=60)
            s.close()
        if drain_errors:
            raise drain_errors[0]
        return (k * batch * fg_batches) / (time.perf_counter() - t0) / 1e9

    progress = {"batches": 0}
    stop = _threading.Event()

    def recovery_loop():
        s = q.stream("recovery", "bench rebuild")
        try:
            while not stop.is_set():
                t, h = s.dispatch(
                    lambda: be.apply_staged(rec_coeffs, be.to_device(data)),
                    rec_cost,
                )
                np.asarray(be.to_host(h))
                s.release(t)
                progress["batches"] += 1
        finally:
            s.close()

    fg_pass()  # warmup (page faults, allocator, coeff caches)
    iso, colo, rec_rates = [], [], []
    for _ in range(reps):
        iso.append(fg_pass())
        stop.clear()
        th = _threading.Thread(target=recovery_loop, daemon=True)
        th.start()
        time.sleep(0.05)  # let the recovery stream saturate first
        progress["batches"] = 0
        t0 = time.perf_counter()
        colo.append(fg_pass())
        dt = time.perf_counter() - t0
        stop.set()
        th.join(timeout=30)
        rec_rates.append(progress["batches"] / max(dt, 1e-9))
    best_iso, best_colo = max(iso), max(colo)
    return {
        # acceptance bar: >= 0.85 with colocated_recovery_bps > 0
        "encode_vs_rebuild_colocated": round(best_colo / best_iso, 3),
        "colocated_fg_gbs": round(best_colo, 3),
        "isolated_fg_gbs": round(best_iso, 3),
        "colocated_recovery_bps": round(min(rec_rates), 2),
    }


def _placement_bench(
    n_streams: int | None = None,
    batch: int | None = None,
    batches: int | None = None,
    reps: int = 3,
) -> dict:
    """multi_stream_placement: aggregate throughput of N concurrent
    encode streams on an emulated 8-device host, whole-stream chip
    placement (ec/chip_pool.py) vs the PR 4 mesh-sliced baseline where
    every stream is column-sliced across all 8 devices and serializes
    behind one admission queue.

    Shape: each stream runs the production encoder's two-thread
    pipeline over `batches` encode batches, rotating through 3
    DISTINCT input buffers (defeats any transfer caching; same trick
    as the kernel loop): the dispatch thread stages H2D + device
    dispatch under queue admission, and the drain thread does
    to_host -> release the window slot -> consume (CRC-verify the
    parity against the CPU truth for that buffer) — exactly
    run_staged_apply's writer discipline, consumer work AFTER the slot
    frees. Every drained parity of every pass is verified, so
    bit-identical outputs per stream is part of the metric, not an
    afterthought. Variants alternate (interleaved best-of-N) so load
    drift hits both equally.

    Shape note: the default batch width (1 KiB per shard = a ~10 KiB
    extent at 10+4) is the SERVING-stream shape — the high-concurrency
    traffic the placement layer exists for is degraded reads and
    small-volume encodes (PR 2/3 reconstruct leaf- and needle-sized
    extents), where per-batch compute is comparable to per-batch
    dispatch cost, exactly as on real TPUs where a 16 MiB batch
    computes in ~100 us against ~50-100 us of per-chip dispatch. Bulk
    lone-stream encodes (16 MiB batches) are the case `ec_placement=
    auto` deliberately LEAVES on the mesh, so they are not this
    metric; the SEAWEED_BENCH_PLACEMENT_* env knobs re-measure any
    other shape. On the mesh baseline every batch pays 8-way sharded
    H2D, shard_map dispatch, and gathered D2H, and all streams share
    ONE admission window; real pods add the parallel-chip compute win
    this 2-core emulation cannot show. Hermetic: the stage child
    forces the 8-device virtual CPU platform — no TPU, no disk."""
    import threading as _threading

    from seaweedfs_tpu.ec.backend import CpuBackend, JaxBackend
    from seaweedfs_tpu.ec.chip_pool import place_stream, pool_for
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ec.device_queue import QueueScope, batch_cost

    n_streams = n_streams or int(
        os.environ.get("SEAWEED_BENCH_PLACEMENT_STREAMS", "4")
    )
    batch = batch or (
        int(os.environ.get("SEAWEED_BENCH_PLACEMENT_BATCH_KB", "1")) << 10
    )
    batches = batches or int(
        os.environ.get("SEAWEED_BENCH_PLACEMENT_BATCHES", "96")
    )
    ctx = DEFAULT_EC_CONTEXT
    be = JaxBackend(ctx)  # 8 virtual devices -> column mesh
    pool = pool_for(be)
    if pool is None:
        return {"error": "no chip pool (forced 8-device platform missing?)"}
    cpu = CpuBackend(ctx)
    NBUF = 3
    datas = [
        [_gen(0x9A0 + i * NBUF + j, batch) for j in range(NBUF)]
        for i in range(n_streams)
    ]
    expected = [
        [zlib.crc32(np.ascontiguousarray(cpu.encode(d)).tobytes()) for d in row]
        for row in datas
    ]
    m = ctx.parity_shards

    def stream_worker(scope, i, oks, errors, barrier):
        # Same two-thread shape as the production encoder: dispatch in
        # this thread, to_host+release in a drain thread behind a
        # bounded queue. NEVER block on the next admission while
        # holding an undrained ticket in the same thread — on a shared
        # (mesh-baseline) queue four such streams would hold every
        # window slot and deadlock each other.
        import queue as _q

        placement = None
        s = None
        # Depth 3 (+1 being drained) matches one chip's window=4: a
        # PLACED stream can keep its whole chip window full, while the
        # mesh baseline's streams share ONE window-4 queue — the
        # pod-serialization this metric exists to expose.
        outq: "_q.Queue" = _q.Queue(maxsize=3)
        ok = True

        def drain():
            nonlocal ok
            while True:
                item = outq.get()
                if item is None:
                    return
                t, h, j = item
                try:
                    parity = np.ascontiguousarray(
                        placement.backend.to_host(h), dtype=np.uint8
                    )
                except BaseException:  # noqa: BLE001
                    ok = False
                    s.release(t)
                    continue
                # production writer discipline: the slot frees the
                # moment the result is on the host; the consumer work
                # (here: CRC verification, in the encoder: fused
                # write+CRC) runs after, backpressuring only THIS
                # stream's drain.
                s.release(t)
                if zlib.crc32(parity.tobytes()) != expected[i][j]:
                    ok = False

        th = None
        try:
            placement = place_stream(
                be, "foreground", scope=scope,
                cost_hint=batch_cost(m, batch * batches),
            )
            s = placement.queue.stream("foreground", f"bench stream {i}")
            th = _threading.Thread(target=drain, daemon=True)
            th.start()
            barrier.wait(timeout=60)
            for b in range(batches):
                j = b % NBUF
                t, h = s.dispatch(
                    lambda j=j: placement.backend.encode_staged(
                        placement.backend.to_device(datas[i][j])
                    ),
                    batch_cost(m, batch),
                )
                outq.put((t, h, j))
            outq.put(None)
            th.join(timeout=240)
            oks[i] = ok and not th.is_alive()
        except BaseException as e:  # noqa: BLE001 — the failure is evidence
            errors.append(repr(e)[:300])
            # A worker dying before its barrier.wait would leave the
            # siblings (and the timer) blocked for the full barrier
            # timeout with no recorded cause; abort unblocks everyone
            # and the captured error becomes the pass's verdict.
            barrier.abort()
            outq.put(None)
        finally:
            if s is not None:
                s.close()
            if placement is not None:
                placement.close()

    def one_pass(mode: str) -> tuple[float, bool]:
        scope = QueueScope(placement=mode)
        oks = [False] * n_streams
        errors: list = []
        barrier = _threading.Barrier(n_streams + 1)
        ts = [
            _threading.Thread(
                target=stream_worker,
                args=(scope, i, oks, errors, barrier),
            )
            for i in range(n_streams)
        ]
        for t in ts:
            t.start()
        try:
            barrier.wait(timeout=60)
        except _threading.BrokenBarrierError:
            for t in ts:
                t.join(timeout=30)
            raise RuntimeError(f"placement stream failed: {errors}")
        t0 = time.perf_counter()
        for t in ts:
            t.join(timeout=240)
        dt = time.perf_counter() - t0
        if errors or any(t.is_alive() for t in ts):
            raise RuntimeError(f"placement stream failed: {errors or 'wedged'}")
        gbs = (n_streams * K * batch * batches) / dt / 1e9
        return gbs, all(oks)

    # Warmup passes compile both shapes (mesh shard_map encode AND
    # per-chip encode) so the timed passes compare steady state; every
    # pass, warm or timed, verifies every parity.
    _, ok_mesh = one_pass("mesh")
    _, ok_chip = one_pass("chip")
    verified = ok_mesh and ok_chip
    best = {"mesh": 0.0, "chip": 0.0}
    for _ in range(reps):
        for mode in ("mesh", "chip"):
            gbs, ok = one_pass(mode)
            best[mode] = max(best[mode], gbs)
            verified = verified and ok
    return {
        # acceptance bar: >= 2.0 at 4 streams on the emulated 8-dev host
        "multi_stream_placement": round(best["chip"] / max(best["mesh"], 1e-9), 3),
        "placed_agg_gbs": round(best["chip"], 4),
        "mesh_agg_gbs": round(best["mesh"], 4),
        "placement_verified": bool(verified),
        "placement_streams": n_streams,
        "placement_chips": pool.n_chips,
        "placement_batch": batch,
        "placement_batches": batches,
    }


def _streaming_encode_bench(
    workdir: str,
    n_appends: int = 3000,
    append_bytes: int = 8192,
    flush_kib: int = 256,
    naive_segment_mb: int = 4,
) -> dict:
    """streaming_encode (ISSUE 14 acceptance metric): sustained append
    load through the online EC encoder vs the naive seal-then-batch-
    encode baseline IN THE SAME RUN, on the same bytes.

    Streaming: every append buffers into an `EcStreamEncoder`; a flush
    (pending >= flush threshold, plus a final one) runs the incremental
    parity math, pwrites, fsyncs, and advances the stripe-cursor
    journal — each append's time-to-durable-parity is the wall time
    from its append() to the flush that covered it.

    Naive: the same appends accumulate in a plain segment file; at
    every `naive_segment_mb` boundary the segment SEALS and
    `write_ec_files` batch-encodes it (fsync'd) — each append's
    time-to-durable-parity is the wall time to the END of its
    segment's encode, the seal-then-encode lag this PR removes.

    stream_vs_batch_identical: the streaming encoder's finalized
    shards + sidecar CRCs must be byte-equal to ONE batch encode over
    the concatenation (the RS-linearity identity, asserted in the
    line)."""
    from seaweedfs_tpu.ec.backend import CpuBackend
    from seaweedfs_tpu.ec.context import ECContext
    from seaweedfs_tpu.ec.encoder import write_ec_files
    from seaweedfs_tpu.ec.stream_encode import EcStreamEncoder

    ctx = ECContext(10, 4)
    be = CpuBackend(ctx)
    block = 256 * 1024
    small = 64 * 1024
    flush_bytes = flush_kib << 10
    rng = np.random.default_rng(0x57E4)
    payload = rng.integers(
        0, 256, n_appends * append_bytes, dtype=np.uint8
    ).tobytes()

    sdir = os.path.join(workdir, "stream_bench")
    os.makedirs(sdir, exist_ok=True)

    def quantiles(lags_ms: list[float]) -> tuple[float, float]:
        s = sorted(lags_ms)
        return (
            s[int(0.50 * (len(s) - 1))],
            s[int(0.99 * (len(s) - 1))],
        )

    # ---- streaming phase ------------------------------------------------
    sbase = os.path.join(sdir, "stream")
    enc = EcStreamEncoder(
        sbase, ctx, backend=be, block_size=block, small_block_size=small
    )
    t_append: list[float] = [0.0] * n_appends
    lags_ms: list[float] = []
    covered = 0
    t0 = time.perf_counter()
    for i in range(n_appends):
        t_append[i] = time.perf_counter()
        enc.append(payload[i * append_bytes : (i + 1) * append_bytes])
        if enc.pending_bytes >= flush_bytes:
            durable = enc.flush()
            now = time.perf_counter()
            while (covered + 1) * append_bytes <= durable:
                lags_ms.append((now - t_append[covered]) * 1e3)
                covered += 1
    durable = enc.flush()
    now = time.perf_counter()
    while covered < n_appends and (covered + 1) * append_bytes <= durable:
        lags_ms.append((now - t_append[covered]) * 1e3)
        covered += 1
    stream_wall = time.perf_counter() - t0
    prot_stream = enc.close()
    p50, p99 = quantiles(lags_ms)

    # ---- naive seal-then-encode phase ----------------------------------
    seg_bytes = naive_segment_mb << 20
    nbase_dir = os.path.join(sdir, "naive")
    os.makedirs(nbase_dir, exist_ok=True)
    naive_lags_ms: list[float] = []
    t0 = time.perf_counter()
    seg_start = 0  # first append index of the open segment
    seg_file = None
    seg = 0
    nt_append: list[float] = [0.0] * n_appends
    for i in range(n_appends):
        if seg_file is None:
            seg_file = open(
                os.path.join(nbase_dir, f"seg{seg:04d}.dat"), "wb"
            )
        nt_append[i] = time.perf_counter()
        seg_file.write(payload[i * append_bytes : (i + 1) * append_bytes])
        if seg_file.tell() >= seg_bytes or i == n_appends - 1:
            seg_file.flush()
            os.fsync(seg_file.fileno())
            seg_file.close()
            write_ec_files(
                os.path.join(nbase_dir, f"seg{seg:04d}"), ctx, be,
                large_block_size=block, small_block_size=small,
            )
            now = time.perf_counter()
            naive_lags_ms.extend(
                (now - nt_append[j]) * 1e3 for j in range(seg_start, i + 1)
            )
            seg_start = i + 1
            seg += 1
            seg_file = None
    naive_wall = time.perf_counter() - t0
    np50, np99 = quantiles(naive_lags_ms)

    # ---- identity: stream shards == ONE batch encode over the concat ---
    bbase = os.path.join(sdir, "batch")
    with open(bbase + ".dat", "wb") as f:
        f.write(payload)
    prot_batch = write_ec_files(
        bbase, ctx, be, large_block_size=block, small_block_size=small
    )
    identical = bool(
        prot_stream is not None
        and prot_stream.shard_crcs == prot_batch.shard_crcs
        and prot_stream.shard_leaf_crcs == prot_batch.shard_leaf_crcs
        and prot_stream.shard_sizes == prot_batch.shard_sizes
        and all(
            open(sbase + ctx.to_ext(i), "rb").read()
            == open(bbase + ctx.to_ext(i), "rb").read()
            for i in range(ctx.total)
        )
    )
    return {
        "time_to_durable_parity_p50_ms": round(p50, 3),
        "time_to_durable_parity_p99_ms": round(p99, 3),
        "streaming_appends_per_s": round(n_appends / stream_wall, 1),
        "streaming_parity_covered": covered,
        "naive_parity_p50_ms": round(np50, 3),
        "naive_parity_p99_ms": round(np99, 3),
        "naive_appends_per_s": round(n_appends / naive_wall, 1),
        "streaming_vs_naive_p99": round(np99 / max(p99, 1e-9), 2),
        "stream_vs_batch_identical": identical,
        "streaming_append_kib": append_bytes >> 10,
        "streaming_flush_kib": flush_kib,
        "naive_segment_mb": naive_segment_mb,
    }


def _leaf_repair_bench(base: str) -> dict:
    """Leaf repair vs full-shard rebuild (ISSUE 8 acceptance metric):
    one rotten 64 KiB leaf in one shard, fixed two ways against the
    same volume — (a) leaf-granular in-place repair under the repair
    journal (~k leaves of sibling I/O), (b) whole-shard rebuild (~k
    shards). Reports bytes moved + wall time for both, asserts both
    outcomes are byte-identical to the original shard."""
    from seaweedfs_tpu.ec.bitrot import BitrotProtection
    from seaweedfs_tpu.ec.backend import CpuBackend
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ec.rebuild import rebuild_ec_files
    from seaweedfs_tpu.ec.repair_journal import (
        apply_leaf_repair,
        leaf_verdict,
        reconstruct_leaves,
    )

    ctx = DEFAULT_EC_CONTEXT
    be = CpuBackend(ctx)
    prot = BitrotProtection.load(base + ".ecsum")
    victim = 1
    path = base + ctx.to_ext(victim)
    with open(path, "rb") as f:
        original = f.read()

    # rot one leaf in the middle of the shard
    leaf = min(len(prot.shard_leaf_crcs[victim]) - 1, 3)
    with open(path, "r+b") as f:
        f.seek(leaf * prot.leaf_size + 17)
        f.write(b"\x5a\xa5\x5a")

    moved = [0]

    def read_range(sid: int, lo: int, size: int) -> bytes | None:
        try:
            with open(base + ctx.to_ext(sid), "rb") as f:
                f.seek(lo)
                return f.read(size)
        except OSError:
            return None

    candidates = [i for i in range(ctx.total) if i != victim]
    t0 = time.perf_counter()
    bad = leaf_verdict(path, victim, prot)
    patches = reconstruct_leaves(
        prot, ctx, victim, bad, read_range, candidates, backend=be,
        on_bytes=lambda n: moved.__setitem__(0, moved[0] + n),
    )
    apply_leaf_repair(path, victim, prot, patches)
    leaf_repair_s = time.perf_counter() - t0
    leaf_repair_bytes = moved[0] + sum(len(p.data) for p in patches)
    with open(path, "rb") as f:
        repaired = f.read()

    # whole-shard rebuild of the same shard (bytes moved: k source
    # shards read + the regenerated shard written)
    os.unlink(path)
    t0 = time.perf_counter()
    rebuilt = rebuild_ec_files(base, ctx, backend=be)
    full_rebuild_s = time.perf_counter() - t0
    full_rebuild_bytes = (ctx.data_shards + 1) * len(original)
    with open(path, "rb") as f:
        rebuilt_bytes_disk = f.read()

    assert rebuilt == [victim]
    bit_identical = repaired == original and rebuilt_bytes_disk == original
    return {
        "leaf_repair_vs_full_rebuild": round(
            full_rebuild_bytes / max(leaf_repair_bytes, 1), 1
        ),
        "leaf_repair_bytes": leaf_repair_bytes,
        "full_rebuild_bytes": full_rebuild_bytes,
        "leaf_repair_s": round(leaf_repair_s, 4),
        "full_rebuild_s": round(full_rebuild_s, 4),
        "leaf_repair_bit_identical": bool(bit_identical),
    }


def _degraded_read_bench(base: str, n_reads: int = 12) -> dict:
    """BASELINE config 4: random needle reads with one data shard lost.
    Measures VERIFIED bytes-read amplification (sibling bytes fetched /
    needle bytes served) on the v2 leaf sidecar vs the same shards
    under a v1 (block-only) sidecar, plus the reconstructed-interval
    cache's effect on repeat reads. Correctness: every payload is
    checked against the fabricated volume's deterministic content."""
    from dataclasses import replace

    from seaweedfs_tpu.ec.bitrot import BitrotProtection
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ec.ec_volume import EcVolume
    from seaweedfs_tpu.ec.locate import locate_data
    from seaweedfs_tpu.storage.types import actual_offset

    ctx = DEFAULT_EC_CONTEXT
    directory = os.path.dirname(base)
    prot_v2 = BitrotProtection.load(base + ".ecsum")
    lost = 0
    shard_path = base + ctx.to_ext(lost)
    with open(shard_path, "rb") as f:
        saved_shard = f.read()
    os.unlink(shard_path)

    # the fabricated volume's deterministic payloads (see _fabricate_volume)
    blob = np.random.default_rng(0xB0B).integers(
        0, 256, size=1 << 20, dtype=np.uint8
    ).tobytes()

    def expected(nid: int) -> bytes:
        return blob[nid % 1024 :] + blob[: nid % 1024]

    def pick_needles(ev) -> list[int]:
        """Needle ids whose extents touch the lost shard (those are the
        degraded reads; others read straight from live shards)."""
        out = []
        nid = 1
        while len(out) < n_reads:
            nv = ev.find_needle(nid)
            if nv is None:
                break
            off = actual_offset(nv.offset)
            from seaweedfs_tpu.ec.decoder import record_actual_size

            rec = record_actual_size(nv.size, ev.version)
            ivs = locate_data(
                off, rec, ev._locate_shard_size, ctx.data_shards
            )
            if any(
                iv.to_shard_and_offset(ctx.data_shards)[0] == lost
                for iv in ivs
            ):
                out.append(nid)
            nid += 1
        return out

    def measure(cache_bytes: int) -> tuple[float, bool, float, "EcVolume"]:
        ev = EcVolume(
            directory, 1, backend_name="cpu",
            interval_cache_bytes=cache_bytes,
        )
        ids = pick_needles(ev)
        if not ids:
            ev.close()
            return 0.0, False, 0.0, ev
        ok = True
        served = 0
        b0 = ev.bytes_read
        t0 = time.perf_counter()
        for nid in ids:
            n = ev.read_needle(nid, cookie=0x1234)
            served += len(n.data)
            if n.data != expected(nid):
                ok = False
        dt = time.perf_counter() - t0
        amp = (ev.bytes_read - b0) / max(served, 1)
        return amp, ok, dt / len(ids), ev

    result: dict = {}
    try:
        # v2 sidecar (leaf-granular verify), cache off = raw amplification
        amp_v2, ok_v2, ms_v2, ev = measure(0)
        ev.close()
        # repeat-read behavior with the interval cache on
        ev = EcVolume(directory, 1, backend_name="cpu")
        ids = pick_needles(ev)
        for nid in ids:
            ev.read_needle(nid, cookie=0x1234)
        b_before = ev.bytes_read
        for nid in ids:
            ev.read_needle(nid, cookie=0x1234)
        cached_extra = ev.bytes_read - b_before
        ev.close()

        # v1 sidecar: same shards, leaves stripped — today's block-
        # granular behavior on identical data.
        replace(
            prot_v2, leaf_size=0, shard_leaf_crcs=[]
        ).save(base + ".ecsum")
        amp_v1, ok_v1, ms_v1, ev = measure(0)
        ev.close()
        result = {
            "degraded_amp_v1": round(amp_v1, 1),
            "degraded_amp_v2": round(amp_v2, 1),
            "degraded_amp_reduction": round(amp_v1 / max(amp_v2, 1e-9), 1),
            "degraded_read_ms_v1": round(ms_v1 * 1e3, 2),
            "degraded_read_ms_v2": round(ms_v2 * 1e3, 2),
            "degraded_verified": bool(ok_v1 and ok_v2),
            "degraded_cached_repeat_bytes": int(cached_extra),
        }
    finally:
        # restore the volume exactly: lost shard back, v2 sidecar back
        with open(shard_path, "wb") as f:
            f.write(saved_shard)
        prot_v2.save(base + ".ecsum")
    return result


def _bench_free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _gateway_client_phase(
    base: str,
    data: bytes,
    clients: int,
    reads_per_client: int,
    headers: dict | None = None,
) -> dict:
    """Fire `clients` concurrent keep-alive sessions, each doing
    `reads_per_client` byte-verified GETs; a threading.Barrier aligns
    the first wave so cold-cache misses genuinely collide. 503s are
    counted separately (clean backpressure, not corruption).
    `headers` (e.g. a SigV4 Authorization set) rides on every GET."""
    import threading

    import requests as _rq

    lat_lock = threading.Lock()
    latencies: list[float] = []
    errors = [0]
    rejected = [0]
    barrier = threading.Barrier(clients)

    def client() -> None:
        sess = _rq.Session()
        try:
            barrier.wait(timeout=30)
        except threading.BrokenBarrierError:
            pass
        for _ in range(reads_per_client):
            t0 = time.perf_counter()
            try:
                rr = sess.get(
                    f"{base}/bench/obj", timeout=120, headers=headers
                )
                if rr.status_code == 503:
                    with lat_lock:
                        rejected[0] += 1
                    continue
                ok = rr.status_code == 200 and rr.content == data
            except Exception:
                ok = False
            dt = time.perf_counter() - t0
            with lat_lock:
                if ok:
                    latencies.append(dt)
                else:
                    errors[0] += 1

    threads = [threading.Thread(target=client) for _ in range(clients)]
    t_all = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_all
    if not latencies:
        return {"error": "no successful GETs", "errors": errors[0]}
    lat_ms = np.array(sorted(latencies)) * 1e3
    return {
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "mean_ms": round(float(lat_ms.mean()), 2),
        "requests": len(latencies),
        "errors": errors[0],
        "rejected_503": rejected[0],
        "gets_per_s": round(len(latencies) / wall, 1),
    }


def _gateway_bench(
    workdir: str,
    clients: int = 100,
    reads_per_client: int = 5,
    naive_reads_per_client: int = 2,
    obj_bytes: int = 256 << 10,
) -> dict:
    """ISSUE 11 headline: p50/p99 S3 GET latency under `clients` (>=100)
    concurrent clients against a DEGRADED EC volume (one shard
    unmounted) over a real in-process cluster — real HTTP/gRPC on
    ephemeral ports, every payload byte-checked. TWO configurations in
    the same run:

    - NAIVE (the PR 9 baseline shape): unbounded one-thread-per-
      connection S3 front end, hot caches DISABLED (capacity 0 = no
      storage, no singleflight) — every GET pays the full
      reconstruction miss path;
    - TUNED: bounded worker-pool front ends + the tiered hot-chunk
      cache with singleflight collapse (first wave of misses collides
      on purpose via a start barrier and must collapse to one load per
      chunk, proven by the emitted singleflight counter).

    Published as gateway_degraded_get_{p50,p99,mean}_ms (tuned, the
    trended headline), gateway_naive_* (same-run baseline), and the
    gateway_singleflight_waits / gateway_hot_cache_* evidence."""
    import requests as _rq

    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.pb import cluster_pb2 as _cpb
    from seaweedfs_tpu.pb import rpc as _brpc
    from seaweedfs_tpu.s3 import S3Server
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.shell.commands import ShellEnv, run_command
    from seaweedfs_tpu.storage.file_id import FileId

    import grpc as _grpc

    gdir = os.path.join(workdir, "gateway")
    os.makedirs(gdir, exist_ok=True)
    mport = _bench_free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[os.path.join(gdir, "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=_bench_free_port(),
        ec_backend="cpu",
    )
    vs.start()
    filer = srv = srv_naive = env = None
    try:
        deadline = time.time() + 20
        while not master.topo.nodes:
            if time.time() > deadline:
                raise TimeoutError("volume server never registered")
            time.sleep(0.05)
        filer = Filer(
            MemoryStore(), master=f"localhost:{mport}",
            chunk_size=64 * 1024,
        )
        # tuned front end: bounded worker pool (the production shape)
        srv = S3Server(filer, ip="localhost", port=_bench_free_port())
        srv.start()
        # naive front end: the unbounded ThreadingHTTPServer baseline,
        # same filer/volume underneath
        srv_naive = S3Server(
            filer, ip="localhost", port=_bench_free_port(), http_workers=0
        )
        srv_naive.start()
        base = f"http://localhost:{srv.port}"
        base_naive = f"http://localhost:{srv_naive.port}"
        rng = np.random.default_rng(0x6A7E)
        data = rng.integers(0, 256, obj_bytes, dtype=np.uint8).tobytes()
        assert _rq.put(f"{base}/bench").status_code == 200
        assert _rq.put(f"{base}/bench/obj", data=data).status_code == 200

        entry = filer.find_entry("/buckets/bench/obj")
        vid = FileId.parse(entry.chunks[0].fid).volume_id
        env = ShellEnv(f"localhost:{mport}")
        out = run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        if "generation" not in out:
            raise RuntimeError(f"ec.encode failed: {out}")
        deadline = time.time() + 20
        while not any(
            vid in n.ec_shards for n in master.topo.nodes.values()
        ):
            if time.time() > deadline:
                raise TimeoutError("ec shards never registered")
            time.sleep(0.1)
        # quarantine one data shard: every GET touching its stripe is
        # now a verified degraded reconstruction on the volume server
        with _grpc.insecure_channel(f"localhost:{vs.grpc_port}") as ch:
            _brpc.volume_stub(ch).VolumeEcShardsUnmount(
                _cpb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[0])
            )
        r = _rq.get(f"{base}/bench/obj", timeout=120)
        if r.status_code != 200 or r.content != data:
            raise RuntimeError(
                f"warmup degraded GET failed: {r.status_code}"
            )

        chunk_cache = filer.chunk_cache
        interval_cache = vs.store.ec_interval_cache
        tuned_caps = (
            chunk_cache.capacity,
            interval_cache.capacity if interval_cache is not None else 0,
        )

        def set_caches(enabled: bool) -> None:
            chunk_cache.capacity = tuned_caps[0] if enabled else 0
            chunk_cache.clear()
            if interval_cache is not None:
                interval_cache.capacity = tuned_caps[1] if enabled else 0
                interval_cache.clear()

        # ---- NAIVE: caches off (capacity 0 = pass-through, no
        # singleflight), unbounded-thread front end — the miss path the
        # tiered cache exists to kill. Fewer reads per client: every
        # one pays a reconstruction.
        set_caches(False)
        naive = _gateway_client_phase(
            base_naive, data, clients, naive_reads_per_client
        )

        # ---- TUNED: caches restored and dropped ONCE, so the barrier-
        # aligned first wave is `clients` concurrent misses that must
        # singleflight-collapse; the rest ride the hot tier.
        set_caches(True)
        sf_before = (
            chunk_cache.singleflight_waits
            + (interval_cache.singleflight_waits if interval_cache else 0)
        )
        loads_before = chunk_cache.loads
        hits_before = chunk_cache.hits
        tuned = _gateway_client_phase(base, data, clients, reads_per_client)
        sf_waits = (
            chunk_cache.singleflight_waits
            + (interval_cache.singleflight_waits if interval_cache else 0)
            - sf_before
        )
        if "error" in tuned:
            return {"gateway_error": tuned["error"]}
        out = {
            "gateway_degraded_get_p50_ms": tuned["p50_ms"],
            "gateway_degraded_get_p99_ms": tuned["p99_ms"],
            "gateway_degraded_get_mean_ms": tuned["mean_ms"],
            "gateway_clients": clients,
            "gateway_requests": tuned["requests"],
            "gateway_errors": tuned["errors"],
            "gateway_rejected_503": tuned["rejected_503"],
            "gateway_object_kb": obj_bytes >> 10,
            "gateway_gets_per_s": tuned["gets_per_s"],
            # singleflight proof: the first wave's concurrent misses
            # joined in-flight loads instead of re-running them; the
            # chunk-load count stays ~#chunks, not #clients x #chunks
            "gateway_singleflight_waits": int(sf_waits),
            "gateway_hot_cache_loads": int(
                chunk_cache.loads - loads_before
            ),
            "gateway_hot_cache_hits": int(chunk_cache.hits - hits_before),
            "gateway_front_end": getattr(
                srv._http, "pool_status", lambda: {"kind": "threading"}
            )(),
        }
        if "error" not in naive:
            out.update(
                {
                    "gateway_naive_p50_ms": naive["p50_ms"],
                    "gateway_naive_p99_ms": naive["p99_ms"],
                    "gateway_naive_mean_ms": naive["mean_ms"],
                    "gateway_naive_gets_per_s": naive["gets_per_s"],
                    "gateway_naive_errors": naive["errors"],
                    "gateway_naive_requests": naive["requests"],
                    "gateway_p99_speedup_vs_naive": round(
                        naive["p99_ms"] / max(tuned["p99_ms"], 1e-9), 2
                    ),
                }
            )
        else:
            out["gateway_naive_error"] = naive["error"]
        return out
    finally:
        for closer in (
            (lambda: env.close()) if env is not None else None,
            (lambda: srv.stop()) if srv is not None else None,
            (lambda: srv_naive.stop()) if srv_naive is not None else None,
            (lambda: filer.close()) if filer is not None else None,
            vs.stop,
            master.stop,
        ):
            if closer is None:
                continue
            try:
                closer()
            except Exception:
                pass


def _net_counter_delta(
    before: dict, after: dict, plane: str, direction: str | None = None
) -> float:
    """Delta of one sw_net_bytes_* family for `plane`, summed across
    directions (or one direction when given) — keys are
    (plane, direction) label tuples."""

    def total(snap: dict) -> float:
        return sum(
            v for k, v in snap.items()
            if k and k[0] == plane
            and (direction is None or (len(k) > 1 and k[1] == direction))
        )

    return float(total(after) - total(before))


def _peer_rebuild_bench(workdir: str, shard_mb: int = 8, reps: int = 2) -> dict:
    """ISSUE 12 headline: peer-fetch rebuild throughput, NATIVE vs
    PYTHON network planes over the SAME loopback TCP wire in one run.

    The native plane is a real ShardNetPlane server (sendfile(2) shard
    egress) with `fetch_into` ingress landing streams straight into
    pooled aligned buffers, the granule CRC fused into the copy-in; the
    Python plane (SEAWEED_EC_NATIVE=0 for the whole run, so source,
    sink, AND wire are Python) moves the same bytes over the same
    socket through `bytes` materialization at every seam. Interleaved
    best-of-`reps`; the regenerated shard is asserted byte-identical
    across planes AND to the original (peer_rebuild_identical in the
    line). bytes_copied_per_byte_served per plane is derived from the
    sw_net_bytes_{copied,received}_total counters around each run —
    ~0.0 for the native plane is the zero-copy evidence."""
    import numpy as _np

    from seaweedfs_tpu.ec import net_plane as _netp
    from seaweedfs_tpu.ec.backend import CpuBackend as _Cpu
    from seaweedfs_tpu.ec.bitrot import (
        BitrotProtection as _BP,
        ShardChecksumBuilder as _Builder,
    )
    from seaweedfs_tpu.ec.context import ECContext as _Ctx
    from seaweedfs_tpu.ec.peer_rebuild import (
        PeerFetchTransient as _Transient,
        rebuild_from_peers as _rebuild,
    )
    from seaweedfs_tpu.utils import metrics as _M

    # tmpfs when available: the ≥1.2x native-vs-python target is a
    # byte-path number, not a disk benchmark
    root = "/dev/shm" if os.access("/dev/shm", os.W_OK) else workdir
    bdir = tempfile.mkdtemp(prefix="sw_peer_bench_", dir=root)
    ctx = _Ctx(4, 2)
    shard_bytes = shard_mb << 20
    generation = 7
    fds: dict = {}
    try:
        rng = _np.random.default_rng(0xBEEF)
        data = rng.integers(
            0, 256, (ctx.data_shards, shard_bytes), dtype=_np.uint8
        )
        shards = _np.concatenate([data, _Cpu(ctx).encode(data)], axis=0)
        builders = [
            _Builder(1 << 22, 64 * 1024) for _ in range(ctx.total)
        ]
        peer_dir = os.path.join(bdir, "peer")
        os.makedirs(peer_dir)
        fds = {}
        for i in range(ctx.total):
            blob = shards[i].tobytes()
            builders[i].write(blob)
            p = os.path.join(peer_dir, f"1{ctx.to_ext(i)}")
            with open(p, "wb") as f:
                f.write(blob)
            fds[i] = os.open(p, os.O_RDONLY)
        prot = _BP.from_builders(ctx, builders, generation=generation)

        def resolve(vid, sid, gen):
            if gen and gen != generation:
                raise _netp.NetPlaneError("stale generation")
            if sid not in fds:
                raise _netp.NetPlaneError("shard not local")
            return fds[sid], shard_bytes

        srv = _netp.ShardNetPlane(
            "127.0.0.1", 0, resolve, server_label="bench-peer"
        )
        srv.start()
        addr = ("127.0.0.1", srv.port)
        client = _netp.NetPlaneClient()

        def fetch(peer, sid, off, size):
            try:
                return client.read_bytes(addr, 1, sid, generation, off, size)
            except (_netp.NetPlaneError, _netp.NetPlaneUnavailable) as e:
                raise _Transient(str(e)) from e

        fetch_into = _netp.make_fetch_into(
            client, 1, generation, addr_of=lambda peer: addr
        )
        backend = _Cpu(ctx)
        # cluster-lost-holder bootstrap shape: NOTHING local but the
        # sidecar, every source crosses the wire — the configuration
        # this plane exists for (wire-dominated, k fetched streams).
        holders = {sid: ["peer"] for sid in range(ctx.data_shards + 1)}

        walls = {"native": [], "python": []}
        copied_per_served = {}
        rebuilt = {}
        prev_env = os.environ.get("SEAWEED_EC_NATIVE")
        try:
            for rep in range(reps):
                for plane in ("native", "python"):
                    ldir = os.path.join(bdir, f"{plane}{rep}")
                    os.makedirs(ldir)
                    base = os.path.join(ldir, "1")
                    prot.save(base + ".ecsum")
                    if plane == "python":
                        os.environ["SEAWEED_EC_NATIVE"] = "0"
                    else:
                        os.environ.pop("SEAWEED_EC_NATIVE", None)
                    cop0 = _M.net_bytes_copied_total.snapshot()
                    rec0 = _M.net_bytes_received_total.snapshot()
                    t0 = time.perf_counter()
                    rep_out = _rebuild(
                        base, holders, fetch, ctx=ctx, targets=[5],
                        backend=backend,
                        fetch_into=(
                            fetch_into if plane == "native" else None
                        ),
                    )
                    walls[plane].append(time.perf_counter() - t0)
                    cop1 = _M.net_bytes_copied_total.snapshot()
                    rec1 = _M.net_bytes_received_total.snapshot()
                    served = _net_counter_delta(rec0, rec1, plane)
                    copied = _net_counter_delta(cop0, cop1, plane)
                    if served > 0:
                        copied_per_served[plane] = round(copied / served, 2)
                    fetched_count = len(rep_out.fetched)
                    if rep_out.rebuilt != [5] or set(
                        rep_out.fetched_plane.values()
                    ) != {plane}:
                        return {
                            "peer_rebuild_error": (
                                f"{plane}: rebuilt={rep_out.rebuilt} "
                                f"planes={rep_out.fetched_plane}"
                            )
                        }
                    with open(base + ctx.to_ext(5), "rb") as f:
                        rebuilt[plane] = f.read()
        finally:
            if prev_env is None:
                os.environ.pop("SEAWEED_EC_NATIVE", None)
            else:
                os.environ["SEAWEED_EC_NATIVE"] = prev_env
            client.close()
            srv.stop()

        identical = (
            rebuilt["native"] == rebuilt["python"] == shards[5].tobytes()
        )
        # throughput denominator: sibling bytes moved over the wire
        wire = fetched_count * shard_bytes
        native_gbs = wire / min(walls["native"]) / 1e9
        python_gbs = wire / min(walls["python"]) / 1e9
        return {
            "peer_rebuild_gbs": round(native_gbs, 3),
            "peer_rebuild_python_gbs": round(python_gbs, 3),
            "peer_rebuild_native_vs_python": round(
                native_gbs / max(python_gbs, 1e-9), 2
            ),
            "peer_rebuild_identical": bool(identical),
            "peer_rebuild_wire_mb": wire >> 20,
            "peer_rebuild_staging": root,
            "bytes_copied_per_byte_served_native": copied_per_served.get(
                "native", 0.0
            ),
            "bytes_copied_per_byte_served_python": copied_per_served.get(
                "python", 0.0
            ),
        }
    finally:
        for fd in fds.values():
            try:
                os.close(fd)
            except OSError:
                pass
        shutil.rmtree(bdir, ignore_errors=True)


def _ec_rebalance_bench(
    workdir: str,
    payload_bytes: int = 1 << 20,
    reads_per_phase: int = 6,
    load_threads: int = 4,
) -> dict:
    """ISSUE 15 headline: degraded-read throughput BEFORE vs AFTER one
    data-gravity pass, in the same run, over a real in-process cluster.

    Shape: a skewed mini-cluster — the hot EC volume lives on node A,
    whose device queue is SATURATED by a competing admission load (the
    chip-poor/busy holder), while node B idles. B's heartbeat telemetry
    is shimmed to report 8 idle chips (this box has none — the same
    emulation discipline as the 8-virtual-device placement bench); A
    reports its real (chip-less, loaded) blob, and the volume HEAT
    counters are real bytes from the measured reads. The gravity pass
    is the PRODUCTION loop end to end: heartbeat telemetry -> master
    scan (`scan_for_ec_rebalance` -> plan_hot_migrations) -> ec_migrate
    task -> a real connected Worker -> `drive_migration` (net-plane
    copy, sidecar verify, unmount-then-mount). Evidence in the line:
    before/after reads-per-second, migrated-shard bit-identity, the
    exactly-one-mounted-holder invariant, and the migration's wire
    bytes attributed to the native plane
    (sw_net_bytes_received_total{plane=native})."""
    import hashlib

    import requests as _rq

    from seaweedfs_tpu.ec import native_io
    from seaweedfs_tpu.ec.device_queue import batch_cost
    from seaweedfs_tpu.pb import cluster_pb2 as _cpb
    from seaweedfs_tpu.pb import rpc as _brpc
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.shell.commands import ShellEnv, run_command
    from seaweedfs_tpu.storage.file_id import FileId
    from seaweedfs_tpu.utils import metrics as _M
    from seaweedfs_tpu.worker.worker import Worker

    import grpc as _grpc

    gdir = os.path.join(workdir, "rebalance")
    os.makedirs(gdir, exist_ok=True)
    mport = _bench_free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs_a = VolumeServer(
        directories=[os.path.join(gdir, "a")],
        master=f"localhost:{mport}", ip="localhost",
        port=_bench_free_port(), ec_backend="cpu",
        ec_interval_cache_mb=0,  # every degraded read reconstructs
    )
    vs_a.start()
    vs_b = env = worker = wt = None
    stop_load = threading.Event()
    loaders: list[threading.Thread] = []
    try:
        deadline = time.time() + 20
        while not master.topo.nodes:
            if time.time() > deadline:
                raise TimeoutError("volume server A never registered")
            time.sleep(0.05)
        # one needle, EC-encoded on A, one data shard quarantined:
        # every read is a verified degraded reconstruction
        a = _rq.get(f"http://localhost:{mport}/dir/assign").json()
        fid = a["fid"]
        vid = FileId.parse(fid).volume_id
        nid, cookie = FileId.parse(fid).needle_id, FileId.parse(fid).cookie
        payload = np.random.default_rng(0x6417).integers(
            0, 256, payload_bytes, dtype=np.uint8
        ).tobytes()
        r = _rq.post(
            f"http://{a['url']}/{fid}", files={"file": ("x.bin", payload)}
        )
        if r.status_code != 201:
            raise RuntimeError(f"upload failed: {r.status_code}")
        env = ShellEnv(f"localhost:{mport}")
        out = run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        if "generation" not in out:
            raise RuntimeError(f"ec.encode failed: {out}")
        with _grpc.insecure_channel(f"localhost:{vs_a.grpc_port}") as ch:
            _brpc.volume_stub(ch).VolumeEcShardsUnmount(
                _cpb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[0])
            )
        abase = vs_a.service._ec_base(vid, "")
        ev_a = vs_a.store.find_ec_volume(vid)
        migr_sids = sorted(ev_a.shard_fds)
        ground = {
            s: hashlib.sha256(
                open(abase + f".ec{s:02d}", "rb").read()
            ).hexdigest()
            for s in migr_sids
        }
        shard_sz = os.path.getsize(abase + f".ec{migr_sids[0]:02d}")

        # node B: the chip-rich idle destination (telemetry shim — the
        # box has no TPUs, so B REPORTS 8 idle chips; heat and every
        # byte moved stay real)
        vs_b = VolumeServer(
            directories=[os.path.join(gdir, "b")],
            master=f"localhost:{mport}", ip="localhost",
            port=_bench_free_port(), ec_backend="cpu",
            ec_interval_cache_mb=0,
        )
        orig_tele = vs_b._ec_telemetry_json

        def b_tele() -> str:
            blob = json.loads(orig_tele())
            blob["chips"] = {
                f"tpu:{i}": {"load": 0, "breaker": "closed"}
                for i in range(8)
            }
            return json.dumps(blob)

        vs_b._ec_telemetry_json = b_tele
        vs_b.start()
        deadline = time.time() + 20
        while len(master.topo.nodes) < 2:
            if time.time() > deadline:
                raise TimeoutError("volume server B never registered")
            time.sleep(0.05)

        # saturate A's device queue: the competing foreground load the
        # hot volume is stuck behind (the busy-holder half of the
        # skew). Loaders must OUTNUMBER the admission window or a slot
        # is always free and reads never wait.
        queue_a = vs_a.store.ec_scheduler.for_backend(ev_a.backend)
        window = getattr(queue_a, "window", 4) if queue_a else 0

        def loader():
            while not stop_load.is_set():
                with queue_a.admission(
                    "foreground", batch_cost(4, 1 << 20)
                ):
                    time.sleep(0.05)

        if queue_a is not None:
            for _ in range(max(load_threads, window + 2)):
                t = threading.Thread(target=loader, daemon=True)
                t.start()
                loaders.append(t)

        def read_phase(vs) -> tuple[float, bool]:
            okay = True
            t0 = time.perf_counter()
            for _ in range(reads_per_phase):
                n = vs.store.read_needle(vid, nid, cookie)
                okay = okay and (n.data == payload)
            return time.perf_counter() - t0, okay

        # connected worker BEFORE the scan (param validation needs its
        # ec_migrate descriptor; dispatch needs a live stream)
        worker = Worker(master=f"localhost:{mport}", backend="cpu")
        wt = threading.Thread(target=worker.run, daemon=True)
        wt.start()
        wc = master.worker_control
        deadline = time.time() + 20
        while not wc.snapshot()[0]:
            if time.time() > deadline:
                raise TimeoutError("worker never registered")
            time.sleep(0.05)

        def heat_at_master() -> int:
            for n in master.topo.nodes.values():
                if n.port == vs_a.port:
                    vols = n.ec_telemetry.get("ec_volumes", {})
                    return int(vols.get(str(vid), {}).get("read_bytes", 0))
            return 0

        # warmup (compile/caches), then wait for the heat counters to
        # reach the master so the BASELINE sweep records them
        read_phase(vs_a)
        deadline = time.time() + 20
        while heat_at_master() == 0:
            if time.time() > deadline:
                raise TimeoutError("heat never reached the master")
            time.sleep(0.1)
        heat_at_baseline = heat_at_master()
        if wc.scan_for_ec_rebalance(topo=master.topo):
            return {
                "ec_rebalance_error": "baseline sweep dispatched early"
            }

        # BEFORE: measured degraded reads on the saturated holder
        before_s, ok_before = read_phase(vs_a)
        deadline = time.time() + 30
        while heat_at_master() <= heat_at_baseline:
            if time.time() > deadline:
                raise TimeoutError("post-read heat never reached master")
            time.sleep(0.1)
        heat_floor = heat_at_master()

        rec0 = _M.net_bytes_received_total.snapshot()
        tids = wc.scan_for_ec_rebalance(topo=master.topo, min_heat=1 << 20)
        if not tids:
            return {"ec_rebalance_error": "gravity scan planned nothing"}
        deadline = time.time() + 120
        while True:
            _, tasks = wc.snapshot()
            t = next(t for t in tasks if t["task_id"] == tids[0])
            if t["state"] == "done":
                break
            if t["state"] == "failed":
                return {
                    "ec_rebalance_error": f"ec_migrate failed: {t['error']}"
                }
            if time.time() > deadline:
                return {"ec_rebalance_error": "ec_migrate never finished"}
            time.sleep(0.1)
        rec1 = _M.net_bytes_received_total.snapshot()
        wire_native = _net_counter_delta(rec0, rec1, "native")
        wire_python = _net_counter_delta(rec0, rec1, "python")

        # convergence + the exactly-one-mounted-holder invariant
        deadline = time.time() + 20
        while vs_b.store.find_ec_volume(vid) is None or set(
            vs_b.store.find_ec_volume(vid).shard_fds
        ) != set(migr_sids):
            if time.time() > deadline:
                raise TimeoutError("destination never mounted the set")
            time.sleep(0.1)
        one_holder = vs_a.store.find_ec_volume(vid) is None
        bbase = vs_b.service._ec_base(vid, "")
        identical = all(
            hashlib.sha256(
                open(bbase + f".ec{s:02d}", "rb").read()
            ).hexdigest() == ground[s]
            for s in migr_sids
        )

        # AFTER: the same degraded reads, now served by the idle node
        # (one unmeasured warmup read pays B's coeff/locate caches the
        # way A's warmup did)
        vs_b.store.read_needle(vid, nid, cookie)
        after_s, ok_after = read_phase(vs_b)
        identical = identical and ok_before and ok_after

        before_rps = reads_per_phase / max(before_s, 1e-9)
        after_rps = reads_per_phase / max(after_s, 1e-9)
        return {
            "ec_rebalance_before_reads_per_s": round(before_rps, 2),
            "ec_rebalance_after_reads_per_s": round(after_rps, 2),
            "ec_rebalance_speedup": round(
                after_rps / max(before_rps, 1e-9), 2
            ),
            "ec_rebalance_identical": bool(identical),
            "ec_rebalance_exactly_one_holder": bool(one_holder),
            "ec_rebalance_migrated_shards": len(migr_sids),
            "ec_rebalance_wire_native_mb": round(wire_native / 1e6, 2),
            "ec_rebalance_wire_python_mb": round(wire_python / 1e6, 2),
            "ec_rebalance_native_plane": bool(
                native_io.enabled() and wire_native >= len(migr_sids)
                * shard_sz
            ),
            "ec_rebalance_heat_bytes": int(heat_floor),
            "ec_rebalance_payload_kb": payload_bytes >> 10,
        }
    finally:
        stop_load.set()
        for t in loaders:
            t.join(timeout=5)
        for closer in (
            (lambda: worker.stop()) if worker is not None else None,
            (lambda: env.close()) if env is not None else None,
            (lambda: vs_b.stop()) if vs_b is not None else None,
            vs_a.stop,
            master.stop,
        ):
            if closer is None:
                continue
            try:
                closer()
            except Exception:
                pass


def _tenant_storm_bench(
    n_storm_scopes: int = 6,
    threads_per_scope: int = 4,
    victim_batches: int = 60,
    work_s: float = 0.002,
    budget: int = 4,
) -> dict:
    """ISSUE 16 headline: victim-tenant p99 under a tenant storm with
    the residency budget ON vs OFF, in the same run.

    Shape: one physical "chip" (a fake backend whose device time is a
    lock + {work_s} of serialized work — the admission-policy analogue
    of the emulated 8-device placement bench), oversubscribed by
    `n_storm_scopes` independently-created QueueScopes all owned by one
    storm tenant. Each scope carries the full default window, so the
    combined LOGICAL windows (scopes x window) admit far past the
    physical chip. A well-behaved victim tenant issues serial
    foreground batches through its own scope the whole time.

    OFF phase (`residency=False`, the pre-PR 16 behavior): every
    scope's window admits independently — the victim's batch queues
    behind up to scopes*window storm batches at the device. ON phase
    (one shared ResidencyLedger): total in-flight is capped at the
    physical budget and deficit-weighted fairness ranks the
    low-usage victim ahead of the storm, so its p99 is bounded.
    Evidence in the line: victim p99 both ways, the ratio, and the
    residency invariant from the ledger's own high-watermark ground
    truth (max_inflight <= budget on the storm chip)."""
    from seaweedfs_tpu.ec.device_queue import (
        DEFAULT_WINDOW,
        QueueScope,
        ResidencyLedger,
    )

    class _StormChip:
        """Fake pinned backend: all instances share ONE chip label, so
        every scope's queue charges the same physical residency key."""

        chip_label = "storm:0"

    dev_lock = threading.Lock()

    def run_phase(ledger) -> tuple[list[float], int]:
        """One storm+victim pass; returns (victim batch latencies s,
        peak concurrent device occupancy observed by the fake chip)."""
        occ = {"now": 0, "peak": 0}
        occ_lock = threading.Lock()

        def device_work():
            with occ_lock:
                occ["now"] += 1
                occ["peak"] = max(occ["peak"], occ["now"])
            try:
                with dev_lock:
                    time.sleep(work_s)
            finally:
                with occ_lock:
                    occ["now"] -= 1

        residency = ledger if ledger is not None else False
        storm_scopes = [
            QueueScope(
                window=DEFAULT_WINDOW, tenant="storm", residency=residency
            )
            for _ in range(n_storm_scopes)
        ]
        victim_scope = QueueScope(
            window=DEFAULT_WINDOW, tenant="victim", residency=residency
        )
        stop = threading.Event()

        def storm(scope):
            backend = _StormChip()
            q = scope.for_backend(backend)
            s = q.stream("foreground")
            try:
                while not stop.is_set():
                    t, _ = s.dispatch(device_work, 1)
                    s.release(t)
            finally:
                s.close()

        storm_threads = [
            threading.Thread(target=storm, args=(sc,), daemon=True)
            for sc in storm_scopes
            for _ in range(threads_per_scope)
        ]
        for t in storm_threads:
            t.start()
        time.sleep(0.05)  # let the storm saturate before measuring
        lat: list[float] = []
        vq = victim_scope.for_backend(_StormChip())
        vs = vq.stream("foreground")
        try:
            for _ in range(victim_batches):
                t0 = time.perf_counter()
                t, _ = vs.dispatch(device_work, 1)
                vs.release(t)
                lat.append(time.perf_counter() - t0)
        finally:
            vs.close()
            stop.set()
            for t in storm_threads:
                t.join(timeout=10)
        return lat, occ["peak"]

    def p99(xs: list[float]) -> float:
        return sorted(xs)[max(int(len(xs) * 0.99) - 1, 0)]

    lat_off, peak_off = run_phase(None)
    ledger = ResidencyLedger(budget=budget)
    lat_on, peak_on = run_phase(ledger)
    snap = ledger.snapshot()
    chip = snap["chips"].get("storm:0", {})
    # Ground truth for the residency invariant is the LEDGER's own
    # high-watermark, cross-checked against the fake chip's
    # independently-observed peak occupancy.
    invariant_ok = bool(
        chip and chip.get("max_inflight", 0) <= budget and peak_on <= budget
    )
    off_p99, on_p99 = p99(lat_off), p99(lat_on)
    return {
        "tenant_storm_victim_p99_ms_budget_on": round(on_p99 * 1e3, 2),
        "tenant_storm_victim_p99_ms_budget_off": round(off_p99 * 1e3, 2),
        "tenant_storm_victim_p99_off_over_on": round(
            off_p99 / max(on_p99, 1e-9), 2
        ),
        "tenant_storm_residency_invariant_ok": invariant_ok,
        "tenant_storm_peak_inflight_budget_on": int(peak_on),
        "tenant_storm_peak_inflight_budget_off": int(peak_off),
        "tenant_storm_scopes": n_storm_scopes,
        "tenant_storm_budget": budget,
    }


def _pod_encode_bench(reps: int = 3, width: int | None = None) -> dict:
    """Pod-sharded wide-stream encode (ISSUE 15): the explicit
    NamedSharding/pjit lowering over the FULL device mesh vs the
    per-device shard_map lowering, same data, interleaved best-of-N,
    parity verified against the CPU truth both ways. Runs on whatever
    mesh the current platform exposes: the hermetic stage forces the
    8-virtual-device CPU platform; the device-phase variant (gated on
    the probe reporting >= 2 devices) runs on the real pod, where pjit
    is also the lowering that can span multi-process platforms."""
    import jax

    from seaweedfs_tpu.ec.backend import CpuBackend
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ops.rs_jax import RSJax
    from seaweedfs_tpu.parallel import MeshRS, make_mesh, pad_cols

    devs = jax.devices()
    if len(devs) < 2:
        return {"skipped": f"single-device platform ({devs[0].platform})"}
    width = width or int(
        os.environ.get("SEAWEED_BENCH_POD_WIDTH_MB", "4")
    ) << 20
    ctx = DEFAULT_EC_CONTEXT
    rng = np.random.default_rng(0x90D)
    data = rng.integers(0, 256, (ctx.data_shards, width), dtype=np.uint8)
    want_crc = zlib.crc32(
        np.ascontiguousarray(CpuBackend(ctx).encode(data)).tobytes()
    )
    rs = RSJax(ctx.data_shards, ctx.parity_shards, impl="xla")
    mesh = make_mesh(len(devs))
    padded, n = pad_cols(data, len(devs))

    prev = os.environ.get("SEAWEED_EC_POD_PJIT")
    variants: dict[str, MeshRS] = {}
    try:
        os.environ["SEAWEED_EC_POD_PJIT"] = "1"
        variants["pjit"] = MeshRS(rs, mesh)
        os.environ["SEAWEED_EC_POD_PJIT"] = "0"
        variants["shard_map"] = MeshRS(rs, mesh)
    finally:
        if prev is None:
            os.environ.pop("SEAWEED_EC_POD_PJIT", None)
        else:
            os.environ["SEAWEED_EC_POD_PJIT"] = prev

    def one(m: MeshRS) -> tuple[float, bool]:
        staged = m.put(padded)
        t0 = time.perf_counter()
        out = np.asarray(m.encode(staged), dtype=np.uint8)[:, :n]
        dt = time.perf_counter() - t0
        return dt, zlib.crc32(np.ascontiguousarray(out).tobytes()) == want_crc

    # warmup compiles both lowerings; timed passes interleave
    ok = all(one(m)[1] for m in variants.values())
    best = {k: float("inf") for k in variants}
    for _ in range(reps):
        for k, m in variants.items():
            dt, good = one(m)
            ok = ok and good
            best[k] = min(best[k], dt)
    gbs = {
        k: (ctx.parity_shards * width) / best[k] / 1e9 for k in best
    }
    return {
        "pod_encode_pjit_gbs": round(gbs["pjit"], 3),
        "pod_encode_shard_map_gbs": round(gbs["shard_map"], 3),
        "pod_encode_pjit_vs_shard_map": round(
            gbs["pjit"] / max(gbs["shard_map"], 1e-9), 2
        ),
        "pod_encode_identical": bool(ok),
        "pod_encode_devices": len(devs),
        "pod_encode_platform": devs[0].platform,
        "pod_encode_width_mb": width >> 20,
    }


def _bench_sign_v4(
    method: str, netloc: str, path: str, access: str, secret: str,
    region: str = "us-east-1",
) -> dict:
    """Header-auth SigV4 signature for the warm bench's client phases
    (UNSIGNED-PAYLOAD, host+date+content-sha signed) — what an SDK
    sends, so the server's s3.auth stage does real verification work.
    Rides the shared signer next to the verifier (s3/auth.sign_v4) so
    canonicalization lives in one place."""
    from seaweedfs_tpu.s3.auth import sign_v4

    return sign_v4(
        method, path,
        access_key=access, secret_key=secret,
        headers={"host": netloc},
        payload_hash="UNSIGNED-PAYLOAD",
        region=region,
    )


# response headers that legitimately differ per request (ids, clocks) —
# everything else must be bit-identical across the fast/off/hit phases
_WARM_VOLATILE_HEADERS = {
    "date", "x-request-id", "x-sw-trace-id", "x-sw-parent-span",
}


def _warm_capture_get(base: str, headers: dict):
    """(status, stable-headers, body) of one GET — the bit-identity
    unit the warm bench compares across fast-paths on/off/hit."""
    import requests as _rq

    r = _rq.get(f"{base}/bench/obj", timeout=60, headers=headers)
    stable = tuple(sorted(
        (k.lower(), v) for k, v in r.headers.items()
        if k.lower() not in _WARM_VOLATILE_HEADERS
    ))
    return r.status_code, stable, r.content


_WARM_STAGES = ("s3.auth", "filer.lookup", "chunk.fetch")


def _warm_stage_ms(snap0: dict, snap1: dict, requests_n: int) -> dict:
    """Per-request mean milliseconds of each gateway stage between two
    sw_ec_stage_seconds snapshots (summed across op/chip labels)."""
    out: dict[str, float] = {}
    for key, (_c, _t, ssum) in snap1.items():
        stage = key[1] if len(key) >= 2 else ""
        if stage not in _WARM_STAGES:
            continue
        prev = snap0.get(key)
        out[stage] = out.get(stage, 0.0) + ssum - (prev[2] if prev else 0.0)
    return {
        k: round(v * 1000.0 / max(requests_n, 1), 3)
        for k, v in out.items()
    }


def _gateway_warm_bench(
    workdir: str,
    clients: int = 16,
    reads_per_client: int = 25,
    obj_bytes: int = 256 << 10,
) -> dict:
    """Warm-path gateway GETs, fast paths ON vs OFF in ONE run
    (ISSUE 13). After PR 12 the residual warm ceiling was the control
    plane: SigV4 auth + filer lookup in Python per request, plus the
    filer->volume chunk fetch re-buffering through `requests`. The
    fast configuration turns on the SigV4 verdict memo, the
    entry-lookup cache, the chunk fetch over the shard net plane, and
    the native body egress; the off configuration disables all four
    (SEAWEED_EC_NATIVE=0, SEAWEED_S3_AUTH_MEMO=0, chunk plane off,
    entry cache capacity 0). The filer CHUNK cache is off in BOTH
    phases so every GET pays the real lookup+fetch path — the line
    measures this PR's stages, not PR 11's hot cache. Requests are
    SigV4-signed so s3.auth does real verification work; every body is
    byte-verified in the client phase AND one (status, headers, body)
    capture per configuration — off, fast-miss, fast-hit — is asserted
    bit-identical in the emitted line. The per-request
    s3.auth/filer.lookup/chunk.fetch stage budget (PR 9 trace stages)
    and the counter evidence (memo/entry-cache hits, chunk bytes on
    the native plane) ride along."""
    import requests as _rq

    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.s3 import S3Server
    from seaweedfs_tpu.s3 import auth as _s3auth
    from seaweedfs_tpu.s3.auth import Identity, IdentityStore
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.utils import metrics as _M
    from seaweedfs_tpu.utils import trace as _tr

    gdir = os.path.join(workdir, "gateway_warm")
    os.makedirs(gdir, exist_ok=True)
    mport = _bench_free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[os.path.join(gdir, "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=_bench_free_port(),
        ec_backend="cpu",
    )
    vs.start()
    filer = srv = None
    _ENV_KEYS = (
        "SEAWEED_EC_NATIVE", "SEAWEED_S3_AUTH_MEMO",
        "SEAWEED_CHUNK_NET_PLANE",
    )
    prev_env = {k: os.environ.get(k) for k in _ENV_KEYS}
    was_armed = _tr.armed
    try:
        deadline = time.time() + 20
        while not master.topo.nodes:
            if time.time() > deadline:
                raise TimeoutError("volume server never registered")
            time.sleep(0.05)
        # chunk cache OFF: every GET pays lookup + volume fetch — the
        # stages this PR targets (the hot-chunk tier is PR 11's win,
        # measured by gateway_degraded_get). SQLITE store, not
        # MemoryStore: the entry cache's claim is "stop hitting
        # store.find", which only means something against a store
        # whose find costs something (a dict-backed MemoryStore would
        # flatter the off phase).
        from seaweedfs_tpu.filer.filer_store import SqliteStore

        filer = Filer(
            SqliteStore(os.path.join(gdir, "filer.db")),
            master=f"localhost:{mport}",
            chunk_size=256 * 1024, chunk_cache_bytes=0,
        )
        idents = IdentityStore()
        idents.add(Identity("bench", "AKIDBENCH", "bench-secret-13"))
        srv = S3Server(
            filer, ip="localhost", port=_bench_free_port(),
            identities=idents,
        )
        srv.start()
        base = f"http://localhost:{srv.port}"
        netloc = f"localhost:{srv.port}"

        def sign(method, path):
            return _bench_sign_v4(
                method, netloc, path, "AKIDBENCH", "bench-secret-13"
            )

        rng = np.random.default_rng(0x3A3A)
        data = rng.integers(0, 256, obj_bytes, dtype=np.uint8).tobytes()
        assert _rq.put(
            f"{base}/bench", headers=sign("PUT", "/bench")
        ).status_code == 200
        assert _rq.put(
            f"{base}/bench/obj", data=data,
            headers=sign("PUT", "/bench/obj"),
        ).status_code == 200
        get_headers = sign("GET", "/bench/obj")
        # warm both byte paths once (page cache + conns)
        for _ in range(2):
            r = _rq.get(f"{base}/bench/obj", timeout=30,
                        headers=get_headers)
            assert r.status_code == 200 and r.content == data
        _tr.configure(enabled=True)  # stage budget needs the recorder
        ecap = filer.entry_cache.capacity

        # ---------------- OFF: every fast path disabled -------------
        os.environ["SEAWEED_EC_NATIVE"] = "0"
        os.environ["SEAWEED_S3_AUTH_MEMO"] = "0"
        os.environ["SEAWEED_CHUNK_NET_PLANE"] = "0"
        filer.entry_cache.capacity = 0
        filer.entry_cache.clear()
        _s3auth.auth_cache_clear()
        cap_off = _warm_capture_get(base, get_headers)
        s0 = _tr._stage_seconds.snapshot()
        python_phase = _gateway_client_phase(
            base, data, clients, reads_per_client, headers=get_headers
        )
        s1 = _tr._stage_seconds.snapshot()
        stage_python = _warm_stage_ms(
            s0, s1, python_phase.get("requests", 0)
        )

        # ---------------- FAST: memo + entry cache + net plane ------
        for k in _ENV_KEYS:
            os.environ.pop(k, None)
        filer.entry_cache.capacity = ecap
        filer.entry_cache.clear()
        _s3auth.auth_cache_clear()
        cap_miss = _warm_capture_get(base, get_headers)  # cold caches
        cap_hit = _warm_capture_get(base, get_headers)   # memo+entry hit
        memo0 = _M.s3_auth_memo_total.snapshot()
        e0 = filer.entry_cache.stats()
        n0 = _M.net_bytes_received_total.snapshot()
        s0 = _tr._stage_seconds.snapshot()
        native_phase = _gateway_client_phase(
            base, data, clients, reads_per_client, headers=get_headers
        )
        s1 = _tr._stage_seconds.snapshot()
        stage_fast = _warm_stage_ms(s0, s1, native_phase.get("requests", 0))
        memo1 = _M.s3_auth_memo_total.snapshot()
        e1 = filer.entry_cache.stats()
        n1 = _M.net_bytes_received_total.snapshot()

        if "error" in native_phase or "error" in python_phase:
            return {
                "gateway_warm_error": (
                    f"fast={native_phase.get('error')} "
                    f"python={python_phase.get('error')}"
                )
            }
        identical = cap_off == cap_miss == cap_hit
        auth_lookup_fast = (
            stage_fast.get("s3.auth", 0.0)
            + stage_fast.get("filer.lookup", 0.0)
        )
        auth_lookup_python = (
            stage_python.get("s3.auth", 0.0)
            + stage_python.get("filer.lookup", 0.0)
        )
        chunk_native = _net_counter_delta(n0, n1, "native")
        return {
            "gateway_warm_get_gets_per_s": native_phase["gets_per_s"],
            "gateway_warm_get_p50_ms": native_phase["p50_ms"],
            "gateway_warm_get_python_gets_per_s": python_phase["gets_per_s"],
            "gateway_warm_get_python_p50_ms": python_phase["p50_ms"],
            "gateway_warm_fast_vs_python": round(
                native_phase["gets_per_s"]
                / max(python_phase["gets_per_s"], 1e-9),
                2,
            ),
            # bit identity across off / fast-miss / fast-hit, headers
            # included (volatile ids/clocks excluded) — IN THE LINE
            "gateway_warm_identical": bool(identical),
            # per-request stage budget, ms (the ISSUE 13 acceptance
            # metric: auth+lookup share drops >=2x fast vs python)
            "gateway_warm_stage_ms_fast": stage_fast,
            "gateway_warm_stage_ms_python": stage_python,
            "gateway_warm_auth_lookup_speedup": round(
                auth_lookup_python / max(auth_lookup_fast, 1e-6), 2
            ),
            # counter evidence that the fast paths actually engaged
            "gateway_warm_auth_memo_hits": int(
                memo1.get(("hit",), 0) - memo0.get(("hit",), 0)
            ),
            "gateway_warm_entry_cache_hits": int(e1["hits"] - e0["hits"]),
            "gateway_warm_entry_cache_loads": int(
                e1["loads"] - e0["loads"]
            ),
            "gateway_warm_chunk_native_mb": round(chunk_native / 1e6, 1),
            "gateway_warm_clients": clients,
            "gateway_warm_object_kb": obj_bytes >> 10,
            "gateway_warm_errors": native_phase["errors"]
            + python_phase["errors"],
        }
    finally:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if not was_armed:
            _tr.configure(enabled=False)
        for closer in (
            (lambda: srv.stop()) if srv is not None else None,
            (lambda: filer.close()) if filer is not None else None,
            vs.stop,
            master.stop,
        ):
            if closer is None:
                continue
            try:
                closer()
            except Exception:
                pass


def _canon_needle(raw: bytes) -> bytes:
    """A needle record's bytes with the append timestamp normalized —
    the only field two write transports may legitimately disagree on."""
    from seaweedfs_tpu.storage.needle import Needle

    n = Needle.from_bytes(bytes(raw))
    n.append_at_ns = 1
    return n.to_bytes()


def _write_bit_identity_probe(vols, ops, payload: bytes) -> bool:
    """The SAME fid written over the native write opcode, the HTTP
    multipart POST, and in-process gRPC WriteNeedle must land
    byte-identical records on disk (name/mime defaulting, flags, CRC)."""
    import requests as _rq

    from seaweedfs_tpu.pb import cluster_pb2 as pb
    from seaweedfs_tpu.storage.file_id import FileId
    from seaweedfs_tpu.storage.types import actual_offset

    os.environ["SEAWEED_CHUNK_NET_PLANE_WRITE"] = "1"
    before = sum(v.net_plane.write_requests for v in vols)
    fid = ops.upload(payload, name="ident.bin", mime="application/x-b")
    # 1 on a bare volume, 2 when the assign lands on a replicated one
    # (the fan-out leg also rides the plane)
    if sum(v.net_plane.write_requests for v in vols) < before + 1:
        return False  # the probe write did not ride the plane
    f = FileId.parse(fid)
    vs = next(v for v in vols if v.store.find_volume(f.volume_id))

    def record() -> bytes:
        vol = vs.store.find_volume(f.volume_id)
        nv = vol.needle_map.get(f.needle_id)
        return vol._pread_record(actual_offset(nv.offset), nv.size)

    raw_plane = record()
    os.environ["SEAWEED_CHUNK_NET_PLANE_WRITE"] = "0"
    loc = ops.master.lookup(f.volume_id)[0]
    rr = _rq.post(
        f"http://{loc.url}/{fid}",
        files={"file": ("ident.bin", payload, "application/x-b")},
        timeout=60,
    )
    if rr.status_code != 201:
        return False
    raw_http = record()
    resp = vs.service.WriteNeedle(
        pb.WriteNeedleRequest(
            volume_id=f.volume_id, needle_id=f.needle_id, cookie=f.cookie,
            data=payload, name="ident.bin", mime="application/x-b",
            is_replicate=True,
        ),
        None,
    )
    if resp.error:
        return False
    raw_grpc = record()
    return (
        _canon_needle(raw_plane) == _canon_needle(raw_http)
        == _canon_needle(raw_grpc)
        and ops.read(fid) == payload
    )


def _group_commit_crash_check(workdir: str) -> bool:
    """SIGKILL between the group-commit durability step and the ack:
    every ACKED needle must replay from the on-disk volume (the bench's
    in-process restatement of tests/test_group_commit.py's matrix)."""
    import multiprocessing

    from seaweedfs_tpu import faults
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    d = os.path.join(workdir, "gc_crash")
    os.makedirs(d, exist_ok=True)
    data = b"acked-then-killed-" * 100

    def child(conn):
        os.environ["SEAWEED_VOLUME_GROUP_COMMIT_MS"] = "10"
        v = Volume(d, 1, create=True)
        v.write_needle(Needle(cookie=0x77, needle_id=1, data=data), fsync=True)
        conn.send("acked")
        faults.inject("volume.write.before_ack", faults.hard_exit(137))
        v.write_needle(Needle(cookie=0x78, needle_id=2, data=data), fsync=True)
        os._exit(0)  # pragma: no cover - the fault kills us first

    mp = multiprocessing.get_context("fork")
    parent, cchild = mp.Pipe()
    p = mp.Process(target=child, args=(cchild,))
    p.start()
    p.join(timeout=60)
    if p.is_alive():
        p.kill()
        return False
    if p.exitcode != 137 or not parent.poll() or parent.recv() != "acked":
        return False
    v = Volume(d, 1, create=False)
    try:
        # needle 1 was acked; needle 2 passed its durability step
        # (before_ack fires after it) — both must replay
        return (
            v.read_needle(1).data == data and v.read_needle(2).data == data
        )
    except Exception:
        return False
    finally:
        v.close()


def _mixed_rw_bench(
    workdir: str,
    clients: int = 48,
    ops_per_client: int = 10,
    obj_bytes: int = 64 << 10,
) -> dict:
    """Mixed 70/30 GET/PUT at high client concurrency, write fast
    paths ON vs OFF in ONE run (ISSUE 18). 48 clients on this 2-core
    box is deep oversubscription (the group-commit batching win is in
    full effect) without the 100-thread scheduler floor that flattens
    the fast phase's p99 tail into pure thread-wakeup jitter. Both
    phases run with
    durable writes (SEAWEED_VOLUME_FSYNC=1) and replication 001, so
    every PUT latency IS time-to-replicated-durable; the fast phase
    turns on the native write opcode (client→primary AND the
    primary→replica fan-out leg) and an 8 ms group-commit window,
    the off phase pins PUTs to HTTP multipart with fsync-per-needle —
    the seed write path. Every GET is byte-verified; the write-side
    native-plane engagement rides in the line from
    sw_net_bytes_received_total{plane=native,direction=write}, and the
    three-transport bit-identity probe runs against the same cluster."""
    import threading

    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.utils import metrics as _M

    gdir = os.path.join(workdir, "mixed_rw")
    os.makedirs(gdir, exist_ok=True)
    mport = _bench_free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    knobs = (
        "SEAWEED_CHUNK_NET_PLANE_WRITE",
        "SEAWEED_VOLUME_FSYNC",
        "SEAWEED_VOLUME_GROUP_COMMIT_MS",
    )
    prev_env = {k: os.environ.get(k) for k in knobs}
    payload = np.random.default_rng(0x18).integers(
        0, 256, obj_bytes, dtype=np.uint8
    ).tobytes()
    try:
        for i in range(2):
            vs = VolumeServer(
                directories=[os.path.join(gdir, f"v{i}")],
                master=f"localhost:{mport}",
                ip="localhost",
                port=_bench_free_port(),
                ec_backend="cpu",
            )
            vs.start()
            vols.append(vs)
        deadline = time.time() + 15
        while len(master.topo.nodes) < 2:
            if time.time() > deadline:
                return {"mixed_rw_error": "volume servers never registered"}
            time.sleep(0.05)

        def phase(fast: bool) -> dict:
            os.environ["SEAWEED_CHUNK_NET_PLANE_WRITE"] = "1" if fast else "0"
            os.environ["SEAWEED_VOLUME_FSYNC"] = "1"
            os.environ["SEAWEED_VOLUME_GROUP_COMMIT_MS"] = (
                "8" if fast else "0"
            )
            ops = Operations(f"localhost:{mport}")
            lock = threading.Lock()
            put_lat: list[float] = []
            get_lat: list[float] = []
            errors = [0]
            barrier = threading.Barrier(clients)

            def client(c: int) -> None:
                fids: list[str] = []
                try:
                    barrier.wait(timeout=60)
                except threading.BrokenBarrierError:
                    pass
                for i in range(ops_per_client):
                    # deterministic 30% writes; the first op seeds the
                    # client's GET target
                    is_put = not fids or (c * 31 + i) % 10 < 3
                    t0 = time.perf_counter()
                    try:
                        if is_put:
                            fids.append(
                                ops.upload(
                                    payload, name="m.bin", replication="001"
                                )
                            )
                            with lock:
                                put_lat.append(time.perf_counter() - t0)
                        else:
                            ok = ops.read(fids[-1]) == payload
                            with lock:
                                if ok:
                                    get_lat.append(time.perf_counter() - t0)
                                else:
                                    errors[0] += 1
                    except Exception:
                        with lock:
                            errors[0] += 1

            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(clients)
            ]
            t_all = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t_all
            ops.close()
            if not put_lat or not get_lat:
                return {"error": f"no completed ops (errors={errors[0]})"}
            puts = np.array(sorted(put_lat)) * 1e3
            gets = np.array(sorted(get_lat)) * 1e3
            return {
                "write_p50_ms": round(float(np.percentile(puts, 50)), 2),
                "write_p99_ms": round(float(np.percentile(puts, 99)), 2),
                "read_p99_ms": round(float(np.percentile(gets, 99)), 2),
                "puts": len(put_lat),
                "gets": len(get_lat),
                "errors": errors[0],
                "ops_per_s": round(
                    (len(put_lat) + len(get_lat)) / wall, 1
                ),
            }

        python_phase = phase(fast=False)
        n0 = _M.net_bytes_received_total.snapshot()
        fast_phase = phase(fast=True)
        n1 = _M.net_bytes_received_total.snapshot()
        if "error" in python_phase or "error" in fast_phase:
            return {
                "mixed_rw_error": (
                    f"python={python_phase.get('error')} "
                    f"fast={fast_phase.get('error')}"
                )
            }
        write_native = _net_counter_delta(n0, n1, "native", "write")
        ident_ops = Operations(f"localhost:{mport}")
        try:
            identical = _write_bit_identity_probe(vols, ident_ops, payload)
        finally:
            ident_ops.close()
        acked_durable = _group_commit_crash_check(gdir)
        return {
            "mixed_rw_write_p99_ms_fast": fast_phase["write_p99_ms"],
            "mixed_rw_write_p99_ms_python": python_phase["write_p99_ms"],
            "mixed_rw_write_speedup": round(
                python_phase["write_p99_ms"]
                / max(fast_phase["write_p99_ms"], 1e-9),
                2,
            ),
            # durable+replicated ack latency under the fast config
            "mixed_rw_durable_ms": fast_phase["write_p50_ms"],
            "mixed_rw_read_p99_ms_fast": fast_phase["read_p99_ms"],
            "mixed_rw_read_p99_ms_python": python_phase["read_p99_ms"],
            "mixed_rw_ops_per_s_fast": fast_phase["ops_per_s"],
            "mixed_rw_ops_per_s_python": python_phase["ops_per_s"],
            "mixed_rw_write_native_mb": round(write_native / 1e6, 1),
            "mixed_rw_identical": bool(identical),
            "mixed_rw_acked_durable": bool(acked_durable),
            "mixed_rw_clients": clients,
            "mixed_rw_object_kb": obj_bytes >> 10,
            "mixed_rw_errors": fast_phase["errors"]
            + python_phase["errors"],
        }
    finally:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for vs in vols:
            try:
                vs.stop()
            except Exception:
                pass
        try:
            master.stop()
        except Exception:
            pass


def _mq_attach_spill(broker, topic: str) -> None:
    """Give every partition log a dict-backed spill store so segments
    seal out of the memory tail like a filer-backed deployment — the
    precondition for the fetch spool's zero-copy sealed-segment path.
    Content-identical to filer spill; only the storage location of the
    sealed bytes differs (the spool re-materializes them on disk)."""
    st = broker.topic("kafka", topic)
    for plog in st.logs.values():
        segs: dict[int, bytes] = {}
        plog._spill = segs.__setitem__
        plog._load = segs.get


def _mq_crash_child(pdir: str, grpc_port: int, kill_window: int) -> None:
    from seaweedfs_tpu import faults
    from seaweedfs_tpu.mq.broker import MqBrokerServer
    from seaweedfs_tpu.mq.kafka.client import KafkaClient
    from seaweedfs_tpu.mq.kafka.records import Record

    os.environ["SEAWEED_MQ_GROUP_COMMIT_MS"] = "10"
    faults.inject(
        "mq.produce.before_flush",
        faults.hard_exit(137),
        when=faults.nth_call(kill_window),
    )
    srv = MqBrokerServer(
        ip="localhost", grpc_port=grpc_port, kafka_port=0, parity_dir=pdir
    )
    srv.start()
    c = KafkaClient("localhost", srv.kafka.port)
    c.create_topic("gc", partitions=1)
    acked = open(os.path.join(pdir, "..", "acked"), "w")
    for i in range(500):
        c.produce(
            "gc", 0,
            [Record(key=b"k%06d" % i, value=b"v%06d-" % i * 16)],
            acks=-1,
        )
        acked.write(f"{i}\n")
        acked.flush()
        os.fsync(acked.fileno())
    os._exit(0)  # pragma: no cover - the armed window kills us first


def _mq_group_commit_crash_check(workdir: str) -> bool:
    """Hard-kill the MQ broker inside a produce group-commit window:
    every Kafka produce acked before the crash must replay from the
    parity streams after restart, dense and byte-exact (the MQ
    restatement of _group_commit_crash_check)."""
    import multiprocessing

    from seaweedfs_tpu.mq.broker import MqBroker
    from seaweedfs_tpu.mq.kafka.gateway import _unpack_null

    d = os.path.join(workdir, "mq_gc_crash")
    pdir = os.path.join(d, "parity")
    os.makedirs(pdir, exist_ok=True)
    prev = os.environ.get("SEAWEED_MQ_GROUP_COMMIT_MS")
    mp = multiprocessing.get_context("fork")
    p = mp.Process(
        target=_mq_crash_child, args=(pdir, _bench_free_port(), 3)
    )
    p.start()
    p.join(timeout=120)
    if prev is None:
        os.environ.pop("SEAWEED_MQ_GROUP_COMMIT_MS", None)
    else:
        os.environ["SEAWEED_MQ_GROUP_COMMIT_MS"] = prev
    if p.is_alive():
        p.kill()
        return False
    if p.exitcode != 137:
        return False
    acked = -1
    acked_path = os.path.join(d, "acked")
    if os.path.exists(acked_path):
        lines = open(acked_path).read().split()
        if lines:
            acked = int(lines[-1])
    br = MqBroker(parity_dir=pdir)
    try:
        recs = br.topic("kafka", "gc").logs[0].read_from(
            0, max_records=10_000
        )
        for n, (off, _ts, k, v) in enumerate(recs):
            if off != n:
                return False  # replay not dense
            if (_unpack_null(k), _unpack_null(v)) != (
                b"k%06d" % n, b"v%06d-" % n * 16
            ):
                return False  # replay not byte-exact
        return len(recs) >= acked + 1  # acked => replayable
    except Exception:
        return False
    finally:
        br.close()


def _mq_fetch_bit_identity_probe(workdir: str) -> tuple[bool, float]:
    """Fetch the same sealed segments over the native (sn_send_file)
    and Python egress planes: the decoded records must be identical.
    Returns (identical, native_mb)."""
    from seaweedfs_tpu.mq.broker import MqBrokerServer
    from seaweedfs_tpu.mq.kafka.client import KafkaClient
    from seaweedfs_tpu.mq.kafka.records import Record
    from seaweedfs_tpu.utils import metrics as _M

    def native_bytes() -> float:
        return dict(_M.mq_fetch_bytes_total.snapshot()).get(
            ("native",), 0
        )

    srv = MqBrokerServer(
        ip="localhost", grpc_port=_bench_free_port(), kafka_port=0,
        segment_records=64,
    )
    srv.start()
    prev = os.environ.get("SEAWEED_EC_NATIVE")
    try:
        c = KafkaClient("localhost", srv.kafka.port)
        c.create_topic("ident", partitions=1)
        _mq_attach_spill(srv.broker, "ident")
        payload = bytes(range(256))
        for i in range(200):
            c.produce("ident", 0, [Record(key=b"k%03d" % i, value=payload)])

        def drain(client):
            out, off = [], 0
            while off < 200:
                _hw, recs = client.fetch(
                    "ident", 0, off, max_wait_ms=0, max_bytes=1 << 22
                )
                if not recs:
                    break
                out.extend((r.offset, r.key, r.value) for r in recs)
                off = out[-1][0] + 1
            return out

        os.environ["SEAWEED_EC_NATIVE"] = "0"
        py_recs = drain(c)
        os.environ["SEAWEED_EC_NATIVE"] = "1"
        n0 = native_bytes()
        c2 = KafkaClient("localhost", srv.kafka.port)
        nat_recs = drain(c2)
        native_mb = (native_bytes() - n0) / 1e6
        c2.close()
        c.close()
        return (
            len(py_recs) == 200 and py_recs == nat_recs,
            round(native_mb, 2),
        )
    finally:
        if prev is None:
            os.environ.pop("SEAWEED_EC_NATIVE", None)
        else:
            os.environ["SEAWEED_EC_NATIVE"] = prev
        srv.stop()


def _mq_sustained_bench(
    workdir: str,
    producers: int = 4,
    consumers: int = 2,
    records_per_producer: int = 400,
    value_bytes: int = 2048,
) -> dict:
    """Sustained Kafka produce/consume at line rate (ISSUE 20): the
    pooled frame server + group commit + zero-copy fetch spool vs the
    naive thread-per-connection/no-group-commit/Python-egress baseline,
    in ONE run. Every record carries its producer-side timestamp, so
    delivery latency is true produce-call-to-consumer-decode; parity
    lag is sampled live during traffic (the durable-parity bound the
    group committer exists to hold). The mid-traffic broker hard-kill +
    dense byte-exact replay assertion rides in the same line."""
    import threading

    from seaweedfs_tpu.mq.broker import MqBrokerServer
    from seaweedfs_tpu.mq.kafka.client import KafkaClient
    from seaweedfs_tpu.mq.kafka.records import Record
    from seaweedfs_tpu.utils import metrics as _M

    gdir = os.path.join(workdir, "mq_sustained")
    os.makedirs(gdir, exist_ok=True)
    knobs = (
        "SEAWEED_MQ_KAFKA_WORKERS",
        "SEAWEED_MQ_GROUP_COMMIT_MS",
        "SEAWEED_EC_NATIVE",
    )
    prev_env = {k: os.environ.get(k) for k in knobs}
    pad = b"\x5a" * max(value_bytes - 8, 0)

    def phase(tuned: bool) -> dict:
        os.environ["SEAWEED_MQ_KAFKA_WORKERS"] = "16" if tuned else "0"
        os.environ["SEAWEED_MQ_GROUP_COMMIT_MS"] = "8" if tuned else "0"
        os.environ["SEAWEED_EC_NATIVE"] = "1" if tuned else "0"
        srv = MqBrokerServer(
            ip="localhost",
            grpc_port=_bench_free_port(),
            kafka_port=0,
            segment_records=64,
            parity_dir=os.path.join(
                gdir, "parity_" + ("tuned" if tuned else "naive")
            ),
        )
        srv.start()
        try:
            setup = KafkaClient("localhost", srv.kafka.port)
            setup.create_topic("wire", partitions=producers)
            setup.close()
            _mq_attach_spill(srv.broker, "wire")
            parities = list(
                srv.broker.topic("kafka", "wire").parity.values()
            )
            lock = threading.Lock()
            deliver_s: list[float] = []
            lag_s: list[float] = []
            consumed = [0]  # bytes
            errors = [0]
            prod_done = threading.Event()

            def producer(idx: int) -> None:
                try:
                    c = KafkaClient(
                        "localhost", srv.kafka.port, client_id=f"p{idx}"
                    )
                    for _i in range(records_per_producer):
                        val = struct.pack(">d", time.perf_counter()) + pad
                        c.produce(
                            "wire", idx, [Record(key=b"k", value=val)],
                            acks=-1,
                        )
                    c.close()
                except Exception:
                    with lock:
                        errors[0] += 1

            def consumer(idx: int) -> None:
                try:
                    c = KafkaClient(
                        "localhost", srv.kafka.port, client_id=f"c{idx}"
                    )
                    mine = list(range(idx, producers, consumers))
                    nxt = {p: 0 for p in mine}
                    idle = 0
                    while any(
                        nxt[p] < records_per_producer for p in mine
                    ):
                        progressed = False
                        for p in mine:
                            if nxt[p] >= records_per_producer:
                                continue
                            _hw, recs = c.fetch(
                                "wire", p, nxt[p],
                                max_wait_ms=50, max_bytes=1 << 22,
                            )
                            now = time.perf_counter()
                            fresh = [
                                r for r in recs if r.offset >= nxt[p]
                            ]
                            if not fresh:
                                continue
                            progressed = True
                            nxt[p] = fresh[-1].offset + 1
                            with lock:
                                for r in fresh:
                                    (t0,) = struct.unpack(
                                        ">d", r.value[:8]
                                    )
                                    deliver_s.append(now - t0)
                                    consumed[0] += len(r.value)
                        if progressed:
                            idle = 0
                        elif prod_done.is_set():
                            # a couple of empty passes once producers
                            # are done = genuinely drained (or wedged)
                            idle += 1
                            if idle >= 3:
                                break
                    c.close()
                except Exception:
                    with lock:
                        errors[0] += 1

            def lag_sampler() -> None:
                while not prod_done.is_set():
                    with lock:
                        lag_s.extend(
                            p.parity_lag_s() for p in parities
                        )
                    time.sleep(0.02)

            pthreads = [
                threading.Thread(target=producer, args=(i,))
                for i in range(producers)
            ]
            cthreads = [
                threading.Thread(target=consumer, args=(i,))
                for i in range(consumers)
            ]
            sampler = threading.Thread(target=lag_sampler)
            t0 = time.perf_counter()
            for t in pthreads + cthreads:
                t.start()
            sampler.start()
            for t in pthreads:
                t.join(timeout=300)
            produce_wall = time.perf_counter() - t0
            prod_done.set()
            for t in cthreads:
                t.join(timeout=300)
            wall = time.perf_counter() - t0
            sampler.join(timeout=10)
            total = producers * records_per_producer
            if errors[0] or len(deliver_s) < total:
                return {
                    "error": (
                        f"errors={errors[0]} "
                        f"delivered={len(deliver_s)}/{total}"
                    )
                }
            # cold replay: a catch-up consumer re-reads every
            # partition from offset 0 — sealed segments egress through
            # the fetch spool (zero-copy native plane when enabled),
            # the backfill/replay case the spool exists for
            rc = KafkaClient(
                "localhost", srv.kafka.port, client_id="replay"
            )

            def replay_pass() -> tuple[int, float]:
                t0 = time.perf_counter()
                nbytes = 0
                for p in range(producers):
                    off = 0
                    while off < records_per_producer:
                        _hw, recs = rc.fetch(
                            "wire", p, off,
                            max_wait_ms=0, max_bytes=1 << 22,
                        )
                        fresh = [r for r in recs if r.offset >= off]
                        if not fresh:
                            raise RuntimeError(
                                f"replay wedged at wire[{p}]@{off}"
                            )
                        off = fresh[-1].offset + 1
                        nbytes += sum(len(r.value) for r in fresh)
                return nbytes, time.perf_counter() - t0

            replay_pass()  # cold: populates the spool (builds)
            replay_bytes, replay_wall = replay_pass()  # warm: egress
            rc.close()
            del_ms = np.array(sorted(deliver_s)) * 1e3
            lag_ms = np.array(sorted(lag_s or [0.0])) * 1e3
            pool = srv.kafka.pool_status()
            return {
                "replay_mb_per_s": round(
                    replay_bytes / 1e6 / max(replay_wall, 1e-9), 2
                ),
                "produce_recs_per_s": round(total / produce_wall, 1),
                "consume_mb_per_s": round(
                    consumed[0] / 1e6 / wall, 2
                ),
                "delivery_p50_ms": round(
                    float(np.percentile(del_ms, 50)), 2
                ),
                "delivery_p99_ms": round(
                    float(np.percentile(del_ms, 99)), 2
                ),
                "parity_lag_p99_ms": round(
                    float(np.percentile(lag_ms, 99)), 2
                ),
                "spool_builds": pool["fetch_spool"]["builds"],
                "kind": pool["kind"],
            }
        finally:
            srv.stop()

    try:
        n0 = dict(_M.mq_fetch_bytes_total.snapshot()).get(("native",), 0)
        naive = phase(tuned=False)
        tuned = phase(tuned=True)
        native_mb = (
            dict(_M.mq_fetch_bytes_total.snapshot()).get(("native",), 0)
            - n0
        ) / 1e6
        if "error" in naive or "error" in tuned:
            return {
                "mq_sustained_error": (
                    f"naive={naive.get('error')} "
                    f"tuned={tuned.get('error')}"
                )
            }
        replay_ok = _mq_group_commit_crash_check(gdir)
        return {
            "mq_produce_recs_per_s_tuned": tuned["produce_recs_per_s"],
            "mq_produce_recs_per_s_naive": naive["produce_recs_per_s"],
            "mq_consume_mb_per_s_tuned": tuned["consume_mb_per_s"],
            "mq_consume_mb_per_s_naive": naive["consume_mb_per_s"],
            "mq_delivery_p99_ms_tuned": tuned["delivery_p99_ms"],
            "mq_delivery_p99_ms_naive": naive["delivery_p99_ms"],
            "mq_delivery_speedup": round(
                naive["delivery_p99_ms"]
                / max(tuned["delivery_p99_ms"], 1e-9),
                2,
            ),
            "mq_replay_mb_per_s_tuned": tuned["replay_mb_per_s"],
            "mq_replay_mb_per_s_naive": naive["replay_mb_per_s"],
            # the group committer's whole job: durable-parity lag stays
            # bounded while the tuned phase runs at full tilt
            "mq_parity_lag_p99_ms_tuned": tuned["parity_lag_p99_ms"],
            "mq_parity_lag_p99_ms_naive": naive["parity_lag_p99_ms"],
            "mq_fetch_native_mb": round(native_mb, 1),
            "mq_spool_builds": tuned["spool_builds"],
            "mq_replay_after_kill_identical": bool(replay_ok),
            "mq_producers": producers,
            "mq_consumers": consumers,
            "mq_value_bytes": value_bytes,
        }
    finally:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --------------------------------------------------------------------------
# Device phase: INDEPENDENTLY WATCHDOGGED STAGES, each in its own
# subprocess, each persisting its JSON fragment to disk the moment it
# completes — a later hang can never erase earlier evidence. The known
# failure mode (3 rounds of it) is a flaky axon relay that hangs jax
# init in C forever; the probe stage retries with backoff to catch the
# relay waking up, and every stage records its rc/duration/attempts
# into the final line's `stages` trail.
# --------------------------------------------------------------------------

STAGE_TIMEOUTS = {
    "probe": 150.0,
    "kernel_small": 240.0,
    "pipeline": 360.0,
    "kernel_full": 300.0,
    "e2e": 600.0,
    # pod-placement bench: ALWAYS on the emulated 8-device CPU platform
    # (hermetic — no TPU dependence), so one attempt suffices.
    "placement": 300.0,
    # pod-sharded pjit-vs-shard_map encode: hermetic 8-virtual-device
    # variant always; `pod_encode_device` is the SAME stage unforced,
    # gated on the probe reporting a real multi-chip platform.
    "pod_encode": 240.0,
    "pod_encode_device": 240.0,
    # --self-check only: a child that never returns. 20 s = _run_stage's
    # minimum useful budget (smaller gets skipped as budget_exhausted).
    "selfcheck_hang": 20.0,
}
STAGE_ATTEMPTS = {
    "probe": 3, "kernel_small": 2, "pipeline": 1, "kernel_full": 1, "e2e": 1,
    "placement": 1, "pod_encode": 1, "pod_encode_device": 1,
    "selfcheck_hang": 3,
}
STAGE_BACKOFF = 10.0  # seconds, grows linearly per retry


class _AllImplsFailed(RuntimeError):
    pass


def _stage_probe() -> dict:
    """Cheapest possible liveness check of the device path: jax init,
    device list, one tiny executed op. Lands first so a later hang still
    leaves the platform/device identity + init timing on record."""
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    devs = jax.devices()
    init_s = time.perf_counter() - t0
    d = devs[0]
    t0 = time.perf_counter()
    val = int(np.asarray(jnp.arange(4096, dtype=jnp.int32).sum()))
    tiny_s = time.perf_counter() - t0
    return {
        "platform": d.platform,
        "kind": str(d.device_kind),
        "n_devices": len(devs),
        "init_s": round(init_s, 2),
        "tiny_op_s": round(tiny_s, 2),
        "tiny_ok": val == 4096 * 4095 // 2,
    }


def _device_kernel(expected: dict, width: int | None = None) -> dict:
    """Timed kernel micro-bench: distinct pre-staged inputs, CRC-verified
    outputs, and RELAY-PROOF timing.

    On the axon TPU relay `jax.block_until_ready` returns before
    execution completes (measured: it "timed" this kernel at 6,676 GB/s,
    8x the chip's HBM bandwidth), so wall-clocking dispatched calls is
    meaningless. Instead the reps run INSIDE a jitted fori_loop whose
    carried value is a checksum of every output — fetching the scalar
    forces the whole chain — and the per-pass time is the slope between
    a 3-rep and a 9-rep loop, cancelling the relay's fixed round-trip
    latency. The loop indexes a different buffer each rep (i % 3), which
    also defeats loop-invariant hoisting."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.rs_jax import RSJax

    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)
    if width is None:
        width = BLOCK if on_tpu else 1 << 20
    if not on_tpu:
        width = min(width, 1 << 20)
    impls = ["pallas", "pallas_aligned", "xla"] if on_tpu else ["xla"]
    forced_impl = os.environ.get("SEAWEED_BENCH_IMPL")
    if forced_impl:
        impls = [forced_impl]
    failures: dict[str, str] = {}

    # The xla impl materialises 8x f32 bit-planes (~10 GB at full BLOCK):
    # measure it on a slice — throughput, not capacity, is the metric.
    xla_width = min(width, 1 << 23)

    def _cks_np(out: np.ndarray) -> int:
        red = np.bitwise_xor.reduce(out[:, ::65537].astype(np.int32), axis=0)
        return int(red.sum(dtype=np.int32))

    for impl in impls:
        w = xla_width if impl == "xla" else width
        bufs_np = [_gen(s, w) for s in SEEDS]
        try:
            rs = RSJax(K, M, impl=impl)
            db = jax.device_put(jnp.asarray(np.stack(bufs_np)))

            # --- verification: fetch every output in full, CRC vs CPU
            # truth, and derive the checksum the timed loop must carry.
            verified = True
            want_cks = 0
            for i, seed in enumerate(SEEDS):
                out = np.asarray(rs.encode(db[i]), dtype=np.uint8)
                want = expected.get(str(seed), {}).get(str(w))
                if want is None or _crc_rows(out) != want:
                    verified = False
                want_cks ^= _cks_np(out)

            def _mkloop(reps):
                @jax.jit
                def loop(d3):
                    def body(i, acc):
                        d = jax.lax.dynamic_index_in_dim(
                            d3, i % REPS, keepdims=False
                        )
                        out = rs.encode(d)
                        red = jnp.bitwise_xor.reduce(
                            out[:, ::65537].astype(jnp.int32)
                        )
                        return acc ^ red.sum().astype(jnp.int32)
                    return jax.lax.fori_loop(0, reps, body, jnp.int32(0))
                return loop

            # reps=3 and reps=9: each buffer appears an odd number of
            # times in both, so both loops must return want_cks.
            l_lo, l_hi = _mkloop(REPS), _mkloop(3 * REPS)
            got_lo = int(l_lo(db))  # compile + warmup
            got_hi = int(l_hi(db))
            t0 = time.perf_counter()
            got_hi2 = int(l_hi(db))
            dt_hi = time.perf_counter() - t0
            t0 = time.perf_counter()
            got_lo2 = int(l_lo(db))
            dt_lo = time.perf_counter() - t0
            if {got_lo, got_hi, got_hi2, got_lo2} != {want_cks}:
                verified = False
        except Exception as e:  # noqa: BLE001 — diagnostic capture
            failures[impl] = repr(e)[:300]
            continue
        dt = (dt_hi - dt_lo) / (2 * REPS)
        if dt <= 0:
            failures[impl] = (
                f"non-positive per-pass slope ({dt_hi:.4f}s@{3*REPS} vs "
                f"{dt_lo:.4f}s@{REPS}): timing unusable"
            )
            continue
        gbs = (K * w) / dt / 1e9
        # --- physical consistency: encode must move >= (1 + m/k) bytes of
        # HBM per data byte; a rate implying more than the chip's bandwidth
        # means the measurement (not the chip) is broken.
        ceiling = _hbm_ceiling(str(dev.device_kind))
        implied_traffic = gbs * (1.0 + M / K)
        suspect = None
        if implied_traffic > ceiling:
            suspect = (
                f"implied HBM traffic {implied_traffic:.0f} GB/s exceeds "
                f"{dev.device_kind} ceiling ~{ceiling:.0f} GB/s"
            )
        return {
            "kernel_gbs": gbs,
            "kernel_impl": impl,
            "kernel_verified": verified,
            "kernel_suspect": suspect,
            "kernel_width": w,
            "dispatch_overhead_s": round(max(dt_lo - REPS * dt, 0.0), 4),
            "kind": str(dev.device_kind),
            "platform": dev.platform,
            "failures": failures,
        }
    raise _AllImplsFailed(f"all device impls failed to compile/run: {failures}")


def _stage_pipeline_file(workdir: str, nbytes: int) -> tuple[str, str]:
    """Materialise the pipeline input where reads cost RAM bandwidth,
    not disk: /dev/shm when it has room, else the workdir with an
    explicit warm-read so the page cache holds it. Returns
    (path, staging_kind). Deterministic content (seeded chunks)."""
    import errno

    chunk = np.random.default_rng(0xF00D).integers(
        0, 256, size=64 << 20, dtype=np.uint8
    ).tobytes()

    def _fill(path: str) -> None:
        with open(path, "wb") as f:
            written = 0
            rot = 0
            while written < nbytes:
                piece = chunk[rot:] + chunk[:rot]  # vary content per chunk
                take = min(len(piece), nbytes - written)
                f.write(piece[:take])
                written += take
                rot = (rot + 4096) % len(chunk)

    shm = "/dev/shm"
    path = None
    try:
        st = os.statvfs(shm)
        if st.f_bavail * st.f_frsize < nbytes + (64 << 20):
            raise OSError(errno.ENOSPC, "tmpfs too small")
        fd, path = tempfile.mkstemp(prefix="seaweed_pipe_", dir=shm)
        os.close(fd)
        _fill(path)
        return path, "tmpfs"
    except OSError:
        # tmpfs raced to full mid-write (or is absent): clean up the
        # partial file, degrade to page-cache staging in the workdir
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass
    path = os.path.join(workdir, "pipeline.bin")
    _fill(path)
    with open(path, "rb") as f:  # warm the cache
        while f.read(64 << 20):
            pass
    return path, "pagecache"


def _run_pipeline(backend, path: str, batch: int, reps: int) -> dict:
    """The full device e2e pipeline minus the disk: striped reads from a
    RAM-backed file -> H2D -> encode -> D2H -> per-shard rolling CRC32C
    of ALL 14 shard streams, double-buffered exactly like the production
    encoder (reader thread / dispatch thread / drain+CRC thread over the
    backend's to_device/encode_staged/to_host hooks). The CRCs make the
    D2H real — a broken block_until_ready cannot fake a number because
    every parity byte is fetched and checksummed on the host.
    Returns {gbs, rep_s: [...], shard_crcs: [14 ints]}."""
    import queue as _queue
    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.ec.encoder import _pread_padded
    from seaweedfs_tpu.utils import native

    size = os.path.getsize(path)
    block = size // K  # bytes per data-shard row
    fd = os.open(path, os.O_RDONLY)
    times: list[float] = []
    crcs_out: list[int] | None = None
    try:
        for _rep in range(reps):
            crcs = np.zeros(K + M, np.uint32)
            read_q: _queue.Queue = _queue.Queue(maxsize=2)
            out_q: _queue.Queue = _queue.Queue(maxsize=2)
            errors: list[BaseException] = []
            abort = _threading.Event()

            def _put(q, item) -> bool:
                """Abort-aware put: never blocks forever on a full queue
                whose consumer has stopped."""
                while True:
                    try:
                        q.put(item, timeout=0.2)
                        return True
                    except _queue.Full:
                        if abort.is_set():
                            return False

            def reader():
                try:
                    for off in range(0, block, batch):
                        if abort.is_set():
                            return
                        w = min(batch, block - off)
                        buf = np.empty((K, w), np.uint8)
                        for i in range(K):
                            _pread_padded(fd, buf[i], i * block + off)
                        if not _put(read_q, buf):
                            return
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                finally:
                    _put(read_q, None)

            def drainer():
                try:
                    with ThreadPoolExecutor(max_workers=K + M) as ex:
                        while True:
                            item = out_q.get()
                            if item is None:
                                return
                            data, handle = item
                            parity = np.ascontiguousarray(
                                backend.to_host(handle), dtype=np.uint8
                            )

                            def crc_row(i):
                                row = data[i] if i < K else parity[i - K]
                                crcs[i] = native.crc32c(row, int(crcs[i]))

                            list(ex.map(crc_row, range(K + M)))
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    while out_q.get() is not None:
                        pass

            rt = _threading.Thread(target=reader, daemon=True)
            st = _threading.Thread(target=drainer, daemon=True)
            t0 = time.perf_counter()
            rt.start()
            st.start()
            try:
                while True:
                    data = read_q.get()
                    if data is None or errors:
                        break
                    out_q.put(
                        (data, backend.encode_staged(backend.to_device(data)))
                    )
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
            finally:
                if errors:
                    abort.set()
                    try:  # unblock a reader stuck on a full queue
                        while True:
                            read_q.get_nowait()
                    except _queue.Empty:
                        pass
                out_q.put(None)
                rt.join(timeout=120)
                st.join(timeout=120)
            dt = time.perf_counter() - t0
            if errors:
                raise errors[0]
            if rt.is_alive() or st.is_alive():
                raise RuntimeError("pipeline thread wedged")
            times.append(dt)
            got = [int(x) for x in crcs]
            if crcs_out is None:
                crcs_out = got
            elif got != crcs_out:
                raise RuntimeError(
                    "pipeline shard CRCs diverged between reps"
                )
    finally:
        os.close(fd)
    return {
        "gbs": size / min(times) / 1e9,
        "rep_s": [round(t, 3) for t in times],
        "shard_crcs": crcs_out,
    }


def _device_pipeline(
    path: str, expected_crcs: list[int], cpu_gbs: float
) -> dict:
    """Device-side pipeline stage: same striped pipeline, JAX backend.
    Bit-exactness gate: the 14 rolling shard CRCs must equal the CPU
    pipeline's. HBM guard: encode moves >= (1+m/k)x the data bytes."""
    import jax

    from seaweedfs_tpu.ec.backend import JaxBackend
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT

    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)
    batch = BLOCK if on_tpu else 1 << 22
    backend = JaxBackend(DEFAULT_EC_CONTEXT, n_devices=1)
    r = _run_pipeline(backend, path, batch, REPS)
    gbs = r["gbs"]
    ceiling = _hbm_ceiling(str(dev.device_kind))
    implied = gbs * (1.0 + M / K)
    suspect = None
    if implied > ceiling:
        suspect = (
            f"implied HBM traffic {implied:.0f} GB/s exceeds "
            f"{dev.device_kind} ceiling ~{ceiling:.0f} GB/s"
        )
    return {
        "pipeline_gbs": gbs,
        "pipeline_rep_s": r["rep_s"],
        "pipeline_verified": r["shard_crcs"] == expected_crcs,
        "pipeline_suspect": suspect,
        "pipeline_vs_cpu_pipeline": (
            round(gbs / cpu_gbs, 3) if cpu_gbs else None
        ),
        "pipeline_batch": batch,
        "kind": str(dev.device_kind),
        "platform": dev.platform,
    }


def _device_e2e(base: str, expected_crcs: list[list[int]], dat_size: int) -> dict:
    """Timed disk->shards encode + 2-shard rebuild on the device backend.
    Bit-exactness: the .ecsum CRCs must equal the CPU run's."""
    from seaweedfs_tpu.ec.backend import JaxBackend
    from seaweedfs_tpu.ec.bitrot import BitrotProtection
    from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
    from seaweedfs_tpu.ec.encoder import ec_encode_volume
    from seaweedfs_tpu.ec.rebuild import rebuild_ec_files

    backend = JaxBackend(DEFAULT_EC_CONTEXT)
    t0 = time.perf_counter()
    ec_encode_volume(base, backend=backend)
    encode_dt = time.perf_counter() - t0
    prot = BitrotProtection.load(base + ".ecsum")
    result = {
        "e2e_gbs": dat_size / encode_dt / 1e9,
        "e2e_verified": prot.shard_crcs == expected_crcs,
    }

    # BASELINE config 2: rebuild 2 missing shards (one data, one parity),
    # staged (async H2D/compute/D2H) AND synchronous-apply, so the line
    # carries the on-device rebuild_staged_vs_sync overlap ratio.
    # rebuild_ec_files verifies regenerated shards against the sidecar
    # and fails closed, so finishing at all means the rebuild is
    # bit-exact; a failure is recorded without discarding the encode.
    try:
        ctx = DEFAULT_EC_CONTEXT

        def timed_rebuild(staged: bool) -> tuple[float, list[int]]:
            for i in (1, K + 1):
                if os.path.exists(base + ctx.to_ext(i)):
                    os.unlink(base + ctx.to_ext(i))
            t0 = time.perf_counter()
            rebuilt = rebuild_ec_files(base, backend=backend, staged=staged)
            return time.perf_counter() - t0, rebuilt

        # Warmup rebuild first (untimed): the first apply pays XLA jit
        # compilation + coefficient bit-expansion; both timed variants
        # hit the same kernel/coeff caches, so the ratio measures
        # OVERLAP, not who compiled. (Both numbers are therefore warm —
        # warmer than pre-PR3 rounds' single cold rebuild.)
        timed_rebuild(staged=True)
        sync_dt, _ = timed_rebuild(staged=False)
        rebuild_dt, rebuilt = timed_rebuild(staged=True)
        result["rebuild_volume_gbs"] = dat_size / rebuild_dt / 1e9
        result["rebuild_sync_volume_gbs"] = dat_size / sync_dt / 1e9
        result["rebuild_staged_vs_sync"] = round(sync_dt / rebuild_dt, 3)
        result["rebuilt_shards"] = rebuilt
    except Exception as e:  # noqa: BLE001 — partial evidence beats none
        result["rebuild_error"] = repr(e)[:500]
    return result


def _stage_child(name: str, workdir: str) -> None:
    """Run one device stage and persist its fragment ATOMICALLY before
    exiting; the parent reads the file, never this process's stdout."""
    forced = os.environ.get("SEAWEED_BENCH_PLATFORM")
    if forced:
        import jax

        jax.config.update("jax_platforms", forced)
    # --trace-out: arm the flight recorder for this stage and dump its
    # span ring as Chrome trace_event JSON (one file per stage — each
    # stage is its own process, so each owns its own ring).
    trace_out = os.environ.get("SEAWEED_BENCH_TRACE_OUT", "")
    if trace_out:
        from seaweedfs_tpu.utils import trace as _tr

        _tr.configure(enabled=True, ring_size=1024)

    with open(os.path.join(workdir, "verify.json")) as f:
        verify = json.load(f)
    try:
        if name == "selfcheck_hang":
            time.sleep(600)  # deliberately exceed the watchdog
            result = {"error": "hang_did_not_hang"}
        elif name == "placement":
            # ALWAYS the emulated 8-device CPU platform: hermetic (no
            # TPU/relay dependence), and the acceptance metric is
            # defined on exactly this topology. _force_virtual_cpu_mesh
            # flips XLA_FLAGS AND the live jax config (the axon
            # sitecustomize may have imported jax already).
            from __graft_entry__ import _force_virtual_cpu_mesh

            _force_virtual_cpu_mesh(8)
            result = _placement_bench()
        elif name == "pod_encode":
            # hermetic variant: same emulated 8-device CPU platform as
            # the placement stage — proves the pjit lowering and its
            # bit-identity without any TPU dependence
            from __graft_entry__ import _force_virtual_cpu_mesh

            _force_virtual_cpu_mesh(8)
            result = _pod_encode_bench()
        elif name == "pod_encode_device":
            # the TPU-pod variant: whatever real multi-chip platform
            # the probe found (the parent gates this stage on it)
            result = _pod_encode_bench()
        elif name == "probe":
            result = _stage_probe()
        elif name == "kernel_small":
            result = _device_kernel(verify["kernel_crcs"], width=SMALL_WIDTH)
        elif name == "kernel_full":
            result = _device_kernel(verify["kernel_crcs"], width=BLOCK)
        elif name == "pipeline":
            result = _device_pipeline(
                verify["pipeline_path"],
                verify["pipeline_crcs"],
                verify["pipeline_cpu_gbs"],
            )
        elif name == "e2e":
            result = _device_e2e(
                verify["volume_base"], verify["shard_crcs"], verify["dat_size"]
            )
        else:
            result = {"error": f"unknown stage {name}"}
    except _AllImplsFailed as e:
        result = {"error": "kernel_compile_failed", "detail": str(e)[:2000]}
    except Exception as e:  # noqa: BLE001 — the failure IS the evidence
        result = {"error": type(e).__name__, "detail": repr(e)[:2000]}
    if trace_out:
        from seaweedfs_tpu.utils import trace as _tr

        root, ext = os.path.splitext(trace_out)
        tpath = f"{root}.{name}{ext or '.json'}"
        ttmp = tpath + ".tmp"
        try:
            with open(ttmp, "w") as f:
                json.dump(_tr.chrome_trace(), f)
            os.replace(ttmp, tpath)
        except OSError as e:  # a failed dump must not eat the fragment
            result.setdefault("trace_out_error", repr(e))
    tmp = os.path.join(workdir, f".stage_{name}.tmp")
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.replace(tmp, os.path.join(workdir, f"stage_{name}.json"))


def _probe_cache_path() -> str:
    # Default lives NEXT TO bench.py, not in $TMPDIR: the BENCH_r05
    # regression was a fresh-container /tmp discarding the hung verdict
    # between harness rounds, so every plain `python bench.py` re-paid
    # the full probe watchdog (3 x 150 s before the single-attempt fix,
    # 1 x 150 s after). The repo checkout is the one thing that
    # persists across rounds — with the verdict parked here, the
    # default no-flag invocation short-circuits to the <=30 s probe and
    # the off-path re-probe daemon owns full-patience retries.
    return os.environ.get(
        "SEAWEED_BENCH_PROBE_CACHE",
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            ".bench_probe_verdict.json",
        ),
    )


def _load_probe_verdict(ignore_ttl: bool = False) -> dict | None:
    """Last run's probe outcome, if fresh. A verdict that says the
    device HUNG collapses this run's probe to one short attempt —
    3 x 150 s of watchdog timeouts against a dead relay happens once,
    not every bench invocation (TTL-bounded so a recovered relay is
    re-probed at full patience — by the BACKGROUND re-probe daemon, so
    the bench path itself never pays the 150 s watchdog again; see
    `_spawn_reprobe_daemon`). `ignore_ttl` returns even an expired
    verdict (the stale-hung short-circuit path)."""
    try:
        with open(_probe_cache_path()) as f:
            v = json.load(f)
        ttl = float(os.environ.get("SEAWEED_BENCH_PROBE_CACHE_TTL", "3600"))
        if ignore_ttl or time.time() - float(v.get("ts", 0)) < ttl:
            return v
    except (OSError, ValueError):
        pass
    return None


def _save_probe_verdict(probe: dict) -> None:
    hung = probe.get("error") in ("device_hung", "no_fragment")
    tmp = _probe_cache_path() + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(
                {
                    "hung": hung,
                    "ts": time.time(),
                    "platform": probe.get("platform"),
                    "error": probe.get("error"),
                },
                f,
            )
        os.replace(tmp, _probe_cache_path())
    except OSError:
        pass


def _reprobe_pid_path() -> str:
    return _probe_cache_path() + ".reprobe.pid"


# A re-probe daemon's whole life is one watchdogged probe attempt
# (<= probe timeout + overhead); a pidfile older than this is stale no
# matter what os.kill says — pids recycle, and the file survives
# reboots/SIGKILL beside the durable verdict cache. Without the age
# bound a recycled pid matching an unrelated long-lived process would
# suppress the full-patience re-probe FOREVER.
_REPROBE_PIDFILE_MAX_AGE = 900.0


def _reprobe_daemon_running() -> bool:
    path = _reprobe_pid_path()
    try:
        if time.time() - os.path.getmtime(path) > _REPROBE_PIDFILE_MAX_AGE:
            return False
        pid = int(open(path).read().strip())
    except (OSError, ValueError):
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:  # alive, different uid
        return True


def _spawn_reprobe_daemon() -> str:
    """Kick off a DETACHED background process that re-runs the probe
    stage at full watchdog patience and stamps the verdict cache.

    This closes the remaining cold-TTL gap: a hung device used to cost
    the bench path one full 150 s watchdog every time the verdict
    expired. Now the bench keeps the stale hung verdict (one short
    probe attempt) and the daemon refreshes the cache OFF-PATH — the
    next invocation reads whatever the daemon found. A pidfile
    singleton keeps daemons from piling up across frequent bench runs.

    Returns "spawned" | "running" (singleton refused) |
    "spawn_failed" (Popen error: NO daemon exists — the caller must
    not report one in flight)."""
    if _reprobe_daemon_running():
        return "running"
    import subprocess

    try:
        p = subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--reprobe", _probe_cache_path(),
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except OSError:
        return "spawn_failed"
    try:
        with open(_reprobe_pid_path(), "w") as f:
            f.write(str(p.pid))
    except OSError:
        pass
    return "spawned"


def _reprobe_main(cache_path: str) -> int:
    """`bench.py --reprobe <cache>`: the background re-probe body."""
    os.environ["SEAWEED_BENCH_PROBE_CACHE"] = cache_path
    workdir = tempfile.mkdtemp(prefix="seaweed_reprobe_")
    try:
        with open(os.path.join(workdir, "verify.json"), "w") as f:
            json.dump({}, f)
        probe = _run_stage(
            "probe", workdir,
            lambda: STAGE_TIMEOUTS["probe"] + 60.0,
            attempts=1, stop_on_timeout=True, on_hang=_save_probe_verdict,
        )
        # A hang was stamped by on_hang the instant it was diagnosed;
        # anything else (success OR fast failure) stamps here, exactly
        # like the on-path cold probe would.
        if "skipped" not in probe and probe.get("error") != "device_hung":
            _save_probe_verdict(probe)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
        try:
            os.unlink(_reprobe_pid_path())
        except OSError:
            pass
    return 0


def _run_stage(
    name: str,
    workdir: str,
    remaining,
    attempts: int | None = None,
    timeout_cap: float | None = None,
    stop_on_timeout: bool = False,
    on_hang=None,
) -> dict:
    """Run stage `name` in a watchdogged subprocess, retrying with
    backoff. Returns the child's persisted fragment merged with the
    parent-side attempt trail ({_rc, _s, _attempts}).

    `stop_on_timeout` gives up after the FIRST watchdog timeout instead
    of burning every attempt against a hung device (fast in-child
    failures still retry — a relay refusing connections may wake up,
    one that HANGS for the full watchdog will not wake within the next
    backoff either).

    `on_hang(result)` fires the moment a hang verdict is reached —
    BEFORE returning to the caller — so the probe-verdict cache is
    stamped even if the driver kills this process right after the
    timeout (BENCH_r05 burned 3 x 150 s because the verdict only
    persisted at the end of a run that never got there)."""
    import subprocess

    path = os.path.join(workdir, f"stage_{name}.json")
    if attempts is None:
        attempts = int(
            os.environ.get(
                f"SEAWEED_BENCH_{name.upper()}_ATTEMPTS", STAGE_ATTEMPTS[name]
            )
        )
    trail: list[dict] = []
    for attempt in range(attempts):
        budget = remaining()
        timeout = min(STAGE_TIMEOUTS[name], budget)
        if timeout_cap is not None:
            timeout = min(timeout, timeout_cap)
        if timeout < 20:
            return {"skipped": "budget_exhausted", "_attempts": trail}
        t0 = time.perf_counter()
        rc: int | str
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--stage", name, workdir],
                capture_output=True,
                text=True,
                timeout=timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            rc = out.returncode
            if out.stderr:
                sys.stderr.write(
                    f"bench[{name}#{attempt}] stderr: {out.stderr[-1500:]}\n"
                )
        except subprocess.TimeoutExpired:
            rc = "timeout"
        trail.append({"rc": rc, "s": round(time.perf_counter() - t0, 1)})
        if os.path.exists(path):
            # A persisted fragment beats the watchdog verdict: the child
            # may have finished its work and hung in teardown — valid
            # evidence must not be discarded (nor poison the probe
            # cache with a false "hung").
            try:
                with open(path) as f:
                    result = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                result = {"error": f"fragment_unreadable: {e!r}"}
            if "error" in result and attempt + 1 < attempts and not (
                rc == "timeout" and stop_on_timeout
            ):
                # A fast in-child failure (e.g. relay refusing
                # connections rather than hanging) deserves the same
                # retry-with-backoff as a hang — the relay may wake.
                trail[-1]["error"] = str(result["error"])[:200]
                os.unlink(path)
            else:
                result["_attempts"] = trail
                return result
        if rc == "timeout" and stop_on_timeout:
            result = {"error": "device_hung", "_attempts": trail}
            if on_hang is not None:
                on_hang(result)
            return result
        if attempt + 1 < attempts:
            backoff = min(STAGE_BACKOFF * (attempt + 1), max(remaining(), 0))
            time.sleep(backoff)
    result = {
        "error": "device_hung" if trail and trail[-1]["rc"] == "timeout" else "no_fragment",
        "_attempts": trail,
    }
    if on_hang is not None and result["error"] == "device_hung":
        on_hang(result)
    return result


# --------------------------------------------------------------------------

def _disk_write_gbs(workdir: str, nbytes: int = 256 << 20) -> float:
    """Measured write+fsync ceiling of the bench volume's disk — context
    for the e2e number: once host overhead is gone, e2e is bound by
    min(disk, kernel) and the line should say which."""
    path = os.path.join(workdir, "disk_probe.bin")
    buf = np.random.default_rng(1).integers(0, 256, size=1 << 22, dtype=np.uint8)
    b = buf.tobytes()
    t0 = time.perf_counter()
    with open(path, "wb") as f:
        for _ in range(nbytes // len(b)):
            f.write(b)
        f.flush()
        os.fsync(f.fileno())
    dt = time.perf_counter() - t0
    os.unlink(path)
    return nbytes / dt / 1e9


def _self_check() -> int:
    """Fast regression asserts (no device, no volume fabrication):

    1. A hung stage under `stop_on_timeout` burns exactly ONE watchdog
       attempt AND stamps the probe-verdict cache IMMEDIATELY (the
       BENCH_r05 regression: 3 x 150 s against a dead relay because the
       verdict persisted only at end-of-run).
    2. The stamped verdict short-circuits the next load.
    3. The shared device queue is bit-identical to the direct staged
       path, and a colocated recovery stream neither starves nor gets
       starved (loose bounds; the measured bar lives in the bench line).
    """
    failures: list[str] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        print(f"self-check {name}: {'OK' if ok else 'FAIL ' + detail}")
        if not ok:
            failures.append(name)

    workdir = tempfile.mkdtemp(prefix="seaweed_selfcheck_")
    cache_path = os.path.join(workdir, "probe_verdict.json")
    prev_cache_env = os.environ.get("SEAWEED_BENCH_PROBE_CACHE")
    os.environ["SEAWEED_BENCH_PROBE_CACHE"] = cache_path
    try:
        with open(os.path.join(workdir, "verify.json"), "w") as f:
            json.dump({}, f)
        saved: list[dict] = []

        def stamp(result: dict) -> None:
            saved.append(dict(result))
            _save_probe_verdict(result)

        t0 = time.perf_counter()
        r = _run_stage(
            "selfcheck_hang", workdir, lambda: 120.0,
            stop_on_timeout=True, on_hang=stamp,
        )
        dt = time.perf_counter() - t0
        check(
            "hang_single_attempt",
            r.get("error") == "device_hung" and len(r["_attempts"]) == 1,
            f"got {r}",
        )
        check(
            "hang_stamped_immediately",
            len(saved) == 1 and os.path.exists(cache_path),
            f"saved={saved} cache_exists={os.path.exists(cache_path)}",
        )
        check("hang_bounded_wall", dt < 2 * STAGE_TIMEOUTS["selfcheck_hang"] + 5,
              f"{dt:.1f}s")
        v = _load_probe_verdict()
        check(
            "verdict_short_circuits",
            bool(v and v.get("hung")),
            f"verdict={v}",
        )

        from seaweedfs_tpu.ec.backend import CpuBackend, _decode_coeffs
        from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT
        from seaweedfs_tpu.ec.device_queue import DeviceQueue
        from seaweedfs_tpu.ec.pipeline import run_staged_apply
        from seaweedfs_tpu.ops import gf256

        ctx = DEFAULT_EC_CONTEXT
        be = CpuBackend(ctx)
        rs = gf256.ReedSolomon(ctx.data_shards, ctx.parity_shards)
        coeffs = _decode_coeffs(
            rs.matrix, ctx.data_shards, (0,), tuple(range(1, 11))
        )
        rng = np.random.default_rng(7)
        total = 4 * 8192 + 99
        data = rng.integers(0, 256, (ctx.data_shards, total), dtype=np.uint8)
        want = be.apply(coeffs, data)
        out = np.zeros((1, total), np.uint8)

        def produce():
            for off in range(0, total, 8192):
                yield off, data[:, off : off + 8192]

        def consume(off, rec):
            out[:, off : off + rec.shape[1]] = rec

        run_staged_apply(
            be, coeffs, produce, consume,
            priority="foreground", device_queue=DeviceQueue(),
        )
        check("queue_bit_identical", bool(np.array_equal(out, want)))

        colo = _colocated_bench(batch=1 << 18, fg_batches=12, reps=2)
        check(
            "colocated_fairness",
            colo["encode_vs_rebuild_colocated"] >= 0.5
            and colo["colocated_recovery_bps"] > 0,
            f"{colo}",
        )

        # ---- residency invariant (ISSUE 16): under an oversubscribed
        # tenant storm the shared ledger's high-watermark never exceeds
        # the physical budget, cross-checked against the fake chip's
        # own peak-occupancy observation --------------------------------
        storm = _tenant_storm_bench(
            n_storm_scopes=3, threads_per_scope=2, victim_batches=20,
            work_s=0.001,
        )
        check(
            "tenant_storm_residency_invariant",
            storm["tenant_storm_residency_invariant_ok"]
            and storm["tenant_storm_peak_inflight_budget_on"]
            <= storm["tenant_storm_budget"],
            f"{storm}",
        )

        # ---- pod placement smoke (no jax: the ChipPool routing core
        # takes any device list + factory) -----------------------------
        from seaweedfs_tpu.ec.chip_pool import ChipPool
        from seaweedfs_tpu.ec.device_queue import batch_cost

        rng = np.random.default_rng(0xA11)
        arrivals = [int(c) for c in rng.integers(1, 1000, 32)]
        # replay the documented policy by hand: least outstanding cost,
        # ties to the lowest index — the pool must match it exactly for
        # a seeded arrival order (routing determinism)
        loads = [0] * 8
        expect = []
        for c in arrivals:
            j = min(range(8), key=lambda x: (loads[x], x))
            expect.append(j)
            loads[j] += c
        pool = ChipPool(range(8), lambda d: f"chip{d}")
        placed = [pool.acquire(c) for c in arrivals]
        check(
            "placement_routing_deterministic",
            [p[0] for p in placed] == expect and pool.loads() == loads,
            f"got={[p[0] for p in placed]} want={expect}",
        )
        for _, _, rel in placed:
            rel()
        check("placement_load_drains", pool.idle() and pool.loads() == [0] * 8)

        # ---- peer-fetch rebuild bit-identity (no servers: injected
        # byte transport) — a shard regenerated from PEER-FETCHED
        # sources must be byte-equal to one regenerated from local
        # sources, and both to the original -------------------------
        from seaweedfs_tpu.ec.bitrot import (
            BitrotProtection,
            ShardChecksumBuilder,
        )
        from seaweedfs_tpu.ec.context import ECContext
        from seaweedfs_tpu.ec.peer_rebuild import rebuild_from_peers
        from seaweedfs_tpu.ec.rebuild import rebuild_ec_files

        pctx = ECContext(4, 2)
        pbe = CpuBackend(pctx)
        prng = np.random.default_rng(0x9EE5)
        pdata = prng.integers(0, 256, (4, 3 * 4096 + 57), dtype=np.uint8)
        pshards = np.concatenate([pdata, pbe.encode(pdata)], axis=0)
        builders = [ShardChecksumBuilder(4096) for _ in range(6)]
        peer_dir = os.path.join(workdir, "peer")
        local_dir = os.path.join(workdir, "local")
        ref_dir = os.path.join(workdir, "ref")
        for d in (peer_dir, local_dir, ref_dir):
            os.makedirs(d)
        for i in range(6):
            b = pshards[i].tobytes()
            builders[i].write(b)
            with open(os.path.join(peer_dir, f"1.ec{i:02d}"), "wb") as f:
                f.write(b)
        prot = BitrotProtection.from_builders(pctx, builders, generation=1)
        # local holds 2 of k=4 sources; shard 5 is the rebuild target
        for d in (local_dir, ref_dir):
            prot.save(os.path.join(d, "1.ecsum"))
            for i in (0, 1):
                with open(os.path.join(d, f"1.ec{i:02d}"), "wb") as f:
                    f.write(pshards[i].tobytes())
        # reference: a LOCAL rebuild with all sources on disk
        for i in (2, 3):
            with open(os.path.join(ref_dir, f"1.ec{i:02d}"), "wb") as f:
                f.write(pshards[i].tobytes())
        rebuild_ec_files(os.path.join(ref_dir, "1"), pctx, backend=pbe)

        def pfetch(peer, sid, off, size):
            with open(os.path.join(peer_dir, f"1.ec{sid:02d}"), "rb") as f:
                f.seek(off)
                return f.read(size)

        rep = rebuild_from_peers(
            os.path.join(local_dir, "1"),
            {2: ["p"], 3: ["p"], 4: ["p"]},
            pfetch,
            ctx=pctx,
            targets=[5],
            backend=pbe,
        )
        peer_bytes = open(os.path.join(local_dir, "1.ec05"), "rb").read()
        ref_bytes = open(os.path.join(ref_dir, "1.ec05"), "rb").read()
        check(
            "peer_fetch_bit_identical",
            rep.rebuilt == [5]
            and peer_bytes == ref_bytes
            and peer_bytes == pshards[5].tobytes(),
            f"rebuilt={rep.rebuilt} equal_ref={peer_bytes == ref_bytes}",
        )

        # ---- leaf-repair bit-identity (no servers): a shard healed by
        # the journal-backed IN-PLACE leaf patch must be byte-equal to
        # one healed by a full rebuild, and both to the original ------
        from seaweedfs_tpu.ec.repair_journal import (
            apply_leaf_repair,
            journal_path,
            leaf_verdict,
            reconstruct_leaves,
        )

        lctx = ECContext(4, 2)
        lbe = CpuBackend(lctx)
        lrng = np.random.default_rng(0x1EAF)
        LEAF, LBLOCK = 1024, 4096
        ldata = lrng.integers(0, 256, (4, 3 * 4096 + 57), dtype=np.uint8)
        lshards = np.concatenate([ldata, lbe.encode(ldata)], axis=0)
        lbuilders = [
            ShardChecksumBuilder(LBLOCK, leaf_size=LEAF) for _ in range(6)
        ]
        repair_dir = os.path.join(workdir, "leafrepair")
        rebuild_dir = os.path.join(workdir, "leafrebuild")
        for d in (repair_dir, rebuild_dir):
            os.makedirs(d)
        for i in range(6):
            b = lshards[i].tobytes()
            lbuilders[i].write(b)
            for d in (repair_dir, rebuild_dir):
                with open(os.path.join(d, f"1.ec{i:02d}"), "wb") as f:
                    f.write(b)
        lprot = BitrotProtection.from_builders(lctx, lbuilders, generation=1)
        for d in (repair_dir, rebuild_dir):
            lprot.save(os.path.join(d, "1.ecsum"))
        # same rot both ways: flip bytes inside leaf 2 of shard 3
        for d in (repair_dir, rebuild_dir):
            with open(os.path.join(d, "1.ec03"), "r+b") as f:
                f.seek(2 * LEAF + 31)
                f.write(b"\xba\xad")
        lbase = os.path.join(repair_dir, "1")
        lpath = lbase + ".ec03"
        lbad = leaf_verdict(lpath, 3, lprot)
        lpatches = reconstruct_leaves(
            lprot, lctx, 3, lbad,
            lambda sid, lo, size: open(
                lbase + f".ec{sid:02d}", "rb"
            ).read()[lo : lo + size],
            [i for i in range(6) if i != 3],
            backend=lbe,
        )
        apply_leaf_repair(lpath, 3, lprot, lpatches)
        # full rebuild path on the twin copy (verify-and-exclude
        # replaces the corrupt shard wholesale)
        rebuild_ec_files(os.path.join(rebuild_dir, "1"), lctx, backend=lbe)
        lrepaired = open(lpath, "rb").read()
        lrebuilt = open(os.path.join(rebuild_dir, "1.ec03"), "rb").read()
        check(
            "leaf_repair_bit_identical",
            lbad == [2]
            and lrepaired == lshards[3].tobytes()
            and lrepaired == lrebuilt
            and not os.path.exists(journal_path(lpath)),
            f"bad={lbad} equal_orig={lrepaired == lshards[3].tobytes()} "
            f"equal_rebuild={lrepaired == lrebuilt}",
        )

        # ---- flight recorder: the DISARMED tracer must never tax the
        # hot path (its per-batch touches are a single is-None check +
        # singleton no-op), and the ARMED tracer must actually record
        # stage-attributed spans ---------------------------------------
        from seaweedfs_tpu.utils import trace as _tr

        noop = _tr.stage(None, "disk_read")
        check(
            "tracer_disarmed_noop_singleton",
            not _tr.armed
            and noop is _tr.stage(None, "h2d_dispatch")
            and _tr.start("ec.encode") is None
            and _tr.current() is None,
        )
        # Measured per-call cost of the disarmed fast path, extrapolated
        # to the pipelined encode's call volume (~8 tracer touches per
        # batch: stage timers in producer/transform/drain/sink plus the
        # queue-put checks): must be <2% of the measured per-batch wall.
        calls = 200_000
        t0 = time.perf_counter()
        for _ in range(calls):
            with _tr.stage(None, "disk_read"):
                pass
        per_call = (time.perf_counter() - t0) / calls
        n_batches = -(-total // 8192)
        t0 = time.perf_counter()
        run_staged_apply(
            be, coeffs, produce, consume,
            priority="foreground", device_queue=DeviceQueue(),
        )
        pipeline_wall = time.perf_counter() - t0
        overhead = 8 * per_call * n_batches / pipeline_wall
        check(
            "tracer_disarmed_overhead_lt_2pct",
            overhead < 0.02,
            f"per_call={per_call * 1e9:.0f}ns batches={n_batches} "
            f"wall={pipeline_wall * 1e3:.1f}ms frac={overhead:.5f}",
        )
        _tr.configure(enabled=True)
        try:
            _tr.reset()
            tsp = _tr.start("ec.encode", name="selfcheck")
            with _tr.activate(tsp):
                run_staged_apply(
                    be, coeffs, produce, consume,
                    priority="foreground", device_queue=DeviceQueue(),
                    span=tsp,
                )
            _tr.finish(tsp)
            docs = _tr.traces()
            doc = docs[-1] if docs else {"stages": {}}
            chrome = _tr.chrome_trace()
            check(
                "tracer_armed_records_stages",
                bool(docs)
                and {"h2d_dispatch", "device_drain"} <= set(doc["stages"])
                and doc.get("overlap_efficiency") is not None
                and any(
                    ev.get("ph") == "X" for ev in chrome["traceEvents"]
                ),
                f"stages={sorted(doc['stages'])}",
            )
        finally:
            _tr.configure(enabled=False)
            _tr.reset()

        # queue-cost accounting: admitted/drained cost sums equal the
        # dispatched work, and the load gauge returns to zero
        q2 = DeviceQueue(window=3)
        costs = {"foreground": [batch_cost(4, w) for w in (64, 4096, 17)],
                 "recovery": [batch_cost(1, w) for w in (4096, 9)]}
        for cls, cs in costs.items():
            s2 = q2.stream(cls)
            try:
                for c in cs:
                    t2, _ = s2.dispatch(lambda: None, c)
                    s2.release(t2)
            finally:
                s2.close()
        st2 = q2.stats()
        check(
            "queue_cost_accounting",
            all(
                st2[cls]["admitted_cost"] == st2[cls]["drained_cost"]
                == sum(cs)
                for cls, cs in costs.items()
            )
            and q2.load() == 0,
            f"{st2}",
        )

        # ---- hot-cache bit-identity (ISSUE 11): the same degraded
        # read with the cache ENABLED vs DISABLED returns identical
        # bytes (and a cache HIT equals the read that populated it) ---
        from seaweedfs_tpu.ec import EcVolume, ec_encode_volume
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.storage.volume import Volume

        cctx = ECContext(4, 2)
        cdir = os.path.join(workdir, "cachebit")
        os.makedirs(cdir)
        cvol = Volume(cdir, 1)
        crng = np.random.default_rng(0xCACE)
        cpayloads = {}
        for i in range(1, 9):
            dd = crng.integers(0, 256, 20_000, dtype=np.uint8).tobytes()
            cvol.write_needle(
                Needle(cookie=0x100 + i, needle_id=i, data=dd)
            )
            cpayloads[i] = dd
        cvol.close()
        cbase = Volume.base_file_name(cdir, "", 1)
        ec_encode_volume(cbase, cctx, backend=CpuBackend(cctx))
        vol_cached = EcVolume(cdir, 1, backend_name="cpu")
        vol_raw = EcVolume(cdir, 1, backend_name="cpu",
                           interval_cache_bytes=0)
        vol_cached.unmount_shards([0])
        vol_raw.unmount_shards([0])
        cache_ok = True
        for i in range(1, 9):
            a = vol_cached.read_needle(i).data  # populates the cache
            b = vol_cached.read_needle(i).data  # hot-tier hit
            c = vol_raw.read_needle(i).data  # cache-off reconstruction
            if not (a == b == c == cpayloads[i]):
                cache_ok = False
                break
        hc = vol_cached.interval_cache
        check(
            "hot_cache_bit_identical",
            cache_ok and hc is not None and hc.hits > 0 and hc.loads > 0,
            f"ok={cache_ok} stats={hc.stats() if hc else None}",
        )
        vol_cached.close()
        vol_raw.close()

        # ---- network-plane bit identity (ISSUE 12): a shard rebuilt
        # from NATIVE-plane peer fetches (real loopback ShardNetPlane,
        # sendfile egress, recv-into-pooled-buffer ingress with fused
        # copy-in CRC) must be byte-equal to the Python-plane rebuild
        # over the same wire, and both to the original; the sw_net_*
        # counters must attribute bytes to both planes ---------------
        net_stats = _peer_rebuild_bench(workdir, shard_mb=1, reps=1)
        check(
            "net_plane_bit_identical",
            net_stats.get("peer_rebuild_identical") is True,
            f"stats={net_stats}",
        )
        check(
            "net_plane_zero_copy_evidence",
            "peer_rebuild_error" not in net_stats
            and net_stats.get("bytes_copied_per_byte_served_native", 1.0)
            < 0.01
            and net_stats.get("bytes_copied_per_byte_served_python", 0.0)
            >= 1.0,
            f"stats={net_stats}",
        )

        # ---- warm-path fast-path bit identity (ISSUE 13): one run of
        # the warm bench with fast paths ON vs OFF vs HIT — status,
        # stable headers, and body must be byte-equal across all three,
        # and the counter evidence must show the fast paths actually
        # engaged (memo hits, entry-cache hits, chunk bytes native) ---
        warm = _gateway_warm_bench(workdir, clients=2, reads_per_client=4)
        check(
            "warm_path_bit_identical",
            warm.get("gateway_warm_identical") is True
            and warm.get("gateway_warm_errors", 1) == 0,
            f"stats={ {k: v for k, v in warm.items() if 'stage' not in k} }",
        )
        check(
            "warm_path_fast_paths_engaged",
            warm.get("gateway_warm_auth_memo_hits", 0) > 0
            and warm.get("gateway_warm_entry_cache_hits", 0) > 0
            and warm.get("gateway_warm_chunk_native_mb", 0.0) > 0,
            f"memo={warm.get('gateway_warm_auth_memo_hits')} "
            f"entry={warm.get('gateway_warm_entry_cache_hits')} "
            f"native_mb={warm.get('gateway_warm_chunk_native_mb')}",
        )

        # ---- write-path bit identity + acked-durable (ISSUE 18): one
        # small mixed_rw run — the native write opcode, HTTP multipart,
        # and gRPC WriteNeedle land byte-identical records (and the
        # fast phase's writes actually rode the plane); a SIGKILL
        # between the group-commit fsync and the ack must leave every
        # acked needle replayable from disk ---------------------------
        mixed = _mixed_rw_bench(workdir, clients=4, ops_per_client=4)
        check(
            "write_path_bit_identical",
            mixed.get("mixed_rw_identical") is True
            and mixed.get("mixed_rw_errors", 1) == 0
            and mixed.get("mixed_rw_write_native_mb", 0.0) > 0,
            f"stats={mixed}",
        )
        check(
            "group_commit_acked_is_durable",
            _group_commit_crash_check(workdir),
        )

        # ---- streaming-EC bit identity (ISSUE 14): N appends through
        # the online encoder == ONE batch encode over the concat, and
        # the streaming path's p99 time-to-durable-parity beats the
        # naive seal-then-encode baseline in the same run ------------
        stream_stats = _streaming_encode_bench(
            workdir, n_appends=400, append_bytes=4096,
            flush_kib=64, naive_segment_mb=1,
        )
        check(
            "stream_vs_batch_bit_identical",
            stream_stats.get("stream_vs_batch_identical") is True
            and stream_stats.get("streaming_parity_covered") == 400,
            f"stats={stream_stats}",
        )
        check(
            "streaming_parity_beats_seal_then_encode",
            stream_stats.get("time_to_durable_parity_p99_ms", 1e9)
            < stream_stats.get("naive_parity_p99_ms", 0.0),
            f"stream p99={stream_stats.get('time_to_durable_parity_p99_ms')}"
            f" naive p99={stream_stats.get('naive_parity_p99_ms')}",
        )

        # ---- entry-lookup singleflight: concurrent warm misses on ONE
        # entry collapse to ONE store.find --------------------------
        import threading as _th

        from seaweedfs_tpu.filer import Filer as _WFiler
        from seaweedfs_tpu.filer import MemoryStore as _WMemStore

        wf = _WFiler(_WMemStore(), master="localhost:1")
        try:
            wf.write_file("/sf/obj", b"collapse")
            wf.entry_cache.clear()
            finds = [0]
            flock = _th.Lock()
            real_find = wf.store.find

            def counting_find(directory, name):
                with flock:
                    finds[0] += 1
                time.sleep(0.05)  # hold the flight open so misses pile up
                return real_find(directory, name)

            wf.store.find = counting_find
            bodies = []

            def rd():
                bodies.append(wf.find_entry("/sf/obj").to_bytes())

            ts = [_th.Thread(target=rd) for _ in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            wf.store.find = real_find
            check(
                "warm_path_lookup_collapse",
                finds[0] == 1 and len(set(bodies)) == 1 and len(bodies) == 8,
                f"store_finds={finds[0]} distinct={len(set(bodies))}",
            )
        finally:
            wf.close()

        # ---- saturated-gateway 503 is a WELL-FORMED S3 error document
        # (Code=SlowDown + Retry-After): SDK clients must parse and
        # back off, not choke on a bare connection close --------------
        import socket as _socket
        import xml.etree.ElementTree as _ET

        import requests as _rq

        from seaweedfs_tpu.filer import Filer as _Filer
        from seaweedfs_tpu.filer import MemoryStore as _MemStore
        from seaweedfs_tpu.s3 import S3Server as _S3Server

        sat_filer = _Filer(_MemStore(), master="localhost:1")
        sat_srv = _S3Server(
            sat_filer, ip="127.0.0.1", port=_bench_free_port(),
            lifecycle_interval=0, http_workers=1, http_queue=0,
        )
        sat_srv.start()
        held = None
        try:
            held = _socket.create_connection(("127.0.0.1", sat_srv.port))
            time.sleep(0.3)  # let the acceptor admit the held conn
            rr = _rq.get(f"http://127.0.0.1:{sat_srv.port}/", timeout=10)
            doc_ok = False
            try:
                doc = _ET.fromstring(rr.content)
                doc_ok = (
                    doc.tag == "Error"
                    and doc.findtext("Code") == "SlowDown"
                    and bool(doc.findtext("Message"))
                )
            except _ET.ParseError:
                pass
            check(
                "saturation_503_s3_error_doc",
                rr.status_code == 503
                and bool(rr.headers.get("Retry-After"))
                and doc_ok,
                f"code={rr.status_code} "
                f"retry_after={rr.headers.get('Retry-After')} "
                f"body={rr.content[:120]!r}",
            )
        finally:
            if held is not None:
                held.close()
            sat_srv.stop()
            sat_filer.close()

        # ---- data gravity (ISSUE 15): one tiny gravity pass over a
        # real 2-node cluster — migrated shards bit-identical (sidecar-
        # verified copy), exactly ONE mounted holder afterwards, and
        # the before/after reads byte-equal ---------------------------
        reb = _ec_rebalance_bench(
            workdir, payload_bytes=256 << 10, reads_per_phase=2,
            load_threads=2,
        )
        check(
            "migration_bit_identical",
            reb.get("ec_rebalance_identical") is True,
            f"stats={reb}",
        )
        check(
            "migration_exactly_one_holder",
            reb.get("ec_rebalance_exactly_one_holder") is True,
            f"stats={reb}",
        )

        # ---- MQ data plane (ISSUE 20): the zero-copy fetch spool must
        # be invisible on the wire (native plane == Python plane, byte
        # for byte), and a broker hard-killed mid-group-commit-window
        # must replay every acked Kafka produce dense and byte-exact --
        ident, native_mb = _mq_fetch_bit_identity_probe(workdir)
        check(
            "mq_fetch_bit_identical",
            ident,
            f"native_mb={native_mb}",
        )
        check(
            "mq_group_commit_acked_is_durable",
            _mq_group_commit_crash_check(
                os.path.join(workdir, "mq_sc")
            ),
        )
    finally:
        if prev_cache_env is None:
            os.environ.pop("SEAWEED_BENCH_PROBE_CACHE", None)
        else:
            os.environ["SEAWEED_BENCH_PROBE_CACHE"] = prev_cache_env
        shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps({"self_check": "pass" if not failures else failures}))
    return 0 if not failures else 1


def main() -> None:
    if "--trace-out" in sys.argv:
        # arm the flight recorder in every stage child (env inherits);
        # each stage dumps <out>.<stage>.json in Chrome trace_event
        # format — load in Perfetto / chrome://tracing
        i = sys.argv.index("--trace-out")
        os.environ["SEAWEED_BENCH_TRACE_OUT"] = os.path.abspath(
            sys.argv[i + 1]
        )
    if "--stage" in sys.argv:
        i = sys.argv.index("--stage")
        _stage_child(sys.argv[i + 1], sys.argv[i + 2])
        return
    if "--reprobe" in sys.argv:
        i = sys.argv.index("--reprobe")
        sys.exit(_reprobe_main(sys.argv[i + 1]))
    if "--self-check" in sys.argv:
        sys.exit(_self_check())

    import signal

    from seaweedfs_tpu.ops import gf256

    coeffs = gf256.ReedSolomon(K, M).parity
    threads = os.cpu_count() or 1
    volume_mb = int(os.environ.get("SEAWEED_BENCH_VOLUME_MB", "1024"))

    workdir = tempfile.mkdtemp(prefix="seaweed_bench_")

    # Best-so-far line, kept current as evidence lands: if the driver
    # kills the bench (its timeout, not ours) we still emit one valid
    # JSON line on the way out instead of nothing.
    best: dict = {
        "metric": "ec_encode_e2e_10p4_cpu_fallback(incomplete)",
        "value": 0.0,
        "vs_baseline": 0.0,
        "unit": "GB/s",
    }
    emitted = False

    def _emit() -> None:
        nonlocal emitted
        if not emitted:
            emitted = True
            print(json.dumps(best))
            sys.stdout.flush()

    def _on_term(signum, frame):  # noqa: ARG001
        best["metric"] += f"(killed_sig{signum})"
        _emit()
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)

    try:
        # ---- CPU truth + baseline ---------------------------------------
        cpu_kernel = _cpu_kernel_gbs(_gen(SEEDS[0], BLOCK), coeffs, threads)
        kernel_crcs = _expected_kernel_crcs(coeffs)
        base = _fabricate_volume(workdir, volume_mb << 20)
        disk_gbs = _disk_write_gbs(workdir)
        # Python-plane reference first (its shards are cleared), then
        # the NATIVE-plane encode — the headline e2e — whose shards and
        # sidecar stay on disk for the recovery benches AND must match
        # the Python run bit-for-bit (shard CRCs + v2 leaf CRCs): the
        # zero-copy plane's identity evidence ships in the line itself.
        from seaweedfs_tpu.ec.bitrot import BitrotProtection as _BP

        cpu_e2e_py, shard_crcs_py, _ = _cpu_e2e(base, force_python=True)
        leaf_crcs_py = _BP.load(base + ".ecsum").shard_leaf_crcs
        _clear_shards(base)
        cpu_e2e, shard_crcs, dat_size = _cpu_e2e(base)
        native_identical = bool(
            shard_crcs == shard_crcs_py
            and _BP.load(base + ".ecsum").shard_leaf_crcs == leaf_crcs_py
        )

        # Recovery-path benches (BASELINE configs 2 and 4) on the CPU
        # backend, against the just-encoded volume; both restore the
        # volume bit-exactly before the device phase clears it.
        rebuild_stats = _cpu_rebuild_bench(base, dat_size)
        degraded_stats = _degraded_read_bench(base)
        # Leaf repair vs full rebuild (ISSUE 8): bytes moved + wall
        # time to fix one rotten 64 KiB leaf both ways, bit-identity
        # asserted; restores the volume before the device phase.
        leaf_repair_stats = _leaf_repair_bench(base)
        # Shared device-queue scheduler: foreground encode vs colocated
        # recovery stream on one queue (PR 4 acceptance metric).
        colocated_stats = _colocated_bench()
        # Gateway serving path (ISSUE 9 / direction 5 seed metric):
        # concurrent S3 GET p50/p99 against a degraded EC volume over a
        # real in-process cluster. Failure is evidence, not fatal.
        try:
            gateway_stats = _gateway_bench(workdir)
        except Exception as e:  # noqa: BLE001
            gateway_stats = {"gateway_error": f"{type(e).__name__}: {e}"}
        # Network byte plane (ISSUE 12): peer-fetch rebuild GB/s over a
        # real loopback ShardNetPlane, native vs Python planes with bit
        # identity asserted, + bytes-copied-per-byte-served per plane.
        try:
            peer_rebuild_stats = _peer_rebuild_bench(workdir)
        except Exception as e:  # noqa: BLE001
            peer_rebuild_stats = {
                "peer_rebuild_error": f"{type(e).__name__}: {e}"
            }
        # Warm gateway GETs with the native body egress on vs off — the
        # PR 11 warm-path GIL ceiling is the target.
        try:
            gateway_warm_stats = _gateway_warm_bench(workdir)
        except Exception as e:  # noqa: BLE001
            gateway_warm_stats = {
                "gateway_warm_error": f"{type(e).__name__}: {e}"
            }
        # Streaming EC (ISSUE 14): time-to-durable-parity under a
        # sustained append load vs the naive seal-then-batch-encode
        # baseline, with stream-vs-batch bit identity in the line.
        try:
            streaming_stats = _streaming_encode_bench(workdir)
        except Exception as e:  # noqa: BLE001
            streaming_stats = {
                "streaming_encode_error": f"{type(e).__name__}: {e}"
            }
        # Data gravity (ISSUE 15): degraded-read throughput before vs
        # after one gravity pass (skewed mini-cluster, real worker-
        # driven ec_migrate), migration bit-identity + native wire
        # bytes in the line.
        try:
            rebalance_stats = _ec_rebalance_bench(workdir)
        except Exception as e:  # noqa: BLE001
            rebalance_stats = {
                "ec_rebalance_error": f"{type(e).__name__}: {e}"
            }
        # Write path at line rate (ISSUE 18): mixed 70/30 GET/PUT,
        # native write plane + group commit vs HTTP + fsync-per-needle
        # in one run, with the three-transport bit-identity probe.
        try:
            mixed_rw_stats = _mixed_rw_bench(workdir)
        except Exception as e:  # noqa: BLE001
            mixed_rw_stats = {
                "mixed_rw_error": f"{type(e).__name__}: {e}"
            }
        # Multi-tenant overload safety (ISSUE 16): victim-tenant p99
        # under a tenant storm with the residency budget on vs off,
        # plus the ledger-ground-truth residency invariant.
        try:
            tenant_storm_stats = _tenant_storm_bench()
        except Exception as e:  # noqa: BLE001
            tenant_storm_stats = {
                "tenant_storm_error": f"{type(e).__name__}: {e}"
            }
        # Streaming at line rate (ISSUE 20): sustained Kafka
        # produce/consume, pooled gateway + group commit + zero-copy
        # fetch vs the naive baseline in one run, with the mid-traffic
        # hard-kill replay assertion.
        try:
            mq_sustained_stats = _mq_sustained_bench(workdir)
        except Exception as e:  # noqa: BLE001
            mq_sustained_stats = {
                "mq_sustained_error": f"{type(e).__name__}: {e}"
            }

        _clear_shards(base)  # device phase re-encodes the same volume

        # Disk-independent pipeline: CPU truth run (same striped
        # read->encode->CRC pipeline the device stage executes) is both
        # the verification oracle and the measured same-pipeline CPU
        # baseline. The device e2e above is ~300x disk-bound on this
        # host (BENCH_r04), so this is the number that can actually show
        # a compute win.
        from seaweedfs_tpu.ec.backend import CpuBackend
        from seaweedfs_tpu.ec.context import DEFAULT_EC_CONTEXT

        pipe_mb = int(os.environ.get("SEAWEED_BENCH_PIPELINE_MB", "1024"))
        pipe_path, pipe_staging = _stage_pipeline_file(workdir, pipe_mb << 20)
        cpu_pipe = _run_pipeline(
            CpuBackend(DEFAULT_EC_CONTEXT), pipe_path, BLOCK, REPS
        )

        with open(os.path.join(workdir, "verify.json"), "w") as f:
            json.dump(
                {
                    "kernel_crcs": kernel_crcs,
                    "volume_base": base,
                    "shard_crcs": shard_crcs,
                    "dat_size": dat_size,
                    "pipeline_path": pipe_path,
                    "pipeline_crcs": cpu_pipe["shard_crcs"],
                    "pipeline_cpu_gbs": cpu_pipe["gbs"],
                },
                f,
            )

        common = {
            "unit": "GB/s",
            "threads": threads,
            "volume_gib": round(dat_size / (1 << 30), 3),
            "cpu_e2e_gbs": round(cpu_e2e, 3),
            # native data plane vs pure-Python source/sink, same volume,
            # bit-identity asserted (ISSUE 10 acceptance evidence)
            "cpu_e2e_python_gbs": round(cpu_e2e_py, 3),
            "e2e_native_vs_python": round(cpu_e2e / max(cpu_e2e_py, 1e-9), 3),
            "e2e_native_identical": native_identical,
            "cpu_kernel_gbs": round(cpu_kernel, 3),
            # Honest derating context (north-star baseline is a 16-core
            # host; this one has `threads`): linear-scaling estimate.
            "cpu_kernel_16core_est_gbs": round(cpu_kernel / threads * 16, 3),
            "disk_write_gbs": round(disk_gbs, 3),
            "cpu_pipeline_gbs": round(cpu_pipe["gbs"], 3),
            "cpu_pipeline_16core_est_gbs": round(
                cpu_pipe["gbs"] / threads * 16, 3
            ),
            "pipeline_staging": pipe_staging,
            "pipeline_gib": round((pipe_mb << 20) / (1 << 30), 3),
            **rebuild_stats,
            **degraded_stats,
            **leaf_repair_stats,
            **colocated_stats,
            **gateway_stats,
            **peer_rebuild_stats,
            **gateway_warm_stats,
            **streaming_stats,
            **rebalance_stats,
            **mixed_rw_stats,
            **tenant_storm_stats,
            **mq_sustained_stats,
        }
        best.update(
            {
                "metric": "ec_encode_e2e_10p4_cpu_fallback(device_pending)",
                "value": round(cpu_e2e, 3),
                "vs_baseline": 1.0,
                **common,
            }
        )

        # ---- device stages ----------------------------------------------
        try:
            budget = float(os.environ.get("SEAWEED_BENCH_DEVICE_TIMEOUT", "1200"))
        except ValueError:
            budget = 1200.0

        stages: dict[str, dict] = {}
        best["stages"] = stages

        # Pod-placement bench: always the emulated 8-device CPU
        # platform inside the stage child — hermetic, so it neither
        # waits on the probe verdict nor spends the device budget
        # (the device deadline starts AFTER it).
        placement_stage = _run_stage(
            "placement", workdir,
            lambda: STAGE_TIMEOUTS["placement"] + 10.0,
        )
        stages["placement"] = placement_stage
        if "multi_stream_placement" in placement_stage:
            for k in (
                "multi_stream_placement", "placed_agg_gbs", "mesh_agg_gbs",
                "placement_verified", "placement_streams", "placement_chips",
            ):
                best[k] = placement_stage[k]

        # Pod-sharded encode, hermetic variant (same forced 8-device
        # CPU platform as the placement stage, so it spends no device
        # budget either): pjit-vs-shard_map with bit-identity — the
        # cross-backend half of the ISSUE 15 acceptance. The real-pod
        # variant runs in the device phase below, gated on the probe.
        pod_stage = _run_stage(
            "pod_encode", workdir,
            lambda: STAGE_TIMEOUTS["pod_encode"] + 10.0,
        )
        stages["pod_encode"] = pod_stage
        for k, v in pod_stage.items():
            if k.startswith("pod_encode_"):
                best[k] = v

        deadline = time.monotonic() + budget
        remaining = lambda: deadline - time.monotonic()  # noqa: E731

        verdict = _load_probe_verdict()
        stale = None if verdict is not None else _load_probe_verdict(
            ignore_ttl=True
        )
        short_circuited = bool(verdict and verdict.get("hung"))
        stale_hung = bool(stale and stale.get("hung"))
        if short_circuited:
            # the device hung within the cache TTL: one short attempt
            # instead of 3 x 150 s of watchdog timeouts
            probe = _run_stage(
                "probe", workdir, remaining, attempts=1, timeout_cap=30.0
            )
            probe["probe_cache"] = "hung_short_circuit"
        elif stale_hung:
            # TTL expired on a HUNG verdict: the promised full-patience
            # re-probe runs OFF-PATH in a background daemon; this run
            # keeps the short-circuit budget instead of paying a fresh
            # 150 s watchdog against a device that was dead an hour ago.
            spawned = _spawn_reprobe_daemon()
            probe = _run_stage(
                "probe", workdir, remaining, attempts=1, timeout_cap=30.0
            )
            probe["probe_cache"] = f"stale_hung_reprobe_{spawned}"
            short_circuited = True  # same verdict-persistence rules
        else:
            # Cold (or healthy) verdict cache: fast in-child failures
            # retry with backoff, but ONE full-watchdog hang is enough
            # evidence — BENCH_r05 burned 3 x 150 s re-proving a dead
            # relay before the CPU fallback could land. The verdict is
            # persisted the INSTANT the hang is diagnosed (on_hang), not
            # at end of run: a driver-killed bench must still leave the
            # short-circuit behind for the next invocation.
            probe = _run_stage(
                "probe", workdir, remaining, stop_on_timeout=True,
                on_hang=_save_probe_verdict,
            )
        # Verdict persistence rules: a budget-skipped probe says nothing
        # (don't erase a valid verdict), and a FAILED short-circuit probe
        # must not refresh the hung timestamp — the reduced-patience
        # attempt can't distinguish dead from slow-to-init, and
        # re-stamping would defer the promised full-patience re-probe
        # forever. Only a successful short-circuit probe (device woke
        # up) updates the cache.
        if "skipped" not in probe and (
            not short_circuited or "platform" in probe
        ):
            _save_probe_verdict(probe)
        stages["probe"] = probe
        on_tpu = probe.get("platform") not in (None, "cpu")
        kernel = None

        pipeline: dict = {"skipped": "probe_failed"}
        if "platform" in probe:
            ks = _run_stage("kernel_small", workdir, remaining)
            stages["kernel_small"] = ks
            if "kernel_gbs" in ks:
                kernel = ks
            # pipeline lands BEFORE kernel_full/e2e: it is the artifact
            # the round is judged on, so it gets budget priority
            if on_tpu and kernel is not None:
                pipeline = _run_stage("pipeline", workdir, remaining)
                stages["pipeline"] = pipeline
                kf = _run_stage("kernel_full", workdir, remaining)
                stages["kernel_full"] = kf
                if "kernel_gbs" in kf:
                    kernel = kf
            if on_tpu:
                e2e = _run_stage("e2e", workdir, remaining)
                stages["e2e"] = e2e
            else:
                e2e = {"skipped": "cpu_platform"}
            # TPU-pod variant of the pod-sharded encode: gated on the
            # probe reporting a real multi-device platform (the
            # hermetic 8-virtual-CPU variant above always ran)
            if on_tpu and int(probe.get("n_devices", 1)) >= 2:
                podd = _run_stage("pod_encode_device", workdir, remaining)
                stages["pod_encode_device"] = podd
                for k, v in podd.items():
                    if k.startswith("pod_encode_"):
                        best[f"device_{k}"] = v
        else:
            e2e = {"skipped": "probe_failed"}

        # ---- metric selection (best verified evidence wins) --------------
        kind = probe.get("kind", "?")
        if kernel is not None:
            best.update(
                {
                    "kernel_gbs": round(kernel.get("kernel_gbs", 0.0), 3),
                    "kernel_impl": kernel.get("kernel_impl"),
                    "kernel_verified": kernel.get("kernel_verified"),
                    "kernel_suspect": kernel.get("kernel_suspect"),
                    "kernel_width": kernel.get("kernel_width"),
                    "kernel_vs_cpu": round(
                        kernel.get("kernel_gbs", 0.0) / cpu_kernel, 3
                    ),
                    "kernel_vs_16core_est": round(
                        kernel.get("kernel_gbs", 0.0)
                        / (cpu_kernel / threads * 16),
                        3,
                    ),
                }
            )

        if e2e.get("e2e_gbs") is not None and on_tpu:
            best.update(
                {
                    "e2e_gbs": round(e2e["e2e_gbs"], 3),
                    "e2e_verified": e2e.get("e2e_verified", False),
                    "e2e_vs_cpu": round(e2e["e2e_gbs"] / cpu_e2e, 3),
                    "rebuild_volume_gbs": round(
                        e2e.get("rebuild_volume_gbs", 0.0), 3
                    ),
                    # on-device overlap win (CPU-host parity ratio lives
                    # in the top-level rebuild_staged_vs_sync key)
                    "rebuild_staged_vs_sync_device": e2e.get(
                        "rebuild_staged_vs_sync"
                    ),
                    "rebuild_error": e2e.get("rebuild_error"),
                }
            )
        if pipeline.get("pipeline_gbs") is not None:
            best.update(
                {
                    "pipeline_gbs": round(pipeline["pipeline_gbs"], 3),
                    "pipeline_verified": pipeline.get("pipeline_verified"),
                    "pipeline_suspect": pipeline.get("pipeline_suspect"),
                    "pipeline_rep_s": pipeline.get("pipeline_rep_s"),
                    "pipeline_vs_16core_est": round(
                        pipeline["pipeline_gbs"]
                        / (cpu_pipe["gbs"] / threads * 16),
                        3,
                    ),
                }
            )

        # ---- headline: ec_encode_e2e (ROADMAP direction 1) ---------------
        # End-to-end disk->shards encode is THE metric now that the byte
        # path rides the native data plane; the kernel-only and
        # disk-independent pipeline figures are context sub-fields
        # (kernel_gbs / pipeline_gbs above), never the headline. The CPU
        # headline's vs_baseline is native-plane / pure-Python-plane on
        # the same volume (bit-identity in e2e_native_identical).
        if e2e.get("e2e_gbs") is not None and on_tpu:
            impl = (kernel or {}).get("kernel_impl")
            if not e2e.get("e2e_verified", False):
                best.update(
                    {
                        "metric": f"ec_encode_e2e_10p4_MISMATCH[{kind}]",
                        "value": 0.0,
                        "vs_baseline": 0.0,
                    }
                )
            else:
                best.update(
                    {
                        "metric": (
                            f"ec_encode_e2e_10p4[{kind}/{impl}"
                            f" vs {threads}-thread avx2 cpu, bit-exact]"
                        ),
                        "value": round(e2e["e2e_gbs"], 3),
                        "vs_baseline": round(e2e["e2e_gbs"] / cpu_e2e, 3),
                    }
                )
        elif not native_identical:
            # Same demotion the device path applies on e2e_verified:
            # a native plane that stopped matching the Python plane
            # byte-for-byte must not publish a headline speedup.
            best.update(
                {
                    "metric": "ec_encode_e2e_10p4_cpu_native_MISMATCH",
                    "value": 0.0,
                    "vs_baseline": 0.0,
                }
            )
        else:
            if kernel is not None and on_tpu:
                reason = "device_e2e_unavailable: " + str(
                    e2e.get("error", e2e.get("skipped", "unavailable"))
                )[:120]
            else:
                reason = str(
                    probe.get("error", probe.get("platform", "unknown"))
                )
            best.update(
                {
                    "metric": (
                        f"ec_encode_e2e_10p4_cpu_native_plane({reason})"
                    ),
                    "value": round(cpu_e2e, 3),
                    "vs_baseline": round(
                        cpu_e2e / max(cpu_e2e_py, 1e-9), 3
                    ),
                }
            )
        _emit()
    finally:
        _emit()
        shutil.rmtree(workdir, ignore_errors=True)
        try:  # the pipeline file may live in /dev/shm, outside workdir
            if "pipe_path" in locals() and os.path.exists(pipe_path):
                os.unlink(pipe_path)
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
