"""Headline benchmark: RS 10+4 erasure-coding encode throughput.

Mirrors the reference's hot loop (weed/storage/erasure_coding/ec_encoder.go
encodeDataOneBatch: klauspost/reedsolomon SIMD GF(2^8) encode) against this
framework's device path (XLA/Pallas bit-matmul encode, seaweedfs_tpu/ops).

Baseline = the C++ AVX2 PSHUFB encoder (native/seaweed_native.cpp), the same
nibble-table technique klauspost uses on amd64, run multi-threaded across all
host cores (ctypes releases the GIL). vs_baseline = device GB/s / CPU GB/s.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

K, M = 10, 4
BLOCK = 32 << 20  # bytes per data shard => 320 MiB data per pass
REPS = 3


class _AllImplsFailed(RuntimeError):
    """Every device impl errored at compile/run (device WAS reachable).

    Distinct from generic RuntimeError so backend-init/device_put
    failures propagate as device_error_rcN instead of being mislabeled
    kernel_compile_failed."""


def _cpu_encode_gbs(data: np.ndarray, coeffs: np.ndarray, threads: int) -> float:
    """Multi-threaded native AVX2 encode throughput (data bytes / s)."""
    from seaweedfs_tpu.utils import native

    n = data.shape[1]
    chunk = max(1 << 20, n // max(threads, 1))
    # Pre-split into contiguous per-thread chunks so the timed region is
    # pure GF math, matching how the reference feeds klauspost contiguous
    # 256KB buffers (ec_encoder.go encodeDataOneBatch).
    chunks = [
        np.ascontiguousarray(data[:, lo : min(lo + chunk, n)])
        for lo in range(0, n, chunk)
    ]

    def run_chunk(c):
        native.rs_apply(coeffs, c)

    with ThreadPoolExecutor(max_workers=threads) as ex:
        list(ex.map(run_chunk, chunks))  # warmup (tables + page-in)
        t0 = time.perf_counter()
        for _ in range(REPS):
            list(ex.map(run_chunk, chunks))
        dt = (time.perf_counter() - t0) / REPS
    return data.nbytes / dt / 1e9


def _device_encode_gbs(data: np.ndarray) -> tuple[float, str, str, dict]:
    """Returns (gbs, device_kind, impl_used, {impl: failure_repr})."""
    import jax

    # The axon sitecustomize freezes jax_platforms at interpreter startup,
    # so an env override must go through the live config, not the env var.
    forced = os.environ.get("SEAWEED_BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    from seaweedfs_tpu.ops.rs_jax import RSJax

    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)
    if not on_tpu:
        # The XLA path materialises 8x f32 bit-planes; at the TPU-sized
        # BLOCK that is ~10 GB — shrink so the CPU plumbing run finishes.
        data = data[:, : 1 << 20]
    # First real-TPU contact may reject a kernel at compile time (Mosaic
    # tiling legality). Try most-fused first, degrade, and RECORD each
    # failure so the bench line distinguishes "kernel failed to compile"
    # from "relay unreachable".
    impls = ["pallas", "pallas_aligned", "xla"] if on_tpu else ["xla"]
    forced_impl = os.environ.get("SEAWEED_BENCH_IMPL")
    if forced_impl:
        impls = [forced_impl]
    failures: dict[str, str] = {}
    ddata = jax.device_put(jax.numpy.asarray(data))
    # The xla impl materialises 8x f32 bit-planes: ~10.7 GB at full
    # BLOCK — an OOM risk on a 16 GB-HBM chip. Measure it on a slice
    # (throughput, not capacity, is the metric).
    ddata_xla = ddata[:, : 1 << 23] if data.shape[1] > (1 << 23) else ddata
    for impl in impls:
        din = ddata_xla if impl == "xla" else ddata
        try:
            rs = RSJax(K, M, impl=impl)
            jax.block_until_ready(rs.encode(din))  # compile + warmup
        except Exception as e:  # noqa: BLE001 — diagnostic capture
            failures[impl] = repr(e)[:300]
            continue
        if impl.startswith("pallas") and os.environ.get("SEAWEED_BENCH_AUTOTUNE"):
            rs = _autotune_tile(RSJax, impl, rs, din, jax)
        t0 = time.perf_counter()
        for _ in range(REPS):
            jax.block_until_ready(rs.encode(din))
        dt = (time.perf_counter() - t0) / REPS
        nbytes = din.shape[0] * din.shape[1]
        return nbytes / dt / 1e9, str(dev.device_kind), impl, failures
    raise _AllImplsFailed(f"all device impls failed to compile/run: {failures}")


def _autotune_tile(RSJax, impl: str, best_rs, ddata, jax):
    """Opt-in (SEAWEED_BENCH_AUTOTUNE=1) tile sweep: each extra config
    costs a compile, so the default driver run skips this."""
    candidates = [4096, 8192, 16384] if impl == "pallas" else [2048, 4096, 8192]

    def once(rs):
        jax.block_until_ready(rs.encode(ddata))  # compile+warm
        t0 = time.perf_counter()
        jax.block_until_ready(rs.encode(ddata))
        return time.perf_counter() - t0

    best_t = once(best_rs)
    for tile in candidates:
        try:
            rs = RSJax(K, M, impl=impl, tile_n=tile)
            t = once(rs)
        except Exception:  # noqa: BLE001 — tuning candidates may not fit
            continue
        if t < best_t:
            best_rs, best_t = rs, t
    return best_rs


def _device_phase() -> tuple[float, str, str, dict] | str:
    """Device measurement in a WATCHDOGGED subprocess (the child rebuilds
    the data from the shared seed): when the TPU relay is down, jax
    backend init hangs forever in C — an in-process attempt would hang
    the whole benchmark run. Returns (gbs, kind, impl, failures) or a
    reason string: "device_hung" = relay unreachable;
    "kernel_compile_failed" = device reachable but every impl errored;
    "device_error_rcN" = child died some other way."""
    import subprocess

    try:
        timeout = float(os.environ.get("SEAWEED_BENCH_DEVICE_TIMEOUT", "600"))
    except ValueError:
        timeout = 600.0
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--device-phase"],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return "device_hung"
    # scan every line: runtimes sometimes log brace-prefixed noise
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            try:
                d = json.loads(line)
                if "error" in d:
                    sys.stderr.write(
                        "bench device phase: " + json.dumps(d) + "\n"
                    )
                    return d["error"]
                return d["gbs"], d["kind"], d["impl"], d.get("failures", {})
            except (json.JSONDecodeError, KeyError):
                continue
    # a fast nonzero exit is a device-path BUG, not an unreachable relay:
    # surface the evidence on stderr instead of hiding it
    sys.stderr.write(
        f"bench device phase failed (rc={out.returncode}):\n"
        + out.stderr[-2000:]
        + "\n"
    )
    return f"device_error_rc{out.returncode}"


def main() -> None:
    rng = np.random.default_rng(0x5EAD)
    data = rng.integers(0, 256, size=(K, BLOCK), dtype=np.uint8)

    if "--device-phase" in sys.argv:
        try:
            dev_gbs, dev_kind, impl, failures = _device_encode_gbs(data)
        except _AllImplsFailed as e:
            print(
                json.dumps(
                    {"error": "kernel_compile_failed", "detail": str(e)[:2000]}
                )
            )
            return
        print(
            json.dumps(
                {
                    "gbs": dev_gbs,
                    "kind": dev_kind,
                    "impl": impl,
                    "failures": failures,
                }
            )
        )
        return

    from seaweedfs_tpu.ops import gf256

    coeffs = gf256.ReedSolomon(K, M).parity

    threads = os.cpu_count() or 1
    cpu_gbs = _cpu_encode_gbs(data, coeffs, threads)
    dev = _device_phase()
    if isinstance(dev, str):  # unreachable/hung/errored: CPU-only line
        print(
            json.dumps(
                {
                    "metric": f"rs_10p4_encode_throughput_cpu_fallback({dev})",
                    "value": round(cpu_gbs, 3),
                    "unit": "GB/s",
                    "vs_baseline": 1.0,
                }
            )
        )
        return
    dev_gbs, dev_kind, impl, failures = dev
    if failures:
        sys.stderr.write(
            "bench: impls that failed before the winner: "
            + json.dumps(failures)
            + "\n"
        )

    print(
        json.dumps(
            {
                "metric": f"rs_10p4_encode_throughput[{dev_kind}/{impl} vs {threads}-thread avx2 cpu]",
                "value": round(dev_gbs, 3),
                "unit": "GB/s",
                "vs_baseline": round(dev_gbs / cpu_gbs, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
