// sn_net.h — shared socket byte-plane helpers for the native core
// (seaweed_native.cpp) and the fastread Unix-socket sidecar
// (fastread.cpp). Both libraries move payload bytes kernel-to-kernel
// (sendfile) or with exactly one userspace hop (read/write loops), so
// the loops live once, here. Callers reach these through ctypes, which
// releases the GIL for the whole call — the reason this layer exists:
// Python-side socket handling holds the GIL per chunk, this does not.
//
// Timeout convention: `timeout_ms` < 0 blocks forever; >= 0 bounds each
// individual poll() wait on a non-blocking fd (Python's settimeout puts
// sockets in O_NONBLOCK, so EAGAIN here is the NORMAL slow-peer case,
// not an error). All helpers return bytes moved (possibly short at
// EOF/peer-close) or a negative errno.

#pragma once

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <poll.h>
#include <unistd.h>

#if defined(__linux__)
#include <sys/sendfile.h>
#endif

namespace sn_net {

// Wait for fd readiness. 0 = ready, -ETIMEDOUT, or -errno.
inline int wait_fd(int fd, short events, int timeout_ms) {
    struct pollfd p;
    p.fd = fd;
    p.events = events;
    p.revents = 0;
    for (;;) {
        int r = poll(&p, 1, timeout_ms);
        if (r > 0) return 0;
        if (r == 0) return -ETIMEDOUT;
        if (errno == EINTR) continue;
        return -errno;
    }
}

// write(2) the whole buffer. 0 on success, negative errno on failure.
inline int write_full(int fd, const uint8_t* p, size_t len, int timeout_ms) {
    while (len) {
        ssize_t w = write(fd, p, len);
        if (w < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                int rc = wait_fd(fd, POLLOUT, timeout_ms);
                if (rc != 0) return rc;
                continue;
            }
            return -errno;
        }
        p += w;
        len -= (size_t)w;
    }
    return 0;
}

// read(2) up to len bytes, stopping at EOF/peer close. Returns bytes
// read (short = EOF) or negative errno.
inline int64_t read_full(int fd, uint8_t* p, size_t len, int timeout_ms) {
    size_t got = 0;
    while (got < len) {
        ssize_t r = read(fd, p + got, len - got);
        if (r < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                int rc = wait_fd(fd, POLLIN, timeout_ms);
                if (rc != 0) return rc;
                continue;
            }
            return -(int64_t)errno;
        }
        if (r == 0) break;
        got += (size_t)r;
    }
    return (int64_t)got;
}

// sendfile(2) `len` bytes of in_fd@offset to out_fd; transparently
// falls back to a pread+write loop when the kernel path is unsupported
// for this fd pair (FUSE/9p-backed files, non-socket out_fd on older
// kernels). Returns bytes sent (short only at in_fd EOF) or -errno.
inline int64_t send_file(int out_fd, int in_fd, uint64_t offset,
                         uint64_t len, int timeout_ms) {
    uint64_t sent = 0;
#if defined(__linux__)
    off_t off = (off_t)offset;
    bool kernel_path = true;
    while (kernel_path && sent < len) {
        ssize_t w = sendfile(out_fd, in_fd, &off, (size_t)(len - sent));
        if (w < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                int rc = wait_fd(out_fd, POLLOUT, timeout_ms);
                if (rc != 0) return (int64_t)rc;
                continue;
            }
            if (sent == 0 && (errno == EINVAL || errno == ENOSYS ||
                              errno == EOPNOTSUPP)) {
                kernel_path = false;  // fall back below
                break;
            }
            return -(int64_t)errno;
        }
        if (w == 0) return (int64_t)sent;  // EOF in the source file
        sent += (uint64_t)w;
    }
    if (sent == len) return (int64_t)sent;
#endif
    // Portable fallback: one userspace hop through a reusable buffer.
    static thread_local uint8_t* buf = nullptr;
    const size_t BUF = 1 << 20;
    if (buf == nullptr) buf = new uint8_t[BUF];
    while (sent < len) {
        size_t want = (size_t)(len - sent) < BUF ? (size_t)(len - sent) : BUF;
        ssize_t r = pread(in_fd, buf, want, (off_t)(offset + sent));
        if (r < 0) {
            if (errno == EINTR) continue;
            return -(int64_t)errno;
        }
        if (r == 0) return (int64_t)sent;  // EOF
        int rc = write_full(out_fd, buf, (size_t)r, timeout_ms);
        if (rc != 0) return (int64_t)rc;
        sent += (uint64_t)r;
    }
    return (int64_t)sent;
}

}  // namespace sn_net
